"""Mixture-of-Experts FFN with expert parallelism, GShard-style.

The expert-parallel (ep) axis of the workload suite: the transformer's FFN
is replaced by N experts whose weights shard over an "expert" mesh axis.
Routing is top-1 with a fixed per-expert capacity, expressed as dense
one-hot dispatch/combine einsums — every shape is static, so the whole
layer jits into a handful of MXU matmuls and XLA inserts the expert-axis
collectives from the sharding annotations alone (the idiomatic TPU
formulation; no hand-written all_to_all).

Capacity keeps the computation static: each expert processes at most
C = ceil(seq * capacity_factor / n_experts) tokens per sequence; overflow
tokens are dropped from the expert path (their residual stream passes
through unchanged — standard top-1 MoE behavior).  A load-balancing
auxiliary loss (mean gate mass x token fraction per expert, scaled by E)
keeps the router from collapsing onto one expert.

Composes with the flagship model: ``init_moe_model_params`` /
``moe_loss_fn`` swap the dense FFN of ``workloads.model`` for this layer,
trained over a ("data", "expert", "model") mesh — dp x ep x tp in one step
(__graft_entry__.dryrun_multichip).

Reference pendant: none — the reference daemon has no model code; this
belongs to the JAX workload suite exercising multi-chip slices the device
plugin allocates (SURVEY.md §2 parallelism checklist note).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .model import ModelConfig, _attention, _rmsnorm


@dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 4
    capacity_factor: float = 2.0
    aux_loss_weight: float = 0.01


def init_moe_ffn_params(key: jax.Array, d_model: int, d_ff: int, n_experts: int):
    k = jax.random.split(key, 3)
    scale = 0.02

    def dense(kk, shape):
        return jax.random.normal(kk, shape, jnp.float32) * scale

    return {
        "router": dense(k[0], (d_model, n_experts)),
        "w_up": dense(k[1], (n_experts, d_model, d_ff)),
        "w_down": dense(k[2], (n_experts, d_ff, d_model)),
    }


def moe_ffn_specs() -> dict:
    """Experts shard over the "expert" axis; the tiny router replicates."""
    return {
        "router": P(),
        "w_up": P("expert", None, None),
        "w_down": P("expert", None, None),
    }


def expert_capacity(seq: int, n_experts: int, capacity_factor: float) -> int:
    return max(1, math.ceil(seq * capacity_factor / n_experts))


def moe_ffn(params: dict, x: jax.Array, moe: MoEConfig):
    """Top-1 MoE FFN.  x: [batch, seq, d_model] -> (y, aux_loss).

    Dense dispatch: gather/scatter is two einsums against one-hot masks, so
    the per-expert batch [E, batch, C, d] is a static-shape tensor sharded
    on the expert axis.
    """
    batch, seq, d_model = x.shape
    n_experts = params["router"].shape[1]
    cap = expert_capacity(seq, n_experts, moe.capacity_factor)

    # Route in float32: tiny tensors, and argmax/softmax stability matters.
    logits = jnp.einsum(
        "bsd,de->bse", x.astype(jnp.float32), params["router"]
    )
    probs = jax.nn.softmax(logits, axis=-1)  # [b, s, E]
    expert_idx = jnp.argmax(probs, axis=-1)  # [b, s]
    gate = jnp.max(probs, axis=-1)  # [b, s]

    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.float32)  # [b,s,E]
    # Position of each token within its expert's buffer (first-come order
    # along the sequence), and the capacity cut.
    pos = jnp.cumsum(onehot, axis=1) * onehot - 1.0  # [b, s, E], -1 if not routed
    kept = (pos >= 0) & (pos < cap)
    dispatch = (
        jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
        * kept[..., None]
    )
    # dispatch: [b, s, E, C] — 1 where token (b, s) occupies slot (e, c).
    combine = dispatch * gate[..., None, None]

    # Load-balancing aux loss (GShard eq. 4): E * Σ_e fraction_e * gatemass_e.
    fraction = jnp.mean(onehot, axis=(0, 1))  # tokens routed to e
    gate_mass = jnp.mean(probs, axis=(0, 1))
    aux = moe.aux_loss_weight * n_experts * jnp.sum(fraction * gate_mass)

    compute_dtype = x.dtype
    expert_in = jnp.einsum(
        "bsec,bsd->ebcd", dispatch.astype(compute_dtype), x
    )  # [E, b, C, d]
    hidden = jax.nn.gelu(
        jnp.einsum("ebcd,edf->ebcf", expert_in, params["w_up"].astype(compute_dtype))
    )
    expert_out = jnp.einsum(
        "ebcf,efd->ebcd", hidden, params["w_down"].astype(compute_dtype)
    )
    y = jnp.einsum("bsec,ebcd->bsd", combine.astype(compute_dtype), expert_out)
    return y, aux


def init_moe_model_params(
    config: ModelConfig, moe: MoEConfig, key: jax.Array
) -> dict:
    """The flagship transformer with its dense FFN swapped for MoE."""
    from .model import init_params

    params = init_params(config, key)
    # Fresh key stream: splitting `key` again would replay the exact keys
    # init_params consumed, making MoE weights bit-identical to attention
    # weights of the neighbouring layer.
    keys = jax.random.split(jax.random.fold_in(key, 1), config.n_layers)
    for i, layer in enumerate(params["layers"]):
        del layer["w_up"], layer["w_down"]
        layer["moe"] = init_moe_ffn_params(
            keys[i], config.d_model, config.d_ff, moe.n_experts
        )
    return params


def moe_param_specs(config: ModelConfig) -> dict:
    """Attention keeps the Megatron "model" cut; experts shard on "expert"."""
    from .model import param_specs

    specs = param_specs(config)
    for layer in specs["layers"]:
        del layer["w_up"], layer["w_down"]
        layer["moe"] = moe_ffn_specs()
    return specs


def moe_forward(
    params: dict, tokens: jax.Array, config: ModelConfig, moe: MoEConfig
):
    """Logits + total aux loss for the MoE transformer."""
    x = params["embed"].astype(config.dtype)[tokens]
    aux_total = jnp.float32(0.0)
    for layer in params["layers"]:
        x = x + _attention(_rmsnorm(x, layer["ln1"]), layer, config)
        ffn_out, aux = moe_ffn(layer["moe"], _rmsnorm(x, layer["ln2"]), moe)
        x = x + ffn_out
        aux_total = aux_total + aux
    return x.astype(jnp.float32) @ params["unembed"], aux_total


def moe_loss_fn(
    params: dict, tokens: jax.Array, config: ModelConfig, moe: MoEConfig
) -> jax.Array:
    """Causal LM cross-entropy + router load-balancing loss."""
    from .model import cross_entropy

    logits, aux = moe_forward(params, tokens[:, :-1], config, moe)
    return cross_entropy(logits, tokens[:, 1:]) + aux


def make_moe_mesh(
    n_devices: int, expert_parallel: int = 2, model_parallel: int = 1
):
    """A ("data", "expert", "model") mesh: dp x ep x tp."""
    from jax.sharding import Mesh
    import numpy as np

    devices = jax.devices()[:n_devices]
    if len(devices) < n_devices:
        raise ValueError(
            f"requested a {n_devices}-device mesh but only "
            f"{len(devices)} devices are visible"
        )
    denom = expert_parallel * model_parallel
    if n_devices % denom:
        raise ValueError(f"{n_devices} devices not divisible by ep*tp={denom}")
    grid = np.array(devices).reshape(
        n_devices // denom, expert_parallel, model_parallel
    )
    return Mesh(grid, axis_names=("data", "expert", "model"))


def make_moe_train_state(
    config: ModelConfig, moe: MoEConfig, mesh, seed: int = 0
):
    """(params, opt_state) placed per moe_param_specs, + the optimizer."""
    from .train import make_sharded_train_state

    return make_sharded_train_state(
        mesh,
        lambda: init_moe_model_params(config, moe, jax.random.PRNGKey(seed)),
        moe_param_specs(config),
    )


def make_moe_train_step(config: ModelConfig, moe: MoEConfig, mesh, optimizer):
    """The full dp x ep x tp training step: forward (attention tensor-
    parallel, FFN expert-parallel), backward, Adam update — XLA derives
    every collective from the shardings."""
    from .train import make_sharded_train_step

    return make_sharded_train_step(
        lambda p, t: moe_loss_fn(p, t, config, moe), mesh, optimizer
    )

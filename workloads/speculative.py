"""Speculative decoding: a small draft model proposes, the target verifies.

Decode latency at batch 1 is bound by streaming the target's weights once
per token; speculative decoding streams them once per ROUND instead — the
draft proposes ``gamma`` tokens autoregressively (cheap weights), the
target scores the whole block in ONE cached forward
(workloads/generate.py ``decode_block``), and the longest prefix whose
greedy picks agree is committed plus one corrected token.  Output is the
target's greedy decode (lossless): every committed token is the target's
own argmax given its committed prefix — scored by the block forward.  A
numerics caveat: block- and single-step forwards reassociate their
matmuls differently, so a near-tied argmax can flip relative to
token-by-token ``generate`` (and self-draft acceptance can dip below
100%) — rare in float32, more visible in bfloat16 on hardware.  The
committed stream is always the target's own block-scored greedy; the
exact-match tests pin the behavior on the deterministic CPU test
platform.

Written for XLA the same way generate() is: one ``lax.while_loop`` under
jit, fixed-size buffers, ``gamma`` static, all indexing via
dynamic-slice.  Stale cache entries past the commit point are harmless —
attention masks by position, and later rounds overwrite them before any
mask admits them.

Batch 1 only: acceptance lengths diverge per batch row, which is a
paging/continuous-batching concern out of scope here.

Reference pendant: none — the reference daemon has no model code; part of
the JAX serving workloads (SURVEY.md §7 step 8).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .generate import decode_block, decode_step, init_kv_cache
from .model import ModelConfig


@partial(
    jax.jit,
    static_argnames=("target_config", "draft_config", "max_new_tokens", "gamma"),
)
def _speculative_impl(
    target_params: dict,
    draft_params: dict,
    prompt: jax.Array,
    target_config: ModelConfig,
    draft_config: ModelConfig,
    max_new_tokens: int,
    gamma: int,
):
    batch, prompt_len = prompt.shape
    max_len = prompt_len + max_new_tokens + gamma + 1  # room for overshoot
    t_cache = init_kv_cache(target_config, batch, max_len)
    d_cache = init_kv_cache(draft_config, batch, max_len)

    # Prefill both caches on the prompt; only the target's last row needs
    # the full-vocab unembed (the draft's prefill is cache-fill only) —
    # prompt_len * vocab logits nobody reads are skipped.
    t_logits, t_cache = decode_block(
        target_params, t_cache, prompt, jnp.int32(0), target_config,
        unembed="last",
    )
    _, d_cache = decode_block(
        draft_params, d_cache, prompt, jnp.int32(0), draft_config,
        unembed="none",
    )
    first = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)

    out = jnp.zeros((batch, max_new_tokens + gamma + 1), jnp.int32)
    out = out.at[:, 0].set(first)

    def cond(state):
        _, _, _, _, n_out, rounds = state
        return n_out < max_new_tokens

    def body(state):
        t_cache, d_cache, cur, out, n_out, rounds = state
        # ``cur`` (the latest committed token) sits at position pos:
        pos = prompt_len + n_out - 1

        # Draft gamma tokens autoregressively from cur.  The scan runs one
        # extra step so the FINAL draft token's k/v also lands in the
        # draft cache: on a fully-accepted round that token is committed
        # at pos+gamma, a position later masks admit — without the extra
        # write it would stay a zero hole and silently degrade every
        # subsequent draft (and with it the acceptance rate).
        def draft_one(carry, i):
            d_cache, tok = carry
            logits, d_cache = decode_step(
                draft_params, d_cache, tok, pos + i, draft_config
            )
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (d_cache, nxt), nxt

        (d_cache, _), proposals = jax.lax.scan(
            draft_one, (d_cache, cur), jnp.arange(gamma + 1)
        )
        drafts = jnp.transpose(proposals, (1, 0))[:, :gamma]  # [batch=1, gamma]

        # Target scores [cur, d_1..d_gamma] in one forward: logits[:, i]
        # is the target's pick after ...cur, d_1..d_i.
        block = jnp.concatenate([cur[:, None], drafts], axis=1)
        t_logits, t_cache = decode_block(
            target_params, t_cache, block, pos, target_config
        )
        picks = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [1, g+1]

        # Longest agreeing prefix: n = #{i : drafts[j] == picks[j-1]
        # for all j <= i}; commit drafts[:n] then picks[n] (the target's
        # correction, or its bonus token after a fully accepted block).
        agree = drafts == picks[:, :-1]
        n = jnp.argmin(
            jnp.concatenate([agree, jnp.zeros((1, 1), bool)], axis=1)[0]
        ).astype(jnp.int32)
        committed = jnp.concatenate(
            [drafts, jnp.zeros((1, 1), jnp.int32)], axis=1
        )
        committed = committed.at[0, n].set(picks[0, n])

        # Write the n+1 committed tokens.  No bounds clamp is needed: the
        # buffer carries a gamma+1 scratch tail precisely so the largest
        # possible write (n_out = max_new-1, j = gamma) lands inside it.
        def write(j, out):
            idx = n_out + j
            val = jnp.where(j <= n, committed[0, j], out[0, idx])
            return out.at[0, idx].set(val)

        out = jax.lax.fori_loop(0, gamma + 1, write, out)
        cur = committed[0, n][None]
        return (t_cache, d_cache, cur, out, n_out + n + 1, rounds + 1)

    state = (t_cache, d_cache, first, out, jnp.int32(1), jnp.int32(1))
    *_, out, n_out, rounds = jax.lax.while_loop(cond, body, state)
    return out[:, :max_new_tokens], rounds


def speculative_generate(
    target_params: dict,
    draft_params: dict,
    prompt: jax.Array,
    target_config: ModelConfig,
    draft_config: ModelConfig,
    max_new_tokens: int,
    gamma: int = 4,
):
    """Greedy speculative decode.  Returns (tokens [1, max_new_tokens],
    rounds) — ``rounds`` counts target forward passes (including the one
    committed-token-per-round floor), the speedup lever: rounds approaches
    max_new_tokens/(gamma+1) when the draft agrees, max_new_tokens when it
    never does."""
    if prompt.shape[0] != 1:
        raise ValueError(
            "speculative decoding is batch-1 (acceptance lengths diverge "
            f"across rows); got batch {prompt.shape[0]}"
        )
    if prompt.shape[1] < 1:
        raise ValueError("prompt must contain at least one token")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if target_config.vocab_size != draft_config.vocab_size:
        raise ValueError("target and draft must share a vocabulary")
    total = prompt.shape[1] + max_new_tokens + gamma + 1
    for name, config in (("target", target_config), ("draft", draft_config)):
        if total > config.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens + gamma overshoot = {total} "
                f"exceeds {name} max_seq_len {config.max_seq_len}"
            )
    tokens, rounds = _speculative_impl(
        target_params, draft_params, prompt, target_config, draft_config,
        max_new_tokens, gamma,
    )
    return tokens, int(rounds)

"""LoRA (low-rank adaptation) fine-tuning for the flagship transformer.

The fine-tuning counterpart of workloads/train.py: the base parameters
stay frozen (and may even be the int8 serving representation —
workloads/quant.py), and only rank-r adapter factors train.  Written the
JAX way: adapters are a separate pytree, the merge ``w + a @ b`` happens
functionally inside the jitted step, and ``jax.grad`` over the adapter
tree alone gives frozen-base training for free — no parameter flags, no
module surgery.  Optimizer state lives only for the adapters, so the
fine-tune memory footprint is the base weights plus O(rank) — the reason
LoRA fits where full fine-tuning does not.

Reference pendant: none — the reference daemon has no model code; part of
the JAX workload suite (SURVEY.md §7 step 8).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .model import ModelConfig, loss_fn, weight

# Which layer weights get adapters; their (fan_in, fan_out) split comes
# from the contraction-axis table quant.py owns (one source of truth for
# the weight layouts).
_TARGETS = ("wqkv", "wq", "wkv", "wo")


def _fans(name: str, shape: tuple) -> tuple[int, int]:
    from .quant import CONTRACTION_AXES

    axes = CONTRACTION_AXES[name]
    axes = (axes,) if isinstance(axes, int) else axes
    # merge_lora reshapes (a @ b) [fan_in, fan_out] straight onto w's
    # shape, which is only correct while the contraction axes are exactly
    # the leading axes; a future layout violating that must fail here,
    # not scramble the adapter delta.
    if tuple(axes) != tuple(range(len(axes))):
        raise ValueError(
            f"LoRA requires {name}'s contraction axes to be its leading "
            f"axes, got {tuple(axes)} for shape {shape}"
        )
    fan_in = fan_out = 1
    for i, s in enumerate(shape):
        if i in axes:
            fan_in *= s
        else:
            fan_out *= s
    return fan_in, fan_out


def lora_init(
    config: ModelConfig, rank: int, key: jax.Array, targets=_TARGETS
) -> list:
    """Adapter pytree: per layer, per target weight, ``{"a": [fan_in, r],
    "b": [r, fan_out]}``.  b starts at zero — the adapted model is exactly
    the base model at step 0 (the standard LoRA init)."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    from .model import init_params

    shapes = jax.eval_shape(lambda: init_params(config, jax.random.PRNGKey(0)))
    adapters = []
    for li, layer in enumerate(shapes["layers"]):
        entry = {}
        for name in targets:
            if name not in layer:
                continue
            fan_in, fan_out = _fans(name, layer[name].shape)
            key, ka = jax.random.split(key)
            entry[name] = {
                "a": jax.random.normal(ka, (fan_in, rank), jnp.float32)
                * (1.0 / fan_in**0.5),
                "b": jnp.zeros((rank, fan_out), jnp.float32),
            }
        adapters.append(entry)
    return adapters


def merge_lora(
    params: dict, adapters: list, alpha: float = 1.0, dtype=None
) -> dict:
    """The base tree with each adapted weight replaced by
    ``w + alpha * (a @ b)`` (dequantizing int8 bases on the fly).  Runs
    inside jit — gradients through the merge reach only a and b.

    The merged copy materialises in ``dtype`` (default: the base leaf's
    own floating dtype, float32 for int8 leaves) — merging a bf16 base in
    float32 would double the transient weight memory for nothing."""
    if len(adapters) != len(params["layers"]):
        raise ValueError(
            f"adapter/layer count mismatch: {len(adapters)} adapters for "
            f"{len(params['layers'])} layers"
        )
    out = {k: v for k, v in params.items() if k != "layers"}
    layers = []
    for layer, entry in zip(params["layers"], adapters):
        new = dict(layer)
        for name, ab in entry.items():
            leaf = layer[name]
            target = dtype
            if target is None:
                leaf_dtype = getattr(leaf, "dtype", None)
                target = (
                    leaf_dtype
                    if leaf_dtype is not None
                    and jnp.issubdtype(leaf_dtype, jnp.floating)
                    else jnp.float32
                )
            # Dequantize/read the base at float32 so the sum happens at
            # full precision; only the merged result lands in the target
            # dtype (reading at bf16 first would round before the add).
            w = weight(leaf, jnp.float32)
            delta = (ab["a"] @ ab["b"]).reshape(w.shape) * alpha
            new[name] = (w + delta).astype(target)
        layers.append(new)
    out["layers"] = layers
    return out


def make_lora_train_step(
    config: ModelConfig, mesh, optimizer, base_params, alpha: float = 1.0
):
    """Jitted fine-tune step: (adapters, opt_state, tokens) ->
    (adapters, opt_state, loss).  The frozen base rides through the shared
    train-step helper's ``frozen`` channel — a runtime jit argument, never
    donated, never a closure constant; only the adapter tree and its
    optimizer state update."""
    from .train import make_sharded_train_step

    def adapter_loss(adapters, base, tokens):
        merged = merge_lora(base, adapters, alpha, dtype=config.dtype)
        return loss_fn(merged, tokens, config)

    return make_sharded_train_step(
        adapter_loss, mesh, optimizer, frozen=base_params
    )


def main(argv=None) -> int:
    """``python -m workloads.lora --steps 30 --rank 8`` — LoRA fine-tune
    of the flagship on synthetic data, optionally from an int8 base."""
    import argparse

    import optax

    parser = argparse.ArgumentParser(description="LoRA fine-tune")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--rank", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--int8-base", action="store_true",
                        help="freeze the base in the int8 serving format")
    args = parser.parse_args(argv)
    if args.steps < 1:
        parser.error("--steps must be >= 1")

    from .model import init_params
    from .train import make_mesh, make_sharded_train_state, synthetic_batch

    config = ModelConfig(max_seq_len=args.seq_len)
    mesh = make_mesh()
    base = init_params(config, jax.random.PRNGKey(0))
    if args.int8_base:
        from .quant import quantize_params

        base = quantize_params(base)
    optimizer = optax.adamw(1e-3)
    from jax.sharding import PartitionSpec as P

    adapters_shape = jax.eval_shape(
        lambda: lora_init(config, args.rank, jax.random.PRNGKey(1))
    )
    specs = jax.tree.map(lambda _: P(), adapters_shape)
    (adapters, opt_state), optimizer = make_sharded_train_state(
        mesh,
        lambda: lora_init(config, args.rank, jax.random.PRNGKey(1)),
        specs,
        optimizer=optimizer,
    )
    step = make_lora_train_step(config, mesh, optimizer, base)
    first = last = None
    for s in range(1, args.steps + 1):
        tokens = synthetic_batch(config, args.batch_size, seed=s)
        adapters, opt_state, loss = step(adapters, opt_state, tokens)
        if first is None:
            first = float(loss)
        last = float(loss)
        if s % 10 == 0 or s == args.steps:
            print(f"step {s}: loss={last:.4f}")
    print(
        f"done: steps={args.steps} rank={args.rank} "
        f"int8_base={args.int8_base} loss {first:.4f} -> {last:.4f}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

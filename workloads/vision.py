"""A small convolutional classifier — the JAX pendant of the reference's
shared-GPU PyTorch MNIST example pod.

Reference pendant: ``examples/pods/pod1-shared-pytorch.yml`` runs the
upstream PyTorch MNIST script on ``nvidia.com/sharedgpu: 1``; this module
is the TPU-native equivalent workload for ``examples/pods/
pod-vision-train.yml`` on ``google.com/shared-tpu: 1``.  Written for the
hardware: convolutions in bfloat16 land on the MXU as implicit matmuls,
the whole train step jits over a ("data",) mesh (pure data parallelism —
the natural cut for a small CNN), and the input pipeline is synthetic
MNIST-shaped tensors so the pod needs zero network egress (the reference
pod downloads its script and dataset at runtime).

Architecture (small on purpose, mirroring the upstream MNIST net's shape):
conv 3x3 x32 -> conv 3x3 x64 -> 2x2 maxpool -> dense 128 -> dense 10.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from .model import cross_entropy


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 28
    channels: int = 1
    n_classes: int = 10
    conv1: int = 32
    conv2: int = 64
    hidden: int = 128
    dtype: jnp.dtype = jnp.bfloat16


def init_params(config: VisionConfig, key: jax.Array) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    scale = 0.1
    pooled = config.image_size // 2
    flat = pooled * pooled * config.conv2
    return {
        # HWIO conv layout — jax.lax.conv_general_dilated's native order.
        "conv1": jax.random.normal(k1, (3, 3, config.channels, config.conv1)) * scale,
        "conv2": jax.random.normal(k2, (3, 3, config.conv1, config.conv2)) * scale,
        "dense1": jax.random.normal(k3, (flat, config.hidden)) * scale,
        "dense2": jax.random.normal(k4, (config.hidden, config.n_classes)) * scale,
    }


def param_specs() -> dict:
    """Replicated weights: a model this size is pure data parallelism."""
    return {"conv1": P(), "conv2": P(), "dense1": P(), "dense2": P()}


def _conv(x: jax.Array, w: jax.Array) -> jax.Array:
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def forward(params: dict, images: jax.Array, config: VisionConfig) -> jax.Array:
    """images [batch, H, W, C] float -> logits [batch, n_classes]."""
    x = images.astype(config.dtype)
    x = jax.nn.relu(_conv(x, params["conv1"]))
    x = jax.nn.relu(_conv(x, params["conv2"]))
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        window_dimensions=(1, 2, 2, 1), window_strides=(1, 2, 2, 1),
        padding="VALID",
    )
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense1"].astype(x.dtype))
    # Final projection in float32 for a stable softmax/loss.
    return x.astype(jnp.float32) @ params["dense2"]


def loss_fn(params, images, labels, config: VisionConfig):
    return cross_entropy(forward(params, images, config), labels)


def synthetic_batch(config: VisionConfig, batch: int, seed: int = 0):
    """MNIST-shaped synthetic data with learnable, class-balanced labels:
    each label is the argmax over n_classes fixed random linear probes of
    the image (iid projections of iid pixels -> near-uniform over classes,
    and linearly separable so the loss demonstrably falls).  The probe
    templates are seed-independent so every batch shares one task."""
    key = jax.random.PRNGKey(seed)
    images = jax.random.uniform(
        key, (batch, config.image_size, config.image_size, config.channels)
    )
    templates = jax.random.normal(
        jax.random.PRNGKey(715),  # fixed task, not per-batch
        (images[0].size, config.n_classes),
    )
    # Center the pixels first: positive-mean inputs would correlate every
    # probe through the shared DC component and skew the argmax toward one
    # class.
    labels = jnp.argmax(
        (images.reshape(batch, -1) - 0.5) @ templates, axis=-1
    ).astype(jnp.int32)
    return images, labels


def make_train_step(config: VisionConfig, mesh: Mesh, optimizer):
    from .train import make_sharded_train_step

    return make_sharded_train_step(
        lambda p, images, labels: loss_fn(p, images, labels, config),
        mesh,
        optimizer,
        batch_specs=(P("data", None, None, None), P("data")),
    )


def main(argv=None) -> int:
    """``python -m workloads.vision --steps 50`` — the example-pod entry."""
    import argparse

    import optax

    parser = argparse.ArgumentParser(description="train the vision workload")
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=64)
    args = parser.parse_args(argv)
    if args.steps < 1:
        parser.error("--steps must be >= 1")

    from . import lease

    lease.hold_claim_leases()  # mixed-strategy lifetime declaration

    from .train import make_sharded_train_state

    config = VisionConfig()
    devices = jax.devices()
    mesh = Mesh(devices, axis_names=("data",))
    optimizer = optax.adamw(1e-3)
    (params, opt_state), optimizer = make_sharded_train_state(
        mesh,
        lambda: init_params(config, jax.random.PRNGKey(0)),
        param_specs(),
        optimizer=optimizer,
    )
    step = make_train_step(config, mesh, optimizer)
    first = last = None
    for s in range(1, args.steps + 1):
        images, labels = synthetic_batch(config, args.batch_size, seed=s)
        params, opt_state, loss = step(params, opt_state, images, labels)
        if first is None:
            first = float(loss)
        last = float(loss)
        if s % 10 == 0 or s == args.steps:
            print(f"step {s}: loss={last:.4f}")
    print(f"done: steps={args.steps} loss {first:.4f} -> {last:.4f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

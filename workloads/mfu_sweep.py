"""MFU experiment sweep: where does the non-MFU fraction go?

One-shot harness behind the perf bench (workloads/perfbench.py): times
the full training step across model shape, batch, sequence length and
remat variants on the real chip, reporting per-point MFU (useful model
FLOPs / time / peak) AND HFU (hardware FLOPs including the flash
backward's recompute and layer-remat recompute / time / peak) — the
difference is the price of memory-saving recompute, which MFU by
convention does not credit.

Run: ``python -m workloads.mfu_sweep [--points base,b16,...]``; prints
one JSON line per point.  The committed record for this project's chip
lives in docs/MFU_EXPERIMENTS.md, and the winner feeds
perfbench.BenchScale.

Reference pendant: none — the reference publishes no perf numbers at all
(SURVEY.md §6).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from .model import ModelConfig
from .perfbench import (
    device_peak_flops,
    fwd_attn_flops,
    layer_matmul_params,
    time_train_step,
    train_step_flops,
)


@dataclass(frozen=True)
class SweepPoint:
    name: str
    d_model: int = 2048
    n_heads: int = 16
    n_layers: int = 8
    d_ff: int = 8192
    vocab: int = 32768
    seq: int = 2048
    batch: int = 8
    remat: bool = False

    def config(self) -> ModelConfig:
        return ModelConfig(
            vocab_size=self.vocab, d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, d_ff=self.d_ff, max_seq_len=self.seq,
            attention_impl="flash", remat_layers=self.remat,
        )


# The sweep: batch scaling, longer sequences (where the flash kernel's
# O(block) VMEM keeps compiling), deeper/wider shapes, and remat trades.
POINTS = {
    "base": SweepPoint("base"),
    "b16": SweepPoint("b16", batch=16),
    "b32": SweepPoint("b32", batch=32),
    "seq4k_b4": SweepPoint("seq4k_b4", seq=4096, batch=4),
    "seq4k_b8": SweepPoint("seq4k_b8", seq=4096, batch=8),
    "deep_l16": SweepPoint("deep_l16", n_layers=16),
    "wide_d2560": SweepPoint(
        "wide_d2560", d_model=2560, n_heads=20, d_ff=10240
    ),
    "remat_b16": SweepPoint("remat_b16", batch=16, remat=True),
    "remat_b32": SweepPoint("remat_b32", batch=32, remat=True),
    "remat_seq4k_b8": SweepPoint("remat_seq4k_b8", seq=4096, batch=8, remat=True),
}


def hardware_flops(config: ModelConfig, batch: int) -> float:
    """train_step_flops plus the recompute the hardware actually executes:
    the flash backward recomputes attention probabilities (one extra
    forward-attention pass), and remat_layers recomputes each layer's
    whole forward once more in the backward.  Both terms reuse
    perfbench's accounting primitives — one source of truth."""
    extra = fwd_attn_flops(config, batch)  # flash bwd probability recompute
    if config.remat_layers:
        # One full extra forward of the layer stack (not the unembed).
        tokens = batch * (config.max_seq_len - 1)
        extra += 2 * tokens * layer_matmul_params(config) + fwd_attn_flops(
            config, batch
        )
    return train_step_flops(config, batch) + extra


def measure_point(point: SweepPoint) -> dict:
    config = point.config()
    secs = time_train_step(config, point.batch)
    peak = device_peak_flops()
    model_flops = train_step_flops(config, point.batch)
    hw_flops = hardware_flops(config, point.batch)
    step_tokens = point.batch * (config.max_seq_len - 1)
    return {
        "point": point.name,
        "batch": point.batch,
        "seq": config.max_seq_len,
        "layers": config.n_layers,
        "d_model": config.d_model,
        "remat": point.remat,
        "step_ms": round(secs * 1000, 3),
        "tokens_per_sec": round(step_tokens / secs, 1),
        "mfu": round(model_flops / secs / peak, 4) if peak else None,
        "hfu": round(hw_flops / secs / peak, 4) if peak else None,
    }


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description="MFU experiment sweep")
    parser.add_argument(
        "--points", default=",".join(POINTS),
        help="comma-separated subset of: " + ", ".join(POINTS),
    )
    args = parser.parse_args(argv)

    from . import lease

    lease.hold_claim_leases()  # mixed-strategy lifetime declaration

    names = [n for n in args.points.split(",") if n]
    unknown = [n for n in names if n not in POINTS]
    if unknown:
        parser.error(f"unknown points: {unknown}")
    for name in names:
        try:
            result = measure_point(POINTS[name])
        except Exception as e:  # OOM etc: record, keep sweeping
            result = {"point": name, "error": f"{type(e).__name__}: {e}"[:300]}
        print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Engine observability: request-lifecycle spans, per-step records, and
a Prometheus bridge for ServeEngine.

The plugin half of this repo treats observability as a subsystem
(tpu_device_plugin/metrics.py: a dependency-free Registry + /metrics
endpoint); this module gives the serving half the same surface.  An
``EngineObserver`` is OPT-IN (``ServeEngine(observer=...)``) and records
at the engine's existing seams — admission, decode dispatch (spec vs
plain), readbacks, retirement — three views of the same run:

  1. **Request lifecycle spans** (``RequestSpan``): queued → admitted →
     first-token → done, with the queue-wait / prefill / decode segments
     derived from the Request's host-side stamps (``t_submit`` /
     ``t_admit`` / ``t_first`` / ``t_done``).
  2. **Per-step engine records** (``StepRecord``): step index, slot
     occupancy, admissions coalesced, retirements, decode mode,
     dispatch counts, host readback time — in a bounded ring with a
     ``drain_steps()`` API mirroring ``engine.drain_completed()``.
  3. **A Prometheus bridge** (``bind_registry``): counters, scrape-time
     gauges and seconds-scale histograms on the shared Registry, served
     by the existing MetricsServer next to the plugin's own metrics.

The observer is deliberately INERT: it never touches device state, RNG
keys, scheduling or page accounting, so token streams are bit-identical
with it on or off (pinned by tests/test_obs.py) and its cost is priced
by the perf bench (``obs_overhead_pct``).  ``trace_events`` renders the
rings as a chrome://tracing-loadable timeline (tools/trace_export.py is
the CLI/validator side).

This module is importable WITHOUT jax — it handles host-side stamps and
counters only — so the metrics lint and trace tooling stay fast.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field, fields

# Seconds-scale histogram ladder for serving latencies.  The Registry's
# default LATENCY_BUCKETS top out at 1.0 s (tuned for Allocate handler
# latency); serve TTFT/e2e routinely exceed that, so the engine families
# override per-family buckets (metrics.Registry.describe(buckets=...)).
SERVE_SECONDS_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0,
)

# Device-time-scale ladder (workloads/profiler.py): per-dispatch device
# windows sit well under a millisecond on real chips, where the serving
# ladder's 5 ms floor would flatten every observation into one bucket —
# so `engine_device_seconds` gets its own sub-millisecond floor.
DEVICE_SECONDS_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5,
)


@dataclass(frozen=True)
class MetricSpec:
    """One engine metric family as exposed on the Registry — the single
    source for bind_registry, the metrics lint test, and the rendered
    docs/OBSERVABILITY.md catalog (render_bench_docs)."""

    name: str
    type: str  # "counter" | "gauge" | "histogram"
    labels: tuple[str, ...]
    help: str


# Every family the bridge ever emits.  The lint test
# (tests/test_metrics_lint.py) cross-checks this catalog against the
# names the code actually inc()s / observe_seconds()s, and the rendered
# metric catalog in docs/OBSERVABILITY.md is generated from it — three
# consumers, one spec, no drift.
ENGINE_METRICS: tuple[MetricSpec, ...] = (
    MetricSpec(
        "engine_tokens_total", "counter", ("engine",),
        "tokens emitted by the serving engine",
    ),
    MetricSpec(
        "engine_requests_admitted_total", "counter", ("engine",),
        "requests admitted into engine slots (instant-finish included)",
    ),
    MetricSpec(
        "engine_requests_retired_total", "counter", ("engine",),
        "requests retired by the serving engine",
    ),
    MetricSpec(
        "engine_mode_switches_total", "counter", ("engine",),
        'spec="auto" decode-mode boundary crossings (each drains the '
        "other mode's pipelined in-flight state)",
    ),
    MetricSpec(
        "engine_decode_steps_total", "counter", ("engine", "mode"),
        "decode dispatches by mode (plain chunk vs speculative superstep)",
    ),
    MetricSpec(
        "engine_prefill_dispatches_total", "counter", ("engine",),
        "target prefill program dispatches (admission sweeps and chunks)",
    ),
    MetricSpec(
        "engine_prefill_deferred_tokens_total", "counter", ("engine",),
        "prompt tokens whose prefill the per-step budget parked for a "
        "later step (prefill_budget chunked-prefill interleaving)",
    ),
    MetricSpec(
        "engine_prefill_inflight", "gauge", ("engine",),
        "admissions currently parked mid-prefill by the step budget "
        "(scrape-time)",
    ),
    MetricSpec(
        "engine_requests_cancelled_total", "counter", ("engine",),
        "requests cancelled via engine.cancel (queued or running)",
    ),
    MetricSpec(
        "engine_requests_expired_total", "counter", ("engine",),
        "requests whose deadline_s passed before completion",
    ),
    MetricSpec(
        "engine_requests_failed_total", "counter", ("engine",),
        "requests failed terminally (retry budget exhausted, or the "
        "engine closed over them)",
    ),
    MetricSpec(
        "engine_requests_retried_total", "counter", ("engine",),
        "replay requeues after a quarantined step (prompt + emitted "
        "tokens re-prefilled; greedy streams resume bit-identically)",
    ),
    MetricSpec(
        "engine_queue_rejections_total", "counter", ("engine",),
        "submissions rejected by bounded admission (max_pending)",
    ),
    MetricSpec(
        "engine_calibration_reused_total", "counter", ("engine",),
        'spec="auto" break-even calibrations adopted from an injected '
        "warm-state snapshot instead of re-running the dead timing "
        "dispatches (workloads/faststart.py EngineSnapshot)",
    ),
    MetricSpec(
        "engine_compile_cache_hits_total", "counter", ("engine",),
        "persistent-compile-cache hits during this engine's lifetime "
        "(executables replayed from disk instead of recompiled — "
        "faststart.enable_compile_cache / --compile-cache-dir)",
    ),
    MetricSpec(
        "engine_compile_cache_misses_total", "counter", ("engine",),
        "persistent-compile-cache misses during this engine's lifetime "
        "(compiles that ran XLA and then populated the cache — the "
        "cold-spawn signature)",
    ),
    MetricSpec(
        "engine_queue_depth", "gauge", ("engine",),
        "requests waiting in the pending queue (scrape-time)",
    ),
    MetricSpec(
        "engine_slot_occupancy", "gauge", ("engine",),
        "batch slots currently decoding a request (scrape-time)",
    ),
    MetricSpec(
        "engine_slots", "gauge", ("engine",),
        "total batch slots the engine was built with",
    ),
    MetricSpec(
        "engine_resident_pages", "gauge", ("engine",),
        "KV-cache pages currently held by live sequences (scrape-time)",
    ),
    MetricSpec(
        "engine_prefix_hit_pages_total", "counter", ("engine",),
        "prompt pages served from the prefix cache (radix or flat) "
        "instead of re-prefilling — host-RAM reloads included",
    ),
    MetricSpec(
        "engine_prefix_miss_total", "counter", ("engine",),
        "prefix-cache lookups that matched nothing (the prompt "
        "prefilled from scratch)",
    ),
    MetricSpec(
        "engine_kv_offloaded_pages", "gauge", ("engine",),
        "KV pages currently parked in the host-RAM offload tier "
        "(kv_offload; scrape-time — state held without holding HBM)",
    ),
    MetricSpec(
        "engine_kv_disk_pages", "gauge", ("engine",),
        "KV pages durable in the disk tier below host RAM "
        "(--kv-disk-dir; per-page files named by chain key, shared "
        "across replicas and processes — scrape-time)",
    ),
    MetricSpec(
        "engine_paused", "gauge", ("engine",),
        "1 while the health bridge holds admission paused on an "
        "Unhealthy chip (scrape-time; fleet routers read this as the "
        "replica's drain signal)",
    ),
    MetricSpec(
        "engine_tokens_overdecoded_total", "counter", ("engine",),
        "device decode steps computed past a row's retirement point "
        "(dead decode-superstep compute, reconciled at each fused "
        "readback)",
    ),
    MetricSpec(
        "engine_observer_dropped_steps_total", "counter", ("engine",),
        "step records the observer's bounded ring evicted UNREAD "
        "(drain_steps too rarely) — non-zero means the scraped "
        "timeline is silently truncated",
    ),
    MetricSpec(
        "engine_observer_dropped_spans_total", "counter", ("engine",),
        "lifecycle spans the observer's bounded ring evicted unread — "
        "silent span loss made visible",
    ),
    MetricSpec(
        "engine_ttft_seconds", "histogram", ("engine",),
        "submission -> first observed token (queue wait included)",
    ),
    MetricSpec(
        "engine_host_sync_seconds", "histogram", ("engine",),
        "wall time one engine step spent BLOCKED in host syncs "
        "(readbacks + fused consumes — the per-step tax decode "
        "supersteps amortize)",
    ),
    MetricSpec(
        "engine_e2e_seconds", "histogram", ("engine",),
        "submission -> retirement end-to-end latency",
    ),
    MetricSpec(
        "engine_step_seconds", "histogram", ("engine",),
        "wall time of one engine step() (admit + dispatch + consume)",
    ),
    MetricSpec(
        "engine_device_seconds", "histogram", ("engine",),
        "estimated DEVICE time of one dispatching step (step wall "
        "minus the engine-measured host-sync stall, smoothed through "
        "the per-(program, seq-bucket, batch-bucket) calibration "
        "table when one is attached — workloads/profiler.py); "
        "sub-millisecond DEVICE_SECONDS_BUCKETS ladder",
    ),
    MetricSpec(
        "engine_device_busy_fraction", "gauge", ("engine",),
        "fraction of observed step wall the device was busy "
        "(scrape-time, cumulative over this observer's run — the "
        "device-side split of the chip-second the ledger charges)",
    ),
    MetricSpec(
        "engine_host_stall_fraction", "gauge", ("engine",),
        "1 - engine_device_busy_fraction: observed step wall spent "
        "host-stalled (readbacks, scheduling, idle admission polls)",
    ),
)

# Fleet-level metric families (workloads/fleet.py; FleetObserver below).
# Same three-consumer contract as ENGINE_METRICS: bind_fleet metrics,
# the lint test, and the rendered docs/OBSERVABILITY.md catalog all read
# this spec.  Engine families additionally carry a ``replica`` label in
# fleet mode (EngineObserver(replica=...)); single-engine output is
# byte-compatible when the label is left at its empty default.
FLEET_METRICS: tuple[MetricSpec, ...] = (
    MetricSpec(
        "fleet_requests_total", "counter", ("fleet",),
        "requests accepted into the fleet router queue",
    ),
    MetricSpec(
        "fleet_tokens_total", "counter", ("fleet",),
        "tokens generated across every live replica",
    ),
    MetricSpec(
        "fleet_failovers_total", "counter", ("fleet",),
        "charged failover requeues after true replica faults "
        "(crash/hang/escaped exception; replay re-prefills prompt + "
        "emitted tokens on a survivor)",
    ),
    MetricSpec(
        "fleet_drain_requeues_total", "counter", ("fleet",),
        "uncharged requeues off health-paused or operator-removed "
        "replicas (a sick chip is not the request's fault)",
    ),
    MetricSpec(
        "fleet_queue_rejections_total", "counter", ("fleet",),
        "submissions rejected by the fleet-wide admission bound "
        "(max_pending)",
    ),
    MetricSpec(
        "fleet_replica_failures_total", "counter", ("fleet", "kind"),
        "replicas lost, by failure kind (crash vs hang)",
    ),
    MetricSpec(
        "fleet_queue_depth", "gauge", ("fleet",),
        "requests waiting in the fleet router queue (scrape-time)",
    ),
    MetricSpec(
        "fleet_replicas", "gauge", ("fleet", "state"),
        "replicas by state (active / draining / dead; scrape-time)",
    ),
    MetricSpec(
        "fleet_replica_state", "gauge", ("fleet", "replica", "state"),
        "1 for each live replica's current router state "
        "(active/draining — the per-replica drain signal; scrape-time)",
    ),
    MetricSpec(
        "fleet_replica_paused", "gauge", ("fleet", "replica"),
        "1 while the replica's engine is health-paused (scrape-time)",
    ),
    MetricSpec(
        "fleet_queue_wait_seconds", "histogram", ("fleet",),
        "submission -> first admission into any replica's slots, "
        "pooled across the fleet",
    ),
    MetricSpec(
        "fleet_ttft_seconds", "histogram", ("fleet",),
        "submission -> first streamed token, pooled across the fleet "
        "(failover re-admissions do not reset it)",
    ),
    MetricSpec(
        "fleet_e2e_seconds", "histogram", ("fleet",),
        "submission -> terminal status, pooled across the fleet",
    ),
    # Per-SLO-class attainment (Fleet.submit(slo_class=...)): the exact
    # inputs the ROADMAP's SLO-class scheduler and autoscaler consume —
    # attainment per class, not just global percentiles.
    MetricSpec(
        "fleet_slo_requests_total", "counter", ("fleet", "slo_class"),
        "SLO-classed requests that reached a terminal status "
        "(cancelled requests are excluded — a client abort is not an "
        "SLO verdict)",
    ),
    MetricSpec(
        "fleet_slo_attained_total", "counter", ("fleet", "slo_class"),
        "SLO-classed requests that finished ok WITHIN their class "
        "targets (TTFT-bound interactive, TPOT-bound bulk); "
        "attained/requests is the per-class attainment ratio",
    ),
    MetricSpec(
        "fleet_slo_burn_rate", "gauge", ("fleet", "slo_class"),
        "windowed error-budget burn rate per class (miss fraction over "
        "the sliding slo_window_s divided by the class's error budget "
        "1-objective; 1.0 = burning exactly the budget, >1 = an SRE "
        "multi-window alert would fire; scrape-time)",
    ),
    MetricSpec(
        "fleet_class_ttft_seconds", "histogram", ("fleet", "slo_class"),
        "submission -> first streamed token, by SLO class (the "
        "interactive class's bound)",
    ),
    MetricSpec(
        "fleet_class_tpot_seconds", "histogram", ("fleet", "slo_class"),
        "per-token decode time (first token -> done over tokens-1), by "
        "SLO class (the bulk class's bound)",
    ),
    # Disaggregated prefill/decode pools (Fleet(roles=...), docs/
    # SERVING.md "Disaggregated prefill/decode"): KV handoff volume and
    # latency, the per-class WFQ dispatch split, and the live role map.
    MetricSpec(
        "fleet_kv_handoffs_total", "counter", ("fleet",),
        "prefill→decode KV handoffs: prompts whose finished pages were "
        "exported off a prefill-pool replica and continued on the "
        "decode pool (greedy streams bit-identical to mixed dispatch)",
    ),
    MetricSpec(
        "fleet_handoff_pages_total", "counter", ("fleet",),
        "KV pages adopted from handoff tickets by decode-pool replicas "
        "(grafted into the target's radix index; reloaded on the "
        "admission sweep)",
    ),
    MetricSpec(
        "fleet_handoff_seconds", "histogram", ("fleet",),
        "prefill-done -> first decode-pool token per handed-off stream "
        "(the bench's disagg_handoff_ms window)",
    ),
    MetricSpec(
        "fleet_wfq_dispatches_total", "counter", ("fleet", "slo_class"),
        "fresh-prompt dispatches granted by the SLO-class weighted "
        "fair queue, by class (wfq_weights=; continuations are free — "
        "they already hold service)",
    ),
    MetricSpec(
        "fleet_replica_role", "gauge", ("fleet", "replica", "role"),
        "1 for each live replica's disaggregation role "
        "(prefill/decode/mixed; scrape-time)",
    ),
    # KV pages as the schedulable unit (Fleet(page_scheduling=True),
    # docs/SERVING.md "Memory as the schedulable unit"): page-granular
    # dispatch volume, live-signal snapshot publications for the device
    # plugin's GetPreferredAllocation scorer, and the free-page headroom
    # the page-aware admission bound scales with.
    MetricSpec(
        "fleet_page_dispatches_total", "counter", ("fleet",),
        "dispatches routed by the page-granular load view (pages held "
        "+ pages the queued work will claim, goodput-penalized) "
        "instead of request counts (page_scheduling=True)",
    ),
    MetricSpec(
        "fleet_stats_published_total", "counter", ("fleet",),
        "live-signal snapshots atomically published to the host-local "
        "stats file the device plugin's preferred-allocation scorer "
        "reads (Fleet.publish_stats; tpu_device_plugin/kvsched.py)",
    ),
    MetricSpec(
        "fleet_free_pages", "gauge", ("fleet", "tier"),
        "aggregate free KV pages across live replicas, by tier (hbm = "
        "unallocated pool pages, host = offload-tier headroom; "
        "scrape-time — the page-aware admission bound's inputs)",
    ),
    # Durable sessions (Fleet(journal_dir=...), docs/SERVING.md
    # "Durable sessions"): session-journal checkpoint volume, injected
    # torn writes, and sessions resurrected by Fleet.restore after a
    # full process restart.
    MetricSpec(
        "fleet_journal_writes_total", "counter", ("fleet",),
        "session-journal checkpoints durably written (atomic, with "
        "the previous generation kept beside the current one as the "
        "torn-write recovery point)",
    ),
    MetricSpec(
        "fleet_journal_torn_total", "counter", ("fleet",),
        "journal checkpoints torn mid-write (the journal_torn_write "
        "chaos seam) — each one left the previous generation as the "
        "restore point, at most one checkpoint interval of progress "
        "lost",
    ),
    MetricSpec(
        "fleet_sessions_restored_total", "counter", ("fleet",),
        "sessions resurrected from the journal + disk tier by "
        "Fleet.restore after a full process restart (greedy "
        "continuations bit-identical to the uninterrupted stream; "
        "interrupted streams true prefixes)",
    ),
    MetricSpec(
        "fleet_observer_dropped_spans_total", "counter", ("fleet",),
        "fleet-request spans the observer's bounded ring evicted "
        "unread — the merged trace and postmortem bundles are "
        "silently missing exactly this many requests",
    ),
)

# Supervisor-level metric families (workloads/supervisor.py;
# SupervisorObserver below).  Same three-consumer contract as
# ENGINE_METRICS / FLEET_METRICS: bind_registry, the lint test, and the
# rendered docs/OBSERVABILITY.md catalog all read this spec.
SUPERVISOR_METRICS: tuple[MetricSpec, ...] = (
    MetricSpec(
        "supervisor_restarts_total", "counter", ("supervisor",),
        "replicas resurrected onto their chip slot (half-open canary "
        "probe passed bit-identically, replica rejoined the router)",
    ),
    MetricSpec(
        "supervisor_restart_failures_total", "counter", ("supervisor",),
        "failed resurrection attempts (respawn seam fault, engine "
        "factory error, or half-open probe divergence) — each feeds "
        "the crash-loop window and escalates the slot's backoff",
    ),
    MetricSpec(
        "supervisor_crash_loops_total", "counter", ("supervisor",),
        "crash-loop verdicts: crash_loop_k failures inside the sliding "
        "window (or a max_restarts budget exhausted) quarantined the "
        "chip slot until an operator clear()",
    ),
    MetricSpec(
        "supervisor_health_deferrals_total", "counter", ("supervisor",),
        "resurrections deferred because the chip slot carried a live "
        "HealthFanout Unhealthy mark (honored, not escalated)",
    ),
    MetricSpec(
        "supervisor_slots", "gauge", ("supervisor", "state"),
        "supervised chip slots by state (serving / backoff / probing / "
        "quarantined / forgotten; scrape-time)",
    ),
    MetricSpec(
        "supervisor_restore_seconds", "histogram", ("supervisor",),
        "replica death detection -> probed replacement rejoined the "
        "router (the bench's selfheal_restore_ms window)",
    ),
    MetricSpec(
        "supervisor_dropped_events_total", "counter", ("supervisor",),
        "supervision-timeline events the bounded ring evicted unread "
        "— the merged trace's supervisor lane and postmortem bundles "
        "are silently missing exactly this many transitions",
    ),
)

# Autoscaler-level metric families (workloads/autoscaler.py;
# AutoscalerObserver below).  Same three-consumer contract as the other
# catalogs: bind_registry, the lint test, and the rendered
# docs/OBSERVABILITY.md catalog all read this spec.
AUTOSCALER_METRICS: tuple[MetricSpec, ...] = (
    MetricSpec(
        "autoscaler_decisions_total", "counter", ("autoscaler", "action"),
        "control-loop decisions by action (scale_up / scale_down / "
        "spawn_failed / brownout / brownout_clear / preempt / "
        "preempt_clear) — the audit trail of every actuation the "
        "closed loop took",
    ),
    MetricSpec(
        "autoscaler_scale_ups_total", "counter", ("autoscaler",),
        "replicas added by the control loop (engine_factory spawn + "
        "bit-identical canary probe passed + add_replica; supervised "
        "fleets adopt the new slot so it heals like a founder)",
    ),
    MetricSpec(
        "autoscaler_scale_downs_total", "counter", ("autoscaler",),
        "replicas retired by the control loop (graceful drain of the "
        "least-loaded ACTIVE replica, removed once idle — never below "
        "min_replicas, never the last dispatchable one)",
    ),
    MetricSpec(
        "autoscaler_spawn_failures_total", "counter", ("autoscaler",),
        "failed scale-up attempts (scale_spawn_fail seam fault, engine "
        "factory error, or canary divergence) — each escalates the "
        "up-gate backoff; persistent failure is what drops the fleet "
        "onto the degradation ladder",
    ),
    MetricSpec(
        "autoscaler_brownouts_total", "counter", ("autoscaler",),
        "degradation-ladder step-1 entries: the capacity-aware "
        "admission bound tightened to brownout_factor while overload "
        "outran elastic capacity (typed QueueFull names the brownout)",
    ),
    MetricSpec(
        "autoscaler_preemptions_total", "counter", ("autoscaler",),
        "degradation-ladder step-2 preemptions: running bulk-class "
        "streams parked via host offload (RadixKV.park) and requeued "
        "uncharged for post-spike resumption as exact continuations",
    ),
    MetricSpec(
        "autoscaler_ladder_level", "gauge", ("autoscaler",),
        "current degradation-ladder level (0 = normal, 1 = brownout, "
        "2 = preemption-via-offload; scrape-time)",
    ),
    MetricSpec(
        "autoscaler_replicas_target", "gauge", ("autoscaler",),
        "replicas the control loop currently wants (provisioned plus "
        "in-flight resurrections, clamped to [min_replicas, "
        "max_replicas]; scrape-time)",
    ),
    MetricSpec(
        "autoscaler_replicas_live", "gauge", ("autoscaler",),
        "replicas actually alive in the fleet right now (target vs "
        "live is the convergence lag the step-load bench measures; "
        "scrape-time)",
    ),
)

# Goodput-controller families (workloads/control.py; ControlObserver
# below).  Same three-consumer contract as the other catalogs:
# bind_registry, the lint test, and the rendered docs/OBSERVABILITY.md
# catalog all read this spec.
CONTROL_METRICS: tuple[MetricSpec, ...] = (
    MetricSpec(
        "control_decisions_total", "counter", ("controller", "action"),
        "goodput-control decisions by action (retune / wfq_reweight / "
        "hold) — the audit trail of every ledger-driven actuation the "
        "online retuning loop took",
    ),
    MetricSpec(
        "control_retunes_total", "counter", ("controller",),
        "ServeEngine.retune() transitions the controller applied "
        "(spec_breakeven shifts, superstep_k / spec_superstep_k steps "
        "— each drained in-flight state first, so greedy streams stay "
        "bit-identical across the knob move)",
    ),
    MetricSpec(
        "control_wfq_reweights_total", "counter", ("controller",),
        "live Fleet.wfq_weights updates from measured per-class "
        "goodput-per-chip-second (operator weights remain the floor; "
        "wasteful classes stop buying dispatch credit)",
    ),
    MetricSpec(
        "control_dropped_events_total", "counter", ("controller",),
        "control-timeline events the bounded ring evicted unread — "
        "the merged trace's supervisor lane is silently missing "
        "exactly this many actuations",
    ),
    MetricSpec(
        "control_goodput_fraction", "gauge", ("controller",),
        "the controller's EWMA-smoothed view of the fleet's goodput "
        "fraction — the signal the retune/reweight/waste-budget "
        "decisions read (scrape-time; absent until the ledger has "
        "accounted a measurable delta)",
    ),
    MetricSpec(
        "control_spec_rejected_fraction", "gauge", ("controller",),
        "EWMA share of newly-accounted device work going to rejected "
        "speculative drafts — the speculation-retune input "
        "(scrape-time; absent until measured)",
    ),
    MetricSpec(
        "control_overdecode_fraction", "gauge", ("controller",),
        "EWMA share of newly-accounted device work going to "
        "overdecode (chained superstep chunks past retirement) — the "
        "superstep-retune input (scrape-time; absent until measured)",
    ),
)

# Chip-time ledger families (workloads/ledger.py; docs/OBSERVABILITY.md
# "Chip-time ledger, goodput & postmortems").  Same three-consumer
# contract as the other catalogs: the engine/fleet bridges push them
# when a ledger is armed, the lint test cross-checks, the docs render
# from this spec.  The engine families ride the EngineObserver (per
# replica in fleet mode); the fleet families ride the FleetObserver.
LEDGER_METRICS: tuple[MetricSpec, ...] = (
    MetricSpec(
        "ledger_chip_seconds_total", "counter", ("engine", "phase"),
        "chip-time attribution: wall seconds of engine work by phase "
        "(prefill / decode / spec_draft / spec_verify / spec_commit / "
        "kv_spill / kv_reload / kv_handoff / probe / warmup / idle) — "
        "sum(phases) == total observed wall, every second lands in "
        "exactly one phase",
    ),
    MetricSpec(
        "ledger_tokens_total", "counter", ("engine", "class"),
        "token accounting by class: goodput (delivered to an "
        "ok-terminal stream) vs the named waste taxonomy (overdecode, "
        "spec_rejected, replay, preempt_recompute, cancelled, "
        "probe_warmup); goodput + waste + pending == every token's "
        "worth of device work the ledger charged",
    ),
    MetricSpec(
        "ledger_busy_fraction", "gauge", ("engine",),
        "fraction of the engine's observed wall time in any non-idle "
        "phase (scrape-time — the serving-side pendant of the "
        "plugin's aggregate_chip_busy_fraction north star)",
    ),
    MetricSpec(
        "ledger_goodput_fraction", "gauge", ("engine",),
        "goodput tokens over every token's worth of device work "
        "charged (scrape-time; 1.0 = zero waste)",
    ),
    MetricSpec(
        "ledger_pending_tokens", "gauge", ("engine",),
        "tokens charged but not yet classified (their request has no "
        "terminal status yet; scrape-time — drains to 0 at quiescence "
        "on a standalone engine)",
    ),
    MetricSpec(
        "ledger_waste_chip_seconds", "gauge", ("engine", "class"),
        "estimated chip-SECONDS behind each waste class (the phase "
        "times scaled by the class's token share of its phase — an "
        "attribution model, documented in workloads/ledger.py; "
        "scrape-time)",
    ),
    MetricSpec(
        "fleet_ledger_tokens_total", "counter",
        ("fleet", "slo_class", "kind"),
        "fleet-scope terminal token classification per SLO class: "
        "kind=goodput (ok streams) vs kind=waste (cancelled/expired/"
        "failed streams) — the per-class goodput split the scheduler "
        "reads",
    ),
    MetricSpec(
        "fleet_ledger_goodput_fraction", "gauge", ("fleet",),
        "fleet-wide goodput tokens over all charged device work, "
        "failover replays and engine-local waste included "
        "(scrape-time)",
    ),
)


@dataclass
class RequestSpan:
    """One finished request's lifecycle, flattened from its Request
    stamps at retirement.  Segment invariants (``t_submit <= t_admit <=
    t_first <= t_done``) hold whenever the engine stamped all four;
    requests that finish AT admission have ``t_first == t_done``.

    ``status`` is the request's terminal status ("ok" / "cancelled" /
    "expired" / "failed") — non-ok spans may be missing admit/first
    stamps (a request cancelled while queued never admitted)."""

    rid: str
    t_submit: float
    t_admit: float | None
    t_first: float | None
    t_done: float
    n_tokens: int
    status: str = "ok"

    @property
    def queue_wait_secs(self) -> float | None:
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    @property
    def prefill_secs(self) -> float | None:
        """Admission -> first token: the prefill + first-sample segment
        (under batched admission this includes riding the step's shared
        sweep; under a ``prefill_budget`` it spans every step the
        admission sat parked mid-prefill — the trace's prefill segment
        is the honest budget-stretched window)."""
        if self.t_admit is None or self.t_first is None:
            return None
        return self.t_first - self.t_admit

    @property
    def decode_secs(self) -> float | None:
        if self.t_first is None:
            return None
        return self.t_done - self.t_first

    @property
    def ttft_secs(self) -> float | None:
        if self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def e2e_secs(self) -> float:
        return self.t_done - self.t_submit

    @classmethod
    def from_request(cls, req) -> "RequestSpan":
        return cls(
            rid=req.rid, t_submit=req.t_submit, t_admit=req.t_admit,
            t_first=req.t_first, t_done=req.t_done,
            n_tokens=len(req.tokens),
            status=getattr(req, "status", "ok"),
        )


@dataclass
class StepRecord:
    """One engine ``step()`` as the observer saw it.  ``mode`` is the
    decode program the step actually DISPATCHED ("plain" chunk, "spec"
    superstep) or "idle" (pure admission / drain / nothing-to-do
    steps).  ``readback_secs`` sums the host syncs the step performed
    (first-token readbacks + chunk/superstep consumes)."""

    index: int
    t_start: float
    dur_secs: float
    occupancy: int
    queue_depth: int
    admitted: int
    retired: int
    mode: str
    prefill_dispatches: int
    decode_dispatches: int
    sweeps: int
    tokens: int
    readback_secs: float
    # Budgeted chunked-prefill interleaving (prefill_budget): admissions
    # parked mid-prefill when the step ended, and the prompt tokens the
    # budget deferred THIS step (defaults keep records from unbudgeted
    # engines and older tooling identical).
    prefill_inflight: int = 0
    deferred_tokens: int = 0
    # Decode supersteps (superstep_k): wall ms this step spent BLOCKED
    # in host syncs (engine.host_sync_s delta — measured engine-side,
    # observer on or off), and the device decode steps computed past
    # rows' retirement points this step (the bounded over-decode the
    # fused readback reconciled).
    host_sync_ms: float = 0.0
    tokens_overdecoded: int = 0
    # Device-time attribution (workloads/profiler.py): estimated DEVICE
    # ms inside this step's wall window (0.0 for idle steps and for
    # records from older tooling — the default keeps them identical).
    device_ms: float = 0.0


@dataclass
class AttemptSpan:
    """One per-replica serving attempt of a fleet request — the unit
    the fleet-scope trace stitches.  A request that fails over carries
    several attempts: each later one is a RETRY CHILD of the previous
    (rendered as a chrome flow link), with ``outcome`` recording why
    the parent ended ("crash"/"hang" for charged faults, "drain" /
    "removed" / "closed" for uncharged operator or health moves,
    "failed" when the engine's own retry budget gave up, or the
    terminal engine status for the final attempt).  Stamps are on the
    fleet's clock (``time.perf_counter`` — the one clock every lane of
    the merged trace shares)."""

    replica: int
    t_dispatch: float
    t_admit: float | None = None
    t_first: float | None = None
    t_end: float | None = None
    tokens: int = 0
    outcome: str = "running"
    charged: bool = False


@dataclass
class FleetSpan:
    """One fleet request's whole lifecycle on the fleet's clock:
    router enqueue -> each per-replica attempt -> exactly one terminal
    status.  ``t_admit``/``t_first`` are FIRST-segment stamps (a
    failover's re-admission never resets them), so queue-wait and TTFT
    attribution stay correct across failovers; ``attempts`` carries
    the per-replica segments with their fault kinds."""

    rid: str
    t_submit: float
    t_done: float
    status: str
    n_tokens: int
    slo_class: str | None = None
    slo_attained: bool | None = None
    t_admit: float | None = None
    t_first: float | None = None
    failovers: int = 0
    attempts: list = field(default_factory=list)

    @property
    def queue_wait_secs(self) -> float | None:
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    @property
    def ttft_secs(self) -> float | None:
        if self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def e2e_secs(self) -> float:
        return self.t_done - self.t_submit

    @property
    def tpot_secs(self) -> float | None:
        """Per-token decode time (first token -> done over the n-1
        decoded tokens) — the bulk class's bound.  None for spans that
        never decoded past their first token."""
        if self.t_first is None or self.n_tokens < 2:
            return None
        return (self.t_done - self.t_first) / (self.n_tokens - 1)

    @classmethod
    def from_fleet_request(cls, fr) -> "FleetSpan":
        return cls(
            rid=fr.rid, t_submit=fr.t_submit, t_done=fr.t_done,
            status=fr.status, n_tokens=len(fr.tokens),
            slo_class=getattr(fr, "slo_class", None),
            slo_attained=getattr(fr, "slo_attained", None),
            t_admit=fr.t_admit, t_first=fr.t_first,
            failovers=getattr(fr, "failovers", 0),
            attempts=list(getattr(fr, "attempts", ())),
        )


@dataclass
class SupervisorEvent:
    """One instant on the supervision timeline (death, backoff wait,
    canary probe, quarantine, rejoin, ...) — rendered as an instant
    event on the merged fleet trace's supervisor lane.  Lives here
    (jax-free, next to the other span types) so the trace tooling
    never needs the supervisor module."""

    t: float
    kind: str
    chip_id: str = ""
    detail: str = ""


class EngineObserver:
    """Opt-in observability for one ServeEngine.

    Construct it, pass it to the engine (``ServeEngine(...,
    observer=obs)``), and optionally ``bind_registry()`` it to a
    metrics Registry.  The engine drives the ``_step_begin`` /
    ``_step_end`` / ``_note_readback`` hooks; everything user-facing is
    the rings (``steps`` / ``spans``), their ``drain_*`` APIs, and
    ``export_trace``.

    Ring bounds: both rings are bounded (``step_limit`` /
    ``span_limit``); evictions are COUNTED (``dropped_steps`` /
    ``dropped_spans``) so a long-running caller who forgot to drain can
    see exactly how much history it lost rather than silently reading a
    truncated timeline."""

    def __init__(
        self,
        *,
        step_limit: int = 2048,
        span_limit: int = 2048,
        name: str = "0",
        replica: str = "",
        device_table=None,
    ):
        if step_limit < 1 or span_limit < 1:
            raise ValueError(
                f"step_limit/span_limit must be >= 1, got "
                f"{step_limit}/{span_limit}"
            )
        self.name = name
        # Fleet mode: a non-empty ``replica`` adds a replica=<id> label
        # to every series AND keys the gauge registrations, so N
        # engines share one registry without colliding.  The empty
        # default keeps single-engine scrape output BYTE-compatible
        # (no replica label, name-keyed gauges) — pinned by
        # tests/test_metrics_lint.py.
        self.replica = replica
        self.steps: deque[StepRecord] = deque(maxlen=step_limit)
        self.spans: deque[RequestSpan] = deque(maxlen=span_limit)
        self.dropped_steps = 0
        self.dropped_spans = 0
        # Device-time attribution (workloads/profiler.py): an optional
        # DeviceTimeTable smooths per-dispatch device estimates; the
        # wall/device running sums back the busy/stall fraction gauges
        # either way (pure host arithmetic over values the step hooks
        # already computed — nothing here touches device state).
        self.device_table = device_table
        self._wall_ms = 0.0
        self._device_ms = 0.0
        self._step_index = 0
        self._readback_secs = 0.0
        self._registry = None
        self._labels: dict = {}
        self._engine = None
        # Last value pushed to the registry per lifecycle counter: these
        # engine counters can also move BETWEEN steps (cancel(),
        # QueueFull rejections at submit time), so per-step snapshot
        # deltas would drop those increments — each _step_end pushes
        # the difference against the engine's running total instead.
        self._pushed: dict[str, float] = {}

    # ---- registry bridge -------------------------------------------------

    def bind_registry(self, reg, labels: dict | None = None) -> None:
        """Attach this observer to a metrics Registry: describe every
        family in ENGINE_METRICS (histograms get the seconds-scale
        bucket ladder), register the scrape-time gauges, and start
        pushing counter/histogram updates from the step hooks.  All
        series carry an ``engine=<name>`` label so several engines can
        share one registry.  Without a ``replica`` id, gauge
        registration replaces by name (give concurrent engines distinct
        observer names and bind the LAST one, or separate registries —
        the single-engine contract, unchanged); WITH one (fleet mode),
        each observer's gauges register under its own key, every series
        additionally carries ``replica=<id>``, and N replicas coexist
        on one registry.  ``unbind_registry()`` detaches when the
        engine retires."""
        self._registry = reg
        self._labels = dict(labels or {})
        self._labels.setdefault("engine", self.name)
        if self.replica:
            self._labels.setdefault("replica", self.replica)
        for m in ENGINE_METRICS:
            if m.type == "histogram":
                # Per-dispatch device windows need the sub-millisecond
                # ladder; every serving latency keeps the seconds scale.
                buckets = (
                    DEVICE_SECONDS_BUCKETS
                    if m.name == "engine_device_seconds"
                    else SERVE_SECONDS_BUCKETS
                )
                reg.describe(m.name, m.help, buckets=buckets)
            else:
                reg.describe(m.name, m.help)
        # Ledger families describe unconditionally (the engine may not
        # exist yet at bind time); their gauges read empty until an
        # armed ledger appears, and the counter pushes are delta-gated.
        for m in LEDGER_METRICS:
            if m.labels[0] == "engine":
                reg.describe(m.name, m.help)
        key = f"replica:{self.replica}" if self.replica else None
        for name, reader in self._GAUGE_READERS.items():
            reg.register_gauge(
                name, lambda reader=reader: self._gauge(reader), key=key
            )
        for name, reader in self._LEDGER_GAUGE_READERS.items():
            reg.register_gauge(
                name, lambda reader=reader: self._ledger_gauge(reader),
                key=key,
            )

    # One engine reader per gauge family in ENGINE_METRICS — bind and
    # unbind both iterate this mapping, so a new gauge cannot be
    # registered without also being unregistered (and the lint test
    # pins it against the catalog).
    _GAUGE_READERS = {
        "engine_queue_depth": lambda e: len(e.pending),
        "engine_slot_occupancy": lambda e: int(e._occupied.sum()),
        "engine_slots": lambda e: e.slots,
        "engine_resident_pages": lambda e: e.ctrl.used_pages,
        "engine_prefill_inflight": (
            lambda e: len(getattr(e, "_inflight_prefill", ()))
        ),
        "engine_paused": (
            lambda e: 1.0 if getattr(e, "paused", False) else 0.0
        ),
        "engine_kv_offloaded_pages": (
            lambda e: getattr(
                getattr(e, "prefix", None), "offloaded_pages", 0
            ) or 0
        ),
        "engine_kv_disk_pages": (
            lambda e: getattr(e, "kv_disk_pages", 0) or 0
        ),
        # Device-time split (workloads/profiler.py): read back through
        # the engine's bound observer; engines without one (or before
        # any step) read empty via _gauge's teardown guard.
        "engine_device_busy_fraction": (
            lambda e: e._obs.device_busy_fraction
        ),
        "engine_host_stall_fraction": (
            lambda e: e._obs.host_stall_fraction
        ),
    }

    # Chip-time ledger gauges (LEDGER_METRICS): ``e`` is the bound
    # engine's ChipTimeLedger; a reader may return a scalar or a
    # [(labels, value), ...] list.  Registered alongside the engine
    # gauges (replica-keyed in fleet mode) and read empty until a
    # ledger is armed.
    _LEDGER_GAUGE_READERS = {
        "ledger_busy_fraction": lambda e: e.busy_fraction,
        "ledger_goodput_fraction": lambda e: e.goodput_fraction,
        "ledger_pending_tokens": lambda e: e.pending_tokens,
        "ledger_waste_chip_seconds": lambda e: [
            ({"class": c}, s) for c, s in sorted(e.waste_chip_s().items())
        ],
    }

    # Lifecycle counter families -> the ServeEngine attribute carrying
    # the running total (fault-tolerance telemetry; the catalog, the
    # lint test and the rendered docs all see these via ENGINE_METRICS).
    _LIFECYCLE_COUNTERS = {
        "engine_requests_cancelled_total": "requests_cancelled",
        "engine_requests_expired_total": "requests_expired",
        "engine_requests_failed_total": "requests_failed",
        "engine_requests_retried_total": "requests_retried",
        "engine_queue_rejections_total": "queue_rejections",
        # Fast-start telemetry (workloads/faststart.py): snapshot
        # calibration skips and the per-engine persistent-compile-cache
        # deltas (properties over the process-global counters).
        "engine_calibration_reused_total": "calibration_reused",
        "engine_compile_cache_hits_total": "compile_cache_hits",
        "engine_compile_cache_misses_total": "compile_cache_misses",
    }

    def unbind_registry(self) -> None:
        """Detach from the bound registry: unregister the gauge
        collectors (whose closures otherwise pin this observer — and
        through it the engine's params and KV page pools — on the
        registry forever) and stop pushing counters.  Call it when the
        engine retires in a long-lived process; already-accumulated
        counter/histogram series stay on the registry, monotonic, but
        no dead engine keeps scraping as live state.  Gauge
        registration replaces by name, so unbind the retiring observer
        BEFORE binding its successor — unbinding afterwards would
        remove the successor's collectors.  (Fleet mode is immune:
        replica-keyed registrations unbind only their own key, so one
        replica retiring never touches its siblings'.)"""
        reg, self._registry = self._registry, None
        if reg is None:
            return
        key = f"replica:{self.replica}" if self.replica else None
        for name in self._GAUGE_READERS:
            reg.unregister_gauge(name, key=key)
        for name in self._LEDGER_GAUGE_READERS:
            reg.unregister_gauge(name, key=key)
        self._engine = None

    def _gauge(self, value_fn) -> list[tuple[dict, float]]:
        eng = self._engine
        if eng is None:
            return []
        try:
            return [(dict(self._labels), float(value_fn(eng)))]
        except Exception:
            # A gauge must never fail a scrape mid-teardown; the
            # Registry logs collector failures, an empty read is honest.
            return []

    def _ledger_gauge(self, value_fn) -> list[tuple[dict, float]]:
        led = getattr(self._engine, "ledger", None)
        if led is None:
            return []
        try:
            out = value_fn(led)
            if isinstance(out, list):
                return [
                    ({**self._labels, **labels}, float(v))
                    for labels, v in out
                ]
            return [(dict(self._labels), float(out))]
        except Exception:
            return []  # a gauge must never fail a scrape mid-teardown

    # ---- device-time split (workloads/profiler.py) -----------------------

    @property
    def device_busy_fraction(self) -> float:
        """Fraction of observed step wall the device was busy, over
        this observer's whole run (0.0 before any step)."""
        if self._wall_ms <= 0:
            return 0.0
        return min(self._device_ms / self._wall_ms, 1.0)

    @property
    def host_stall_fraction(self) -> float:
        if self._wall_ms <= 0:
            return 0.0
        return 1.0 - self.device_busy_fraction

    # ---- engine-facing hooks --------------------------------------------

    def _bind(self, engine) -> None:
        self._engine = engine

    def _note_readback(self, secs: float) -> None:
        """Called by the engine around every host sync (first-token
        readbacks, chunk/superstep consumes) while an observer is
        attached."""
        self._readback_secs += secs

    def _step_begin(self, engine) -> tuple:
        self._readback_secs = 0.0
        prefix = getattr(engine, "prefix", None)
        return (
            time.perf_counter(),
            engine.generated_tokens,
            engine.requests_admitted,
            engine.requests_retired,
            engine.prefill_dispatches,
            engine.prefill_sweeps,
            engine.chunks_run,
            engine.spec_rounds,
            engine.mode_switches,
            getattr(engine, "prefill_deferred_tokens", 0),
            getattr(engine, "host_sync_s", 0.0),
            getattr(engine, "tokens_overdecoded", 0),
            getattr(prefix, "hits", 0),
            getattr(prefix, "misses", 0),
        )

    def _step_end(self, engine, snap: tuple, finished) -> StepRecord:
        (
            t0, tokens0, adm0, ret0, pd0, sw0, ch0, sr0, ms0, dt0, hs0,
            od0, ph0, pm0,
        ) = snap
        dur = time.perf_counter() - t0
        host_sync = getattr(engine, "host_sync_s", 0.0) - hs0
        overdecoded = getattr(engine, "tokens_overdecoded", 0) - od0
        tokens = engine.generated_tokens - tokens0
        admitted = engine.requests_admitted - adm0
        retired = engine.requests_retired - ret0
        # chunks_run counts device decode CHUNKS; a superstep engine
        # runs superstep_k of them per dispatch, so normalize both
        # decode families to DISPATCH counts.
        chunk_d = (engine.chunks_run - ch0) // max(
            getattr(engine, "superstep_k", 1), 1
        )
        spec_rounds_d = engine.spec_rounds - sr0
        spec_d = spec_rounds_d // max(
            engine.spec_lookahead, getattr(engine, "spec_superstep_k", 1), 1
        )
        # The mode the step actually DISPATCHED: the engine runs at most
        # one decode program per step (drains only consume in-flight
        # work; they never dispatch).
        mode = "spec" if spec_d else ("plain" if chunk_d else "idle")
        # Device-time attribution: the measured device window is the
        # step wall minus the engine-measured host-sync stall; idle
        # steps (pure admission/drain, no dispatch) attribute nothing.
        # A prefill-only step dispatches too — count it as its own
        # program so the calibration table keys don't mix phases.
        program = mode
        if mode == "idle" and engine.prefill_dispatches - pd0 > 0:
            program = "prefill"
        device_ms = 0.0
        if program != "idle":
            measured_ms = max((dur - host_sync) * 1000.0, 0.0)
            device_ms = measured_ms
            if self.device_table is not None:
                batch = int(engine._occupied.sum())
                self.device_table.observe(
                    program, tokens, batch, measured_ms
                )
                est = self.device_table.estimate(program, tokens, batch)
                if est is not None:
                    device_ms = est
        self._wall_ms += dur * 1000.0
        self._device_ms += device_ms
        rec = StepRecord(
            index=self._step_index,
            t_start=t0,
            dur_secs=dur,
            occupancy=int(engine._occupied.sum()),
            queue_depth=len(engine.pending),
            admitted=admitted,
            retired=retired,
            mode=mode,
            prefill_dispatches=engine.prefill_dispatches - pd0,
            decode_dispatches=chunk_d + spec_d,
            sweeps=engine.prefill_sweeps - sw0,
            tokens=tokens,
            readback_secs=self._readback_secs,
            prefill_inflight=len(getattr(engine, "_inflight_prefill", ())),
            deferred_tokens=(
                getattr(engine, "prefill_deferred_tokens", 0) - dt0
            ),
            host_sync_ms=round(host_sync * 1000, 3),
            tokens_overdecoded=overdecoded,
            device_ms=round(device_ms, 3),
        )
        self._step_index += 1
        if len(self.steps) == self.steps.maxlen:
            self.dropped_steps += 1
        self.steps.append(rec)
        new_spans = self._record_spans(finished)
        reg = self._registry
        if reg is not None:
            labels = self._labels
            if tokens:
                reg.inc("engine_tokens_total", labels, tokens)
            if admitted:
                reg.inc("engine_requests_admitted_total", labels, admitted)
            if retired:
                reg.inc("engine_requests_retired_total", labels, retired)
            if rec.prefill_dispatches:
                reg.inc(
                    "engine_prefill_dispatches_total", labels,
                    rec.prefill_dispatches,
                )
            if rec.deferred_tokens:
                reg.inc(
                    "engine_prefill_deferred_tokens_total", labels,
                    rec.deferred_tokens,
                )
            switches = engine.mode_switches - ms0
            if switches:
                reg.inc("engine_mode_switches_total", labels, switches)
            if overdecoded:
                reg.inc(
                    "engine_tokens_overdecoded_total", labels, overdecoded
                )
            prefix = getattr(engine, "prefix", None)
            prefix_hits = getattr(prefix, "hits", 0) - ph0
            prefix_misses = getattr(prefix, "misses", 0) - pm0
            if prefix_hits:
                reg.inc(
                    "engine_prefix_hit_pages_total", labels, prefix_hits
                )
            if prefix_misses:
                reg.inc("engine_prefix_miss_total", labels, prefix_misses)
            if host_sync > 0:
                reg.observe_seconds("engine_host_sync", host_sync, labels)
            if rec.device_ms > 0:
                reg.observe_seconds(
                    "engine_device", rec.device_ms / 1000.0, labels
                )
            self._push_lifecycle(engine, reg, labels)
            self._push_ring_drops(reg, labels)
            self._push_ledger(engine, reg, labels)
            if mode != "idle":
                reg.inc(
                    "engine_decode_steps_total", {**labels, "mode": mode}
                )
            reg.observe_seconds("engine_step", dur, labels)
            for span in new_spans:
                if span.ttft_secs is not None:
                    reg.observe_seconds(
                        "engine_ttft", span.ttft_secs, labels
                    )
                reg.observe_seconds("engine_e2e", span.e2e_secs, labels)
        return rec

    def _record_spans(self, finished) -> list[RequestSpan]:
        """Append one RequestSpan per finished request to the bounded
        ring, counting drops; returns the new spans."""
        new_spans = [RequestSpan.from_request(req) for req in finished]
        for span in new_spans:
            if len(self.spans) == self.spans.maxlen:
                self.dropped_spans += 1
            self.spans.append(span)
        return new_spans

    def _push_lifecycle(self, engine, reg, labels) -> None:
        """Push the lifecycle counter families as deltas against the
        engine's running totals (totals, not per-step increments, so
        between-step transitions — cancels, rejections, close-time
        fails — land on the registry too)."""
        for metric, attr in self._LIFECYCLE_COUNTERS.items():
            total = float(getattr(engine, attr, 0))
            delta = total - self._pushed.get(metric, 0.0)
            if delta:
                reg.inc(metric, labels, delta)
                self._pushed[metric] = total

    def _push_ring_drops(self, reg, labels) -> None:
        """Ring-overflow visibility: evictions the bounded step/span
        rings made unread land as counters, so silent history loss is
        a scrapeable signal instead of a surprise during a postmortem."""
        for metric, total in (
            ("engine_observer_dropped_steps_total", self.dropped_steps),
            ("engine_observer_dropped_spans_total", self.dropped_spans),
        ):
            delta = float(total) - self._pushed.get(metric, 0.0)
            if delta:
                reg.inc(metric, labels, delta)
                self._pushed[metric] = float(total)

    def _push_ledger(self, engine, reg, labels) -> None:
        """Chip-time ledger counter families, pushed as deltas against
        the armed ledger's running totals (phase seconds and the
        goodput/waste token taxonomy — LEDGER_METRICS)."""
        led = getattr(engine, "ledger", None)
        if led is None:
            return
        for phase, secs in led.phase_s.items():
            key = f"ledger_chip_seconds_total:{phase}"
            delta = float(secs) - self._pushed.get(key, 0.0)
            if delta > 0:
                reg.inc(
                    "ledger_chip_seconds_total",
                    {**labels, "phase": phase}, delta,
                )
                self._pushed[key] = float(secs)
        classes = [("goodput", led.goodput_tokens)]
        classes += sorted(led.waste_tokens.items())
        for cls, total in classes:
            key = f"ledger_tokens_total:{cls}"
            delta = float(total) - self._pushed.get(key, 0.0)
            if delta > 0:
                reg.inc(
                    "ledger_tokens_total", {**labels, "class": cls}, delta
                )
                self._pushed[key] = float(total)

    def _engine_closed(self, engine, finished) -> None:
        """Final flush at ``engine.close()``: counters are pushed and
        spans recorded at step boundaries, but close() fails in-flight
        work and then refuses further steps — so the last lifecycle
        deltas and the close-failed requests' spans land here, before
        the registry unbinds (a shutdown that failed N requests must
        not scrape as 0 failures)."""
        new_spans = self._record_spans(finished)
        reg = self._registry
        if reg is None:
            return
        labels = self._labels
        self._push_lifecycle(engine, reg, labels)
        self._push_ring_drops(reg, labels)
        self._push_ledger(engine, reg, labels)
        for span in new_spans:
            if span.ttft_secs is not None:
                reg.observe_seconds("engine_ttft", span.ttft_secs, labels)
            reg.observe_seconds("engine_e2e", span.e2e_secs, labels)

    # ---- drains ---------------------------------------------------------

    def drain_steps(self) -> list[StepRecord]:
        """Hand back (and clear) the step-record ring — the same
        between-measurement-windows contract as
        ``engine.drain_completed()``."""
        out = list(self.steps)
        self.steps.clear()
        return out

    def drain_spans(self) -> list[RequestSpan]:
        """Hand back (and clear) the finished-request span ring."""
        out = list(self.spans)
        self.spans.clear()
        return out

    # ---- chrome trace export --------------------------------------------

    def export_trace(self, path: str) -> int:
        """Write the recorded timeline as chrome://tracing-loadable
        trace_event JSON.  Returns the number of trace events written."""
        trace = trace_events(self)
        with open(path, "w") as f:
            json.dump(trace, f)
            f.write("\n")
        return len(trace["traceEvents"])


class FleetObserver:
    """Fleet-level Prometheus bridge (workloads/fleet.py): aggregate
    counters, scrape-time gauges and pooled latency histograms NEXT TO
    the per-replica engine series (give each replica's EngineObserver a
    distinct ``replica=`` id and bind everything to one registry).

    Same discipline as the engine bridge: inert (host counters only,
    never scheduling state), jax-free, counters pushed as deltas
    against the fleet's running totals at each ``Fleet.step()``.

    Beyond the bridge, the observer keeps the FLEET-SCOPE request
    timeline: one ``FleetSpan`` per terminal request (router enqueue ->
    every per-replica attempt -> exactly one terminal status) in a
    bounded ring with the engine observer's drain/dropped contract —
    the raw material ``fleet_trace_events`` merges into one chrome
    trace.  Spans record with or without a bound registry."""

    def __init__(self, *, name: str = "0", span_limit: int = 2048):
        if span_limit < 1:
            raise ValueError(
                f"span_limit must be >= 1, got {span_limit}"
            )
        self.name = name
        self.spans: deque[FleetSpan] = deque(maxlen=span_limit)
        self.dropped_spans = 0
        self._registry = None
        self._labels: dict = {}
        self._fleet = None
        self._pushed: dict[str, float] = {}

    # Scrape-time readers; ``e`` is the bound Fleet (the lint's
    # reader-regex contract shared with the engine bridge).
    _FLEET_GAUGE_READERS = {
        "fleet_queue_depth": lambda e: [({}, float(len(e.queue)))],
        "fleet_replicas": lambda e: [
            ({"state": state}, float(
                sum(1 for r in e.replicas if r.state == state)
            ))
            for state in ("active", "draining", "dead")
        ],
        "fleet_replica_state": lambda e: [
            ({"replica": str(r.index), "state": r.state}, 1.0)
            for r in e.replicas if r.state != "dead"
        ],
        "fleet_replica_paused": lambda e: [
            ({"replica": str(r.index)}, 1.0 if r.paused else 0.0)
            for r in e.replicas if r.state != "dead"
        ],
        "fleet_slo_burn_rate": lambda e: [
            ({"slo_class": name}, float(rate))
            for name, rate in sorted(e.slo_burn_rates().items())
        ],
        "fleet_replica_role": lambda e: [
            ({"replica": str(r.index), "role": r.role}, 1.0)
            for r in e.replicas if r.state != "dead"
        ],
        "fleet_free_pages": lambda e: [
            ({"tier": "hbm"}, float(sum(
                r.free_pages() or 0 for r in e.replicas
                if r.state != "dead" and hasattr(r, "free_pages")
            ))),
            ({"tier": "host"}, float(sum(
                r.host_free_pages() for r in e.replicas
                if r.state != "dead" and hasattr(r, "host_free_pages")
            ))),
        ],
    }

    # Fleet-scope chip-time ledger gauge (LEDGER_METRICS): reads the
    # armed FleetLedger off the bound fleet; empty until one exists.
    # The counter-derived property, NOT a full snapshot — this runs on
    # every scrape.
    _FLEET_LEDGER_GAUGE_READERS = {
        "fleet_ledger_goodput_fraction": lambda e: (
            [({}, float(e.ledger.goodput_fraction))]
            if getattr(e, "ledger", None) is not None else []
        ),
    }

    # Counter family -> Fleet attribute carrying the running total.
    _FLEET_COUNTERS = {
        "fleet_requests_total": "requests_submitted",
        "fleet_tokens_total": "generated_tokens",
        "fleet_failovers_total": "failover_requeues",
        "fleet_drain_requeues_total": "drain_requeues",
        "fleet_queue_rejections_total": "queue_rejections",
        "fleet_kv_handoffs_total": "kv_handoffs",
        "fleet_handoff_pages_total": "handoff_pages",
        "fleet_page_dispatches_total": "page_dispatches",
        "fleet_stats_published_total": "stats_published",
        "fleet_journal_writes_total": "journal_writes",
        "fleet_journal_torn_total": "journal_torn",
        "fleet_sessions_restored_total": "sessions_restored",
    }

    def bind_registry(self, reg, labels: dict | None = None) -> None:
        self._registry = reg
        self._labels = dict(labels or {})
        self._labels.setdefault("fleet", self.name)
        for m in FLEET_METRICS:
            if m.type == "histogram":
                reg.describe(m.name, m.help, buckets=SERVE_SECONDS_BUCKETS)
            else:
                reg.describe(m.name, m.help)
        for m in LEDGER_METRICS:
            if m.labels[0] == "fleet":
                reg.describe(m.name, m.help)
        for name, reader in {
            **self._FLEET_GAUGE_READERS,
            **self._FLEET_LEDGER_GAUGE_READERS,
        }.items():
            reg.register_gauge(
                name, lambda reader=reader: self._gauge(reader),
                key=f"fleet:{self.name}",
            )

    def unbind_registry(self) -> None:
        reg, self._registry = self._registry, None
        if reg is None:
            return
        for name in self._FLEET_GAUGE_READERS:
            reg.unregister_gauge(name, key=f"fleet:{self.name}")
        for name in self._FLEET_LEDGER_GAUGE_READERS:
            reg.unregister_gauge(name, key=f"fleet:{self.name}")
        self._fleet = None

    def _gauge(self, value_fn) -> list[tuple[dict, float]]:
        fleet = self._fleet
        if fleet is None:
            return []
        try:
            return [
                ({**self._labels, **labels}, float(v))
                for labels, v in value_fn(fleet)
            ]
        except Exception:
            return []  # a gauge must never fail a scrape mid-teardown

    # ---- fleet-facing hooks ---------------------------------------------

    def _bind(self, fleet) -> None:
        self._fleet = fleet

    def drain_spans(self) -> list[FleetSpan]:
        """Hand back (and clear) the fleet-span ring — the same
        between-measurement-windows contract as the engine observer's."""
        out = list(self.spans)
        self.spans.clear()
        return out

    def _fleet_step_end(self, fleet, finished) -> None:
        # The span ring fills whether or not a registry is bound — a
        # --trace-out run without --metrics-port still gets its merged
        # timeline.
        new_spans = []
        for fr in finished:
            span = FleetSpan.from_fleet_request(fr)
            if len(self.spans) == self.spans.maxlen:
                self.dropped_spans += 1
            self.spans.append(span)
            new_spans.append(span)
        reg = self._registry
        if reg is None:
            return
        labels = self._labels
        for metric, attr in self._FLEET_COUNTERS.items():
            total = float(getattr(fleet, attr, 0))
            delta = total - self._pushed.get(metric, 0.0)
            if delta:
                reg.inc(metric, labels, delta)
                self._pushed[metric] = total
        for kind, attr in (
            ("crash", "replica_crashes"), ("hang", "replica_hangs"),
        ):
            metric = f"fleet_replica_failures_total:{kind}"
            total = float(getattr(fleet, attr, 0))
            delta = total - self._pushed.get(metric, 0.0)
            if delta:
                reg.inc(
                    "fleet_replica_failures_total",
                    {**labels, "kind": kind}, delta,
                )
                self._pushed[metric] = total
        for cls, total in sorted(
            getattr(fleet, "wfq_dispatches", {}).items()
        ):
            metric = f"fleet_wfq_dispatches_total:{cls}"
            delta = float(total) - self._pushed.get(metric, 0.0)
            if delta:
                reg.inc(
                    "fleet_wfq_dispatches_total",
                    {**labels, "slo_class": cls or "untagged"}, delta,
                )
                self._pushed[metric] = float(total)
        # Ring-overflow visibility (the engine bridge's contract).
        drops = float(self.dropped_spans)
        drop_delta = drops - self._pushed.get(
            "fleet_observer_dropped_spans_total", 0.0
        )
        if drop_delta:
            reg.inc(
                "fleet_observer_dropped_spans_total", labels, drop_delta
            )
            self._pushed["fleet_observer_dropped_spans_total"] = drops
        # Fleet-scope ledger: per-SLO-class terminal token
        # classification, pushed as running-total deltas.
        led = getattr(fleet, "ledger", None)
        if led is not None:
            for cls, counts in sorted(
                getattr(led, "class_tokens", {}).items()
            ):
                for kind in ("goodput", "waste"):
                    key = f"fleet_ledger_tokens_total:{cls}:{kind}"
                    total = float(counts.get(kind, 0))
                    delta = total - self._pushed.get(key, 0.0)
                    if delta > 0:
                        reg.inc(
                            "fleet_ledger_tokens_total",
                            {**labels, "slo_class": cls, "kind": kind},
                            delta,
                        )
                        self._pushed[key] = total
        # Handoff windows closed since the last step (the list only
        # appends, so the pushed length is the delta cursor).
        windows = getattr(fleet, "handoff_s", ())
        seen = int(self._pushed.get("fleet_handoff_seconds:n", 0.0))
        for secs in list(windows)[seen:]:
            reg.observe_seconds("fleet_handoff", secs, labels)
        self._pushed["fleet_handoff_seconds:n"] = float(len(windows))
        for span in new_spans:
            if span.queue_wait_secs is not None:
                reg.observe_seconds(
                    "fleet_queue_wait", span.queue_wait_secs, labels
                )
            if span.ttft_secs is not None:
                reg.observe_seconds("fleet_ttft", span.ttft_secs, labels)
            if span.e2e_secs is not None:
                reg.observe_seconds("fleet_e2e", span.e2e_secs, labels)
            if span.slo_class is None:
                continue
            cls_labels = {**labels, "slo_class": span.slo_class}
            # The fleet's accounting decision travels on the request:
            # slo_attained is None for spans the fleet excluded
            # (cancelled — a client abort is not an SLO verdict).
            if span.slo_attained is not None:
                reg.inc("fleet_slo_requests_total", cls_labels)
                if span.slo_attained:
                    reg.inc("fleet_slo_attained_total", cls_labels)
            if span.ttft_secs is not None:
                reg.observe_seconds(
                    "fleet_class_ttft", span.ttft_secs, cls_labels
                )
            if span.tpot_secs is not None:
                reg.observe_seconds(
                    "fleet_class_tpot", span.tpot_secs, cls_labels
                )


class SupervisorObserver:
    """Supervisor-level Prometheus bridge (workloads/supervisor.py):
    restart / crash-loop / quarantine counters, a slots-by-state
    scrape gauge and the restore-time histogram, NEXT TO the fleet and
    per-replica engine series on one shared registry.

    Same discipline as the other bridges: inert (host counters only,
    never scheduling state), jax-free, counters pushed as deltas
    against the supervisor's running totals at each ``poll()``."""

    def __init__(self, *, name: str = "0"):
        self.name = name
        self._registry = None
        self._labels: dict = {}
        self._supervisor = None
        self._pushed: dict[str, float] = {}
        self._restores_pushed = 0

    # Scrape-time readers; ``e`` is the bound FleetSupervisor (the
    # lint's reader-regex contract shared with the other bridges).
    _SUPERVISOR_GAUGE_READERS = {
        "supervisor_slots": lambda e: [
            ({"state": state}, float(
                sum(1 for s in e.slots if s.state == state)
            ))
            for state in (
                "serving", "backoff", "probing", "quarantined",
                "forgotten",
            )
        ],
    }

    # Counter family -> FleetSupervisor attribute with the running total.
    _SUPERVISOR_COUNTERS = {
        "supervisor_restarts_total": "restarts_total",
        "supervisor_restart_failures_total": "restart_failures",
        "supervisor_crash_loops_total": "crash_loops",
        "supervisor_health_deferrals_total": "health_deferrals",
    }

    def bind_registry(self, reg, labels: dict | None = None) -> None:
        self._registry = reg
        self._labels = dict(labels or {})
        self._labels.setdefault("supervisor", self.name)
        for m in SUPERVISOR_METRICS:
            if m.type == "histogram":
                reg.describe(m.name, m.help, buckets=SERVE_SECONDS_BUCKETS)
            else:
                reg.describe(m.name, m.help)
        for name, reader in self._SUPERVISOR_GAUGE_READERS.items():
            reg.register_gauge(
                name, lambda reader=reader: self._gauge(reader),
                key=f"supervisor:{self.name}",
            )

    def unbind_registry(self) -> None:
        reg, self._registry = self._registry, None
        if reg is None:
            return
        for name in self._SUPERVISOR_GAUGE_READERS:
            reg.unregister_gauge(name, key=f"supervisor:{self.name}")
        self._supervisor = None

    def _gauge(self, value_fn) -> list[tuple[dict, float]]:
        sup = self._supervisor
        if sup is None:
            return []
        try:
            return [
                ({**self._labels, **labels}, float(v))
                for labels, v in value_fn(sup)
            ]
        except Exception:
            return []  # a gauge must never fail a scrape mid-teardown

    # ---- supervisor-facing hooks ----------------------------------------

    def _bind(self, supervisor) -> None:
        self._supervisor = supervisor

    def _supervisor_poll_end(self, supervisor) -> None:
        reg = self._registry
        if reg is None:
            return
        labels = self._labels
        for metric, attr in self._SUPERVISOR_COUNTERS.items():
            total = float(getattr(supervisor, attr, 0))
            delta = total - self._pushed.get(metric, 0.0)
            if delta:
                reg.inc(metric, labels, delta)
                self._pushed[metric] = total
        drops = float(getattr(supervisor, "dropped_events", 0) or 0)
        drop_delta = drops - self._pushed.get(
            "supervisor_dropped_events_total", 0.0
        )
        if drop_delta:
            reg.inc("supervisor_dropped_events_total", labels, drop_delta)
            self._pushed["supervisor_dropped_events_total"] = drops
        fresh = supervisor.restore_s[self._restores_pushed:]
        for secs in fresh:
            reg.observe_seconds("supervisor_restore", secs, labels)
        self._restores_pushed += len(fresh)


class AutoscalerObserver:
    """Autoscaler-level Prometheus bridge (workloads/autoscaler.py):
    decision/actuation counters, the degradation-ladder level and the
    replicas-target-vs-live gauges, NEXT TO the fleet, supervisor and
    per-replica engine series on one shared registry.

    Same discipline as the other bridges: inert (host counters only,
    never control state), jax-free, counters pushed as deltas against
    the autoscaler's running totals at each ``poll()``."""

    def __init__(self, *, name: str = "0"):
        self.name = name
        self._registry = None
        self._labels: dict = {}
        self._autoscaler = None
        self._pushed: dict[str, float] = {}

    # Scrape-time readers; ``e`` is the bound FleetAutoscaler (the
    # lint's reader-regex contract shared with the other bridges).
    _AUTOSCALER_GAUGE_READERS = {
        "autoscaler_ladder_level": lambda e: [
            ({}, float(e.ladder_level))
        ],
        "autoscaler_replicas_target": lambda e: [
            ({}, float(e.target_replicas))
        ],
        "autoscaler_replicas_live": lambda e: [
            ({}, float(len(e.fleet.alive)))
        ],
    }

    # Counter family -> FleetAutoscaler attribute with the running
    # total.
    _AUTOSCALER_COUNTERS = {
        "autoscaler_scale_ups_total": "scale_ups",
        "autoscaler_scale_downs_total": "scale_downs",
        "autoscaler_spawn_failures_total": "spawn_failures",
        "autoscaler_brownouts_total": "brownouts",
        "autoscaler_preemptions_total": "preemptions_total",
    }

    def bind_registry(self, reg, labels: dict | None = None) -> None:
        self._registry = reg
        self._labels = dict(labels or {})
        self._labels.setdefault("autoscaler", self.name)
        for m in AUTOSCALER_METRICS:
            if m.type == "histogram":
                reg.describe(m.name, m.help, buckets=SERVE_SECONDS_BUCKETS)
            else:
                reg.describe(m.name, m.help)
        for name, reader in self._AUTOSCALER_GAUGE_READERS.items():
            reg.register_gauge(
                name, lambda reader=reader: self._gauge(reader),
                key=f"autoscaler:{self.name}",
            )

    def unbind_registry(self) -> None:
        reg, self._registry = self._registry, None
        if reg is None:
            return
        for name in self._AUTOSCALER_GAUGE_READERS:
            reg.unregister_gauge(name, key=f"autoscaler:{self.name}")
        self._autoscaler = None

    def _gauge(self, value_fn) -> list[tuple[dict, float]]:
        asc = self._autoscaler
        if asc is None:
            return []
        try:
            return [
                ({**self._labels, **labels}, float(v))
                for labels, v in value_fn(asc)
            ]
        except Exception:
            return []  # a gauge must never fail a scrape mid-teardown

    # ---- autoscaler-facing hooks ----------------------------------------

    def _bind(self, autoscaler) -> None:
        self._autoscaler = autoscaler

    def _autoscaler_poll_end(self, autoscaler) -> None:
        reg = self._registry
        if reg is None:
            return
        labels = self._labels
        for metric, attr in self._AUTOSCALER_COUNTERS.items():
            total = float(getattr(autoscaler, attr, 0))
            delta = total - self._pushed.get(metric, 0.0)
            if delta:
                reg.inc(metric, labels, delta)
                self._pushed[metric] = total
        for action, total in autoscaler.decisions.items():
            key = f"autoscaler_decisions_total:{action}"
            delta = float(total) - self._pushed.get(key, 0.0)
            if delta:
                reg.inc(
                    "autoscaler_decisions_total",
                    {**labels, "action": action}, delta,
                )
                self._pushed[key] = float(total)


class ControlObserver:
    """Goodput-controller Prometheus bridge (workloads/control.py):
    actuation counters and the EWMA signal gauges, NEXT TO the fleet,
    supervisor, autoscaler and per-replica engine series on one shared
    registry.

    Same discipline as the other bridges: inert (host counters only,
    never control state), jax-free, counters pushed as deltas against
    the controller's running totals at each ``poll()``."""

    def __init__(self, *, name: str = "0"):
        self.name = name
        self._registry = None
        self._labels: dict = {}
        self._controller = None
        self._pushed: dict[str, float] = {}

    # Scrape-time readers; ``e`` is the bound GoodputController (the
    # lint's reader-regex contract shared with the other bridges).
    # EWMA gauges emit NO sample until the signal has been measured —
    # a 0.0 placeholder would read as "perfect waste" on dashboards.
    _CONTROL_GAUGE_READERS = {
        "control_goodput_fraction": lambda e: (
            [] if e.goodput_fraction_ewma is None
            else [({}, float(e.goodput_fraction_ewma))]
        ),
        "control_spec_rejected_fraction": lambda e: (
            [] if e.spec_rejected_fraction_ewma is None
            else [({}, float(e.spec_rejected_fraction_ewma))]
        ),
        "control_overdecode_fraction": lambda e: (
            [] if e.overdecode_fraction_ewma is None
            else [({}, float(e.overdecode_fraction_ewma))]
        ),
    }

    # Counter family -> GoodputController attribute with the running
    # total.
    _CONTROL_COUNTERS = {
        "control_retunes_total": "retunes_applied",
        "control_wfq_reweights_total": "wfq_reweights",
        "control_dropped_events_total": "dropped_events",
    }

    def bind_registry(self, reg, labels: dict | None = None) -> None:
        self._registry = reg
        self._labels = dict(labels or {})
        self._labels.setdefault("controller", self.name)
        for m in CONTROL_METRICS:
            if m.type == "histogram":
                reg.describe(m.name, m.help, buckets=SERVE_SECONDS_BUCKETS)
            else:
                reg.describe(m.name, m.help)
        for name, reader in self._CONTROL_GAUGE_READERS.items():
            reg.register_gauge(
                name, lambda reader=reader: self._gauge(reader),
                key=f"controller:{self.name}",
            )

    def unbind_registry(self) -> None:
        reg, self._registry = self._registry, None
        if reg is None:
            return
        for name in self._CONTROL_GAUGE_READERS:
            reg.unregister_gauge(name, key=f"controller:{self.name}")
        self._controller = None

    def _gauge(self, value_fn) -> list[tuple[dict, float]]:
        ctrl = self._controller
        if ctrl is None:
            return []
        try:
            return [
                ({**self._labels, **labels}, float(v))
                for labels, v in value_fn(ctrl)
            ]
        except Exception:
            return []  # a gauge must never fail a scrape mid-teardown

    # ---- controller-facing hooks ----------------------------------------

    def _bind(self, controller) -> None:
        self._controller = controller

    def _control_poll_end(self, controller) -> None:
        reg = self._registry
        if reg is None:
            return
        labels = self._labels
        for metric, attr in self._CONTROL_COUNTERS.items():
            total = float(getattr(controller, attr, 0))
            delta = total - self._pushed.get(metric, 0.0)
            if delta:
                reg.inc(metric, labels, delta)
                self._pushed[metric] = total
        for action, total in controller.decisions.items():
            key = f"control_decisions_total:{action}"
            delta = float(total) - self._pushed.get(key, 0.0)
            if delta:
                reg.inc(
                    "control_decisions_total",
                    {**labels, "action": action}, delta,
                )
                self._pushed[key] = float(total)


def _us(t: float, t0: float) -> float:
    return round((t - t0) * 1e6, 3)


def trace_events(observer: EngineObserver, t0: float | None = None) -> dict:
    """Render an observer's rings (NON-destructively — drains are the
    caller's business) as a Chrome trace_event object: request lifecycle
    spans as complete ("X") events on a per-request lane under the
    "requests" process, step records as "X" events plus occupancy /
    queue-depth counter ("C") tracks under the "engine" process.  Load
    the written file in chrome://tracing or https://ui.perfetto.dev.
    ``t0`` pins the timeline origin to an EXTERNAL clock zero — the
    merged fleet trace passes the fleet-wide minimum so every lane
    shares one clock; standalone export derives it from the rings."""
    steps = list(observer.steps)
    spans = list(observer.spans)
    if t0 is None:
        stamps = [s.t_start for s in steps] + [sp.t_submit for sp in spans]
        t0 = min(stamps) if stamps else 0.0
    events: list[dict] = [
        {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
         "args": {"name": f"requests (engine {observer.name})"}},
        {"ph": "M", "pid": 2, "tid": 0, "name": "process_name",
         "args": {"name": f"engine {observer.name} steps"}},
        {"ph": "M", "pid": 2, "tid": 1, "name": "thread_name",
         "args": {"name": "step()"}},
        {"ph": "M", "pid": 2, "tid": 2, "name": "thread_name",
         "args": {"name": "device"}},
    ]
    for lane, span in enumerate(spans, start=1):
        events.append(
            {"ph": "M", "pid": 1, "tid": lane, "name": "thread_name",
             "args": {"name": span.rid}}
        )
        # A request that reached a terminal status while still queued
        # (cancelled/expired/failed-at-close) has no admit/first stamps;
        # its queued segment runs to t_done so the lane still shows it.
        segments = (
            ("queued", span.t_submit,
             span.t_admit if span.t_admit is not None else span.t_done),
            ("prefill", span.t_admit, span.t_first),
            ("decode", span.t_first, span.t_done),
        )
        for name, start, end in segments:
            if start is None or end is None:
                continue
            events.append({
                "ph": "X", "pid": 1, "tid": lane, "cat": "request",
                "name": name, "ts": _us(start, t0),
                "dur": max(_us(end, t0) - _us(start, t0), 0.0),
                "args": {
                    "rid": span.rid, "tokens": span.n_tokens,
                    "status": span.status,
                },
            })
    for rec in steps:
        events.append({
            "ph": "X", "pid": 2, "tid": 1, "cat": "step",
            "name": f"step[{rec.mode}]", "ts": _us(rec.t_start, t0),
            "dur": max(round(rec.dur_secs * 1e6, 3), 0.0),
            "args": {
                f.name: getattr(rec, f.name)
                for f in fields(rec) if f.name not in ("t_start", "index")
            },
        })
        # Device lane (workloads/profiler.py): the step's attributed
        # device window rendered directly under its step() span — in
        # the merged fleet trace this lane rides each replica's
        # process, aligned under that replica's attempt spans.
        if getattr(rec, "device_ms", 0.0) > 0:
            program = rec.mode
            if program == "idle" and rec.prefill_dispatches > 0:
                program = "prefill"
            events.append({
                "ph": "X", "pid": 2, "tid": 2, "cat": "device",
                "name": f"device[{program}]",
                "ts": _us(rec.t_start, t0),
                "dur": max(round(rec.device_ms * 1000.0, 3), 0.0),
                "args": {
                    "device_ms": rec.device_ms,
                    "host_sync_ms": rec.host_sync_ms,
                    "mode": rec.mode,
                },
            })
        for counter, value in (
            ("occupancy", rec.occupancy),
            ("queue_depth", rec.queue_depth),
        ):
            events.append({
                "ph": "C", "pid": 2, "tid": 1, "name": counter,
                "ts": _us(rec.t_start, t0), "args": {counter: value},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# Merged fleet trace pid layout: the router process, the supervisor
# process, then two pids per replica (its requests + its engine steps,
# the same split the single-engine export uses).
_ROUTER_PID = 1
_SUPERVISOR_PID = 2
_REPLICA_PID_BASE = 10


def fleet_trace_events(
    fleet_observer,
    engine_observers=(),
    supervisor_events=(),
) -> dict:
    """Merge the whole fleet's timelines into ONE chrome trace_event
    object, all lanes on the fleet's clock:

      * **Router process** (pid 1): one lane per terminal fleet request
        — its queued segment, then one complete event per per-replica
        attempt (replica id, outcome/fault kind, charged flag, SLO
        class in ``args``), failover replays linked to the attempt they
        retry by chrome flow events ("s"/"f"), and an instant event at
        the exactly-one terminal status.
      * **Supervisor process** (pid 2): instant events for every
        supervision transition (death, backoff wait, canary probe,
        quarantine, rejoin, ... — ``SupervisorEvent``).
      * **Per-replica processes** (pids 10+): each replica's full
        engine timeline exactly as its own ``trace_events`` renders it
        (request lanes + step/counter tracks), re-based onto the shared
        clock zero.

    Load the written file in chrome://tracing or perfetto;
    ``tools/trace_export.py --validate`` schema-checks it."""
    spans = list(fleet_observer.spans) if fleet_observer is not None else []
    engine_observers = [o for o in engine_observers if o is not None]
    supervisor_events = list(supervisor_events)
    stamps = [s.t_submit for s in spans]
    stamps += [ev.t for ev in supervisor_events]
    for obs in engine_observers:
        stamps += [r.t_start for r in obs.steps]
        stamps += [sp.t_submit for sp in obs.spans]
    t0 = min(stamps) if stamps else 0.0
    events: list[dict] = [
        {"ph": "M", "pid": _ROUTER_PID, "tid": 0, "name": "process_name",
         "args": {"name": "fleet router"}},
        {"ph": "M", "pid": _SUPERVISOR_PID, "tid": 0,
         "name": "process_name", "args": {"name": "supervisor"}},
        {"ph": "M", "pid": _SUPERVISOR_PID, "tid": 1, "name": "thread_name",
         "args": {"name": "events"}},
    ]
    flow_id = 0
    for lane, span in enumerate(spans, start=1):
        events.append(
            {"ph": "M", "pid": _ROUTER_PID, "tid": lane,
             "name": "thread_name", "args": {"name": span.rid}}
        )
        first_dispatch = (
            span.attempts[0].t_dispatch if span.attempts else span.t_done
        )
        events.append({
            "ph": "X", "pid": _ROUTER_PID, "tid": lane, "cat": "request",
            "name": "queued", "ts": _us(span.t_submit, t0),
            "dur": max(
                _us(first_dispatch, t0) - _us(span.t_submit, t0), 0.0
            ),
            "args": {
                "rid": span.rid, "slo_class": span.slo_class,
                "status": span.status,
            },
        })
        prev_end = None
        for i, att in enumerate(span.attempts):
            end = att.t_end if att.t_end is not None else span.t_done
            events.append({
                "ph": "X", "pid": _ROUTER_PID, "tid": lane,
                "cat": "attempt", "name": f"attempt r{att.replica}",
                "ts": _us(att.t_dispatch, t0),
                "dur": max(
                    _us(end, t0) - _us(att.t_dispatch, t0), 0.0
                ),
                "args": {
                    "rid": span.rid, "replica": att.replica,
                    "attempt": i, "outcome": att.outcome,
                    "charged": att.charged, "tokens": att.tokens,
                    "retry_of": i - 1 if i else None,
                    "slo_class": span.slo_class,
                },
            })
            if i:
                # Chrome flow link: the replay attempt is a retry CHILD
                # of the attempt the fault ended ("s" at the parent's
                # end, "f" at the child's dispatch; matched by
                # cat+name+id).
                flow_id += 1
                events.append({
                    "ph": "s", "pid": _ROUTER_PID, "tid": lane,
                    "cat": "failover", "name": "failover",
                    "id": flow_id,
                    "ts": _us(prev_end if prev_end is not None
                              else att.t_dispatch, t0),
                })
                events.append({
                    "ph": "f", "pid": _ROUTER_PID, "tid": lane,
                    "cat": "failover", "name": "failover",
                    "id": flow_id, "bp": "e",
                    "ts": _us(att.t_dispatch, t0),
                })
            prev_end = end
        events.append({
            "ph": "i", "pid": _ROUTER_PID, "tid": lane, "cat": "request",
            "name": f"terminal:{span.status}", "ts": _us(span.t_done, t0),
            "s": "t",
            "args": {
                "rid": span.rid, "status": span.status,
                "failovers": span.failovers,
                "slo_class": span.slo_class,
                "slo_attained": span.slo_attained,
                "tokens": span.n_tokens,
            },
        })
    for ev in supervisor_events:
        events.append({
            "ph": "i", "pid": _SUPERVISOR_PID, "tid": 1,
            "cat": "supervisor", "name": ev.kind, "ts": _us(ev.t, t0),
            "s": "t",
            "args": {"chip_id": ev.chip_id, "detail": ev.detail},
        })
    for idx, obs in enumerate(engine_observers):
        base = _REPLICA_PID_BASE + 2 * idx
        for ev in trace_events(obs, t0=t0)["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = base + (ev["pid"] - 1)
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_fleet_trace(
    path: str,
    fleet_observer,
    engine_observers=(),
    supervisor_events=(),
) -> tuple[int, int]:
    """Write the merged fleet timeline (``fleet_trace_events``) as
    chrome://tracing-loadable JSON.  Returns ``(n_events,
    n_replicas)`` — how much of the fleet the file actually covers, so
    the CLI can say so instead of silently exporting one replica."""
    engine_observers = [o for o in engine_observers if o is not None]
    trace = fleet_trace_events(
        fleet_observer, engine_observers, supervisor_events
    )
    with open(path, "w") as f:
        json.dump(trace, f)
        f.write("\n")
    return len(trace["traceEvents"]), len(engine_observers)

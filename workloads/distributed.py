"""Multi-host bring-up: jax.distributed from the daemon-injected slice env.

The communication backend of the workload suite.  On a multi-host slice
each host's device plugin stamps the global-slice env into its Allocate
responses (`TPU_WORKER_ID`, `TPU_TOPOLOGY`, `TPU_HOST_BOUNDS` —
tpu_device_plugin/slice_topology.py `container_slice_env`); this module
turns that env into a connected JAX runtime: ``initialize_from_slice_env``
wires `jax.distributed` (coordinator = worker 0), after which
``jax.devices()`` spans every host and ``global_mesh`` lays the usual
parallelism axes over the whole slice.  All cross-host traffic is XLA
collectives — psum/all_gather/ppermute over ICI within a host block and
DCN between blocks — inserted by the compiler from shardings; there is no
NCCL/MPI-style hand-driven transport to manage, which IS the TPU-native
replacement for one.

Hardware-free testing: the same code path runs N CPU processes
(`tests/test_distributed.py` spawns two and psums across them), so the
multi-host bring-up logic is exercised in CI without a pod slice.

Reference pendant: none — the reference daemon is strictly single-node
(SURVEY.md §5 distributed-communication note); its workloads never span
hosts.
"""

from __future__ import annotations

import os

import jax
import numpy as np

DEFAULT_COORDINATOR_PORT = 8476


def slice_process_info(environ=None) -> tuple[int, int] | None:
    """(process_id, num_processes) from the daemon-injected slice env
    (TPU_TOPOLOGY + TPU_HOST_BOUNDS + TPU_WORKER_ID), or None when this
    container is not part of a declared multi-host slice.

    Parsing delegates to the daemon's own canonical parser
    (slice_topology.slice_info_from_env) so arity/range validation — wrong
    bounds arity, worker id outside the host grid — stays in one place;
    malformed env raises its SliceConfigError.  The node-metadata fallback
    is disabled: a workload container must carry an explicit worker id.
    """
    from tpu_device_plugin.slice_topology import (
        SliceConfigError,
        slice_info_from_env,
    )

    env = os.environ if environ is None else environ
    info = slice_info_from_env(env=env, metadata_worker_id=None)
    if info is None:
        # A partial slice env must fail loud, not silently train
        # single-host while the slice's worker 0 blocks waiting for this
        # process to connect.
        present = [k for k in ("TPU_WORKER_ID", "TPU_HOST_BOUNDS") if k in env]
        if present and "TPU_TOPOLOGY" not in env:
            raise SliceConfigError(
                f"partial slice env: {', '.join(present)} set but "
                f"TPU_TOPOLOGY missing — the daemon injects all three "
                f"(slice_topology.container_slice_env)"
            )
        return None
    return info.worker_id, info.n_hosts


def initialize_from_slice_env(
    coordinator_address: str | None = None, environ=None
) -> bool:
    """Connect this process to the slice-wide JAX runtime.

    Returns True when a multi-host slice env was found and
    jax.distributed.initialize ran; False on a single-host container (no
    initialization needed — jax.devices() is already complete).

    ``coordinator_address`` defaults to ``$TPU_COORDINATOR_ADDRESS`` or
    worker 0's pod DNS name from ``$TPU_WORKER_HOSTNAMES`` (comma list)
    on port 8476 — pass it explicitly when neither is set.

    Caveat: some TPU runtimes rewrite ``TPU_TOPOLOGY``-family env vars at
    interpreter start (a sitecustomize registering the local PJRT plugin).
    The daemon-injected values must win, so such containers should mount
    the plugin's env last — the daemon side has the analogous --slice-*
    flag overrides (slice_topology.slice_info_from_env).
    """
    env = os.environ if environ is None else environ
    info = slice_process_info(env)
    if info is None:
        return False
    process_id, num_processes = info
    if num_processes <= 1:
        return False
    if coordinator_address is None:
        coordinator_address = env.get("TPU_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        hostnames = env.get("TPU_WORKER_HOSTNAMES")
        if hostnames:
            coordinator_address = (
                f"{hostnames.split(',')[0]}:{DEFAULT_COORDINATOR_PORT}"
            )
    if coordinator_address is None:
        raise ValueError(
            "multi-host slice env present but no coordinator address: set "
            "TPU_COORDINATOR_ADDRESS or TPU_WORKER_HOSTNAMES, or pass "
            "coordinator_address="
        )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh(
    data: int | None = None, model: int = 1, axis_names=("data", "model")
) -> jax.sharding.Mesh:
    """A mesh over every device of the connected slice (all hosts).

    Defaults to all-data-parallel; pass ``model`` to carve a trailing
    tensor-parallel axis (kept within a host when model divides the local
    device count, so its collectives ride ICI not DCN).
    """
    devices = jax.devices()
    n = len(devices)
    if data is None:
        if n % model:
            raise ValueError(f"{n} global devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} global devices")
    grid = np.array(devices).reshape(data, model)
    return jax.sharding.Mesh(grid, axis_names=axis_names)

"""Weight-only int8 quantization for serving.

KV-cached decode is HBM-bandwidth-bound: every step streams the full
weight set through the chip.  Storing the matmul weights as int8 with
per-output-channel float scales halves that traffic versus bfloat16; the
dequantize (convert + scale multiply) happens after the HBM read and
fuses into the consuming matmul, so the compute path stays MXU-shaped.

Quantized tensors are plain pytrees — ``{"q8": int8, "scale": f32}`` —
so they ride jax.jit / shardings / checkpoints unchanged, and the model's
weight reads (workloads/model.py ``weight()``) accept either
representation.  Norm gains and the (gather-read) embedding stay in float.

Reference pendant: none — the reference daemon has no model code; part of
the JAX serving workloads (SURVEY.md §7 step 8).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# The pytree marker for a quantized leaf. weight() in model.py keys on it.
QUANT_KEY = "q8"


def quantize(w: jax.Array, axis=0) -> dict:
    """Symmetric per-output-channel int8: scale = max|w| / 127 reduced
    over ``axis`` — the CONTRACTION axis (or axes) of the consuming
    matmul, so each output channel gets its own scale (kept with
    keepdims, so dequant broadcasts back)."""
    w = jnp.asarray(w)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {QUANT_KEY: q, "scale": scale}


def dequantize(entry: dict, dtype=jnp.float32) -> jax.Array:
    return entry[QUANT_KEY].astype(dtype) * entry["scale"].astype(dtype)


def is_quantized(entry) -> bool:
    return isinstance(entry, dict) and QUANT_KEY in entry


# The per-layer matmul weights worth quantizing (the big HBM streams),
# each with the contraction axis/axes of its consuming matmul — what the
# scale is reduced over so it lands per output channel.  Single source of
# the per-weight contraction layout; LoRA's fan computation
# (workloads/lora.py) derives from it too.
CONTRACTION_AXES = {
    "wqkv": 0,      # [d, 3, H, hd] contracts d
    "wq": 0,        # [d, H, hd] contracts d
    "wkv": 0,       # [d, 2, Hkv, hd] contracts d
    "wo": (0, 1),   # [H, hd, d] contracts (H, hd)
    "w_up": 0,      # [d, ff] contracts d
    "w_down": 0,    # [ff, d] contracts ff
}
_LAYER_WEIGHTS = CONTRACTION_AXES


def quantize_params(params: dict) -> dict:
    """The flagship model's parameter tree with every matmul weight
    (layer projections + unembed) stored int8; ln gains and the embedding
    table stay float (the embedding is a gather, not a matmul stream)."""
    out = {k: v for k, v in params.items() if k not in ("layers", "unembed")}
    out["unembed"] = quantize(params["unembed"], axis=0)  # [d, vocab]
    out["layers"] = [
        {
            k: (quantize(v, axis=_LAYER_WEIGHTS[k]) if k in _LAYER_WEIGHTS else v)
            for k, v in layer.items()
        }
        for layer in params["layers"]
    ]
    return out


def tree_bytes(tree) -> int:
    """Total parameter bytes of a pytree — compare a quantized tree
    against its source to see the HBM saving."""
    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(tree)
    )

"""Goodput-optimal control plane: ledger-driven online retuning.

PR 15's chip-time ledger made every charged second ATTRIBUTABLE (phase
taxonomy, goodput-vs-waste token classes, per-SLO-class roll-ups) and
PR 17 made KV pages the schedulable unit — but nothing in the fleet
ACTED on the measurements: spec/superstep knobs froze at startup
calibration, WFQ weights were static operator inputs, the autoscaler
ignored waste, and preemption victims were picked without regard to
what they'd throw away.  ``GoodputController`` closes that loop — the
serving-layer mirror of the reference plugin's ``replicas = -1`` auto
mode (PAPER.md §0.5: the advertised resource re-sizes itself to live
capacity once per discovery pass), applied here to chip-TIME instead of
chip-count.

One controller watches one ``Fleet`` (or a bare ``ServeEngine``).  Each
``poll()`` reads the armed ledger's running totals, EWMA-smooths the
newly-accounted delta's goodput / spec-rejected / overdecode shares,
and actuates through four existing seams:

  * **Online speculation retune** — ``ServeEngine.retune()`` shifts
    ``spec_breakeven`` and steps ``superstep_k`` /
    ``spec_superstep_k`` between dispatches from the observed
    ``spec_rejected`` / ``overdecode`` burn.  The engine drains every
    in-flight pipelined chunk, speculative round and superstep through
    the existing ``_drain_pending_*`` mode-boundary rules before a
    knob mutates, so greedy streams are bit-identical across every
    transition (pinned by tests/test_control.py).  Hill-climb with
    hysteresis: one knob move per cooldown, the cooldown escalating
    through the shared ``workloads.backoff`` policy while moves keep
    landing and resetting once the signal reaches the dead band —
    an oscillating signal slows itself down instead of thrashing.
  * **WFQ re-weighting** — ``Fleet.wfq_weights`` updates live from
    ``FleetLedger.class_economics()``'s measured per-class
    goodput-per-chip-second, so classes that waste chip-time stop
    buying dispatch credit.  Operator weights remain the FLOOR (a
    class is only ever boosted above its configured weight, capped at
    ``wfq_max_boost``); ``parked_classes`` stays the hard backstop.
  * **Waste-budget autoscaling** — the controller feeds its smoothed
    waste fraction to ``FleetAutoscaler.waste_fraction_hint``; with
    ``waste_budget=`` set the autoscaler HOLDS scale-ups while
    measured waste exceeds the budget (more replicas multiply waste —
    the ladder and the retunes attack it instead) and relaxes the
    scale-down streak while waste sits comfortably inside it (goodput
    headroom means capacity above the floor is pure
    ``autoscale_overprovision_chip_s``).
  * **Preemption victim scoring** — the PR-13 ladder's preempt step
    (``FleetAutoscaler._preempt_some``) walks
    ``Fleet.preempt_candidates``: ascending goodput-per-retained-page
    from the fleet's delivered-token counts and the page pool's
    refcounts (``ServeEngine.retained_pages`` — RadixKV/fork-shared
    pages count 1/refcount), so the stream that frees the most pages
    per token thrown away parks first.

The controller is cooperative and deterministic like the supervisor
and the autoscaler: ``poll()`` runs after each step (or use ``step()``
/ ``run()`` / ``serve_forever``, which wrap whatever driver it was
given — fleet, supervisor or autoscaler), takes no threads of its own,
and every actuation lands on the event ring the merged fleet trace
renders on the supervisor lane, plus the registry via
``ControlObserver`` (CONTROL_METRICS, docs/OBSERVABILITY.md).

Inert by default: without a controller nothing changes (``control``
stays opt-in everywhere), and an attached controller actuates nothing
until an armed ledger has accounted a measurable delta — token streams
are bit-identical controller on/off either way for greedy decoding
(every retune drains first; pinned by the fuzz arms and the
``measure_goodput_ctrl`` bench arm, which prices the poll tax as
``ctrl_overhead_pct``).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass

from .backoff import Backoff
from .errors import EngineClosed
from .obs import SupervisorEvent


@dataclass(frozen=True)
class ControlSignals:
    """One poll's view of the ledger-derived control inputs.  The
    fractions are EWMA-smoothed over per-poll accounted deltas and
    ``None`` until the first delta of at least ``min_sample_tokens``
    lands (no evidence — never an actuation on its own)."""

    accounted_tokens: int
    delta_tokens: int
    goodput_fraction: float | None
    spec_rejected_fraction: float | None
    overdecode_fraction: float | None


class GoodputController:
    """Close the chip-time loop: poll the armed ledger, retune the
    engines' speculation knobs, re-weight WFQ, hint the autoscaler's
    waste budget, all through existing seams (module docstring).

    ``target`` is a ``Fleet`` (its ``FleetLedger`` supplies the
    signals and per-class economics) or a bare ``ServeEngine`` (its
    ``ChipTimeLedger`` supplies engine-local signals; the WFQ seam is
    then moot).  ``driver`` is what ``step()`` steps — defaults to
    ``autoscaler`` when given (heal → scale → retune layering), else
    the target itself."""

    def __init__(
        self,
        target,
        *,
        autoscaler=None,
        driver=None,
        ewma_alpha: float = 0.3,
        min_sample_tokens: int = 64,
        spec_reject_high: float = 0.3,
        spec_reject_low: float = 0.05,
        overdecode_high: float = 0.3,
        overdecode_low: float = 0.05,
        breakeven_step: float = 1.0,
        wfq_max_boost: float = 4.0,
        wfq_deadband: float = 0.25,
        retune_backoff: Backoff | None = None,
        wfq_backoff: Backoff | None = None,
        observer=None,
        clock=time.perf_counter,
    ):
        if not hasattr(target, "step"):
            raise ValueError(
                "target must be a Fleet or ServeEngine (needs .step())"
            )
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}"
            )
        if min_sample_tokens < 1:
            raise ValueError(
                f"min_sample_tokens must be >= 1, got {min_sample_tokens}"
            )
        for name, low, high in (
            ("spec_reject", spec_reject_low, spec_reject_high),
            ("overdecode", overdecode_low, overdecode_high),
        ):
            if not 0.0 <= low < high <= 1.0:
                raise ValueError(
                    f"{name} thresholds need 0 <= low < high <= 1 (the "
                    f"dead band between them is the hysteresis), got "
                    f"low={low} high={high}"
                )
        if breakeven_step <= 0:
            raise ValueError(
                f"breakeven_step must be > 0, got {breakeven_step}"
            )
        if wfq_max_boost < 1.0:
            raise ValueError(
                f"wfq_max_boost must be >= 1 (operator weights are the "
                f"floor; boosts only go up), got {wfq_max_boost}"
            )
        if wfq_deadband < 0.0:
            raise ValueError(
                f"wfq_deadband must be >= 0, got {wfq_deadband}"
            )
        self.target = target
        self.fleet = target if hasattr(target, "replicas") else None
        self.engine = None if self.fleet is not None else target
        self.autoscaler = autoscaler
        self.driver = (
            driver if driver is not None
            else (autoscaler if autoscaler is not None else target)
        )
        self.ewma_alpha = float(ewma_alpha)
        self.min_sample_tokens = int(min_sample_tokens)
        self.spec_reject_high = float(spec_reject_high)
        self.spec_reject_low = float(spec_reject_low)
        self.overdecode_high = float(overdecode_high)
        self.overdecode_low = float(overdecode_low)
        self.breakeven_step = float(breakeven_step)
        self.wfq_max_boost = float(wfq_max_boost)
        self.wfq_deadband = float(wfq_deadband)
        # Hysteresis from the shared backoff policy: the retune gate
        # escalates while moves keep landing (an oscillating signal
        # slows itself down) and resets at the dead band; the WFQ gate
        # spaces re-weights the same way.
        self._retune = (
            retune_backoff if retune_backoff is not None
            else Backoff(base_s=0.25, max_s=8.0)
        ).derive("retune")
        self._wfq = (
            wfq_backoff if wfq_backoff is not None
            else Backoff(base_s=1.0, max_s=30.0)
        ).derive("wfq")
        self._clock = clock
        # Operator WFQ weights ARE the floor: captured before the first
        # re-weight ever mutates them (lazily, so a fleet that arms WFQ
        # after controller construction still records its own floor).
        self._wfq_floor: dict | None = (
            dict(self.fleet.wfq_weights)
            if self.fleet is not None
            and getattr(self.fleet, "wfq_weights", None) is not None
            else None
        )
        # Control state.
        self._seen: dict[str, int] = {}
        self._ewma: dict[str, float] = {}
        self._retune_gate = float("-inf")
        self._wfq_gate = float("-inf")
        self._retune_streak = 0
        self._wfq_streak = 0
        # Telemetry (mirrored to the registry by ControlObserver).
        self.polls = 0
        self.poll_s = 0.0  # wall seconds spent inside poll(): the tax
        self.samples = 0
        self.retunes_applied = 0
        self.wfq_reweights = 0
        self.decisions: dict[str, int] = {}
        self.last_signals: ControlSignals | None = None
        # The control timeline: one SupervisorEvent per actuation, on
        # the merged fleet trace's supervisor lane next to the heal and
        # scale events.
        self.events: deque = deque(maxlen=4096)
        self.dropped_events = 0
        self._obs = observer
        if observer is not None:
            observer._bind(self)

    # ---- bookkeeping -----------------------------------------------------

    def _event(
        self, kind: str, chip_id: str = "", detail: str = "",
        t: float | None = None,
    ) -> None:
        if len(self.events) == self.events.maxlen:
            self.dropped_events += 1
        self.events.append(SupervisorEvent(
            t=self._clock() if t is None else t, kind=kind,
            chip_id=chip_id, detail=detail,
        ))

    def drain_events(self) -> list:
        out = list(self.events)
        self.events.clear()
        return out

    def _decide(self, action: str) -> None:
        self.decisions[action] = self.decisions.get(action, 0) + 1

    @property
    def goodput_fraction_ewma(self) -> float | None:
        return self._ewma.get("goodput")

    @property
    def spec_rejected_fraction_ewma(self) -> float | None:
        return self._ewma.get("spec_rejected")

    @property
    def overdecode_fraction_ewma(self) -> float | None:
        return self._ewma.get("overdecode")

    def states(self) -> dict:
        """The /healthz introspection blob: where the control loop is
        right now."""
        return {
            "polls": self.polls,
            "poll_s": round(self.poll_s, 6),
            "samples": self.samples,
            "retunes_applied": self.retunes_applied,
            "wfq_reweights": self.wfq_reweights,
            "goodput_fraction_ewma": self.goodput_fraction_ewma,
            "spec_rejected_fraction_ewma":
                self.spec_rejected_fraction_ewma,
            "overdecode_fraction_ewma": self.overdecode_fraction_ewma,
            "wfq_floor": (
                dict(self._wfq_floor)
                if self._wfq_floor is not None else None
            ),
            "decisions": dict(self.decisions),
        }

    # ---- signal plumbing -------------------------------------------------

    def _ledger(self):
        obj = self.fleet if self.fleet is not None else self.engine
        return getattr(obj, "ledger", None)

    def _engines(self) -> list[tuple[str, object]]:
        if self.fleet is not None:
            return [
                (str(rep.index), rep.engine)
                for rep in self.fleet.replicas
                if rep.state != "dead"
            ]
        return [("0", self.engine)]

    @staticmethod
    def _totals(led) -> dict[str, int]:
        """Cumulative accounted/goodput/waste token totals, shape-
        agnostic across ``FleetLedger`` (fleet target) and
        ``ChipTimeLedger`` (bare engine target)."""
        if hasattr(led, "engine_ledgers"):  # FleetLedger
            # Running counters only — no snapshot materialization on
            # the per-step poll path (the controller's steady-state tax
            # is priced by the bench's ctrl_overhead_pct).
            sr = od = 0
            for _, el in led.engine_ledgers:
                w = el.waste_tokens
                sr += int(w.get("spec_rejected", 0))
                od += int(w.get("overdecode", 0))
            return {
                "accounted": int(led.tokens_accounted),
                "goodput": int(led.goodput_tokens),
                "spec_rejected": sr,
                "overdecode": od,
            }
        w = led.waste_tokens
        return {
            "accounted": int(led.tokens_accounted),
            "goodput": int(led.goodput_tokens),
            "spec_rejected": int(w.get("spec_rejected", 0)),
            "overdecode": int(w.get("overdecode", 0)),
        }

    def _update_ewma(self, key: str, value: float) -> None:
        prev = self._ewma.get(key)
        a = self.ewma_alpha
        self._ewma[key] = (
            value if prev is None else prev + a * (value - prev)
        )

    # ---- actuation: speculation retune -----------------------------------

    def _pick_move(self) -> str | None:
        """The hill-climb direction at the current EWMAs, dead-band
        gated: down-moves (waste above the high threshold) win over
        up-moves (waste below the low threshold — step back toward the
        construction-time ceilings to recapture the win); between the
        thresholds, hold.  Moves with no capable engine are never
        picked (a draftless fleet has no speculation to retune)."""
        engines = [e for _, e in self._engines()]
        if not engines:
            return None
        spec_capable = any(
            getattr(e, "draft_params", None) is not None for e in engines
        )
        super_capable = any(
            getattr(e, "_superstep_k_max", getattr(e, "superstep_k", 1))
            > 1
            or getattr(
                e, "_spec_superstep_k_max",
                getattr(e, "spec_superstep_k", 1),
            ) > 1
            for e in engines
        )
        sr = self._ewma.get("spec_rejected")
        od = self._ewma.get("overdecode")
        if spec_capable and sr is not None and sr > self.spec_reject_high:
            return "spec_down"
        if super_capable and od is not None and od > self.overdecode_high:
            return "super_down"
        if spec_capable and sr is not None and sr < self.spec_reject_low:
            return "spec_up"
        if super_capable and od is not None and od < self.overdecode_low:
            return "super_up"
        return None

    def _apply_move(self, move: str, eng) -> dict:
        """One knob move on one engine via ``ServeEngine.retune()``
        (which drains in-flight state first).  Returns retune()'s
        ``{knob: (old, new)}``, empty when the move has nothing left
        to do on this engine."""
        auto = (
            getattr(eng, "spec", None) == "auto"
            and getattr(eng, "draft_params", None) is not None
        )
        breakeven = getattr(eng, "spec_breakeven", None)
        k_sup = getattr(eng, "superstep_k", 1)
        k_spec = getattr(eng, "spec_superstep_k", 1)
        kmax_sup = getattr(eng, "_superstep_k_max", k_sup)
        kmax_spec = getattr(eng, "_spec_superstep_k_max", k_spec)
        if move == "spec_down":
            # Less speculation: lower the auto-mode threshold first
            # (the cheapest lever), then shrink the fused spec rounds.
            if auto and breakeven is not None and float(breakeven) > 0:
                return eng.retune(spec_breakeven=max(
                    0.0, float(breakeven) - self.breakeven_step
                ))
            if k_spec > 1:
                return eng.retune(spec_superstep_k=max(1, k_spec // 2))
            return {}
        if move == "spec_up":
            if k_spec < kmax_spec:
                return eng.retune(spec_superstep_k=min(
                    kmax_spec, max(2, k_spec * 2)
                ))
            if auto and breakeven is not None and (
                float(breakeven) < float(getattr(eng, "slots", 1))
            ):
                return eng.retune(spec_breakeven=min(
                    float(getattr(eng, "slots", 1)),
                    float(breakeven) + self.breakeven_step,
                ))
            return {}
        if move == "super_down":
            # Overdecode is chained chunks burned past retirement —
            # shrink whichever superstep family is fused.
            if k_sup > 1:
                return eng.retune(superstep_k=max(1, k_sup // 2))
            if k_spec > 1:
                return eng.retune(spec_superstep_k=max(1, k_spec // 2))
            return {}
        if move == "super_up":
            if k_sup < kmax_sup:
                return eng.retune(superstep_k=min(
                    kmax_sup, max(2, k_sup * 2)
                ))
            return {}
        return {}

    def _maybe_retune(self, now: float) -> None:
        if now < self._retune_gate:
            return
        move = self._pick_move()
        if move is None:
            # Dead band: the signal converged — reset the escalation so
            # the next genuine excursion acts at base cadence.
            self._retune_streak = 0
            return
        applied: list[str] = []
        for label, eng in self._engines():
            if getattr(eng, "closed", False):
                continue
            try:
                changes = self._apply_move(move, eng)
            except (ValueError, EngineClosed):
                continue  # knob not applicable on this engine's shape
            if changes:
                self.retunes_applied += 1
                applied.append(
                    f"{label}:"
                    + ",".join(
                        f"{k}{old}->{new}"
                        for k, (old, new) in sorted(changes.items())
                    )
                )
        if not applied:
            return  # nothing actionable; re-evaluate next poll
        self._decide("retune")
        self._retune_streak += 1
        self._retune_gate = now + self._retune.delay(
            min(self._retune_streak, 8)
        )
        self._event("retune", "", f"{move} " + "; ".join(applied), t=now)

    # ---- actuation: WFQ re-weighting -------------------------------------

    def _maybe_reweight(self, now: float) -> None:
        fleet = self.fleet
        if fleet is None:
            return
        weights = getattr(fleet, "wfq_weights", None)
        if weights is None:
            return
        led = self._ledger()
        if led is None or not hasattr(led, "class_economics"):
            return
        if now < self._wfq_gate:
            return
        # class_economics() materializes a snapshot — every pass
        # through here (actuating or not) re-arms the gate so the
        # computation runs at the backoff cadence, never per step.
        self._wfq_gate = now + self._wfq.delay(0)
        econ = led.class_economics()
        rates = {
            cls: e["goodput_per_chip_s"]
            for cls, e in econ.items() if e["chip_s"] > 0
        }
        if len(rates) < 2:
            self._wfq_streak = 0
            return  # relative ranking needs at least two measured classes
        mean = sum(rates.values()) / len(rates)
        if mean <= 0:
            self._wfq_streak = 0
            return
        if self._wfq_floor is None:
            self._wfq_floor = dict(weights)
        changed: dict[str, tuple[float, float]] = {}
        for cls, rate in rates.items():
            floor = float(self._wfq_floor.get(cls, 1.0))
            # Boost-above-floor only: an efficient class earns up to
            # wfq_max_boost x its operator weight; a wasteful class
            # holds at its floor — RELATIVE credit shifts away from it
            # without ever starving it below what the operator set
            # (parked_classes stays the hard backstop).
            mult = max(1.0, min(self.wfq_max_boost, rate / mean))
            new = round(floor * mult, 4)
            old = float(weights.get(cls, floor))
            if old > 0 and abs(new - old) / old > self.wfq_deadband:
                changed[cls] = (old, new)
        if not changed:
            self._wfq_streak = 0
            return
        for cls, (_, new) in changed.items():
            weights[cls] = new
        self.wfq_reweights += 1
        self._decide("wfq_reweight")
        self._wfq_streak += 1
        self._wfq_gate = now + self._wfq.delay(min(self._wfq_streak, 8))
        self._event(
            "wfq_reweight", "",
            "; ".join(
                f"{cls}:{old:g}->{new:g}"
                for cls, (old, new) in sorted(changed.items())
            ),
            t=now,
        )

    # ---- the control loop ------------------------------------------------

    def poll(self, now: float | None = None) -> None:
        """One control pass: read the ledger's newly-accounted delta,
        EWMA the waste shares, hint the autoscaler's waste budget, then
        retune / re-weight as the signal demands.  Call after each
        step (or use ``step()``/``run()``, which do).  A no-op without
        an armed ledger — the controller never actuates on zero
        evidence."""
        if self.closed:
            return
        t_tax = time.perf_counter()  # real clock: poll_s meters the
        now = self._clock() if now is None else now  # actual tax even
        self.polls += 1  # when gating runs on an injected clock
        led = self._ledger()
        if led is None:
            if self._obs is not None:
                self._obs._control_poll_end(self)
            self.poll_s += time.perf_counter() - t_tax
            return
        tot = self._totals(led)
        d_acc = max(0, tot["accounted"] - self._seen.get("accounted", 0))
        d_good = max(0, tot["goodput"] - self._seen.get("goodput", 0))
        d_sr = max(
            0, tot["spec_rejected"] - self._seen.get("spec_rejected", 0)
        )
        d_od = max(
            0, tot["overdecode"] - self._seen.get("overdecode", 0)
        )
        if d_acc >= self.min_sample_tokens:
            self._seen = tot
            self._update_ewma("goodput", d_good / d_acc)
            self._update_ewma("spec_rejected", d_sr / d_acc)
            self._update_ewma("overdecode", d_od / d_acc)
            self.samples += 1
        self.last_signals = ControlSignals(
            accounted_tokens=tot["accounted"],
            delta_tokens=d_acc,
            goodput_fraction=self.goodput_fraction_ewma,
            spec_rejected_fraction=self.spec_rejected_fraction_ewma,
            overdecode_fraction=self.overdecode_fraction_ewma,
        )
        if (
            self.autoscaler is not None
            and self.goodput_fraction_ewma is not None
        ):
            # Seam 3: the autoscaler's waste-budget SLO reads the
            # smoothed view instead of the instantaneous ledger read.
            self.autoscaler.waste_fraction_hint = max(
                0.0, min(1.0, 1.0 - self.goodput_fraction_ewma)
            )
        if self.samples:
            self._maybe_retune(now)
            self._maybe_reweight(now)
        if self._obs is not None:
            self._obs._control_poll_end(self)
        self.poll_s += time.perf_counter() - t_tax

    # ---- fleet-shaped driving surface ------------------------------------
    # Duck-typed to the Fleet/Supervisor/Autoscaler loop API so
    # drive_open_loop and the serve CLI can run CONTROLLED by passing
    # the controller where a fleet goes.

    def submit(self, *args, **kwargs):
        return self.driver.submit(*args, **kwargs)

    def cancel(self, rid: str) -> bool:
        return self.driver.cancel(rid)

    @property
    def idle(self) -> bool:
        return self.driver.idle

    @property
    def closed(self) -> bool:
        return self.driver.closed

    def step(self):
        """One controlled iteration: step the wrapped driver (fleet,
        supervisor or autoscaler — heal and scale before retune), then
        run the control pass."""
        finished = self.driver.step()
        self.poll()
        return finished

    def _parked(self) -> bool:
        fn = getattr(self.driver, "_parked", None)
        if callable(fn):
            return bool(fn())
        return False

    def run(self) -> dict[str, list[int]]:
        """Drive to idle (the fleet.run contract) with the control
        loop running between steps."""
        out: dict[str, list[int]] = {}
        while not self.driver.idle:
            for fr in self.step():
                out[fr.rid] = list(fr.tokens)
            if self._parked():
                time.sleep(0.001)
        return out

    def serve_forever(self, stop_event) -> None:
        """The controlled front-end driver loop: only the fleet step
        runs under the lock; heal/scale polls and the control pass run
        OUTSIDE it (a retune drains pipelined state and a scale-up may
        compile — HTTP handlers must keep submitting throughout)."""
        from .supervisor import drive_forever

        fleet = self.fleet
        if fleet is None:
            raise ValueError(
                "serve_forever needs a fleet-backed controller (a bare "
                "engine has no front-end driver loop)"
            )
        drv = self.driver

        def step_fn():
            finished = fleet.step()
            note = getattr(drv, "note_finished", None)
            if note is not None:
                note(finished)

        def poll_fn():
            sup = getattr(drv, "supervisor", None)
            if sup is not None:
                sup.poll()
            if drv is not fleet:
                drv.poll()
            self.poll()

        parked_fn = getattr(drv, "_parked", None)
        if parked_fn is None:
            def parked_fn():
                return (
                    not any(r.dispatchable for r in fleet.alive)
                    and bool(fleet.alive)
                )

        drive_forever(
            fleet, stop_event,
            step_fn=step_fn, poll_fn=poll_fn, parked_fn=parked_fn,
        )

    def wait_quiescent(self, timeout_s: float = 30.0) -> bool:
        """Delegate to the wrapped driver's quiescence wait when it has
        one (the autoscaler's scale-back-down convergence), else step
        to idle."""
        fn = getattr(self.driver, "wait_quiescent", None)
        if fn is not None:
            return bool(fn(timeout_s))
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.step()
            if self.driver.idle:
                return True
            time.sleep(0.001)
        return False

"""Cooperative per-chip lease client for time-sliced TPU pods.

CUDA time-shares GPU contexts natively, which is all the reference needs
(its containers just see the same GPU).  libtpu instead grants one process
exclusive chip access, so pods oversubscribed onto a chip must *cooperate*:
each takes the chip lease (an flock on a per-chip file in the host-shared
lease directory the plugin mounts into every shared pod), runs a burst of
steps, releases, repeats.  The kernel guarantees fairness-by-queueing and
automatic release when a pod dies mid-burst (flocks drop with the fd).

Usage inside a pod (env vars are injected by the plugin's Allocate):

    from workloads import lease
    with lease.chip_lease():          # blocks until this pod owns its chips
        ... run a burst of train steps ...
    # released: another pod's turn
"""

from __future__ import annotations

import fcntl
import os
import time
from contextlib import contextmanager

from tpu_device_plugin.sharing import (  # noqa: F401  (lease_path re-exported)
    CLAIM_EPOCH_ENV,
    CLAIM_LEASE_DIR_ENV,
    DEFAULT_LEASE_DIR,
    LEASE_DIR_ENV,
    claim_lease_path,
    lease_path,
)


def chip_ids_from_env() -> list[str]:
    """Chip ids the plugin granted this pod (from TPU_VISIBLE_CHIPS)."""
    raw = os.environ.get("TPU_VISIBLE_CHIPS", "")
    return [c for c in raw.split(",") if c]


# fds of lifetime claim leases, held until process exit (the kernel drops
# the flocks with the fds — crash-safe by construction), and the paths
# they cover (idempotence).
_claim_fds: list[int] = []
_claim_paths: set[str] = set()


def hold_claim_leases(
    chip_ids: list[str] | None = None, lease_dir: str | None = None
) -> int:
    """Declare this workload's lifetime to the device-plugin daemon.

    Under the mixed strategy the daemon's ClaimLedger needs to observe
    workload exits to release cross-view chip claims; with the chart's
    default ``hostPID: false`` it cannot see other namespaces' /proc, so
    the contract is filesystem-level: take a per-chip flock here and hold
    it until the process exits.  The daemon reads held = alive, dropped =
    exited (released within one probe interval), and treats workloads
    that never call this as unknown (their claims fall back to the TTL).

    The flock is SHARED: every pod time-sliced onto a chip holds its own
    shared lock on the same file, the daemon's probe takes a momentary
    exclusive lock to test for holders, and acquisition here BLOCKS —
    which only ever waits out that probe's microsecond hold, never a
    sibling (shared locks compose).

    The claim file name carries this allocation's epoch (TPU_CLAIM_EPOCH,
    injected by Allocate) so the daemon reads death evidence only from
    the allocation it belongs to — a predecessor pod's dropped flock can
    never read as THIS pod's exit.

    No-op (returns 0) when TPU_CLAIM_LEASE_DIR is absent — non-mixed
    deployments inject no claim-lease env.  Idempotent per process.
    Returns the number of flocks newly taken."""
    lease_dir = lease_dir or os.environ.get(CLAIM_LEASE_DIR_ENV, "")
    if not lease_dir:
        return 0
    epoch = os.environ.get(CLAIM_EPOCH_ENV) or None
    chip_ids = sorted(chip_ids if chip_ids is not None else chip_ids_from_env())
    os.makedirs(lease_dir, exist_ok=True)
    taken = 0
    for cid in chip_ids:
        path = claim_lease_path(lease_dir, cid, epoch)
        if path in _claim_paths:
            continue  # this process already declares this chip
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
        fcntl.flock(fd, fcntl.LOCK_SH)
        _claim_fds.append(fd)
        _claim_paths.add(path)
        taken += 1
    return taken


@contextmanager
def chip_lease(chip_ids: list[str] | None = None, lease_dir: str | None = None):
    """Blocks until ALL of this pod's chips are leased, then yields.

    Chips are locked in sorted order, which makes concurrent gang
    acquisitions deadlock-free.  Defaults come from the environment the
    plugin injected (TPU_VISIBLE_CHIPS, TPU_SHARED_LEASE_DIR).
    """
    lease_dir = lease_dir or os.environ.get(LEASE_DIR_ENV, DEFAULT_LEASE_DIR)
    chip_ids = sorted(chip_ids if chip_ids is not None else chip_ids_from_env())
    os.makedirs(lease_dir, exist_ok=True)
    fds: list[int] = []
    try:
        for cid in chip_ids:
            fd = os.open(lease_path(lease_dir, cid), os.O_CREAT | os.O_RDWR, 0o666)
            fcntl.flock(fd, fcntl.LOCK_EX)
            fds.append(fd)
        yield
    finally:
        for fd in reversed(fds):
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)


def try_chip_lease(chip_ids: list[str] | None = None, lease_dir: str | None = None):
    """Non-blocking variant: returns a release() callable or None if any
    chip is currently owned by another pod."""
    lease_dir = lease_dir or os.environ.get(LEASE_DIR_ENV, DEFAULT_LEASE_DIR)
    chip_ids = sorted(chip_ids if chip_ids is not None else chip_ids_from_env())
    os.makedirs(lease_dir, exist_ok=True)
    fds: list[int] = []

    def release():
        for fd in reversed(fds):
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)

    for cid in chip_ids:
        fd = os.open(lease_path(lease_dir, cid), os.O_CREAT | os.O_RDWR, 0o666)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except BlockingIOError:
            os.close(fd)
            release()
            return None
        fds.append(fd)
    return release


def run_leased_bursts(
    burst_fn,
    duration_secs: float,
    chip_ids: list[str] | None = None,
    lease_dir: str | None = None,
    backoff_secs: float = 0.002,
) -> dict:
    """Interleave with sibling pods for ``duration_secs``: lease, run one
    burst_fn() (a batch of steps), release, repeat.  Returns busy/wall
    accounting used by the busy probe."""
    t_start = time.monotonic()
    busy = 0.0
    bursts = 0
    while time.monotonic() - t_start < duration_secs:
        with chip_lease(chip_ids, lease_dir):
            t0 = time.monotonic()
            burst_fn()
            busy += time.monotonic() - t0
        bursts += 1
        time.sleep(backoff_secs)  # let a waiting sibling grab the flock
    wall = time.monotonic() - t_start
    return {"busy_secs": busy, "wall_secs": wall, "bursts": bursts}

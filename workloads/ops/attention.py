"""Flash attention as a Pallas TPU kernel.

The flagship workload's hot op, written for the hardware (see
/opt/skills/guides/pallas_guide.md): the [seq, seq] score matrix never
materialises in HBM — and VMEM residency is O(block), not O(seq).  The
k/v stream is part of the Pallas grid itself: the innermost grid dimension
walks k/v blocks while a float32 online-softmax accumulator lives in VMEM
scratch, persisting across those sequential iterations and re-initialising
at each new q block.  HBM traffic is O(seq * d) instead of O(seq^2), the
matmuls stay on the MXU, and long contexts (32k+) compile because no
BlockSpec ever maps a whole sequence into VMEM.

Differentiable via jax.custom_vjp: the kernel saves the per-row logsumexp,
and the backward pass recomputes probabilities from (q, k, lse) — the
standard flash recipe (memory-efficient forward, recompute backward) —
in plain fused XLA ops.

Reference pendant: none.  The reference daemon has no compute kernels at
all; this lives with the JAX example workloads that replace its CUDA/
PyTorch example pods (SURVEY.md §7 step 8).

Interpret mode (``interpret=True``, auto-detected off-TPU) runs the same
kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# Online-softmax running stats (m, l) are kept lane-broadcast at this width
# in VMEM scratch: a [block_q] vector cannot tile the (8, 128) Mosaic
# constraint, so the stats occupy a full lane dimension with every lane
# holding the same value.
_STATS_LANES = 128

# Shared by all three kernels: batch*heads and the outer block axis fan out
# across cores; the innermost axis is the sequential accumulation walk the
# VMEM scratch carries state across.
from .pallas_compat import ARBITRARY, PARALLEL, dimension_semantics_params

_SEQ_INNER_SEMANTICS = dimension_semantics_params(
    PARALLEL, PARALLEL, ARBITRARY
)


def _flash_kernel(
    q_ref, k_ref, v_ref, *rest,
    sm_scale, causal, block_q, block_k, seq_valid, n_k_blocks, window,
    segmented,
):
    """One (batch*head, q-block, k-block) grid cell.  The k dimension is the
    innermost (sequential) grid axis; (m, l, acc) persist in VMEM scratch
    across its iterations and reset when a new q block begins.  Refs:
    q [block_q, d], k/v [block_k, d], o [block_q, d], lse [block_q, 1],
    scratch m/l [block_q, _STATS_LANES], acc [block_q, d].  With
    ``segmented``, two extra int32 refs (seg_q [block_q, 1], seg_k
    [block_k, 1]) precede the outputs and rows only attend within their
    own segment — sequence packing."""
    if segmented:
        seg_q_ref, seg_k_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        seg_q_ref = seg_k_ref = None
        o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _body():
        # Matmul inputs stay in the storage dtype (bf16 on the MXU's native
        # fast path); only the accumulators and softmax math are float32.
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        k_ids = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_ids < seq_valid
        if causal:
            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask &= k_ids <= q_ids
            if window is not None:
                # Sliding window: row i sees only [i-window+1, i].
                mask &= k_ids > q_ids - window
        if segmented:
            mask &= seg_q_ref[:] == seg_k_ref[:].T  # [bq,1] vs [1,bk]
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]                                   # [bq, LANES]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=-1)[:, None]                # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)                  # lane-broadcast
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)[:, None]
        m_ref[:] = m_new
        l_ref[:] = l_new
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )

    if causal:
        # A k block fully past this q block's last row — or, with a
        # sliding window, fully before its first row's window start — is
        # all masked: skip its compute (the DMA still happens; the win is
        # not doing the matmuls).
        live = ki * block_k <= (qi + 1) * block_q - 1
        if window is not None:
            live &= ki * block_k + block_k - 1 > qi * block_q - window
        pl.when(live)(_body)
    else:
        _body()

    @pl.when(ki == n_k_blocks - 1)
    def _finalize():
        m = m_ref[:][:, :1]
        l = l_ref[:][:, :1]
        l_safe = jnp.where(l > 0, l, 1.0)  # fully-masked (padded) rows
        o_ref[:] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[:] = m + jnp.log(l_safe)


def _pad_seq(x, multiple):
    seq = x.shape[1]
    pad = (-seq) % multiple
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _pad_segments(segment_ids, seq_pad: int) -> jax.Array:
    """[batch, seq] int32 -> [batch, seq_pad, 1], padded with -1 so padded
    positions match no real segment."""
    batch, seq = segment_ids.shape
    segs = segment_ids.astype(jnp.int32)
    if seq_pad > seq:
        segs = jnp.pad(segs, ((0, 0), (0, seq_pad - seq)), constant_values=-1)
    return segs[:, :, None]


def _clamp_block(block: int, seq: int) -> int:
    """Shrink a default block size for short sequences without losing
    Mosaic tileability: the result is the requested block or the sequence
    rounded up to a 128-sublane multiple, whichever is smaller.  A naive
    min(block, seq) would make an unaligned sequence length (e.g. 300) the
    literal block shape, which fails to tile on real hardware."""
    return min(block, max(-(-seq // 128) * 128, 128))


def _kv_row(heads: int, kv_heads: int):
    """Index-map helper for grouped-query attention: flattened q row
    b = batch_i * heads + h reads flattened k/v row
    batch_i * kv_heads + h // group.  With kv_heads == heads this is the
    identity, and the k/v stream is shared across each q-head group with
    no materialised repeat."""
    group = heads // kv_heads
    return lambda b: (b // heads) * kv_heads + (b % heads) // group


def _check_gqa(heads: int, kv_heads: int) -> None:
    if heads % kv_heads:
        raise ValueError(
            f"q heads ({heads}) must be a multiple of kv heads ({kv_heads})"
        )


def _flash_forward(q, k, v, causal, interpret, block_q, block_k, window=None,
                   segment_ids=None):
    """q: [batch, seq, heads, head_dim]; k/v: [batch, seq, kv_heads,
    head_dim] with kv_heads dividing heads (grouped-query attention; equal
    is plain MHA) -> (out, lse[batch*heads, seq_pad])."""
    batch, seq, heads, head_dim = q.shape
    kv_heads = k.shape[2]
    _check_gqa(heads, kv_heads)
    sm_scale = 1.0 / (head_dim**0.5)
    block_q = _clamp_block(block_q, seq)
    block_k = _clamp_block(block_k, seq)
    kv_row = _kv_row(heads, kv_heads)

    qf = _pad_seq(
        jnp.transpose(q, (0, 2, 1, 3)).reshape(batch * heads, seq, head_dim), block_q
    )
    kf = _pad_seq(
        jnp.transpose(k, (0, 2, 1, 3)).reshape(batch * kv_heads, seq, head_dim),
        block_k,
    )
    vf = _pad_seq(
        jnp.transpose(v, (0, 2, 1, 3)).reshape(batch * kv_heads, seq, head_dim),
        block_k,
    )
    seq_q_pad = qf.shape[1]
    n_k_blocks = kf.shape[1] // block_k
    segmented = segment_ids is not None

    in_specs = [
        pl.BlockSpec((None, block_q, head_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec(
            (None, block_k, head_dim), lambda b, i, j: (kv_row(b), j, 0)
        ),
        pl.BlockSpec(
            (None, block_k, head_dim), lambda b, i, j: (kv_row(b), j, 0)
        ),
    ]
    operands = [qf, kf, vf]
    if segmented:
        # Per-position document ids, shared across heads: [batch, seq, 1]
        # padded with -1 (matches nothing).  The q and k streams read the
        # same array through their own block index maps.
        segs = _pad_segments(segment_ids, max(qf.shape[1], kf.shape[1]))
        in_specs += [
            pl.BlockSpec(
                (None, block_q, 1), lambda b, i, j, H=heads: (b // H, i, 0)
            ),
            pl.BlockSpec(
                (None, block_k, 1), lambda b, i, j, H=heads: (b // H, j, 0)
            ),
        ]
        operands += [segs, segs]

    kernel = functools.partial(
        _flash_kernel,
        sm_scale=sm_scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        seq_valid=seq,
        n_k_blocks=n_k_blocks,
        window=window,
        segmented=segmented,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(batch * heads, seq_q_pad // block_q, n_k_blocks),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            jax.ShapeDtypeStruct((batch * heads, seq_q_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, head_dim), jnp.float32),      # acc
        ],
        compiler_params=_SEQ_INNER_SEMANTICS,
        interpret=interpret,
    )(*operands)

    out = out[:, :seq].reshape(batch, heads, seq, head_dim).transpose(0, 2, 1, 3)
    return out, lse[:, :seq, 0]


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    sm_scale, causal, block_q, block_k, seq_valid, n_k_blocks, window,
    segmented,
):
    """One (batch*head, q-block, k-block) grid cell of the backward pass:
    accumulate dq in VMEM scratch over the sequential k axis.  p is
    recomputed from (q, k, lse) — the flash recipe's recompute-don't-store
    backward, as a kernel."""
    if segmented:
        seg_q_ref, seg_k_ref, dq_ref, dq_acc_ref = rest
    else:
        seg_q_ref = seg_k_ref = None
        dq_ref, dq_acc_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    def _body():
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:][:, 0]
        delta = delta_ref[:][:, 0]
        k = k_ref[:]
        v = v_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        q_ids = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_ids = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = (k_ids < seq_valid) & (q_ids < seq_valid)
        if causal:
            mask &= k_ids <= q_ids
            if window is not None:
                mask &= k_ids > q_ids - window
        if segmented:
            mask &= seg_q_ref[:] == seg_k_ref[:].T
        # Explicit zeroing (not just s=-inf): padded q rows carry lse=-inf,
        # where exp(s - lse) would otherwise produce 1, not 0.
        p = jnp.exp(jnp.where(mask, s, NEG_INF) - lse[:, None]) * mask
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_acc_ref[:] = dq_acc_ref[:] + jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    if causal:
        live = ki * block_k <= (qi + 1) * block_q - 1
        if window is not None:
            live &= ki * block_k + block_k - 1 > qi * block_q - window
        pl.when(live)(_body)
    else:
        _body()

    @pl.when(ki == n_k_blocks - 1)
    def _finalize():
        dq_ref[:] = dq_acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    sm_scale, causal, block_q, block_k, seq_valid, n_q_blocks, group,
    window, segmented,
):
    """One (batch*kv_head, k-block, group*q-block) grid cell: accumulate
    dk/dv in VMEM scratch over the sequential innermost axis, which walks
    every (q-head-in-group, q-block) pair sharing this k/v head — grouped-
    query attention sums each group's contributions here — skipping q
    blocks fully above the diagonal when causal."""
    if segmented:
        seg_q_ref, seg_k_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = rest
    else:
        seg_q_ref = seg_k_ref = None
        dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = rest
    ki = pl.program_id(1)
    j = pl.program_id(2)
    qi = j % n_q_blocks  # q block within the current group member

    @pl.when(j == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    def _body():
        k = k_ref[:]
        v = v_ref[:]
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:][:, 0]
        delta = delta_ref[:][:, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        q_ids = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_ids = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = (k_ids < seq_valid) & (q_ids < seq_valid)
        if causal:
            mask &= k_ids <= q_ids
            if window is not None:
                mask &= k_ids > q_ids - window
        if segmented:
            mask &= seg_q_ref[:] == seg_k_ref[:].T
        p = jnp.exp(jnp.where(mask, s, NEG_INF) - lse[:, None]) * mask
        dv_acc_ref[:] = dv_acc_ref[:] + jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_acc_ref[:] = dk_acc_ref[:] + jnp.dot(
            ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32
        )

    if causal:
        # q blocks whose last row precedes this k block's first row are
        # fully above the diagonal and contribute nothing; with a sliding
        # window, q blocks whose first row starts past the window of this
        # k block's last id contribute nothing either.
        live = (qi + 1) * block_q - 1 >= ki * block_k
        if window is not None:
            live &= qi * block_q <= ki * block_k + block_k - 1 + window - 1
        pl.when(live)(_body)
    else:
        _body()

    @pl.when(j == group * n_q_blocks - 1)
    def _finalize():
        dk_ref[:] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc_ref[:].astype(dv_ref.dtype)


def _flash_backward_pallas(q, k, v, out, dout, lse, causal, interpret, block_q,
                           block_k, window=None, segment_ids=None):
    """dq/dk/dv via the two backward kernels; same layout contract as
    _flash_forward (k/v may carry fewer heads — grouped-query)."""
    batch, seq, heads, head_dim = q.shape
    kv_heads = k.shape[2]
    _check_gqa(heads, kv_heads)
    group = heads // kv_heads
    kv_row = _kv_row(heads, kv_heads)
    sm_scale = 1.0 / (head_dim**0.5)
    block_q = _clamp_block(block_q, seq)
    block_k = _clamp_block(block_k, seq)

    def flat(x):
        n_heads = x.shape[2]
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(
            batch * n_heads, seq, head_dim
        )

    qf = _pad_seq(flat(q), block_q)
    dof = _pad_seq(flat(dout), block_q)
    of = _pad_seq(flat(out), block_q)
    kf = _pad_seq(flat(k), block_k)
    vf = _pad_seq(flat(v), block_k)
    seq_q_pad, seq_k_pad = qf.shape[1], kf.shape[1]
    # Per-row lse (padded rows -> -inf so they can't fake p=1) and
    # delta = rowsum(dout * out), the softmax-jacobian diagonal term.
    lse_pad = jnp.pad(
        lse, ((0, 0), (0, seq_q_pad - seq)), constant_values=NEG_INF
    )[..., None]
    delta = jnp.sum(
        dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1
    )[..., None]

    n_q_blocks = seq_q_pad // block_q
    n_k_blocks = seq_k_pad // block_k
    segmented = segment_ids is not None
    kwargs = dict(
        sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_valid=seq, window=window,
        segmented=segmented,
    )
    dq_in_specs = [
        pl.BlockSpec((None, block_q, head_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec(
            (None, block_k, head_dim), lambda b, i, j: (kv_row(b), j, 0)
        ),
        pl.BlockSpec(
            (None, block_k, head_dim), lambda b, i, j: (kv_row(b), j, 0)
        ),
        pl.BlockSpec((None, block_q, head_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    dq_operands = [qf, kf, vf, dof, lse_pad, delta]
    if segmented:
        segs = _pad_segments(segment_ids, max(seq_q_pad, seq_k_pad))
        dq_in_specs += [
            pl.BlockSpec(
                (None, block_q, 1), lambda b, i, j, H=heads: (b // H, i, 0)
            ),
            pl.BlockSpec(
                (None, block_k, 1), lambda b, i, j, H=heads: (b // H, j, 0)
            ),
        ]
        dq_operands += [segs, segs]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, n_k_blocks=n_k_blocks, **kwargs),
        grid=(batch * heads, n_q_blocks, n_k_blocks),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((None, block_q, head_dim), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=_SEQ_INNER_SEMANTICS,
        interpret=interpret,
    )(*dq_operands)

    # dk/dv: one grid row per kv head; the innermost axis walks every
    # (group member, q block) pair so the scratch accumulates the whole
    # q-head group's contribution before writing this k block.
    def q_row(b, j):
        return (b // kv_heads) * heads + (b % kv_heads) * group + j // n_q_blocks

    dkv_in_specs = [
        pl.BlockSpec(
            (None, block_q, head_dim),
            lambda b, i, j: (q_row(b, j), j % n_q_blocks, 0),
        ),
        pl.BlockSpec((None, block_k, head_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((None, block_k, head_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec(
            (None, block_q, head_dim),
            lambda b, i, j: (q_row(b, j), j % n_q_blocks, 0),
        ),
        pl.BlockSpec(
            (None, block_q, 1), lambda b, i, j: (q_row(b, j), j % n_q_blocks, 0)
        ),
        pl.BlockSpec(
            (None, block_q, 1), lambda b, i, j: (q_row(b, j), j % n_q_blocks, 0)
        ),
    ]
    dkv_operands = [qf, kf, vf, dof, lse_pad, delta]
    if segmented:
        # Batch-row index for segments: q rows flatten over q HEADS, k
        # rows over KV heads; both collapse to the same [batch, seq] ids.
        dkv_in_specs += [
            pl.BlockSpec(
                (None, block_q, 1),
                lambda b, i, j, H=heads: (
                    q_row(b, j) // H, j % n_q_blocks, 0
                ),
            ),
            pl.BlockSpec(
                (None, block_k, 1),
                lambda b, i, j, Hkv=kv_heads: (b // Hkv, i, 0),
            ),
        ]
        dkv_operands += [segs, segs]
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, n_q_blocks=n_q_blocks, group=group, **kwargs
        ),
        grid=(batch * kv_heads, n_k_blocks, group * n_q_blocks),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((None, block_k, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, head_dim), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kf.shape, k.dtype),
            jax.ShapeDtypeStruct(vf.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        compiler_params=_SEQ_INNER_SEMANTICS,
        interpret=interpret,
    )(*dkv_operands)

    def unflat(x, seq_len):
        return (
            x[:, :seq_len]
            .reshape(batch, -1, seq_len, head_dim)
            .transpose(0, 2, 1, 3)
        )

    return unflat(dq, seq), unflat(dk, seq), unflat(dv, seq)


def _default_interpret() -> bool:
    # Device platform, not backend name: tunnelled/proxied TPU platforms
    # present platform "tpu" on their devices and compile Pallas for real.
    devices = jax.devices()
    return not devices or devices[0].platform != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    interpret: bool | None = None,
    block_q: int = 256,
    block_k: int = 512,
    bwd_impl: str = "pallas",
    window: int | None = None,
    segment_ids=None,
):
    """Scaled-dot-product attention, [batch, seq, heads, head_dim] layout.

    k/v may carry fewer heads than q (grouped-query attention): any
    kv_heads dividing heads works, each group of heads//kv_heads q heads
    reading one shared k/v head straight from the kernel grid's index maps
    — no materialised repeat, so the HBM k/v traffic shrinks by the group
    factor.

    ``interpret=None`` auto-selects interpret mode off-TPU so the same code
    runs in CPU tests and compiles to a real kernel on TPU hardware.
    ``bwd_impl`` picks the backward pass: "pallas" (the blocked recompute
    kernels — the [seq, seq] matrices never touch HBM in either direction)
    or "xla" (dense recompute in fused XLA einsums; fine at short seq).
    """
    _check_bwd_impl(bwd_impl)
    _check_window(window, causal)
    _check_segment_ids(segment_ids, q)
    out, _ = _flash_forward(
        q, k, v, causal, _default_interpret() if interpret is None else interpret,
        block_q, block_k, window, segment_ids,
    )
    return out


def _check_segment_ids(segment_ids, q) -> None:
    """Eager shape validation: a silently padded-or-clamped mismatch would
    produce wrong attention, not an error."""
    if segment_ids is None:
        return
    expected = (q.shape[0], q.shape[1])
    if tuple(segment_ids.shape) != expected:
        raise ValueError(
            f"segment_ids shape {tuple(segment_ids.shape)} must be "
            f"[batch, seq] = {expected}"
        )


def _check_window(window, causal: bool) -> None:
    """Sliding windows are a causal construct here (the serving pattern);
    validated eagerly so a bad config fails at the call site."""
    if window is None:
        return
    if not causal:
        raise ValueError("window requires causal=True")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def _check_bwd_impl(bwd_impl: str) -> None:
    """Validated at the call site (not first grad trace) so a typo fails in
    the inference code that introduced it, not weeks later in fine-tuning."""
    if bwd_impl not in ("pallas", "xla"):
        raise ValueError(f"bwd_impl must be 'pallas' or 'xla', got {bwd_impl!r}")


def _fwd(q, k, v, causal, interpret, block_q, block_k, bwd_impl, window,
         segment_ids):
    _check_bwd_impl(bwd_impl)
    _check_window(window, causal)
    _check_segment_ids(segment_ids, q)
    out, lse = _flash_forward(
        q, k, v, causal, _default_interpret() if interpret is None else interpret,
        block_q, block_k, window, segment_ids,
    )
    return out, (q, k, v, out, lse, segment_ids)


def _flash_backward_xla(q, k, v, out, dout, lse, causal, window=None,
                        segment_ids=None):
    """Dense recompute backward in plain XLA: materialises [seq, seq] p, so
    only suitable when that fits comfortably — kept as the reference
    implementation the Pallas kernels are pinned against.  Grouped-query
    k/v are materialised to full heads here (it is the *fallback*), with
    dk/dv summed back over each group."""
    batch, seq, heads, head_dim = q.shape
    kv_heads = k.shape[2]
    _check_gqa(heads, kv_heads)
    group = heads // kv_heads
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    sm_scale = 1.0 / (head_dim**0.5)
    f32 = jnp.float32
    qf, kf, vf, of, dof = (x.astype(f32) for x in (q, k, v, out, dout))

    s = jnp.einsum("bshk,bthk->bhst", qf, kf) * sm_scale
    mask = jnp.ones((1, seq, seq), bool)
    if causal:
        mask = mask & jnp.tril(jnp.ones((seq, seq), bool))
        if window is not None:
            ids = jnp.arange(seq)
            mask = mask & (ids[None, :] > ids[:, None] - window)
    if segment_ids is not None:
        mask = mask & (segment_ids[:, :, None] == segment_ids[:, None, :])
    s = jnp.where(mask[:, None], s, NEG_INF)
    lse_b = lse.reshape(batch, heads, seq)
    p = jnp.exp(s - lse_b[..., None])

    dv = jnp.einsum("bhst,bshk->bthk", p, dof)
    dp = jnp.einsum("bshk,bthk->bhst", dof, vf)
    delta = jnp.sum(dof * of, axis=-1).transpose(0, 2, 1)  # [b, h, s]
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bhst,bthk->bshk", ds, kf)
    dk = jnp.einsum("bhst,bshk->bthk", ds, qf)
    if group > 1:
        dk = dk.reshape(batch, seq, kv_heads, group, head_dim).sum(axis=3)
        dv = dv.reshape(batch, seq, kv_heads, group, head_dim).sum(axis=3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd(causal, interpret, block_q, block_k, bwd_impl, window, residuals, dout):
    """Flash backward: recompute p from (q, k, lse) instead of storing the
    [seq, seq] probability matrix — as blocked Pallas kernels by default,
    dense XLA einsums with bwd_impl="xla".  segment_ids is a
    non-differentiable primal: its cotangent is the float0 symbolic zero
    (the type custom_vjp documents for integer primals — a bare None only
    works by tolerance, fragile across JAX upgrades)."""
    q, k, v, out, lse, segment_ids = residuals
    if bwd_impl == "xla":
        dq, dk, dv = _flash_backward_xla(
            q, k, v, out, dout, lse, causal, window, segment_ids
        )
    else:
        dq, dk, dv = _flash_backward_pallas(
            q, k, v, out, dout, lse, causal,
            _default_interpret() if interpret is None else interpret,
            block_q, block_k, window, segment_ids,
        )
    d_seg = (
        None
        if segment_ids is None
        else jax.custom_derivatives.zero_from_primal(segment_ids)
    )
    return dq, dk, dv, d_seg


flash_attention.defvjp(_fwd, _bwd)

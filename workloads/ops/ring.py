"""Ring attention: sequence/context parallelism over a TPU device mesh.

Long-context attention where the sequence axis is sharded across devices:
each device keeps its q shard resident and the k/v shards circulate around
the mesh's ring via ``lax.ppermute`` (XLA lowers this to ICI neighbour
transfers), merging each visiting block into a running online-softmax
accumulator.  Peak memory per device is O(seq/N * d) with no device ever
holding the full sequence — the standard ring-attention recipe, expressed
with jax.shard_map + XLA collectives (the idiomatic TPU formulation; a
Pallas RDMA double-buffered variant is a drop-in optimisation behind the
same function).

Differentiable end-to-end (ppermute transposes to the reverse ring), so it
can sit inside a sequence-parallel training step.

Reference pendant: none — the reference daemon has no model code; this is
part of the JAX workload suite that exercises the multi-chip slices the
device plugin allocates (SURVEY.md §5 "long-context" analog note).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _ring_local(q, k, v, axis_name: str, n_shards: int, causal: bool):
    """Per-device body (inside shard_map): q/k/v [batch, s_local, heads, d]."""
    batch, s_local, heads, head_dim = q.shape
    sm_scale = 1.0 / (head_dim**0.5)
    my = jax.lax.axis_index(axis_name)
    q32 = q.astype(jnp.float32) * sm_scale
    q_pos = my * s_local + jax.lax.broadcasted_iota(jnp.int32, (s_local, 1), 0)[:, 0]

    m = jnp.full((batch, heads, s_local), NEG_INF, jnp.float32)
    l = jnp.zeros((batch, heads, s_local), jnp.float32)
    acc = jnp.zeros((batch, s_local, heads, head_dim), jnp.float32)

    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    for step in range(n_shards):
        # After `step` rotations every device holds shard (my - step) mod N.
        src = (my - step) % n_shards
        k_pos = src * s_local + jax.lax.broadcasted_iota(
            jnp.int32, (s_local, 1), 0
        )[:, 0]
        s = jnp.einsum(
            "bshk,bthk->bhst", q32, k.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]  # [s_local_q, s_local_k]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)  # [b, h, s]
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * jnp.transpose(alpha, (0, 2, 1))[..., None] + jnp.einsum(
            "bhst,bthk->bshk", p, v.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        m = m_new
        if step != n_shards - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    l_safe = jnp.where(l > 0, l, 1.0)
    out = acc / jnp.transpose(l_safe, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mesh,
    axis: str = "seq",
    causal: bool = True,
    batch_axis: str | None = None,
):
    """Sequence-parallel attention over ``mesh[axis]``.

    q/k/v: [batch, seq, heads, head_dim] global arrays with seq divisible by
    the mesh axis size.  Returns attention output with the same sharding.
    On a multi-axis mesh pass ``batch_axis`` (e.g. ``"data"``) so the batch
    dim stays sharded across that axis — leaving it unmapped would make
    shard_map all-gather the batch and replicate the attention compute on
    every device along it.
    """
    n_shards = mesh.shape[axis]
    if q.shape[1] % n_shards:
        raise ValueError(
            f"seq {q.shape[1]} not divisible by mesh axis {axis!r} size {n_shards}"
        )
    spec = P(batch_axis, axis, None, None)
    run = shard_map(
        partial(_ring_local, axis_name=axis, n_shards=n_shards, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return run(q, k, v)

"""Version-tolerant aliases for the Pallas TPU compiler-params API.

The kernels declare grid dimension semantics (which axes fan out across
cores vs. walk sequentially) through an API JAX has renamed twice:
newer releases spell it ``pltpu.CompilerParams`` with a
``GridDimensionSemantics`` enum, while the pinned 0.4.x line spells it
``pltpu.TPUCompilerParams`` taking plain strings.  Resolving the names
HERE — once, at import time — keeps every kernel module importable on
either line; without it, 16 test modules fail collection with an
``AttributeError`` before a single test runs.
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

# The params dataclass: new name first, old name as the fallback.
CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

# Dimension-semantics values: the enum where it exists, the strings the
# old dataclass accepts otherwise.
_GRID_ENUM = getattr(pltpu, "GridDimensionSemantics", None)
PARALLEL = _GRID_ENUM.PARALLEL if _GRID_ENUM is not None else "parallel"
ARBITRARY = _GRID_ENUM.ARBITRARY if _GRID_ENUM is not None else "arbitrary"


def dimension_semantics_params(*semantics) -> "CompilerParams":
    """CompilerParams carrying the given dimension semantics (each one
    of the PARALLEL/ARBITRARY aliases above), built against whichever
    API this JAX exposes."""
    return CompilerParams(dimension_semantics=tuple(semantics))

"""TPU-first custom ops for the example workloads: Pallas kernels and
mesh-level collectives (ring attention)."""

from .attention import flash_attention  # noqa: F401
from .ring import ring_attention  # noqa: F401

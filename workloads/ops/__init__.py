"""TPU-first custom ops (Pallas kernels) for the example workloads."""

from .attention import flash_attention  # noqa: F401

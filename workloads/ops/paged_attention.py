"""Paged decode attention as a Pallas TPU kernel.

The serving hot op: one new query token per sequence attends over that
sequence's KV history stored in non-contiguous fixed-size pages.  The
block table is a SCALAR-PREFETCH argument — the kernel's k/v BlockSpec
index maps look the physical page up from the table while the grid walks
logical pages, so the pages stream HBM->VMEM directly.  Nothing gathers
the paged cache into a contiguous view first: per-token HBM traffic is
the live pages only, which is what makes paging a *throughput* feature
rather than just an allocation-on-demand feature.

Grid and layout are chosen for DMA efficiency (measured on v5e):
  * pages are [kv_heads, page_size, head_dim] with the head axis INSIDE
    the page, so one page is ONE contiguous DMA block — and the head
    axis leads, so the kernel's kv-head-batched dots need no transpose
    (Mosaic requires batch dims at the same operand index);
  * a grid cell is (batch row, logical page) and computes ALL query
    heads against the page — not one cell per (row, kv head), which
    costs ~16x the grid overhead and splinters each page into per-head
    strided reads.

Three properties carry the serving wins:
  * per-row lengths — each sequence attends over its own history length,
    so a batch of sequences at different positions decodes in one call
    (continuous batching's compute path);
  * dead-page DMA elision — for grid steps past a row's last live page
    (or before its sliding-window start) the index map CLAMPS to the
    nearest live page: Pallas skips the copy when consecutive grid steps
    map to the same block, so short rows in a long-table batch cost only
    their own pages' bandwidth;
  * grouped-query layout — each group of heads//kv_heads query heads
    reads its shared k/v head once from the page block.

The online-softmax accumulator lives in VMEM scratch across the
sequential page walk, exactly like the flash kernel's k-block walk.

Reference pendant: none — the reference daemon has no model code; this
is the perf bar VERDICT.md round 2 set (paged decode >= contiguous
decode throughput).  Interpret mode runs the same kernel on CPU for
tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_INF, _STATS_LANES, _check_gqa, _default_interpret
from .pallas_compat import ARBITRARY, PARALLEL, dimension_semantics_params


def _paged_decode_kernel(
    tables_ref, lengths_ref, q_ref, k_ref, v_ref, o_ref,
    m_ref, l_ref, acc_ref,
    *, sm_scale, page_size, kv_heads, n_page_steps, window,
):
    """One (batch row, logical page) grid cell.  The page axis is the
    innermost (sequential) walk; (m, l, acc) persist in VMEM scratch
    across it and reset when a new row begins.  Refs: q [heads, hd],
    k/v [kv_heads, page_size, hd] (the physical page the index map
    selected), o [heads, hd], scratch m/l [heads, _STATS_LANES],
    acc [heads, hd]."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    length = lengths_ref[b]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _body():
        heads, head_dim = q_ref.shape
        group = heads // kv_heads
        k = k_ref[:]  # [kv_heads, ps, hd]
        v = v_ref[:]
        q = q_ref[:].reshape(kv_heads, group, head_dim)
        # Per-kv-head batched: s[n, g, t] = q[n, g, :]·k[n, t, :]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        s = s.reshape(heads, page_size)
        k_ids = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        mask = k_ids < length
        if window is not None:
            # The single query sits at position length-1; it sees only
            # the last ``window`` positions [length-window, length-1].
            mask &= k_ids >= length - window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]                       # [heads, LANES]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=-1)[:, None]
        m_new = jnp.maximum(m_prev, m_cur)      # lane-broadcast
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1)[:, None]
        m_ref[:] = m_new
        pv = jax.lax.dot_general(
            p.reshape(kv_heads, group, page_size).astype(v.dtype), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(heads, head_dim)
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + pv

    # A page fully past the row's length — or fully before its window
    # start — contributes nothing; its compute is skipped here and its
    # DMA is skipped by the index-map clamp (same-block revisits copy
    # nothing).
    live = j * page_size < length
    if window is not None:
        live &= (j + 1) * page_size > length - window
    pl.when(live)(_body)

    @pl.when(j == n_page_steps - 1)
    def _finalize():
        l = l_ref[:][:, :1]
        l_safe = jnp.where(l > 0, l, 1.0)  # fully-dead rows (empty slots)
        o_ref[:] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _paged_attention_xla(
    q, k_pages, v_pages, tables, lengths, *, layer, window
):
    """Gathered-view fallback with the kernel's exact semantics, for
    shapes Mosaic cannot lay out (narrow head dims).  Gathers the rows'
    table-mapped pages into a dense [batch, T, kv_heads, hd] view and
    masks by per-row length — O(T) HBM per token, which is fine for the
    small models that land here."""
    batch, heads, head_dim = q.shape
    kv_heads, page_size = k_pages.shape[2], k_pages.shape[3]
    group = heads // kv_heads
    max_pages = tables.shape[1]

    def view(pool):
        g = pool[layer][tables]  # [b, maxp, Hkv, ps, hd]
        g = jnp.transpose(g, (0, 1, 3, 2, 4))
        return g.reshape(batch, max_pages * page_size, kv_heads, head_dim)

    k, v = view(k_pages), view(v_pages)
    qg = q.reshape(batch, kv_heads, group, head_dim)
    s = jnp.einsum(
        "bngk,btnk->bngt", qg.astype(jnp.float32), k.astype(jnp.float32)
    ) / (head_dim**0.5)
    ids = jnp.arange(max_pages * page_size)
    mask = ids[None, :] < lengths[:, None]
    if window is not None:
        mask &= ids[None, :] >= (lengths - window)[:, None]
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bngt,btnk->bngk", p, v.astype(jnp.float32))
    # Fully-dead rows (length 0 — empty serve slots) have an all-False
    # mask: softmax over uniform NEG_INF would average garbage pages.
    # Match the kernel's _finalize l_safe semantics: zeros.
    out = jnp.where((lengths > 0)[:, None, None, None], out, 0.0)
    return out.reshape(batch, heads, head_dim).astype(q.dtype)


def paged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tables: jax.Array,
    lengths: jax.Array,
    *,
    layer: int = 0,
    window: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Decode attention over a paged KV cache.

    q: [batch, heads, head_dim] — the current token's queries;
    k_pages/v_pages: [layers, n_pages, kv_heads, page_size, head_dim]
    (the whole pool rides in so no XLA slice materialises a copy —
    ``layer`` is folded into the BlockSpec index maps);
    tables: [batch, max_pages] int32 physical page ids (padding entries
    are never admitted: they sit past ``lengths`` and their DMA is
    elided);
    lengths: [batch] int32, the number of valid cache positions per row
    (the query's own k/v must already be written at position length-1).

    kv_heads may be fewer than heads (grouped-query); heads must divide
    evenly.  Returns [batch, heads, head_dim].

    Hardware notes: the Pallas kernel runs when head_dim is a multiple
    of 128 and page_size a multiple of 8 — the serving shapes (narrower
    dims trip Mosaic's layout inference on the group-axis reshapes).
    Anything else on hardware routes through a gathered-view XLA
    fallback with identical semantics, so small demo/test models still
    serve; interpret mode (off-TPU) always uses the kernel code path.
    """
    batch, heads, head_dim = q.shape
    layers, n_pages, kv_heads, page_size, hd2 = k_pages.shape
    if hd2 != head_dim:
        raise ValueError(
            f"head_dim mismatch: q has {head_dim}, pages have {hd2}"
        )
    if v_pages.shape != k_pages.shape:
        raise ValueError(
            f"k/v page pools disagree: {k_pages.shape} vs {v_pages.shape}"
        )
    if not (0 <= layer < layers):
        raise ValueError(f"layer {layer} out of range [0, {layers})")
    if tables.shape[0] != batch or lengths.shape != (batch,):
        raise ValueError(
            f"tables {tables.shape} / lengths {lengths.shape} do not match "
            f"batch {batch}"
        )
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    _check_gqa(heads, kv_heads)
    max_pages = tables.shape[1]
    sm_scale = 1.0 / (head_dim**0.5)
    if interpret is None:
        interpret = _default_interpret()
    if not interpret and (head_dim % 128 or page_size % 8):
        return _paged_attention_xla(
            q, k_pages, v_pages, tables, lengths, layer=layer, window=window
        )

    def kv_map(b, j, tables_ref, lengths_ref):
        length = lengths_ref[b]
        # Clamp before dividing: a fully-dead row (length 0, which
        # _finalize supports) must not index tables_ref at -1 — interpret
        # mode would wrap pythonically but a negative scalar-prefetch
        # block index is undefined on hardware.
        last = jnp.maximum(length - 1, 0) // page_size
        j_eff = jnp.minimum(j, last)
        if window is not None:
            # Pages fully before the window start clamp forward to the
            # first live page, so their DMA is elided too.
            first = jnp.maximum(length - window, 0) // page_size
            j_eff = jnp.maximum(j_eff, jnp.minimum(first, last))
        return (layer, tables_ref[b, j_eff], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, max_pages),
        in_specs=[
            pl.BlockSpec(
                (None, heads, head_dim), lambda b, j, t, l: (b, 0, 0)
            ),
            pl.BlockSpec(
                (None, None, kv_heads, page_size, head_dim), kv_map
            ),
            pl.BlockSpec(
                (None, None, kv_heads, page_size, head_dim), kv_map
            ),
        ],
        out_specs=pl.BlockSpec(
            (None, heads, head_dim), lambda b, j, t, l: (b, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((heads, _STATS_LANES), jnp.float32),  # m
            pltpu.VMEM((heads, _STATS_LANES), jnp.float32),  # l
            pltpu.VMEM((heads, head_dim), jnp.float32),      # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel,
            sm_scale=sm_scale,
            page_size=page_size,
            kv_heads=kv_heads,
            n_page_steps=max_pages,
            window=window,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=dimension_semantics_params(PARALLEL, ARBITRARY),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), q, k_pages, v_pages)
    return out

"""Ulysses-style all-to-all sequence/context parallelism.

The second of the two standard long-context recipes (the first, ring
attention, is ``workloads/ops/ring.py``): instead of circulating k/v shards
around a ring, one ``lax.all_to_all`` per tensor re-partitions the
sequence-sharded activations into head-sharded full sequences — each device
then runs ordinary full-sequence attention over heads/N local heads — and a
reverse all-to-all restores the sequence sharding.  On TPU the all-to-alls
ride the ICI mesh; the local attention is the Pallas flash kernel
(``workloads/ops/attention.py``), so the [seq, seq] score matrix still never
touches HBM.

Trade-off vs ring: Ulysses moves each activation exactly twice (two
all-to-alls of 1/N of the tensor per device) regardless of sequence length,
while ring moves k/v N-1 times but overlaps transfers with compute; Ulysses
needs heads divisible by the axis size, ring does not.  Both are exposed so
the training step can pick per topology (``workloads/train.py``).

Differentiable end-to-end: all_to_all transposes to the reverse all_to_all,
and the local kernel carries its own custom_vjp.

Reference pendant: none — the reference daemon has no model code; this is
part of the JAX workload suite exercising the multi-chip slices the device
plugin allocates (SURVEY.md §5 "long-context" analog note).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from .attention import flash_attention

_SEQ_DIM, _HEAD_DIM = 1, 2


def _ulysses_local(q, k, v, axis_name: str, causal: bool, local_attn):
    """Per-device body: q/k/v [batch, seq/N, heads, d] -> same shape."""

    def seq_to_heads(x):  # -> [batch, seq, heads/N, d]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=_HEAD_DIM, concat_axis=_SEQ_DIM, tiled=True
        )

    def heads_to_seq(x):  # -> [batch, seq/N, heads, d]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=_SEQ_DIM, concat_axis=_HEAD_DIM, tiled=True
        )

    out = local_attn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal)
    return heads_to_seq(out)


def ulysses_attention(
    q,
    k,
    v,
    mesh,
    axis: str = "seq",
    causal: bool = True,
    batch_axis: str | None = None,
    local_attn: Callable | None = None,
):
    """Sequence-parallel attention over ``mesh[axis]`` via head/seq all-to-all.

    q/k/v: [batch, seq, heads, head_dim] global arrays with both seq and
    heads divisible by the mesh axis size.  Returns attention output with the
    same sharding.  ``batch_axis`` keeps the batch dim mapped on a second
    mesh axis (see ring_attention's note).  ``local_attn(q, k, v, causal)``
    overrides the per-device full-sequence attention (default: the Pallas
    flash kernel).
    """
    n_shards = mesh.shape[axis]
    if q.shape[_SEQ_DIM] % n_shards:
        raise ValueError(
            f"seq {q.shape[_SEQ_DIM]} not divisible by mesh axis {axis!r} "
            f"size {n_shards}"
        )
    if q.shape[_HEAD_DIM] % n_shards:
        raise ValueError(
            f"heads {q.shape[_HEAD_DIM]} not divisible by mesh axis {axis!r} "
            f"size {n_shards} (use ring attention for head counts the axis "
            f"cannot split)"
        )
    attn = local_attn if local_attn is not None else flash_attention
    spec = P(batch_axis, axis, None, None)
    body = partial(_ulysses_local, axis_name=axis, causal=causal, local_attn=attn)
    # The Pallas kernel's out_shape carries no varying-mesh-axes (vma)
    # annotation, so shard_map's replication checker must be off; sharding
    # correctness is pinned by the dense-reference tests instead.
    try:
        run = shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    except TypeError:  # pragma: no cover - older jax spells it check_rep
        run = shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False,
        )
    return run(q, k, v)

"""Per-sequence-bucket attention kernel selection.

The flash/dense routing used to be a single crossover threshold
(model.flash_min_seq), but the committed bench artifact shows the
decision is not monotone enough for one number to be honest everywhere:
flash was 0.80x the dense XLA core at seq 1024 on the bench chip while
winning at 2048+ — so prefill at a mid-length bucket was paying a
measured 20% kernel tax for no reason.  This module is the fix: a tiny
per-(sequence-bucket) dispatch TABLE of measured winners, consulted at
trace time by model._attention, with three layers of precedence:

  1. an injected override (``set_kernel_table`` — the "measured once"
     hook: feed it ``table_from_measurements`` over a fresh
     ``measure_flash_vs_xla`` sweep, or the committed artifact's
     ``kernel_pick_seq*`` fields via ``table_from_artifact``);
  2. the per-device-kind measured defaults below (from the committed
     BENCH artifacts; kinds not yet measured skip this layer rather
     than guess);
  3. the legacy single-crossover fallback (the caller passes
     ``model.flash_min_seq()``'s value), so unknown hardware — CPU test
     hosts included — behaves exactly as before this table existed.

A lookup takes the SMALLEST table bucket >= seq (buckets are ceilings);
sequences beyond the largest bucket pick "flash" — the kernel's
asymptotic regime, where the dense core's [seq, seq] score matrix is
HBM-hostile regardless of what any mid-length measurement said.

The table is trace-time routing, not data: changing it recompiles, it
never changes numerics (both cores are parity-pinned against each
other in tests/test_flash_attention.py).

The perf bench publishes each sweep length's winner as
``kernel_pick_seq{N}`` in the bench artifact (workloads/perfbench.py),
so the committed measurement and the routing that should follow it are
reviewable side by side.
"""

from __future__ import annotations

IMPLS = ("flash", "xla")

# Measured per-device-kind winners, from the committed bench artifacts'
# flash-vs-XLA sweep (fwd+bwd slope ratio > 1 => flash wins).  On the
# r05 chip flash is 0.80x at 1024 and >1x from 2048 up (BENCH_r05 /
# docs/bench-builder-latest.json flash_vs_xla family).  Add a row by
# re-running `python -m workloads.perfbench` on the new generation and
# reading its kernel_pick_seq* fields.
_MEASURED_PICKS: tuple[tuple[str, tuple[tuple[int, str], ...]], ...] = (
    ("v5 lite", ((1024, "xla"), (2048, "flash"), (4096, "flash"))),
    ("v5e", ((1024, "xla"), (2048, "flash"), (4096, "flash"))),
)

_override: tuple[tuple[int, str], ...] | None = None


def _validate(picks) -> tuple[tuple[int, str], ...]:
    table = []
    for bucket, impl in sorted(dict(picks).items()):
        if int(bucket) < 1:
            raise ValueError(f"bucket ceilings must be >= 1, got {bucket}")
        if impl not in IMPLS:
            raise ValueError(
                f"kernel impl must be one of {IMPLS}, got {impl!r}"
            )
        table.append((int(bucket), impl))
    return tuple(table)


def set_kernel_table(picks: dict[int, str] | None) -> None:
    """Install a measured {bucket_ceiling: "flash"|"xla"} override (or
    None to fall back to the per-device-kind defaults).  Trace-time
    only: programs compiled before the call keep their routing."""
    global _override
    _override = None if picks is None else _validate(picks)


def kernel_table() -> tuple[tuple[int, str], ...] | None:
    """The effective dispatch table: the injected override, else this
    device kind's measured defaults, else None (threshold fallback)."""
    if _override is not None:
        return _override
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # no backend — routing still needs an answer
        return None
    for marker, picks in _MEASURED_PICKS:
        if marker in kind:
            return picks
    return None


def kernel_for_seq(seq: int, default_min_seq: int | None = None) -> str:
    """The measured winner for a (static) sequence length: the smallest
    table bucket >= seq decides; past the largest bucket flash's
    asymptotic win decides.  Without any table (unknown kind, nothing
    injected) the legacy single-crossover rule applies against
    ``default_min_seq``."""
    table = kernel_table()
    if table is None:
        if default_min_seq is None:
            from workloads.model import flash_min_seq

            default_min_seq = flash_min_seq()
        return "flash" if seq >= default_min_seq else "xla"
    for bucket, impl in table:
        if seq <= bucket:
            return impl
    return "flash"


def table_from_measurements(speedups: dict[int, float]) -> dict[int, str]:
    """{seq: flash_over_xla_speedup} -> a dispatch table: each measured
    length becomes a bucket picking the side that won there (ties to
    flash — at parity the kernel's O(seq*d) HBM footprint wins)."""
    return {
        int(seq): ("flash" if ratio >= 1.0 else "xla")
        for seq, ratio in speedups.items()
    }


def table_from_artifact(artifact: dict) -> dict[int, str] | None:
    """Rebuild the dispatch table from a committed bench artifact's
    ``kernel_pick_seq{N}`` fields (None when the artifact predates
    them) — the 'measured once' injection path for serving hosts."""
    picks = {}
    for key, val in artifact.items():
        if key.startswith("kernel_pick_seq") and val in IMPLS:
            try:
                picks[int(key[len("kernel_pick_seq"):])] = val
            except ValueError:
                continue
    return picks or None

"""Unified (2D) sequence parallelism: Ulysses x ring composed.

For contexts longer than either recipe scales to alone, the sequence dim
shards over TWO mesh axes: an outer ring axis and an inner Ulysses axis
(spec ``P(batch, ("seq_r", "seq_u"))`` — ring-major, so each ring shard
owns a contiguous span of the sequence).  Per attention call:

  1. all_to_all over the Ulysses axis re-partitions seq<->heads — each
     device now holds its ring shard's FULL contiguous span with heads/u
     local heads (workloads/ops/ulysses.py recipe);
  2. ring attention circulates k/v spans around the ring axis via ppermute
     (workloads/ops/ring.py recipe, unchanged — the contiguous-span
     position math holds because the ring axis is major);
  3. the reverse all_to_all restores the 2D sharding.

Capacity multiplies: seq/(r*u) resident per device, Ulysses head-split
bounded by n_heads only per u, ring unbounded in r.  On a TPU mesh the
Ulysses axis should map to ICI-adjacent chips (its all-to-alls move the
most bytes at once) with the ring axis across trays/hosts — ring transfers
overlap with compute.

Differentiable end-to-end (both building blocks are).

Reference pendant: none — the reference daemon has no model code
(SURVEY.md §5 long-context note); part of the JAX workload suite.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from .ring import _ring_local
from .ulysses import _ulysses_local

_SEQ_DIM, _HEAD_DIM = 1, 2


def usp_attention(
    q,
    k,
    v,
    mesh,
    ring_axis: str = "seq_r",
    ulysses_axis: str = "seq_u",
    causal: bool = True,
    batch_axis: str | None = None,
):
    """2D sequence-parallel attention over ``mesh[ring_axis] x
    mesh[ulysses_axis]``.

    q/k/v: [batch, seq, heads, head_dim] global arrays; seq must divide by
    ring*ulysses and heads by ulysses.  Returns output with the same
    sharding.  ``batch_axis`` keeps the batch dim mapped (see
    ring_attention's note).
    """
    n_ring = mesh.shape[ring_axis]
    n_uly = mesh.shape[ulysses_axis]
    if q.shape[_SEQ_DIM] % (n_ring * n_uly):
        raise ValueError(
            f"seq {q.shape[_SEQ_DIM]} not divisible by "
            f"{ring_axis}*{ulysses_axis} = {n_ring}*{n_uly}"
        )
    if q.shape[_HEAD_DIM] % n_uly:
        raise ValueError(
            f"heads {q.shape[_HEAD_DIM]} not divisible by {ulysses_axis} "
            f"size {n_uly}"
        )
    # Ring-major: each ring shard owns a contiguous global span, so the
    # ring body's block-position math (causal masking) holds unchanged.
    # The per-device body IS the Ulysses body with the ring body as its
    # local attention — the composition is literal reuse.
    def ring_as_local_attn(ql, kl, vl, causal_):
        return _ring_local(
            ql, kl, vl, axis_name=ring_axis, n_shards=n_ring, causal=causal_
        )

    spec = P(batch_axis, (ring_axis, ulysses_axis), None, None)
    run = shard_map(
        partial(
            _ulysses_local,
            axis_name=ulysses_axis,
            causal=causal,
            local_attn=ring_as_local_attn,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return run(q, k, v)

"""Aggregate chip-busy measurement for oversubscribed TPU sharing.

The BASELINE.md north star is "≥90% aggregate chip-busy with 8 time-sliced
JAX pods on a v5e-4 host" — a metric the reference never instrumented
(SURVEY.md §6).  This probe is that instrumentation: each participating pod
runs compute bursts under the cooperative chip lease and appends its
busy/wall accounting to a shared stats file; the aggregate busy fraction is
the unioned busy time across pods divided by wall time.

Run standalone (one process simulates one pod):

    python -m workloads.busy_probe --duration 10 --report /path/stats.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from contextlib import nullcontext

import jax
import jax.numpy as jnp

from . import lease


def _calibrate_steps(
    run_n, target_burst_secs: float, n_lo: int = 1, n_hi: int = 4
) -> int:
    """Steps per burst so one burst runs ~target_burst_secs of DEVICE
    time.  Per-step seconds come from the repo's median-slope estimator
    (perfbench.measure_slope_secs): the constant dispatch+readback
    round-trip — large and NOISY on a tunnelled chip — cancels in the
    slope and the median defeats its jitter, instead of being mistaken
    for step cost (which would shrink bursts until the chip idles
    through a readback per lease hold)."""
    from .perfbench import measure_slope_secs

    def chain(n: int) -> float:
        run_n(n)
        return 0.0

    per_step = measure_slope_secs(
        chain, n_lo=n_lo, n_hi=n_hi, repeats=3, min_window_secs=0.1, max_n=64
    )
    # Floor and cap: a jitter-dominated slope can collapse to the
    # estimator's 1e-9 floor, and an uncapped division would size a burst
    # that holds the chip lease for hours.  1e-6 s/step is faster than
    # any real step (each includes at least a dispatch), and 100k steps
    # bounds one burst to ~target regardless.
    per_step = max(per_step, 1e-6)
    return min(max(int(target_burst_secs / per_step), 1), 100_000)


def make_burst_fn(
    matrix_dim: int = 1024,
    target_burst_secs: float = 1.0,
    timed_section=nullcontext,
):
    """A compute burst sized to keep the MXU busy: chained bf16 matmuls.

    The step count is slope-calibrated so one burst runs
    ~target_burst_secs of device time — long enough that lease-handoff
    overhead AND the per-burst readback round-trip stay a small fraction
    of the duty cycle, short enough that siblings still interleave every
    second or so.

    Compilation is done ahead-of-time (host-side, no chip time needed), so
    only the short calibration runs under ``timed_section`` — holding the
    chip lease across a multi-second compile would starve siblings that
    are already in their measured window."""

    def chained(x):
        for _ in range(8):
            x = jnp.tanh(x @ x)
        return x

    x = jnp.ones((matrix_dim, matrix_dim), jnp.bfloat16)
    compiled = jax.jit(chained).lower(x).compile()

    # Synchronization is a real host READBACK, not block_until_ready: on
    # the tunnelled single-chip target block_until_ready does not wait for
    # the device, which would turn every busy/calibration number into a
    # dispatch-rate measurement.
    def run_n(n: int):
        result = x
        for _ in range(n):
            result = compiled(result)
        float(result[0, 0])

    with timed_section():
        steps_per_burst = _calibrate_steps(run_n, target_burst_secs)

    def burst():
        run_n(steps_per_burst)

    return burst


def make_train_burst_fn(target_burst_secs: float = 1.0, timed_section=nullcontext):
    """A compute burst that is USEFUL work: full training steps of the
    flagship transformer at a tiny scale (forward, backward, Adam), so
    the oversubscription harness can report aggregate tokens/s — useful
    throughput under time-slicing — next to raw chip-busy occupancy.

    Returns (burst, tokens_per_burst).  Same calibration/AOT-compile
    discipline as make_burst_fn: only the single timed calibration step
    runs under the chip lease."""
    from .model import ModelConfig
    from .train import make_mesh, make_train_state, make_train_step, synthetic_batch

    config = ModelConfig(
        d_model=256, n_heads=4, n_layers=2, d_ff=1024, vocab_size=2048,
        max_seq_len=128,
    )
    batch = 8
    mesh = make_mesh(1)
    (params, opt_state), optimizer = make_train_state(config, mesh)
    step = make_train_step(config, mesh, optimizer)
    tokens = synthetic_batch(config, batch)
    # AOT compile OUTSIDE the chip lease (same discipline as
    # make_burst_fn): a multi-second fwd+bwd+Adam compile inside the
    # lease would starve siblings already in their measured windows.
    compiled = step.aot_compile(params, opt_state, tokens)
    tokens_per_step = batch * (config.max_seq_len - 1)
    state = [params, opt_state]

    # float(loss) is a REAL host readback (see make_burst_fn —
    # block_until_ready does not synchronize on the tunnelled chip).
    def run_n(n: int):
        loss = None
        for _ in range(n):
            state[0], state[1], loss = compiled(state[0], state[1], tokens)
        float(loss)

    with timed_section():
        steps_per_burst = _calibrate_steps(run_n, target_burst_secs)

    def burst():
        run_n(steps_per_burst)

    return burst, steps_per_burst * tokens_per_step


def make_serve_burst_fn(target_burst_secs: float = 1.0, timed_section=nullcontext):
    """A compute burst that is SERVING work: full requests through the
    continuous-batching engine (workloads/serve.py — paged KV cache,
    chunked decode, sampling) at a tiny scale, so the oversubscription
    harness can report aggregate GENERATED tokens/s under time-slicing —
    the serving-era counterpart of make_train_burst_fn.

    Returns (burst, tokens_per_burst).  Same discipline as the other
    burst builders: the engine's three programs compile ahead-of-time
    (one warm request) outside the chip lease; only the short
    calibration runs under ``timed_section``."""
    from .model import ModelConfig, init_params
    from .serve import ServeEngine

    config = ModelConfig(
        d_model=256, n_heads=4, n_layers=2, d_ff=1024, vocab_size=2048,
        max_seq_len=64,
    )
    params = jax.tree.map(
        lambda w: w.astype(config.dtype),
        init_params(config, jax.random.PRNGKey(0)),
    )
    prompt = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(1), (16,), 0, config.vocab_size, jnp.int32
    )]
    new_tokens = 32
    engine = ServeEngine(
        params, config, slots=2, page_size=8, prompt_bucket=16, chunk=8,
        temperature=0.8, top_k=50, rng=jax.random.PRNGKey(2),
    )
    # engine.run() ends on host-side token readbacks — real syncs (see
    # make_burst_fn on why block_until_ready cannot be trusted here).
    def run_n(n: int):
        for _ in range(n):
            engine.submit(prompt, new_tokens)
        engine.run()

    with timed_section():
        # Unlike the matmul/train builders (whose warm-up is host-only
        # AOT compilation), warming the engine EXECUTES a request — so
        # it runs under the lease too, or a standalone late-starting pod
        # would compute unleased inside a sibling's measured window.
        engine.submit(prompt, new_tokens)
        engine.run()
        # Calibrate at multiples of the slot count: odd request counts
        # cost the same waves as the next multiple, which would bias the
        # slope ~1.5x low and oversize the burst.
        requests_per_burst = _calibrate_steps(
            run_n, target_burst_secs, n_lo=2, n_hi=8
        )

    def burst():
        run_n(requests_per_burst)

    return burst, requests_per_burst * (new_tokens)


def _start_barrier(barrier_dir: str, count: int, timeout_secs: float):
    """Gate the measured window on every sibling pod being READY (compiled
    + calibrated): without it, one pod's lease-held calibration lands
    inside another's measured window and reads as idle chip time.  Each
    pod drops a ready-file and polls for ``count``; a straggler past the
    timeout releases the barrier rather than wedging the harness (the
    caller keeps the timeout BELOW the harness's own wedge deadline so a
    crashed sibling surfaces as the failure, not its healthy peers).

    The directory must be FRESH PER RUN (the oversubscribe harness
    passes a subdirectory of its own mkdtemp): stale ready-files from a
    previous run would release the barrier early."""
    os.makedirs(barrier_dir, exist_ok=True)
    open(os.path.join(barrier_dir, f"ready-{os.getpid()}"), "w").close()
    deadline = time.monotonic() + timeout_secs
    while time.monotonic() < deadline:
        ready = [f for f in os.listdir(barrier_dir) if f.startswith("ready-")]
        if len(ready) >= count:
            return
        time.sleep(0.05)


def run_probe(
    duration_secs: float,
    report_path: str | None,
    matrix_dim: int = 1024,
    workload: str = "matmul",
    barrier_dir: str | None = None,
    barrier_count: int = 0,
) -> dict:
    """One pod's measured window.  workload="matmul" keeps the original
    occupancy burst; "train" runs flagship train steps and "serve" runs
    full serving-engine requests — both add a ``tokens`` count to the
    row so the aggregate can report useful throughput.  With ``barrier_dir``/``barrier_count``, the measured
    window starts only after every sibling finished compiling and
    calibrating (see _start_barrier)."""
    lease.hold_claim_leases()  # mixed-strategy lifetime declaration
    if workload == "train":
        burst, tokens_per_burst = make_train_burst_fn(
            timed_section=lease.chip_lease
        )
    elif workload == "serve":
        burst, tokens_per_burst = make_serve_burst_fn(
            timed_section=lease.chip_lease
        )
    elif workload == "matmul":
        burst = make_burst_fn(matrix_dim=matrix_dim, timed_section=lease.chip_lease)
        tokens_per_burst = 0
    else:
        raise ValueError(
            f"workload must be 'matmul', 'train' or 'serve', got {workload!r}"
        )
    if barrier_dir and barrier_count:
        # Stay under oversubscribe's wedge deadline (duration*10 + 300s).
        _start_barrier(
            barrier_dir, barrier_count,
            timeout_secs=duration_secs * 10 + 180,
        )
    stats = lease.run_leased_bursts(burst, duration_secs)
    stats.update(
        {
            "pid": os.getpid(),
            "chips": sorted(lease.chip_ids_from_env()),
            "busy_fraction": stats["busy_secs"] / max(stats["wall_secs"], 1e-9),
            "t_end": time.time(),
        }
    )
    if tokens_per_burst:
        stats["tokens"] = stats["bursts"] * tokens_per_burst
    if report_path:
        with open(report_path, "a") as f:
            f.write(json.dumps(stats) + "\n")
    return stats


def aggregate(report_path: str) -> dict:
    """Aggregate busy fraction across all pods that appended to the report.

    Bursts hold an exclusive per-chip lease, so sibling pods' busy intervals
    on one chip are disjoint: per-chip busy = sum of its pods' busy seconds,
    per-chip fraction = busy / the union wall window of the pods that used it,
    and the aggregate (the BASELINE north-star number) is the mean fraction
    over chips.  Rows without chip attribution keep the original single-chip
    semantics (one shared bucket) — but only when the whole report lacks it:
    mixing them with attributed rows would double-count a chip as a phantom
    extra bucket, so then they are left out of the per-chip fractions (still
    counted in pods/busy totals).
    """
    rows = []
    with open(report_path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if not rows:
        return {"pods": 0, "aggregate_busy_fraction": 0.0}
    any_attributed = any(r.get("chips") for r in rows)
    per_chip: dict[str, list[dict]] = {}
    for r in rows:
        chips = r.get("chips") or ([] if any_attributed else [""])
        for chip in chips:
            per_chip.setdefault(chip, []).append(r)
    chip_fractions = {}
    for chip, chip_rows in per_chip.items():
        busy = sum(r["busy_secs"] for r in chip_rows)
        ends = [r.get("t_end") for r in chip_rows]
        if all(e is not None for e in ends):
            # True union of the pods' measurement intervals: a gap where no
            # pod was probing the chip is unmeasured, not idle.
            intervals = sorted(
                (e - r["wall_secs"], e) for e, r in zip(ends, chip_rows)
            )
            window = 0.0
            cur_start, cur_end = intervals[0]
            for start, end in intervals[1:]:
                if start > cur_end:
                    window += cur_end - cur_start
                    cur_start, cur_end = start, end
                else:
                    cur_end = max(cur_end, end)
            window += cur_end - cur_start
        else:
            window = max(r["wall_secs"] for r in chip_rows)
        chip_fractions[chip] = min(busy / max(window, 1e-9), 1.0)
    wall = max(r["wall_secs"] for r in rows)
    busy = sum(r["busy_secs"] for r in rows)
    out = {
        "pods": len(rows),
        "chips": len(per_chip),
        "wall_secs": wall,
        "busy_secs": busy,
        "per_chip_busy_fraction": chip_fractions,
        "aggregate_busy_fraction": sum(chip_fractions.values()) / len(chip_fractions),
    }
    tokens = sum(r.get("tokens", 0) for r in rows)
    if tokens:
        # Useful throughput under time-slicing: total train tokens over
        # the longest pod window — the number occupancy alone can fake
        # but this cannot.
        out["tokens"] = tokens
        out["aggregate_tokens_per_sec"] = round(tokens / max(wall, 1e-9), 1)
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="TPU chip-busy probe")
    parser.add_argument("--duration", type=float, default=10.0)
    parser.add_argument("--report", default="")
    parser.add_argument("--matrix-dim", type=int, default=1024)
    parser.add_argument("--workload", default="matmul", choices=["matmul", "train", "serve"],
                        help="burst content: occupancy matmuls, flagship train "
                        "steps, or serving-engine requests ('train'/'serve' report tokens)")
    parser.add_argument("--barrier-dir", default="",
                        help="start-barrier directory shared by sibling pods")
    parser.add_argument("--barrier-count", type=int, default=0,
                        help="pods that must be ready before measuring")
    parser.add_argument("--aggregate", action="store_true",
                        help="aggregate an existing report instead of probing")
    args = parser.parse_args(argv)
    # Honour JAX_PLATFORMS even when a host sitecustomize pre-registered a
    # different backend: config.update wins as long as no backend has
    # initialised yet in this process (same pattern as __graft_entry__).
    platforms = os.environ.get("JAX_PLATFORMS")
    prior_platforms = _sentinel = object()
    if platforms:
        try:
            prior_platforms = jax.config.read("jax_platforms")
        except (AttributeError, RuntimeError):
            prior_platforms = _sentinel
        try:
            jax.config.update("jax_platforms", platforms)
        except (AttributeError, RuntimeError) as e:
            prior_platforms = _sentinel  # nothing changed; nothing to undo
            print(
                f"busy_probe: could not force JAX_PLATFORMS={platforms} "
                f"({e}); measuring on the already-initialised backend",
                file=sys.stderr,
            )
    # jax.config is process-global: restore the prior value even when the
    # probe raises, so a failed probe can't poison engine spawns that a
    # library caller runs in this same process afterwards.
    try:
        if args.aggregate:
            print(json.dumps(aggregate(args.report)))
            return 0
        stats = run_probe(
            args.duration, args.report or None, args.matrix_dim, args.workload,
            args.barrier_dir or None, args.barrier_count,
        )
        print(json.dumps(stats))
        return 0
    finally:
        if prior_platforms is not _sentinel:
            try:
                jax.config.update("jax_platforms", prior_platforms)
            except (AttributeError, RuntimeError):
                pass


if __name__ == "__main__":
    raise SystemExit(main())

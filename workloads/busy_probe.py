"""Aggregate chip-busy measurement for oversubscribed TPU sharing.

The BASELINE.md north star is "≥90% aggregate chip-busy with 8 time-sliced
JAX pods on a v5e-4 host" — a metric the reference never instrumented
(SURVEY.md §6).  This probe is that instrumentation: each participating pod
runs compute bursts under the cooperative chip lease and appends its
busy/wall accounting to a shared stats file; the aggregate busy fraction is
the unioned busy time across pods divided by wall time.

Run standalone (one process simulates one pod):

    python -m workloads.busy_probe --duration 10 --report /path/stats.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp

from . import lease


def make_burst_fn(matrix_dim: int = 1024, target_burst_secs: float = 0.25):
    """A compute burst sized to keep the MXU busy: chained bf16 matmuls.

    The step count is calibrated so one burst takes ~target_burst_secs on
    this device — long enough that lease-handoff overhead (flock wakeup,
    scheduling) stays a small fraction of the duty cycle, short enough that
    siblings still interleave many times per second."""

    @jax.jit
    def chained(x):
        for _ in range(8):
            x = jnp.tanh(x @ x)
        return x

    x = jnp.ones((matrix_dim, matrix_dim), jnp.bfloat16)
    chained(x).block_until_ready()  # compile outside the measured region
    t0 = time.monotonic()
    chained(x).block_until_ready()
    step_secs = max(time.monotonic() - t0, 1e-6)
    steps_per_burst = max(int(target_burst_secs / step_secs), 1)

    def burst():
        result = x
        for _ in range(steps_per_burst):
            result = chained(result)
        result.block_until_ready()

    return burst


def run_probe(duration_secs: float, report_path: str | None, matrix_dim: int = 1024) -> dict:
    burst = make_burst_fn(matrix_dim=matrix_dim)
    stats = lease.run_leased_bursts(burst, duration_secs)
    stats.update(
        {
            "pid": os.getpid(),
            "busy_fraction": stats["busy_secs"] / max(stats["wall_secs"], 1e-9),
            "t_end": time.time(),
        }
    )
    if report_path:
        with open(report_path, "a") as f:
            f.write(json.dumps(stats) + "\n")
    return stats


def aggregate(report_path: str) -> dict:
    """Aggregate busy fraction across all pods that appended to the report.

    Bursts hold an exclusive per-chip lease, so per-pod busy intervals are
    disjoint and aggregate busy = sum of busy seconds / max wall window.
    """
    rows = []
    with open(report_path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if not rows:
        return {"pods": 0, "aggregate_busy_fraction": 0.0}
    wall = max(r["wall_secs"] for r in rows)
    busy = sum(r["busy_secs"] for r in rows)
    return {
        "pods": len(rows),
        "wall_secs": wall,
        "busy_secs": busy,
        "aggregate_busy_fraction": min(busy / max(wall, 1e-9), 1.0),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="TPU chip-busy probe")
    parser.add_argument("--duration", type=float, default=10.0)
    parser.add_argument("--report", default="")
    parser.add_argument("--matrix-dim", type=int, default=1024)
    parser.add_argument("--aggregate", action="store_true",
                        help="aggregate an existing report instead of probing")
    args = parser.parse_args(argv)
    if args.aggregate:
        print(json.dumps(aggregate(args.report)))
        return 0
    stats = run_probe(args.duration, args.report or None, args.matrix_dim)
    print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""End-to-end oversubscription harness: the BASELINE north-star measurement.

Stands up the daemon's plugin server exactly as production does (time-sliced
shared resource, real unix socket, real kubelet registration), then plays the
role of kubelet + N JAX pods:

  1. ListAndWatch streams the replica-expanded device list.
  2. For each pod, GetPreferredAllocation picks the least-shared replica and
     Allocate returns the container environment (TPU_VISIBLE_CHIPS, lease dir,
     libtpu multi-process env — tpu_device_plugin/sharing.py).
  3. Each pod is a real subprocess running ``workloads.busy_probe`` under that
     environment, interleaving compute bursts through the cooperative chip
     lease.
  4. The per-chip busy accounting is aggregated into the north-star number:
     aggregate chip-busy fraction (target >= 0.90 with 8 pods on a v5e-4
     host — BASELINE.md; the reference never instrumented this, SURVEY.md §6).

Run (CPU anywhere, or on a TPU host with --platform tpu):

    python -m workloads.oversubscribe --chips 4 --replicas 2 --pods 8 \
        --duration 8 --platform cpu

Prints ONE JSON line with the aggregate busy fraction and vs_baseline
(value / 0.90; >= 1.0 beats the target).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

import grpc

BASELINE_BUSY_FRACTION = 0.90


def _start_stack(n_chips: int, chips_per_tray: int, replicas: int, tmp: str):
    """Daemon-side setup: fake kubelet registration server + shared plugin."""
    from tpu_device_plugin.api import pb, rpc
    from tpu_device_plugin.backend.fake import FakeChipManager
    from tpu_device_plugin.config import Config, Flags
    from tpu_device_plugin.plugin import TpuDevicePlugin
    from tpu_device_plugin.strategy import chip_units

    class _Kubelet(rpc.RegistrationServicer):
        def Register(self, request, context):  # noqa: N802
            return pb.Empty()

    kubelet_server = grpc.server(ThreadPoolExecutor(max_workers=2))
    rpc.add_registration_servicer(_Kubelet(), kubelet_server)
    kubelet_sock = os.path.join(tmp, "kubelet.sock")
    if kubelet_server.add_insecure_port(f"unix:{kubelet_sock}") == 0:
        raise RuntimeError(f"could not bind fake kubelet socket at {kubelet_sock}")
    kubelet_server.start()

    manager = FakeChipManager(n_chips=n_chips, chips_per_tray=chips_per_tray)
    manager.init()
    plugin = TpuDevicePlugin(
        config=Config(flags=Flags(backend="fake")),
        resource_name="google.com/shared-tpu",
        units_fn=lambda: chip_units(manager),
        chip_manager=manager,
        socket_path=os.path.join(tmp, "tpu-shared-tpu.sock"),
        kubelet_socket=kubelet_sock,
        replicas=replicas,
        lease_dir=os.path.join(tmp, "leases"),
    )
    plugin.start()
    return plugin, manager, kubelet_server


def _admit_pods(stub, pb, n_pods: int) -> list[dict]:
    """Kubelet-side admission: preferred allocation + Allocate per pod."""
    stream = stub.ListAndWatch(pb.Empty())
    advertised = [d.ID for d in next(iter(stream)).devices]
    stream.cancel()
    available = sorted(advertised)
    pod_envs = []
    for _ in range(n_pods):
        pref = stub.GetPreferredAllocation(
            pb.PreferredAllocationRequest(
                container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=available, allocation_size=1
                    )
                ]
            )
        )
        chosen = list(pref.container_responses[0].deviceIDs)
        if len(chosen) != 1:
            raise RuntimeError(
                f"preferred allocation returned {chosen!r} for size 1 — "
                f"likely more pods than replicas ({len(available)} device(s) left)"
            )
        resp = stub.Allocate(
            pb.AllocateRequest(
                container_requests=[
                    pb.ContainerAllocateRequest(devicesIDs=chosen)
                ]
            )
        )
        pod_envs.append(dict(resp.container_responses[0].envs))
        available.remove(chosen[0])
    return pod_envs


def run(
    n_chips: int = 4,
    chips_per_tray: int = 4,
    replicas: int = 2,
    n_pods: int = 8,
    duration_secs: float = 8.0,
    matrix_dim: int = 512,
    platform: str | None = None,
    workload: str = "matmul",
) -> dict:
    from tpu_device_plugin.api import pb, rpc
    from workloads import busy_probe

    tmp = tempfile.mkdtemp(prefix="tpu-dp-oversub-")
    report = os.path.join(tmp, "stats.jsonl")
    plugin, manager, kubelet_server = _start_stack(
        n_chips, chips_per_tray, replicas, tmp
    )
    try:
        channel = grpc.insecure_channel(f"unix:{plugin.socket_path}")
        try:
            grpc.channel_ready_future(channel).result(timeout=5)
            stub = rpc.DevicePluginStub(channel)
            pod_envs = _admit_pods(stub, pb, n_pods)
        finally:
            channel.close()

        procs = []
        stderr_paths = []
        for i, env_overlay in enumerate(pod_envs):
            env = dict(os.environ)
            env.update(env_overlay)
            if platform:
                env["JAX_PLATFORMS"] = platform
                if platform not in ("tpu", "axon"):
                    # Neutralise any host sitecustomize that force-registers a
                    # TPU PJRT backend in every python process (it would win
                    # over JAX_PLATFORMS and serialise pods on the real chip).
                    # "axon" (tunnelled TPU) keeps it: that env is what
                    # registers the tunnel's PJRT plugin in the pod.
                    env.pop("PALLAS_AXON_POOL_IPS", None)
            # Per-pod stderr files, not pipes: a chatty pod that filled a
            # 64KiB pipe would block mid-write while holding its chip lease,
            # wedging every sibling waiting on the flock.
            stderr_path = os.path.join(tmp, f"pod-{i}.stderr")
            stderr_paths.append(stderr_path)
            with open(stderr_path, "wb") as stderr_file:
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            "-m",
                            "workloads.busy_probe",
                            "--duration",
                            str(duration_secs),
                            "--matrix-dim",
                            str(matrix_dim),
                            "--workload",
                            workload,
                            "--barrier-dir",
                            os.path.join(tmp, "barrier"),
                            "--barrier-count",
                            str(n_pods),
                            "--report",
                            report,
                        ],
                        env=env,
                        stdout=subprocess.DEVNULL,
                        stderr=stderr_file,
                        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    )
                )
        t0 = time.monotonic()
        failures = []
        wedged = []
        try:
            deadline = time.monotonic() + duration_secs * 10 + 300
            for i, (p, stderr_path) in enumerate(zip(procs, stderr_paths)):
                try:
                    p.wait(timeout=max(deadline - time.monotonic(), 1.0))
                except subprocess.TimeoutExpired:
                    wedged.append(i)
                    continue
                if p.returncode != 0:
                    with open(stderr_path, "rb") as f:
                        failures.append(f.read().decode(errors="replace")[-2000:])
        finally:
            for p in procs:  # don't orphan wedged pods holding chip leases
                if p.poll() is None:
                    p.kill()
                    p.wait()
        if wedged:
            tails = []
            for i in wedged:
                with open(stderr_paths[i], "rb") as f:
                    tails.append(f"pod {i}: {f.read().decode(errors='replace')[-2000:]}")
            raise RuntimeError(
                f"{len(wedged)} pod(s) timed out and were killed: " + "; ".join(tails)
            )
        if failures:
            raise RuntimeError(f"{len(failures)} pod(s) failed: {failures[0]}")
        harness_wall = time.monotonic() - t0
        agg = busy_probe.aggregate(report)
    finally:
        plugin.stop()
        kubelet_server.stop(grace=0.2).wait()
        manager.shutdown()
        shutil.rmtree(tmp, ignore_errors=True)
    agg.update(
        {
            "n_pods": n_pods,
            "n_chips": n_chips,
            "replicas_per_chip": replicas,
            "harness_wall_secs": round(harness_wall, 3),
        }
    )
    return agg


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--chips", type=int, default=4)
    parser.add_argument("--chips-per-tray", type=int, default=4)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--pods", type=int, default=8)
    parser.add_argument("--duration", type=float, default=8.0)
    parser.add_argument("--matrix-dim", type=int, default=512)
    parser.add_argument("--workload", default="matmul", choices=["matmul", "train", "serve"],
                        help="pod burst content; 'train'/'serve' report aggregate "
                        "useful tokens/s next to the busy fraction")
    parser.add_argument(
        "--platform",
        default=None,
        help="force JAX_PLATFORMS in pods (cpu for hardware-free runs, tpu on a TPU host)",
    )
    args = parser.parse_args(argv)
    agg = run(
        n_chips=args.chips,
        chips_per_tray=args.chips_per_tray,
        replicas=args.replicas,
        n_pods=args.pods,
        duration_secs=args.duration,
        matrix_dim=args.matrix_dim,
        platform=args.platform,
        workload=args.workload,
    )
    value = agg["aggregate_busy_fraction"]
    print(
        json.dumps(
            {
                "metric": "aggregate_chip_busy_fraction",
                "value": round(value, 4),
                "unit": "fraction",
                "vs_baseline": round(value / BASELINE_BUSY_FRACTION, 4),
                **{k: v for k, v in agg.items() if k != "aggregate_busy_fraction"},
            }
        )
    )
    return 0 if value >= BASELINE_BUSY_FRACTION else 1


if __name__ == "__main__":
    raise SystemExit(main())

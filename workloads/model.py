"""A small decoder-only transformer, written TPU-first.

Pure-JAX (explicit parameter pytree, no framework classes) so that sharding
is transparent: every parameter leaf carries an obvious partition axis and
the whole model jits into a handful of large MXU-friendly matmuls in
bfloat16 compute.  Used as the flagship workload by the example pods, the
benchmark and the multi-chip dry-run (__graft_entry__.py).

Sharding convention over a Mesh with axes ("data", "model"):
  * activations  : batch sharded on "data"
  * attention    : head dimension sharded on "model"
  * MLP          : hidden dimension sharded on "model"
  * embeddings   : replicated (small at these sizes)
XLA inserts the all-reduces at the attention/MLP output projections — the
standard Megatron-style tensor-parallel cut expressed purely through
jax.sharding annotations.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 128
    max_seq_len: int = 128
    dtype: jnp.dtype = jnp.bfloat16
    # "native": XLA einsum attention — partitions under pjit/tensor
    # parallelism.  "flash": the Pallas online-softmax kernel
    # (workloads/ops/attention.py) for the single-device hot path; compiles
    # to a real TPU kernel on hardware, interpret mode elsewhere.
    attention_impl: str = "native"
    # Grouped-query attention: None = multi-head (kv heads == n_heads,
    # parameter tree unchanged).  Setting a divisor of n_heads shares each
    # k/v head across a group of query heads and shrinks the KV cache —
    # the serving-era memory trade, supported end-to-end (flash kernel,
    # dense core, cached decode).
    n_kv_heads: int | None = None
    # Sliding-window attention: None = full causal span.  A positive
    # window bounds each token's attention to the last ``window``
    # positions — the long-context serving pattern, honoured by the flash
    # kernel (with block-level compute skip), the dense core, and the
    # cached decode.
    attention_window: int | None = None
    # Rematerialise each transformer layer in the backward pass
    # (jax.checkpoint): activations are recomputed instead of stored,
    # trading ~one extra forward of FLOPs for O(layers) less activation
    # memory — the knob that buys a bigger batch (and with it, MFU) when
    # HBM, not FLOPs, is the binding constraint.
    remat_layers: bool = False

    def __post_init__(self):
        if self.attention_impl not in ("native", "flash"):
            raise ValueError(
                f"attention_impl must be 'native' or 'flash', got {self.attention_impl!r}"
            )
        if self.n_kv_heads is not None and (
            self.n_kv_heads < 1 or self.n_heads % self.n_kv_heads
        ):
            raise ValueError(
                f"n_kv_heads ({self.n_kv_heads}) must be a positive divisor "
                f"of n_heads ({self.n_heads})"
            )
        if self.attention_window is not None and self.attention_window < 1:
            raise ValueError(
                f"attention_window must be >= 1, got {self.attention_window}"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads


def init_params(config: ModelConfig, key: jax.Array) -> dict:
    """Parameter pytree; leaf names mirror the sharding specs in
    param_specs()."""
    keys = jax.random.split(key, 2 + config.n_layers)
    scale = 0.02

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * scale)

    params = {
        "embed": dense(keys[0], (config.vocab_size, config.d_model)),
        "unembed": dense(keys[1], (config.d_model, config.vocab_size)),
        "layers": [],
    }
    for i in range(config.n_layers):
        k = jax.random.split(keys[2 + i], 6)
        layer = {
            "ln1": jnp.ones((config.d_model,), jnp.float32),
            "ln2": jnp.ones((config.d_model,), jnp.float32),
            "wo": dense(k[1], (config.n_heads, config.head_dim, config.d_model)),
            "w_up": dense(k[2], (config.d_model, config.d_ff)),
            "w_down": dense(k[3], (config.d_ff, config.d_model)),
        }
        if config.kv_heads == config.n_heads:
            # Multi-head: fused qkv projection (tree unchanged from the
            # pre-GQA layout, so existing checkpoints keep loading).
            layer["wqkv"] = dense(
                k[0], (config.d_model, 3, config.n_heads, config.head_dim)
            )
        else:
            layer["wq"] = dense(
                k[0], (config.d_model, config.n_heads, config.head_dim)
            )
            layer["wkv"] = dense(
                k[4], (config.d_model, 2, config.kv_heads, config.head_dim)
            )
        params["layers"].append(layer)
    return params


def param_specs(config: ModelConfig) -> dict:
    """PartitionSpecs matching init_params' tree: the Megatron tensor-
    parallel cut over the "model" mesh axis."""
    layer = {
        "ln1": P(),
        "ln2": P(),
        "wo": P("model", None, None),
        "w_up": P(None, "model"),
        "w_down": P("model", None),
    }
    if config.kv_heads == config.n_heads:
        layer["wqkv"] = P(None, None, "model", None)
    else:
        layer["wq"] = P(None, "model", None)
        # kv heads are the scarce axis under GQA; shard them only when the
        # "model" degree still divides them at mesh-build time (callers pick
        # model_parallel accordingly), which P("model") expresses directly.
        layer["wkv"] = P(None, None, "model", None)
    return {
        "embed": P(),
        "unembed": P(),
        "layers": [dict(layer) for _ in range(config.n_layers)],
    }


def _rmsnorm(x: jax.Array, gain: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * gain.astype(x.dtype)


def rope_angles(positions: jax.Array, head_dim: int) -> jax.Array:
    """Rotary angles for the given positions: [n_positions, head_dim//2].
    Single source of the frequency formula — the KV-cache decode path
    (workloads/generate.py) must stay numerically identical to this."""
    half = head_dim // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    return positions.astype(jnp.float32)[:, None] * freqs[None, :]


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """Rotate x [batch, seq, heads, head_dim] by angles [seq, head_dim//2]
    (seq may be 1 for broadcasting a single position)."""
    half = x.shape[-1] // 2
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def _rope(x: jax.Array) -> jax.Array:
    """Rotary position embedding over the last (head_dim) axis.
    x: [batch, seq, heads, head_dim]."""
    _, seq, _, head_dim = x.shape
    return apply_rope(x, rope_angles(jnp.arange(seq), head_dim))


def masked_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, mask: jax.Array, head_dim: int
) -> jax.Array:
    """The scale/mask/float32-softmax attention core, [batch, seq, heads,
    head_dim] layout, mask broadcastable to [batch, heads, s_q, s_k].
    k/v may carry fewer heads (grouped-query): each group of
    heads//kv_heads query heads reads one shared k/v head, expressed as a
    grouped einsum — no materialised repeat.  Single source shared by the
    dense forward and the KV-cached decode (workloads/generate.py) so the
    two can never silently diverge."""
    scale = jnp.sqrt(head_dim).astype(q.dtype)
    heads, kv_heads = q.shape[2], k.shape[2]
    if heads == kv_heads:
        logits = jnp.einsum("bshk,bthk->bhst", q, k) / scale
        logits = jnp.where(mask, logits.astype(jnp.float32), -1e30)
        weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhst,bthk->bshk", weights, v)
    group = heads // kv_heads
    batch, s_q = q.shape[:2]
    qg = q.reshape(batch, s_q, kv_heads, group, head_dim)
    logits = jnp.einsum("bsngk,btnk->bngst", qg, k) / scale
    # Honour the documented mask contract under grouping: a full per-head
    # mask splits its heads axis into (kv_heads, group); a broadcastable
    # (size-1) heads axis just gains the group dimension.
    if mask.ndim >= 4 and mask.shape[1] == heads:
        maskg = mask.reshape(
            mask.shape[0], kv_heads, group, *mask.shape[2:]
        )
    else:
        maskg = mask[:, :, None] if mask.ndim >= 4 else mask
    logits = jnp.where(maskg, logits.astype(jnp.float32), -1e30)
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bngst,btnk->bsngk", weights, v)
    return out.reshape(batch, s_q, heads, head_dim)


def weight(entry, dtype) -> jax.Array:
    """A weight leaf in compute dtype — transparently dequantizing the
    int8 serving representation (workloads/quant.py): the convert+scale
    happens after the (halved) HBM read and fuses into the consuming
    matmul."""
    from .quant import dequantize, is_quantized

    if is_quantized(entry):
        return dequantize(entry, dtype)
    return entry.astype(dtype)


def project_qkv(x: jax.Array, layer: dict):
    """(q, k, v) from either the fused MHA projection (wqkv) or the split
    grouped-query pair (wq + wkv).  Shared with the cached decode path."""
    if "wqkv" in layer:
        qkv = jnp.einsum("bsd,dthk->tbshk", x, weight(layer["wqkv"], x.dtype))
        return qkv[0], qkv[1], qkv[2]
    q = jnp.einsum("bsd,dhk->bshk", x, weight(layer["wq"], x.dtype))
    kv = jnp.einsum("bsd,dthk->tbshk", x, weight(layer["wkv"], x.dtype))
    return q, kv[0], kv[1]


# Routing thresholds for attention_impl="flash": the dense XLA core wins
# below the crossover sequence length where the quadratic term is still
# cheap — but only while its [batch, heads, seq, seq] float32 score
# matrix stays small enough not to pressure HBM.  The crossover is a
# HARDWARE property (compute/bandwidth balance moves per generation), so
# it is a per-device-kind table of MEASURED values from the perf bench's
# flash_vs_xla_detail sweep (workloads/perfbench.py) — on v5e, flash is
# 0.3x dense at seq 1024 and 1.6x at 2048 (BENCH_r02).  Kinds not yet
# measured fall back to the v5e value rather than a guess dressed up as
# data; re-run `python -m workloads.perfbench` on a new generation and
# add its row.
_FLASH_MIN_SEQ_BY_KIND = (
    ("v5 lite", 2048),  # v5e, measured
    ("v5e", 2048),
)
_FLASH_MIN_SEQ_DEFAULT = 2048
_DENSE_SCORE_BYTES_CAP = 256 << 20


def flash_min_seq() -> int:
    """The flash/dense crossover for the device this process runs on.
    Consulted at trace time by _attention; unknown kinds (including CPU
    test runs, where the routing is exercised but not perf-relevant) use
    the default."""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except RuntimeError:  # no backend — routing still needs an answer
        return _FLASH_MIN_SEQ_DEFAULT
    for marker, crossover in _FLASH_MIN_SEQ_BY_KIND:
        if marker in kind:
            return crossover
    return _FLASH_MIN_SEQ_DEFAULT


def _pick_kernel(seq: int) -> str:
    """Per-bucket flash/dense routing (workloads/ops/kernel_select.py):
    a MEASURED per-(seq-bucket) dispatch table — the committed bench
    artifact had flash at 0.80x dense at seq 1024 while winning at
    2048+, which a single crossover number cannot express — with the
    legacy ``flash_min_seq()`` threshold as the fallback for hardware
    no table covers (so CPU test hosts and monkeypatched crossovers
    behave exactly as before the table existed)."""
    from workloads.ops.kernel_select import kernel_for_seq

    return kernel_for_seq(seq, default_min_seq=flash_min_seq())


def _attention(
    x: jax.Array, layer: dict, config: ModelConfig, attention_fn=None
) -> jax.Array:
    batch, seq, _ = x.shape
    q, k, v = project_qkv(x, layer)
    q, k = _rope(q), _rope(k)
    if attention_fn is not None:
        # Injected core (e.g. sequence-parallel ring attention bound to a
        # mesh — workloads/train.py make_seq_parallel_train_step).  The
        # injected cores compute full causal spans; silently training
        # full-span while serving windowed would be a train/serve
        # mismatch, so a windowed config fails loudly here.
        if config.attention_window is not None:
            raise ValueError(
                "attention_window is not supported with an injected "
                "attention_fn (ring/ulysses/usp compute full causal spans)"
            )
        out = attention_fn(q, k, v)
    elif config.attention_impl == "flash" and (
        _pick_kernel(seq) == "flash"
        or 4 * batch * config.n_heads * seq * seq > _DENSE_SCORE_BYTES_CAP
    ):
        from workloads.ops import flash_attention

        out = flash_attention(q, k, v, window=config.attention_window)
    else:
        # Short sequences (static shapes — this routing is trace-time):
        # the dense core is faster than the kernel here and the score
        # matrix is bounded by the cap above.
        mask = jnp.tril(jnp.ones((seq, seq), bool))
        if config.attention_window is not None:
            ids = jnp.arange(seq)
            mask &= ids[None, :] > ids[:, None] - config.attention_window
        out = masked_attention(q, k, v, mask[None, None], config.head_dim)
    return jnp.einsum("bshk,hkd->bsd", out, weight(layer["wo"], x.dtype))


def _mlp(x: jax.Array, layer: dict) -> jax.Array:
    hidden = jax.nn.gelu(x @ weight(layer["w_up"], x.dtype))
    return hidden @ weight(layer["w_down"], x.dtype)


def forward(
    params: dict, tokens: jax.Array, config: ModelConfig, attention_fn=None
) -> jax.Array:
    """Logits for next-token prediction.  tokens: [batch, seq] int32."""
    x = params["embed"].astype(config.dtype)[tokens]

    def layer_step(x, layer):
        x = x + _attention(_rmsnorm(x, layer["ln1"]), layer, config, attention_fn)
        return x + _mlp(_rmsnorm(x, layer["ln2"]), layer)

    if config.remat_layers:
        layer_step = jax.checkpoint(layer_step)
    for layer in params["layers"]:
        x = layer_step(x, layer)
    # Final projection in float32 for a stable softmax/loss.
    return x.astype(jnp.float32) @ weight(params["unembed"], jnp.float32)


def cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token NLL; shared by every loss variant (dense, MoE,
    pipeline)."""
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def loss_fn(
    params: dict, tokens: jax.Array, config: ModelConfig, attention_fn=None
) -> jax.Array:
    """Causal LM cross-entropy: predict tokens[:, 1:] from tokens[:, :-1]."""
    logits = forward(params, tokens[:, :-1], config, attention_fn)
    return cross_entropy(logits, tokens[:, 1:])


def make_forward_fn(config: ModelConfig):
    """A jittable (params, tokens) -> logits closure for the graft entry."""
    return partial(forward, config=config)

"""Device-time observability for the serving stack: on-demand deep
profiles, per-dispatch device-time attribution, and a live
perf-regression sentry (docs/OBSERVABILITY.md "Device-time profiling &
regression sentry").

Every existing observability layer (PR-3 metrics, PR-10 traces, PR-15
chip-time ledger) attributes HOST wall-clock; this module adds the
device-side decomposition of the chip-second the ledger charges:

  * ``ProfileSession`` — bounded ``jax.profiler`` trace capture
    (duration AND disk budget), exposed live as ``FleetServer POST
    /profile?secs=`` and the serve CLI's ``--profile-dir``, so an
    operator can pull a device trace from a running fleet without
    restarting anything.
  * ``DeviceTimeTable`` — an EWMA calibration table of measured device
    times per (program, seq-bucket, batch-bucket), built from the
    warmup/serve dispatches the engine already runs, snapshot-persisted
    via ``EngineSnapshot.device_time_table`` (workloads/faststart.py)
    and refreshable from the committed bench artifact.  It feeds the
    ``device_ms`` estimate on every ``StepRecord`` so each charged wall
    window splits into device-busy vs host-stall.
  * ``RegressionSentry`` + ``SentryFeed`` — rolling EWMA + z-score
    detectors over tokens/sec, TTFT p99, ``host_sync_ms`` and
    ``device_busy_fraction`` against the committed bench baseline,
    firing a ``perf_regression`` trigger into the PR-15 flight
    recorder (the bundle embeds the detector state).

Deliberately importable WITHOUT jax, like obs.py and ledger.py: the
``jax.profiler`` import is gated inside ``ProfileSession.start()``, so
the sentry/table machinery stays testable jax-free and the whole layer
is inert by default — it only ever READS engine counters (token
streams are asserted bit-identical profiler on/off, priced by the
``measure_profiler`` perfbench arm as ``profiler_overhead_pct``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

__all__ = [
    "ProfileSession",
    "DeviceTimeTable",
    "RegressionSentry",
    "SentryFeed",
    "sentry_from_artifact",
    "artifact_spread_fraction",
    "device_report",
]


# ---- on-demand deep profiles -------------------------------------------


class ProfileSession:
    """Bounded ``jax.profiler`` trace capture for a live process.

    One session owns one output directory and two budgets: every
    capture's duration is clamped to ``max_secs`` (a background timer
    stops a capture the caller forgets), and the summed on-disk size of
    all captures is capped at ``max_bytes`` — ``start()`` refuses once
    the budget is spent, so an operator hammering ``POST /profile``
    cannot fill the node's disk.  Thread-safe: the fleet HTTP handler
    and the auto-stop timer race ``stop()`` harmlessly."""

    def __init__(
        self,
        out_dir: str,
        *,
        max_secs: float = 30.0,
        max_bytes: int = 256 * 1024 * 1024,
        clock=time.monotonic,
    ):
        if max_secs <= 0:
            raise ValueError(f"max_secs must be > 0, got {max_secs}")
        if max_bytes <= 0:
            raise ValueError(f"max_bytes must be > 0, got {max_bytes}")
        self.out_dir = out_dir
        self.max_secs = float(max_secs)
        self.max_bytes = int(max_bytes)
        self.captures: list[dict] = []
        self._clock = clock
        self._lock = threading.Lock()
        self._active_dir: str | None = None
        self._t_start: float | None = None
        self._timer: threading.Timer | None = None

    @property
    def active(self) -> bool:
        return self._active_dir is not None

    @property
    def bytes_spent(self) -> int:
        return sum(c["bytes"] for c in self.captures)

    def start(self, secs: float | None = None) -> dict:
        """Begin one capture.  ``secs`` arms an auto-stop timer (clamped
        to ``max_secs``); ``None`` captures until ``stop()`` — still
        duration-bounded by a ``max_secs`` timer, so a dropped client
        can never leave the profiler running forever.  Raises
        ``RuntimeError`` when a capture is already active or the disk
        budget is spent."""
        with self._lock:
            if self._active_dir is not None:
                raise RuntimeError(
                    f"profile capture already active in {self._active_dir}"
                )
            if self.bytes_spent >= self.max_bytes:
                raise RuntimeError(
                    f"profile disk budget spent ({self.bytes_spent} of "
                    f"{self.max_bytes} bytes across "
                    f"{len(self.captures)} captures)"
                )
            secs = self.max_secs if secs is None else min(
                float(secs), self.max_secs
            )
            if secs <= 0:
                raise ValueError(f"secs must be > 0, got {secs}")
            dump_dir = os.path.join(
                self.out_dir, f"profile-{len(self.captures):03d}"
            )
            os.makedirs(dump_dir, exist_ok=True)
            import jax.profiler  # gated: the rest of the module is jax-free

            jax.profiler.start_trace(dump_dir)
            self._active_dir = dump_dir
            self._t_start = self._clock()
            self._timer = threading.Timer(secs, self.stop)
            self._timer.daemon = True
            self._timer.start()
            return {"dir": dump_dir, "secs": secs}

    def stop(self) -> dict | None:
        """End the active capture (idempotent: the auto-stop timer and
        an explicit caller may both arrive).  Returns the capture
        record — dump dir, wall secs, on-disk bytes — or ``None`` when
        nothing was active."""
        with self._lock:
            dump_dir, self._active_dir = self._active_dir, None
            if dump_dir is None:
                return None
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            import jax.profiler

            jax.profiler.stop_trace()
            size = 0
            for root, _, files in os.walk(dump_dir):
                for fn in files:
                    try:
                        size += os.path.getsize(os.path.join(root, fn))
                    except OSError:
                        pass
            rec = {
                "dir": dump_dir,
                "secs": round(self._clock() - (self._t_start or 0.0), 3),
                "bytes": size,
            }
            self.captures.append(rec)
            return rec

    def state(self) -> dict:
        """JSON-able session state for the HTTP endpoint and bundles."""
        with self._lock:
            return {
                "out_dir": self.out_dir,
                "active": self._active_dir is not None,
                "active_dir": self._active_dir,
                "max_secs": self.max_secs,
                "max_bytes": self.max_bytes,
                "bytes_spent": self.bytes_spent,
                "captures": [dict(c) for c in self.captures],
            }

    def close(self) -> dict | None:
        return self.stop()


# ---- per-dispatch device-time attribution ------------------------------


def _pow2_bucket(n: int) -> int:
    """Next power-of-two bucket (0 stays 0): dispatch shapes the engine
    actually compiles are bucketed, so measured times generalize across
    requests without one table entry per exact size."""
    n = int(n)
    if n <= 0:
        return 0
    b = 1
    while b < n:
        b *= 2
    return b


class DeviceTimeTable:
    """EWMA calibration table: (program, seq-bucket, batch-bucket) ->
    measured device milliseconds per dispatch.

    The observer feeds it every non-idle step's measured device window
    (step wall minus the engine-measured host-sync stall) and reads the
    smoothed estimate back as ``StepRecord.device_ms`` — warmup
    dispatches the engine already runs populate the first entries, so
    attribution works from the first served request.  ``to_dict`` /
    ``load`` round-trip through JSON for ``EngineSnapshot`` persistence
    and the bench artifact (``profiler_device_time_table``)."""

    def __init__(self, *, alpha: float = 0.25):
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self._table: dict[str, dict] = {}
        # Artifact refreshes that fail to parse (absent, truncated,
        # corrupt, wrong schema) adopt nothing and count here —
        # attribution degrades to live-measurement warmup (the cold
        # path), never raises.
        self.refresh_errors = 0

    @staticmethod
    def key(program: str, seq_tokens: int, batch: int) -> str:
        return (
            f"{program}|s{_pow2_bucket(seq_tokens)}|b{_pow2_bucket(batch)}"
        )

    def __len__(self) -> int:
        return len(self._table)

    def observe(
        self, program: str, seq_tokens: int, batch: int, device_ms: float
    ) -> None:
        if device_ms < 0:
            return
        k = self.key(program, seq_tokens, batch)
        ent = self._table.get(k)
        if ent is None:
            self._table[k] = {"ms": float(device_ms), "n": 1}
        else:
            ent["ms"] += self.alpha * (float(device_ms) - ent["ms"])
            ent["n"] += 1

    def estimate(
        self, program: str, seq_tokens: int, batch: int
    ) -> float | None:
        """Smoothed device-ms for a dispatch shape: the exact bucket
        when calibrated, else the nearest same-program bucket (a coarse
        prior beats attributing nothing), else ``None``."""
        k = self.key(program, seq_tokens, batch)
        ent = self._table.get(k)
        if ent is not None:
            return ent["ms"]
        want_s = _pow2_bucket(seq_tokens)
        want_b = _pow2_bucket(batch)
        best, best_d = None, None
        for other, ent in self._table.items():
            prog, s_s, b_s = other.split("|")
            if prog != program:
                continue
            d = abs(int(s_s[1:]) - want_s) + abs(int(b_s[1:]) - want_b)
            if best_d is None or d < best_d:
                best, best_d = ent["ms"], d
        return best

    def to_dict(self) -> dict:
        return {
            k: {"ms": round(v["ms"], 4), "n": v["n"]}
            for k, v in sorted(self._table.items())
        }

    def load(self, table: dict | None) -> int:
        """Merge a persisted table (snapshot / bench artifact); existing
        live entries win — a snapshot must never overwrite fresher
        measurements.  Returns the number of entries adopted."""
        if not isinstance(table, dict):
            if table is not None:
                self.refresh_errors += 1
            return 0
        adopted = 0
        for k, v in table.items():
            if k in self._table or not isinstance(v, dict):
                continue
            ms = v.get("ms")
            n = v.get("n", 1)
            if not isinstance(n, (int, float)):
                n = 1
            if isinstance(ms, (int, float)) and ms >= 0:
                self._table[k] = {"ms": float(ms), "n": int(n) or 1}
                adopted += 1
        return adopted

    def refresh_from_artifact(self, artifact) -> int:
        """Adopt the calibration the committed bench artifact carries
        (``profiler_device_time_table``, published by the
        ``measure_profiler`` arm).  ``artifact`` is the parsed artifact
        dict OR a path to the JSON file; an absent/truncated/corrupt
        file or a malformed payload adopts nothing and bumps
        ``refresh_errors`` — the table stays on live-measurement
        warmup, the cold path."""
        if isinstance(artifact, (str, os.PathLike)):
            try:
                with open(artifact, encoding="utf-8") as f:
                    artifact = json.load(f)
            except (OSError, ValueError):
                self.refresh_errors += 1
                return 0
        if not isinstance(artifact, dict):
            self.refresh_errors += 1
            return 0
        return self.load(artifact.get("profiler_device_time_table"))


def device_report(observers) -> dict:
    """Fleet-wide device-busy/host-stall split, per dispatch program,
    from the observers' step rings: the per-phase decomposition the
    chip-time ledger's wall windows lack.  Read-only over already-
    recorded rings — safe to call from ``/healthz`` or a summary
    print."""
    phases: dict[str, dict] = {}
    wall_ms = device_ms = 0.0
    for obs in observers:
        if obs is None:
            continue
        for rec in list(obs.steps):
            ph = phases.setdefault(
                rec.mode, {"wall_ms": 0.0, "device_ms": 0.0, "steps": 0}
            )
            w = rec.dur_secs * 1000.0
            d = getattr(rec, "device_ms", 0.0)
            ph["wall_ms"] += w
            ph["device_ms"] += d
            ph["steps"] += 1
            wall_ms += w
            device_ms += d
    for ph in phases.values():
        ph["device_busy_fraction"] = round(
            min(ph["device_ms"] / ph["wall_ms"], 1.0), 4
        ) if ph["wall_ms"] > 0 else 0.0
        ph["wall_ms"] = round(ph["wall_ms"], 3)
        ph["device_ms"] = round(ph["device_ms"], 3)
    busy = min(device_ms / wall_ms, 1.0) if wall_ms > 0 else 0.0
    return {
        "device_busy_fraction": round(busy, 4),
        "host_stall_fraction": round(1.0 - busy, 4),
        "wall_ms": round(wall_ms, 3),
        "device_ms": round(device_ms, 3),
        "phases": {k: phases[k] for k in sorted(phases)},
    }


# ---- live regression sentry --------------------------------------------


@dataclass
class _Detector:
    """One watched signal: EWMA-smoothed value scored as a z against
    the committed baseline's mean and noise band.  ``direction`` is +1
    when HIGHER is bad (latency, stall) and -1 when LOWER is bad
    (throughput, busy fraction) — the signed z is positive exactly when
    the signal moved the bad way."""

    name: str
    baseline: float | None
    spread: float
    direction: int
    warmup: int
    ewma: float | None = None
    breaches: int = 0
    oks: int = 0
    samples: int = 0
    last_z: float = 0.0
    _warm: list = field(default_factory=list)


class RegressionSentry:
    """Rolling EWMA + z-score regression detection over live serving
    signals, firing ``perf_regression`` into an attached
    ``FlightRecorder`` exactly once per incident.

    ``watch()`` registers a signal with a committed baseline mean and
    an absolute noise band (``spread``); ``observe()`` feeds live
    values.  A detector breaches when its smoothed z crosses
    ``z_threshold`` for ``confirm`` consecutive observations; the FIRST
    breach while armed fires the trigger and DISARMS the sentry, so a
    sustained regression produces one bundle, not one per poll.  The
    sentry re-arms only after every breached detector has read
    in-band for ``rearm`` consecutive observations (recovery), at
    which point a NEW regression fires again.  A ``baseline=None``
    watch self-baselines from its first ``warmup`` observations (the
    live-fleet mode: the committed artifact contributes the RELATIVE
    noise band, the run contributes its own operating point — a CLI
    fleet on a different model shape must not compare absolute tok/s
    against the bench's).  Everything here is host-side float
    arithmetic over values the caller already computed: the sentry
    never touches device state, RNG or scheduling — streams are
    bit-identical sentry on/off."""

    def __init__(
        self,
        *,
        z_threshold: float = 4.0,
        alpha: float = 0.3,
        confirm: int = 3,
        rearm: int = 5,
        clock=time.monotonic,
        history: int = 64,
    ):
        if z_threshold <= 0:
            raise ValueError(f"z_threshold must be > 0, got {z_threshold}")
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if confirm < 1 or rearm < 1:
            raise ValueError("confirm/rearm must be >= 1 observations")
        self.z_threshold = z_threshold
        self.alpha = alpha
        self.confirm = confirm
        self.rearm = rearm
        self.clock = clock
        self.armed = True
        self.fired = 0
        self.incidents: list[dict] = []
        self.recorder = None
        self._detectors: dict[str, _Detector] = {}
        self._history: list[dict] = []
        self._history_limit = history

    def watch(
        self,
        name: str,
        baseline: float | None,
        spread: float,
        *,
        direction: str = "down_bad",
        warmup: int = 4,
    ) -> None:
        if direction not in ("down_bad", "up_bad"):
            raise ValueError(
                f"direction must be down_bad|up_bad, got {direction!r}"
            )
        if spread <= 0:
            raise ValueError(f"spread must be > 0, got {spread}")
        self._detectors[name] = _Detector(
            name=name,
            baseline=None if baseline is None else float(baseline),
            spread=float(spread),
            direction=+1 if direction == "up_bad" else -1,
            warmup=max(int(warmup), 1),
        )

    @property
    def signals(self) -> tuple[str, ...]:
        return tuple(sorted(self._detectors))

    def observe(self, name: str, value: float) -> dict | None:
        """Feed one live sample; returns the incident dict when THIS
        observation fired the trigger, else ``None``.  Unwatched names
        are ignored (the feed may offer more signals than the baseline
        could anchor)."""
        det = self._detectors.get(name)
        if det is None:
            return None
        value = float(value)
        det.samples += 1
        if det.baseline is None:
            # Self-baselining: the first `warmup` samples fix the
            # operating point; the RELATIVE band from the artifact
            # becomes absolute against it.
            det._warm.append(value)
            if len(det._warm) < det.warmup:
                return None
            det.baseline = sum(det._warm) / len(det._warm)
            det.spread = max(
                det.spread * abs(det.baseline), 1e-9
            )
            det._warm.clear()
            return None
        det.ewma = value if det.ewma is None else (
            det.ewma + self.alpha * (value - det.ewma)
        )
        z = (
            (det.ewma - det.baseline) / max(det.spread, 1e-9)
        ) * det.direction
        det.last_z = round(z, 3)
        self._history.append({
            "t": self.clock(), "signal": name,
            "value": round(value, 4), "z": det.last_z,
        })
        del self._history[: -self._history_limit]
        if z >= self.z_threshold:
            det.breaches += 1
            det.oks = 0
        else:
            det.oks += 1
            if det.oks >= self.rearm:
                det.breaches = 0
        incident = None
        if det.breaches >= self.confirm and self.armed:
            self.armed = False
            self.fired += 1
            incident = {
                "signal": name,
                "z": det.last_z,
                "ewma": round(det.ewma, 4),
                "baseline": round(det.baseline, 4),
                "spread": round(det.spread, 4),
                "t": self.clock(),
            }
            self.incidents.append(incident)
            if self.recorder is not None:
                self.recorder.trigger(
                    "perf_regression",
                    detail=(
                        f"{name} z={det.last_z} "
                        f"ewma={incident['ewma']} "
                        f"baseline={incident['baseline']} "
                        f"spread={incident['spread']}"
                    ),
                )
        elif not self.armed and all(
            d.breaches == 0 for d in self._detectors.values()
        ):
            # Every breached signal has recovered: re-arm so the NEXT
            # regression fires its own bundle.
            self.armed = True
        return incident

    def state(self) -> dict:
        """Detector state for flight-recorder bundles: baselines,
        smoothed values, z-scores, breach counters, the incident log
        and the last N raw observations."""
        return {
            "armed": self.armed,
            "fired": self.fired,
            "z_threshold": self.z_threshold,
            "alpha": self.alpha,
            "confirm": self.confirm,
            "rearm": self.rearm,
            "detectors": {
                name: {
                    "baseline": (
                        None if d.baseline is None
                        else round(d.baseline, 4)
                    ),
                    "spread": round(d.spread, 4),
                    "direction": (
                        "up_bad" if d.direction > 0 else "down_bad"
                    ),
                    "ewma": None if d.ewma is None else round(d.ewma, 4),
                    "last_z": d.last_z,
                    "breaches": d.breaches,
                    "oks": d.oks,
                    "samples": d.samples,
                }
                for name, d in sorted(self._detectors.items())
            },
            "incidents": [dict(i) for i in self.incidents],
            "recent": [dict(h) for h in self._history],
        }


def artifact_spread_fraction(
    artifact: dict, floor: float = 0.08
) -> float:
    """The committed artifact's own measured cross-run noise band: the
    median relative half-width of its pooled ``<key>_samples`` spread
    families (the same derivation tools/bench_diff.py uses for its
    spread-guarded thresholds), floored for artifacts that predate the
    samples."""
    widths = []
    for key in artifact:
        if not key.endswith("_samples"):
            continue
        base = key[: -len("_samples")]
        lo, hi, mid = (
            artifact.get(base + "_min"),
            artifact.get(base + "_max"),
            artifact.get(base),
        )
        if all(
            isinstance(v, (int, float)) for v in (lo, hi, mid)
        ) and mid:
            widths.append((hi - lo) / (2 * abs(mid)))
    if not widths:
        return floor
    widths.sort()
    return max(floor, widths[len(widths) // 2])


# Signal -> (artifact key carrying its baseline, bad direction).  The
# four live signals the ISSUE's sentry watches; keys absent from the
# artifact degrade to an unwatched signal, loudly listed in state().
_SENTRY_SIGNALS = (
    ("tokens_per_sec", "profiler_on_tokens_per_sec", "down_bad"),
    ("ttft_p99_ms", "serve_ttft_p99_ms", "up_bad"),
    ("host_sync_ms", "decode_host_sync_ms", "up_bad"),
    ("device_busy_fraction", "device_busy_fraction", "down_bad"),
)


def sentry_from_artifact(
    artifact: dict,
    *,
    live: bool = False,
    recorder=None,
    **kw,
) -> RegressionSentry:
    """Build the four-signal sentry from the committed bench artifact.

    ``live=False`` (tests, bench-shaped runs): baselines are the
    artifact's ABSOLUTE values, spreads its measured noise band times
    each baseline — in-band noise at the committed spread can never
    fire.  ``live=True`` (the serve CLI's fleet loop): the artifact
    contributes only the RELATIVE spread; each detector self-baselines
    from its first observed windows, because a CLI fleet on a different
    model shape must not be scored against the bench's absolute
    numbers.  Artifact keys that are missing leave their signal
    unwatched."""
    sentry = RegressionSentry(**kw)
    if recorder is not None:
        recorder.attach_sentry(sentry)
    rel = artifact_spread_fraction(artifact)
    for signal, key, direction in _SENTRY_SIGNALS:
        base = artifact.get(key)
        if signal == "tokens_per_sec" and not isinstance(
            base, (int, float)
        ):
            base = artifact.get("serve_tokens_per_sec")
        if not isinstance(base, (int, float)) or not base:
            continue
        if live:
            sentry.watch(signal, None, rel, direction=direction)
        else:
            sentry.watch(
                signal, float(base), rel * abs(float(base)),
                direction=direction,
            )
    return sentry


class SentryFeed:
    """Windowed signal extraction from a live fleet into the sentry:
    polled from the drive loop (next to ``FlightRecorder.poll``), it
    reads engine counters and observer rings — never device state —
    and feeds tokens/sec, host-sync ms/step, TTFT p99 and the
    device-busy fraction once per ``min_window_s`` window."""

    def __init__(
        self,
        sentry: RegressionSentry,
        *,
        min_window_s: float = 0.25,
        clock=time.perf_counter,
    ):
        self.sentry = sentry
        self.min_window_s = min_window_s
        self._clock = clock
        self._engines: list = []
        self._observers: list = []
        self._t_last: float | None = None
        self._tokens_last = 0
        self._sync_last = 0.0
        self._steps_last = 0
        self._spans_seen: dict[int, int] = {}
        self._ttft_ms: list[float] = []

    def attach(self, engine, observer=None) -> None:
        self._engines.append(engine)
        if observer is not None:
            self._observers.append(observer)

    def poll(self) -> list[dict]:
        """One windowed observation sweep; returns any incidents fired."""
        now = self._clock()
        if self._t_last is None:
            self._t_last = now
            self._tokens_last = self._total("generated_tokens")
            self._sync_last = self._total("host_sync_s")
            self._steps_last = sum(
                o._step_index for o in self._observers
            )
            return []
        window = now - self._t_last
        if window < self.min_window_s:
            return []
        incidents = []
        tokens = self._total("generated_tokens")
        d_tokens = tokens - self._tokens_last
        inc = self.sentry.observe("tokens_per_sec", d_tokens / window)
        if inc:
            incidents.append(inc)
        sync = self._total("host_sync_s")
        steps = sum(o._step_index for o in self._observers)
        d_steps = steps - self._steps_last
        if d_steps > 0:
            inc = self.sentry.observe(
                "host_sync_ms",
                (sync - self._sync_last) * 1000.0 / d_steps,
            )
            if inc:
                incidents.append(inc)
        for obs in self._observers:
            # Non-destructive new-span cursor: spans-ever-recorded is
            # ring length + counted evictions, so the feed never drains
            # (the trace export owns the rings) and never double-counts.
            ever = len(obs.spans) + obs.dropped_spans
            seen = self._spans_seen.get(id(obs), 0)
            fresh = min(ever - seen, len(obs.spans))
            self._spans_seen[id(obs)] = ever
            if fresh > 0:
                for span in list(obs.spans)[-fresh:]:
                    if span.ttft_secs is not None:
                        self._ttft_ms.append(span.ttft_secs * 1000.0)
        del self._ttft_ms[:-256]
        if self._ttft_ms:
            ordered = sorted(self._ttft_ms)
            p99 = ordered[
                min(int(len(ordered) * 0.99), len(ordered) - 1)
            ]
            inc = self.sentry.observe("ttft_p99_ms", p99)
            if inc:
                incidents.append(inc)
        fracs = [
            o.device_busy_fraction
            for o in self._observers
            if getattr(o, "_wall_ms", 0.0) > 0
        ]
        if fracs:
            inc = self.sentry.observe(
                "device_busy_fraction", sum(fracs) / len(fracs)
            )
            if inc:
                incidents.append(inc)
        self._t_last = now
        self._tokens_last = tokens
        self._sync_last = sync
        self._steps_last = steps
        return incidents

    def _total(self, attr: str) -> float:
        total = 0.0
        for eng in self._engines:
            try:
                total += float(getattr(eng, attr, 0) or 0)
            except Exception:
                pass
        return total


def load_committed_artifact(repo_root: str | None = None) -> dict | None:
    """The committed bench artifact the sentry baselines against
    (docs/bench-builder-latest.json), or ``None`` when absent/broken —
    the CLI degrades to no sentry rather than failing a serve run over
    a docs file."""
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    path = os.path.join(root, "docs", "bench-builder-latest.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None

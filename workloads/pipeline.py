"""Pipeline parallelism: GPipe-schedule training over a "pipe" mesh axis.

The pp axis of the workload suite.  The flagship transformer's layer stack
is split into S contiguous stages whose (stacked) weights shard over the
mesh's "pipe" axis; the batch is split into M microbatches that stream
through the stages.  Expressed the idiomatic TPU way: one jitted
``shard_map`` whose body runs a ``lax.scan`` over the M+S-1 schedule steps,
passing activations stage-to-stage with ``lax.ppermute`` (ICI neighbour
transfers) — no host-side scheduling, no per-stage processes; XLA sees one
static program.  Differentiable end-to-end (scan + ppermute transpose), so
the full fwd+bwd+Adam step jits over ("data", "pipe"): dp x pp.

Embedding/unembedding are replicated and computed outside the pipelined
region (they are tiny at these sizes); only the transformer blocks are
staged.  Bubble fraction is the GPipe (S-1)/(M+S-1); pick M >= S.

Reference pendant: none — the reference daemon has no model code; this
belongs to the JAX workload suite exercising multi-chip slices the device
plugin allocates (SURVEY.md §2 parallelism checklist note).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from .model import ModelConfig, _attention, _mlp, _rmsnorm, init_params


def make_pp_mesh(n_devices: int, pipe_parallel: int = 2) -> Mesh:
    """A ("data", "pipe") mesh: batch data-parallel, layers staged."""
    devices = jax.devices()[:n_devices]
    if len(devices) < n_devices:
        raise ValueError(
            f"requested a {n_devices}-device mesh but only "
            f"{len(devices)} devices are visible"
        )
    if n_devices % pipe_parallel:
        raise ValueError(
            f"{n_devices} devices not divisible by pipe_parallel={pipe_parallel}"
        )
    grid = np.array(devices).reshape(n_devices // pipe_parallel, pipe_parallel)
    return Mesh(grid, axis_names=("data", "pipe"))


def init_pipeline_params(config: ModelConfig, n_stages: int, key: jax.Array):
    """Flagship params with the layer list stacked into [S, L/S, ...] leaves
    (stage-major), ready to shard on the "pipe" axis."""
    if config.n_layers % n_stages:
        raise ValueError(
            f"n_layers ({config.n_layers}) must divide into {n_stages} stages"
        )
    params = init_params(config, key)
    layers = params.pop("layers")
    per_stage = config.n_layers // n_stages
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *layers)
    stacked = jax.tree.map(
        lambda leaf: leaf.reshape((n_stages, per_stage) + leaf.shape[1:]), stacked
    )
    params["stages"] = stacked
    return params


def pipeline_param_specs(config: ModelConfig) -> dict:
    """Stage-stacked leaves shard their leading dim on "pipe"."""
    layer = {
        "ln1": P("pipe"),
        "ln2": P("pipe"),
        "wo": P("pipe"),
        "w_up": P("pipe"),
        "w_down": P("pipe"),
    }
    if config.kv_heads == config.n_heads:
        layer["wqkv"] = P("pipe")
    else:
        layer["wq"] = P("pipe")
        layer["wkv"] = P("pipe")
    return {"embed": P(), "unembed": P(), "stages": layer}


def _stage_blocks(local_layers: dict, x: jax.Array, config: ModelConfig):
    """Apply this stage's L/S transformer blocks (leaves [L/S, ...])."""

    def block(carry, layer):
        h = carry + _attention(_rmsnorm(carry, layer["ln1"]), layer, config)
        h = h + _mlp(_rmsnorm(h, layer["ln2"]), layer)
        return h, None

    out, _ = jax.lax.scan(block, x, local_layers)
    return out


def _pipeline_local(
    stages, x_mb, *, config: ModelConfig, n_stages: int, n_microbatches: int
):
    """Per-device body: stages leaves [1, L/S, ...] (this stage's slice),
    x_mb [M, mb_local, s, d].  Returns [M, mb_local, s, d] — the last
    stage's outputs, replicated over "pipe" via a masked psum."""
    local_layers = jax.tree.map(lambda leaf: leaf[0], stages)
    stage = jax.lax.axis_index("pipe")
    m, mb, seq, d = x_mb.shape
    is_first = (stage == 0).astype(x_mb.dtype)
    is_last = (stage == n_stages - 1).astype(x_mb.dtype)

    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def schedule_step(carry, t):
        state, ys = carry
        # Activations flow one stage down the ring; stage 0 instead picks up
        # the next microbatch (clamped index: past-the-end steps reprocess
        # the last microbatch, and their products never reach collection).
        incoming = jax.lax.ppermute(state, "pipe", perm)
        xt = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, n_microbatches - 1), keepdims=False
        )
        inp = is_first * xt + (1 - is_first) * incoming
        out = _stage_blocks(local_layers, inp, config)
        # The last stage banks microbatch t-(S-1) once the pipe has filled.
        idx = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
        collect = is_last * (t >= n_stages - 1).astype(x_mb.dtype)
        slot = jax.lax.dynamic_index_in_dim(ys, idx, keepdims=False)
        ys = jax.lax.dynamic_update_index_in_dim(
            ys, collect * out + (1 - collect) * slot, idx, 0
        )
        return (out, ys), None

    state0 = jnp.zeros((mb, seq, d), x_mb.dtype)
    ys0 = jnp.zeros_like(x_mb)
    (_, ys), _ = jax.lax.scan(
        schedule_step, (state0, ys0), jnp.arange(m + n_stages - 1)
    )
    # Only the last stage holds real outputs; psum replicates them pipe-wide.
    return jax.lax.psum(is_last * ys, "pipe")


def pipeline_forward(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    mesh: Mesh,
    n_microbatches: int,
) -> jax.Array:
    """Logits via the pipelined layer stack.  tokens: [batch, T] with batch
    divisible by n_microbatches x mesh["data"]."""
    n_stages = mesh.shape["pipe"]
    batch, seq = tokens.shape
    if batch % n_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by n_microbatches={n_microbatches}"
        )
    x = params["embed"].astype(config.dtype)[tokens]
    x_mb = x.reshape(n_microbatches, batch // n_microbatches, seq, -1)

    body = partial(
        _pipeline_local,
        config=config,
        n_stages=n_stages,
        n_microbatches=n_microbatches,
    )
    stage_spec = jax.tree.map(lambda _: P("pipe"), params["stages"])
    act_spec = P(None, "data", None, None)
    kwargs = dict(
        mesh=mesh, in_specs=(stage_spec, act_spec), out_specs=act_spec
    )
    try:
        run = shard_map(body, check_vma=False, **kwargs)
    except TypeError:  # pragma: no cover - older jax spells it check_rep
        run = shard_map(body, check_rep=False, **kwargs)
    ys = run(params["stages"], x_mb)
    ys = ys.reshape(batch, seq, -1)
    return ys.astype(jnp.float32) @ params["unembed"]


def pipeline_loss_fn(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    mesh: Mesh,
    n_microbatches: int,
) -> jax.Array:
    """Causal LM loss through the pipeline (same contract as model.loss_fn)."""
    from .model import cross_entropy

    logits = pipeline_forward(params, tokens[:, :-1], config, mesh, n_microbatches)
    return cross_entropy(logits, tokens[:, 1:])


def make_pipeline_train_state(
    config: ModelConfig, mesh: Mesh, seed: int = 0
):
    """(params, opt_state) with stages sharded on "pipe"."""
    from .train import make_sharded_train_state

    n_stages = mesh.shape["pipe"]
    return make_sharded_train_state(
        mesh,
        lambda: init_pipeline_params(config, n_stages, jax.random.PRNGKey(seed)),
        pipeline_param_specs(config),
    )


def make_pipeline_train_step(
    config: ModelConfig, mesh: Mesh, optimizer, n_microbatches: int = 4
):
    """The full dp x pp training step: pipelined forward, backward through
    the schedule (scan/ppermute transpose), Adam update."""
    from .train import make_sharded_train_step

    return make_sharded_train_step(
        lambda p, t: pipeline_loss_fn(p, t, config, mesh, n_microbatches),
        mesh,
        optimizer,
    )

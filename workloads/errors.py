"""Typed error taxonomy for the serving engine.

The reference plugin treats failure as a first-class state (it blocks on
critical-error events and flips devices Unhealthy instead of letting
faults surface as hangs — PAPER.md, nvidia.go:181-269); this module is
the serving half's analog at the API seam: every way a request can be
refused or abandoned is a distinct, catchable type instead of a bare
``ValueError``/``RuntimeError`` the caller must string-match.

The hierarchy deliberately double-inherits from the builtin types the
engine historically raised (``InvalidRequest``/``RequestTooLarge`` are
``ValueError``s, ``QueueFull``/``EngineClosed`` are ``RuntimeError``s),
so existing ``except ValueError`` call sites and tests keep working —
the messages are unchanged, only the types are narrower.

Deliberately dependency-free (no jax): importable by tooling and tests
that never touch a device.
"""

from __future__ import annotations

__all__ = [
    "ServeError",
    "InvalidRequest",
    "RequestTooLarge",
    "QueueFull",
    "EngineClosed",
]


class ServeError(Exception):
    """Base of every typed serving-engine error."""


class InvalidRequest(ServeError, ValueError):
    """A submission the engine can never serve as specified (unknown
    adapter, duplicate in-flight rid, malformed knobs) — resubmit with
    corrected arguments; retrying unchanged can never succeed."""


class RequestTooLarge(InvalidRequest):
    """A submission whose size can never fit this engine: prompt outside
    the [1, max_seq_len-1] window, prompt + max_new_tokens beyond the
    context window, or a worst-case page need exceeding the whole pool.
    A structural rejection, not backpressure — shrink the request or
    build a bigger engine."""


class QueueFull(ServeError, RuntimeError):
    """Bounded-admission backpressure: the pending queue is at
    ``max_pending`` and the engine rejects rather than queue without
    bound.  Transient by design — retry after retirements drain the
    queue (internal replay requeues are exempt from the bound, so
    recovery can never deadlock against it)."""


class EngineClosed(ServeError, RuntimeError):
    """The engine was ``close()``d: submissions and steps are refused,
    and requests that were pending or running at close time were failed
    with this error recorded on them."""

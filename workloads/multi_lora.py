"""Multi-LoRA serving: many fine-tuned adapters over ONE shared base.

The serving-side counterpart of workloads/lora.py (which trains one
adapter): a fleet of rank-r adapters — one per tenant/task — serves
through a single ServeEngine over one copy of the base weights.  The
S-LoRA/punica idea, expressed the JAX way:

  * adapters are STACKED into one pytree per layer
    (``{"a": [n, fan_in, r], "b": [n, r, fan_out]}``) so the batched
    decode path gathers each row's factors by index — data, not shape;
    admitting a request for a different adapter never recompiles;
  * the adapted weight is never materialised: the delta is applied on
    the ACTIVATION side, ``x @ W + alpha * (x @ a_i) @ b_i`` — O(r)
    extra HBM per row versus the O(fan_in * fan_out) a per-request merge
    would stream;
  * index 0 is the reserved BASE entry (zero factors): requests without
    an adapter ride the same code path at the same cost shape.

Reference pendant: none — the reference daemon has no model code; part
of the JAX serving workloads (SURVEY.md §7 step 8).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .model import ModelConfig


def synthetic_adapters(
    config: ModelConfig,
    n: int,
    rank: int = 8,
    scale: float = 0.1,
    seed: int = 0,
    prefix: str = "tenant",
) -> dict:
    """N trained-looking adapters for demos/benches/tests: lora_init's
    zero ``b`` (the identity adapter) is replaced with scaled normals so
    each tenant genuinely changes the model.  One source for the CLI,
    the bench, and the tests — the adapter layout lives here."""
    from .lora import lora_init

    key = jax.random.PRNGKey(seed)
    out = {}
    for i in range(n):
        ad = lora_init(config, rank, jax.random.PRNGKey(seed + 1000 + i))
        for layer in ad:
            for ab in layer.values():
                key, k = jax.random.split(key)
                ab["b"] = (
                    jax.random.normal(k, ab["b"].shape, jnp.float32) * scale
                )
        out[f"{prefix}-{i}"] = ad
    return out


def stack_adapters(adapters: list, config: ModelConfig) -> list:
    """[adapter][layer]{name: {a, b}} -> [layer]{name: {a: [n+1, fi, r],
    b: [n+1, r, fo]}} with the zero BASE adapter prepended at index 0.

    Every adapter must target the same weights at the same rank (one
    compiled gather shape); lora_init with shared (config, rank,
    targets) guarantees that."""
    if not adapters:
        raise ValueError("stack_adapters needs at least one adapter")
    n_layers = len(adapters[0])
    for i, ad in enumerate(adapters):
        if len(ad) != n_layers:
            raise ValueError(
                f"adapter {i} has {len(ad)} layers, expected {n_layers}"
            )
    stacked = []
    for li in range(n_layers):
        names = set(adapters[0][li])
        entry = {}
        for i, ad in enumerate(adapters):
            if set(ad[li]) != names:
                raise ValueError(
                    f"adapter {i} targets {sorted(ad[li])} at layer {li}, "
                    f"expected {sorted(names)} (all adapters must target "
                    "the same weights)"
                )
        for name in sorted(names):
            a_list = [ad[li][name]["a"] for ad in adapters]
            b_list = [ad[li][name]["b"] for ad in adapters]
            shapes = {(a.shape, b.shape) for a, b in zip(a_list, b_list)}
            if len(shapes) != 1:
                raise ValueError(
                    f"adapter factor shapes disagree for {name!r} at layer "
                    f"{li}: {sorted(shapes)} (same rank required)"
                )
            a = jnp.stack([jnp.zeros_like(a_list[0])] + list(a_list))
            b = jnp.stack([jnp.zeros_like(b_list[0])] + list(b_list))
            entry[name] = {"a": a, "b": b}
        stacked.append(entry)
    return stacked


def _row_delta(x: jax.Array, ab: dict, idx: jax.Array) -> jax.Array:
    """Per-row adapter delta: x [b, fan_in] (or [b, s, fan_in]) through
    each row's own (a, b) factors -> [b(, s), fan_out]."""
    a = ab["a"][idx]  # [b, fan_in, r]
    b = ab["b"][idx]  # [b, r, fan_out]
    if x.ndim == 2:
        u = jnp.einsum("bd,bdr->br", x.astype(jnp.float32), a)
        return jnp.einsum("br,brf->bf", u, b)
    u = jnp.einsum("bsd,bdr->bsr", x.astype(jnp.float32), a)
    return jnp.einsum("bsr,brf->bsf", u, b)


def qkv_row_deltas(h: jax.Array, entry: dict, idx: jax.Array,
                   config: ModelConfig):
    """(dq, dk, dv) — UNSCALED — for the layer's q/k/v projections from
    per-row adapters — fused ``wqkv`` or split ``wq``/``wkv`` layouts,
    matching model.project_qkv's output shapes; None where the layer has
    no such target."""
    lead = h.shape[:-1]  # (b,) or (b, s)
    H, Hkv, hd = config.n_heads, config.kv_heads, config.head_dim
    if "wqkv" in entry:
        d = _row_delta(h, entry["wqkv"], idx).reshape(*lead, 3, H, hd)
        d = jnp.moveaxis(d, len(lead), 0)
        return d[0], d[1], d[2]
    dq = dk = dv = None
    if "wq" in entry:
        dq = _row_delta(h, entry["wq"], idx).reshape(*lead, H, hd)
    if "wkv" in entry:
        dkv = _row_delta(h, entry["wkv"], idx).reshape(*lead, 2, Hkv, hd)
        dkv = jnp.moveaxis(dkv, len(lead), 0)
        dk, dv = dkv[0], dkv[1]
    return dq, dk, dv


def wo_row_delta(attn: jax.Array, entry: dict, idx: jax.Array,
                 alpha: float):
    """Output-projection delta (alpha-scaled) from per-row adapters:
    attn [b(, s), H, hd] -> [b(, s), d_model]; None when wo is
    untargeted."""
    if "wo" not in entry:
        return None
    flat = attn.reshape(*attn.shape[:-2], attn.shape[-2] * attn.shape[-1])
    return _row_delta(flat, entry["wo"], idx) * alpha


def apply_qkv(q, k, v, h, entry, idx, config, alpha, dtype):
    """q/k/v with the per-row adapter deltas added (alpha-scaled), cast
    back to the compute dtype; untargeted projections pass through."""
    dq, dk, dv = qkv_row_deltas(h, entry, idx, config)

    def add(x, d):
        if d is None:
            return x
        return (x.astype(jnp.float32) + alpha * d).astype(dtype)

    return add(q, dq), add(k, dk), add(v, dv)

# Central version pins consumed by the Makefile and image packaging
# (reference analog: versions.mk).

VERSION ?= v0.1.0

# Container image coordinates.  REGISTRY is empty for local-only builds;
# set REGISTRY=<host>/<org> to namespace pushes.
REGISTRY ?=
IMAGE_NAME ?= $(if $(REGISTRY),$(REGISTRY)/)tpu-device-plugin

# Toolchain floors (informational; the devel image and CI enforce them).
PYTHON_MIN_VERSION := 3.10
CXX_STANDARD := c++17

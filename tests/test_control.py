"""Goodput-control contracts on REAL engines (workloads/control.py +
ServeEngine.retune): every retune transition the controller can emit —
breakeven shift, superstep_k step, spec_superstep_k step, WFQ
re-weight, scored preempt — pinned for bit-identical greedy streams
against the dense oracle, plus the closed loop itself: a seeded waste
spike makes the controller walk the speculation knobs down and the
measured goodput fraction recovers, with no slot/page leaks.  The
jax-free hill-climb/hysteresis units live in test_control_units.py;
``make control-check`` runs ``test_control_check_smoke`` alone."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.autoscaler import FleetAutoscaler
from workloads.backoff import Backoff
from workloads.control import GoodputController
from workloads.errors import EngineClosed
from workloads.fleet import DEAD, Fleet
from workloads.generate import generate
from workloads.ledger import ChipTimeLedger, FleetLedger
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)
PARAMS = init_params(CONFIG, jax.random.PRNGKey(0))
# An UNCORRELATED draft: near-chance acceptance, so always-speculate
# engines burn heavy spec_rejected waste — the seeded spike the
# controller exists to retune away.  Greedy spec decoding stays exact
# regardless of draft quality, so oracle parity still pins every
# stream.
BAD_DRAFT = init_params(DRAFT_CONFIG, jax.random.PRNGKey(99))
ENGINE_KW = dict(slots=2, page_size=4, prompt_bucket=8)
FAST = Backoff(base_s=1e-6, max_s=1e-6, jitter=0.0)


def _spec_engine(**kw):
    base = dict(ENGINE_KW)
    base.update(kw)
    return ServeEngine(
        PARAMS, CONFIG, draft_params=BAD_DRAFT, draft_config=DRAFT_CONFIG,
        gamma=3, spec="auto", **base,
    )


def _plain_engine(**kw):
    base = dict(ENGINE_KW)
    base.update(kw)
    return ServeEngine(PARAMS, CONFIG, **base)


def _oracle(prompt, new):
    return [int(t) for t in np.asarray(generate(
        PARAMS, jnp.asarray([prompt], jnp.int32), CONFIG,
        max_new_tokens=new,
    )[0])]


def _controller(fleet, **kw):
    kw.setdefault("min_sample_tokens", 16)
    kw.setdefault("spec_reject_low", 0.02)
    kw.setdefault("spec_reject_high", 0.2)
    kw.setdefault("retune_backoff", FAST)
    kw.setdefault("wfq_backoff", FAST)
    return GoodputController(fleet, **kw)


def _assert_no_leaks(fleet):
    for rep in fleet.replicas:
        if rep.state == DEAD:
            continue
        e = rep.engine
        assert not e._occupied.any(), rep.index
        assert e._committed_pages == 0, rep.index
        assert not e._groups, rep.index
        pinned = e.prefix.cached_pages if e.prefix is not None else 0
        assert e.ctrl.used_pages == pinned, rep.index
        assert not rep.rids, rep.index


# ---- ServeEngine.retune: the actuation seam ------------------------------


def test_retune_validates_and_counts_only_real_changes():
    eng = _spec_engine(
        spec_breakeven=2.0, superstep_k=2, spec_superstep_k=2,
    )
    # No-op retunes return {} and never count (no drain, no churn).
    assert eng.retune(spec_breakeven=2.0) == {}
    assert eng.retune(superstep_k=2, spec_superstep_k=2) == {}
    assert eng.retunes == 0
    # The k knobs are bounded by their construction-time ceilings.
    with pytest.raises(ValueError, match="superstep_k"):
        eng.retune(superstep_k=4)
    with pytest.raises(ValueError, match="superstep_k"):
        eng.retune(superstep_k=0)
    with pytest.raises(ValueError, match="spec_superstep_k"):
        eng.retune(spec_superstep_k=3)
    with pytest.raises(ValueError, match="spec_breakeven"):
        eng.retune(spec_breakeven=-1.0)
    # A real change reports {knob: (old, new)} and counts once.
    assert eng.retune(superstep_k=1, spec_breakeven=0.5) == {
        "superstep_k": (2, 1), "spec_breakeven": (2.0, 0.5),
    }
    assert eng.retunes == 1
    eng.close()
    with pytest.raises(EngineClosed):
        eng.retune(spec_breakeven=1.0)
    # Breakeven shifts need spec="auto": other modes never consult the
    # threshold, so accepting one would fake an actuation.
    plain = _plain_engine()
    with pytest.raises(ValueError, match="auto"):
        plain.retune(spec_breakeven=1.0)
    plain.close()


def test_retune_breakeven_shift_mid_stream_bit_identical():
    """The spec_down/spec_up transition: breakeven slots -> 0 flips the
    engine from always-speculate to never mid-flight (draining the
    in-flight rounds), and back up again — streams stay oracle-exact
    across both switches."""
    eng = _spec_engine(spec_breakeven=2.0)
    reqs = [([5, 6, 7], 20), ([1, 2], 16), ([9], 12)]
    rids = [eng.submit(p, n) for p, n in reqs]
    out = {}
    for _ in range(3):
        for fr in eng.step():
            out[fr.rid] = fr.tokens
    assert eng.retune(spec_breakeven=0.0) == {
        "spec_breakeven": (2.0, 0.0),
    }
    for _ in range(3):
        for fr in eng.step():
            out[fr.rid] = fr.tokens
    assert eng.retune(spec_breakeven=2.0) == {
        "spec_breakeven": (0.0, 2.0),
    }
    for rid, toks in eng.run().items():
        out[rid] = toks
    assert eng.retunes == 2
    assert eng.spec_rounds > 0, "never speculated below the threshold"
    assert eng.chunks_run > 0, "never decoded plainly at breakeven 0"
    for rid, (prompt, new) in zip(rids, reqs):
        assert list(out[rid]) == _oracle(prompt, new), rid
    eng.close()


def test_retune_superstep_k_step_mid_stream_bit_identical():
    """The super_down/super_up transition on the plain fused path:
    k 4 -> 2 -> 4 mid-flight, never above the construction ceiling,
    streams oracle-exact throughout."""
    eng = _plain_engine(superstep_k=4)
    reqs = [([3, 4, 5, 6], 18), ([7, 8], 14)]
    rids = [eng.submit(p, n) for p, n in reqs]
    out = {}
    for _ in range(2):
        for fr in eng.step():
            out[fr.rid] = fr.tokens
    assert eng.retune(superstep_k=2) == {"superstep_k": (4, 2)}
    for _ in range(2):
        for fr in eng.step():
            out[fr.rid] = fr.tokens
    # Back UP to (never past) the constructed ceiling.
    assert eng.retune(superstep_k=4) == {"superstep_k": (2, 4)}
    with pytest.raises(ValueError):
        eng.retune(superstep_k=8)
    for rid, toks in eng.run().items():
        out[rid] = toks
    for rid, (prompt, new) in zip(rids, reqs):
        assert list(out[rid]) == _oracle(prompt, new), rid
    eng.close()


def test_retune_spec_superstep_k_step_mid_stream_bit_identical():
    """The fused-speculative-round transition: spec_superstep_k
    2 -> 1 -> 2 mid-flight on an always-speculating engine, streams
    oracle-exact."""
    eng = _spec_engine(spec_breakeven=2.0, spec_superstep_k=2)
    reqs = [([11, 12, 13], 16), ([14], 12)]
    rids = [eng.submit(p, n) for p, n in reqs]
    out = {}
    for _ in range(2):
        for fr in eng.step():
            out[fr.rid] = fr.tokens
    assert eng.retune(spec_superstep_k=1) == {
        "spec_superstep_k": (2, 1),
    }
    for _ in range(2):
        for fr in eng.step():
            out[fr.rid] = fr.tokens
    assert eng.retune(spec_superstep_k=2) == {
        "spec_superstep_k": (1, 2),
    }
    for rid, toks in eng.run().items():
        out[rid] = toks
    for rid, (prompt, new) in zip(rids, reqs):
        assert list(out[rid]) == _oracle(prompt, new), rid
    eng.close()


def test_retained_pages_fractional_for_fanout_shared_pages():
    """Preemption-score input: a fork-shared page retains 1/refcount
    per holder, so summing retained_pages over a fan-out group counts
    every unique page exactly once; 0.0 before admission and after
    retirement."""
    eng = _plain_engine(slots=2)
    assert eng.retained_pages("nope") == 0.0
    r1, r2 = eng.submit_fanout([21, 22, 23, 24, 25, 26], 8, 2)
    eng.step()  # admit + prefill: prompt pages now shared
    a, b = eng.retained_pages(r1), eng.retained_pages(r2)
    assert a > 0 and b > 0
    union = set()
    for seq, table in eng.ctrl.tables.items():
        if (
            isinstance(seq, tuple) and len(seq) == 3
            and seq[0] == "slot" and seq[2] in (r1, r2)
        ):
            union.update(table)
    assert a + b == pytest.approx(len(union))
    # Shared prompt pages count HALF per child: each child retains
    # strictly less than the pages its table lists.
    tables = [
        t for s, t in eng.ctrl.tables.items()
        if isinstance(s, tuple) and len(s) == 3
        and s[0] == "slot" and s[2] == r1
    ]
    assert a < len(tables[0])
    eng.run()
    assert eng.retained_pages(r1) == 0.0
    assert eng.retained_pages(r2) == 0.0
    eng.close()


# ---- scored preemption ---------------------------------------------------


def test_preempt_candidates_order_and_scored_preempt_exact_resume():
    """The ladder's victim scoring: ascending goodput-per-retained-
    page — a dispatched-but-unadmitted rid (0 pages, nothing lost)
    parks first, then the stream delivering the fewest tokens per
    retained page; the scored preempt itself resumes as an EXACT
    continuation."""
    fleet = Fleet(
        [_plain_engine(slots=2)], chip_ids=["chip-0"],
        hang_timeout_s=None,
    )
    # A: long prompt (many retained pages), B: short prompt (few) —
    # comparable emissions, so A scores lower goodput-per-page than B.
    reqs = {
        "A": (list(range(30, 42)), 20),
        "B": ([43, 44], 20),
        "C": ([45, 46, 47], 6),  # third on 2 slots: queued, 0 pages
    }
    rids = {
        k: fleet.submit(p, n, slo_class="bulk")
        for k, (p, n) in reqs.items()
    }
    out = {}
    for _ in range(2):  # prefill + one decode chunk: nothing finished
        for fr in fleet.step():
            out[fr.rid] = list(fr.tokens)
    rep = fleet.replicas[0]
    eng = rep.engine

    def retained(k):
        # Fleet rids map to engine-level requests; retained_pages is
        # keyed by the ENGINE rid (what preempt_candidates passes).
        ereq = rep.rids[rids[k]]
        return eng.retained_pages(getattr(ereq, "rid", rids[k]))

    pages = {k: retained(k) for k in ("A", "B")}
    assert pages["A"] > pages["B"] > 0
    assert retained("C") == 0.0
    cands = fleet.preempt_candidates("bulk")
    assert cands[0] == rids["C"], "the free victim must park first"
    assert cands[1:] == [rids["A"], rids["B"]]
    assert fleet.preempt_candidates("interactive") == []
    # Park the scored head and drain: the preempted stream must come
    # back bit-identical (uncharged continuation), like every other.
    assert fleet.preempt(cands[0])
    for rid, toks in fleet.run().items():
        out[rid] = list(toks)
    for k, (prompt, new) in reqs.items():
        assert out[rids[k]] == _oracle(prompt, new), k
    _assert_no_leaks(fleet)
    fleet.close()


def test_autoscaler_preempt_walks_the_scored_order():
    """FleetAutoscaler._preempt_some consumes Fleet.preempt_candidates
    head-first: with preempt_batch=1 exactly the lowest-scored victim
    parks."""
    fleet = Fleet(
        [_plain_engine(slots=2)], chip_ids=["chip-0"],
        hang_timeout_s=None,
    )

    def factory(slot):
        return _plain_engine()

    asc = FleetAutoscaler(
        fleet, factory, min_replicas=1, max_replicas=1,
        up_backoff=FAST, down_backoff=FAST, preempt_batch=1,
        window_s=0.5,
    )
    reqs = {
        "A": (list(range(50, 62)), 20),
        "B": ([63, 64], 20),
    }
    rids = {
        k: fleet.submit(p, n, slo_class="bulk")
        for k, (p, n) in reqs.items()
    }
    out = {}
    for _ in range(2):
        for fr in fleet.step():
            out[fr.rid] = list(fr.tokens)
    expect = fleet.preempt_candidates("bulk")[0]
    assert asc._preempt_some(0.0) == 1
    assert asc.preemptions_total == 1
    # The scored head was the one parked: it left its replica's rids.
    assert expect not in fleet.replicas[0].rids
    other = [r for r in rids.values() if r != expect][0]
    assert other in fleet.replicas[0].rids
    for rid, toks in fleet.run().items():
        out[rid] = list(toks)
    for k, (prompt, new) in reqs.items():
        assert out[rids[k]] == _oracle(prompt, new), k
    _assert_no_leaks(fleet)
    fleet.close()


# ---- the controller on real fleets ---------------------------------------


def _spike_fleet(n=1, **engine_kw):
    engine_kw.setdefault("spec_breakeven", 2.0)  # slots: always spec
    return Fleet(
        [
            _spec_engine(ledger=ChipTimeLedger(name=str(i)), **engine_kw)
            for i in range(n)
        ],
        chip_ids=[f"chip-{i}" for i in range(n)],
        hang_timeout_s=None,
        ledger=FleetLedger(),
    )


def _spike_reqs(seed, n, new=14):
    rng = np.random.default_rng(seed)
    return [
        (
            [int(t) for t in rng.integers(0, CONFIG.vocab_size, 1 + i % 5)],
            new,
        )
        for i in range(n)
    ]


def test_controller_retunes_away_spec_waste_streams_exact():
    """The tentpole loop on a real fleet: a bad draft at
    always-speculate burns spec_rejected waste, the controller walks
    the breakeven down until speculation stops, and every stream is
    still bit-identical to the dense oracle."""
    fleet = _spike_fleet(1)
    ctrl = _controller(fleet)
    reqs = _spike_reqs(3, 6)
    rids = [ctrl.submit(p, n, slo_class="bulk") for p, n in reqs]
    out = ctrl.run()
    assert ctrl.samples >= 1
    assert ctrl.retunes_applied >= 1, ctrl.states()
    eng = fleet.replicas[0].engine
    assert eng.spec_breakeven < 2.0, "breakeven never walked down"
    assert eng.retunes >= 1
    assert ctrl.spec_rejected_fraction_ewma is not None
    assert any(ev.kind == "retune" for ev in ctrl.events)
    for rid, (prompt, new) in zip(rids, reqs):
        assert list(out[rid]) == _oracle(prompt, new), rid
    _assert_no_leaks(fleet)
    fleet.close()


def test_controller_off_and_inert_streams_identical_to_bare():
    """Inert-by-default pin: the same workload on a bare fleet and on
    a controller-attached fleet with dead-band-everything thresholds
    yields identical streams and zero actuations — attaching the
    controller is free until the signal demands otherwise."""
    def run(controlled):
        fleet = _spike_fleet(1)
        reqs = _spike_reqs(7, 4)
        if controlled:
            ctrl = _controller(
                fleet,
                spec_reject_low=0.0, spec_reject_high=0.999,
                overdecode_low=0.0, overdecode_high=0.999,
                wfq_deadband=1e9,
            )
            rids = [ctrl.submit(p, n) for p, n in reqs]
            out = ctrl.run()
            assert ctrl.retunes_applied == 0
            assert ctrl.wfq_reweights == 0
            assert ctrl.polls > 0 and ctrl.samples > 0
        else:
            ctrl = None
            rids = [fleet.submit(p, n) for p, n in reqs]
            out = fleet.run()
        eng = fleet.replicas[0].engine
        assert eng.retunes == 0
        assert eng.spec_breakeven == 2.0
        streams = [list(out[r]) for r in rids]
        fleet.close()
        return streams

    assert run(controlled=True) == run(controlled=False)


def test_controller_wfq_reweight_boosts_measured_class_on_real_fleet():
    """The WFQ seam end-to-end: interactive finishes clean while bulk
    streams cancel mid-flight (their tokens classify as waste), so
    measured goodput-per-chip-second diverges and the controller
    boosts interactive ABOVE its operator floor without ever dropping
    bulk below its own."""
    fleet = Fleet(
        [_plain_engine(slots=2, ledger=ChipTimeLedger(name="0"))],
        chip_ids=["chip-0"], hang_timeout_s=None,
        ledger=FleetLedger(),
        wfq_weights={"interactive": 1.0, "bulk": 1.0},
    )
    ctrl = _controller(fleet, wfq_deadband=0.1)
    good = [fleet.submit([70 + i], 20, slo_class="interactive")
            for i in range(2)]
    bad = [fleet.submit([80 + i], 20, slo_class="bulk")
           for i in range(2)]
    out = {}
    for _ in range(2):
        for fr in fleet.step():
            out[fr.rid] = list(fr.tokens)
    for rid in bad:
        fleet.cancel(rid)
    for rid, toks in fleet.run().items():
        out[rid] = list(toks)
    ctrl.poll()
    assert ctrl.wfq_reweights >= 1, ctrl.states()
    assert ctrl._wfq_floor == {"interactive": 1.0, "bulk": 1.0}
    assert fleet.wfq_weights["interactive"] > 1.0
    assert fleet.wfq_weights["bulk"] == 1.0
    assert any(ev.kind == "wfq_reweight" for ev in ctrl.events)
    for rid in good:
        prompt = [70 + good.index(rid)]
        assert list(out[rid]) == _oracle(prompt, 20), rid
    fleet.close()


def test_control_check_smoke():
    """``make control-check``: the seeded waste spike — bad-draft
    engines at always-speculate — is retuned away by the controller
    (breakeven walks down, speculation stops) and the measured goodput
    fraction RECOVERS: the post-retune batch's delta fraction beats
    the spike batch's.  Streams stay oracle-exact and nothing leaks."""
    fleet = _spike_fleet(2)
    # spec_reject_low=0.0: converge to no-speculation and STAY — the
    # smoke wants recovery, not the up-move's win-recapture probing.
    ctrl = _controller(fleet, spec_reject_low=0.0)
    led = fleet.ledger

    def run_batch(seed):
        before = (led.tokens_accounted, led.goodput_tokens)
        reqs = _spike_reqs(seed, 8)
        rids = [ctrl.submit(p, n, slo_class="bulk") for p, n in reqs]
        out = ctrl.run()
        for rid, (prompt, new) in zip(rids, reqs):
            assert list(out[rid]) == _oracle(prompt, new), rid
        d_acc = led.tokens_accounted - before[0]
        d_good = led.goodput_tokens - before[1]
        assert d_acc > 0
        return d_good / d_acc

    spike = run_batch(11)
    assert ctrl.retunes_applied >= 1, ctrl.states()
    for rep in fleet.replicas:
        assert rep.engine.spec_breakeven < 2.0, rep.index
    recovered = run_batch(12)
    assert recovered > spike, (spike, recovered, ctrl.states())
    assert recovered > 0.9, recovered  # speculation actually stopped
    assert ctrl.poll_s >= 0.0
    st = ctrl.states()
    assert st["retunes_applied"] == ctrl.retunes_applied
    assert st["decisions"].get("retune", 0) >= 1
    _assert_no_leaks(fleet)
    fleet.close()

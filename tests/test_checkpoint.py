"""Checkpoint/resume: a resumed run continues bit-for-bit where the
uninterrupted run would be, restoring straight onto the sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from workloads.checkpoint import TrainCheckpointer
from workloads.model import ModelConfig
from workloads.train import (
    make_mesh,
    make_train_state,
    make_train_step,
    synthetic_batch,
)

CONFIG = ModelConfig(max_seq_len=16, n_layers=1, dtype=jnp.float32)


def test_restore_resumes_identically(tmp_path):
    mesh = make_mesh(8)
    (params, opt_state), optimizer = make_train_state(CONFIG, mesh)
    step = make_train_step(CONFIG, mesh, optimizer)
    tokens = synthetic_batch(CONFIG, 4)

    # Uninterrupted run: 4 steps, record the losses of steps 3-4.
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    for i in range(2):
        params, opt_state, _ = step(params, opt_state, tokens)
    ckpt.save(2, (params, opt_state))
    ckpt.wait()
    expected = []
    for i in range(2):
        params, opt_state, loss = step(params, opt_state, tokens)
        expected.append(float(loss))

    # "Preempted pod": fresh state, restore, rerun steps 3-4.
    (fresh_params, fresh_opt), _ = make_train_state(CONFIG, mesh, seed=123)
    assert ckpt.latest_step == 2
    restored = ckpt.restore_latest(like=(fresh_params, fresh_opt))
    assert restored is not None
    r_params, r_opt = restored
    # Restored leaves carry the mesh shardings of the donor state.
    assert r_params["embed"].sharding == fresh_params["embed"].sharding
    got = []
    for i in range(2):
        r_params, r_opt, loss = step(r_params, r_opt, tokens)
        got.append(float(loss))
    np.testing.assert_array_equal(np.array(got), np.array(expected))
    ckpt.close()


def test_restore_latest_none_when_empty(tmp_path):
    ckpt = TrainCheckpointer(str(tmp_path / "empty"))
    mesh = make_mesh(8)
    (params, opt_state), _ = make_train_state(CONFIG, mesh)
    assert ckpt.latest_step is None
    assert ckpt.restore_latest(like=(params, opt_state)) is None
    ckpt.close()


def test_max_to_keep_prunes_old_steps(tmp_path):
    mesh = make_mesh(8)
    (params, opt_state), _ = make_train_state(CONFIG, mesh)
    ckpt = TrainCheckpointer(str(tmp_path / "keep"), max_to_keep=2)
    for s in (1, 2, 3):
        ckpt.save(s, (params, opt_state))
        ckpt.wait()
    assert ckpt.latest_step == 3
    steps = ckpt._manager.all_steps()
    assert list(sorted(steps)) == [2, 3]
    ckpt.close()


def run_train_cli(extra_args, timeout=300):
    """Launch `python -m workloads.train` with the common tiny-model flags;
    shared by every CLI behavior test below."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [
        sys.executable, "-m", "workloads.train",
        "--batch-size", "2", "--seq-len", "16", "--layers", "1",
        *extra_args,
    ]
    return subprocess.run(
        cmd, capture_output=True, text=True, cwd=repo, env=env, timeout=timeout
    )


def test_train_cli_resumes_from_checkpoint(tmp_path):
    """The pod-facing entry (`python -m workloads.train`) checkpoints and
    resumes across process restarts."""

    def args(steps):
        return [
            "--steps", str(steps),
            "--checkpoint-dir", str(tmp_path / "ckpt"), "--checkpoint-every", "3",
        ]

    first = run_train_cli(args(3))
    assert first.returncode == 0, first.stderr
    assert "resumed" not in first.stdout

    second = run_train_cli(args(6))
    assert second.returncode == 0, second.stderr
    assert "resumed from checkpoint step 3" in second.stdout
    assert "done: steps=6" in second.stdout


def test_train_cli_profile_dir_writes_trace(tmp_path):
    import os

    out = run_train_cli(["--steps", "2", "--profile-dir", str(tmp_path / "trace")])
    assert out.returncode == 0, out.stderr
    assert "profile trace written" in out.stdout
    # jax writes <dir>/plugins/profile/<ts>/*.trace.json.gz (or .xplane.pb).
    found = []
    for root, _, files in os.walk(tmp_path / "trace"):
        found += [f for f in files if "trace" in f or f.endswith(".pb")]
    assert found, "no trace artifacts written"


def test_gqa_tree_roundtrips(tmp_path):
    """The grouped-query parameter tree (split wq/wkv leaves) saves and
    restores onto the sharded mesh like the fused MHA tree."""
    gqa = ModelConfig(
        max_seq_len=16, n_layers=1, n_heads=8, n_kv_heads=4,
        dtype=jnp.float32,
    )
    mesh = make_mesh(8)  # model_parallel=4 divides the 4 kv heads
    (params, opt_state), optimizer = make_train_state(gqa, mesh)
    ckpt = TrainCheckpointer(str(tmp_path / "gqa"))
    ckpt.save(1, (params, opt_state))
    ckpt.wait()
    abstract, _ = make_train_state(gqa, mesh, abstract=True)
    restored = ckpt.restore_latest(like=abstract)
    r_params, _ = restored
    np.testing.assert_array_equal(
        np.asarray(r_params["layers"][0]["wkv"]),
        np.asarray(params["layers"][0]["wkv"]),
    )
    ckpt.close()

"""Budgeted chunked-prefill / decode interleaving (workloads/serve.py
``prefill_budget``): admission becomes RESUMABLE — each step dispatches
at most the budget's worth of prompt-bucket prefill chunks and carries
partially-prefilled admissions across steps — with greedy token streams
BIT-IDENTICAL to run-to-completion admission across serial / batched /
pipelined / spec="auto" engines, and no page/slot/commitment leak after
a mid-prefill cancel, deadline, fault replay, health pause, or close."""

import queue
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft_params():
    return init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))


def _mixed_requests(n, rng_seed=7, p_lo=2, p_hi=31):
    """Mixed prompt lengths, long multi-chunk prompts included — the
    head-of-line-blocking shape the budget exists to defuse."""
    rng = np.random.default_rng(rng_seed)
    out = []
    for _ in range(n):
        plen = int(rng.integers(p_lo, p_hi))
        new = int(rng.integers(2, 13))
        out.append(([int(t) for t in rng.integers(
            0, CONFIG.vocab_size, plen)], new))
    return out


def _hygiene(engine):
    """No slot, page, commitment, group, or in-flight-prefill leak;
    only prefix-cache pins may remain."""
    assert not engine._occupied.any()
    assert engine._committed_pages == 0
    assert not engine._inflight_prefill
    assert not engine._groups
    pinned = engine.prefix.cached_pages if engine.prefix is not None else 0
    assert engine.ctrl.used_pages == pinned


def _serve(params, requests, budget, **kw):
    engine = ServeEngine(
        params, CONFIG, slots=kw.pop("slots", 2), page_size=4,
        prompt_bucket=8, prefill_budget=budget, **kw,
    )
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()
    _hygiene(engine)
    return {r: served[r] for r in rids}, engine


# ---- parity pins: budget on/off is bit-identical ------------------------


@pytest.mark.parametrize("engine_kw", [
    {},                                      # batched (default)
    {"batched_admission": False},            # serial reference
    {"pipelined": True},
    {"pipelined": True, "prefix_cache": True},
], ids=["batched", "serial", "pipelined", "pipelined-prefix"])
def test_budget_streams_bit_identical(params, engine_kw):
    """The core pin: with a prefill_budget set, greedy streams are
    bit-identical to prefill_budget=None — chunked prefill is per-row
    math, so WHEN a chunk dispatches cannot change WHAT it computes.
    (A budget always routes through the plan/sweep machinery, including
    under batched_admission=False: the serial one-dispatch-per-admission
    path cannot park a half-prefilled prompt.)"""
    requests = _mixed_requests(6, rng_seed=3)
    base, _ = _serve(params, requests, None, **engine_kw)
    for budget in (8, 16, 1):
        got, engine = _serve(params, requests, budget, **engine_kw)
        assert got == base, (engine_kw, budget)
    # The smallest budget genuinely parked work across steps.
    _, engine = _serve(params, requests, 8, **engine_kw)
    assert engine.prefill_deferred_tokens > 0


def test_budget_streams_bit_identical_spec_auto(params, draft_params):
    """Budgeted admission composes with adaptive speculation: the
    spec="auto" engine sees budget-deferred admissions (occupancy climbs
    as parked rows finish), and greedy streams stay pinned."""
    requests = _mixed_requests(5, rng_seed=11)
    kw = dict(
        draft_params=draft_params, draft_config=DRAFT_CONFIG, gamma=3,
        spec="auto", spec_breakeven=1.0, pipelined=True,
    )
    base, _ = _serve(params, requests, None, **kw)
    got, engine = _serve(params, requests, 8, **kw)
    assert got == base
    # The draft pools swept the same remainder: spec rounds ran after
    # budget-parked admissions finished.
    assert engine.spec_rounds > 0


def test_budget_fanout_streams_bit_identical(params):
    """Fan-out groups sweep the same budgeted remainder: the first
    member prefills across steps, siblings wait for its logits row to
    resolve, tail pages copy at finish — tokens pinned against the
    unbudgeted group path."""
    rng = np.random.default_rng(5)
    long_prompt = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 27)]
    short = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 5)]

    def run(budget):
        engine = ServeEngine(
            params, CONFIG, slots=3, page_size=4, prompt_bucket=8,
            prefill_budget=budget, pipelined=True,
        )
        rids = engine.submit_fanout(long_prompt, 6, n_samples=3)
        rids.append(engine.submit(short, 5))
        served = engine.run()
        _hygiene(engine)
        return [served[r] for r in rids]

    assert run(None) == run(8)


def test_budget_sampled_streams_structurally_sound(params):
    """Sampled streams under a budget have no bitwise oracle (the
    engine key schedule legitimately differs when finishes cross step
    boundaries) but every request still gets exactly its token budget,
    in-vocab, with clean teardown."""
    requests = _mixed_requests(5, rng_seed=2)
    got, _ = _serve(
        params, requests, 8, temperature=0.8, top_k=40,
        rng=jax.random.PRNGKey(5), pipelined=True,
    )
    for (prompt, new), (rid, toks) in zip(requests, got.items()):
        assert len(toks) == new
        assert all(0 <= t < CONFIG.vocab_size for t in toks)


# ---- budget accounting --------------------------------------------------


def test_budget_bounds_chunk_dispatches_per_step(params):
    """<= max(1, budget // prompt_bucket) prefill chunk dispatches per
    step, however much prefill work is queued — the stall-free
    contract's mechanical half."""
    rng = np.random.default_rng(9)
    long = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 30)]
    for budget, per_step in ((8, 1), (16, 2), (1, 1)):
        engine = ServeEngine(
            params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
            prefill_budget=budget,
        )
        for _ in range(2):
            engine.submit(long, 4)
        while not engine.idle:
            pd0 = engine.prefill_dispatches
            engine.step()
            assert engine.prefill_dispatches - pd0 <= per_step, budget
        _hygiene(engine)


def test_budget_interleaves_decode_with_parked_prefill(params):
    """The stall-free contract's point: while a long admission sits
    parked mid-prefill, occupied slots keep DECODING — the unbudgeted
    engine would run the whole multi-chunk sweep before the decode chunk
    dispatches."""
    rng = np.random.default_rng(4)
    long = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 30)]
    short = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 3)]
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        prefill_budget=8,
    )
    engine.submit(short, 20)
    engine.step()  # the short prompt occupies a slot and decodes
    engine.submit(long, 4)
    interleaved = 0
    while not engine.idle:
        ch0 = engine.chunks_run
        engine.step()
        if engine._inflight_prefill and engine.chunks_run > ch0:
            interleaved += 1  # a decode chunk ran with prefill parked
    assert interleaved > 0
    _hygiene(engine)
    assert engine.prefill_deferred_tokens > 0


def test_budget_deferred_tokens_counter(params):
    """prefill_deferred_tokens counts the prompt tokens each step's
    budget parked; an unbudgeted engine never moves it."""
    rng = np.random.default_rng(6)
    long = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 30)]
    _, budgeted = _serve(params, [(long, 3)], 8)
    assert budgeted.prefill_deferred_tokens > 0
    _, unbudgeted = _serve(params, [(long, 3)], None)
    assert unbudgeted.prefill_deferred_tokens == 0


def test_budget_validation():
    with pytest.raises(ValueError, match="prefill_budget"):
        ServeEngine(
            init_params(CONFIG, jax.random.PRNGKey(0)), CONFIG,
            slots=1, prefill_budget=0,
        )


# ---- mid-prefill lifecycle: no leaks ------------------------------------


def _park_one(params, **kw):
    """An engine with one long admission parked mid-prefill."""
    rng = np.random.default_rng(8)
    long = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 30)]
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        prefill_budget=8, **kw,
    )
    rid = engine.submit(long, 6)
    engine.step()
    assert engine._inflight_prefill
    return engine, rid, long


def test_duplicate_rid_rejected_while_parked(params):
    """A rid parked in _inflight_prefill is still in flight: resubmitting
    it must raise instead of silently overwriting the original's tokens
    in run()'s {rid: tokens} result."""
    from workloads.errors import InvalidRequest

    engine, rid, long = _park_one(params)
    with pytest.raises(InvalidRequest, match="already in flight"):
        engine.submit(long, 2, rid=rid)
    engine.run()
    _hygiene(engine)


def test_cancel_mid_prefill_reclaims(params):
    engine, rid, long = _park_one(params)
    assert engine.cancel(rid)
    assert not engine._inflight_prefill
    engine.run()
    _hygiene(engine)
    statuses = {r.rid: r.status for r in engine.completed}
    assert statuses[rid] == "cancelled"


def test_cancel_mid_prefill_fanout_requeues_siblings_solo(params):
    """Cancelling one mid-prefill fan-out member cannot leave the group
    half-alive: in-flight siblings abort and requeue as solo replays
    (no retry charge), and their streams still match the solo oracle."""
    rng = np.random.default_rng(12)
    long = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 27)]
    engine = ServeEngine(
        params, CONFIG, slots=3, page_size=4, prompt_bucket=8,
        prefill_budget=8,
    )
    rids = engine.submit_fanout(long, 5, n_samples=2)
    engine.step()
    assert engine._inflight_prefill
    assert engine.cancel(rids[0])
    served = engine.run()
    _hygiene(engine)
    statuses = {r.rid: r.status for r in engine.completed}
    assert statuses[rids[0]] == "cancelled"
    assert statuses[rids[1]] == "ok"
    retried = {r.rid: r.retries for r in engine.completed}
    assert retried[rids[1]] == 0  # requeue, not a retry charge
    solo, _ = _serve(params, [(long, 5)], None)
    assert served[rids[1]] == next(iter(solo.values()))


def test_deadline_mid_prefill_expires(params):
    rng = np.random.default_rng(13)
    long = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 30)]
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        prefill_budget=8,
    )
    rid = engine.submit(long, 6, deadline_s=0.001)
    engine.step()
    time.sleep(0.01)
    engine.run()
    _hygiene(engine)
    statuses = {r.rid: r.status for r in engine.completed}
    assert statuses[rid] == "expired"


def test_fault_mid_prefill_replays_bit_identical(params):
    """A dispatch fault with admissions parked mid-prefill quarantines
    them (pages dropped, commitment rolled back) and replays under the
    retry budget — finished streams bit-identical to the fault-free
    run."""
    from workloads.faults import FaultInjector

    requests = _mixed_requests(4, rng_seed=14)
    base, _ = _serve(params, requests, None)
    injector = FaultInjector(schedule={"prefill_dispatch": [2]})
    got, engine = _serve(
        params, requests, 8, fault_injector=injector, max_retries=2,
    )
    assert engine.steps_quarantined >= 1
    assert got == base


def test_fault_mid_prefill_exhausted_retries_fail_terminally(params):
    """The retry budget still bounds budgeted replays: a seam that
    fires every prefill dispatch drives each parked admission to the
    `failed` terminal status with everything reclaimed."""
    from workloads.faults import FaultInjector

    rng = np.random.default_rng(15)
    long = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 30)]
    injector = FaultInjector(
        schedule={"prefill_dispatch": list(range(1, 50))}
    )
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        prefill_budget=8, fault_injector=injector, max_retries=1,
    )
    rid = engine.submit(long, 6)
    engine.run()
    _hygiene(engine)
    statuses = {r.rid: r.status for r in engine.completed}
    assert statuses[rid] == "failed"


def test_health_pause_requeues_mid_prefill_without_charge(params):
    """An Unhealthy chip with admissions parked mid-prefill requeues
    them (no retry-budget charge), holds admission while paused, and
    replays to the bit-identical stream on recovery."""
    from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
    from tpu_device_plugin.device import HealthEvent

    q = queue.Queue()
    engine, rid, long = _park_one(params, health_events=q)
    q.put(HealthEvent(chip_id="chip-0", health=UNHEALTHY, code=2))
    engine.step()
    assert engine.paused
    assert not engine._inflight_prefill  # parked row requeued
    assert engine.pending and engine.pending[0].rid == rid
    assert engine.pending[0].retries == 0  # no retry-budget charge
    engine.step()
    assert not engine._inflight_prefill  # held: no admission
    q.put(HealthEvent(chip_id="chip-0", health=HEALTHY, code=2))
    served = engine.run()
    _hygiene(engine)
    base, _ = _serve(params, [(long, 6)], None)
    assert served[rid] == next(iter(base.values()))


def test_close_mid_prefill_reclaims(params):
    engine, rid, _ = _park_one(params)
    engine.close()
    _hygiene(engine)
    statuses = {r.rid: r.status for r in engine.completed}
    assert statuses[rid] == "failed"


# ---- prefix-cache composition -------------------------------------------


def test_budget_defers_prefix_insert_until_pages_written(params):
    """The budgeted path defers prefix-cache inserts to admission
    finish: a lookup landing while the writer is still parked
    mid-prefill must MISS (a promissory entry could serve half-written
    pages across steps), and a lookup after the writer finished must
    HIT with bit-identical tokens."""
    rng = np.random.default_rng(16)
    prefix = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 24)]
    tail = [int(t) for t in rng.integers(0, CONFIG.vocab_size, 4)]
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        prefill_budget=8, prefix_cache=True,
    )
    r1 = engine.submit(prefix + tail, 4)
    engine.step()
    assert engine._inflight_prefill
    # While r1 sits parked, its prompt must not be adoptable.
    assert engine.prefix.lookup(prefix + tail, 6, granularity=2) == []
    served = engine.run()
    _hygiene(engine)
    # After finish the insert landed: a repeat admission hits the cache
    # and the stream stays pinned against the uncached oracle.
    r2 = engine.submit(prefix + tail, 4)
    served2 = engine.run()
    assert engine.prefix.hits >= 1
    base, _ = _serve(params, [(prefix + tail, 4)], None)
    assert served[r1] == served2[r2] == next(iter(base.values()))

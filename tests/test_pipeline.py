"""Pipeline parallelism (GPipe schedule over shard_map/ppermute) vs the
sequential flagship model, 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.model import ModelConfig, loss_fn
from workloads.pipeline import (
    init_pipeline_params,
    make_pipeline_train_state,
    make_pipeline_train_step,
    make_pp_mesh,
    pipeline_loss_fn,
    pipeline_param_specs,
)

CONFIG = ModelConfig(max_seq_len=17, n_layers=4, dtype=jnp.float32)


def unstack_to_sequential(params, config):
    """[S, L/S, ...] stage leaves -> the flagship's flat layer list."""
    stages = params["stages"]
    n_stages = jax.tree.leaves(stages)[0].shape[0]
    per_stage = jax.tree.leaves(stages)[0].shape[1]
    layers = []
    for s in range(n_stages):
        for l in range(per_stage):
            layers.append(jax.tree.map(lambda leaf: leaf[s, l], stages))
    return {"embed": params["embed"], "unembed": params["unembed"], "layers": layers}


@pytest.fixture
def pp_mesh():
    return make_pp_mesh(8, pipe_parallel=4)  # data=2, pipe=4


def test_pipeline_loss_matches_sequential(pp_mesh):
    params = init_pipeline_params(CONFIG, 4, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, CONFIG.max_seq_len), 0, CONFIG.vocab_size,
        jnp.int32,
    )
    got = float(pipeline_loss_fn(params, tokens, CONFIG, pp_mesh, n_microbatches=4))
    expected = float(loss_fn(unstack_to_sequential(params, CONFIG), tokens, CONFIG))
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_pipeline_gradients_match_sequential(pp_mesh):
    params = init_pipeline_params(CONFIG, 4, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, CONFIG.max_seq_len), 0, CONFIG.vocab_size,
        jnp.int32,
    )
    got = jax.grad(
        lambda p: pipeline_loss_fn(p, tokens, CONFIG, pp_mesh, n_microbatches=2)
    )(params)
    ref = jax.grad(
        lambda p: loss_fn(p, tokens, CONFIG)
    )(unstack_to_sequential(params, CONFIG))

    np.testing.assert_allclose(
        np.asarray(got["embed"]), np.asarray(ref["embed"]), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(got["unembed"]), np.asarray(ref["unembed"]), atol=1e-5
    )
    # Spot-check one leaf of the first and last pipeline stages.
    np.testing.assert_allclose(
        np.asarray(got["stages"]["wqkv"][0, 0]),
        np.asarray(ref["layers"][0]["wqkv"]),
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(got["stages"]["w_down"][3, 0]),
        np.asarray(ref["layers"][3]["w_down"]),
        atol=1e-5,
    )


def test_pipeline_train_step_dp_pp(pp_mesh):
    (params, opt_state), optimizer = make_pipeline_train_state(CONFIG, pp_mesh)
    step = make_pipeline_train_step(CONFIG, pp_mesh, optimizer, n_microbatches=4)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (8, CONFIG.max_seq_len), 0, CONFIG.vocab_size,
        jnp.int32,
    )
    params, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    _, _, loss2 = step(params, opt_state, tokens)
    assert float(loss2) < float(loss)  # actually learns on a repeated batch


def test_pipeline_param_sharding_lands_on_pipe(pp_mesh):
    (params, _), _ = make_pipeline_train_state(CONFIG, pp_mesh)
    spec = params["stages"]["wqkv"].sharding.spec
    assert spec[0] == "pipe"
    assert pipeline_param_specs(CONFIG)["stages"]["wqkv"] == jax.sharding.PartitionSpec(
        "pipe"
    )


def test_pipeline_rejects_bad_shapes():
    with pytest.raises(ValueError, match="divide"):
        init_pipeline_params(ModelConfig(n_layers=3), 2, jax.random.PRNGKey(0))
    mesh = make_pp_mesh(8, pipe_parallel=2)
    params = init_pipeline_params(CONFIG, 2, jax.random.PRNGKey(0))
    tokens = jnp.zeros((6, CONFIG.max_seq_len), jnp.int32)
    with pytest.raises(ValueError, match="n_microbatches"):
        pipeline_loss_fn(params, tokens, CONFIG, mesh, n_microbatches=4)


def test_pipeline_specs_follow_gqa_tree():
    import jax.numpy as jnp

    from workloads.model import ModelConfig
    from workloads.pipeline import pipeline_param_specs

    gqa = ModelConfig(n_heads=4, n_kv_heads=2, dtype=jnp.float32)
    specs = pipeline_param_specs(gqa)["stages"]
    assert "wqkv" not in specs and {"wq", "wkv"} <= set(specs)

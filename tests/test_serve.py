"""Serving loop (workloads/serve.py): paged greedy decode matches
generate(), pages recycle across batches, CLI entry."""

import jax
import jax.numpy as jnp
import numpy as np

from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.paged import PagePool, init_page_pool_array
from workloads.serve import serve_batch

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)


def test_paged_serve_matches_generate_greedy():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    prompts = jax.random.randint(
        jax.random.PRNGKey(1), (2, 8), 0, CONFIG.vocab_size, jnp.int32
    )
    ctrl = PagePool(n_pages=32, page_size=4)
    pool = init_page_pool_array(CONFIG, 32, 4)
    got, pool = serve_batch(params, CONFIG, prompts, 10, ctrl, pool)
    want = generate(params, prompts, CONFIG, max_new_tokens=10)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert ctrl.used_pages == 0  # the batch retired its pages


def test_pages_recycle_across_batches():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    ctrl = PagePool(n_pages=12, page_size=4)
    pool = init_page_pool_array(CONFIG, 12, 4)
    for seed in range(3):  # 3 batches through a pool sized for ~one
        prompts = jax.random.randint(
            jax.random.PRNGKey(seed), (2, 8), 0, CONFIG.vocab_size, jnp.int32
        )
        out, pool = serve_batch(params, CONFIG, prompts, 8, ctrl, pool)
        assert out.shape == (2, 8)
        assert ctrl.used_pages == 0


def test_cli_entry():
    from workloads.serve import main

    assert main([
        "--requests", "3", "--batch", "2", "--prompt-len", "8",
        "--max-new-tokens", "4", "--temperature", "0.8",
    ]) == 0
    assert main([
        "--requests", "2", "--batch", "2", "--prompt-len", "8",
        "--max-new-tokens", "4", "--int8", "--kv-heads", "4",
    ]) == 0

"""Serving engine (workloads/serve.py): continuous batching matches
generate(), beats lockstep on mixed-length streams, never recompiles
mid-stream, recycles pages; lockstep baseline parity; CLI entry."""

import jax
import jax.numpy as jnp
import numpy as np

from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.paged import PagePool, init_page_pools, paged_decode_chunk
from workloads.serve import ServeEngine, serve_batch

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)


def test_paged_serve_matches_generate_greedy():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    prompts = jax.random.randint(
        jax.random.PRNGKey(1), (2, 8), 0, CONFIG.vocab_size, jnp.int32
    )
    ctrl = PagePool(n_pages=32, page_size=4)
    pools = init_page_pools(CONFIG, 32, 4)
    got, pools = serve_batch(params, CONFIG, prompts, 10, ctrl, pools)
    want = generate(params, prompts, CONFIG, max_new_tokens=10)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert ctrl.used_pages == 0  # the batch retired its pages


def test_pages_recycle_across_batches():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    ctrl = PagePool(n_pages=12, page_size=4)
    pools = init_page_pools(CONFIG, 12, 4)
    for seed in range(3):  # 3 batches through a pool sized for ~one
        prompts = jax.random.randint(
            jax.random.PRNGKey(seed), (2, 8), 0, CONFIG.vocab_size, jnp.int32
        )
        out, pools = serve_batch(params, CONFIG, prompts, 8, ctrl, pools)
        assert out.shape == (2, 8)
        assert ctrl.used_pages == 0


def _mixed_requests(n, vocab, rng_seed=7):
    """A mixed-length stream: prompts 3..10 tokens, generations 2..24."""
    rng = np.random.default_rng(rng_seed)
    out = []
    for _ in range(n):
        plen = int(rng.integers(3, 11))
        new = int(rng.integers(2, 25))
        out.append((list(rng.integers(0, vocab, plen)), new))
    return out


def test_engine_greedy_matches_generate():
    """Every request served through the continuous-batching engine gets
    exactly the tokens generate() produces for it alone — admission
    order, slot turnover and chunk overshoot change nothing."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=12, chunk=4
    )
    requests = _mixed_requests(5, CONFIG.vocab_size)
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()
    assert set(served) == set(rids)
    for rid, (prompt, new) in zip(rids, requests):
        want = generate(
            params, jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )
        np.testing.assert_array_equal(
            np.asarray(served[rid]), np.asarray(want[0]),
            err_msg=f"{rid} (prompt {len(prompt)}, new {new})",
        )
    assert engine.ctrl.used_pages == 0  # all pages recycled


def test_engine_eos_retires_early():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=1, page_size=4, prompt_bucket=8, chunk=4
    )
    prompt = [1, 2, 3]
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=20
    )
    eos = int(np.asarray(want[0, 2]))  # the 3rd token it will emit
    rid = engine.submit(prompt, 20, eos_token=eos)
    served = engine.run()
    assert served[rid][-1] == eos
    assert len(served[rid]) <= 3 + engine.chunk  # stopped near the eos
    assert engine.ctrl.used_pages == 0


def test_continuous_beats_lockstep_on_mixed_stream():
    """The scheduling win, pinned deterministically: a mixed-length
    stream needs fewer decode steps under slot turnover than under
    lockstep admission batches (each lockstep batch runs to its longest
    member while finished rows idle)."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    slots = 2
    requests = [(list(range(3, 8)), n) for n in (2, 24, 2, 24, 2, 24)]
    engine = ServeEngine(
        params, CONFIG, slots=slots, page_size=4, prompt_bucket=8, chunk=4
    )
    for p, n in requests:
        engine.submit(p, n)
    engine.run()
    engine_steps = engine.chunks_run * engine.chunk

    # Lockstep: groups of ``slots`` in arrival order; each group costs
    # max(max_new) - 1 decode steps after its prefill (which emits the
    # first token), finished rows riding along until the group drains.
    lockstep_steps = 0
    for i in range(0, len(requests), slots):
        group = requests[i : i + slots]
        lockstep_steps += max(n for _, n in group) - 1
    assert engine_steps < lockstep_steps, (
        f"continuous batching took {engine_steps} decode steps, "
        f"lockstep {lockstep_steps}"
    )


def test_engine_never_recompiles_mid_stream():
    """Admission, retirement and occupancy churn are data, not shape: the
    chunk program compiles exactly once for the whole mixed stream."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4
    )
    before = paged_decode_chunk._cache_size()
    for p, n in _mixed_requests(6, CONFIG.vocab_size, rng_seed=11):
        engine.submit(p[:8], n)
    engine.run()
    assert paged_decode_chunk._cache_size() - before <= 1


def test_engine_sampling_stream_runs():
    """Temperature/top-k/top-p serving drains a stream (values are
    random; the pin is that sampling composes with the engine)."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4,
        temperature=0.8, top_k=20, top_p=0.9, rng=jax.random.PRNGKey(3),
    )
    rids = [engine.submit([1, 2, 3], 6) for _ in range(3)]
    served = engine.run()
    assert set(served) == set(rids)
    for rid in rids:
        assert len(served[rid]) == 6
        assert all(0 <= t < CONFIG.vocab_size for t in served[rid])


def test_chunked_prefill_serves_long_prompts():
    """Prompts longer than the prefill bucket are admitted via
    page-aligned chunked prefill and still emit exactly generate()'s
    tokens; the bucket remains the compile-shape bound."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4
    )
    rng = np.random.default_rng(13)
    requests = []
    for plen in (9, 23, 37, 8):  # 2, 3, 5 chunks and the 1-chunk path
        prompt = list(rng.integers(0, CONFIG.vocab_size, plen))
        requests.append((prompt, int(rng.integers(2, 12))))
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()
    for rid, (prompt, new) in zip(rids, requests):
        want = generate(
            params, jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )
        np.testing.assert_array_equal(
            np.asarray(served[rid]), np.asarray(want[0]),
            err_msg=f"{rid} (prompt {len(prompt)})",
        )
    assert engine.ctrl.used_pages == 0


def test_submit_rejects_past_context():
    import pytest

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=1, page_size=4, prompt_bucket=8, chunk=4
    )
    engine.submit(list(range(CONFIG.max_seq_len - 1)), 1)  # at the cap
    with pytest.raises(ValueError, match="prompt length"):
        engine.submit(list(range(CONFIG.max_seq_len)), 1)


def test_engine_backpressure_defers_admission():
    """A pool too small for every slot at once serializes admissions
    instead of dying mid-stream: allocate/extend can never raise because
    admission commits worst-case pages up front."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4,
        # Room for exactly one worst-case request (prompt 8 + 24 new +
        # chunk overshoot = 8 + 24 pages/4 -> 8 pages).
        n_pages=8,
    )
    requests = [(list(range(1, 8)), 20) for _ in range(3)]
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()  # must drain without RuntimeError
    assert set(served) == set(rids)
    for rid, (prompt, new) in zip(rids, requests):
        want = generate(
            params, jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )
        np.testing.assert_array_equal(np.asarray(served[rid]), np.asarray(want[0]))
    assert engine.ctrl.used_pages == 0


def test_engine_rejects_never_admittable_request():
    import pytest

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=1, page_size=4, prompt_bucket=8, chunk=4,
        n_pages=4,
    )
    with pytest.raises(ValueError, match="never be admitted"):
        engine.submit(list(range(1, 8)), 30)


def test_fanout_shares_prompt_pages_and_matches_greedy():
    """submit_fanout: N samples of one prompt hold its full prompt pages
    ONCE (refcounted fork), each member still emits exactly generate()'s
    greedy tokens, and everything releases at drain."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=3, page_size=4, prompt_bucket=12, chunk=4
    )
    prompt = list(range(2, 12))  # 10 tokens: 2 full shared pages + tail
    rids = engine.submit_fanout(prompt, 6, n_samples=3)
    # Admit all three members, then check the sharing arithmetic while
    # they are live: 2 shared pages + 3 private tail pages + decode pages.
    finished = engine.step()
    assert not finished
    full = engine.ctrl.pages_needed(len(prompt))  # 3 pages unshared
    independent_first_chunk = 3 * engine.ctrl.pages_needed(len(prompt) + 4)
    shared_prefix_pages = len(prompt) // 4  # 2
    # Sharing must be VISIBLE in the accounting: 2 shared + 3x(own tail
    # + first decode page) = 8 < the 12 unshared allocation would hold.
    assert engine.ctrl.used_pages == shared_prefix_pages + 3 * 2
    assert engine.ctrl.used_pages < 3 * full  # a fortiori < unshared
    assert engine.ctrl.used_pages < independent_first_chunk
    # The shared pages are refcounted, not duplicated: the three tables
    # start with the same physical pages.
    tables = [
        engine.ctrl.tables[engine._seq_id(s, engine._slot_req[s])]
        for s in range(3)
    ]
    for t in tables[1:]:
        assert t[:shared_prefix_pages] == tables[0][:shared_prefix_pages]
        assert t[shared_prefix_pages] != tables[0][shared_prefix_pages]

    served = engine.run()
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=6
    )
    for rid in rids:
        np.testing.assert_array_equal(np.asarray(served[rid]), np.asarray(want[0]))
    assert engine.ctrl.used_pages == 0
    # Shared COMPUTE too: one prefill served all three members (siblings
    # copy the retained tail page and reuse the cached logits).
    assert engine.prefills_run == 1


def test_fanout_sampling_diverges():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=4, page_size=4, prompt_bucket=8, chunk=4,
        temperature=1.0, rng=jax.random.PRNGKey(9),
    )
    rids = engine.submit_fanout([1, 2, 3, 4, 5], 8, n_samples=4)
    served = engine.run()
    assert len({tuple(served[r]) for r in rids}) >= 2  # samples diverge
    assert engine.ctrl.used_pages == 0


def test_fanout_short_prompt_degrades_to_independent():
    """A prompt shorter than one page has nothing shareable; the fan-out
    still serves correctly."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=8, prompt_bucket=8, chunk=8
    )
    rids = engine.submit_fanout([1, 2, 3], 5, n_samples=2)
    served = engine.run()
    want = generate(
        params, jnp.asarray([[1, 2, 3]], jnp.int32), CONFIG, max_new_tokens=5
    )
    for rid in rids:
        np.testing.assert_array_equal(np.asarray(served[rid]), np.asarray(want[0]))
    assert engine.ctrl.used_pages == 0


def test_pipelined_engine_matches_generate():
    """pipelined=True overlaps each chunk's readback with the next
    chunk's compute; emission lags one chunk but every request's tokens
    are identical — pinned against generate() over a mixed stream with
    slot turnover."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=12, chunk=4,
        pipelined=True,
    )
    requests = _mixed_requests(6, CONFIG.vocab_size, rng_seed=23)
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()
    assert set(served) == set(rids)
    for rid, (prompt, new) in zip(rids, requests):
        want = generate(
            params, jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )
        np.testing.assert_array_equal(
            np.asarray(served[rid]), np.asarray(want[0]),
            err_msg=f"{rid} (prompt {len(prompt)}, new {new})",
        )
    assert engine.ctrl.used_pages == 0
    assert engine._pending_read is None  # fully drained


def test_pipelined_engine_eos_and_fanout():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4,
        pipelined=True,
    )
    prompt = [1, 2, 3]
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=20
    )
    eos = int(np.asarray(want[0, 2]))
    rid = engine.submit(prompt, 20, eos_token=eos)
    fan = engine.submit_fanout([4, 5, 6, 7], 6, n_samples=2)
    served = engine.run()
    assert served[rid][-1] == eos and len(served[rid]) <= 3 + 2 * engine.chunk
    fan_want = generate(
        params, jnp.asarray([[4, 5, 6, 7]], jnp.int32), CONFIG,
        max_new_tokens=6,
    )
    for r in fan:
        np.testing.assert_array_equal(np.asarray(served[r]), np.asarray(fan_want[0]))
    assert engine.ctrl.used_pages == 0


def test_pipelined_engine_full_length_request():
    """A request using the FULL context window (prompt + max_new ==
    max_seq_len, with (max_new-1) % chunk == 1 so the dead pipelined
    chunk lands at the window edge) must serve without exhausting the
    page pool: per-dispatch extension is one chunk past the position,
    and only the admission commitment carries the 2-chunk pipelined
    overshoot.  Regression test for the page-budget invariant (a valid
    request that passed submit() must never crash mid-stream)."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=1, page_size=16, prompt_bucket=16, chunk=16,
        pipelined=True,
    )
    prompt = list(range(1, 15))  # 14 + 50 == max_seq_len == 64
    rid = engine.submit(prompt, 50)
    served = engine.run()
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=50
    )
    np.testing.assert_array_equal(np.asarray(served[rid]), np.asarray(want[0]))
    assert engine.ctrl.used_pages == 0


DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)


def test_speculative_engine_matches_generate():
    """Batched speculative serving: a draft model proposes per row, the
    target verifies every row's block in one forward, rows commit
    DIFFERENT accepted lengths — and each request still emits exactly
    the target's greedy tokens."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4,
        draft_params=draft, draft_config=DRAFT_CONFIG, gamma=3,
    )
    requests = _mixed_requests(5, CONFIG.vocab_size, rng_seed=17)
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()
    for rid, (prompt, new) in zip(rids, requests):
        want = generate(
            params, jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )
        np.testing.assert_array_equal(
            np.asarray(served[rid]), np.asarray(want[0]),
            err_msg=f"{rid} (prompt {len(prompt)}, new {new})",
        )
    assert engine.ctrl.used_pages == 0
    assert engine.spec_rounds > 0


def test_speculative_engine_self_draft_accepts_blocks():
    """With the target as its own draft, acceptance approaches 100% and
    the round count collapses toward tokens/(gamma+1) — the speculative
    speedup lever, observable in the engine's telemetry."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    gamma = 4
    engine = ServeEngine(
        params, CONFIG, slots=1, page_size=4, prompt_bucket=8,
        draft_params=params, draft_config=CONFIG, gamma=gamma,
    )
    new = 24
    rid = engine.submit([1, 2, 3, 4], new)
    served = engine.run()
    want = generate(
        params, jnp.asarray([[1, 2, 3, 4]], jnp.int32), CONFIG,
        max_new_tokens=new,
    )
    np.testing.assert_array_equal(np.asarray(served[rid]), np.asarray(want[0]))
    # Perfect self-agreement: ceil((new-1)/(gamma+1)) rounds, not new-1.
    assert engine.spec_rounds <= -(-(new - 1) // (gamma + 1)) + 1


def test_speculative_engine_fanout():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        draft_params=draft, draft_config=DRAFT_CONFIG, gamma=3,
    )
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    rids = engine.submit_fanout(prompt, 10, n_samples=2)
    served = engine.run()
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=10
    )
    for rid in rids:  # greedy fan-out: identical, exact
        np.testing.assert_array_equal(np.asarray(served[rid]), np.asarray(want[0]))
    assert engine.prefills_run == 1
    assert engine.ctrl.used_pages == 0


def test_speculative_engine_validations():
    import pytest

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    with pytest.raises(ValueError, match="come together"):
        ServeEngine(params, CONFIG, draft_params=draft)
    # temperature > 0 with a draft is VALID since lossless speculative
    # sampling landed — construction must succeed (behavior pinned in
    # tests/test_spec_sampling.py).
    engine = ServeEngine(
        params, CONFIG, draft_params=draft, draft_config=DRAFT_CONFIG,
        temperature=0.5, rng=jax.random.PRNGKey(1),
    )
    assert engine.sampling


def test_pipelined_speculative_matches_generate():
    """Pipelined speculative rounds (round N+1 dispatches chained on
    round N's device-side advance; the readback overlaps the next
    round's compute): every request still emits exactly the target's
    greedy tokens, across slot turnover and mixed lengths."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        draft_params=draft, draft_config=DRAFT_CONFIG, gamma=3,
        pipelined=True,
    )
    requests = _mixed_requests(6, CONFIG.vocab_size, rng_seed=29)
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()
    assert set(served) == set(rids)
    for rid, (prompt, new) in zip(rids, requests):
        want = generate(
            params, jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )
        np.testing.assert_array_equal(
            np.asarray(served[rid]), np.asarray(want[0]),
            err_msg=f"{rid} (prompt {len(prompt)}, new {new})",
        )
    assert engine.spec_rounds > 0
    assert engine._pending_spec is None  # fully drained
    assert engine.ctrl.used_pages == 0


def test_pipelined_speculative_full_length_and_eos():
    """The page-budget edge (prompt + max_new == max_seq_len) and early
    eos both hold under pipelined speculation — the dead in-flight round
    after retirement never exhausts the pool or corrupts a successor."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    engine = ServeEngine(
        params, CONFIG, slots=1, page_size=4, prompt_bucket=8,
        draft_params=draft, draft_config=DRAFT_CONFIG, gamma=3,
        pipelined=True,
    )
    prompt = [1, 2, 3, 4, 5, 6]
    rid = engine.submit(prompt, CONFIG.max_seq_len - len(prompt))
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG,
        max_new_tokens=CONFIG.max_seq_len - len(prompt),
    )
    eos_want = generate(
        params, jnp.asarray([[9, 8, 7]], jnp.int32), CONFIG, max_new_tokens=20
    )
    eos = int(np.asarray(eos_want[0, 2]))
    rid2 = engine.submit([9, 8, 7], 20, eos_token=eos)
    served = engine.run()
    np.testing.assert_array_equal(np.asarray(served[rid]), np.asarray(want[0]))
    assert served[rid2][-1] == eos
    assert engine.ctrl.used_pages == 0


def test_engine_validates_submissions():
    import pytest

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(params, CONFIG, slots=1, page_size=4, prompt_bucket=8)
    with pytest.raises(ValueError, match="prompt length"):
        engine.submit([], 4)
    with pytest.raises(ValueError, match="max_seq_len"):
        engine.submit([1, 2], CONFIG.max_seq_len)
    engine.submit([1, 2], 4, rid="dup")
    with pytest.raises(ValueError, match="already in flight"):
        engine.submit([3, 4], 4, rid="dup")


def test_cli_entry():
    from workloads.serve import main

    assert main([
        "--requests", "3", "--slots", "2", "--prompt-len", "8",
        "--max-new-tokens", "4", "--temperature", "0.8",
    ]) == 0
    assert main([
        "--requests", "2", "--slots", "2", "--prompt-len", "8",
        "--max-new-tokens", "4", "--int8", "--kv-heads", "4",
    ]) == 0

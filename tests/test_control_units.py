"""Fast-tier units for the goodput control plane (workloads/control.py)
and the stable ``FleetLedger.class_economics()`` query it consumes.

Everything here is jax-free: the controller's hill-climb, hysteresis,
EWMA plumbing, WFQ floor/boost arithmetic and autoscaler hint feed are
pure host-side control logic, exercised against fake engines/ledgers
that honour the real ``ServeEngine.retune()`` contract (returns
``{knob: (old, new)}``, validates ceilings, raises on closed / wrong
mode).  The real-engine transitions — drains, stream bit-parity, the
seeded waste-spike smoke — live in tests/test_control.py (slow tier).
"""

from types import SimpleNamespace

import pytest

from workloads.backoff import Backoff
from workloads.control import ControlSignals, GoodputController
from workloads.errors import EngineClosed
from workloads.ledger import ChipTimeLedger, FleetLedger

# Deterministic hysteresis for clock-injected tests: delay(attempt) is
# exactly base * 2**attempt, no jitter.
FAST = Backoff(base_s=1.0, factor=2.0, max_s=64.0, jitter=0.0)


# ---- fakes ---------------------------------------------------------------


class FakeEngine:
    """A ServeEngine stand-in honouring the retune() contract the
    controller depends on: change-dict returns, construction-time k
    ceilings, spec="auto" gating, EngineClosed on a closed engine."""

    def __init__(
        self,
        *,
        draft=True,
        spec="auto",
        spec_breakeven=4.0,
        superstep_k=1,
        superstep_k_max=None,
        spec_superstep_k=1,
        spec_superstep_k_max=None,
        slots=8,
    ):
        self.spec = spec
        self.draft_params = object() if draft else None
        self.spec_breakeven = (
            float(spec_breakeven) if spec_breakeven is not None else None
        )
        self.superstep_k = superstep_k
        self._superstep_k_max = (
            superstep_k_max if superstep_k_max is not None else superstep_k
        )
        self.spec_superstep_k = spec_superstep_k
        self._spec_superstep_k_max = (
            spec_superstep_k_max if spec_superstep_k_max is not None
            else spec_superstep_k
        )
        self.slots = slots
        self.closed = False
        self.retune_log = []

    def retune(self, **knobs):
        if self.closed:
            raise EngineClosed("engine is closed; no retune")
        changes = {}
        if "spec_breakeven" in knobs:
            if self.spec != "auto" or self.draft_params is None:
                raise ValueError("spec_breakeven retune needs auto+draft")
            new = float(knobs["spec_breakeven"])
            if new < 0:
                raise ValueError("spec_breakeven must be >= 0")
            if new != self.spec_breakeven:
                changes["spec_breakeven"] = (self.spec_breakeven, new)
        for knob, ceiling in (
            ("superstep_k", self._superstep_k_max),
            ("spec_superstep_k", self._spec_superstep_k_max),
        ):
            if knob in knobs:
                new = int(knobs[knob])
                if not 1 <= new <= ceiling:
                    raise ValueError(f"{knob} out of [1, {ceiling}]")
                if new != getattr(self, knob):
                    changes[knob] = (getattr(self, knob), new)
        for knob, (_, new) in changes.items():
            setattr(self, knob, new)
        if changes:
            self.retune_log.append(dict(changes))
        return changes


class FakeFleetLedger:
    """FleetLedger-shaped totals source: running counters the
    controller's ``_totals`` fleet branch reads, plus an injectable
    ``class_economics`` table for the WFQ seam."""

    def __init__(self):
        self.tokens_accounted = 0
        self.goodput_tokens = 0
        self._chip = ChipTimeLedger(name="fake")
        self.econ = {}

    @property
    def engine_ledgers(self):
        return [("0", self._chip)]

    def feed(self, *, goodput=0, spec_rejected=0, overdecode=0):
        """Account one delta: the controller only ever reads totals."""
        self.tokens_accounted += goodput + spec_rejected + overdecode
        self.goodput_tokens += goodput
        self._chip.waste_tokens["spec_rejected"] += spec_rejected
        self._chip.waste_tokens["overdecode"] += overdecode

    def class_economics(self):
        return {
            cls: dict(row) for cls, row in self.econ.items()
        }


class FakeFleet:
    """Just enough Fleet surface for the controller: replicas, a step
    that finishes nothing, the armed ledger, live WFQ weights."""

    def __init__(self, engines, *, wfq_weights=None):
        self.replicas = [
            SimpleNamespace(index=i, state="serving", engine=e)
            for i, e in enumerate(engines)
        ]
        self.ledger = FakeFleetLedger()
        self.wfq_weights = wfq_weights
        self.closed = False
        self.idle = True
        self.steps = 0

    def step(self):
        self.steps += 1
        return []

    def submit(self, prompt, new):
        return "rid-fake"

    def cancel(self, rid):
        return False


def _ctrl(fleet, **kw):
    kw.setdefault("retune_backoff", FAST)
    kw.setdefault("wfq_backoff", FAST)
    kw.setdefault("min_sample_tokens", 10)
    clock = kw.pop("clock", None)
    if clock is None:
        t = [0.0]
        kw["clock"] = lambda: t[0]
        return GoodputController(fleet, **kw), t
    kw["clock"] = clock
    return GoodputController(fleet, **kw), None


# ---- construction validation ---------------------------------------------


def test_rejects_invalid_construction():
    fleet = FakeFleet([FakeEngine()])
    with pytest.raises(ValueError, match="step"):
        GoodputController(object())
    with pytest.raises(ValueError, match="ewma_alpha"):
        GoodputController(fleet, ewma_alpha=0.0)
    with pytest.raises(ValueError, match="ewma_alpha"):
        GoodputController(fleet, ewma_alpha=1.5)
    with pytest.raises(ValueError, match="min_sample_tokens"):
        GoodputController(fleet, min_sample_tokens=0)
    with pytest.raises(ValueError, match="spec_reject"):
        GoodputController(fleet, spec_reject_low=0.5, spec_reject_high=0.3)
    with pytest.raises(ValueError, match="overdecode"):
        GoodputController(fleet, overdecode_low=-0.1)
    with pytest.raises(ValueError, match="overdecode"):
        GoodputController(fleet, overdecode_high=1.1)
    with pytest.raises(ValueError, match="breakeven_step"):
        GoodputController(fleet, breakeven_step=0.0)
    with pytest.raises(ValueError, match="wfq_max_boost"):
        GoodputController(fleet, wfq_max_boost=0.5)
    with pytest.raises(ValueError, match="wfq_deadband"):
        GoodputController(fleet, wfq_deadband=-1.0)


def test_driver_defaults_autoscaler_then_target():
    fleet = FakeFleet([FakeEngine()])
    ctrl, _ = _ctrl(fleet)
    assert ctrl.driver is fleet
    asc = SimpleNamespace(waste_fraction_hint=None, closed=False)
    ctrl2, _ = _ctrl(fleet, autoscaler=asc)
    assert ctrl2.driver is asc
    drv = SimpleNamespace(closed=False)
    ctrl3, _ = _ctrl(fleet, autoscaler=asc, driver=drv)
    assert ctrl3.driver is drv


# ---- signal plumbing -----------------------------------------------------


def test_poll_without_ledger_never_actuates():
    eng = FakeEngine()
    eng.step = lambda: []
    eng.ledger = None
    ctrl, _ = _ctrl(eng)
    ctrl.poll()
    assert ctrl.polls == 1
    assert ctrl.samples == 0
    assert ctrl.last_signals is None
    assert ctrl.retunes_applied == 0


def test_min_sample_gating_accumulates_small_deltas():
    fleet = FakeFleet([FakeEngine()])
    ctrl, _ = _ctrl(fleet, min_sample_tokens=10)
    fleet.ledger.feed(goodput=4)
    ctrl.poll()
    # Below the floor: no sample, but the delta is NOT consumed — the
    # baseline holds so small trickles accumulate into one sample.
    assert ctrl.samples == 0
    assert ctrl.last_signals.delta_tokens == 4
    assert ctrl.goodput_fraction_ewma is None
    fleet.ledger.feed(goodput=6)
    ctrl.poll()
    assert ctrl.samples == 1
    assert ctrl.last_signals.delta_tokens == 10
    assert ctrl.goodput_fraction_ewma == 1.0


def test_ewma_seeds_then_blends():
    fleet = FakeFleet([FakeEngine()])
    ctrl, _ = _ctrl(fleet, ewma_alpha=0.5, min_sample_tokens=10)
    fleet.ledger.feed(goodput=10)  # fraction 1.0 seeds
    ctrl.poll()
    assert ctrl.goodput_fraction_ewma == 1.0
    fleet.ledger.feed(spec_rejected=10)  # fraction 0.0 blends
    ctrl.poll()
    assert ctrl.goodput_fraction_ewma == pytest.approx(0.5)
    assert ctrl.spec_rejected_fraction_ewma == pytest.approx(0.5)
    sig = ctrl.last_signals
    assert isinstance(sig, ControlSignals)
    assert sig.accounted_tokens == 20
    assert sig.goodput_fraction == pytest.approx(0.5)


def test_autoscaler_hint_is_clamped_smoothed_waste():
    asc = SimpleNamespace(waste_fraction_hint=None, closed=False)
    fleet = FakeFleet([FakeEngine()])
    ctrl, _ = _ctrl(fleet, autoscaler=asc, driver=fleet, ewma_alpha=1.0)
    ctrl.poll()
    assert asc.waste_fraction_hint is None  # no evidence, no hint
    fleet.ledger.feed(goodput=3, spec_rejected=7)
    ctrl.poll()
    assert asc.waste_fraction_hint == pytest.approx(0.7)


# ---- hill-climb moves ----------------------------------------------------


def _spike(fleet, ctrl, *, goodput=0, spec_rejected=0, overdecode=0):
    fleet.ledger.feed(
        goodput=goodput, spec_rejected=spec_rejected, overdecode=overdecode,
    )
    ctrl.poll()


def test_spec_down_walks_breakeven_then_halves_spec_superstep():
    eng = FakeEngine(
        spec_breakeven=2.0, spec_superstep_k=4, spec_superstep_k_max=4,
    )
    fleet = FakeFleet([eng])
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0, breakeven_step=1.0)
    # Sustained spec_rejected burn: each cooldown expiry lands exactly
    # one knob move — breakeven walks 2 -> 1 -> 0 (clamped), then the
    # fused spec rounds halve 4 -> 2 -> 1, then nothing is left.
    expect = [
        ("spec_breakeven", 1.0), ("spec_breakeven", 0.0),
        ("spec_superstep_k", 2), ("spec_superstep_k", 1),
    ]
    for knob, value in expect:
        t[0] += 1000.0  # past any escalated gate
        _spike(fleet, ctrl, goodput=2, spec_rejected=18)
        assert getattr(eng, knob) == value, (knob, eng.retune_log)
    applied = ctrl.retunes_applied
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=2, spec_rejected=18)
    assert ctrl.retunes_applied == applied  # floor reached: no-op
    assert ctrl.decisions["retune"] == applied
    kinds = [ev.kind for ev in ctrl.events]
    assert kinds.count("retune") == applied


def test_super_down_halves_superstep_then_spec_superstep():
    eng = FakeEngine(
        draft=False, spec="on", spec_breakeven=None,
        superstep_k=4, superstep_k_max=4,
        spec_superstep_k=2, spec_superstep_k_max=2,
    )
    fleet = FakeFleet([eng])
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0)
    for k_sup, k_spec in ((2, 2), (1, 2), (1, 1)):
        t[0] += 1000.0
        _spike(fleet, ctrl, goodput=2, overdecode=18)
        assert (eng.superstep_k, eng.spec_superstep_k) == (k_sup, k_spec)


def test_spec_up_doubles_spec_superstep_then_raises_breakeven():
    eng = FakeEngine(
        spec_breakeven=1.0, slots=4,
        spec_superstep_k=1, spec_superstep_k_max=4,
    )
    fleet = FakeFleet([eng])
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0, breakeven_step=2.0)
    # Near-zero rejected waste: recapture the fused-round win first
    # (1 -> 2 -> 4, the construction ceiling), then push breakeven
    # toward slots, clamped at slots.
    expect = [
        ("spec_superstep_k", 2), ("spec_superstep_k", 4),
        ("spec_breakeven", 3.0), ("spec_breakeven", 4.0),
    ]
    for knob, value in expect:
        t[0] += 1000.0
        _spike(fleet, ctrl, goodput=100)
        assert getattr(eng, knob) == value, (knob, eng.retune_log)
    t[0] += 1000.0
    applied = ctrl.retunes_applied
    _spike(fleet, ctrl, goodput=100)
    assert ctrl.retunes_applied == applied  # at the ceilings


def test_super_up_doubles_toward_construction_ceiling_only():
    eng = FakeEngine(
        draft=False, spec="off", spec_breakeven=None,
        superstep_k=1, superstep_k_max=8,
    )
    fleet = FakeFleet([eng])
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0)
    for k in (2, 4, 8):
        t[0] += 1000.0
        _spike(fleet, ctrl, goodput=100)
        assert eng.superstep_k == k
    t[0] += 1000.0
    applied = ctrl.retunes_applied
    _spike(fleet, ctrl, goodput=100)
    assert eng.superstep_k == 8  # never above the ceiling
    assert ctrl.retunes_applied == applied


def test_dead_band_holds_and_resets_escalation():
    eng = FakeEngine(spec_breakeven=8.0, slots=8)
    fleet = FakeFleet([eng])
    ctrl, t = _ctrl(
        fleet, ewma_alpha=1.0,
        spec_reject_low=0.05, spec_reject_high=0.3,
    )
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=60, spec_rejected=40)  # 0.4 > high
    assert ctrl.retunes_applied == 1
    assert ctrl._retune_streak == 1
    # Signal lands inside the dead band: hold, and the escalation
    # streak resets so the next excursion acts at base cadence.
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=90, spec_rejected=10)  # 0.1 in band
    assert ctrl.retunes_applied == 1
    assert ctrl._retune_streak == 0


def test_hysteresis_gate_blocks_until_cooldown_expires():
    eng = FakeEngine(spec_breakeven=8.0, slots=8)
    fleet = FakeFleet([eng])
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0)
    t[0] = 10.0
    _spike(fleet, ctrl, goodput=2, spec_rejected=18)
    assert ctrl.retunes_applied == 1
    gate = ctrl._retune_gate
    assert gate == 10.0 + FAST.derive("retune").delay(1)
    # Polls inside the cooldown never move a knob however hot the
    # signal stays.
    t[0] = gate - 1e-6
    _spike(fleet, ctrl, goodput=2, spec_rejected=18)
    assert ctrl.retunes_applied == 1
    # Past the gate the next single move lands, and the escalated
    # streak buys a LONGER cooldown (delay(2) > delay(1)).
    t[0] = gate
    _spike(fleet, ctrl, goodput=2, spec_rejected=18)
    assert ctrl.retunes_applied == 2
    assert ctrl._retune_gate == gate + FAST.derive("retune").delay(2)


def test_incapable_engines_are_never_picked():
    # No draft anywhere and every k ceiling at 1: there is nothing to
    # retune, whatever the waste says.
    eng = FakeEngine(draft=False, spec="off", spec_breakeven=None)
    fleet = FakeFleet([eng])
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0)
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=1, spec_rejected=10, overdecode=9)
    assert ctrl.retunes_applied == 0
    assert ctrl._pick_move() is None


def test_closed_engine_is_skipped_not_fatal():
    dead = FakeEngine(spec_breakeven=4.0)
    dead.closed = True
    live = FakeEngine(spec_breakeven=4.0)
    fleet = FakeFleet([dead, live])
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0)
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=2, spec_rejected=18)
    assert dead.spec_breakeven == 4.0
    assert live.spec_breakeven == 3.0
    assert ctrl.retunes_applied == 1


# ---- WFQ re-weighting ----------------------------------------------------


def _wfq_fleet(econ, weights):
    # Engines with nothing to retune, so only the WFQ seam actuates.
    fleet = FakeFleet(
        [FakeEngine(draft=False, spec="off", spec_breakeven=None)],
        wfq_weights=weights,
    )
    fleet.ledger.econ = econ
    return fleet


def test_wfq_boosts_efficient_class_above_operator_floor():
    econ = {
        "interactive": {"goodput_per_chip_s": 30.0, "chip_s": 1.0},
        "bulk": {"goodput_per_chip_s": 10.0, "chip_s": 1.0},
    }
    fleet = _wfq_fleet(econ, {"interactive": 2.0, "bulk": 1.0})
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0, wfq_deadband=0.25)
    assert ctrl._wfq_floor == {"interactive": 2.0, "bulk": 1.0}
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=100)
    # mean rate 20: interactive earns 1.5x its floor; bulk holds AT its
    # floor (boost-above-floor only — never starved below the operator
    # weight).
    assert fleet.wfq_weights == {"interactive": 3.0, "bulk": 1.0}
    assert ctrl.wfq_reweights == 1
    assert ctrl.decisions["wfq_reweight"] == 1
    assert any(ev.kind == "wfq_reweight" for ev in ctrl.events)


def test_wfq_boost_caps_at_max_boost():
    econ = {"interactive": {"goodput_per_chip_s": 1000.0, "chip_s": 1.0}}
    for i in range(4):
        econ[f"bulk{i}"] = {"goodput_per_chip_s": 1.0, "chip_s": 1.0}
    weights = {cls: 1.0 for cls in econ}
    fleet = _wfq_fleet(econ, weights)
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0, wfq_max_boost=4.0)
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=100)
    # interactive's raw rate/mean multiplier is ~5x: capped at 4.
    assert fleet.wfq_weights["interactive"] == 4.0
    assert fleet.wfq_weights["bulk0"] == 1.0


def test_wfq_deadband_suppresses_small_moves():
    econ = {
        "interactive": {"goodput_per_chip_s": 22.0, "chip_s": 1.0},
        "bulk": {"goodput_per_chip_s": 18.0, "chip_s": 1.0},
    }
    fleet = _wfq_fleet(econ, {"interactive": 1.0, "bulk": 1.0})
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0, wfq_deadband=0.25)
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=100)
    # interactive's earned mult is 1.1: an 10% move under the 25%
    # deadband — weights hold, no reweight counted.
    assert fleet.wfq_weights == {"interactive": 1.0, "bulk": 1.0}
    assert ctrl.wfq_reweights == 0


def test_wfq_needs_two_measured_classes():
    econ = {"interactive": {"goodput_per_chip_s": 30.0, "chip_s": 1.0}}
    fleet = _wfq_fleet(econ, {"interactive": 1.0, "bulk": 1.0})
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0)
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=100)
    assert ctrl.wfq_reweights == 0
    assert fleet.wfq_weights == {"interactive": 1.0, "bulk": 1.0}


def test_wfq_noop_without_weights_or_economics():
    fleet = FakeFleet(
        [FakeEngine(draft=False, spec="off", spec_breakeven=None)],
        wfq_weights=None,
    )
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0)
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=100)
    assert ctrl.wfq_reweights == 0


# ---- telemetry, events, driving surface ----------------------------------


def test_states_and_drain_events_and_overflow():
    eng = FakeEngine(spec_breakeven=8.0, slots=8)
    fleet = FakeFleet([eng])
    ctrl, t = _ctrl(fleet, ewma_alpha=1.0)
    t[0] += 1000.0
    _spike(fleet, ctrl, goodput=2, spec_rejected=18)
    st = ctrl.states()
    assert st["polls"] == 1
    assert st["samples"] == 1
    assert st["retunes_applied"] == 1
    assert st["goodput_fraction_ewma"] == pytest.approx(0.1)
    assert st["decisions"] == {"retune": 1}
    assert st["poll_s"] >= 0.0
    drained = ctrl.drain_events()
    assert [ev.kind for ev in drained] == ["retune"]
    assert not ctrl.events
    # Ring overflow counts drops instead of growing unbounded.
    from collections import deque

    ctrl.events = deque(maxlen=1)
    ctrl._event("a")
    ctrl._event("b")
    assert ctrl.dropped_events == 1
    assert [ev.kind for ev in ctrl.events] == ["b"]


def test_step_polls_after_driving_and_run_collects():
    eng = FakeEngine(spec_breakeven=8.0, slots=8)
    fleet = FakeFleet([eng])
    ctrl, _ = _ctrl(fleet)
    assert ctrl.step() == []
    assert fleet.steps == 1
    assert ctrl.polls == 1
    # run() drives the wrapped driver to idle, collecting finished
    # streams fleet.run-style.
    fr = SimpleNamespace(rid="r1", tokens=[1, 2, 3])
    fleet.idle = False

    def step_once():
        fleet.steps += 1
        fleet.idle = True
        return [fr]

    fleet.step = step_once
    assert ctrl.run() == {"r1": [1, 2, 3]}
    assert ctrl.submit([1], 2) == "rid-fake"
    assert ctrl.cancel("r1") is False
    assert ctrl.closed is False
    assert ctrl.idle is True


def test_engine_target_reads_chip_ledger_totals():
    eng = FakeEngine(spec_breakeven=2.0)
    eng.step = lambda: []
    eng.ledger = ChipTimeLedger(name="solo")
    ctrl, t = _ctrl(eng, ewma_alpha=1.0)
    assert ctrl.fleet is None and ctrl.engine is eng
    eng.ledger.tokens_accounted = 20
    eng.ledger.goodput_tokens = 2
    eng.ledger.waste_tokens["spec_rejected"] = 18
    t[0] += 1000.0
    ctrl.poll()
    assert ctrl.samples == 1
    assert ctrl.spec_rejected_fraction_ewma == pytest.approx(0.9)
    assert eng.spec_breakeven == 1.0  # retune reached the bare engine


# ---- FleetLedger.class_economics -----------------------------------------


def _fleet_stub(generated=0, replayed=0):
    return SimpleNamespace(
        replicas=(), generated_tokens=generated, tokens_replayed=replayed,
    )


def _fin(n, cls, status="ok"):
    return SimpleNamespace(tokens=[0] * n, slo_class=cls, status=status)


def test_class_economics_empty_ledger_is_empty():
    assert FleetLedger().class_economics() == {}


def test_class_economics_apportions_busy_seconds_by_token_share():
    led = FleetLedger()
    chip = ChipTimeLedger(name="0")
    chip.phase_s["decode"] = 6.0
    chip.phase_s["idle"] = 4.0  # idle never charges a class
    chip.wall_s = 10.0
    led.attach("0", chip)
    led.step_end(
        _fleet_stub(generated=90),
        [_fin(60, "interactive"), _fin(30, "bulk", status="cancelled")],
    )
    econ = led.class_economics()
    assert set(econ) == {"interactive", "bulk"}
    ia, bk = econ["interactive"], econ["bulk"]
    assert ia["goodput_tokens"] == 60 and ia["waste_tokens"] == 0
    assert bk["goodput_tokens"] == 0 and bk["waste_tokens"] == 30
    # Shares partition the classified tokens; busy (non-idle) seconds
    # are charged by share.
    assert ia["token_share"] + bk["token_share"] == pytest.approx(1.0)
    assert ia["chip_s"] == pytest.approx(4.0)
    assert bk["chip_s"] == pytest.approx(2.0)
    assert ia["chip_s_by_phase"]["decode"] == pytest.approx(4.0)
    assert "idle" not in ia["chip_s_by_phase"]
    # The WFQ ranking headline: goodput per attributed chip-second.
    assert ia["goodput_per_chip_s"] == pytest.approx(15.0)
    assert bk["goodput_per_chip_s"] == 0.0


def test_class_economics_zero_seconds_is_zero_safe():
    led = FleetLedger()
    led.step_end(_fleet_stub(generated=10), [_fin(10, "interactive")])
    econ = led.class_economics()
    assert econ["interactive"]["chip_s"] == 0.0
    assert econ["interactive"]["goodput_per_chip_s"] == 0.0
    assert econ["interactive"]["token_share"] == pytest.approx(1.0)


def test_class_economics_untagged_bucket_for_unclassed_traffic():
    led = FleetLedger()
    led.step_end(
        _fleet_stub(generated=10),
        [SimpleNamespace(tokens=[0] * 10, slo_class=None, status="ok")],
    )
    econ = led.class_economics()
    assert econ["untagged"]["goodput_tokens"] == 10

"""Device-time profiling & regression sentry units (jax-free:
workloads/profiler.py gates its jax import inside ProfileSession.start,
so the table/sentry machinery and the trace-lane validator run in the
fast suite — docs/OBSERVABILITY.md "Device-time profiling & regression
sentry").

Pinned here: the EWMA/z-score sentry fires EXACTLY ONE perf_regression
trigger per incident under a scripted regression on a fake clock,
re-arms after recovery, and stays quiet under baseline noise at the
committed artifact's own spread; the DeviceTimeTable round-trips its
calibration; the chrome-trace validator rejects empty traces and
pid/tid lane collisions across replicas.  The real-capture smoke
(ProfileSession dumping an actual jax.profiler trace) lives in
tests/test_profile_capture.py behind `make profile-check`.
"""

import json
import os
import sys
from types import SimpleNamespace

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)

from workloads.profiler import (  # noqa: E402
    DeviceTimeTable,
    ProfileSession,
    RegressionSentry,
    SentryFeed,
    artifact_spread_fraction,
    device_report,
    load_committed_artifact,
    sentry_from_artifact,
    _pow2_bucket,
)

from postmortem import validate_file  # noqa: E402
from trace_export import validate_trace  # noqa: E402


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t

    def advance(self, secs: float) -> None:
        self.t += secs


# ---- device-time attribution table --------------------------------------


def test_pow2_bucketing():
    assert [_pow2_bucket(n) for n in (0, 1, 2, 3, 5, 8, 9)] == [
        0, 1, 2, 4, 8, 8, 16,
    ]
    assert DeviceTimeTable.key("plain", 5, 3) == "plain|s8|b4"


def test_device_table_observe_estimate_and_roundtrip():
    table = DeviceTimeTable(alpha=0.5)
    table.observe("plain", 8, 4, 10.0)
    table.observe("plain", 8, 4, 20.0)  # EWMA: 10 + 0.5*(20-10) = 15
    assert table.estimate("plain", 8, 4) == pytest.approx(15.0)
    # Unknown bucket of a KNOWN program falls back to the nearest
    # same-program entry (a coarse prior beats attributing nothing)...
    assert table.estimate("plain", 64, 1) == pytest.approx(15.0)
    # ...but never crosses programs.
    assert table.estimate("spec", 8, 4) is None
    # JSON round-trip; existing live entries win over persisted ones.
    table2 = DeviceTimeTable()
    table2.observe("plain", 8, 4, 99.0)
    adopted = table2.load(json.loads(json.dumps(table.to_dict())))
    assert adopted == 0
    assert table2.estimate("plain", 8, 4) == pytest.approx(99.0)
    table3 = DeviceTimeTable()
    assert table3.load(table.to_dict()) == len(table)
    assert table3.estimate("plain", 8, 4) == pytest.approx(15.0)
    # Artifact refresh reads the measure_profiler key.
    table4 = DeviceTimeTable()
    n = table4.refresh_from_artifact(
        {"profiler_device_time_table": table.to_dict()}
    )
    assert n == len(table) and len(table4) == len(table)
    # Negative samples and malformed entries are ignored.
    table4.observe("plain", 8, 4, -1.0)
    assert table4.load({"bad": "nope", "worse": {"ms": -3}}) == 0


# ---- profile session budgets (capture itself needs jax; see
# ---- tests/test_profile_capture.py) -------------------------------------


def test_profile_session_validates_budgets(tmp_path):
    with pytest.raises(ValueError):
        ProfileSession(str(tmp_path), max_secs=0)
    with pytest.raises(ValueError):
        ProfileSession(str(tmp_path), max_bytes=0)
    sess = ProfileSession(str(tmp_path), max_secs=5.0, max_bytes=1024)
    assert not sess.active and sess.bytes_spent == 0
    state = sess.state()
    assert state["active"] is False and state["captures"] == []
    # A spent disk budget refuses the NEXT capture before any jax
    # import happens — the budget check runs first.
    sess.captures.append({"dir": "x", "secs": 1.0, "bytes": 2048})
    with pytest.raises(RuntimeError, match="disk budget"):
        sess.start(1.0)
    assert sess.stop() is None  # idempotent when nothing is active


# ---- regression sentry ---------------------------------------------------


def _scripted(sentry, name, values):
    incidents = []
    for v in values:
        inc = sentry.observe(name, v)
        if inc:
            incidents.append(inc)
    return incidents


def test_sentry_scripted_regression_fires_exactly_once():
    clock = FakeClock()
    sentry = RegressionSentry(
        z_threshold=4.0, alpha=0.5, confirm=3, rearm=5, clock=clock,
    )
    sentry.watch("tokens_per_sec", 100.0, 2.0, direction="down_bad")
    # In-band noise: no breach.
    assert _scripted(sentry, "tokens_per_sec",
                     [101.0, 99.0, 100.5, 98.5, 101.5]) == []
    assert sentry.armed and sentry.fired == 0
    # Sustained collapse: one confirmed incident, then the latch holds
    # however long the regression persists.
    incidents = _scripted(sentry, "tokens_per_sec", [20.0] * 10)
    assert len(incidents) == 1
    assert sentry.fired == 1 and not sentry.armed
    assert incidents[0]["signal"] == "tokens_per_sec"
    assert incidents[0]["z"] >= 4.0
    state = sentry.state()
    assert state["fired"] == 1 and state["armed"] is False
    assert state["detectors"]["tokens_per_sec"]["breaches"] >= 3
    assert state["recent"], "observations must land in the history ring"


def test_sentry_recovery_rearms_and_second_incident_fires():
    sentry = RegressionSentry(
        z_threshold=4.0, alpha=1.0, confirm=2, rearm=3,
        clock=FakeClock(),
    )
    sentry.watch("ttft_p99_ms", 50.0, 2.0, direction="up_bad")
    assert len(_scripted(sentry, "ttft_p99_ms", [500.0] * 4)) == 1
    assert not sentry.armed
    # Recovery: `rearm` consecutive in-band reads clear the breach
    # counter and re-arm the sentry...
    assert _scripted(sentry, "ttft_p99_ms", [50.0, 51.0, 49.0]) == []
    assert sentry.armed
    # ...so the NEXT regression is its own incident.
    assert len(_scripted(sentry, "ttft_p99_ms", [400.0] * 4)) == 1
    assert sentry.fired == 2


def test_sentry_self_baselines_in_live_mode():
    sentry = RegressionSentry(
        z_threshold=4.0, alpha=1.0, confirm=2, rearm=3,
        clock=FakeClock(),
    )
    # baseline=None + relative spread: the live-fleet mode.  The first
    # `warmup` samples fix the operating point (no scoring yet).
    sentry.watch("tokens_per_sec", None, 0.05, direction="down_bad",
                 warmup=4)
    assert _scripted(sentry, "tokens_per_sec",
                     [200.0, 202.0, 198.0, 200.0]) == []
    det = sentry.state()["detectors"]["tokens_per_sec"]
    assert det["baseline"] == pytest.approx(200.0)
    assert det["spread"] == pytest.approx(10.0)  # 0.05 * 200
    assert _scripted(sentry, "tokens_per_sec", [199.0, 201.0]) == []
    assert len(_scripted(sentry, "tokens_per_sec", [100.0] * 3)) == 1


def test_sentry_bad_watch_args_raise():
    sentry = RegressionSentry()
    with pytest.raises(ValueError):
        sentry.watch("x", 1.0, 0.0)
    with pytest.raises(ValueError):
        sentry.watch("x", 1.0, 1.0, direction="sideways_bad")
    with pytest.raises(ValueError):
        RegressionSentry(z_threshold=0)
    with pytest.raises(ValueError):
        RegressionSentry(confirm=0)
    # Unwatched signals are ignored, not errors: the feed may offer
    # more signals than the artifact could anchor.
    assert sentry.observe("unwatched", 1.0) is None


def test_sentry_quiet_under_committed_artifact_noise():
    """The no-false-positive pin from the acceptance criteria: a sentry
    built from the COMMITTED artifact must not fire when fed its own
    baselines jittered within the artifact's measured spread."""
    artifact = load_committed_artifact()
    assert artifact, "docs/bench-builder-latest.json must exist"
    sentry = sentry_from_artifact(artifact, clock=FakeClock())
    assert sentry.signals, (
        "committed artifact must anchor at least one sentry signal"
    )
    rel = artifact_spread_fraction(artifact)
    baselines = {
        name: sentry.state()["detectors"][name]["baseline"]
        for name in sentry.signals
    }
    for i in range(200):
        for name in sentry.signals:
            jitter = 0.9 * rel * baselines[name] * (1 if i % 2 else -1)
            sentry.observe(name, baselines[name] + jitter)
    assert sentry.fired == 0 and sentry.armed, sentry.state()


def test_artifact_spread_fraction_derivation():
    art = {
        "a": 100.0, "a_min": 90.0, "a_max": 110.0, "a_samples": [1],
        "b": 10.0, "b_min": 9.0, "b_max": 11.0, "b_samples": [1],
    }
    assert artifact_spread_fraction(art) == pytest.approx(0.10)
    # Artifacts predating the samples families get the floor.
    assert artifact_spread_fraction({}, floor=0.08) == 0.08


def test_sentry_from_artifact_degrades_on_missing_keys():
    sentry = sentry_from_artifact({"serve_ttft_p99_ms": 12.0})
    assert sentry.signals == ("ttft_p99_ms",)
    # tokens_per_sec falls back to serve_tokens_per_sec when the
    # profiler arm hasn't published yet.
    sentry = sentry_from_artifact({"serve_tokens_per_sec": 500.0})
    assert sentry.signals == ("tokens_per_sec",)
    assert sentry_from_artifact({}).signals == ()


# ---- sentry -> flight recorder: the perf_regression bundle ---------------


def test_scripted_regression_dumps_exactly_one_validating_bundle(tmp_path):
    from workloads.ledger import FlightRecorder

    rec = FlightRecorder(out_dir=str(tmp_path), name="sentrytest")
    sentry = RegressionSentry(
        z_threshold=4.0, alpha=1.0, confirm=3, rearm=4,
        clock=FakeClock(),
    )
    rec.attach_sentry(sentry)
    assert sentry.recorder is rec
    sentry.watch("tokens_per_sec", 100.0, 2.0, direction="down_bad")
    _scripted(sentry, "tokens_per_sec", [100.0, 99.5] + [15.0] * 8)
    bundles = [p for p in rec.dumped if "perf_regression" in p]
    assert len(bundles) == 1 and len(rec.dumped) == 1
    errors = validate_file(bundles[0])
    assert errors == [], errors
    obj = json.load(open(bundles[0]))
    assert obj["trigger"]["kind"] == "perf_regression"
    # The bundle embeds the detector state — the postmortem reader must
    # see WHAT the sentry believed when it fired.
    assert obj["sentry"]["fired"] == 1
    assert obj["sentry"]["detectors"]["tokens_per_sec"]["breaches"] >= 3
    assert obj["sentry"]["incidents"][0]["signal"] == "tokens_per_sec"


def test_perf_regression_bundle_without_sentry_state_fails_validation(
    tmp_path,
):
    from workloads.ledger import FlightRecorder
    from postmortem import validate_bundle

    rec = FlightRecorder(out_dir=str(tmp_path), name="nostate")
    path = rec.trigger("perf_regression", detail="hand-rolled")
    obj = json.load(open(path))
    obj.pop("sentry", None)
    errors = validate_bundle(obj)
    assert any("sentry" in e for e in errors), errors


# ---- observer-side attribution + fleet report ---------------------------


def _drive_observed_engine(obs, steps=3):
    import numpy as np

    eng = SimpleNamespace(
        generated_tokens=0, requests_admitted=0, requests_retired=0,
        prefill_dispatches=0, prefill_sweeps=0, chunks_run=0,
        spec_rounds=0, mode_switches=0, admission_readbacks=0,
        spec_lookahead=1, prefill_deferred_tokens=0, host_sync_s=0.0,
        _inflight_prefill=[], pending=[], _occupied=np.ones(2, bool),
        slots=2, ctrl=SimpleNamespace(used_pages=0), paused=False,
    )
    obs._bind(eng)
    for _ in range(steps):
        snap = obs._step_begin(eng)
        eng.generated_tokens += 4
        eng.chunks_run += 1
        obs._step_end(eng, snap, [])
    return eng


def test_observer_attributes_device_time_and_reports():
    from workloads.obs import EngineObserver

    table = DeviceTimeTable()
    obs = EngineObserver(device_table=table)
    _drive_observed_engine(obs, steps=4)
    assert len(table) > 0
    recs = list(obs.steps)
    assert all(r.device_ms >= 0.0 for r in recs)
    assert any(r.device_ms > 0.0 for r in recs)
    assert 0.0 < obs.device_busy_fraction <= 1.0
    assert obs.host_stall_fraction == pytest.approx(
        1.0 - obs.device_busy_fraction
    )
    report = device_report([obs, None])
    assert 0.0 < report["device_busy_fraction"] <= 1.0
    assert report["device_busy_fraction"] + report[
        "host_stall_fraction"
    ] == pytest.approx(1.0)
    assert "plain" in report["phases"]
    assert report["phases"]["plain"]["steps"] == 4
    # device_ms is a table-smoothed ESTIMATE, so a single µs-scale fake
    # step may estimate past its own wall — the published fractions are
    # clamped instead of asserting per-step wall >= device.
    assert report["wall_ms"] > 0.0 and report["device_ms"] > 0.0
    # Empty observers report a clean zero, not a division error.
    assert device_report([])["device_busy_fraction"] == 0.0


def test_sentry_feed_extracts_windowed_signals():
    from workloads.obs import EngineObserver

    clock = FakeClock()
    sentry = RegressionSentry(
        z_threshold=4.0, alpha=1.0, confirm=2, rearm=3, clock=clock,
    )
    for name, direction in (
        ("tokens_per_sec", "down_bad"),
        ("host_sync_ms", "up_bad"),
        ("device_busy_fraction", "down_bad"),
    ):
        sentry.watch(name, None, 0.25, direction=direction, warmup=2)
    feed = SentryFeed(sentry, min_window_s=0.5, clock=clock)
    obs = EngineObserver(device_table=DeviceTimeTable())
    eng = _drive_observed_engine(obs, steps=2)
    feed.attach(eng, obs)
    assert feed.poll() == []  # first poll only anchors the window
    clock.advance(0.1)
    assert feed.poll() == []  # sub-window polls are free early-returns
    detectors = sentry.state()["detectors"]
    assert detectors["tokens_per_sec"]["samples"] == 0
    for _ in range(4):
        clock.advance(1.0)
        eng.generated_tokens += 10
        eng.host_sync_s += 0.002
        snap = obs._step_begin(eng)
        eng.chunks_run += 1
        obs._step_end(eng, snap, [])
        feed.poll()
    detectors = sentry.state()["detectors"]
    assert detectors["tokens_per_sec"]["samples"] == 4
    assert detectors["host_sync_ms"]["samples"] == 4
    assert detectors["device_busy_fraction"]["samples"] == 4
    assert sentry.fired == 0  # a steady fake load is not a regression


# ---- chrome-trace validator regressions ---------------------------------


def _meta(pid, tid, name, label):
    return {"ph": "M", "name": name, "pid": pid, "tid": tid,
            "args": {"name": label}}


def _valid_trace():
    return {"traceEvents": [
        _meta(1, 0, "process_name", "requests"),
        _meta(2, 0, "process_name", "engine"),
        _meta(2, 1, "thread_name", "step()"),
        _meta(2, 2, "thread_name", "device"),
        {"ph": "X", "name": "step 0", "pid": 2, "tid": 1,
         "ts": 0, "dur": 5},
        {"ph": "X", "name": "device[plain]", "pid": 2, "tid": 2,
         "ts": 0, "dur": 3},
    ]}


def test_trace_validator_accepts_device_lanes():
    assert validate_trace(_valid_trace()) == []


def test_trace_validator_rejects_empty_traces():
    errors = validate_trace({"traceEvents": []})
    assert any("empty" in e.lower() for e in errors), errors


def test_trace_validator_rejects_cross_replica_lane_collisions():
    # Two replicas merged onto the SAME pid with different labels: the
    # rebase-by-replica-index contract broke, and chrome would silently
    # interleave their lanes.
    trace = _valid_trace()
    trace["traceEvents"].append(
        _meta(2, 0, "process_name", "replica 1 engine")
    )
    errors = validate_trace(trace)
    assert any("pid" in e and "collision" in e for e in errors), errors
    # Same pid/tid pair renamed: a thread-lane collision.
    trace2 = _valid_trace()
    trace2["traceEvents"].append(_meta(2, 2, "thread_name", "steps"))
    errors2 = validate_trace(trace2)
    assert any("tid" in e and "collision" in e for e in errors2), errors2
    # Re-declaring the SAME label is idempotent, not a collision (the
    # single-engine export emits metadata once per lane per export).
    trace3 = _valid_trace()
    trace3["traceEvents"].append(_meta(2, 2, "thread_name", "device"))
    assert validate_trace(trace3) == []


# --------------------------------------------------------------------
# FleetServer /profile endpoints (workloads/fleet.py): jax-free via a
# duck-typed ProfileSession stub — the handler's contract is "translate
# the session's refusals to HTTP", so the stub only needs to refuse the
# way the real one does (RuntimeError -> 409, ValueError -> 400).
# The real-capture path is tests/test_profile_capture.py's business.


class _StubFleet:
    """Just enough Fleet for FleetServer's driver thread to idle."""

    closed = False
    replicas = ()
    queue_depth = 0

    def serve_forever(self, stop):
        stop.wait()


class _StubProfiler:
    def __init__(self):
        self.active = False
        self.calls = []

    def start(self, secs=None):
        self.calls.append(("start", secs))
        if secs is not None and secs <= 0:
            raise ValueError(f"secs must be > 0, got {secs}")
        if self.active:
            raise RuntimeError("a capture is already active")
        self.active = True
        return {"dir": "/tmp/p/profile-000", "secs": secs or 30.0}

    def stop(self):
        self.calls.append(("stop", None))
        if not self.active:
            return None
        self.active = False
        return {"dir": "/tmp/p/profile-000", "bytes": 7}

    def state(self):
        return {"active": self.active, "captures": []}


def _http(method, port, path):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=b"" if method == "POST" else None,
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_fleet_profile_endpoints_drive_the_armed_session():
    from workloads.fleet import FleetServer

    profiler = _StubProfiler()
    server = FleetServer(_StubFleet(), 0, profiler=profiler)
    port = server.start()
    try:
        # start -> capture opens; a second start is refused with 409.
        code, body = _http("POST", port, "/profile?secs=5")
        assert code == 200 and body["ok"] and body["secs"] == 5.0
        code, body = _http("POST", port, "/profile")
        assert code == 409 and "active" in body["error"]
        # state rides GET; stop closes and returns the capture record.
        code, body = _http("GET", port, "/profile")
        assert code == 200 and body["active"]
        code, body = _http("POST", port, "/profile/stop")
        assert code == 200 and body["capture"]["bytes"] == 7
        code, body = _http("POST", port, "/profile/stop")
        assert code == 409 and "no capture" in body["error"]
        # Malformed secs dies in the handler, before the session.
        n_calls = len(profiler.calls)
        code, body = _http("POST", port, "/profile?secs=abc")
        assert code == 400 and "secs" in body["error"]
        assert len(profiler.calls) == n_calls
        # Non-positive secs: the session's ValueError surfaces as 400.
        code, body = _http("POST", port, "/profile?secs=0")
        assert code == 400
    finally:
        server.stop()


def test_fleet_profile_endpoints_409_when_unarmed():
    from workloads.fleet import FleetServer

    server = FleetServer(_StubFleet(), 0)  # no --profile-dir
    port = server.start()
    try:
        code, body = _http("POST", port, "/profile?secs=5")
        assert code == 409 and "--profile-dir" in body["error"]
    finally:
        server.stop()

"""Mixed-strategy end-to-end: overlapping chip/tray views, shared health
fan-out, claim reconciliation with TTL recovery (BASELINE configs[3])."""

import time

import pytest

from tpu_device_plugin.api import pb
from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
from tpu_device_plugin.backend.fake import FakeChipManager
from tpu_device_plugin.config import Config, Flags
from tpu_device_plugin.resource_config import ResourceConfig
from tpu_device_plugin.strategy import new_topology_strategy

from .fake_kubelet import FakeKubelet


@pytest.fixture
def stack(tmp_path):
    kubelet = FakeKubelet(str(tmp_path / "device-plugins"))
    kubelet.start()
    mgr = FakeChipManager(n_chips=4, chips_per_tray=4)
    mgr.init()
    cfg = Config(
        flags=Flags(
            backend="fake",
            topology_strategy="mixed",
            mixed_claim_ttl_secs=1.0,
            device_plugin_path=kubelet.plugin_dir,
        )
    )
    strategy = new_topology_strategy(
        cfg,
        ResourceConfig(),
        mgr,
        plugin_dir=kubelet.plugin_dir,
        kubelet_socket=kubelet.socket_path,
        lease_dir=str(tmp_path / "leases"),
    )
    plugins = strategy.get_plugins()
    for p in plugins:
        p.start()
    yield kubelet, mgr, plugins
    for p in plugins:
        p.stop()
    kubelet.stop()


def stub_for(kubelet, plugins, resource):
    plugin = next(p for p in plugins if p.resource_name == resource)
    import os

    return kubelet.plugin_client(os.path.basename(plugin.socket_path))


def test_health_event_reaches_both_views(stack):
    kubelet, mgr, plugins = stack
    chip_stub = stub_for(kubelet, plugins, "google.com/tpu")
    tray_stub = stub_for(kubelet, plugins, "google.com/tpu-tray")

    chip_stream = iter(chip_stub.ListAndWatch(pb.Empty()))
    tray_stream = iter(tray_stub.ListAndWatch(pb.Empty()))
    assert all(d.health == HEALTHY for d in next(chip_stream).devices)
    assert all(d.health == HEALTHY for d in next(tray_stream).devices)

    mgr.inject("tpu-1", UNHEALTHY)
    chip_update = {d.ID: d.health for d in next(chip_stream).devices}
    tray_update = {d.ID: d.health for d in next(tray_stream).devices}
    # Both plugins observed the same event (single watcher, fanned out).
    assert chip_update["tpu-1"] == UNHEALTHY
    assert chip_update["tpu-0"] == HEALTHY
    assert tray_update["tray-0"] == UNHEALTHY  # tray contains the dead chip


def test_tray_allocation_claims_chips_and_ttl_recovers(stack):
    kubelet, mgr, plugins = stack
    chip_stub = stub_for(kubelet, plugins, "google.com/tpu")
    tray_stub = stub_for(kubelet, plugins, "google.com/tpu-tray")

    chip_stream = iter(chip_stub.ListAndWatch(pb.Empty()))
    next(chip_stream)  # initial, all healthy

    tray_stub.Allocate(
        pb.AllocateRequest(
            container_requests=[pb.ContainerAllocateRequest(devicesIDs=["tray-0"])]
        )
    )
    update = {d.ID: d.health for d in next(chip_stream).devices}
    assert all(h == UNHEALTHY for h in update.values())  # all 4 chips claimed

    # After the claim TTL, the chip view recovers via the lazy sweep.
    deadline = time.monotonic() + 5
    recovered = {}
    while time.monotonic() < deadline:
        recovered = {d.ID: d.health for d in next(chip_stream).devices}
        if all(h == HEALTHY for h in recovered.values()):
            break
    assert all(h == HEALTHY for h in recovered.values())


def test_invalid_multi_container_allocate_leaves_no_orphan_claims(stack):
    import grpc

    kubelet, mgr, plugins = stack
    chip_stub = stub_for(kubelet, plugins, "google.com/tpu")
    tray_stub = stub_for(kubelet, plugins, "google.com/tpu-tray")

    with pytest.raises(grpc.RpcError):
        tray_stub.Allocate(
            pb.AllocateRequest(
                container_requests=[
                    pb.ContainerAllocateRequest(devicesIDs=["tray-0"]),  # valid
                    pb.ContainerAllocateRequest(devicesIDs=["bogus"]),  # invalid
                ]
            )
        )
    # The failed request must not have claimed tray-0's chips.
    resp = next(iter(chip_stub.ListAndWatch(pb.Empty())))
    assert all(d.health == HEALTHY for d in resp.devices)


def test_late_subscriber_sees_prior_health_state(tmp_path):
    """A plugin that starts after a chip already failed must still advertise
    it Unhealthy (fan-out replays latched state on subscribe)."""
    import queue

    from tpu_device_plugin.health import HealthFanout

    mgr = FakeChipManager(n_chips=2)
    mgr.init()
    fanout = HealthFanout(mgr)
    q1 = fanout.subscribe()
    mgr.inject("tpu-0", UNHEALTHY)
    ev = q1.get(timeout=5)
    assert ev.chip_id == "tpu-0"

    q2 = fanout.subscribe()  # late joiner
    ev = q2.get(timeout=5)
    assert ev.chip_id == "tpu-0" and ev.health == UNHEALTHY
    # Recovery reaches both, and a third subscriber sees nothing stale.
    mgr.inject("tpu-0", HEALTHY)
    assert q1.get(timeout=5).health == HEALTHY
    assert q2.get(timeout=5).health == HEALTHY
    q3 = fanout.subscribe()
    with pytest.raises(queue.Empty):
        q3.get(timeout=0.3)
    for q in (q1, q2, q3):
        fanout.unsubscribe(q)


@pytest.fixture
def live_stack(tmp_path):
    """Mixed stack with probe-driven claim release enabled and a scriptable
    in-use map (the fake's tpuinfo_chips_in_use analog)."""
    kubelet = FakeKubelet(str(tmp_path / "device-plugins"))
    kubelet.start()
    mgr = FakeChipManager(n_chips=4, chips_per_tray=4)
    mgr.init()
    cfg = Config(
        flags=Flags(
            backend="fake",
            topology_strategy="mixed",
            mixed_claim_ttl_secs=0.5,
            mixed_claim_grace_secs=0.0,
            claim_liveness_release=True,
            device_plugin_path=kubelet.plugin_dir,
        )
    )
    strategy = new_topology_strategy(
        cfg,
        ResourceConfig(),
        mgr,
        plugin_dir=kubelet.plugin_dir,
        kubelet_socket=kubelet.socket_path,
        lease_dir=str(tmp_path / "leases"),
    )
    plugins = strategy.get_plugins()
    # Probe on every sweep tick: the test asserts *within seconds* behavior.
    plugins[0]._claims._probe_interval = 0.0
    for p in plugins:
        p.start()
    yield kubelet, mgr, plugins
    for p in plugins:
        p.stop()
    kubelet.stop()


def _chip_view_health(stream):
    return {d.ID: d.health for d in next(stream).devices}


def test_pod_outliving_ttl_keeps_other_view_blocked_then_exit_releases(live_stack):
    """VERDICT next-round item 2, both halves: a workload holding its chips
    past the TTL keeps the overlapping view blocked (claim renewal), and its
    observed exit releases the claim within seconds (not at the TTL)."""
    kubelet, mgr, plugins = live_stack
    chip_stub = stub_for(kubelet, plugins, "google.com/tpu")
    tray_stub = stub_for(kubelet, plugins, "google.com/tpu-tray")

    chip_stream = iter(chip_stub.ListAndWatch(pb.Empty()))
    next(chip_stream)

    # "Pod" opens all four chips: one open handle each.
    mgr.set_in_use({0: 1, 1: 1, 2: 1, 3: 1})
    tray_stub.Allocate(
        pb.AllocateRequest(
            container_requests=[pb.ContainerAllocateRequest(devicesIDs=["tray-0"])]
        )
    )
    update = _chip_view_health(chip_stream)
    assert all(h == UNHEALTHY for h in update.values())

    # Far past the 0.5 s TTL the chip view must STILL be blocked: the live
    # workload renews its claim.
    time.sleep(1.5)
    resp = next(iter(chip_stub.ListAndWatch(pb.Empty())))
    assert all(d.health == UNHEALTHY for d in resp.devices), (
        "live workload's chips were re-advertised through the other view"
    )

    # The pod exits (device handles close): released within seconds.
    mgr.set_in_use({0: 0, 1: 0, 2: 0, 3: 0})
    deadline = time.monotonic() + 5
    recovered = {}
    while time.monotonic() < deadline:
        recovered = _chip_view_health(chip_stream)
        if all(h == HEALTHY for h in recovered.values()):
            break
    assert all(h == HEALTHY for h in recovered.values())


@pytest.fixture
def default_release_stack(tmp_path):
    """Mixed stack at the CHART DEFAULTS for release: hostPID off, so
    claim_liveness_release (zero-count death evidence) is False — the
    claim-lease flock is the only exit signal.  TTL deliberately huge so
    any recovery within seconds proves the flock path, not the TTL."""
    kubelet = FakeKubelet(str(tmp_path / "device-plugins"))
    kubelet.start()
    mgr = FakeChipManager(n_chips=4, chips_per_tray=4)
    mgr.init()
    cfg = Config(
        flags=Flags(
            backend="fake",
            topology_strategy="mixed",
            mixed_claim_ttl_secs=300.0,
            mixed_claim_grace_secs=0.0,
            device_plugin_path=kubelet.plugin_dir,
        )
    )
    lease_dir = str(tmp_path / "leases")
    strategy = new_topology_strategy(
        cfg,
        ResourceConfig(),
        mgr,
        plugin_dir=kubelet.plugin_dir,
        kubelet_socket=kubelet.socket_path,
        lease_dir=lease_dir,
    )
    plugins = strategy.get_plugins()
    plugins[0]._claims._probe_interval = 0.0
    for p in plugins:
        p.start()
    yield kubelet, mgr, plugins, lease_dir
    for p in plugins:
        p.stop()
    kubelet.stop()


def test_claim_lease_releases_exited_pod_without_hostpid(default_release_stack):
    """VERDICT round-2 item 6: with default chart values (hostPID false,
    no zero-count evidence), a workload that declared its lifetime via the
    claim lease is released within a probe interval of its exit — not at
    the (5-minute) TTL."""
    import fcntl
    import os

    from tpu_device_plugin import sharing

    kubelet, mgr, plugins, lease_dir = default_release_stack
    chip_stub = stub_for(kubelet, plugins, "google.com/tpu")
    tray_stub = stub_for(kubelet, plugins, "google.com/tpu-tray")

    chip_stream = iter(chip_stub.ListAndWatch(pb.Empty()))
    next(chip_stream)

    resp = tray_stub.Allocate(
        pb.AllocateRequest(
            container_requests=[pb.ContainerAllocateRequest(devicesIDs=["tray-0"])]
        )
    )
    # The Allocate response carries the claim-lease contract: env pointing
    # at the lease dir, and the dir mounted so the flock crosses pods.
    envs = dict(resp.container_responses[0].envs)
    assert envs[sharing.CLAIM_LEASE_DIR_ENV] == lease_dir
    assert any(m.host_path == lease_dir for m in resp.container_responses[0].mounts)
    update = _chip_view_health(chip_stream)
    assert all(h == UNHEALTHY for h in update.values())

    # "Pod" declares its lifetime: one SHARED claim flock per chip (what
    # workloads.lease.hold_claim_leases does inside the container), plus
    # a time-sliced SIBLING on tpu-0 whose shared flock composes.
    os.makedirs(lease_dir, exist_ok=True)
    fds = []
    for cid in ("tpu-0", "tpu-1", "tpu-2", "tpu-3"):
        fd = os.open(
            sharing.claim_lease_path(lease_dir, cid), os.O_CREAT | os.O_RDWR, 0o666
        )
        fcntl.flock(fd, fcntl.LOCK_SH)
        fds.append(fd)
    sibling = os.open(sharing.claim_lease_path(lease_dir, "tpu-0"), os.O_RDWR)
    fcntl.flock(sibling, fcntl.LOCK_SH)

    # While the flocks are held the chip view stays blocked.
    time.sleep(1.0)
    resp2 = next(iter(chip_stub.ListAndWatch(pb.Empty())))
    assert all(d.health == UNHEALTHY for d in resp2.devices)

    # The first pod exits: the kernel drops its flocks with the fds.  The
    # sibling still holds tpu-0, so that chip must stay claimed while the
    # sibling-free chips release within seconds — 1/60th of the TTL.
    for fd in fds:
        os.close(fd)
    deadline = time.monotonic() + 5
    partial = {}
    while time.monotonic() < deadline:
        partial = _chip_view_health(chip_stream)
        if all(
            h == (UNHEALTHY if cid == "tpu-0" else HEALTHY)
            for cid, h in partial.items()
        ):
            break
    assert partial["tpu-0"] == UNHEALTHY, partial  # sibling still alive
    assert all(h == HEALTHY for cid, h in partial.items() if cid != "tpu-0")

    # The sibling exits too: the last chip recovers.
    os.close(sibling)
    deadline = time.monotonic() + 5
    recovered = {}
    while time.monotonic() < deadline:
        recovered = _chip_view_health(chip_stream)
        if all(h == HEALTHY for h in recovered.values()):
            break
    assert all(h == HEALTHY for h in recovered.values()), recovered


def test_stale_claim_file_cleared_at_allocate(default_release_stack):
    """A predecessor's leftover (unheld) claim file must not read as the
    NEW pod's death: Allocate clears stale files, so a non-cooperative
    successor falls back to the TTL instead of being released."""
    import os

    from tpu_device_plugin import sharing

    kubelet, mgr, plugins, lease_dir = default_release_stack
    tray_stub = stub_for(kubelet, plugins, "google.com/tpu-tray")
    chip_stub = stub_for(kubelet, plugins, "google.com/tpu")

    # Leftover from a dead previous workload.
    os.makedirs(lease_dir, exist_ok=True)
    for cid in ("tpu-0", "tpu-1", "tpu-2", "tpu-3"):
        open(sharing.claim_lease_path(lease_dir, cid), "w").close()

    tray_stub.Allocate(
        pb.AllocateRequest(
            container_requests=[pb.ContainerAllocateRequest(devicesIDs=["tray-0"])]
        )
    )
    for cid in ("tpu-0", "tpu-1", "tpu-2", "tpu-3"):
        assert not os.path.exists(sharing.claim_lease_path(lease_dir, cid))

    # The new "pod" never declares itself; sweeps must NOT release it
    # early (probe says unknown -> TTL fallback, which is far away).
    time.sleep(1.0)
    resp = next(iter(chip_stub.ListAndWatch(pb.Empty())))
    assert all(d.health == UNHEALTHY for d in resp.devices)


def test_chip_allocation_marks_tray_unhealthy(stack):
    kubelet, mgr, plugins = stack
    chip_stub = stub_for(kubelet, plugins, "google.com/tpu")
    tray_stub = stub_for(kubelet, plugins, "google.com/tpu-tray")

    tray_stream = iter(tray_stub.ListAndWatch(pb.Empty()))
    next(tray_stream)

    chip_stub.Allocate(
        pb.AllocateRequest(
            container_requests=[pb.ContainerAllocateRequest(devicesIDs=["tpu-2"])]
        )
    )
    update = {d.ID: d.health for d in next(tray_stream).devices}
    assert update["tray-0"] == UNHEALTHY

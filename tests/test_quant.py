"""Weight-only int8 quantization (workloads/quant.py): roundtrip error,
tree shape, and the quantized serving path end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np

from workloads.model import ModelConfig, init_params
from workloads.quant import (
    dequantize,
    is_quantized,
    quantize,
    quantize_params,
    tree_bytes,
)

CONFIG = ModelConfig(max_seq_len=32, n_layers=2, dtype=jnp.float32)


def test_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
    q = quantize(w)
    assert q["q8"].dtype == jnp.int8
    # Symmetric int8: error per element <= half a quantization step.
    step = np.asarray(q["scale"])
    err = np.abs(np.asarray(dequantize(q)) - np.asarray(w))
    assert (err <= step / 2 + 1e-7).all()


def test_zero_channel_is_stable():
    w = jnp.zeros((4, 8)).at[0].set(1.0)
    q = quantize(w, axis=-1)
    np.testing.assert_allclose(np.asarray(dequantize(q)), np.asarray(w))


def test_quantize_params_tree_shape_and_bytes():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    layer = qparams["layers"][0]
    assert is_quantized(layer["wqkv"]) and is_quantized(qparams["unembed"])
    assert not is_quantized(layer["ln1"])
    assert not is_quantized(qparams["embed"])
    # Matmul weights dominate this tree; int8 + scales must land well
    # under half the float32 original.
    assert tree_bytes(qparams) < 0.5 * tree_bytes(params)


def test_quantized_decode_logits_close_and_generate_runs():
    from workloads.generate import decode_step, generate, init_kv_cache

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 8), 0, CONFIG.vocab_size, jnp.int32
    )
    cache_f = init_kv_cache(CONFIG, 2, 8)
    cache_q = init_kv_cache(CONFIG, 2, 8)
    for pos in range(8):
        logits_f, cache_f = decode_step(
            params, cache_f, tokens[:, pos], jnp.int32(pos), CONFIG
        )
        logits_q, cache_q = decode_step(
            qparams, cache_q, tokens[:, pos], jnp.int32(pos), CONFIG
        )
        # int8 weights perturb logits by ~the quantization noise, far
        # below the logits' own spread.
        denom = float(np.abs(np.asarray(logits_f)).max()) or 1.0
        rel = float(np.abs(np.asarray(logits_q - logits_f)).max()) / denom
        assert rel < 0.08, (pos, rel)

    out = generate(qparams, tokens[:, :4], CONFIG, max_new_tokens=6)
    assert out.shape == (2, 6)
    assert (np.asarray(out) >= 0).all()
    assert (np.asarray(out) < CONFIG.vocab_size).all()


def test_gqa_tree_quantizes():
    gqa = ModelConfig(
        max_seq_len=32, n_layers=1, n_heads=4, n_kv_heads=2,
        dtype=jnp.float32,
    )
    qparams = quantize_params(init_params(gqa, jax.random.PRNGKey(0)))
    layer = qparams["layers"][0]
    assert is_quantized(layer["wq"]) and is_quantized(layer["wkv"])


def test_quantized_tree_checkpoints_roundtrip(tmp_path):
    """The int8 serving tree (plain pytree of q8/scale leaves) rides the
    orbax checkpointer unchanged — a quantized model can be shipped as a
    checkpoint."""
    from workloads.checkpoint import TrainCheckpointer

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    ckpt = TrainCheckpointer(str(tmp_path / "q"))
    ckpt.save(1, qparams)
    ckpt.wait()
    like = jax.tree.map(
        lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype), qparams
    )
    restored = ckpt.restore_latest(like=like)
    assert restored["layers"][0]["wqkv"]["q8"].dtype == jnp.int8
    np.testing.assert_array_equal(
        np.asarray(restored["layers"][0]["wqkv"]["q8"]),
        np.asarray(qparams["layers"][0]["wqkv"]["q8"]),
    )
    ckpt.close()

"""Batched admission (workloads/serve.py): all admissions in one step()
coalesce into ONE multi-row prefill sweep and ONE fused first-token
readback, with token streams BIT-IDENTICAL to serial admission — across
mixed prompt lengths, chunked prefill, prefix-cache hits, LoRA adapters,
fan-out groups, sampling, and speculative serving."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)


def _mixed_requests(n, vocab, rng_seed=7, p_lo=3, p_hi=11):
    rng = np.random.default_rng(rng_seed)
    out = []
    for _ in range(n):
        plen = int(rng.integers(p_lo, p_hi))
        new = int(rng.integers(2, 25))
        out.append((list(rng.integers(0, vocab, plen)), new))
    return out


def _serve_both(params, requests, config=CONFIG, submit=None, **kw):
    """Run the same stream through a serial-admission and a
    batched-admission engine; return (serial_out, batched_out, engines)."""
    outs, engines = [], []
    for batched in (False, True):
        engine = ServeEngine(
            params, config, batched_admission=batched, **kw
        )
        if submit is not None:
            rids = submit(engine)
        else:
            rids = [engine.submit(p, n) for p, n in requests]
        served = engine.run()
        outs.append({r: served[r] for r in rids})
        engines.append(engine)
    return outs[0], outs[1], engines


def _assert_identical(serial, batched):
    assert set(serial) == set(batched)
    for rid in serial:
        assert serial[rid] == batched[rid], (
            f"{rid}: serial {serial[rid]} != batched {batched[rid]}"
        )


def test_batched_matches_serial_greedy_mixed_lengths():
    """The core parity pin: mixed prompt lengths (including prompts
    longer than the bucket, so rows finish in DIFFERENT chunks of the
    shared sweep) emit bit-identical greedy streams."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    requests = _mixed_requests(7, CONFIG.vocab_size, rng_seed=3, p_lo=3, p_hi=20)
    serial, batched, (es, eb) = _serve_both(
        params, requests, slots=3, page_size=4, prompt_bucket=8, chunk=4,
    )
    _assert_identical(serial, batched)
    assert es.ctrl.used_pages == 0 and eb.ctrl.used_pages == 0
    # Same per-row accounting through a different execution shape.
    assert es.prefill_tokens == eb.prefill_tokens
    assert es.prefills_run == eb.prefills_run
    assert eb.prefill_sweeps > 0
    assert eb.admission_readbacks < es.admission_readbacks


def test_batched_matches_serial_sampling_stream():
    """Bit-identical SAMPLED streams: the fused sampler draws each row
    under its own key, in the serial path's exact _next_key() order."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    requests = _mixed_requests(6, CONFIG.vocab_size, rng_seed=5)
    serial, batched, _ = _serve_both(
        params, requests, slots=3, page_size=4, prompt_bucket=8, chunk=4,
        temperature=0.8, top_k=20, top_p=0.9, rng=jax.random.PRNGKey(11),
    )
    _assert_identical(serial, batched)


def test_batched_admission_dispatch_counts():
    """The structural claim: admitting R requests in one step issues
    exactly ONE prefill sweep (one dispatch for single-bucket prompts)
    and ONE first-token readback — not R of each."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    R = 4
    engine = ServeEngine(
        params, CONFIG, slots=R, page_size=4, prompt_bucket=8, chunk=4,
    )
    rng = np.random.default_rng(9)
    for _ in range(R):
        engine.submit(list(rng.integers(0, CONFIG.vocab_size, 7)), 4)
    engine.step()  # all R admit here
    assert engine.prefill_sweeps == 1
    assert engine.prefill_dispatches == 1
    assert engine.admission_readbacks == 1
    assert engine.prefills_run == R
    # The serial reference really pays R of each.
    serial = ServeEngine(
        params, CONFIG, slots=R, page_size=4, prompt_bucket=8, chunk=4,
        batched_admission=False,
    )
    rng = np.random.default_rng(9)
    for _ in range(R):
        serial.submit(list(rng.integers(0, CONFIG.vocab_size, 7)), 4)
    serial.step()
    assert serial.prefill_dispatches == R
    assert serial.admission_readbacks == R


def test_batched_ragged_sweep_is_one_sweep():
    """Rows of different chunk counts still ride ONE sweep: its dispatch
    count is the LONGEST row's chunk count, not the sum over rows."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=3, page_size=4, prompt_bucket=8, chunk=4,
    )
    rng = np.random.default_rng(13)
    for plen in (5, 14, 23):  # 1, 2 and 3 bucket chunks
        engine.submit(list(rng.integers(0, CONFIG.vocab_size, plen)), 3)
    engine.step()
    assert engine.prefill_sweeps == 1
    assert engine.prefill_dispatches == 3  # ceil(23 / 8)
    assert engine.admission_readbacks == 1


def test_batched_matches_serial_prefix_cache():
    """Prefix-cache hits ride the shared sweep (row_start guards their
    shared pages from the scatter): identical tokens AND identical
    prefill-compute accounting, including same-step repeated prompts
    hitting the promissory insert."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    rng = np.random.default_rng(17)
    common = list(rng.integers(0, CONFIG.vocab_size, 19))
    fresh = list(rng.integers(0, CONFIG.vocab_size, 9))

    def submit(engine):
        engine.submit(common, 6)
        engine.run()  # seed the cache (drained before the compared run)
        rids = [engine.submit(common, 4)]          # cache hit
        rids.append(engine.submit(fresh, 5))       # miss, same step
        rids.append(engine.submit(common[:12], 4))  # partial-prefix hit
        return rids

    serial, batched, (es, eb) = _serve_both(
        params, None, submit=submit, slots=3, page_size=4, prompt_bucket=8,
        chunk=4, prefix_cache=True,
    )
    _assert_identical(serial, batched)
    assert es.prefill_tokens == eb.prefill_tokens
    assert es.prefix.hits == eb.prefix.hits


def test_batched_same_step_repeated_prompt_shares_pages():
    """Two identical prompts admitted in the SAME step share the first
    row's pages through the promissory insert — the sweep's chunk order
    writes them before the second row's chunks read them."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    prompt = list(range(2, 21))  # 19 tokens, 4 full pages
    outs = {}
    for batched in (False, True):
        engine = ServeEngine(
            params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4,
            prefix_cache=True, batched_admission=batched,
        )
        r1, r2 = engine.submit(prompt, 5), engine.submit(prompt, 5)
        served = engine.run()
        outs[batched] = (served[r1], served[r2], engine.prefill_tokens,
                         engine.prefix.hits)
    assert outs[False] == outs[True]
    assert outs[True][3] >= 4  # the second row really hit


def test_batched_matches_serial_multi_lora():
    """Per-row adapter indices ride the sweep as data: every tenant gets
    its serial tokens, including base (adapter-less) rows in the same
    sweep as adapted ones."""
    from workloads.multi_lora import synthetic_adapters

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    adapters = synthetic_adapters(CONFIG, 2, rank=4, seed=21)
    names = [None] + sorted(adapters)
    requests = _mixed_requests(6, CONFIG.vocab_size, rng_seed=23)

    def submit(engine):
        return [
            engine.submit(p, n, adapter=names[i % len(names)])
            for i, (p, n) in enumerate(requests)
        ]

    serial, batched, _ = _serve_both(
        params, None, submit=submit, slots=3, page_size=4, prompt_bucket=8,
        chunk=4, adapters=adapters,
    )
    _assert_identical(serial, batched)


def test_batched_matches_serial_fanout_groups():
    """Fan-out groups under batched admission: the first member's sweep
    row becomes the group's cached logits, later members copy the tail
    page after the sweep — same tokens, same single prefill."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))

    def submit(engine):
        rids = engine.submit_fanout(list(range(2, 12)), 6, n_samples=3)
        rids += [engine.submit([5, 4, 3, 2], 4)]
        return rids

    serial, batched, (es, eb) = _serve_both(
        params, None, submit=submit, slots=4, page_size=4,
        prompt_bucket=12, chunk=4,
    )
    _assert_identical(serial, batched)
    assert es.prefills_run == eb.prefills_run == 2  # group once + lone req
    assert eb.ctrl.used_pages == 0


def test_batched_matches_serial_speculative():
    """The draft pools prefill through the same batched sweep; the
    speculative rounds then commit identical tokens."""
    draft_config = ModelConfig(
        max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
        dtype=jnp.float32,
    )
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(draft_config, jax.random.PRNGKey(7))
    requests = _mixed_requests(5, CONFIG.vocab_size, rng_seed=29)
    serial, batched, _ = _serve_both(
        params, requests, slots=2, page_size=4, prompt_bucket=8,
        draft_params=draft, draft_config=draft_config, gamma=3,
    )
    _assert_identical(serial, batched)


def test_batched_matches_serial_pipelined():
    """Pipelined stepping composes: freshly admitted rows inject their
    host-side first token exactly as under serial admission."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    requests = _mixed_requests(6, CONFIG.vocab_size, rng_seed=31)
    serial, batched, _ = _serve_both(
        params, requests, slots=2, page_size=4, prompt_bucket=12, chunk=4,
        pipelined=True,
    )
    _assert_identical(serial, batched)


def test_batched_instant_retirement_and_backpressure():
    """max_new_tokens=1 retirements roll their tentative page commitment
    back and re-plan within the same step (the serial pass's freed-
    budget-within-a-pass behavior) — same admissions, same tokens,
    under a pool sized for ~one request."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    rng = np.random.default_rng(37)
    requests = [
        (list(rng.integers(0, CONFIG.vocab_size, 7)), 1 if i % 2 else 8)
        for i in range(6)
    ]
    serial, batched, (es, eb) = _serve_both(
        params, requests, slots=2, page_size=4, prompt_bucket=8, chunk=4,
        n_pages=8,
    )
    _assert_identical(serial, batched)
    assert eb.ctrl.used_pages == 0


def test_batched_matches_serial_eos_at_admission():
    """A first token that IS the eos retires at admission on both paths
    (emission folded into the post-readback loop)."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    probe = ServeEngine(params, CONFIG, slots=1, page_size=4, prompt_bucket=8)
    rid = probe.submit([1, 2, 3], 1)
    eos = probe.run()[rid][0]  # the token the prompt emits first
    requests = [([1, 2, 3], 10), ([4, 5, 6], 6)]

    def submit(engine):
        return [
            engine.submit(p, n, eos_token=eos) for p, n in requests
        ]

    serial, batched, _ = _serve_both(
        params, None, submit=submit, slots=2, page_size=4, prompt_bucket=8,
        chunk=4,
    )
    _assert_identical(serial, batched)
    assert len(serial[list(serial)[0]]) == 1  # really retired at admission


def test_batched_matches_serial_under_tp_mesh():
    """The explicitly-sharded TP chunked-prefill program family emits
    the same tokens as serial TP admission."""
    from workloads.train import make_mesh

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    mesh = make_mesh(2, model_parallel=2)
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    requests = _mixed_requests(4, CONFIG.vocab_size, rng_seed=41, p_hi=16)
    serial, batched, (es, eb) = _serve_both(
        params, requests, slots=2, page_size=4, prompt_bucket=8, chunk=4,
        mesh=mesh,
    )
    _assert_identical(serial, batched)
    assert eb.prefill_sweeps > 0


def test_completed_ring_bounded_and_drainable():
    """engine.completed is a bounded deque under ``completed_limit`` and
    drain_completed() hands the window back — the unbounded-growth fix."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4,
        completed_limit=3,
    )
    for i in range(5):
        engine.submit([1 + i, 2, 3], 2)
    engine.run()
    assert len(engine.completed) == 3  # oldest two evicted by maxlen
    drained = engine.drain_completed()
    assert len(drained) == 3 and len(engine.completed) == 0
    # Unbounded default still collects everything.
    engine2 = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4,
    )
    for i in range(4):
        engine2.submit([1 + i, 2], 2)
    engine2.run()
    assert len(engine2.completed) == 4

"""Ulysses all-to-all sequence parallelism vs dense attention, 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from workloads.ops.ulysses import ulysses_attention

from .test_flash_attention import make_qkv, naive_attention


@pytest.fixture
def seq_mesh():
    devices = np.array(jax.devices()[:8])
    assert devices.size == 8, "conftest provides an 8-device CPU mesh"
    return Mesh(devices, ("seq",))


@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense(seq_mesh, causal):
    q, k, v = make_qkv(batch=2, seq=64, heads=8, head_dim=16)
    out = ulysses_attention(q, k, v, seq_mesh, causal=causal)
    expected = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_gradients_match_dense(seq_mesh):
    q, k, v = make_qkv(batch=1, seq=32, heads=8, head_dim=16)

    def loss_ulysses(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, seq_mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(naive_attention(q, k, v, True) ** 2)

    got = jax.grad(loss_ulysses, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=1e-4, err_msg=f"d{name}"
        )


def test_matches_ring(seq_mesh):
    """Both sequence-parallel formulations agree on the same inputs."""
    from workloads.ops.ring import ring_attention

    q, k, v = make_qkv(batch=2, seq=64, heads=8, head_dim=16)
    out_u = ulysses_attention(q, k, v, seq_mesh)
    out_r = ring_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r), atol=2e-5)


def test_jit_and_seq_sharded_inputs(seq_mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = make_qkv(batch=2, seq=64, heads=8, head_dim=16)
    sharding = NamedSharding(seq_mesh, P(None, "seq", None, None))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, seq_mesh))(q, k, v)
    assert out.sharding.spec == P(None, "seq", None, None)
    expected = naive_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_rejects_indivisible_heads(seq_mesh):
    q, k, v = make_qkv(batch=1, seq=64, heads=2, head_dim=16)  # 2 heads, 8 devs
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, seq_mesh)


def test_rejects_indivisible_seq(seq_mesh):
    q, k, v = make_qkv(batch=1, seq=60, heads=8, head_dim=16)
    with pytest.raises(ValueError, match="seq"):
        ulysses_attention(q, k, v, seq_mesh)


def test_seq_parallel_train_step_ulysses():
    """The full training step runs with the Ulysses core and matches the
    dense forward's loss scale."""
    from workloads.model import ModelConfig
    from workloads.train import (
        make_seq_parallel_train_step,
        make_sp_mesh,
        make_train_state,
        synthetic_batch,
    )

    config = ModelConfig(max_seq_len=33, n_layers=1)  # n_heads=4, seq axis 4
    mesh = make_sp_mesh(8, seq_parallel=4)
    (params, opt_state), optimizer = make_train_state(config, mesh)
    step = make_seq_parallel_train_step(config, mesh, optimizer, attention="ulysses")
    tokens = synthetic_batch(config, batch_size=4)
    params, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))


def test_seq_parallel_train_step_rejects_bad_head_split():
    from workloads.model import ModelConfig
    from workloads.train import make_seq_parallel_train_step, make_sp_mesh

    config = ModelConfig(max_seq_len=33, n_layers=1)  # n_heads=4
    mesh = make_sp_mesh(8, seq_parallel=8)

    class _Opt:  # never reached; the check fires first
        pass

    with pytest.raises(ValueError, match="n_heads"):
        make_seq_parallel_train_step(config, mesh, _Opt(), attention="ulysses")

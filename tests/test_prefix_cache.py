"""Cross-request prefix caching (workloads/paged.py PrefixCache +
ServeEngine prefix_cache=True): repeated prompts reuse k/v pages and skip
their prefill compute; tokens stay exactly the uncached tokens; the
cache yields pages back under pool pressure (LRU, index-only)."""

import jax
import jax.numpy as jnp
import numpy as np

from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.paged import PagePool, PrefixCache
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)


def _engine(params, config=CONFIG, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("prompt_bucket", 8)
    kw.setdefault("chunk", 4)
    return ServeEngine(params, config, prefix_cache=True, **kw)


def test_prefix_cache_unit_chain_and_eviction():
    """PrefixCache alone: chain keys share only true common prefixes;
    eviction frees LRU index-only pages and skips shared ones."""
    ctrl = PagePool(n_pages=8, page_size=4)
    cache = PrefixCache(ctrl)
    t_a = ctrl.allocate("a", 12)  # 3 pages for tokens A
    tokens_a = list(range(12))
    cache.insert(tokens_a, t_a)
    assert cache.cached_pages == 3
    # Full-prefix hit, capped.
    assert cache.lookup(tokens_a, 3) == t_a
    assert cache.lookup(tokens_a, 2) == t_a[:2]
    # A prompt sharing only the first block hits one page.
    tokens_b = tokens_a[:4] + [99, 98, 97, 96]
    assert cache.lookup(tokens_b, 2) == t_a[:1]
    # A different first block misses entirely.
    assert cache.lookup([7] * 8, 2) == []
    # Release the sequence: pages become index-only (refcount 1).
    ctrl.release("a")
    assert ctrl.used_pages == 3
    # Evict 2: LRU entries go first; the pages return to the free list.
    assert cache.evict(2) == 2
    assert ctrl.used_pages == 1
    cache.clear()
    assert ctrl.used_pages == 0 and cache.cached_pages == 0


def test_second_request_reuses_prefix_tokens_identical():
    """The parity pin: with the cache on, a repeated prompt emits exactly
    the tokens generate() produces, while its prefill computes only the
    un-cached remainder."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = _engine(params)
    prompt = list(range(1, 20))  # 19 tokens: 4 full pages, bucket=8 -> bp=2
    r1 = engine.submit(prompt, 8)
    engine.run()
    first_prefill = engine.prefill_tokens
    assert first_prefill == 19
    r2 = engine.submit(prompt, 8)
    served = engine.run()
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=8
    )
    np.testing.assert_array_equal(np.asarray(served[r2]), np.asarray(want[0]))
    # Hits capped to bucket-aligned pages: 4 full pages of 19 tokens,
    # cap (19-1)//4=4 floored to bp-multiple 4 -> 16 tokens skipped.
    assert engine.prefill_tokens - first_prefill == 3
    assert engine.prefix.hits >= 4


def test_shared_512_token_prefix_cuts_prefill_compute_4x():
    """VERDICT r4 item: the second request with a shared 512-token prefix
    runs >= ~4x less prefill compute (here 64x: only the 8-token suffix
    forwards; prefill_tokens counts tokens actually forwarded)."""
    config = ModelConfig(max_seq_len=640, n_layers=1, dtype=jnp.float32)
    params = init_params(config, jax.random.PRNGKey(0))
    engine = ServeEngine(
        params, config, slots=2, page_size=16, prompt_bucket=64, chunk=16,
        prefix_cache=True,
    )
    rng = np.random.default_rng(3)
    prefix = list(rng.integers(0, config.vocab_size, 512))
    a = engine.submit(prefix + [1, 2, 3, 4, 5, 6, 7, 8], 4)
    engine.run()
    first = engine.prefill_tokens
    assert first == 520
    b = engine.submit(prefix + [11, 12, 13, 14, 15, 16, 17, 18], 4)
    served = engine.run()
    second = engine.prefill_tokens - first
    assert second * 4 <= first, (first, second)  # >= 4x less (actually 65x)
    assert second == 8
    # And the tokens are exactly the uncached engine's.
    clean = ServeEngine(
        params, config, slots=2, page_size=16, prompt_bucket=64, chunk=16,
    )
    b2 = clean.submit(prefix + [11, 12, 13, 14, 15, 16, 17, 18], 4)
    want = clean.run()[b2]
    assert served[b] == want


def test_eviction_under_pressure_keeps_serving():
    """A pool sized for ~one request still serves a stream with the cache
    on: admissions evict index-only pages on demand, and evicted prefixes
    simply re-prefill (miss, not failure)."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    # slots=1 so max_pages default sizes the pool to ONE request.
    engine = ServeEngine(
        params, CONFIG, slots=1, page_size=4, prompt_bucket=8, chunk=4,
        prefix_cache=True,
    )
    rng = np.random.default_rng(11)
    prompts = [list(rng.integers(0, CONFIG.vocab_size, 12)) for _ in range(3)]
    outs = {}
    for p in prompts + prompts:  # replay: some hit, some re-prefill
        rid = engine.submit(p, 6)
        outs[rid] = (p, engine.run()[rid])
    for rid, (p, got) in outs.items():
        want = generate(
            params, jnp.asarray([p], jnp.int32), CONFIG, max_new_tokens=6
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want[0]))
    # The cache held pages between requests but never broke an admission.
    assert engine.ctrl.used_pages == engine.prefix.cached_pages
    engine.prefix.clear()
    assert engine.ctrl.used_pages == 0


def test_prefix_cache_composes_with_speculative():
    """Prefix reuse under speculative serving: the draft's cached pages
    carry its own k/v from the original prefill, so a repeated prompt
    skips BOTH models' prefill and still emits the target's greedy
    tokens."""
    draft_config = ModelConfig(
        max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
        dtype=jnp.float32,
    )
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(draft_config, jax.random.PRNGKey(7))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        draft_params=draft, draft_config=draft_config, gamma=3,
        prefix_cache=True,
    )
    prompt = list(range(3, 17))  # 14 tokens
    r1 = engine.submit(prompt, 10)
    engine.run()
    first = engine.prefill_tokens
    r2 = engine.submit(prompt, 10)
    served = engine.run()
    assert engine.prefill_tokens - first < first
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=10
    )
    np.testing.assert_array_equal(np.asarray(served[r2]), np.asarray(want[0]))


def test_prefix_cache_composes_with_tp_mesh():
    """Sharded pools change nothing: page indices are mesh-agnostic, so
    prefix hits skip the TP prefill too and tokens match single-device."""
    from workloads.train import make_mesh

    mesh = make_mesh(2, model_parallel=2)
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = _engine(params, mesh=mesh)
    prompt = list(range(2, 15))
    r1 = engine.submit(prompt, 6)
    engine.run()
    first = engine.prefill_tokens
    r2 = engine.submit(prompt, 6)
    served = engine.run()
    assert engine.prefill_tokens - first < len(prompt)
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=6
    )
    np.testing.assert_array_equal(np.asarray(served[r2]), np.asarray(want[0]))

"""The conv-net example workload (workloads/vision.py): shapes, sharded
training convergence on the virtual device mesh, and the CLI entry."""

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from workloads.vision import (
    VisionConfig,
    forward,
    init_params,
    make_train_step,
    param_specs,
    synthetic_batch,
)


def test_forward_shapes_and_dtype():
    config = VisionConfig()
    params = init_params(config, jax.random.PRNGKey(0))
    images, _ = synthetic_batch(config, batch=4)
    logits = forward(params, images, config)
    assert logits.shape == (4, config.n_classes)
    assert logits.dtype == jnp.float32  # loss head stays f32


def test_synthetic_labels_cover_classes():
    config = VisionConfig()
    _, labels = synthetic_batch(config, batch=256)
    assert labels.min() >= 0 and labels.max() < config.n_classes
    # argmax over iid random probes is near-uniform: 256 samples must
    # populate (nearly) all 10 classes, not collapse to a couple.
    assert len(set(labels.tolist())) >= config.n_classes - 1


def test_training_reduces_loss_on_data_mesh():
    config = VisionConfig()
    mesh = Mesh(jax.devices(), axis_names=("data",))
    from workloads.train import make_sharded_train_state

    (params, opt_state), optimizer = make_sharded_train_state(
        mesh,
        lambda: init_params(config, jax.random.PRNGKey(0)),
        param_specs(),
        optimizer=optax.adamw(1e-3),
    )
    step = make_train_step(config, mesh, optimizer)
    images, labels = synthetic_batch(config, batch=64, seed=0)
    first = last = None
    for s in range(30):
        params, opt_state, loss = step(params, opt_state, images, labels)
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < first * 0.7, (first, last)


def test_cli_entry():
    from workloads.vision import main

    assert main(["--steps", "3", "--batch-size", "16"]) == 0


def test_cli_rejects_zero_steps(capsys):
    import pytest

    from workloads.vision import main

    with pytest.raises(SystemExit) as exc:
        main(["--steps", "0"])
    assert exc.value.code != 0

"""Self-healing fleet contracts (workloads/supervisor.py +
workloads/backoff.py): a FleetSupervisor watches the fleet's replica
states and resurrects failed replicas on their chip slot.

The pinned contracts: a crashed replica respawns WITHOUT operator
intervention and the fleet returns to its pre-fault alive count, with
ok streams bit-identical to the dense oracle through the failover; the
half-open canary probe gates rejoin on bit-identity (a diverging
replacement never rejoins); restart scheduling is exponential, capped,
deterministic per (seed, slot) and escalates per consecutive failure;
K failures inside the sliding window quarantine the slot (the
replica_respawn repeat-crash schedules) until a manual clear(), which
rejoins via the probe; live HealthFanout marks defer resurrection
uncharged; capacity-aware admission sheds (typed QueueFull) while
degraded and restores with capacity; supervisor counters mirror to the
Prometheus bridge."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
from tpu_device_plugin.device import HealthEvent
from workloads.backoff import Backoff
from workloads.errors import QueueFull
from workloads.faults import FaultInjector, crash_loop_schedule
from workloads.fleet import DEAD, Fleet
from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine
from workloads.supervisor import (
    BACKOFF,
    QUARANTINED,
    SERVING,
    FleetSupervisor,
    make_engine_factory,
)

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
PARAMS = init_params(CONFIG, jax.random.PRNGKey(0))
ENGINE_KW = dict(slots=2, page_size=4, prompt_bucket=8)
PROBE = ([1, 2, 3], 4)

# Tiny, jitter-free backoff so tests converge in milliseconds while the
# schedule stays exactly predictable.
FAST = Backoff(base_s=1e-3, factor=2.0, max_s=8e-3, jitter=0.0)


def _engine(**kw):
    base = dict(ENGINE_KW)
    base.update(kw)
    return ServeEngine(PARAMS, CONFIG, **base)


def _fleet(n=2, **fleet_kw):
    fleet_kw.setdefault("chip_ids", [f"chip-{i}" for i in range(n)])
    fleet_kw.setdefault("hang_timeout_s", None)
    return Fleet([_engine() for _ in range(n)], **fleet_kw)


def _supervised(n=2, *, fleet_kw=None, **sup_kw):
    fleet = _fleet(n, **(fleet_kw or {}))
    factory, oracle = make_engine_factory(
        PARAMS, CONFIG, engine_kw=ENGINE_KW, probe=PROBE
    )
    sup_kw.setdefault("backoff", FAST)
    sup_kw.setdefault("probe", PROBE)
    sup_kw.setdefault("probe_oracle", oracle)
    return FleetSupervisor(fleet, factory, **sup_kw), fleet


def _oracle(prompt, new):
    return [int(t) for t in np.asarray(generate(
        PARAMS, jnp.asarray([prompt], jnp.int32), CONFIG,
        max_new_tokens=new,
    )[0])]


def _prompts(seed, n, new_lo=4, new_hi=12):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        plen = int(rng.integers(1, 20))
        prompt = [int(t) for t in rng.integers(0, CONFIG.vocab_size, plen)]
        out.append((prompt, int(rng.integers(new_lo, new_hi))))
    return out


def _assert_no_leaks(fleet):
    for rep in fleet.replicas:
        if rep.state == DEAD:
            continue
        e = rep.engine
        assert not e._occupied.any(), rep.index
        assert e._committed_pages == 0, rep.index
        pinned = e.prefix.cached_pages if e.prefix is not None else 0
        assert e.ctrl.used_pages == pinned, rep.index
        assert not rep.rids, rep.index


# ---- backoff policy ------------------------------------------------------


def test_backoff_escalates_caps_and_jitters_deterministically():
    b = Backoff(base_s=0.5, factor=2.0, max_s=4.0, jitter=0.0)
    assert [b.delay(k) for k in range(5)] == [0.5, 1.0, 2.0, 4.0, 4.0]
    j = Backoff(base_s=0.5, factor=2.0, max_s=4.0, jitter=0.2, seed=3)
    # Jitter is additive within [0, jitter*delay], pure per (seed,
    # attempt): same inputs, same delay; a different seed decorrelates.
    for k in range(6):
        d = j.delay(k)
        base = b.delay(k)
        assert base <= d <= base * 1.2, (k, d)
        assert d == j.delay(k)
    other = Backoff(base_s=0.5, factor=2.0, max_s=4.0, jitter=0.2, seed=4)
    assert [j.delay(k) for k in range(6)] != [
        other.delay(k) for k in range(6)
    ]
    # Huge attempts stay at the cap instead of overflowing.
    assert b.delay(10_000) == 4.0
    # Interruptible: a pre-set event returns immediately, flagged.
    import threading

    ev = threading.Event()
    ev.set()
    slow = Backoff(base_s=30.0, max_s=30.0, jitter=0.0)
    t0 = time.monotonic()
    assert slow.sleep(0, interrupt=ev) is True
    assert time.monotonic() - t0 < 1.0
    for bad in (
        lambda: Backoff(base_s=0.0),
        lambda: Backoff(factor=0.5),
        lambda: Backoff(base_s=2.0, max_s=1.0),
        lambda: Backoff(jitter=1.5),
        lambda: Backoff().delay(-1),
    ):
        with pytest.raises(ValueError):
            bad()


# ---- resurrection --------------------------------------------------------


def test_crash_resurrects_capacity_and_streams_bit_identical():
    """The headline acceptance contract: a mid-stream replica crash
    with the supervisor armed — the fleet returns to its pre-fault
    alive count without operator intervention, ok streams stay
    bit-identical to the dense oracle, restore time is recorded, and
    the resurrected replica really serves."""
    n = 2
    sup, fleet = _supervised(
        n, fleet_kw=dict(
            fault_injector=FaultInjector({"replica_crash": 3}),
        ),
    )
    reqs = _prompts(0, 6, new_lo=6)
    rids = [fleet.submit(p, nw) for p, nw in reqs]
    sup.run()
    terminal = {fr.rid: fr.status for fr in fleet.completed}
    assert fleet.replica_crashes == 1
    assert sup.wait_healed(20.0), sup.states()
    alive = [r for r in fleet.replicas if r.state == "active"]
    assert len(alive) == n  # pre-fault capacity, no operator involved
    assert sup.restarts_total == 1
    assert len(sup.restore_ms) == 1 and sup.restore_ms[0] > 0
    assert sup.states() == {"chip-0": SERVING, "chip-1": SERVING}
    for rid, (p, nw) in zip(rids, reqs):
        fr = fleet._reqs[rid]
        ref = _oracle(p, nw)
        if terminal.get(rid) == "ok":
            assert fr.tokens == ref, rid
        else:
            assert fr.tokens == ref[: len(fr.tokens)], rid
    # The respawned replica takes real traffic.
    new_idx = sup.slot_for("chip-0").index
    admitted0 = fleet.replicas[new_idx].engine.requests_admitted
    more = _prompts(1, 4, new_lo=2)
    rids2 = [fleet.submit(p, nw, session="pin") for p, nw in more]
    sup.run()
    assert sum(
        r.engine.requests_admitted for r in fleet.replicas
        if r.state != DEAD
    ) > admitted0
    for rid, (p, nw) in zip(rids2, more):
        assert fleet._reqs[rid].tokens == _oracle(p, nw)
    _assert_no_leaks(fleet)
    fleet.close()


def test_restart_backoff_escalates_per_failure_and_is_deterministic():
    """Failed restarts push the next attempt out exponentially (capped)
    on a schedule that replays exactly for the same (policy seed, chip
    slot) — pinned with a fake clock and an always-failing factory."""
    t = [0.0]

    def run_schedule():
        fleet = _fleet(2, fault_injector=FaultInjector(
            {"replica_crash": 3}
        ))
        boom_factory = lambda slot: (_ for _ in ()).throw(  # noqa: E731
            RuntimeError("no chip")
        )
        sup = FleetSupervisor(
            fleet, boom_factory,
            backoff=Backoff(base_s=1.0, factor=2.0, max_s=8.0,
                            jitter=0.1, seed=5),
            probe=PROBE, probe_oracle=[0],
            crash_loop_k=99, crash_loop_window_s=1e9,
            clock=lambda: t[0],
        )
        for p, nw in _prompts(2, 4):
            fleet.submit(p, nw)
        t[0] = 0.0
        delays = []
        while len(delays) < 5:
            if not fleet.idle:
                fleet.step()
            sup.poll(now=t[0])
            slot = sup.slot_for("chip-0")
            if slot.state == BACKOFF and (
                not delays or slot.next_due - t[0] != delays[-1]
            ):
                if slot.next_due > t[0]:
                    delays.append(slot.next_due - t[0])
                    t[0] = slot.next_due  # jump to the attempt
        fleet.close()
        return delays

    first = run_schedule()
    # Escalates ~2x per consecutive failure (jitter <= 10% never breaks
    # monotonicity at factor 2) and hits the cap band.
    for a, b in zip(first, first[1:-1]):
        assert b > a, first
    assert first[0] <= 1.1 and first[-1] >= 8.0, first
    assert run_schedule() == first  # deterministic replay


def test_probe_divergence_keeps_the_replacement_out():
    """Half-open means half-open: a respawned engine whose canary
    stream diverges from the oracle is discarded (a failed restart),
    and only a bit-identical probe rejoins."""
    sup, fleet = _supervised(
        2, fleet_kw=dict(
            fault_injector=FaultInjector({"replica_crash": 3}),
        ),
    )
    bad_params = init_params(CONFIG, jax.random.PRNGKey(9))
    good_factory = sup.engine_factory
    sup.engine_factory = lambda slot: ServeEngine(
        bad_params, CONFIG, **ENGINE_KW
    )
    for p, nw in _prompts(3, 4):
        fleet.submit(p, nw)
    sup.run()
    deadline = time.monotonic() + 20
    while sup.restart_failures == 0 and time.monotonic() < deadline:
        sup.step()
        time.sleep(0.002)
    assert sup.restart_failures >= 1
    assert sup.slot_for("chip-0").state != SERVING
    assert "probe" in (sup.slot_for("chip-0").reason or "")
    assert sum(1 for r in fleet.replicas if r.state == "active") == 1
    # The good factory heals it — probe passes bit-identically.
    sup.engine_factory = good_factory
    assert sup.wait_healed(20.0), sup.states()
    assert sup.restarts_total == 1
    fleet.close()


def test_crash_loop_quarantines_until_manual_clear_then_rejoins():
    """The make selfheal-check story, pinned step by step: a scripted
    repeat-crash-on-restart (replica_respawn schedule) trips the
    sliding-window detector -> the slot QUARANTINES (no rejoin, no
    further attempts) -> an operator clear() forgives it -> the
    half-open probe rejoins the replica."""
    sup, fleet = _supervised(
        2,
        fleet_kw=dict(fault_injector=FaultInjector({"replica_crash": 3})),
        crash_loop_k=3, crash_loop_window_s=60.0,
        fault_injector=FaultInjector(crash_loop_schedule(2)),
    )
    reqs = _prompts(4, 5, new_lo=6)
    rids = [fleet.submit(p, nw) for p, nw in reqs]
    sup.run()
    deadline = time.monotonic() + 20
    while (
        sup.slot_for("chip-0").state != QUARANTINED
        and time.monotonic() < deadline
    ):
        sup.step()
        time.sleep(0.002)
    slot = sup.slot_for("chip-0")
    # Death + 2 respawn crashes = 3 window failures = quarantine.
    assert slot.state == QUARANTINED, sup.states()
    assert sup.crash_loops == 1
    assert sup.restart_failures == 2
    assert "crash loop" in slot.reason
    assert sup.quarantined == ["chip-0"]
    # Quarantined means OUT: no rejoin however long we step.
    for _ in range(10):
        sup.step()
    assert sum(1 for r in fleet.replicas if r.state == "active") == 1
    assert sup.restarts_total == 0
    # Every request still finished ok on the survivor, oracle-true.
    for rid, (p, nw) in zip(rids, reqs):
        fr = fleet._reqs[rid]
        if fr.status == "ok":
            assert fr.tokens == _oracle(p, nw), rid
    # Manual clear -> half-open probe -> rejoin (the respawn schedule
    # is exhausted, so the next attempt survives).
    sup.clear("chip-0")
    assert sup.wait_healed(20.0), sup.states()
    assert sup.restarts_total == 1
    assert sup.states() == {"chip-0": SERVING, "chip-1": SERVING}
    _assert_no_leaks(fleet)
    fleet.close()


def test_max_restarts_budget_exhaustion_quarantines():
    sup, fleet = _supervised(
        2,
        fleet_kw=dict(
            fault_injector=FaultInjector({"replica_crash": 3}),
        ),
        max_restarts=1,
    )
    for p, nw in _prompts(5, 6, new_lo=8):
        fleet.submit(p, nw)
    sup.run()
    assert sup.wait_healed(20.0)
    assert sup.restarts_total == 1  # first death: within budget
    # The REPLACEMENT dies too (an escaped exception is a crash): the
    # per-slot budget is spent, so the slot quarantines instead of
    # burning restarts forever.
    idx = sup.slot_for("chip-0").index

    def boom():
        raise RuntimeError("chip fell off the bus")

    fleet.replicas[idx].engine.step = boom
    fleet.submit([1, 2], 2)
    sup.run()
    for _ in range(5):
        sup.step()
    slot = sup.slot_for("chip-0")
    assert slot.state == QUARANTINED, sup.states()
    assert "budget" in slot.reason
    assert sup.restarts_total == 1  # no second resurrection
    fleet.close()


def test_single_replica_fleet_parks_queue_through_resurrection():
    """The all-dead edge: when the fleet's ONLY replica crashes
    mid-stream with a supervisor armed, the queue PARKS for the
    replacement (the revival seam) instead of failing terminally with
    'no live replicas remain' — and the replayed stream is
    bit-identical.  Without supervision the loud failure stays."""
    sup, fleet = _supervised(
        1, fleet_kw=dict(
            fault_injector=FaultInjector({"replica_crash": 2}),
        ),
    )
    reqs = _prompts(20, 3, new_lo=8, new_hi=12)
    rids = [fleet.submit(p, nw) for p, nw in reqs]
    deadline = time.monotonic() + 40
    while (
        any(not fleet._reqs[r].done for r in rids)
        and time.monotonic() < deadline
    ):
        sup.step()
        if sup._parked():
            time.sleep(0.001)
    assert fleet.replica_crashes == 1
    assert sup.restarts_total == 1
    for rid, (p, nw) in zip(rids, reqs):
        fr = fleet._reqs[rid]
        assert fr.status == "ok", (rid, fr.status, fr.error)
        assert fr.tokens == _oracle(p, nw), rid
    # A fleet-wide wipeout with NO revival pending still fails loudly.
    fleet.revival_hook = None
    _assert_no_leaks(fleet)
    fleet.close()


# ---- health marks --------------------------------------------------------


def test_health_mark_defers_resurrection_until_cleared():
    """A chip carrying a HealthFanout Unhealthy mark gets no new
    engine: resurrection defers (counted, not escalated) until the
    mark lifts — a sick chip is not a place to put a fresh replica."""
    sup, fleet = _supervised(
        2, fleet_kw=dict(
            fault_injector=FaultInjector({"replica_crash": 3}),
        ),
    )
    sup.note_health([HealthEvent(chip_id="chip-0", health=UNHEALTHY)])
    for p, nw in _prompts(7, 4):
        fleet.submit(p, nw)
    sup.run()
    for _ in range(5):
        sup.step()
        time.sleep(0.003)
    assert fleet.replica_crashes == 1
    assert sup.restarts_total == 0
    assert sup.health_deferrals >= 1
    assert sup.slot_for("chip-0").state == BACKOFF  # deferred, not failed
    assert sup.restart_failures == 0
    # The all-clear lifts the mark; resurrection proceeds.
    sup.note_health([HealthEvent(chip_id="", health=HEALTHY)])
    assert sup.wait_healed(20.0), sup.states()
    assert sup.restarts_total == 1
    fleet.close()


# ---- capacity-aware load shedding ---------------------------------------


def test_capacity_aware_bound_sheds_while_degraded_and_recovers():
    """With max_pending_per_replica the fleet-wide admission bound
    tracks the ACTIVE replica count: full fleet 2x2=4, degraded 1x2=2
    (typed QueueFull sheds the overflow), healed back to 4."""
    sup, fleet = _supervised(
        2,
        fleet_kw=dict(
            fault_injector=FaultInjector({"replica_crash": 3}),
            max_pending_per_replica=2,
        ),
        backoff=Backoff(base_s=5.0, max_s=5.0, jitter=0.0),  # stay down
    )
    assert fleet.admission_bound == 4
    for p, nw in _prompts(8, 4, new_lo=6):
        fleet.submit(p, nw)
    sup.run()  # the crash fires mid-run; requests finish on survivors
    assert fleet.replica_crashes == 1
    assert fleet.admission_bound == 2  # scaled down with capacity
    fleet.submit([1, 2], 4)
    fleet.submit([3, 4], 4)
    with pytest.raises(QueueFull) as exc:
        fleet.submit([5, 6], 4)
    assert "capacity-aware" in str(exc.value)
    assert fleet.queue_rejections == 1
    # Heal now (collapse the deliberate backoff) -> bound restored.
    sup.slot_for("chip-0").next_due = 0.0
    assert sup.wait_healed(20.0), sup.states()
    assert fleet.admission_bound == 4
    fleet.submit([5, 6], 4)  # fits again
    sup.run()
    _assert_no_leaks(fleet)
    fleet.close()


def test_static_max_pending_converts_to_capacity_aware_on_arming():
    fleet = _fleet(2, max_pending=8)
    factory, oracle = make_engine_factory(
        PARAMS, CONFIG, engine_kw=ENGINE_KW, probe=PROBE
    )
    FleetSupervisor(
        fleet, factory, backoff=FAST, probe=PROBE, probe_oracle=oracle
    )
    assert fleet.max_pending is None
    assert fleet.max_pending_per_replica == 4
    assert fleet.admission_bound == 8  # unchanged at full capacity
    fleet.close()


# ---- membership / operator surface --------------------------------------


def test_adopt_forget_and_observer_counters():
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import SupervisorObserver

    reg = Registry()
    obs = SupervisorObserver(name="t")
    obs.bind_registry(reg)
    sup, fleet = _supervised(
        2, fleet_kw=dict(
            # With 3 replicas stepping in index order, crossing 2 is
            # step 1 replica 1 (chip-1) and crossing 4 is step 2
            # replica 2 (chip-2): the forgotten chip and the adopted
            # chip both die.
            fault_injector=FaultInjector({"replica_crash": [2, 4]}),
        ),
        observer=obs,
    )
    # A third replica joins live; adopt() brings it under supervision,
    # forget() stands down for chip-1 (its death then stays dead).
    idx = fleet.add_replica(_engine(), chip_id="chip-2")
    sup.adopt("chip-2", idx)
    sup.forget("chip-1")
    for p, nw in _prompts(9, 6, new_lo=6):
        fleet.submit(p, nw)
    sup.run()  # both scheduled crashes fire (chip-1 and chip-2 die)
    assert sup.wait_healed(20.0), sup.states()
    assert sup.slot_for("chip-0").state == SERVING  # never died
    assert sup.slot_for("chip-1").state == "forgotten"  # stayed down
    assert sup.slot_for("chip-2").state == SERVING  # adopted + healed
    assert sup.restarts_total == 1
    text = reg.render()
    assert f"{PREFIX}_supervisor_restarts_total" in text
    assert 'state="serving",supervisor="t"} 2' in text
    assert f"{PREFIX}_supervisor_restore_seconds_count" in text
    obs.unbind_registry()
    fleet.close()


# ---- the make selfheal-check smoke --------------------------------------


def test_selfheal_smoke():
    """ONE seeded supervisor chaos round — the `make selfheal-check`
    tripwire: scripted crash -> resurrection; scripted crash-loop ->
    quarantine -> manual clear -> probed rejoin; streams oracle-true
    throughout, no leaks, full capacity at the end."""
    sup, fleet = _supervised(
        2,
        fleet_kw=dict(fault_injector=FaultInjector({"replica_crash": 3})),
        crash_loop_k=3, crash_loop_window_s=60.0,
        fault_injector=FaultInjector(crash_loop_schedule(2)),
    )
    reqs = _prompts(11, 6, new_lo=6)
    rids = [fleet.submit(p, nw) for p, nw in reqs]
    sup.run()
    deadline = time.monotonic() + 30
    while (
        sup.slot_for("chip-0").state != QUARANTINED
        and time.monotonic() < deadline
    ):
        sup.step()
        time.sleep(0.002)
    assert sup.slot_for("chip-0").state == QUARANTINED
    assert sup.crash_loops == 1
    sup.clear("chip-0")
    assert sup.wait_healed(30.0), sup.states()
    assert sup.restarts_total == 1
    assert sum(1 for r in fleet.replicas if r.state == "active") == 2
    for rid, (p, nw) in zip(rids, reqs):
        fr = fleet._reqs[rid]
        ref = _oracle(p, nw)
        if fr.status == "ok":
            assert fr.tokens == ref, rid
        else:
            assert fr.tokens == ref[: len(fr.tokens)], rid
    _assert_no_leaks(fleet)
    fleet.close()

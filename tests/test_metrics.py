"""Observability endpoint: registry rendering, HTTP scrape, daemon wiring."""

import queue
import threading
import urllib.request

import pytest

from tpu_device_plugin.metrics import MetricsServer, Registry

from .fake_kubelet import FakeKubelet


def test_registry_counters_and_labels():
    reg = Registry()
    reg.describe("allocations_total", "allocs")
    reg.inc("allocations_total", {"resource": "google.com/tpu"})
    reg.inc("allocations_total", {"resource": "google.com/tpu"}, 2)
    text = reg.render()
    assert 'tpu_device_plugin_allocations_total{resource="google.com/tpu"} 3' in text
    assert "# TYPE tpu_device_plugin_allocations_total counter" in text


def test_counter_precision_past_six_digits():
    # %g-style rendering would flatten 1000001 to "1e+06", breaking rate().
    reg = Registry()
    reg.inc("allocations_total", {}, 1_000_001)
    assert "tpu_device_plugin_allocations_total 1000001" in reg.render()
    reg2 = Registry()
    reg2.inc("request_seconds_sum", {}, 123456.789012)
    assert "123456.789012" in reg2.render()


def test_non_finite_values_render_as_prometheus_specials():
    reg = Registry()
    reg.register_gauge("devices", lambda: [({"k": "inf"}, float("inf")),
                                           ({"k": "nan"}, float("nan")),
                                           ({"k": "ninf"}, float("-inf"))])
    text = reg.render()
    assert 'k="inf"} +Inf' in text
    assert 'k="nan"} NaN' in text
    assert 'k="ninf"} -Inf' in text


def test_label_values_are_escaped():
    reg = Registry()
    reg.inc("allocations_total", {"resource": 'a"b\\c\nd'})
    line = [l for l in reg.render().splitlines() if l.startswith("tpu_")][0]
    assert 'resource="a\\"b\\\\c\\nd"' in line
    assert "\n" not in line


def test_registry_gauges_and_failing_collector():
    reg = Registry()
    reg.register_gauge("devices", lambda: [({"health": "Healthy"}, 4.0)])
    reg.register_gauge("broken", lambda: 1 / 0)
    text = reg.render()
    assert 'tpu_device_plugin_devices{health="Healthy"} 4' in text  # scrape survives


def test_timed_context_manager():
    from tpu_device_plugin import metrics

    with metrics.timed("allocate", {"resource": "r"}):
        pass
    text = metrics.registry.render()
    assert 'tpu_device_plugin_allocate_seconds_count{resource="r"}' in text


def test_http_scrape():
    reg = Registry()
    reg.inc("allocations_total", {}, 7)
    server = MetricsServer(0, reg)
    port = server.start()
    try:
        body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics").read().decode()
        assert "tpu_device_plugin_allocations_total 7" in body
        health = urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz").read()
        assert health == b"ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
    finally:
        server.stop()


def test_daemon_serves_device_gauge_and_allocation_counters(tmp_path):
    import socket

    from tpu_device_plugin.api import pb
    from tpu_device_plugin.backend.fake import FakeChipManager
    from tpu_device_plugin.config import Config, Flags
    from tpu_device_plugin.main import Daemon

    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]

    kubelet = FakeKubelet(str(tmp_path / "dp"))
    kubelet.start()
    mgr = FakeChipManager(n_chips=4, chips_per_tray=4)
    flags = Flags(
        backend="fake",
        device_plugin_path=kubelet.plugin_dir,
        metrics_port=port,
        resource_config="tpu:shared-tpu:2",
    )
    daemon = Daemon(Config(flags=flags), backend=mgr, events=queue.Queue(),
                    lease_dir=str(tmp_path / "leases"))
    t = threading.Thread(target=daemon.run, daemon=True)
    t.start()
    try:
        assert daemon.started.wait(10)
        stub = kubelet.plugin_client("tpu-shared-tpu.sock")
        stub.Allocate(
            pb.AllocateRequest(
                container_requests=[
                    pb.ContainerAllocateRequest(devicesIDs=["tpu-0-replica-0"])
                ]
            )
        )
        body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics").read().decode()
        assert 'allocations_total{resource="google.com/shared-tpu"}' in body
        assert 'devices{health="Healthy",resource="google.com/shared-tpu"} 8' in body
        assert "allocate_seconds_sum" in body
        assert "TYPE tpu_device_plugin_allocate_seconds histogram" in body
    finally:
        daemon.request_stop()
        t.join(timeout=10)
        kubelet.stop()


def test_per_family_bucket_override():
    """describe(name, help, buckets=...) overrides LATENCY_BUCKETS for
    that family only: serve e2e latencies (> 1 s) get a seconds-scale
    ladder instead of all collapsing into +Inf, while undescribed
    families keep the Allocate-tuned default."""
    reg = Registry()
    reg.describe("engine_e2e_seconds", "serve e2e", buckets=(1.0, 30.0, 60.0))
    reg.observe_seconds("engine_e2e", 4.2, {"engine": "0"})
    reg.observe_seconds("allocate", 4.2)
    out = reg.render()
    assert 'engine_e2e_seconds_bucket{engine="0",le="30.0"} 1' in out
    assert 'engine_e2e_seconds_bucket{engine="0",le="60.0"} 1' in out
    # Default ladder not applied to the override family (labels render
    # alphabetically: engine before le).
    assert 'engine="0",le="0.0005"' not in out
    # The undescribed family still rides the default ladder: 4.2 s is
    # +Inf-only there.
    assert 'allocate_seconds_bucket{le="1.0"} 0' not in out
    assert 'allocate_seconds_bucket{le="+Inf"} 1' in out
    assert 'allocate_seconds_bucket{le="30.0"}' not in out


def test_bucket_override_rejects_bad_ladders():
    reg = Registry()
    for bad in ((), (0.5, 0.1), (1.0, 1.0), (-1.0, 2.0), (float("inf"),)):
        with pytest.raises(ValueError):
            reg.describe("x_seconds", "x", buckets=bad)


def test_metrics_server_port_zero_reports_bound_port():
    """Port 0 binds an ephemeral port; start() returns it AND updates
    .port, so serve-workload tests can scrape without port collisions
    under parallel CI."""
    server = MetricsServer(0, Registry())
    assert server.port == 0
    port = server.start()
    try:
        assert port > 0
        assert server.port == port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz"
        ).read()
        assert body == b"ok\n"
    finally:
        server.stop()


def test_observe_seconds_emits_histogram_buckets():
    from tpu_device_plugin.metrics import Registry

    reg = Registry()
    reg.observe_seconds("allocate", 0.003, {"resource": "tpu"})
    reg.observe_seconds("allocate", 0.3, {"resource": "tpu"})
    out = reg.render()
    # 3ms lands in every bucket from le=0.005 up; 300ms only in le=0.5/1.0/+Inf.
    assert 'allocate_seconds_bucket{le="0.005",resource="tpu"} 1' in out
    assert 'allocate_seconds_bucket{le="0.5",resource="tpu"} 2' in out
    assert 'allocate_seconds_bucket{le="+Inf",resource="tpu"} 2' in out
    assert 'allocate_seconds_count{resource="tpu"} 2' in out
    assert 'allocate_seconds_sum{resource="tpu"}' in out
    # One TYPE line for the whole family, marked histogram, buckets in
    # ascending le order with +Inf last.
    assert out.count("TYPE tpu_device_plugin_allocate_seconds ") == 1
    assert "TYPE tpu_device_plugin_allocate_seconds histogram" in out
    bucket_lines = [l for l in out.splitlines() if "_bucket" in l]
    les = [l.split('le="')[1].split('"')[0] for l in bucket_lines]
    inf_pos = les.index("+Inf")
    floats = [float(x) for x in les[:inf_pos]]
    assert floats == sorted(floats)

"""Adaptive speculation (ServeEngine(spec="auto")): the engine keeps
both decode programs resident and dispatches speculative vs plain per
step from live slot occupancy against the break-even threshold.  Pins:
the mode actually switches when occupancy crosses the threshold; token
parity with the dense oracle across switches (fan-out, LoRA and
prefix-cache admissions straddling a switch, pipelined and lookahead
compositions); the threshold extremes reduce to the pure per-regime
engines; the startup calibration path; and the constructor contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def models():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    return params, draft


def _engine(params, draft, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("prompt_bucket", 8)
    return ServeEngine(
        params, CONFIG, draft_params=draft, draft_config=DRAFT_CONFIG,
        gamma=3, spec="auto", **kw,
    )


def _ref(model, prompt, new):
    return [int(t) for t in np.asarray(generate(
        model, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=new,
    )[0])]


def test_mode_switches_when_occupancy_crosses_threshold(models):
    """slots=3, threshold 1.5: three concurrent requests decode plainly;
    retirements drop occupancy to 1 and the engine flips to speculation.
    The per-step trace must agree with the policy at every step, and
    every stream with the dense oracle in both regimes."""
    params, draft = models
    engine = _engine(params, draft, slots=3, spec_breakeven=1.5)
    expected = {}
    for prompt, new in (([5, 6, 7], 24), ([1, 2], 6), ([9], 4)):
        expected[engine.submit(prompt, new)] = (prompt, new)
    out = engine.run()
    assert engine.plain_mode_steps > 0, "never decoded above the threshold"
    assert engine.spec_mode_steps > 0, "never decoded below the threshold"
    assert engine.mode_switches >= 1
    for occ, mode in engine.decode_mode_trace:
        assert (mode == "spec") == (occ <= 1.5), (occ, mode)
    for rid, (prompt, new) in expected.items():
        assert list(out[rid]) == _ref(params, prompt, new), rid


def test_threshold_extremes_reduce_to_the_pure_engines(models):
    """breakeven=0 never speculates (spec_rounds stays 0); breakeven=
    slots always does (no plain chunks after admission) — and both emit
    the same oracle stream."""
    params, draft = models
    prompts = [([1, 2, 3], 8), ([4, 5], 8)]

    def run(breakeven):
        engine = _engine(params, draft, slots=2, spec_breakeven=breakeven)
        rids = [engine.submit(p, n) for p, n in prompts]
        out = engine.run()
        return engine, [list(out[r]) for r in rids]

    never, toks_never = run(0.0)
    assert never.spec_mode_steps == 0 and never.spec_rounds == 0
    assert never.plain_mode_steps > 0 and never.chunks_run > 0
    always, toks_always = run(2.0)
    assert always.plain_mode_steps == 0 and always.chunks_run == 0
    assert always.spec_mode_steps > 0 and always.spec_rounds > 0
    assert toks_never == toks_always
    for (prompt, new), got in zip(prompts, toks_never):
        assert got == _ref(params, prompt, new)


def test_admissions_straddling_a_switch(models):
    """Fan-out, LoRA and prefix-cache admissions land on BOTH sides of
    mode switches (pipelined, so the boundary drains real in-flight
    state); every stream still matches its merged-model oracle."""
    from workloads.lora import merge_lora
    from workloads.multi_lora import synthetic_adapters

    params, draft = models
    adapters = synthetic_adapters(CONFIG, 2, rank=4, scale=0.3, seed=3)
    name = sorted(adapters)[0]
    engine = ServeEngine(
        params, CONFIG, draft_params=draft, draft_config=DRAFT_CONFIG,
        gamma=3, spec="auto", spec_breakeven=1.0, slots=2, page_size=4,
        prompt_bucket=8, prefix_cache=True, adapters=adapters,
        pipelined=True,
    )
    prefix = list(range(10, 22))
    expected = {}
    # r1 outlasts every other request by several chunks, so the tail
    # decodes it ALONE — dispatches genuinely below the threshold, not
    # just drained there.
    r1 = engine.submit(prefix + [1], 40)
    expected[r1] = (prefix + [1], 40, None)
    r2 = engine.submit(prefix + [2], 8, adapter=name)
    expected[r2] = (prefix + [2], 8, name)
    for rid in engine.submit_fanout([3, 4, 5], 6, n_samples=2):
        expected[rid] = ([3, 4, 5], 6, None)
    out = engine.run()
    assert set(out) == set(expected)
    assert engine.mode_switches >= 1, "the stream never crossed the threshold"
    merged = merge_lora(params, adapters[name], dtype=jnp.float32)
    for rid, (prompt, new, adapter) in expected.items():
        model = merged if adapter else params
        assert list(out[rid]) == _ref(model, prompt, new), rid


def test_lookahead_composes_with_auto(models):
    """spec_lookahead > 1 under auto: supersteps below the threshold,
    plain chunks above, same oracle tokens."""
    params, draft = models
    engine = _engine(
        params, draft, slots=2, spec_breakeven=1.0, spec_lookahead=2,
        pipelined=True,
    )
    expected = {}
    for prompt, new in (([7, 8, 9], 16), ([2, 3], 6)):
        expected[engine.submit(prompt, new)] = (prompt, new)
    out = engine.run()
    for rid, (prompt, new) in expected.items():
        assert list(out[rid]) == _ref(params, prompt, new), rid
    assert engine.spec_mode_steps > 0 and engine.plain_mode_steps > 0


def test_tp_auto_matches_greedy(models):
    """spec="auto" under tensor parallelism: both TP programs (the
    decode chunk and make_tp_spec_superstep) dispatch by occupancy on
    the model mesh, and the mixed stream still matches plain greedy."""
    from workloads.train import make_mesh

    params, draft = models
    mesh = make_mesh(2, model_parallel=2)
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        draft_params=draft, draft_config=DRAFT_CONFIG, gamma=3,
        mesh=mesh, pipelined=True, spec="auto", spec_breakeven=1.0,
    )
    requests = [([1, 2, 3, 4], 14), ([5, 6], 6)]
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()
    for rid, (p, n) in zip(rids, requests):
        assert list(served[rid]) == _ref(params, p, n), rid
    assert engine.spec_mode_steps > 0 and engine.plain_mode_steps > 0
    assert engine.ctrl.used_pages == 0


def test_calibration_path(models):
    """No injected threshold: the engine calibrates at its first decode
    step (binary verdict at its own static shape), records the timings,
    and the stream is still the oracle's."""
    params, draft = models
    engine = _engine(params, draft, slots=2)
    assert engine.spec_breakeven is None
    rid = engine.submit([1, 2, 3], 6)
    out = engine.run()
    assert engine.spec_breakeven in (0.0, 2.0)
    assert engine.spec_calibration is not None
    assert engine.spec_calibration["threshold"] == engine.spec_breakeven
    assert engine.spec_calibration["plain_dispatch_ms"] > 0
    assert engine.spec_calibration["spec_dispatch_ms"] > 0
    assert list(out[rid]) == _ref(params, [1, 2, 3], 6)


def test_auto_contract(models):
    params, draft = models
    with pytest.raises(ValueError, match="spec"):
        ServeEngine(params, CONFIG, spec="auto")
    with pytest.raises(ValueError, match="spec"):
        ServeEngine(
            params, CONFIG, draft_params=draft, draft_config=DRAFT_CONFIG,
            spec="bogus",
        )
    with pytest.raises(ValueError, match="spec_breakeven"):
        ServeEngine(
            params, CONFIG, draft_params=draft, draft_config=DRAFT_CONFIG,
            spec_breakeven=2.0,
        )

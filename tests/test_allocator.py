"""Preferred-allocation policies: simple, ICI best-effort, static slices."""

import pytest

from tpu_device_plugin.allocator import PolicyError, SimplePolicy
from tpu_device_plugin.allocator.besteffort import BestEffortPolicy
from tpu_device_plugin.allocator.static_slices import (
    StaticSlicePolicy,
    multi_host_slice_policy,
    tray_aligned_policy,
)
from tpu_device_plugin.topology import Topology, build_fake_topology
from tpu_device_plugin.device import Chip


def ids(n, prefix="tpu"):
    return [f"{prefix}-{i}" for i in range(n)]


class TestSimplePolicy:
    def test_sorted_prefix(self):
        got = SimplePolicy().allocate(["tpu-2", "tpu-0", "tpu-1"], [], 2)
        assert got == ["tpu-0", "tpu-1"]

    def test_required_first(self):
        got = SimplePolicy().allocate(["tpu-2", "tpu-0", "tpu-1"], ["tpu-2"], 2)
        assert got == ["tpu-0", "tpu-2"]

    @pytest.mark.parametrize(
        "available, required, size",
        [
            (["a"], [], 2),          # size > available
            (["a", "b"], ["c"], 2),  # required not available
            (["a", "b"], ["a", "b"], 1),  # required > size
            (["a"], [], -1),
        ],
    )
    def test_invalid_requests(self, available, required, size):
        with pytest.raises(PolicyError):
            SimplePolicy().allocate(available, required, size)


class TestBestEffortPolicy:
    def test_prefers_same_tray(self):
        topo = build_fake_topology(8, 4)  # trays {0..3}, {4..7}
        policy = BestEffortPolicy(topo)
        got = policy.allocate(ids(8), [], 4)
        assert got == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]

    def test_packs_around_required(self):
        topo = build_fake_topology(8, 4)
        policy = BestEffortPolicy(topo)
        got = policy.allocate(ids(8), ["tpu-5"], 2)
        # Best partner for tpu-5 is a same-tray neighbour.
        assert "tpu-5" in got and set(got) <= {"tpu-4", "tpu-5", "tpu-6", "tpu-7"}

    def test_leaves_remainder_compact(self):
        # 2 trays of 2: picking one whole tray keeps the other intact.
        topo = build_fake_topology(4, 2)
        policy = BestEffortPolicy(topo)
        got = policy.allocate(["tpu-0", "tpu-1", "tpu-2", "tpu-3"], [], 2)
        assert got in (["tpu-0", "tpu-1"], ["tpu-2", "tpu-3"])

    def test_deterministic_tie_break(self):
        topo = build_fake_topology(4, 4)
        policy = BestEffortPolicy(topo)
        assert policy.allocate(ids(4), [], 1) == policy.allocate(ids(4), [], 1)

    def test_greedy_path_on_large_pools(self, monkeypatch):
        import tpu_device_plugin.allocator.besteffort as be

        monkeypatch.setattr(be, "MAX_EXHAUSTIVE_WORK", 1)
        topo = build_fake_topology(8, 4)
        policy = BestEffortPolicy(topo)
        got = policy.allocate(ids(8), [], 4)
        assert got == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]

    def test_greedy_tie_break_prefers_lexicographically_smallest(self, monkeypatch):
        import tpu_device_plugin.allocator.besteffort as be

        monkeypatch.setattr(be, "MAX_EXHAUSTIVE_WORK", 1)
        # All pair scores equal (single tray), IDs where one is a prefix of
        # another: 'c-1' must beat 'c-10'.
        from tpu_device_plugin.device import Chip
        from tpu_device_plugin.topology import Topology

        topo = Topology(torus_shape=(12, 1, 1))
        for i in range(12):
            cid = f"c-{i}"
            topo.chips_by_id[cid] = Chip(id=cid, index=i, coords=(0, 0, 0), tray=0)
        policy = BestEffortPolicy(topo)
        got = policy.allocate([f"c-{i}" for i in range(12)], [], 1)
        assert got == ["c-0"]
        got = policy.allocate(["c-10", "c-1", "c-11", "c-12"], [], 1)
        assert got == ["c-1"]

    def test_admission_path_latency_budget(self):
        # GetPreferredAllocation runs inside a synchronous kubelet RPC; the
        # v5p-16-host worst case must stay far below the dial timeout.
        import time

        topo = build_fake_topology(16, 4)
        policy = BestEffortPolicy(topo)
        t0 = time.monotonic()
        got = policy.allocate(sorted(topo.chips_by_id), [], 8)
        elapsed = time.monotonic() - t0
        assert len(got) == 8
        assert elapsed < 0.5, f"preferred allocation took {elapsed:.2f}s"


class TestStaticSlicePolicy:
    def test_tray_aligned_whole_tray_first(self):
        topo = build_fake_topology(8, 4)
        policy = tray_aligned_policy(topo)
        got = policy.allocate(ids(8), [], 4)
        assert got == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
        # With tray 0 partly taken, the intact tray 1 wins.
        got = policy.allocate(["tpu-1", "tpu-2", "tpu-3", "tpu-4", "tpu-5", "tpu-6", "tpu-7"], [], 4)
        assert got == ["tpu-4", "tpu-5", "tpu-6", "tpu-7"]

    def test_fallback_to_besteffort_for_odd_sizes(self):
        topo = build_fake_topology(8, 4)
        policy = tray_aligned_policy(topo)
        got = policy.allocate(ids(8), [], 3)  # no static set of size 3
        assert len(got) == 3 and len(set(got)) == 3

    def test_multi_host_v5p16_packing(self):
        # v5p-16 slice: 4 hosts x 4 chips; the policy packs whole hosts and
        # then ICI-adjacent host groups (BASELINE configs[4]).
        topo = Topology(accelerator_type="v5p", torus_shape=(4, 4, 1), wraparound=False)
        hosts = {}
        for h in range(4):
            chip_ids = []
            for i in range(4):
                cid = f"host{h}-chip{i}"
                coords = (i, h, 0)
                if h == 0:
                    topo.chips_by_id[cid] = Chip(id=cid, index=i, coords=coords, tray=h)
                else:
                    topo.remote_coords[cid] = coords
                    topo.remote_trays[cid] = h
                chip_ids.append(cid)
            hosts[f"host{h}"] = chip_ids
        policy = multi_host_slice_policy(topo, hosts)
        all_ids = [c for ids_ in hosts.values() for c in ids_]
        got = policy.allocate(all_ids, [], 4)
        assert got == sorted(hosts["host0"])
        got8 = policy.allocate(all_ids, [], 8)
        assert got8 == sorted(hosts["host0"] + hosts["host1"])
        # host0 busy -> next adjacent pair.
        remaining = [c for h in ("host1", "host2", "host3") for c in hosts[h]]
        got8b = policy.allocate(remaining, [], 8)
        assert got8b == sorted(hosts["host1"] + hosts["host2"])

    def test_static_respects_required_and_availability(self):
        topo = build_fake_topology(8, 4)
        policy = StaticSlicePolicy(
            topo, {2: [["tpu-0", "tpu-1"], ["tpu-2", "tpu-3"]]}
        )
        assert policy.allocate(ids(8), ["tpu-2"], 2) == ["tpu-2", "tpu-3"]


class TestStatefulAllocator:
    """The gpuallocator.Allocator analog (allocator.go:14-120)."""

    def test_allocate_free_cycle(self):
        from tpu_device_plugin.allocator import new_simple_allocator

        alloc = new_simple_allocator(ids(4))
        got = alloc.allocate(2)
        assert got == ["tpu-0", "tpu-1"]
        assert alloc.remaining == ["tpu-2", "tpu-3"]
        assert alloc.allocated == ["tpu-0", "tpu-1"]
        alloc.free(got)
        assert alloc.remaining == ids(4)
        assert alloc.allocated == []

    def test_allocate_exhausted_returns_empty(self):
        from tpu_device_plugin.allocator import new_simple_allocator

        alloc = new_simple_allocator(ids(2))
        assert alloc.allocate(2) == ["tpu-0", "tpu-1"]
        # allocator.go:81-93 — unsatisfiable num yields the empty set, no error.
        assert alloc.allocate(1) == []
        assert alloc.allocate(0) == []

    def test_allocate_specific_unavailable(self):
        from tpu_device_plugin.allocator import new_simple_allocator

        alloc = new_simple_allocator(ids(3))
        alloc.allocate_specific(["tpu-1"])
        with pytest.raises(PolicyError, match="unavailable"):
            alloc.allocate_specific(["tpu-1", "tpu-2"])
        # All-or-nothing: tpu-2 must not have been claimed by the failed call.
        assert "tpu-2" in alloc.remaining

    def test_free_unknown_id_rejected(self):
        from tpu_device_plugin.allocator import new_simple_allocator

        alloc = new_simple_allocator(ids(2))
        with pytest.raises(PolicyError, match="do not belong"):
            alloc.free(["ghost"])
        assert alloc.remaining == ids(2)

    def test_best_effort_allocator_prefers_trays(self):
        from tpu_device_plugin.allocator import new_best_effort_allocator

        topo = build_fake_topology(8, 4)
        alloc = new_best_effort_allocator(topo, ids(8))
        first = alloc.allocate(4)
        second = alloc.allocate(4)
        # Two tray-aligned grabs drain the host cleanly.
        assert first == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
        assert second == ["tpu-4", "tpu-5", "tpu-6", "tpu-7"]
        alloc.free(first)
        assert alloc.allocate(4) == first

    def test_inventory_defaults_to_topology(self):
        from tpu_device_plugin.allocator import new_best_effort_allocator

        topo = build_fake_topology(4, 4)
        alloc = new_best_effort_allocator(topo)
        assert alloc.remaining == ids(4)

    def test_double_free_rejected(self):
        from tpu_device_plugin.allocator import new_simple_allocator

        alloc = new_simple_allocator(ids(2))
        got = alloc.allocate(1)
        alloc.free(got)
        with pytest.raises(PolicyError, match="stale or double free"):
            alloc.free(got)
        # The pool is unchanged by the rejected free.
        assert alloc.remaining == ids(2)


class TestLargeTableBounds:
    """The greedy degrade must keep preferred-allocation latency bounded at
    realistic device counts (SURVEY.md §3.5 hard part #5: the expensive
    topology work happens here, never in Allocate)."""

    def test_besteffort_64_chips_goes_greedy_and_stays_fast(self):
        import time

        topo = build_fake_topology(64, 4)
        policy = BestEffortPolicy(topo)
        t0 = time.perf_counter()
        got = policy.allocate(ids(64), [], 8)
        elapsed = time.perf_counter() - t0
        assert len(got) == 8 and len(set(got)) == 8
        # C(64,8) exhaustive would be ~4e9 candidate sets; the work budget
        # must have kicked in.  2s is ~100x the expected greedy cost — a
        # regression to exhaustive blows it by orders of magnitude.
        assert elapsed < 2.0
        # Greedy still packs an ICI-coherent set: all 8 from 2 trays.
        trays = {int(g.split("-")[1]) // 4 for g in got}
        assert len(trays) == 2

    def test_replica_table_256_prioritize_stays_fast(self):
        import time

        from tpu_device_plugin.replica import prioritize_devices, replica_id

        table = [
            replica_id(f"tpu-{c}", r) for c in range(16) for r in range(16)
        ]
        t0 = time.perf_counter()
        got = prioritize_devices(table, [], 16)
        elapsed = time.perf_counter() - t0
        assert len(got.devices) == 16
        assert elapsed < 2.0

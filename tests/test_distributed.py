"""Multi-host bring-up (workloads/distributed.py): two real processes wire
jax.distributed from the daemon-injected slice env and psum across the
process boundary — the hardware-free stand-in for a two-host slice."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")

    from workloads.distributed import global_mesh, initialize_from_slice_env

    assert initialize_from_slice_env() is True
    import numpy as np
    import jax.numpy as jnp
    try:  # same compat range as workloads/ops (jax >= 0.4.35)
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    pid = jax.process_index()
    mesh = global_mesh()
    n = jax.device_count()
    assert n == 2 * jax.local_device_count(), (n, jax.local_device_count())

    x = jnp.arange(n, dtype=jnp.float32)
    total = jax.jit(
        shard_map(
            lambda s: jax.lax.psum(s, "data"),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        )
    )(x)
    local = np.concatenate(
        [np.asarray(s.data) for s in total.addressable_shards]
    )
    expected = float(sum(range(n)))
    assert np.allclose(local, expected), (local, expected)
    print(f"worker {pid}: psum over {n} devices across 2 processes ok", flush=True)
    """
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_slice_bringup():
    port = free_port()
    procs = []
    for worker_id in range(2):
        env = dict(os.environ)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                # Exactly what the daemon stamps into slice containers
                # (slice_topology.container_slice_env) + the coordinator.
                "TPU_WORKER_ID": str(worker_id),
                "TPU_TOPOLOGY": "2x2x2",
                "TPU_HOST_BOUNDS": "1,1,2",
                "TPU_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            }
        )
        env.pop("PALLAS_AXON_POOL_IPS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        # A hung worker must not outlive the test (orphans wedge CI).
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    for worker_id, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {worker_id} failed:\n{out}"
        assert f"worker {worker_id}: psum" in out


def test_single_host_env_is_noop():
    from workloads.distributed import initialize_from_slice_env, slice_process_info

    assert slice_process_info({}) is None
    assert initialize_from_slice_env(environ={}) is False
    # A 1-host slice needs no distributed runtime either.
    env = {
        "TPU_WORKER_ID": "0",
        "TPU_TOPOLOGY": "2x2x1",
        "TPU_HOST_BOUNDS": "1,1,1",
    }
    assert initialize_from_slice_env(environ=env) is False


def test_malformed_slice_env_fails_loud():
    """Validation comes from the daemon's canonical parser."""
    from tpu_device_plugin.slice_topology import SliceConfigError
    from workloads.distributed import slice_process_info

    with pytest.raises(SliceConfigError, match="invalid TPU_WORKER_ID"):
        slice_process_info(
            {
                "TPU_WORKER_ID": "x",
                "TPU_TOPOLOGY": "2x2x2",
                "TPU_HOST_BOUNDS": "1,1,2",
            }
        )
    with pytest.raises(SliceConfigError, match="outside host grid"):
        slice_process_info(
            {
                "TPU_WORKER_ID": "7",
                "TPU_TOPOLOGY": "2x2x2",
                "TPU_HOST_BOUNDS": "1,1,2",
            }
        )


def test_missing_coordinator_fails_loud():
    from workloads.distributed import initialize_from_slice_env

    env = {
        "TPU_WORKER_ID": "1",
        "TPU_TOPOLOGY": "2x2x2",
        "TPU_HOST_BOUNDS": "1,1,2",
    }
    with pytest.raises(ValueError, match="coordinator"):
        initialize_from_slice_env(environ=env)


def test_two_process_training_step(tmp_path):
    """`python -m workloads.train` joins the slice from the daemon-injected
    env and runs the full step across two real processes."""
    port = free_port()
    procs = []
    for worker_id in range(2):
        env = dict(os.environ)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "TPU_WORKER_ID": str(worker_id),
                "TPU_TOPOLOGY": "2x2x2",
                "TPU_HOST_BOUNDS": "1,1,2",
                "TPU_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            }
        )
        env.pop("PALLAS_AXON_POOL_IPS", None)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "workloads.train",
                    # batch divisible by the data axis whatever the local
                    # device count (1 outside pytest, 8 under conftest's
                    # XLA_FLAGS -> up to data=4 after the tp cut).
                    "--steps", "2", "--batch-size", "8",
                    "--seq-len", "16", "--layers", "1",
                ],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    for worker_id, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {worker_id} failed:\n{out}"
        assert f"joined slice as worker {worker_id}/2" in out
        assert "done: steps=2" in out


def test_partial_slice_env_fails_loud():
    """worker-id/host-bounds without topology is a misconfiguration, not a
    single-host container: silent False would hang the rest of the slice."""
    from tpu_device_plugin.slice_topology import SliceConfigError
    from workloads.distributed import slice_process_info

    with pytest.raises(SliceConfigError, match="partial slice env"):
        slice_process_info({"TPU_WORKER_ID": "1", "TPU_HOST_BOUNDS": "1,1,2"})

"""Spec for --resource-config parsing (reference: main.go:171-203)."""

import pytest

from tpu_device_plugin.resource_config import Variant, parse_resource_config


def test_basic_entry():
    rc = parse_resource_config("tpu:shared-tpu:4")
    assert rc.get("tpu") == Variant(name="shared-tpu", replicas=4, auto_replicas=False)
    assert rc.get("tpu").shared


def test_multiple_entries_and_whitespace():
    rc = parse_resource_config(" tpu:shared-tpu:4 , tpu-tray:tray:2 ,")
    assert rc.get("tpu").name == "shared-tpu"
    assert rc.get("tpu-tray") == Variant(name="tray", replicas=2)


def test_auto_replicas():
    rc = parse_resource_config("tpu:tpu-mem-gb:-1")
    v = rc.get("tpu")
    assert v.auto_replicas and v.replicas == 1 and v.name == "tpu-mem-gb"
    assert v.shared


def test_unconfigured_resource_identity_fallback():
    rc = parse_resource_config("tpu:shared:2")
    assert rc.get("other") == Variant(name="other", replicas=0, auto_replicas=False)
    assert not rc.get("other").shared


def test_empty_string():
    assert parse_resource_config("") == {}


@pytest.mark.parametrize("bad", ["tpu:x", "tpu:x:y:z", "tpu:x:notanint", "tpu:x:-2"])
def test_malformed_entries(bad):
    with pytest.raises(ValueError):
        parse_resource_config(bad)


def test_rename_without_sharing():
    rc = parse_resource_config("tpu:renamed:1")
    v = rc.get("tpu")
    assert v.name == "renamed" and not v.shared

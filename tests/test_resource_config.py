"""Spec for --resource-config parsing (reference: main.go:171-203)."""

import pytest

from tpu_device_plugin.resource_config import (
    Variant,
    parse_resource_config,
    parse_size_bytes,
)


def test_basic_entry():
    rc = parse_resource_config("tpu:shared-tpu:4")
    assert rc.get("tpu") == Variant(name="shared-tpu", replicas=4, auto_replicas=False)
    assert rc.get("tpu").shared


def test_multiple_entries_and_whitespace():
    rc = parse_resource_config(" tpu:shared-tpu:4 , tpu-tray:tray:2 ,")
    assert rc.get("tpu").name == "shared-tpu"
    assert rc.get("tpu-tray") == Variant(name="tray", replicas=2)


def test_auto_replicas():
    rc = parse_resource_config("tpu:tpu-mem-gb:-1")
    v = rc.get("tpu")
    assert v.auto_replicas and v.replicas == 1 and v.name == "tpu-mem-gb"
    assert v.shared


def test_unconfigured_resource_identity_fallback():
    rc = parse_resource_config("tpu:shared:2")
    assert rc.get("other") == Variant(name="other", replicas=0, auto_replicas=False)
    assert not rc.get("other").shared


def test_empty_string():
    assert parse_resource_config("") == {}


@pytest.mark.parametrize("bad", ["tpu:x", "tpu:x:y:z", "tpu:x:notanint", "tpu:x:-2"])
def test_malformed_entries(bad):
    with pytest.raises(ValueError):
        parse_resource_config(bad)


def test_rename_without_sharing():
    rc = parse_resource_config("tpu:renamed:1")
    v = rc.get("tpu")
    assert v.name == "renamed" and not v.shared


# ---- KV-page units (the optional fourth field) ---------------------------


def test_auto_replicas_with_kv_page_size():
    rc = parse_resource_config("tpu:tpu-kv-pages:-1:16Mi")
    v = rc.get("tpu")
    assert v == Variant(
        name="tpu-kv-pages",
        replicas=1,
        auto_replicas=True,
        kv_page_bytes=16 << 20,
    )
    assert v.shared


def test_kv_page_size_defaults_to_none_in_plain_auto_mode():
    assert parse_resource_config("tpu:x:-1").get("tpu").kv_page_bytes is None


@pytest.mark.parametrize(
    ("text", "expect"),
    [
        ("512", 512),
        ("4Ki", 4 << 10),
        ("16Mi", 16 << 20),
        ("2Gi", 2 << 30),
        (" 1Gi ", 1 << 30),
    ],
)
def test_parse_size_bytes(text, expect):
    assert parse_size_bytes(text) == expect


@pytest.mark.parametrize("bad", ["", "Mi", "1.5Gi", "16MB", "0", "-4Ki"])
def test_parse_size_bytes_rejects(bad):
    with pytest.raises(ValueError, match="size"):
        parse_size_bytes(bad)


def test_page_size_requires_auto_mode():
    with pytest.raises(ValueError, match="only .*valid with replicas = -1"):
        parse_resource_config("tpu:x:4:16Mi")


def test_bad_page_size_names_the_entry():
    with pytest.raises(
        ValueError, match="resource-config entry 'tpu:x:-1:huge'"
    ):
        parse_resource_config("tpu:x:-1:huge")


def test_kv_page_entry_round_trips_next_to_legacy_entries():
    rc = parse_resource_config("tpu:legacy:-1, tray:paged:-1:4Ki, t2:shared:2")
    assert rc.get("tpu").kv_page_bytes is None
    assert rc.get("tray").kv_page_bytes == 4 << 10
    assert rc.get("t2") == Variant(name="shared", replicas=2)

"""Metrics exposition lint (make obs-check): every metric name the
plugin or the engine bridge ever emits has describe() help text, and
Registry.render() output parses as valid Prometheus exposition format —
HELP/TYPE before any series of a family, cumulative histogram buckets
with sorted le and +Inf last, _count matching the +Inf bucket.

Deliberately jax-free (workloads.obs is importable without jax) so the
lint runs in seconds inside the fast suite and `make obs-check`.
"""

import os
import re
from types import SimpleNamespace

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Call-site patterns for the emission APIs.  \s* spans newlines, so
# multi-line calls (plugin.py's health_events_total) are caught.
_INC_RE = re.compile(r"\.inc\(\s*\n?\s*[\"']([a-z0-9_]+)[\"']")
_OBSERVE_RE = re.compile(r"\.observe_seconds\(\s*\n?\s*[\"']([a-z0-9_]+)[\"']")
_TIMED_RE = re.compile(r"(?:metrics_timed|metrics\.timed|\btimed)\(\s*[\"']([a-z0-9_]+)[\"']")
_GAUGE_RE = re.compile(r"\.register_gauge\(\s*\n?\s*[\"']([a-z0-9_]+)[\"']")
# The observer registers its gauges by iterating _GAUGE_READERS, so the
# emitted names are that mapping's keys: "name": lambda e: ...
_GAUGE_READER_RE = re.compile(r"[\"']([a-z0-9_]+)[\"']:\s*lambda e:")


def _emitted_names() -> set[str]:
    """Every family name the plugin daemon or the engine bridge emits,
    scraped from source text (histogram call names normalised to their
    rendered ``<x>_seconds`` family)."""
    names: set[str] = set()
    roots = []
    plugin_dir = os.path.join(REPO, "tpu_device_plugin")
    for fn in os.listdir(plugin_dir):
        if fn.endswith(".py") and fn != "metrics.py":  # skip definitions
            roots.append(os.path.join(plugin_dir, fn))
    roots.append(os.path.join(REPO, "workloads", "obs.py"))
    roots.append(os.path.join(REPO, "workloads", "fleet.py"))
    for path in roots:
        text = open(path, encoding="utf-8").read()
        names |= set(_INC_RE.findall(text))
        names |= {f"{n}_seconds" for n in _OBSERVE_RE.findall(text)}
        names |= {f"{n}_seconds" for n in _TIMED_RE.findall(text)}
        names |= set(_GAUGE_RE.findall(text))
        names |= set(_GAUGE_READER_RE.findall(text))
    return names


def _described_names() -> set[str]:
    from tpu_device_plugin import metrics
    from workloads.obs import (
        AUTOSCALER_METRICS,
        CONTROL_METRICS,
        ENGINE_METRICS,
        FLEET_METRICS,
        LEDGER_METRICS,
        SUPERVISOR_METRICS,
    )

    return (
        set(metrics.registry._help)
        | {m.name for m in ENGINE_METRICS}
        | {m.name for m in FLEET_METRICS}
        | {m.name for m in SUPERVISOR_METRICS}
        | {m.name for m in AUTOSCALER_METRICS}
        | {m.name for m in CONTROL_METRICS}
        | {m.name for m in LEDGER_METRICS}
    )


def test_every_emitted_metric_has_help_text():
    emitted = _emitted_names()
    assert emitted, "the scanner found no emission call sites at all"
    # Sanity-pin a few names the scan must catch (a regex rot tripwire:
    # an over-narrow pattern would silently lint nothing).
    for expected in (
        "allocations_total", "health_events_total", "allocate_seconds",
        "devices", "engine_tokens_total", "engine_ttft_seconds",
        "engine_queue_depth",
    ):
        assert expected in emitted, f"scanner missed {expected}"
    undescribed = emitted - _described_names()
    assert not undescribed, (
        f"metric names emitted without describe() help text: "
        f"{sorted(undescribed)} — add them to the module-level describes "
        f"(tpu_device_plugin/metrics.py) or ENGINE_METRICS (workloads/obs.py)"
    )


def test_engine_catalog_is_fully_described_on_bind():
    """bind_registry must describe EVERY catalog family (the rendered
    docs table promises them all)."""
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import ENGINE_METRICS, EngineObserver

    reg = Registry()
    EngineObserver().bind_registry(reg)
    missing = {m.name for m in ENGINE_METRICS} - set(reg._help)
    assert not missing, missing


def test_gauge_readers_match_the_catalog():
    """bind/unbind both iterate _GAUGE_READERS; if it drifts from the
    catalog's gauge families, either a documented gauge never registers
    or an unregistered one leaks past unbind_registry."""
    from workloads.obs import ENGINE_METRICS, EngineObserver

    catalog_gauges = {m.name for m in ENGINE_METRICS if m.type == "gauge"}
    assert catalog_gauges == set(EngineObserver._GAUGE_READERS)


def test_fleet_gauge_readers_match_the_catalog():
    """Same drift pin for the fleet bridge's gauge families."""
    from workloads.obs import FLEET_METRICS, FleetObserver

    catalog_gauges = {m.name for m in FLEET_METRICS if m.type == "gauge"}
    assert catalog_gauges == set(FleetObserver._FLEET_GAUGE_READERS)


def test_ledger_gauge_readers_match_the_catalog():
    """Drift pin for the chip-time-ledger gauge families: the
    engine-labeled ones ride EngineObserver._LEDGER_GAUGE_READERS, the
    fleet-labeled one FleetObserver._FLEET_LEDGER_GAUGE_READERS —
    nothing documented can fail to register, nothing registered can
    leak past unbind."""
    from workloads.obs import LEDGER_METRICS, EngineObserver, FleetObserver

    engine_gauges = {
        m.name for m in LEDGER_METRICS
        if m.type == "gauge" and m.labels[0] == "engine"
    }
    assert engine_gauges == set(EngineObserver._LEDGER_GAUGE_READERS)
    fleet_gauges = {
        m.name for m in LEDGER_METRICS
        if m.type == "gauge" and m.labels[0] == "fleet"
    }
    assert fleet_gauges == set(FleetObserver._FLEET_LEDGER_GAUGE_READERS)


def test_ledger_catalog_is_fully_described_on_bind():
    """Both bridges together must describe every LEDGER_METRICS family
    (the rendered docs table promises them all)."""
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import LEDGER_METRICS, EngineObserver, FleetObserver

    reg = Registry()
    EngineObserver().bind_registry(reg)
    FleetObserver().bind_registry(reg)
    missing = {m.name for m in LEDGER_METRICS} - set(reg._help)
    assert not missing, missing


def test_fleet_catalog_is_fully_described_on_bind():
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import FLEET_METRICS, FleetObserver

    reg = Registry()
    FleetObserver().bind_registry(reg)
    missing = {m.name for m in FLEET_METRICS} - set(reg._help)
    assert not missing, missing


def test_supervisor_gauge_readers_match_the_catalog():
    """Same drift pin for the supervisor bridge's gauge families."""
    from workloads.obs import SUPERVISOR_METRICS, SupervisorObserver

    catalog_gauges = {
        m.name for m in SUPERVISOR_METRICS if m.type == "gauge"
    }
    assert catalog_gauges == set(
        SupervisorObserver._SUPERVISOR_GAUGE_READERS
    )


def test_supervisor_catalog_is_fully_described_on_bind():
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import SUPERVISOR_METRICS, SupervisorObserver

    reg = Registry()
    SupervisorObserver().bind_registry(reg)
    missing = {m.name for m in SUPERVISOR_METRICS} - set(reg._help)
    assert not missing, missing


def test_autoscaler_gauge_readers_match_the_catalog():
    """Same drift pin for the autoscaler bridge's gauge families."""
    from workloads.obs import AUTOSCALER_METRICS, AutoscalerObserver

    catalog_gauges = {
        m.name for m in AUTOSCALER_METRICS if m.type == "gauge"
    }
    assert catalog_gauges == set(
        AutoscalerObserver._AUTOSCALER_GAUGE_READERS
    )


def test_autoscaler_catalog_is_fully_described_on_bind():
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import AUTOSCALER_METRICS, AutoscalerObserver

    reg = Registry()
    AutoscalerObserver().bind_registry(reg)
    missing = {m.name for m in AUTOSCALER_METRICS} - set(reg._help)
    assert not missing, missing


def test_control_gauge_readers_match_the_catalog():
    """Same drift pin for the goodput-controller bridge's gauge
    families."""
    from workloads.obs import CONTROL_METRICS, ControlObserver

    catalog_gauges = {
        m.name for m in CONTROL_METRICS if m.type == "gauge"
    }
    assert catalog_gauges == set(ControlObserver._CONTROL_GAUGE_READERS)


def test_control_catalog_is_fully_described_on_bind():
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import CONTROL_METRICS, ControlObserver

    reg = Registry()
    ControlObserver().bind_registry(reg)
    missing = {m.name for m in CONTROL_METRICS} - set(reg._help)
    assert not missing, missing


def test_control_bridge_render_is_valid_exposition():
    """Drive the control bridge against a fake controller (no jax):
    actuation counters land as running-total deltas, the per-action
    decisions counter carries the action label, the EWMA gauges emit
    no sample until measured and scrape once they are — then unbind
    releases the gauges."""
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import ControlObserver

    reg = Registry()
    obs = ControlObserver(name="ctl0")
    obs.bind_registry(reg)
    ctrl = SimpleNamespace(
        retunes_applied=0, wfq_reweights=0, dropped_events=0,
        decisions={},
        goodput_fraction_ewma=None, spec_rejected_fraction_ewma=None,
        overdecode_fraction_ewma=None,
    )
    obs._bind(ctrl)
    obs._control_poll_end(ctrl)
    # Unmeasured EWMAs emit NO gauge sample (0.0 would read as
    # "perfect waste" on a dashboard).
    assert f"{PREFIX}_control_goodput_fraction{{" not in reg.render()
    ctrl.retunes_applied = 3
    ctrl.wfq_reweights = 1
    ctrl.decisions = {"retune": 3, "wfq_reweight": 1}
    ctrl.goodput_fraction_ewma = 0.75
    ctrl.spec_rejected_fraction_ewma = 0.15
    ctrl.overdecode_fraction_ewma = 0.05
    obs._control_poll_end(ctrl)
    obs._control_poll_end(ctrl)  # unchanged totals push no deltas
    families = _parse_exposition(reg.render())
    assert families[
        f"{PREFIX}_control_retunes_total"
    ]["samples"][0][2] == 3.0
    assert families[
        f"{PREFIX}_control_wfq_reweights_total"
    ]["samples"][0][2] == 1.0
    decisions = families[f"{PREFIX}_control_decisions_total"]["samples"]
    assert {
        (labels["action"], v) for _, labels, v in decisions
    } == {("retune", 3.0), ("wfq_reweight", 1.0)}
    assert families[
        f"{PREFIX}_control_goodput_fraction"
    ]["samples"][0][2] == 0.75
    assert families[
        f"{PREFIX}_control_overdecode_fraction"
    ]["samples"][0][2] == 0.05
    obs.unbind_registry()
    assert f"{PREFIX}_control_goodput_fraction" not in _parse_exposition(
        reg.render()
    )


# ---- exposition-format parsing -----------------------------------------


def _parse_exposition(text: str):
    """Parse Prometheus text format into {family: {"type": ..., "help":
    ..., "samples": [(name, labels dict, value)]}}, asserting the
    structural rules as it goes: HELP and TYPE precede every family's
    first sample, sample lines parse, label values stay escaped."""
    families: dict[str, dict] = {}
    line_re = re.compile(
        r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
        r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
    )
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            fam = line.split()[2]
            families.setdefault(fam, {"samples": []})["help"] = line
            continue
        if line.startswith("# TYPE "):
            _, _, fam, mtype = line.split(None, 3)
            assert fam in families and "help" in families[fam], (
                f"TYPE before HELP for {fam}"
            )
            assert "type" not in families[fam], f"duplicate TYPE for {fam}"
            families[fam]["type"] = mtype
            continue
        assert not line.startswith("#"), f"unknown comment line: {line}"
        m = line_re.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name = m.group("name")
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)].endswith("_seconds"):
                fam = name[: -len(suffix)]
        assert fam in families and "type" in families[fam], (
            f"sample {name} before its family's HELP/TYPE"
        )
        labels = {}
        if m.group("labels"):
            for part in re.findall(r'(\w+)="((?:[^"\\]|\\.)*)"', m.group("labels")):
                labels[part[0]] = part[1]
        value = float(m.group("value").replace("+Inf", "inf").replace("-Inf", "-inf").replace("NaN", "nan"))
        families[fam]["samples"].append((name, labels, value))
    return families


def _assert_histogram_sound(fam: str, info: dict):
    assert info["type"] == "histogram", fam
    by_series: dict[tuple, list] = {}
    counts, sums = {}, {}
    for name, labels, value in info["samples"]:
        if name.endswith("_bucket"):
            key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            by_series.setdefault(key, []).append((labels["le"], value))
        elif name.endswith("_count"):
            counts[tuple(sorted(labels.items()))] = value
        elif name.endswith("_sum"):
            sums[tuple(sorted(labels.items()))] = value
    assert by_series and counts and sums, f"{fam}: incomplete triple"
    for key, buckets in by_series.items():
        les = [le for le, _ in buckets]
        assert les[-1] == "+Inf", f"{fam}: +Inf not last: {les}"
        floats = [float(le) for le in les[:-1]]
        assert floats == sorted(floats), f"{fam}: le out of order: {les}"
        values = [v for _, v in buckets]
        assert values == sorted(values), (
            f"{fam}: buckets not cumulative: {values}"
        )
        assert counts[key] == values[-1], (
            f"{fam}: _count {counts[key]} != +Inf bucket {values[-1]}"
        )


def test_render_parses_as_valid_exposition_format():
    """A registry exercising every series shape — counters with and
    without labels, default- and override-bucket histograms, gauges —
    renders to text the parser accepts with sound histograms."""
    from tpu_device_plugin.metrics import PREFIX, Registry

    reg = Registry()
    reg.describe("allocations_total", "allocs")
    reg.describe("allocate_seconds", "latency")
    reg.describe("engine_e2e_seconds", "e2e", buckets=(0.5, 2.5, 10.0))
    reg.describe("devices", "devices by health")
    reg.inc("allocations_total", {"resource": "google.com/tpu"})
    reg.inc("allocations_total")
    for s in (0.003, 0.07, 4.2):
        reg.observe_seconds("allocate", s, {"resource": "r"})
        reg.observe_seconds("engine_e2e", s, {"engine": "0"})
    reg.register_gauge("devices", lambda: [({"health": "Healthy"}, 4.0)])
    families = _parse_exposition(reg.render())
    assert f"{PREFIX}_allocations_total" in families
    assert families[f"{PREFIX}_allocations_total"]["type"] == "counter"
    assert families[f"{PREFIX}_devices"]["type"] == "gauge"
    for fam in (f"{PREFIX}_allocate_seconds", f"{PREFIX}_engine_e2e_seconds"):
        _assert_histogram_sound(fam, families[fam])
    # The override ladder actually applied: 4.2 s lands in a finite
    # bucket of the serve family but only +Inf of the default one.
    e2e_les = {
        labels["le"]
        for name, labels, _ in families[f"{PREFIX}_engine_e2e_seconds"]["samples"]
        if name.endswith("_bucket")
    }
    assert e2e_les == {"0.5", "2.5", "10.0", "+Inf"}


def test_engine_bridge_render_is_valid_exposition():
    """Drive the full observer bridge against a FAKE engine (no jax:
    the hooks only read counters/mirrors) and parse the rendered
    output — the engine families obey the same exposition rules as the
    plugin's."""
    import numpy as np

    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import EngineObserver

    reg = Registry()
    obs = EngineObserver(name="lint")
    obs.bind_registry(reg)

    class _Ctrl(SimpleNamespace):
        pass

    eng = SimpleNamespace(
        generated_tokens=0, requests_admitted=0, requests_retired=0,
        prefill_dispatches=0, prefill_sweeps=0, chunks_run=0, spec_rounds=0,
        mode_switches=0, admission_readbacks=0, spec_lookahead=1,
        prefill_deferred_tokens=0, _inflight_prefill=[],
        pending=[], _occupied=np.zeros(4, bool), slots=4,
        ctrl=_Ctrl(used_pages=0),
    )
    obs._bind(eng)
    finished = SimpleNamespace(
        rid="req-0", t_submit=1.0, t_admit=1.1, t_first=1.5, t_done=3.0,
        tokens=[7, 8, 9],
    )
    for i in range(3):
        snap = obs._step_begin(eng)
        eng.generated_tokens += 4
        eng.chunks_run += 1
        if i == 0:
            eng.requests_admitted += 2
            eng.prefill_dispatches += 1
            eng.prefill_sweeps += 1
            # A budget-deferred admission: the counter pushes as a
            # step delta and the in-flight gauge reads it.
            eng.prefill_deferred_tokens += 16
            eng._inflight_prefill = [SimpleNamespace()]
        done = []
        if i == 2:
            eng.requests_retired += 1
            eng.spec_rounds += 1  # exercise the spec-mode label too
            eng.chunks_run -= 1
            done = [finished]
        obs._step_end(eng, snap, done)
    families = _parse_exposition(reg.render())
    assert families[f"{PREFIX}_engine_tokens_total"]["samples"][0][2] == 12.0
    assert (
        families[f"{PREFIX}_engine_prefill_deferred_tokens_total"][
            "samples"
        ][0][2]
        == 16.0
    )
    for fam in (
        f"{PREFIX}_engine_ttft_seconds",
        f"{PREFIX}_engine_e2e_seconds",
        f"{PREFIX}_engine_step_seconds",
    ):
        _assert_histogram_sound(fam, families[fam])
    modes = {
        labels.get("mode")
        for _, labels, _ in families[f"{PREFIX}_engine_decode_steps_total"]["samples"]
    }
    assert modes == {"plain", "spec"}
    gauges = {
        fam for fam, info in families.items() if info["type"] == "gauge"
    }
    assert f"{PREFIX}_engine_queue_depth" in gauges
    assert f"{PREFIX}_engine_resident_pages" in gauges


def _drive_fake_engine(obs, steps: int = 2):
    """Minimal fake-engine bridge drive shared by the replica-label
    pins (no jax: the hooks only read counters/mirrors)."""
    import numpy as np

    eng = SimpleNamespace(
        generated_tokens=0, requests_admitted=0, requests_retired=0,
        prefill_dispatches=0, prefill_sweeps=0, chunks_run=0, spec_rounds=0,
        mode_switches=0, admission_readbacks=0, spec_lookahead=1,
        prefill_deferred_tokens=0, _inflight_prefill=[],
        pending=[], _occupied=np.zeros(2, bool), slots=2,
        ctrl=SimpleNamespace(used_pages=0), paused=False,
    )
    obs._bind(eng)
    for _ in range(steps):
        snap = obs._step_begin(eng)
        eng.generated_tokens += 3
        eng.chunks_run += 1
        obs._step_end(eng, snap, [])
    return eng


def test_single_engine_scrape_has_no_replica_label():
    """The replica label is OPT-IN: with the default empty ``replica``
    the rendered output carries no replica label anywhere and gauges
    register name-keyed — single-engine scrape output stays
    byte-compatible with the pre-fleet bridge (the multi-engine
    collision fix must not move anyone's dashboards)."""
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import EngineObserver

    reg = Registry()
    obs = EngineObserver(name="solo")
    obs.bind_registry(reg)
    _drive_fake_engine(obs)
    text = reg.render()
    assert 'engine="solo"' in text
    assert "replica=" not in text
    # Keyless gauges keep the replace-by-name contract: a successor
    # observer's registration replaces, never duplicates.
    obs2 = EngineObserver(name="solo2")
    obs2.bind_registry(reg)
    _drive_fake_engine(obs2)
    depth_lines = [
        ln for ln in reg.render().splitlines()
        if ln.startswith("tpu_device_plugin_engine_queue_depth{")
    ]
    assert len(depth_lines) == 1, depth_lines


def test_multi_replica_engines_share_one_registry():
    """Fleet mode: N observers with distinct ``replica`` ids coexist on
    one registry — every engine family series carries its replica
    label, per-replica gauges all scrape (no last-binder-wins
    collision), the exposition stays valid, and one replica unbinding
    leaves its siblings' collectors alone."""
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import EngineObserver

    reg = Registry()
    observers = [
        EngineObserver(name=str(i), replica=str(i)) for i in range(3)
    ]
    for obs in observers:
        obs.bind_registry(reg)
        _drive_fake_engine(obs)
    families = _parse_exposition(reg.render())
    depth = families[f"{PREFIX}_engine_queue_depth"]["samples"]
    assert {labels["replica"] for _, labels, _ in depth} == {"0", "1", "2"}
    tokens = families[f"{PREFIX}_engine_tokens_total"]["samples"]
    assert {labels["replica"] for _, labels, _ in tokens} == {"0", "1", "2"}
    assert all(v == 6.0 for _, _, v in tokens)
    # Replica 1 retires: its gauges go, 0 and 2 keep scraping.
    observers[1].unbind_registry()
    families = _parse_exposition(reg.render())
    depth = families[f"{PREFIX}_engine_queue_depth"]["samples"]
    assert {labels["replica"] for _, labels, _ in depth} == {"0", "2"}


def _fake_fleet_request(
    rid="fr-0", *, status="ok", slo_class=None, slo_attained=None,
    n_tokens=5, t_first=1.05,
):
    """A terminal FleetRequest stand-in carrying the stamp/class fields
    FleetSpan.from_fleet_request flattens (no jax, no fleet)."""
    return SimpleNamespace(
        rid=rid, t_submit=1.0, t_admit=1.01, t_first=t_first,
        t_done=1.3, status=status, tokens=[7] * n_tokens,
        slo_class=slo_class, slo_attained=slo_attained, failovers=0,
        attempts=[],
    )


def test_fleet_bridge_render_is_valid_exposition():
    """Drive the fleet bridge against a fake fleet (no jax) next to a
    replica-labeled engine bridge and parse the render: fleet families
    obey the exposition rules, per-replica state/paused gauges emit one
    sample per live replica, counters land as running-total deltas, and
    the SLO-class families carry the class label."""
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import FleetObserver

    reg = Registry()
    obs = FleetObserver(name="f0")
    obs.bind_registry(reg)
    replicas = [
        SimpleNamespace(index=0, state="active", paused=False),
        SimpleNamespace(index=1, state="draining", paused=True),
        SimpleNamespace(index=2, state="dead", paused=False),
    ]
    fleet = SimpleNamespace(
        queue=[1, 2], replicas=replicas, requests_submitted=5,
        generated_tokens=40, failover_requeues=2, drain_requeues=1,
        queue_rejections=3, replica_crashes=1, replica_hangs=0,
        slo_burn_rates=lambda: {"interactive": 1.5, "bulk": 0.0},
    )
    obs._bind(fleet)
    finished = [
        _fake_fleet_request(
            "fr-0", slo_class="interactive", slo_attained=True,
        ),
        _fake_fleet_request(
            "fr-1", status="failed", slo_class="interactive",
            slo_attained=False,
        ),
        _fake_fleet_request("fr-2", slo_class="bulk", slo_attained=True),
        # Cancelled before the verdict: excluded from attainment but
        # its stamps still pool into the unclassed histograms.
        _fake_fleet_request(
            "fr-3", status="cancelled", slo_class="bulk",
        ),
        _fake_fleet_request("fr-4"),  # untagged
    ]
    obs._fleet_step_end(fleet, finished)
    obs._fleet_step_end(fleet, [])  # unchanged totals push no deltas
    families = _parse_exposition(reg.render())
    assert families[f"{PREFIX}_fleet_requests_total"]["samples"][0][2] == 5.0
    assert families[f"{PREFIX}_fleet_tokens_total"]["samples"][0][2] == 40.0
    assert families[f"{PREFIX}_fleet_failovers_total"]["samples"][0][2] == 2.0
    crash = families[f"{PREFIX}_fleet_replica_failures_total"]["samples"]
    assert [(labels["kind"], v) for _, labels, v in crash] == [("crash", 1.0)]
    states = families[f"{PREFIX}_fleet_replica_state"]["samples"]
    assert {
        (labels["replica"], labels["state"]) for _, labels, _ in states
    } == {("0", "active"), ("1", "draining")}
    paused = families[f"{PREFIX}_fleet_replica_paused"]["samples"]
    assert {
        (labels["replica"], v) for _, labels, v in paused
    } == {("0", 0.0), ("1", 1.0)}
    by_state = families[f"{PREFIX}_fleet_replicas"]["samples"]
    assert {
        (labels["state"], v) for _, labels, v in by_state
    } == {("active", 1.0), ("draining", 1.0), ("dead", 1.0)}
    for fam in (
        f"{PREFIX}_fleet_ttft_seconds",
        f"{PREFIX}_fleet_e2e_seconds",
        f"{PREFIX}_fleet_queue_wait_seconds",
        f"{PREFIX}_fleet_class_ttft_seconds",
        f"{PREFIX}_fleet_class_tpot_seconds",
    ):
        _assert_histogram_sound(fam, families[fam])
    # Per-class attainment counters: every series carries the class
    # label; the cancelled request is excluded, the untagged one never
    # lands in a classed family.
    slo_req = families[f"{PREFIX}_fleet_slo_requests_total"]["samples"]
    assert {
        (labels["slo_class"], v) for _, labels, v in slo_req
    } == {("interactive", 2.0), ("bulk", 1.0)}
    slo_att = families[f"{PREFIX}_fleet_slo_attained_total"]["samples"]
    assert {
        (labels["slo_class"], v) for _, labels, v in slo_att
    } == {("interactive", 1.0), ("bulk", 1.0)}
    burn = families[f"{PREFIX}_fleet_slo_burn_rate"]["samples"]
    assert {
        (labels["slo_class"], v) for _, labels, v in burn
    } == {("interactive", 1.5), ("bulk", 0.0)}
    class_ttft = families[f"{PREFIX}_fleet_class_ttft_seconds"]["samples"]
    assert {
        labels["slo_class"] for _, labels, _ in class_ttft
    } == {"interactive", "bulk"}
    # The span ring filled alongside the registry pushes.
    assert [s.rid for s in obs.spans] == [
        "fr-0", "fr-1", "fr-2", "fr-3", "fr-4",
    ]
    assert obs.drain_spans() and not obs.spans


def test_fleet_spans_record_without_a_registry():
    """--trace-out without --metrics-port: the span ring must fill with
    no registry bound (the merged trace's raw material), bounded with
    counted drops."""
    from workloads.obs import FleetObserver

    obs = FleetObserver(name="f1", span_limit=2)
    fleet = SimpleNamespace(replicas=[], queue=[])
    obs._bind(fleet)
    obs._fleet_step_end(
        fleet, [_fake_fleet_request(f"fr-{i}") for i in range(3)]
    )
    assert [s.rid for s in obs.spans] == ["fr-1", "fr-2"]
    assert obs.dropped_spans == 1


def test_supervisor_bridge_render_is_valid_exposition():
    """Drive the supervisor bridge against a fake supervisor (no jax):
    counters land as running-total deltas (re-polling unchanged totals
    pushes nothing), the slots-by-state gauge emits every state, and
    the restore-time histogram obeys the exposition rules."""
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import SupervisorObserver

    reg = Registry()
    obs = SupervisorObserver(name="sup0")
    obs.bind_registry(reg)
    sup = SimpleNamespace(
        slots=[
            SimpleNamespace(state="serving"),
            SimpleNamespace(state="backoff"),
            SimpleNamespace(state="quarantined"),
        ],
        restarts_total=2, restart_failures=3, crash_loops=1,
        health_deferrals=0, restore_s=[0.08, 1.4],
    )
    obs._bind(sup)
    obs._supervisor_poll_end(sup)
    obs._supervisor_poll_end(sup)  # unchanged totals push no deltas
    families = _parse_exposition(reg.render())
    assert families[
        f"{PREFIX}_supervisor_restarts_total"
    ]["samples"][0][2] == 2.0
    assert families[
        f"{PREFIX}_supervisor_restart_failures_total"
    ]["samples"][0][2] == 3.0
    assert families[
        f"{PREFIX}_supervisor_crash_loops_total"
    ]["samples"][0][2] == 1.0
    slots = families[f"{PREFIX}_supervisor_slots"]["samples"]
    assert {
        (labels["state"], v) for _, labels, v in slots
    } == {
        ("serving", 1.0), ("backoff", 1.0), ("probing", 0.0),
        ("quarantined", 1.0), ("forgotten", 0.0),
    }
    restore = f"{PREFIX}_supervisor_restore_seconds"
    _assert_histogram_sound(restore, families[restore])
    count = [
        v for name, _, v in families[restore]["samples"]
        if name.endswith("_count")
    ]
    assert count == [2.0]  # both restores observed exactly once
    obs.unbind_registry()
    assert f"{PREFIX}_supervisor_slots" not in _parse_exposition(
        reg.render()
    )


def test_autoscaler_bridge_render_is_valid_exposition():
    """Drive the autoscaler bridge against a fake autoscaler (no jax):
    actuation counters land as running-total deltas, the per-action
    decisions counter carries the action label, and the ladder/target/
    live gauges scrape — then unbind releases the gauges."""
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import AutoscalerObserver

    reg = Registry()
    obs = AutoscalerObserver(name="asc0")
    obs.bind_registry(reg)
    asc = SimpleNamespace(
        scale_ups=3, scale_downs=1, spawn_failures=2, brownouts=1,
        preemptions_total=4, ladder_level=2, target_replicas=3,
        decisions={"scale_up": 3, "brownout": 1, "preempt": 2},
        fleet=SimpleNamespace(alive=[1, 2]),
    )
    obs._bind(asc)
    obs._autoscaler_poll_end(asc)
    obs._autoscaler_poll_end(asc)  # unchanged totals push no deltas
    families = _parse_exposition(reg.render())
    assert families[
        f"{PREFIX}_autoscaler_scale_ups_total"
    ]["samples"][0][2] == 3.0
    assert families[
        f"{PREFIX}_autoscaler_preemptions_total"
    ]["samples"][0][2] == 4.0
    decisions = families[
        f"{PREFIX}_autoscaler_decisions_total"
    ]["samples"]
    assert {
        (labels["action"], v) for _, labels, v in decisions
    } == {("scale_up", 3.0), ("brownout", 1.0), ("preempt", 2.0)}
    assert families[
        f"{PREFIX}_autoscaler_ladder_level"
    ]["samples"][0][2] == 2.0
    assert families[
        f"{PREFIX}_autoscaler_replicas_target"
    ]["samples"][0][2] == 3.0
    assert families[
        f"{PREFIX}_autoscaler_replicas_live"
    ]["samples"][0][2] == 2.0
    obs.unbind_registry()
    assert f"{PREFIX}_autoscaler_ladder_level" not in _parse_exposition(
        reg.render()
    )


def test_ring_overflow_counters_are_scrapeable():
    """Satellite contract: observer ring evictions (dropped_steps /
    dropped_spans / dropped_events) land on the registry as counters,
    so silent history loss is a scrapeable signal."""
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import EngineObserver, FleetObserver, SupervisorObserver

    reg = Registry()
    obs = EngineObserver(name="tiny", step_limit=1, span_limit=1)
    obs.bind_registry(reg)
    _drive_fake_engine(obs, steps=4)  # 4 steps into a 1-deep ring
    families = _parse_exposition(reg.render())
    drops = families[f"{PREFIX}_engine_observer_dropped_steps_total"]
    assert drops["samples"][0][2] == 3.0
    assert f"{PREFIX}_engine_observer_dropped_spans_total" not in families

    fobs = FleetObserver(name="f0", span_limit=1)
    fobs.bind_registry(reg)
    fleet = SimpleNamespace(
        queue=[], replicas=[], requests_submitted=0, generated_tokens=0,
        failover_requeues=0, drain_requeues=0, queue_rejections=0,
        replica_crashes=0, replica_hangs=0,
        slo_burn_rates=lambda: {},
    )
    fobs._bind(fleet)
    fobs._fleet_step_end(
        fleet, [_fake_fleet_request(f"fr-{i}") for i in range(3)]
    )
    families = _parse_exposition(reg.render())
    fdrops = families[f"{PREFIX}_fleet_observer_dropped_spans_total"]
    assert fdrops["samples"][0][2] == 2.0

    sobs = SupervisorObserver(name="s0")
    sobs.bind_registry(reg)
    sup = SimpleNamespace(
        slots=[], restarts_total=0, restart_failures=0, crash_loops=0,
        health_deferrals=0, restore_s=[], dropped_events=5,
    )
    sobs._bind(sup)
    sobs._supervisor_poll_end(sup)
    sobs._supervisor_poll_end(sup)  # unchanged total pushes no delta
    families = _parse_exposition(reg.render())
    sdrops = families[f"{PREFIX}_supervisor_dropped_events_total"]
    assert sdrops["samples"][0][2] == 5.0


def test_ledger_families_render_as_valid_exposition():
    """Drive the engine bridge over a fake engine carrying a REAL
    ChipTimeLedger (still no jax): the phase/token counter families
    push as deltas, the fraction/pending gauges and the per-class
    waste-seconds gauge scrape, and a ledger-less engine emits no
    ledger series at all."""
    import numpy as np

    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.ledger import ChipTimeLedger, WASTE_CLASSES
    from workloads.obs import EngineObserver

    reg = Registry()
    obs = EngineObserver(name="led")
    obs.bind_registry(reg)
    led = ChipTimeLedger()
    eng = SimpleNamespace(
        generated_tokens=0, requests_admitted=0, requests_retired=0,
        prefill_dispatches=0, prefill_sweeps=0, chunks_run=0, spec_rounds=0,
        mode_switches=0, admission_readbacks=0, spec_lookahead=1,
        prefill_deferred_tokens=0, _inflight_prefill=[],
        pending=[], _occupied=np.zeros(2, bool), slots=2,
        ctrl=SimpleNamespace(used_pages=0), paused=False,
        tokens_overdecoded=0, spec_tokens_rejected=0, tokens_replayed=0,
        preempt_recompute_tokens=0, kv_spill_s=0.0, kv_reload_s=0.0,
        kv_handoff_s=0.0, prefill_tokens=0, superstep_k=1,
        spec_superstep_k=1, host_sync_s=0.0, ledger_phase="serve",
        ledger=led,
    )
    obs._bind(eng)
    finished = SimpleNamespace(
        rid="req-0", t_submit=1.0, t_admit=1.1, t_first=1.5, t_done=3.0,
        tokens=[7] * 6, status="ok",
    )
    for i in range(2):
        lsnap = led.step_begin(eng)
        snap = obs._step_begin(eng)
        eng.generated_tokens += 3
        eng.chunks_run += 1
        if i == 1:
            eng.tokens_replayed += 4
        done = [finished] if i == 1 else []
        led.step_end(eng, lsnap, done)
        obs._step_end(eng, snap, done)
    families = _parse_exposition(reg.render())
    tokens = families[f"{PREFIX}_ledger_tokens_total"]["samples"]
    by_class = {labels["class"]: v for _, labels, v in tokens}
    assert by_class["goodput"] == 6.0
    assert by_class["replay"] == 4.0
    chip = families[f"{PREFIX}_ledger_chip_seconds_total"]["samples"]
    assert {labels["phase"] for _, labels, _ in chip} >= {"decode"}
    assert families[f"{PREFIX}_ledger_pending_tokens"]["samples"][0][2] == 0.0
    frac = families[f"{PREFIX}_ledger_goodput_fraction"]["samples"][0][2]
    assert frac == pytest.approx(6.0 / 10.0)
    waste_s = families[f"{PREFIX}_ledger_waste_chip_seconds"]["samples"]
    assert {labels["class"] for _, labels, _ in waste_s} == set(WASTE_CLASSES)
    # A ledger-less engine emits no ledger SAMPLES (described help
    # text is fine; series are not).
    reg2 = Registry()
    obs2 = EngineObserver(name="bare")
    obs2.bind_registry(reg2)
    _drive_fake_engine(obs2)
    samples = [
        ln for ln in reg2.render().splitlines()
        if not ln.startswith("#") and ln.startswith(f"{PREFIX}_ledger_")
    ]
    assert samples == []


def test_fleet_ledger_families_render_as_valid_exposition():
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.ledger import ChipTimeLedger, FleetLedger
    from workloads.obs import FleetObserver

    reg = Registry()
    obs = FleetObserver(name="fl")
    obs.bind_registry(reg)
    fled = FleetLedger()
    fled.attach("0", ChipTimeLedger())
    fleet = SimpleNamespace(
        queue=[], replicas=[], requests_submitted=2, generated_tokens=9,
        failover_requeues=0, drain_requeues=0, queue_rejections=0,
        replica_crashes=0, replica_hangs=0, tokens_replayed=0,
        slo_burn_rates=lambda: {}, ledger=fled,
    )
    obs._bind(fleet)
    finished = [
        _fake_fleet_request("fr-0", slo_class="interactive",
                            slo_attained=True, n_tokens=6),
        _fake_fleet_request("fr-1", status="failed", slo_class="bulk",
                            n_tokens=3),
    ]
    fled.step_end(fleet, finished)
    obs._fleet_step_end(fleet, finished)
    obs._fleet_step_end(fleet, [])  # unchanged totals push no deltas
    families = _parse_exposition(reg.render())
    tokens = families[f"{PREFIX}_fleet_ledger_tokens_total"]["samples"]
    assert {
        (labels["slo_class"], labels["kind"], v)
        for _, labels, v in tokens
    } == {("interactive", "goodput", 6.0), ("bulk", "waste", 3.0)}
    frac = families[f"{PREFIX}_fleet_ledger_goodput_fraction"]
    assert frac["samples"][0][2] == pytest.approx(6.0 / 9.0)


def test_metrics_http_scrape_sets_prometheus_content_type():
    """HTTP scrape contract: /metrics serves the standard Prometheus
    text exposition content type (``text/plain; version=0.0.4``) — the
    version parameter is what lets scrapers negotiate the format — and
    the body it ships is valid exposition of the bound registry."""
    import urllib.request

    from tpu_device_plugin.metrics import PREFIX, MetricsServer, Registry
    from workloads.obs import EngineObserver

    reg = Registry()
    obs = EngineObserver()
    obs.bind_registry(reg)
    _drive_fake_engine(obs)

    server = MetricsServer(0, reg)  # ephemeral port
    port = server.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as resp:
            assert resp.headers["Content-Type"] == "text/plain; version=0.0.4"
            body = resp.read().decode()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as resp:
            # /healthz is NOT exposition; it must not claim the format.
            assert resp.headers["Content-Type"] == "text/plain"
    finally:
        server.stop()

    families = _parse_exposition(body)
    assert f"{PREFIX}_engine_decode_steps_total" in families
    for fam, info in families.items():
        if info["type"] == "histogram":
            _assert_histogram_sound(fam, info)

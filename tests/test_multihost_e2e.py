"""Multi-host slice end-to-end: two hosts of a v5p-16, full daemon stack each.

BASELINE configs[4] ("Multi-host v5p-16 slice: GetPreferredAllocation packs
ICI-adjacent chips across hosts").  The device-plugin API is node-local, so a
v5p-16 (topology 2x2x4, host grid 1,1,4, 4 chips/host) runs one daemon per
host; this test simulates two of the four hosts in-process — each with its own
fake kubelet, its own chips, and the same slice flags apart from the worker id
— and checks the cross-host contract:

  * both daemons advertise the same resource with host-local devices;
  * Allocate stamps each container with ITS host's global-slice environment
    (TPU_WORKER_ID differs, grids match) so a one-worker-per-host job can
    initialise multi-host JAX;
  * preferred allocation on each host packs an ICI-compact set in *global*
    coordinates (the reference has no cross-host story at all — SURVEY.md §5).
"""

import threading

import pytest

from tpu_device_plugin.api import pb
from tpu_device_plugin.backend.fake import FakeChipManager
from tpu_device_plugin.config import Config, Flags
from tpu_device_plugin.main import Daemon

from .fake_kubelet import FakeKubelet

V5P16 = dict(slice_topology="2x2x4", slice_host_bounds="1,1,4")


class Host:
    """One simulated slice member: fake kubelet + full daemon."""

    def __init__(self, tmp_path, worker_id: int, n_chips: int = 4):
        self.worker_id = worker_id
        self.kubelet = FakeKubelet(str(tmp_path / f"host{worker_id}" / "dp"))
        self.kubelet.start()
        flags = Flags(
            backend="fake",
            fake_topology=f"{n_chips}x4",
            slice_worker_id=worker_id,
            device_plugin_path=self.kubelet.plugin_dir,
            **V5P16,
        )
        self.daemon = Daemon(
            Config(flags=flags),
            backend=FakeChipManager(
                n_chips=n_chips,
                chips_per_tray=4,
                accelerator_type="v5p",
                id_prefix=f"h{worker_id}-tpu",
            ),
            lease_dir=str(tmp_path / f"host{worker_id}" / "leases"),
        )
        self.result: dict = {}
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()
        self.registration = self.kubelet.wait_for_registration()
        assert self.daemon.started.wait(5)
        self.stub = self.kubelet.plugin_client(self.registration.endpoint)

    def _run(self):
        self.result["code"] = self.daemon.run()

    def stop(self):
        self.daemon.request_stop()
        self.thread.join(timeout=10)
        self.kubelet.stop()

    def devices(self):
        stream = self.stub.ListAndWatch(pb.Empty())
        devices = list(next(iter(stream)).devices)
        stream.cancel()
        return devices


@pytest.fixture
def hosts(tmp_path):
    members = [Host(tmp_path, worker_id=0), Host(tmp_path, worker_id=2)]
    yield members
    for h in members:  # stop everything before asserting, so one hung
        h.stop()  # daemon can't leak the other host's servers
    for h in members:
        assert not h.thread.is_alive(), f"host {h.worker_id} daemon did not stop"
        assert h.result["code"] == 0


def test_both_hosts_advertise_same_resource_with_local_devices(hosts):
    h0, h2 = hosts
    assert h0.registration.resource_name == h2.registration.resource_name
    ids0 = {d.ID for d in h0.devices()}
    ids2 = {d.ID for d in h2.devices()}
    assert len(ids0) == len(ids2) == 4
    assert ids0.isdisjoint(ids2)  # node-local advertisement, no phantom remotes


def test_allocate_stamps_per_host_slice_env(hosts):
    for host in hosts:
        ids = sorted(d.ID for d in host.devices())
        resp = host.stub.Allocate(
            pb.AllocateRequest(
                container_requests=[pb.ContainerAllocateRequest(devicesIDs=ids)]
            )
        )
        envs = dict(resp.container_responses[0].envs)
        assert envs["TPU_WORKER_ID"] == str(host.worker_id)
        assert envs["TPU_TOPOLOGY"] == "2x2x4"
        assert envs["TPU_HOST_BOUNDS"] == "1,1,4"


def test_preferred_allocation_packs_ici_compact_global_sets(hosts):
    """Size-2 requests come back as global-coordinate ICI neighbours.

    Each v5p host block is 2x2x1, so within a host every chip pair differs by
    one hop in x or y, except diagonal pairs (2 hops).  The policy must avoid
    the diagonals: for a must-include corner chip, the partner is an adjacent
    chip, never the diagonal one.
    """
    for host in hosts:
        ids = sorted(d.ID for d in host.devices())
        # Host chips are laid out row-major in the 2x2 block:
        # index 0=(0,0) 1=(1,0) 2=(0,1) 3=(1,1); 0-3 and 1-2 are diagonals.
        corner, diagonal = ids[0], ids[3]
        pref = host.stub.GetPreferredAllocation(
            pb.PreferredAllocationRequest(
                container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=ids,
                        must_include_deviceIDs=[corner],
                        allocation_size=2,
                    )
                ]
            )
        )
        chosen = set(pref.container_responses[0].deviceIDs)
        assert corner in chosen and len(chosen) == 2
        assert diagonal not in chosen, (
            f"host {host.worker_id}: picked diagonal {chosen} over an ICI neighbour"
        )

"""Fast replica start (workloads/faststart.py): the persistent compile
cache + warm-state EngineSnapshot subsystem that makes respawns and
scale-ups cheap enough for fleet capacity to be fluid.

The pinned contracts: a snapshot round-trips through JSON/disk exactly;
a snapshot-primed respawn skips the spec-breakeven calibration's dead
dispatches (``calibration_reused`` ticks) while its streams stay
bit-identical to a cold-spawned oracle engine — greedy AND sampled,
spec="auto" bare and with ``spec_superstep_k`` armed; a stale snapshot
(config or version mismatch) is REJECTED and the engine calibrates cold
(never serves a foreign table or threshold); the supervisor and
autoscaler consume the snapshot at their calibrate_probe/resurrection/
scale-up seams; and the per-engine compile-cache + calibration-reuse
counters surface through obs.py onto the metrics registry.
"""

import dataclasses
import inspect
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.backoff import Backoff
from workloads.faststart import (
    SNAPSHOT_VERSION,
    EngineSnapshot,
    cache_stats,
    compile_cache_dir,
    enable_compile_cache,
    fingerprint_engine,
)
from workloads.faults import FaultInjector
from workloads.fleet import Fleet
from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine
from workloads.supervisor import FleetSupervisor, make_engine_factory

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)
PROBE = ([1, 2, 3], 4)
ENGINE_KW = dict(slots=2, page_size=4, prompt_bucket=8)
FAST = Backoff(base_s=1e-3, factor=2.0, max_s=8e-3, jitter=0.0)


@pytest.fixture(scope="module")
def models():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    return params, draft


def _auto_engine(params, draft, **kw):
    base = dict(ENGINE_KW)
    base.update(kw)
    return ServeEngine(
        params, CONFIG, draft_params=draft, draft_config=DRAFT_CONFIG,
        gamma=3, spec="auto", **base,
    )


def _auto_kw(draft, **kw):
    base = dict(
        ENGINE_KW, draft_params=draft, draft_config=DRAFT_CONFIG,
        gamma=3, spec="auto",
    )
    base.update(kw)
    return base


def _ref(params, prompt, new):
    return [int(t) for t in np.asarray(generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG,
        max_new_tokens=new,
    )[0])]


def _serve(engine, requests):
    rids = [engine.submit(p, n) for p, n in requests]
    out = engine.run()
    return [list(out[r]) for r in rids]


def _calibrated_snapshot(params, draft, **kw):
    """Build, warm (calibration runs at the first decode step) and
    capture — the producer side every consumer test primes from."""
    engine = _auto_engine(params, draft, **kw)
    rid = engine.submit(PROBE[0], PROBE[1])
    out = engine.run()
    assert engine.spec_breakeven is not None
    assert engine.spec_calibration is not None
    assert engine.calibration_reused == 0  # cold producer, by definition
    snap = EngineSnapshot.capture(
        engine, probe=PROBE, probe_oracle=list(out[rid]),
    )
    engine.close()
    return snap


# ---- snapshot round-trip -------------------------------------------------


def test_snapshot_round_trip(models, tmp_path):
    """capture -> to_json -> from_json -> save -> load is exact: every
    field (including the int-keyed kernel table and the probe tuple)
    survives, and the reloaded snapshot still primes."""
    params, draft = models
    from workloads.ops.kernel_select import set_kernel_table

    set_kernel_table({64: "flash", 128: "xla"})
    try:
        snap = _calibrated_snapshot(params, draft)
    finally:
        set_kernel_table(None)
    assert snap.version == SNAPSHOT_VERSION
    assert snap.spec_breakeven is not None
    assert snap.spec_calibration["threshold"] == snap.spec_breakeven
    assert snap.kernel_table == {64: "flash", 128: "xla"}
    assert snap.probe == ([1, 2, 3], 4)
    assert snap.meta["jax"] == jax.__version__

    again = EngineSnapshot.from_json(snap.to_json())
    assert dataclasses.asdict(again) == dataclasses.asdict(snap)
    assert again.kernel_table == {64: "flash", 128: "xla"}  # int keys

    path = snap.save(str(tmp_path / "snap.json"))
    loaded = EngineSnapshot.load(path)
    assert dataclasses.asdict(loaded) == dataclasses.asdict(snap)

    engine = _auto_engine(params, draft)
    try:
        assert loaded.compatible(engine)
        assert loaded.prime(engine)
        assert engine._injected_calibration == snap.spec_calibration
    finally:
        engine.close()
        set_kernel_table(None)  # prime restored the captured table


def test_fingerprint_tracks_shape_not_weights(models):
    """The compatibility key moves with anything that shapes the
    compile set or calibration verdict — and ONLY with those (two
    same-shape engines share a key)."""
    params, draft = models
    a = _auto_engine(params, draft)
    b = _auto_engine(params, draft)
    c = _auto_engine(params, draft, slots=3)
    d = _auto_engine(params, draft, spec_superstep_k=2)
    try:
        assert fingerprint_engine(a) == fingerprint_engine(b)
        assert fingerprint_engine(a) != fingerprint_engine(c)
        assert fingerprint_engine(a) != fingerprint_engine(d)
    finally:
        for e in (a, b, c, d):
            e.close()


# ---- respawn bit-identity vs a cold oracle engine ------------------------


REQUESTS_GREEDY = [([5, 6, 7], 12), ([1, 2], 6), ([9], 4)]


@pytest.mark.parametrize("extra_kw", [
    {},                        # spec="auto" bare
    {"spec_superstep_k": 2},   # chained spec supersteps armed
])
def test_primed_respawn_streams_bit_identical_greedy(models, extra_kw):
    """The acceptance pin: a snapshot-primed respawn skips calibration
    (calibration_reused == 1, the calibration dict adopted verbatim)
    and its greedy streams are bit-identical to a COLD-spawned engine's
    and to the dense oracle."""
    params, draft = models
    snap = _calibrated_snapshot(params, draft, **extra_kw)

    cold = _auto_engine(params, draft, **extra_kw)
    cold_streams = _serve(cold, REQUESTS_GREEDY)
    assert cold.calibration_reused == 0
    cold.close()

    warm = _auto_engine(params, draft, **extra_kw)
    assert snap.prime(warm)
    warm_streams = _serve(warm, REQUESTS_GREEDY)
    assert warm.calibration_reused == 1
    assert warm.spec_calibration == snap.spec_calibration
    assert warm.spec_breakeven == snap.spec_breakeven
    warm.close()

    assert warm_streams == cold_streams
    for (prompt, new), stream in zip(REQUESTS_GREEDY, warm_streams):
        assert stream == _ref(params, prompt, new)


def test_primed_respawn_streams_bit_identical_sampled(models):
    """Same contract at temperature > 0: calibration uses a private
    rng key, so skipping it must not perturb the served sampling
    stream's key schedule — sampled streams are bit-identical snapshot
    on/off."""
    params, draft = models
    kw = dict(temperature=0.8, top_k=20)
    snap = _calibrated_snapshot(
        params, draft, rng=jax.random.PRNGKey(123), **kw
    )

    requests = [([5, 6, 7], 10), ([2, 4], 6)]
    cold = _auto_engine(params, draft, rng=jax.random.PRNGKey(123), **kw)
    cold_streams = _serve(cold, requests)
    cold.close()

    warm = _auto_engine(params, draft, rng=jax.random.PRNGKey(123), **kw)
    assert snap.prime(warm)
    warm_streams = _serve(warm, requests)
    assert warm.calibration_reused == 1
    warm.close()
    assert warm_streams == cold_streams


def test_constructor_injection_matches_prime(models):
    """spec_calibration= at construction (the engine_kw() path) is the
    same seam prime() rides: calibration skipped, same streams."""
    params, draft = models
    snap = _calibrated_snapshot(params, draft)
    assert snap.engine_kw() == {"spec_calibration": snap.spec_calibration}
    engine = _auto_engine(params, draft, **snap.engine_kw())
    streams = _serve(engine, REQUESTS_GREEDY)
    assert engine.calibration_reused == 1
    assert engine.spec_breakeven == snap.spec_breakeven
    engine.close()
    for (prompt, new), stream in zip(REQUESTS_GREEDY, streams):
        assert stream == _ref(params, prompt, new)


def test_spec_calibration_kwarg_contract(models):
    params, draft = models
    with pytest.raises(ValueError, match="spec_calibration"):
        ServeEngine(params, CONFIG, spec_calibration={"threshold": 1.0})
    with pytest.raises(ValueError, match="threshold"):
        _auto_engine(params, draft, spec_calibration={"bogus": 1.0})


# ---- stale-snapshot rejection --------------------------------------------


def test_stale_snapshot_rejected_config_mismatch(models):
    """A snapshot from a different engine shape must NOT apply: prime
    returns False, nothing is injected, and the engine calibrates
    itself cold — wrong-threshold poisoning is structurally
    impossible."""
    params, draft = models
    snap = _calibrated_snapshot(params, draft)
    other = _auto_engine(params, draft, slots=3)
    assert not snap.compatible(other)
    assert snap.prime(other) is False
    assert other._injected_calibration is None
    rid = other.submit([1, 2, 3], 6)
    out = other.run()
    assert other.calibration_reused == 0          # cold path ran
    assert other.spec_calibration is not None     # ... and measured
    assert list(out[rid]) == _ref(params, [1, 2, 3], 6)
    other.close()


def test_stale_snapshot_rejected_version_mismatch(models):
    params, draft = models
    snap = _calibrated_snapshot(params, draft)
    blob = json.loads(snap.to_json())
    blob["version"] = SNAPSHOT_VERSION + 1
    foreign = EngineSnapshot.from_json(json.dumps(blob))
    engine = _auto_engine(params, draft)
    assert not foreign.compatible(engine)
    assert foreign.prime(engine) is False
    assert engine._injected_calibration is None
    engine.close()


def test_factory_with_incompatible_snapshot_spawns_cold(models):
    """make_engine_factory(snapshot=) with a foreign-shape snapshot
    still spawns working engines — prime no-ops, the cold path
    serves."""
    params, draft = models
    snap = _calibrated_snapshot(params, draft, slots=3)  # foreign shape
    factory, oracle = make_engine_factory(
        params, CONFIG, engine_kw=_auto_kw(draft), snapshot=snap,
    )
    assert oracle == snap.probe_oracle  # the oracle still seeds
    engine = factory(None)
    assert engine._injected_calibration is None
    rid = engine.submit([1, 2, 3], 6)
    out = engine.run()
    assert engine.calibration_reused == 0
    assert list(out[rid]) == _ref(params, [1, 2, 3], 6)
    engine.close()


# ---- supervisor + autoscaler reuse ---------------------------------------


def test_supervisor_calibrate_probe_reuses_snapshot_oracle(models):
    """FleetSupervisor(snapshot=): the snapshot's captured probe oracle
    seeds the canary — calibrate_probe() returns WITHOUT building a
    scratch engine — and a crashed replica's respawn consumes the
    snapshot (calibration_reused ticks) while ok streams stay
    bit-identical to the dense oracle through the failover."""
    params, draft = models
    engine_kw = _auto_kw(draft)
    snap = _calibrated_snapshot(params, draft)
    factory_calls = []
    base_factory, oracle = make_engine_factory(
        params, CONFIG, engine_kw=engine_kw, snapshot=snap,
    )
    assert oracle == snap.probe_oracle

    def factory(slot):
        factory_calls.append(slot)
        return base_factory(slot)

    fleet = Fleet(
        [ServeEngine(params, CONFIG, **engine_kw) for _ in range(2)],
        chip_ids=["chip-0", "chip-1"], hang_timeout_s=None,
        fault_injector=FaultInjector({"replica_crash": 3}),
    )
    sup = FleetSupervisor(
        fleet, factory, backoff=FAST, probe=PROBE, snapshot=snap,
    )
    # The scratch-calibration seam: with the snapshot's oracle seeded,
    # arming builds NO scratch engine.
    assert sup.calibrate_probe() == snap.probe_oracle
    assert factory_calls == []

    reqs = REQUESTS_GREEDY * 2
    rids = [fleet.submit(p, n) for p, n in reqs]
    sup.run()
    terminal = {fr.rid: fr.status for fr in fleet.completed}
    assert fleet.replica_crashes == 1
    assert sup.wait_healed(20.0), sup.states()
    for rid, (p, n) in zip(rids, reqs):
        ref = _ref(params, p, n)
        fr = fleet._reqs[rid]
        if terminal.get(rid) == "ok":
            assert fr.tokens == ref, rid
        else:
            assert fr.tokens == ref[: len(fr.tokens)], rid
    # Exactly the resurrection went through the factory, and the
    # respawned replica consumed the snapshot instead of re-calibrating.
    assert len(factory_calls) >= 1
    reused = sum(
        r.engine.calibration_reused for r in fleet.replicas
        if r.engine is not None
    )
    assert reused >= 1
    fleet.close()


def test_supervisor_ignores_snapshot_with_foreign_probe(models):
    """A snapshot captured against a DIFFERENT canary must not seed the
    oracle — the supervisor keeps its scratch-calibration path."""
    params, draft = models
    snap = _calibrated_snapshot(params, draft)  # snap.probe == PROBE
    fleet = Fleet(
        [ServeEngine(params, CONFIG, **_auto_kw(draft))],
        chip_ids=["chip-0"], hang_timeout_s=None,
    )
    sup = FleetSupervisor(
        fleet, lambda slot: None, probe=([7, 8], 3), snapshot=snap,
    )
    assert sup._probe_oracle is None
    fleet.close()


def test_autoscaler_scaleup_consumes_snapshot(models):
    """FleetAutoscaler(snapshot=): the oracle seeds from the snapshot
    (calibrate_probe builds nothing) and a probed scale-up joins a
    replica whose calibration came from the snapshot — and that
    replica serves oracle-true."""
    from workloads.autoscaler import FleetAutoscaler

    params, draft = models
    engine_kw = _auto_kw(draft)
    snap = _calibrated_snapshot(params, draft)
    factory, _ = make_engine_factory(
        params, CONFIG, engine_kw=engine_kw, snapshot=snap,
    )
    fleet = Fleet(
        [ServeEngine(params, CONFIG, **engine_kw)],
        chip_ids=["chip-0"], hang_timeout_s=None,
    )
    asc = FleetAutoscaler(
        fleet, factory, min_replicas=1, max_replicas=2,
        probe=PROBE, snapshot=snap,
        up_backoff=Backoff(base_s=1e-3, max_s=8e-3, jitter=0.0),
    )
    assert asc.calibrate_probe() == snap.probe_oracle
    assert asc._try_scale_up(asc._clock())
    assert len(fleet.replicas) == 2
    joined = fleet.replicas[1].engine
    assert joined.calibration_reused == 1
    assert joined.spec_breakeven == snap.spec_breakeven
    rids = [fleet.submit(p, n) for p, n in REQUESTS_GREEDY]
    out = fleet.run()
    for rid, (p, n) in zip(rids, REQUESTS_GREEDY):
        assert out[rid] == _ref(params, p, n)
    fleet.close()


def test_fleet_add_replica_primes(models):
    """Fleet.add_replica(snapshot=): a live joiner is primed before it
    takes traffic — drain the incumbent and the joiner serves with the
    snapshot's calibration, never re-running the dead dispatches."""
    params, draft = models
    snap = _calibrated_snapshot(params, draft)
    engine_kw = _auto_kw(draft)
    fleet = Fleet(
        [ServeEngine(params, CONFIG, **engine_kw)],
        chip_ids=["chip-0"], hang_timeout_s=None,
    )
    joiner = ServeEngine(params, CONFIG, **engine_kw)
    index = fleet.add_replica(joiner, "chip-1", snapshot=snap)
    assert index == 1
    assert joiner._injected_calibration == snap.spec_calibration
    fleet.drain(0)  # route everything to the primed joiner
    rid = fleet.submit([1, 2, 3], 6)
    out = fleet.run()
    assert out[rid] == _ref(params, [1, 2, 3], 6)
    assert joiner.calibration_reused == 1
    fleet.close()


# ---- compile cache + counters --------------------------------------------


def test_compile_cache_enable_and_engine_deltas(models, tmp_path):
    """enable_compile_cache points jax at the directory (idempotently,
    returning the canonical path) and each engine reports hit/miss
    DELTAS against the process-wide counters from its own birth."""
    params, draft = models
    cache = str(tmp_path / "cc")
    enabled = enable_compile_cache(cache)
    assert enabled == compile_cache_dir() == os.path.abspath(cache)
    assert enable_compile_cache(cache) == enabled  # idempotent
    before = cache_stats()
    engine = _auto_engine(params, draft)
    rid = engine.submit([1, 2, 3], 6)
    out = engine.run()
    assert list(out[rid]) == _ref(params, [1, 2, 3], 6)
    after = cache_stats()
    assert engine.compile_cache_hits == after["hits"] - before["hits"]
    assert engine.compile_cache_misses == (
        after["misses"] - before["misses"]
    )
    engine.close()


def test_faststart_counters_reach_registry(models):
    """The obs.py mirror: calibration_reused (and the compile-cache
    families) are catalogued ENGINE_METRICS, and a primed engine's
    skip lands on a bound metrics registry as a counter series."""
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import ENGINE_METRICS, EngineObserver

    names = {m.name for m in ENGINE_METRICS}
    for name in (
        "engine_calibration_reused_total",
        "engine_compile_cache_hits_total",
        "engine_compile_cache_misses_total",
    ):
        assert name in names
    params, draft = models
    snap = _calibrated_snapshot(params, draft)
    reg = Registry()
    obs = EngineObserver()
    engine = _auto_engine(params, draft, observer=obs)
    obs.bind_registry(reg)
    assert snap.prime(engine)
    rid = engine.submit([1, 2, 3], 6)
    out = engine.run()
    assert list(out[rid]) == _ref(params, [1, 2, 3], 6)
    assert engine.calibration_reused == 1
    text = reg.render()
    assert "engine_calibration_reused_total" in text
    engine.close()


def test_serve_cli_and_constructor_expose_compile_cache(models, tmp_path):
    """The two wiring points: the ServeEngine constructor kwarg enables
    the process cache before its first compile, and the serve CLI
    carries the matching --compile-cache-dir flag."""
    from workloads import serve

    params, draft = models
    cache = str(tmp_path / "ctor-cc")
    engine = _auto_engine(params, draft, compile_cache_dir=cache)
    assert compile_cache_dir() == os.path.abspath(cache)
    engine.close()
    assert "--compile-cache-dir" in inspect.getsource(serve.main)


def test_smoke(models):
    """make faststart-check: a seeded crash under supervision with the
    snapshot armed — the respawn skips calibration and ok streams stay
    bit-identical to the dense oracle (the acceptance contract in one
    fast pin)."""
    test_supervisor_calibrate_probe_reuses_snapshot_oracle(models)

"""Property-based spec of the replica allocator (hypothesis).

The reference's table tests pin specific cases; these properties pin the
invariants for ALL inputs: completeness, membership, no-double-spend of
replica IDs, correctness of the uniqueness verdict, and determinism.
"""

import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from tpu_device_plugin.replica import (  # noqa: E402
    AllocationError,
    prioritize_devices,
    strip_replica,
    strip_replicas,
)

chip_ids = st.sampled_from(["a", "b", "c", "d", "e"])
replica_pools = st.lists(
    st.tuples(chip_ids, st.integers(0, 5)).map(lambda t: f"{t[0]}-replica-{t[1]}"),
    min_size=0,
    max_size=20,
    unique=True,
)


@given(replica_pools, st.integers(0, 20), st.data())
@settings(max_examples=200, deadline=None)
def test_prioritize_invariants(available, size, data):
    must_include = data.draw(
        st.lists(st.sampled_from(available), max_size=min(size, len(available)), unique=True)
        if available and size
        else st.just([])
    )
    try:
        result = prioritize_devices(available, must_include, size)
    except AllocationError:
        # Legal only when the request is unsatisfiable.
        assert size > len(available) or any(
            m not in available for m in must_include
        ) or (size > 0 and not available)
        return
    devices = result.devices
    assert len(devices) == size
    assert len(set(devices)) == size  # no replica ID handed out twice
    assert set(devices) <= set(available)
    assert set(must_include) <= set(devices)
    assert devices == sorted(devices)
    chips = [strip_replica(d) for d in devices]
    if result.unique:
        assert len(set(chips)) == size  # verdict "unique" means distinct chips
    else:
        assert len(set(chips)) < size


@given(replica_pools, st.integers(0, 10))
@settings(max_examples=100, deadline=None)
def test_prioritize_deterministic(available, size):
    try:
        first = prioritize_devices(available, [], size)
        second = prioritize_devices(list(reversed(available)), [], size)
    except AllocationError:
        return
    assert first == second  # input order never matters


@given(replica_pools, st.integers(1, 10))
@settings(max_examples=100, deadline=None)
def test_prioritize_spreads_before_doubling(available, size):
    """No chip receives a second replica while another chip is untouched."""
    try:
        result = prioritize_devices(available, [], size)
    except AllocationError:
        return
    used = [strip_replica(d) for d in result.devices]
    counts = {c: used.count(c) for c in used}
    untouched = {strip_replica(a) for a in available} - set(used)
    if untouched:
        assert max(counts.values()) == 1


@given(st.lists(st.text(alphabet="ab-replic0123", max_size=12), max_size=10))
@settings(max_examples=100, deadline=None)
def test_strip_replicas_sorted_unique(ids):
    out = strip_replicas(ids)
    assert out == sorted(set(out))

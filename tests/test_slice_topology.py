"""Multi-host slice metadata: env/flag parsing, global coordinates, torus
wrap distances, and the global-slice container env injected by Allocate
(BASELINE configs[4])."""

import pytest

from tpu_device_plugin.backend.fake import FakeChipManager
from tpu_device_plugin.slice_topology import (
    SliceConfigError,
    SliceInfo,
    apply_slice,
    container_slice_env,
    slice_info_from_env,
)
from tpu_device_plugin.topology import build_fake_topology


V5P16_ENV = {
    "TPU_WORKER_ID": "1",
    "TPU_TOPOLOGY": "2x2x4",
    "TPU_HOST_BOUNDS": "1,1,4",
}


def test_parse_env():
    info = slice_info_from_env(V5P16_ENV)
    assert info == SliceInfo(worker_id=1, topology=(2, 2, 4), host_bounds=(1, 1, 4))
    assert info.n_hosts == 4
    assert info.chips_per_host_block == (2, 2, 1)
    assert info.host_offset(0) == (0, 0, 0)
    assert info.host_offset(1) == (0, 0, 1)
    assert info.host_offset(3) == (0, 0, 3)


def test_parse_env_absent_and_partial():
    assert slice_info_from_env({}) is None
    assert slice_info_from_env({"TPU_TOPOLOGY": "2x2x4"}) is None


def test_flag_overrides_beat_env():
    # Runtimes may rewrite the TPU_* metadata at process start; explicit
    # daemon flags win.
    info = slice_info_from_env(
        {"TPU_TOPOLOGY": "1x1", "TPU_HOST_BOUNDS": "1,1,1", "TPU_WORKER_ID": "0"},
        topology_override="2x2x4",
        host_bounds_override="1,1,4",
        worker_id_override=2,
    )
    assert info.topology == (2, 2, 4)
    assert info.worker_id == 2


@pytest.mark.parametrize(
    "env",
    [
        {**V5P16_ENV, "TPU_TOPOLOGY": "2x2x5"},  # not divisible by host grid
        {**V5P16_ENV, "TPU_WORKER_ID": "9"},  # outside host grid
        {**V5P16_ENV, "TPU_WORKER_ID": "x"},
        {**V5P16_ENV, "TPU_HOST_BOUNDS": "0,1,4"},
        {**V5P16_ENV, "TPU_TOPOLOGY": "axb"},
    ],
)
def test_parse_env_invalid(env):
    with pytest.raises(SliceConfigError):
        slice_info_from_env(env)


def test_wraparound_flag():
    info = slice_info_from_env({**V5P16_ENV, "TPU_TOPOLOGY_WRAP": "true,true,true"})
    assert info.wraparound == (True, True, True)
    # Per-axis: only the z axis is a ring.
    info = slice_info_from_env({**V5P16_ENV, "TPU_TOPOLOGY_WRAP": "false,false,true"})
    assert info.wraparound == (False, False, True)
    # A single value broadcasts.
    info = slice_info_from_env({**V5P16_ENV, "TPU_TOPOLOGY_WRAP": "true"})
    assert info.wraparound == (True, True, True)
    # Malformed ambient wrap degrades to no-wrap (never fatal: there is no
    # wrap flag, so this can only come from the node environment).
    info = slice_info_from_env({**V5P16_ENV, "TPU_TOPOLOGY_WRAP": "yes,no,maybe"})
    assert info.wraparound == (False, False, False)


def test_half_configured_slice_flags_rejected():
    # An explicit --slice-topology without --slice-host-bounds (and no env
    # fallback) must raise, not silently run node-local.
    with pytest.raises(SliceConfigError):
        slice_info_from_env({}, topology_override="2x2x4")
    with pytest.raises(SliceConfigError):
        slice_info_from_env({}, host_bounds_override="1,1,4")
    # A lone --slice-worker-id is just as explicit.
    with pytest.raises(SliceConfigError):
        slice_info_from_env({}, worker_id_override=2)
    assert slice_info_from_env({}, worker_id_override=-1) is None
    # ...but env can supply the missing half (worker id still required for
    # a multi-host grid).
    info = slice_info_from_env(
        {"TPU_HOST_BOUNDS": "1,1,4", "TPU_WORKER_ID": "2"},
        topology_override="2x2x4",
    )
    assert info.topology == (2, 2, 4)


def test_multi_host_slice_requires_worker_id():
    # Defaulting to worker 0 on a 4-host slice would make every host claim
    # block 0; must raise instead (metadata server also unreachable here).
    env = {k: v for k, v in V5P16_ENV.items() if k != "TPU_WORKER_ID"}
    with pytest.raises(SliceConfigError, match="worker id"):
        slice_info_from_env(env, metadata_worker_id=None)
    with pytest.raises(SliceConfigError, match="worker id"):
        slice_info_from_env(env, metadata_worker_id=lambda: None)
    # Single-host "slice" is fine without one.
    info = slice_info_from_env({"TPU_TOPOLOGY": "2x2x1", "TPU_HOST_BOUNDS": "1,1,1"})
    assert info.worker_id == 0


def test_worker_id_falls_back_to_node_metadata():
    """DaemonSet containers don't inherit the TPU VM env; the node metadata
    server (agent-worker-number) is the source of last resort."""
    env = {k: v for k, v in V5P16_ENV.items() if k != "TPU_WORKER_ID"}
    info = slice_info_from_env(env, metadata_worker_id=lambda: 3)
    assert info.worker_id == 3
    # Env beats metadata when both exist.
    info = slice_info_from_env(
        dict(env, TPU_WORKER_ID="1"), metadata_worker_id=lambda: 3
    )
    assert info.worker_id == 1


def test_daemon_exits_on_explicit_half_configured_slice_flags(tmp_path, monkeypatch):
    from tpu_device_plugin.config import Config, Flags
    from tpu_device_plugin.main import Daemon

    for k in ("TPU_TOPOLOGY", "TPU_HOST_BOUNDS", "TPU_WORKER_ID", "TPU_TOPOLOGY_WRAP"):
        monkeypatch.delenv(k, raising=False)
    flags = Flags(
        backend="fake",
        device_plugin_path=str(tmp_path / "dp"),
        slice_topology="2x2x4",  # no --slice-host-bounds, no env fallback
    )
    daemon = Daemon(Config(flags=flags), backend=FakeChipManager(n_chips=4),
                    lease_dir=str(tmp_path / "leases"))
    assert daemon.run() == 1


def test_apply_slice_global_coords_from_index_order():
    # 4 local chips laid out 4x1 locally; the slice block is 2x2, so global
    # in-block positions come from chip index order, NOT local coords —
    # distinct chips must never collide.
    topo = build_fake_topology(4, 4)
    assert topo.torus_shape == (4, 1, 1)
    info = slice_info_from_env(V5P16_ENV)  # worker 1 -> z offset 1
    apply_slice(topo, info)
    assert topo.torus_shape == (2, 2, 4)
    coords = {c.id: c.coords for c in topo.chips_by_id.values()}
    assert coords == {
        "tpu-0": (0, 0, 1),
        "tpu-1": (1, 0, 1),
        "tpu-2": (0, 1, 1),
        "tpu-3": (1, 1, 1),
    }
    assert len(set(coords.values())) == 4  # no collisions
    assert topo.slice_info is info


def test_apply_slice_wrap_distance():
    # With torus wrap, worker 0's block and worker 3's block are 1 hop apart
    # on the z ring; verify via a chip moved to each end.
    topo0 = build_fake_topology(4, 2)
    info_wrap = slice_info_from_env({**V5P16_ENV, "TPU_WORKER_ID": "0",
                                     "TPU_TOPOLOGY_WRAP": "true,true,true"})
    apply_slice(topo0, info_wrap)
    # Simulate a remote chip on worker 3's block for distance checking.
    topo0.remote_coords["far"] = (0, 0, 3)
    assert topo0.ici_distance("tpu-0", "far") == 1  # wraps around the ring


def test_apply_slice_per_axis_wrap():
    # z-only ring: z distances wrap, x distances don't.
    topo = build_fake_topology(4, 2)
    info = slice_info_from_env({**V5P16_ENV, "TPU_WORKER_ID": "0",
                                "TPU_TOPOLOGY_WRAP": "false,false,true"})
    apply_slice(topo, info)
    assert topo.wraparound == (False, False, True)
    topo.remote_coords["far-z"] = (0, 0, 3)
    assert topo.ici_distance("tpu-0", "far-z") == 1  # wraps on z
    # x axis must NOT wrap: distance along x stays |dx|.
    topo.remote_coords["far-x"] = (1, 0, 0)
    assert topo.ici_distance("tpu-0", "far-x") == 1
    env = container_slice_env(info)
    assert env["TPU_TOPOLOGY_WRAP"] == "false,false,true"


def test_apply_slice_mismatch_leaves_wrap_untouched():
    # Rejected slice metadata must not flip wraparound on the local topology.
    topo = build_fake_topology(8, 4)
    info = slice_info_from_env({**V5P16_ENV, "TPU_WORKER_ID": "0",
                                "TPU_TOPOLOGY_WRAP": "true,true,true"})
    with pytest.raises(SliceConfigError):
        apply_slice(topo, info)
    assert topo.slice_info is None
    assert not any(topo.wrap_axes())


def test_daemon_exits_on_explicit_slice_flags_with_mismatched_block(tmp_path, monkeypatch):
    # Explicit flags whose block can't fit this host's chips must fail loud
    # (8 local chips, per-host block of 4).
    from tpu_device_plugin.config import Config, Flags
    from tpu_device_plugin.main import Daemon

    for k in ("TPU_TOPOLOGY", "TPU_HOST_BOUNDS", "TPU_WORKER_ID", "TPU_TOPOLOGY_WRAP"):
        monkeypatch.delenv(k, raising=False)
    flags = Flags(
        backend="fake",
        device_plugin_path=str(tmp_path / "dp"),
        slice_topology="2x2x4",
        slice_host_bounds="1,1,4",
        slice_worker_id=0,
    )
    daemon = Daemon(Config(flags=flags), backend=FakeChipManager(n_chips=8),
                    lease_dir=str(tmp_path / "leases"))
    assert daemon.run() == 1


def test_apply_slice_mismatched_block_raises_and_leaves_topo_untouched():
    topo = build_fake_topology(8, 4)  # 8 local chips, block would be 4
    info = SliceInfo(worker_id=0, topology=(2, 2, 2), host_bounds=(1, 1, 2))
    with pytest.raises(SliceConfigError):
        apply_slice(topo, info)
    assert topo.slice_info is None
    assert topo.torus_shape == (4, 2, 1)  # untouched
    assert topo.chips_by_id["tpu-0"].coords == (0, 0, 0)


def test_container_slice_env():
    info = slice_info_from_env({**V5P16_ENV, "TPU_TOPOLOGY_WRAP": "true,true,true"})
    env = container_slice_env(info)
    assert env == {
        "TPU_WORKER_ID": "1",
        "TPU_TOPOLOGY": "2x2x4",
        "TPU_HOST_BOUNDS": "1,1,4",
        "TPU_TOPOLOGY_WRAP": "true,true,true",
    }


def test_daemon_injects_slice_env_into_allocations(tmp_path):
    """End-to-end: a daemon on a slice member host stamps every allocated
    container with the global-slice environment."""
    import queue
    import threading

    from tpu_device_plugin.api import pb
    from tpu_device_plugin.config import Config, Flags
    from tpu_device_plugin.main import Daemon

    from .fake_kubelet import FakeKubelet

    kubelet = FakeKubelet(str(tmp_path / "dp"))
    kubelet.start()
    mgr = FakeChipManager(n_chips=4, chips_per_tray=2, accelerator_type="v5p")
    flags = Flags(
        backend="fake",
        device_plugin_path=kubelet.plugin_dir,
        slice_topology="2x2x4",
        slice_host_bounds="1,1,4",
        slice_worker_id=1,
    )
    daemon = Daemon(Config(flags=flags), backend=mgr, events=queue.Queue(),
                    lease_dir=str(tmp_path / "leases"))
    t = threading.Thread(target=daemon.run, daemon=True)
    t.start()
    try:
        assert daemon.started.wait(10)
        topo = mgr.topology()
        assert topo.torus_shape == (2, 2, 4)
        stub = kubelet.plugin_client("tpu-tpu.sock")
        resp = stub.Allocate(
            pb.AllocateRequest(
                container_requests=[pb.ContainerAllocateRequest(devicesIDs=["tpu-0"])]
            )
        )
        envs = dict(resp.container_responses[0].envs)
        assert envs["TPU_WORKER_ID"] == "1"
        assert envs["TPU_TOPOLOGY"] == "2x2x4"
        assert envs["TPU_HOST_BOUNDS"] == "1,1,4"
    finally:
        daemon.request_stop()
        t.join(timeout=10)
        kubelet.stop()

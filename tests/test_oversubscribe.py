"""End-to-end oversubscription: the BASELINE north-star config in miniature.

BASELINE.md: ">= 90% aggregate chip-busy with 8 time-sliced JAX pods on a
v5e-4 host".  The full 8-pod/4-chip run is `python -m workloads.oversubscribe`
(and passes with ~0.96); the suite runs a scaled-down 4-pod/2-chip version of
the same full stack — real gRPC admission (ListAndWatch ->
GetPreferredAllocation -> Allocate), real subprocess pods interleaving through
the cooperative chip lease — to keep CI wall-clock reasonable.
"""

import json

from workloads import busy_probe
from workloads.oversubscribe import BASELINE_BUSY_FRACTION, run


def test_oversubscribed_pods_hit_busy_target():
    agg = run(
        n_chips=2,
        chips_per_tray=2,
        replicas=2,
        n_pods=4,
        duration_secs=3.0,
        matrix_dim=256,
        platform="cpu",
    )
    assert agg["pods"] == 4
    assert agg["chips"] == 2
    # Every pod leased exactly one chip, two pods per chip.
    assert set(agg["per_chip_busy_fraction"]) == {"tpu-0", "tpu-1"}
    assert agg["aggregate_busy_fraction"] >= BASELINE_BUSY_FRACTION


def test_oversubscribed_serve_pods_report_tokens():
    """Serving pods time-slice too: the 'serve' workload runs full
    requests through the continuous-batching engine per burst and the
    aggregate carries generated tokens/s next to the busy fraction."""
    agg = run(
        n_chips=1,
        chips_per_tray=1,
        replicas=2,
        n_pods=2,
        duration_secs=3.0,
        platform="cpu",
        workload="serve",
    )
    assert agg["pods"] == 2 and agg["chips"] == 1
    assert agg["aggregate_busy_fraction"] >= BASELINE_BUSY_FRACTION
    assert agg["tokens"] > 0 and agg["aggregate_tokens_per_sec"] > 0


def test_aggregate_per_chip_union_window(tmp_path):
    """Per-chip busy fractions use the union wall window of the pods that
    used the chip, so staggered pod start-up does not deflate the metric."""
    report = tmp_path / "stats.jsonl"
    rows = [
        # chip-a: two pods, staggered by 2s, each 90% busy over 4s windows.
        {"chips": ["a"], "busy_secs": 2.0, "wall_secs": 4.0, "t_end": 104.0},
        {"chips": ["a"], "busy_secs": 3.4, "wall_secs": 4.0, "t_end": 106.0},
        # chip-b: one pod, fully busy.
        {"chips": ["b"], "busy_secs": 4.0, "wall_secs": 4.0, "t_end": 104.0},
    ]
    report.write_text("".join(json.dumps(r) + "\n" for r in rows))
    agg = busy_probe.aggregate(str(report))
    assert agg["pods"] == 3
    assert agg["chips"] == 2
    # chip-a union window: [100, 106] = 6s, busy 5.4 -> 0.9
    assert abs(agg["per_chip_busy_fraction"]["a"] - 0.9) < 1e-9
    assert agg["per_chip_busy_fraction"]["b"] == 1.0
    assert abs(agg["aggregate_busy_fraction"] - 0.95) < 1e-9

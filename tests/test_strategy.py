"""Topology strategy factory: chip/tray/mixed plugin construction."""

import pytest

from tpu_device_plugin.backend.fake import FakeChipManager
from tpu_device_plugin.config import Config, Flags
from tpu_device_plugin.resource_config import ResourceConfig, parse_resource_config
from tpu_device_plugin.strategy import (
    ChipStrategy,
    MixedStrategy,
    TrayStrategy,
    chip_units,
    new_topology_strategy,
    tray_units,
)


def make_strategy(strategy_name, mgr, rc_text="", plugin_dir="/tmp/dp", **flag_kwargs):
    cfg = Config(flags=Flags(topology_strategy=strategy_name, backend="fake", **flag_kwargs))
    rc = parse_resource_config(rc_text) if rc_text else ResourceConfig()
    return new_topology_strategy(
        cfg, rc, mgr, plugin_dir=plugin_dir, kubelet_socket="/tmp/dp/kubelet.sock"
    )


@pytest.fixture
def v5e4():
    mgr = FakeChipManager(n_chips=4, chips_per_tray=4)
    mgr.init()
    return mgr


@pytest.fixture
def two_trays():
    mgr = FakeChipManager(n_chips=8, chips_per_tray=4)
    mgr.init()
    return mgr


def test_unit_builders(v5e4):
    assert [u.id for u in chip_units(v5e4)] == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
    trays = tray_units(v5e4)
    assert [u.id for u in trays] == ["tray-0"]
    assert trays[0].chip_ids == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
    assert trays[0].hbm_bytes == 4 * (16 << 30)


def test_chip_strategy(v5e4):
    strategy = make_strategy("chip", v5e4)
    assert isinstance(strategy, ChipStrategy)
    (plugin,) = strategy.get_plugins()
    assert plugin.resource_name == "google.com/tpu"
    assert plugin.socket_path == "/tmp/dp/tpu-tpu.sock"
    assert plugin._policy is not None  # ICI best-effort for exclusive chips
    assert not plugin.shared


def test_chip_strategy_with_sharing_rename(v5e4):
    strategy = make_strategy("chip", v5e4, rc_text="tpu:shared-tpu:4")
    (plugin,) = strategy.get_plugins()
    assert plugin.resource_name == "google.com/shared-tpu"
    assert plugin.replicas == 4 and plugin.shared
    # Sharing and topology policy are mutually exclusive (server.go:269-270).
    assert plugin._policy is None


def test_chip_strategy_auto_replicas(v5e4):
    strategy = make_strategy("chip", v5e4, rc_text="tpu:tpu-mem-gb:-1")
    (plugin,) = strategy.get_plugins()
    assert plugin.auto_replicas and plugin.shared


def test_tray_strategy(two_trays):
    strategy = make_strategy("tray", two_trays)
    assert isinstance(strategy, TrayStrategy)
    (plugin,) = strategy.get_plugins()
    assert plugin.resource_name == "google.com/tpu"
    plugin.initialize()
    assert {a.id for a in plugin._advertised} == {"tray-0", "tray-1"}


def test_tray_strategy_fails_loud_without_multichip_trays():
    # Reference parity: `single` errors when the host cannot satisfy the
    # requested granularity (mig-strategy.go:114-203); an operator who asked
    # for trays must not silently get chips.
    mgr = FakeChipManager(n_chips=4, chips_per_tray=1)
    mgr.init()
    strategy = make_strategy("tray", mgr)
    with pytest.raises(RuntimeError, match="no multi-chip trays"):
        strategy.get_plugins()


def test_tray_strategy_falls_back_to_chips_when_allowed():
    mgr = FakeChipManager(n_chips=4, chips_per_tray=1)
    mgr.init()
    strategy = make_strategy("tray", mgr, tray_allow_chip_fallback=True)
    (plugin,) = strategy.get_plugins()
    plugin.initialize()
    assert {a.id for a in plugin._advertised} == {"tpu-0", "tpu-1", "tpu-2", "tpu-3"}


class TestClaimLivenessProbe:
    def test_open_count_positive_is_alive(self, v5e4, tmp_path):
        from tpu_device_plugin.strategy import make_claim_liveness_probe

        v5e4.set_in_use({0: 2, 1: 0, 2: 0, 3: 0})
        probe = make_claim_liveness_probe(v5e4, str(tmp_path), counts_authoritative=True)
        verdicts = probe(["tpu-0", "tpu-1"])
        assert verdicts["tpu-0"] is True
        assert verdicts["tpu-1"] is False  # authoritative zero, no flock

    def test_zero_count_not_authoritative_is_unknown(self, v5e4, tmp_path):
        # A namespace-local /proc walk returns confident zeros for other
        # pods' handles; without hostPID those zeros must not read as death.
        from tpu_device_plugin.strategy import make_claim_liveness_probe

        v5e4.set_in_use({0: 0, 1: 0, 2: 0, 3: 0})
        probe = make_claim_liveness_probe(v5e4, str(tmp_path), counts_authoritative=False)
        assert probe(["tpu-0"]) == {"tpu-0": None}

    def test_held_flock_outranks_zero_count(self, v5e4, tmp_path):
        import fcntl
        import os

        from tpu_device_plugin.sharing import lease_path
        from tpu_device_plugin.strategy import make_claim_liveness_probe

        v5e4.set_in_use({0: 0, 1: 0, 2: 0, 3: 0})
        probe = make_claim_liveness_probe(v5e4, str(tmp_path), counts_authoritative=True)
        fd = os.open(lease_path(str(tmp_path), "tpu-0"), os.O_CREAT | os.O_RDWR, 0o666)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            verdicts = probe(["tpu-0", "tpu-1"])
            # Lease-holding workload is alive even when the walk says 0.
            assert verdicts["tpu-0"] is True
            assert verdicts["tpu-1"] is False
        finally:
            os.close(fd)

    def test_probe_unavailable_falls_to_unknown(self, v5e4, tmp_path):
        from tpu_device_plugin.strategy import make_claim_liveness_probe

        # {} = probe unavailable (native .so predates the call), never "idle".
        probe = make_claim_liveness_probe(v5e4, str(tmp_path), counts_authoritative=True)
        assert probe(["tpu-0"]) == {"tpu-0": None}

    def test_predecessor_drop_cannot_condemn_successor(self, v5e4, tmp_path):
        """The ADVICE-r3 misfire: sibling A (epoch e1) declared its claim
        lease and exited AFTER pod B (epoch e2) was allocated but BEFORE
        B called hold_claim_leases.  A's unheld file must read as unknown
        for B's epoch-scoped probe — never as B's death."""
        import fcntl
        import os

        from tpu_device_plugin.sharing import claim_lease_path
        from tpu_device_plugin.strategy import make_claim_liveness_probe

        probe = make_claim_liveness_probe(v5e4, str(tmp_path))
        # A declared at epoch e1 then exited: file exists, flock dropped.
        open(claim_lease_path(str(tmp_path), "tpu-0", "e1"), "w").close()
        assert probe({"tpu-0": "e2"}) == {"tpu-0": None}  # NOT False
        # While A still lives (held flock), any epoch proves the chip alive.
        fd = os.open(
            claim_lease_path(str(tmp_path), "tpu-0", "e1"),
            os.O_CREAT | os.O_RDWR, 0o666,
        )
        try:
            fcntl.flock(fd, fcntl.LOCK_SH)
            assert probe({"tpu-0": "e2"}) == {"tpu-0": True}
        finally:
            os.close(fd)
        # B declares under ITS epoch and exits: that IS death evidence.
        open(claim_lease_path(str(tmp_path), "tpu-0", "e2"), "w").close()
        assert probe({"tpu-0": "e2"}) == {"tpu-0": False}


def test_mixed_strategy_both_views_share_ledger(v5e4):
    strategy = make_strategy("mixed", v5e4)
    assert isinstance(strategy, MixedStrategy)
    plugins = strategy.get_plugins()
    names = {p.resource_name for p in plugins}
    assert names == {"google.com/tpu", "google.com/tpu-tray"}
    chip_plugin = next(p for p in plugins if p.resource_name == "google.com/tpu")
    tray_plugin = next(p for p in plugins if p.resource_name == "google.com/tpu-tray")
    assert chip_plugin._claims is tray_plugin._claims is not None
    assert chip_plugin.socket_path != tray_plugin.socket_path
    chip_plugin.initialize()
    tray_plugin.initialize()
    assert len(chip_plugin._advertised) == 4  # 4x1-chip
    assert len(tray_plugin._advertised) == 1  # 1x4-chip (BASELINE configs[3])


def test_mixed_strategy_trayless_host_has_single_plugin():
    mgr = FakeChipManager(n_chips=2, chips_per_tray=1)
    mgr.init()
    plugins = make_strategy("mixed", mgr).get_plugins()
    assert [p.resource_name for p in plugins] == ["google.com/tpu"]


def test_mixed_sharing_via_resource_config(v5e4):
    strategy = make_strategy("mixed", v5e4, rc_text="tpu:shared-tpu:4,tpu-tray:tray:2")
    plugins = strategy.get_plugins()
    by_name = {p.resource_name: p for p in plugins}
    assert by_name["google.com/shared-tpu"].replicas == 4
    assert by_name["google.com/tray"].replicas == 2

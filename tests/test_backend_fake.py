"""Fake backend: lifecycle, discovery, scriptable health events."""

import queue
import threading

import pytest

from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
from tpu_device_plugin.backend import BackendInitError
from tpu_device_plugin.backend.fake import FakeChipManager


def test_lifecycle_and_devices():
    mgr = FakeChipManager(n_chips=4, chips_per_tray=4)
    with pytest.raises(BackendInitError):
        mgr.devices()
    mgr.init()
    devs = mgr.devices()
    assert [d.id for d in devs] == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
    assert all(d.health == HEALTHY for d in devs)
    # Snapshots are copies; mutating them does not corrupt the backend.
    devs[0].health = UNHEALTHY
    assert mgr.devices()[0].health == HEALTHY
    mgr.shutdown()


def test_fail_init():
    mgr = FakeChipManager(fail_init=True)
    with pytest.raises(BackendInitError):
        mgr.init()


def test_health_event_forwarding_and_filtering():
    mgr = FakeChipManager(n_chips=2)
    mgr.init()
    chips = mgr.devices()
    stop = threading.Event()
    events: queue.Queue = queue.Queue()
    t = threading.Thread(
        target=mgr.check_health, args=(stop, events, chips[:1]), daemon=True
    )
    t.start()
    try:
        mgr.inject("tpu-1", UNHEALTHY)  # not watched by this plugin
        mgr.inject("tpu-0", UNHEALTHY)
        ev = events.get(timeout=2)
        assert ev.chip_id == "tpu-0" and ev.health == UNHEALTHY
        mgr.inject("tpu-0", HEALTHY)  # recovery events are supported
        ev = events.get(timeout=2)
        assert ev.health == HEALTHY
        mgr.inject("", UNHEALTHY)  # unattributed event reaches every watcher
        ev = events.get(timeout=2)
        assert ev.all_chips
        assert events.empty()
    finally:
        stop.set()
        t.join(timeout=2)
    assert not t.is_alive()

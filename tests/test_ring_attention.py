"""Ring attention (sequence parallelism) vs dense attention, 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from workloads.ops.ring import ring_attention

from .test_flash_attention import make_qkv, naive_attention


@pytest.fixture
def seq_mesh():
    devices = np.array(jax.devices()[:8])
    assert devices.size == 8, "conftest provides an 8-device CPU mesh"
    return Mesh(devices, ("seq",))


@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense(seq_mesh, causal):
    q, k, v = make_qkv(batch=2, seq=64, heads=2, head_dim=16)
    out = ring_attention(q, k, v, seq_mesh, causal=causal)
    expected = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_gradients_match_dense(seq_mesh):
    q, k, v = make_qkv(batch=1, seq=32, heads=2, head_dim=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(naive_attention(q, k, v, True) ** 2)

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=1e-4, err_msg=f"d{name}"
        )


def test_jit_and_seq_sharded_inputs(seq_mesh):
    """Compiles under jit with inputs already sequence-sharded on the mesh
    (as a sequence-parallel train step would feed it)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = make_qkv(batch=2, seq=64, heads=2, head_dim=16)
    sharding = NamedSharding(seq_mesh, P(None, "seq", None, None))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, seq_mesh))(q, k, v)
    assert out.sharding.spec == P(None, "seq", None, None)
    expected = naive_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_rejects_indivisible_seq(seq_mesh):
    q, k, v = make_qkv(seq=30)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, seq_mesh)


def test_bfloat16(seq_mesh):
    q, k, v = make_qkv(batch=1, seq=32, heads=2, head_dim=16, dtype=jnp.bfloat16)
    out = ring_attention(q, k, v, seq_mesh)
    expected = naive_attention(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected, np.float32), atol=3e-2
    )

"""Harness tests for workloads/perfbench.py at the tiny scale.

These validate structure and math, not performance: perf numbers from the
CPU interpreter are meaningless, but the analytic-FLOPs accounting, the
slope estimator, and the output schema bench.py merges must hold anywhere.
"""

import time

import pytest

from workloads.perfbench import (
    BenchScale,
    _publish_ratio_spread,
    derive_breakeven,
    device_peak_flops,
    measure_slope_samples,
    measure_slope_secs,
    train_step_flops,
)


def test_bench_scale_named():
    full, tiny = BenchScale.named("full"), BenchScale.named("tiny")
    assert full.seq > tiny.seq
    with pytest.raises(ValueError):
        BenchScale.named("nope")


def test_train_step_flops_analytic():
    from workloads.model import ModelConfig

    config = ModelConfig(
        vocab_size=100, d_model=8, n_heads=2, n_layers=3, d_ff=16,
        max_seq_len=5,
    )
    batch = 2
    s = 4  # max_seq_len - 1
    tokens = batch * s
    p_matmul = 3 * (4 * 8 * 8 + 2 * 8 * 16) + 8 * 100
    expected = 3 * (2 * tokens * p_matmul + 3 * batch * 4 * s * s * 8 * 0.5)
    assert train_step_flops(config, batch) == expected


def test_device_peak_flops_table(monkeypatch):
    import jax

    class _Dev:
        def __init__(self, kind):
            self.device_kind = kind

    def fake_devices(kind):
        monkeypatch.setattr(jax, "devices", lambda: [_Dev(kind)])

    fake_devices("TPU v5 lite")
    assert device_peak_flops() == 197e12
    fake_devices("TPU v4")
    assert device_peak_flops() == 275e12
    # Unknown kinds (future generations) yield None so MFU is omitted
    # instead of reported against a guessed peak.
    fake_devices("TPU v99 hyperdrive")
    assert device_peak_flops() is None
    fake_devices("cpu")
    assert device_peak_flops() is None


def test_device_peak_resolves_on_real_tpu():
    """On an actual TPU runner the device kind must be in the peak table —
    otherwise MFU silently vanishes from the bench JSON.  (Production
    degrades gracefully by design; the *test* is where drift gets loud.)"""
    import jax

    if jax.devices()[0].platform != "tpu":
        pytest.skip("no TPU on this runner")
    assert device_peak_flops(), (
        f"device kind {jax.devices()[0].device_kind!r} missing from "
        "_PEAK_BF16_FLOPS"
    )


def test_measure_slope_cancels_constant_overhead():
    calls = []

    def run_chain(n):
        calls.append(n)
        time.sleep(0.05 + n * 0.02)  # constant 50ms + 20ms/iter

    secs = measure_slope_secs(run_chain, n_lo=2, n_hi=8, repeats=2,
                              min_window_secs=0.05)
    assert 0.015 < secs < 0.025  # slope recovers the per-iter cost
    assert calls[0] == 2 and calls[1] == 8  # warm pass precedes timing


def test_measure_slope_grows_until_window():
    seen = []

    def run_chain(n):
        seen.append(n)
        time.sleep(n * 0.004)

    # 4ms/iter: the first (2, 8) round gives a 24ms window < 60ms, so the
    # chain lengths must double at least once.
    secs = measure_slope_secs(run_chain, n_lo=2, n_hi=8, repeats=1,
                              min_window_secs=0.06)
    assert max(seen) >= 16
    assert 0.002 < secs < 0.006


def test_derive_breakeven():
    """The break-even derivation: log2 interpolation at the win->lose
    crossing, a floor at the largest measured batch when every batch
    wins, 0 when even batch 1 loses."""
    # Crossing between 2 and 4 at equal distance -> log-midpoint 2.83.
    assert derive_breakeven([1, 2, 4, 8], [1.3, 1.1, 0.9, 0.7]) == 2.83
    # Exactly break-even at a measured batch interpolates to it.
    assert derive_breakeven([1, 2, 4], [1.2, 1.0, 0.8]) == 2.0
    assert derive_breakeven([1, 2, 4, 8], [1.3, 1.2, 1.1, 1.05]) == 8.0
    assert derive_breakeven([1, 2], [0.9, 0.7]) == 0.0
    # Non-monotone noise: the FIRST crossing wins (conservative).
    assert derive_breakeven([1, 2, 4], [1.1, 0.9, 1.02]) < 2.0


def test_publish_ratio_spread_pools_across_runs():
    """Current samples pool with the prior artifact's persisted ones —
    a genuinely separate process — and the scope field says which kind
    of range was published."""
    out = {}
    _publish_ratio_spread(out, "r", [1.30, 1.35], None)
    assert out["r_samples"] == [1.3, 1.35]
    assert (out["r_min"], out["r_max"]) == (1.3, 1.35)
    assert out["r_spread_scope"] == "within-run"
    prior = {"r_samples": [1.2, 1.4, "junk"]}
    out = {}
    _publish_ratio_spread(out, "r", [1.30, 1.35], prior)
    assert (out["r_min"], out["r_max"]) == (1.2, 1.4)
    assert out["r_spread_scope"] == "pooled-cross-run"
    # The persisted samples are the CURRENT run's (next round pools them).
    assert out["r_samples"] == [1.3, 1.35]


def test_measure_slope_samples_returns_per_repeat_slopes():
    def run_chain(n):
        time.sleep(0.05 + n * 0.02)

    median, samples = measure_slope_samples(
        run_chain, n_lo=2, n_hi=8, repeats=3, min_window_secs=0.05
    )
    assert len(samples) == 3
    assert min(samples) <= median <= max(samples)
    assert all(0.01 < s < 0.04 for s in samples)


@pytest.mark.slow
def test_perfbench_tiny_end_to_end():
    """The whole suite runs on CPU at tiny scale and produces the schema
    bench.py consumes (values are interpreter noise; only shape/keys and
    basic sanity are asserted)."""
    from workloads import perfbench

    import jax

    out = perfbench.run("tiny")
    for key in (
        "train_step_ms",
        "train_tokens_per_sec",
        "mfu",
        "flash_vs_xla_speedup",
        "flash_vs_xla_detail",
        "decode_ms_per_token",
        "decode_tokens_per_sec",
        "paged_decode_tokens_per_sec",
        "paged_vs_contiguous_decode",
        "serve_tokens_per_sec",
        "serve_requests_per_sec",
        "serve_pool_peak_fraction",
        # Fleet serving & failover arm (docs/SERVING.md).
        "fleet_replicas",
        "fleet_tokens_per_sec",
        "fleet_ttft_p50_ms",
        "fleet_ttft_p99_ms",
        "router_overhead_ms",
        "router_overhead_ms_min",
        "router_overhead_ms_max",
        "failover_recovery_ms",
        "failover_recovery_ms_min",
        "failover_recovery_ms_max",
        # Self-healing supervision arm (docs/SERVING.md).
        "selfheal_restore_ms",
        "selfheal_restore_ms_min",
        "selfheal_restore_ms_max",
        "selfheal_capacity_recovered",
        "selfheal_goodput_retained",
        "selfheal_crash_loops",
        "replica_restore_cold_ms",
        "replica_restore_warm_ms",
        # Decode-superstep arm (docs/SERVING.md "Decode supersteps &
        # double-buffered scheduling").
        "superstep_best_k",
        "superstep_tokens_per_sec",
        "superstep_tokens_per_sec_k1",
        "superstep_tokens_per_sec_k8",
        "superstep_speedup",
        "superstep_overdecode_pct",
        "decode_host_sync_ms",
        "superstep_tokens_per_sec_samples",
        "superstep_tokens_per_sec_min",
        "superstep_tokens_per_sec_max",
        # Observability overhead arm (docs/OBSERVABILITY.md).
        "obs_overhead_pct",
        "obs_on_tokens_per_sec",
        "obs_off_tokens_per_sec",
        # Round-6 speculation economics family.
        "spec_breakeven_batch",
        "spec_phase_dominant",
        "spec_phase_tokens_per_round",
        "spec_draft_ms_b1",
        "spec_verify_ms_b1",
        "spec_commit_ms_b1",
        "spec_phase_ratio_b1",
        "spec_engine_vs_plain_b1",
        "spec_engine_vs_plain_b4",
        "spec_engine_best_k",
        # KV-cache hierarchy arm (docs/SERVING.md "KV-cache
        # hierarchy").
        "kv_multiturn_speedup",
        "kv_radix_vs_flat_hit_ratio",
        "kv_flat_hit_pages",
        "kv_radix_hit_pages",
        "kv_oversub_pool_pages",
        "kv_oversub_live_pages",
        "kv_offload_spills",
        "kv_offload_reloads",
        "kv_resident_pages_saved",
        # KV-page scheduling arm (docs/SERVING.md "Memory as the
        # schedulable unit").
        "kvsched_vs_replica_tokens_per_sec",
        "kvsched_vs_replica_tokens_per_sec_min",
        "kvsched_vs_replica_tokens_per_sec_max",
        "kvsched_busy_fraction",
        "kvsched_goodput_fraction",
        "kvsched_page_waste_pct",
        "kvsched_page_dispatches",
        "kvsched_offload_spills",
        # Cross-run-poolable ratio spreads.
        "paged_vs_contiguous_decode_samples",
        "paged_vs_contiguous_decode_min",
        "decode_int8_speedup_samples",
        "flash_vs_xla_speedup_samples",
        "flash_window_speedup_samples",
    ):
        assert key in out, key
    assert 0.0 < out["serve_pool_peak_fraction"] <= 1.0
    # KV hierarchy: the tiny trace genuinely oversubscribes its pool,
    # the offload tier is exercised both directions (streams asserted
    # bit-identical inside the arm), and the tree never hits fewer
    # pages than the flat index on the same trace.
    assert out["kv_oversub_live_pages"] > out["kv_oversub_pool_pages"]
    assert out["kv_offload_spills"] >= 1
    assert out["kv_offload_reloads"] >= 1
    assert out["kv_offload_reload_ms"] > 0
    assert out["kv_radix_hit_pages"] >= out["kv_flat_hit_pages"]
    # KV-page scheduling: the page arm stayed busy on useful work,
    # costed its dispatches in pages, and the tight pools spilled
    # (streams asserted bit-identical to the replica arm inside the
    # arm itself).
    assert 0.0 < out["kvsched_busy_fraction"] <= 1.0
    assert 0.0 < out["kvsched_goodput_fraction"] <= 1.0
    assert out["kvsched_page_dispatches"] > 0
    assert out["kvsched_offload_spills"] >= 1
    assert 0.0 <= out["kvsched_page_waste_pct"] <= 100.0
    assert out["fleet_replicas"] == 4
    assert out["fleet_tokens_per_sec"] > 0
    assert out["failover_recovery_ms"] > 0
    assert out["failover_requeued"] >= 1
    # Self-healing: full capacity back, nothing shed under closed-loop
    # load, the scripted crash loop quarantined, cold beats nothing —
    # the warm respawn just has to be a real positive measurement.
    assert out["selfheal_restore_ms"] > 0
    assert out["selfheal_capacity_recovered"] == 1.0
    assert out["selfheal_goodput_retained"] == 1.0
    assert out["selfheal_crash_loops"] == 1
    assert out["replica_restore_warm_ms"] > 0
    assert out["replica_restore_cold_ms"] > 0
    assert out["spec_phase_dominant"] in ("draft", "verify", "commit")
    assert out["spec_breakeven_batch"] >= 0.0
    assert out["superstep_best_k"] in out["superstep_ks"]
    assert out["superstep_tokens_per_sec"] > 0
    assert out["decode_host_sync_ms"] >= 0
    assert 0.0 <= out["superstep_overdecode_pct"] < 100.0
    for b in out["spec_phase_batches"]:
        assert f"spec_verify_ms_b{b}" in out
    # No spread pooling source passed -> within-run scope.
    assert out["paged_vs_contiguous_decode_spread_scope"] == "within-run"
    # Median-of-medians ratio sits inside the sample range (odd repeat
    # counts guarantee it; the epsilon absorbs the published rounding).
    assert (
        out["paged_vs_contiguous_decode_min"] - 0.001
        <= out["paged_vs_contiguous_decode"]
        <= out["paged_vs_contiguous_decode_max"] + 0.001
    )
    if jax.devices()[0].platform != "tpu":
        assert out["mfu"] is None  # no known peak -> omitted, not guessed
    assert out["train_step_ms"] >= 0
    assert set(out["flash_vs_xla_detail"]) == {"128"}


def test_mfu_sweep_hardware_flops_accounting():
    """HFU accounting: flash recompute adds one forward-attention pass;
    remat adds one full layer-stack forward on top."""
    from workloads.mfu_sweep import POINTS, SweepPoint, hardware_flops
    from workloads.perfbench import train_step_flops

    p = SweepPoint("x", d_model=8, n_heads=2, n_layers=3, d_ff=16,
                   vocab=100, seq=5, batch=2)
    config = p.config()
    s = 4
    fwd_attn = 3 * 2 * (4 * s * s * 8) * 0.5
    base = train_step_flops(config, 2)
    assert hardware_flops(config, 2) == base + fwd_attn

    r = SweepPoint("y", d_model=8, n_heads=2, n_layers=3, d_ff=16,
                   vocab=100, seq=5, batch=2, remat=True)
    rconfig = r.config()
    p_layers = 3 * (2 * 8 * 8 + 2 * 8 * (2 * 4) + 2 * 8 * 16)
    extra_layers_fwd = 2 * 2 * s * p_layers + fwd_attn
    assert hardware_flops(rconfig, 2) == base + fwd_attn + extra_layers_fwd
    # Registry sanity: every point builds a valid config.
    for point in POINTS.values():
        point.config()


def test_train_step_flops_gqa_counts_smaller_kv():
    from workloads.model import ModelConfig

    base = dict(vocab_size=100, d_model=8, n_heads=4, n_layers=3, d_ff=16,
                max_seq_len=5)
    mha = ModelConfig(**base)
    gqa = ModelConfig(**base, n_kv_heads=2)
    # Same everything except the k/v projections, which halve.
    diff = train_step_flops(mha, 2) - train_step_flops(gqa, 2)
    s = 4
    tokens = 2 * s
    expected = 3 * 2 * tokens * 3 * (2 * 8 * (4 * 2) - 2 * 8 * (2 * 2))
    assert diff == expected

"""KV pages as the schedulable unit (tpu_device_plugin/kvsched.py +
Fleet(page_scheduling=True)): the live-signal snapshot protocol and the
GetPreferredAllocation scorer built on it.

The pinned contracts: the snapshot is atomic (write-then-rename — a
reader never sees a torn file) with a monotonically increasing epoch
that survives publisher restarts; the reader's fallback taxonomy is
exactly absent/stale/corrupt/ok; and the scorer degrades
BIT-IDENTICALLY to the static least-shared spread on every fallback —
the serving fleet is advisory icing on the allocation path, never a
dependency.  The unit tier here is jax-free; the `make kvsched-check`
smoke at the bottom drives a real oversubscribed page-scheduled fleet.
"""

import json
import os

import pytest

from tpu_device_plugin import kvsched
from tpu_device_plugin.replica import (
    AllocationError,
    prioritize_devices,
    replica_id,
)


def _snap(tmp_path, name="fleet-stats.json"):
    return str(tmp_path / name)


# ---- snapshot hygiene ----------------------------------------------------


def test_write_read_round_trip_filters_to_known_signals(tmp_path):
    path = _snap(tmp_path)
    epoch = kvsched.write_stats_snapshot(
        path,
        {
            "tpu-0": {
                "free_pages": 7,
                "total_pages": 16,
                "busy_fraction": 0.25,
                "future_signal_v9": 42,  # unknown keys must be dropped
                "not_a_number": "nan-ish",
            }
        },
        now=1000.0,
    )
    assert epoch == 0
    stats, reason = kvsched.read_stats_snapshot(path, now=1000.0)
    assert reason == "ok"
    assert stats["__epoch__"] == 0
    assert stats["tpu-0"] == {
        "free_pages": 7.0,
        "total_pages": 16.0,
        "busy_fraction": 0.25,
    }
    # No temp debris left behind by the write-then-rename.
    assert os.listdir(tmp_path) == ["fleet-stats.json"]


def test_epoch_is_monotonic_even_across_publisher_restart(tmp_path):
    path = _snap(tmp_path)
    assert kvsched.write_stats_snapshot(path, {}, epoch=5) == 5
    # A respawned fleet restarts its own counter at zero; the stamped
    # epoch must still advance past what is on disk.
    assert kvsched.write_stats_snapshot(path, {}, epoch=0) == 6
    assert kvsched.write_stats_snapshot(path, {}) == 7
    with open(path, encoding="utf-8") as f:
        assert json.load(f)["epoch"] == 7


def test_reader_reason_taxonomy(tmp_path):
    absent = _snap(tmp_path, "never-written.json")
    assert kvsched.read_stats_snapshot(absent) == (None, "absent")
    assert kvsched.read_stats_snapshot(None) == (None, "absent")

    path = _snap(tmp_path)
    for garbage in [
        "{truncated",
        json.dumps({"written_at": 1.0, "chips": {}}),  # no epoch
        json.dumps({"epoch": -3, "written_at": 1.0, "chips": {}}),
        json.dumps({"epoch": 1, "written_at": 1.0, "chips": [1, 2]}),
        json.dumps([1, 2, 3]),
    ]:
        with open(path, "w", encoding="utf-8") as f:
            f.write(garbage)
        assert kvsched.read_stats_snapshot(path, now=1.0) == (
            None,
            "corrupt",
        ), garbage

    kvsched.write_stats_snapshot(path, {"tpu-0": {"free_pages": 1}}, now=100.0)
    ok, reason = kvsched.read_stats_snapshot(path, ttl_secs=10.0, now=109.0)
    assert reason == "ok" and ok is not None
    assert kvsched.read_stats_snapshot(path, ttl_secs=10.0, now=110.5) == (
        None,
        "stale",
    )
    # A clock that runs BACKWARD past the write is also stale-shaped
    # garbage, not a fresh snapshot.
    assert kvsched.read_stats_snapshot(
        path, ttl_secs=10.0, now=float("nan")
    ) == (None, "stale")
    # min_epoch: a reader that accepted epoch N refuses a rollback.
    assert kvsched.read_stats_snapshot(
        path, now=100.0, min_epoch=0
    ) == (None, "stale")
    ok, reason = kvsched.read_stats_snapshot(path, now=100.0, min_epoch=-1)
    assert reason == "ok" and ok["__epoch__"] == 0


def test_reader_never_sees_a_torn_write(tmp_path):
    """The rename is the commit point: a concurrent reader gets either
    the previous complete snapshot or the new one."""
    path = _snap(tmp_path)
    kvsched.write_stats_snapshot(path, {"tpu-0": {"free_pages": 1}})
    before = kvsched.load_stats_snapshot(path, ttl_secs=None)
    kvsched.write_stats_snapshot(path, {"tpu-0": {"free_pages": 2}})
    after = kvsched.load_stats_snapshot(path, ttl_secs=None)
    assert before["tpu-0"]["free_pages"] == 1.0
    assert after["tpu-0"]["free_pages"] == 2.0
    assert after["__epoch__"] == before["__epoch__"] + 1


# ---- the degrade contract ------------------------------------------------


def _expand(chips, replicas):
    return [replica_id(c, i) for c in chips for i in range(replicas)]


def test_fallback_is_bit_identical_to_the_static_spread():
    """score_devices(..., stats=None) IS prioritize_devices — same
    devices, same uniqueness verdict, same errors, over randomized
    availability/must-include shapes."""
    import random

    rng = random.Random(1234)
    for case in range(300):
        chips = [f"tpu-{i}" for i in range(rng.randint(1, 5))]
        pool = _expand(chips, rng.randint(1, 4))
        available = sorted(rng.sample(pool, rng.randint(1, len(pool))))
        rng.shuffle(available)
        must = rng.sample(available, rng.randint(0, min(2, len(available))))
        if rng.random() < 0.15:
            must = must + [replica_id("tpu-99", 0)]  # not offered
        size = rng.randint(max(1, len(must)), len(available) + 2)

        try:
            want = prioritize_devices(list(available), list(must), size)
            want_err = None
        except AllocationError as e:
            want, want_err = None, str(e)
        try:
            got = kvsched.score_devices(list(available), list(must), size, None)
            got_err = None
        except AllocationError as e:
            got, got_err = None, str(e)
        assert (want, want_err) == (got, got_err), (case, available, must, size)


def test_plugin_preferred_for_degrades_bit_identically(tmp_path):
    """The plugin path pins the same contract one layer up: with the
    stats file absent, stale, or corrupt, _preferred_for returns
    exactly the static spread and labels the fallback reason."""
    from tpu_device_plugin.backend.fake import FakeChipManager
    from tpu_device_plugin.config import Config, Flags
    from tpu_device_plugin.device import Unit
    from tpu_device_plugin.plugin import TpuDevicePlugin

    mgr = FakeChipManager(n_chips=3, chips_per_tray=4)
    mgr.init()
    path = _snap(tmp_path)
    plugin = TpuDevicePlugin(
        config=Config(flags=Flags(backend="fake", driver_root="/")),
        resource_name="google.com/shared-tpu",
        units_fn=lambda: [Unit(id=c.id, chips=[c]) for c in mgr.devices()],
        chip_manager=mgr,
        socket_path=str(tmp_path / "shared.sock"),
        replicas=2,
        lease_dir=str(tmp_path / "leases"),
        stats_path=path,
    )
    available = _expand(["tpu-0", "tpu-1", "tpu-2"], 2)

    static = prioritize_devices(list(available), [], 2).devices
    assert plugin._preferred_for(list(available), [], 2) == static  # absent

    with open(path, "w", encoding="utf-8") as f:
        f.write("{not json")
    assert plugin._preferred_for(list(available), [], 2) == static  # corrupt

    kvsched.write_stats_snapshot(
        path, {"tpu-2": {"free_pages": 99, "total_pages": 99}}, now=0.0
    )
    assert plugin._preferred_for(list(available), [], 2) == static  # stale

    # Fresh snapshot: the scorer now steers toward the signalled chip.
    kvsched.write_stats_snapshot(
        path,
        {
            "tpu-0": {"free_pages": 0, "total_pages": 16, "busy_fraction": 1.0},
            "tpu-1": {"free_pages": 2, "total_pages": 16, "busy_fraction": 0.9},
            "tpu-2": {"free_pages": 15, "total_pages": 16, "busy_fraction": 0.1},
        },
    )
    scored = plugin._preferred_for(list(available), [], 2)
    assert replica_id("tpu-2", 0) in scored
    assert len({d.split("-replica-")[0] for d in scored}) == 2


def test_non_shared_no_policy_returns_kubelet_legal_prefix():
    """S1: a plain exclusive resource with no topology policy answers
    GetPreferredAllocation with the identity prefix of the offer (the
    reference returns an empty response) — never an error that would
    fail pod admission."""
    from tpu_device_plugin.backend.fake import FakeChipManager
    from tpu_device_plugin.config import Config, Flags
    from tpu_device_plugin.device import Unit
    from tpu_device_plugin.plugin import TpuDevicePlugin

    mgr = FakeChipManager(n_chips=4, chips_per_tray=4)
    mgr.init()
    plugin = TpuDevicePlugin(
        config=Config(flags=Flags(backend="fake", driver_root="/")),
        resource_name="google.com/tpu",
        units_fn=lambda: [Unit(id=c.id, chips=[c]) for c in mgr.devices()],
        chip_manager=mgr,
        socket_path="/tmp/unused.sock",
        lease_dir="/tmp/unused-leases",
    )
    assert not plugin.shared and plugin._policy is None
    got = plugin._preferred_for(
        ["tpu-0", "tpu-1", "tpu-2", "tpu-3"], ["tpu-2"], 2
    )
    assert got == ["tpu-2", "tpu-0"]
    assert plugin._preferred_for(["tpu-0"], [], 3) == ["tpu-0"]


# ---- live-signal ranking -------------------------------------------------


def test_scorer_prefers_free_idle_goodput_chips():
    available = _expand(["tpu-0", "tpu-1", "tpu-2"], 2)
    stats = {
        "tpu-0": {
            "free_pages": 1, "total_pages": 16,
            "busy_fraction": 1.0, "goodput_fraction": 0.2,
        },
        "tpu-1": {
            "free_pages": 14, "total_pages": 16,
            "busy_fraction": 0.2, "goodput_fraction": 0.9,
        },
        "tpu-2": {
            "free_pages": 8, "total_pages": 16,
            "busy_fraction": 0.5, "goodput_fraction": 0.9,
        },
    }
    got = kvsched.score_devices(list(available), [], 2, stats)
    assert got.unique
    chips = [d.split("-replica-")[0] for d in got.devices]
    assert set(chips) == {"tpu-1", "tpu-2"}  # the freest two, not tpu-0


def test_scorer_keeps_the_static_spread_structure():
    available = _expand(["tpu-0", "tpu-1"], 2)
    stats = {"tpu-1": {"free_pages": 9, "total_pages": 9}}
    # must_include honoured first; a missing must-include raises the
    # SAME error text as the static path.
    got = kvsched.score_devices(
        list(available), [replica_id("tpu-0", 1)], 2, stats
    )
    assert replica_id("tpu-0", 1) in got.devices and got.unique
    with pytest.raises(AllocationError, match="mustIncludeDeviceIDs"):
        kvsched.score_devices(
            list(available), [replica_id("tpu-9", 0)], 2, stats
        )
    with pytest.raises(AllocationError, match="no devices left"):
        kvsched.score_devices(list(available), [], 5, stats)
    # Requesting more than the unique chips marks non-unique, like the
    # static spread does.
    assert not kvsched.score_devices(list(available), [], 3, stats).unique


def test_chips_absent_from_snapshot_score_zero_not_crash():
    available = _expand(["tpu-0", "tpu-1"], 1)
    stats = {"tpu-1": {"free_pages": 1, "total_pages": 4}}
    got = kvsched.score_devices(list(available), [], 1, stats)
    assert got.devices == [replica_id("tpu-1", 0)]


# ---- the `make kvsched-check` smoke --------------------------------------


def test_kvsched_check_smoke(tmp_path):
    """Seeded oversubscribed multi-tenant stream on a page-scheduled
    fleet: every request served, at least one host-tier offload spill,
    no page/slot leak at drain, the fleet-ledger busy fraction above
    the floor, and the published stats snapshot round-trips into the
    device plugin's live-signal scorer."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    import numpy as np

    from workloads.fleet import DEAD, Fleet
    from workloads.ledger import ChipTimeLedger, FleetLedger
    from workloads.model import ModelConfig, init_params
    from workloads.serve import ServeEngine

    config = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
    params = init_params(config, jax.random.PRNGKey(0))
    ps, batch = 4, 2
    # A pool tight enough that tenant prefixes must spill to the host
    # tier under the oversubscribed stream (the kvcache-check recipe).
    n_pages = 12

    def engine():
        return ServeEngine(
            params, config, slots=batch, page_size=ps, prompt_bucket=8,
            n_pages=n_pages, prefix_cache=True, kv_offload=True,
            kv_host_pages=8 * n_pages, ledger=ChipTimeLedger(),
        )

    stats_path = str(tmp_path / "fleet-stats.json")
    fleet_ledger = FleetLedger()
    fleet = Fleet(
        [engine(), engine()],
        chip_ids=["chip-0", "chip-1"],
        hang_timeout_s=None,
        page_scheduling=True,
        stats_path=stats_path,
        ledger=fleet_ledger,
    )
    rng = np.random.default_rng(7)
    prefixes = {
        t: [int(x) for x in rng.integers(0, config.vocab_size, 2 * ps)]
        for t in range(3)
    }
    reqs = []
    for i in range(12):
        tenant = i % 3
        tail = [int(x) for x in rng.integers(0, config.vocab_size, 1 + i % 5)]
        reqs.append((prefixes[tenant] + tail, 2 + i % 6, tenant))
    rids = [
        fleet.submit(p, n, session=f"tenant-{t}") for p, n, t in reqs
    ]
    served = fleet.run()
    assert sorted(served) == sorted(rids)
    assert fleet.requests_ok == len(reqs)
    assert fleet.page_dispatches > 0

    # The oversubscription actually bit: the radix tier spilled.
    spills = sum(int(r.engine.prefix.spills) for r in fleet.replicas)
    assert spills >= 1, "pool was not tight enough to force an offload"

    # Chip time was spent working, not idling the oversubscribed queue.
    assert fleet_ledger.snapshot()["busy_fraction"] >= 0.5
    assert fleet_ledger.goodput_fraction >= 0.99

    # No page/slot leaks at drain (prefix-pinned pages are not leaks).
    for rep in fleet.replicas:
        if rep.state == DEAD:
            continue
        e = rep.engine
        assert not e._occupied.any(), rep.index
        assert e._committed_pages == 0, rep.index
        pinned = e.prefix.cached_pages if e.prefix is not None else 0
        assert e.ctrl.used_pages == pinned, rep.index
        assert not rep.rids, rep.index

    # publish -> plugin scorer round trip: the snapshot the fleet just
    # wrote is fresh, epoch-stamped, and steers score_devices.
    assert fleet.publish_stats() == stats_path
    assert fleet.stats_published >= 1
    stats, reason = kvsched.read_stats_snapshot(stats_path)
    assert reason == "ok"
    assert set(stats) >= {"chip-0", "chip-1"}
    for cid in ("chip-0", "chip-1"):
        assert stats[cid]["total_pages"] == float(n_pages)
        assert 0.0 <= stats[cid]["busy_fraction"] <= 1.0
    available = _expand(["chip-0", "chip-1"], 2)
    got = kvsched.score_devices(list(available), [], 2, stats)
    assert len({d.split("-replica-")[0] for d in got.devices}) == 2
    fleet.close()

"""Tensor-parallel serving (workloads/tp_serve.py) on the 8-device CPU
mesh: TP cached decode and the TP serving engine emit exactly the
single-device tokens; invalid meshes fail loudly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine
from workloads.tp_serve import make_tp_generate, make_tp_serve_programs
from workloads.train import make_mesh

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)


def _params(config):
    return init_params(config, jax.random.PRNGKey(0))


def test_tp_generate_matches_single_device():
    """dp x tp decode emits the single-device greedy tokens exactly."""
    mesh = make_mesh(8, model_parallel=4)  # ("data", "model") = (2, 4)
    params = _params(CONFIG)
    prompts = jax.random.randint(
        jax.random.PRNGKey(1), (4, 8), 0, CONFIG.vocab_size, jnp.int32
    )
    tp_gen = make_tp_generate(CONFIG, mesh)
    got = tp_gen(params, prompts, 12)
    want = generate(params, prompts, CONFIG, max_new_tokens=12)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_generate_gqa_shards_kv_heads():
    """Grouped-query decode under tensor parallelism: the kv-heads axis
    (the scarce one) carries the model cut."""
    config = ModelConfig(
        max_seq_len=64, n_layers=2, n_heads=4, n_kv_heads=2,
        dtype=jnp.float32,
    )
    mesh = make_mesh(8, model_parallel=2)  # kv_heads=2 shards over mp=2
    params = _params(config)
    prompts = jax.random.randint(
        jax.random.PRNGKey(2), (4, 6), 0, config.vocab_size, jnp.int32
    )
    got = make_tp_generate(config, mesh)(params, prompts, 10)
    want = generate(params, prompts, config, max_new_tokens=10)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_generate_rejects_indivisible_heads():
    config = ModelConfig(max_seq_len=64, n_layers=2, n_heads=4, n_kv_heads=2)
    mesh = make_mesh(8, model_parallel=4)  # 4 does not divide kv_heads=2
    with pytest.raises(ValueError, match="kv_heads"):
        make_tp_generate(config, mesh)


def test_tp_serve_programs_require_data_degree_one():
    mesh = make_mesh(8, model_parallel=4)  # data degree 2
    with pytest.raises(ValueError, match="data degree 1"):
        make_tp_serve_programs(CONFIG, mesh, chunk=4, sampling=False)


def test_tp_engine_matches_generate():
    """The continuous-batching engine over a model-parallel mesh serves
    exactly the single-device tokens — sharded pools, shard_mapped
    kernel, mixed-length stream and all."""
    mesh = make_mesh(4, model_parallel=4)  # ("data", "model") = (1, 4)
    params = _params(CONFIG)
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=12, chunk=4,
        mesh=mesh,
    )
    rng = np.random.default_rng(5)
    requests = []
    for _ in range(4):
        plen = int(rng.integers(3, 11))
        requests.append(
            (list(rng.integers(0, CONFIG.vocab_size, plen)), int(rng.integers(2, 20)))
        )
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()
    for rid, (prompt, new) in zip(rids, requests):
        want = generate(
            params, jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )
        np.testing.assert_array_equal(
            np.asarray(served[rid]), np.asarray(want[0]),
            err_msg=f"{rid} (prompt {len(prompt)}, new {new})",
        )
    assert engine.ctrl.used_pages == 0


def test_tp_engine_chunked_prefill_long_prompt():
    """Prompts beyond the bucket admit via chunked prefill on the TP
    engine too (the chunked path is pure XLA — GSPMD partitions it from
    the sharded pools) and still match single-device greedy exactly."""
    mesh = make_mesh(4, model_parallel=4)
    params = _params(CONFIG)
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8, chunk=4,
        mesh=mesh,
    )
    rng = np.random.default_rng(21)
    prompt = list(rng.integers(0, CONFIG.vocab_size, 21))  # 3 chunks
    rid = engine.submit(prompt, 10)
    served = engine.run()
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=10
    )
    np.testing.assert_array_equal(np.asarray(served[rid]), np.asarray(want[0]))
    assert engine.ctrl.used_pages == 0


def test_tp_engine_fanout_shares_pages():
    """Fan-out sampling composes with tensor parallelism: one prefill,
    shared (sharded) prompt pages, greedy members match single-device."""
    mesh = make_mesh(2, model_parallel=2)
    params = _params(CONFIG)
    engine = ServeEngine(
        params, CONFIG, slots=3, page_size=4, prompt_bucket=12, chunk=4,
        mesh=mesh,
    )
    prompt = list(range(2, 12))
    rids = engine.submit_fanout(prompt, 6, n_samples=3)
    served = engine.run()
    want = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=6
    )
    for rid in rids:
        np.testing.assert_array_equal(np.asarray(served[rid]), np.asarray(want[0]))
    assert engine.prefills_run == 1
    assert engine.ctrl.used_pages == 0


DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)


def test_tp_spec_engine_matches_single_device():
    """Tensor-parallel speculative serving: draft and verify both run
    under the model mesh, and every request's tokens match BOTH the
    single-device speculative engine and plain greedy generate() —
    speculation and tensor parallelism compose losslessly."""
    mesh = make_mesh(2, model_parallel=2)
    params = _params(CONFIG)
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    kwargs = dict(
        slots=2, page_size=4, prompt_bucket=8,
        draft_config=DRAFT_CONFIG, gamma=3,
    )
    rng = np.random.default_rng(31)
    requests = []
    for _ in range(4):
        plen = int(rng.integers(3, 9))
        requests.append(
            (list(rng.integers(0, CONFIG.vocab_size, plen)),
             int(rng.integers(2, 20)))
        )

    single = ServeEngine(params, CONFIG, draft_params=draft, **kwargs)
    for i, (p, n) in enumerate(requests):
        single.submit(p, n, rid=f"r{i}")
    want = single.run()
    assert single.spec_rounds > 0

    tp = ServeEngine(params, CONFIG, draft_params=draft, mesh=mesh, **kwargs)
    for i, (p, n) in enumerate(requests):
        tp.submit(p, n, rid=f"r{i}")
    got = tp.run()
    assert got == want
    assert tp.spec_rounds > 0
    for i, (prompt, new) in enumerate(requests):
        ref = generate(
            params, jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )
        np.testing.assert_array_equal(np.asarray(got[f"r{i}"]), np.asarray(ref[0]))
    assert tp.ctrl.used_pages == 0


def test_tp_spec_rejects_indivisible_draft_heads():
    """A draft whose kv heads cannot shard over the mesh's model degree
    fails loudly at construction, not mid-serve."""
    mesh = make_mesh(4, model_parallel=4)  # DRAFT_CONFIG has 2 heads
    params = _params(CONFIG)
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    with pytest.raises(ValueError, match="kv_heads"):
        ServeEngine(
            params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
            draft_params=draft, draft_config=DRAFT_CONFIG, mesh=mesh,
        )


def test_tp_pipelined_spec_engine_matches_greedy():
    """The full composition: tensor parallelism x speculation x
    pipelined rounds — tokens still exactly match plain greedy."""
    mesh = make_mesh(2, model_parallel=2)
    params = _params(CONFIG)
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        draft_params=draft, draft_config=DRAFT_CONFIG, gamma=3,
        mesh=mesh, pipelined=True,
    )
    requests = [([1, 2, 3, 4], 10), ([5, 6], 14), ([7, 8, 9], 6)]
    rids = [engine.submit(p, n) for p, n in requests]
    served = engine.run()
    for rid, (p, n) in zip(rids, requests):
        want = generate(
            params, jnp.asarray([p], jnp.int32), CONFIG, max_new_tokens=n
        )
        np.testing.assert_array_equal(
            np.asarray(served[rid]), np.asarray(want[0]), err_msg=rid
        )
    assert engine.spec_rounds > 0
    assert engine.ctrl.used_pages == 0


def test_tp_engine_pipelined_matches_unpipelined():
    """VERDICT r3 weak #5: the highest-throughput configuration of the
    highest-capacity configuration — pipelined stepping on a model mesh —
    serves exactly the unpipelined TP tokens (readback overlap changes
    scheduling, never values)."""
    mesh = make_mesh(2, model_parallel=2)
    params = _params(CONFIG)
    kwargs = dict(slots=2, page_size=4, prompt_bucket=12, chunk=4)
    rng = np.random.default_rng(41)
    requests = []
    for _ in range(4):
        plen = int(rng.integers(3, 11))
        requests.append(
            (list(rng.integers(0, CONFIG.vocab_size, plen)),
             int(rng.integers(2, 20)))
        )

    plain = ServeEngine(params, CONFIG, mesh=mesh, **kwargs)
    for i, (p, n) in enumerate(requests):
        plain.submit(p, n, rid=f"r{i}")
    want = plain.run()

    piped = ServeEngine(params, CONFIG, mesh=mesh, pipelined=True, **kwargs)
    for i, (p, n) in enumerate(requests):
        piped.submit(p, n, rid=f"r{i}")
    got = piped.run()
    assert got == want
    assert piped._pending_read is None
    assert piped.ctrl.used_pages == 0


def test_tp_engine_gqa_window_stream():
    """GQA + sliding window through the TP engine drains and matches the
    single-device engine's greedy tokens."""
    config = ModelConfig(
        max_seq_len=64, n_layers=2, n_heads=4, n_kv_heads=2,
        attention_window=8, dtype=jnp.float32,
    )
    mesh = make_mesh(2, model_parallel=2)
    params = _params(config)
    kwargs = dict(slots=2, page_size=4, prompt_bucket=8, chunk=4)
    requests = [([1, 2, 3, 4], 10), ([5, 6], 6), ([7, 8, 9], 12)]

    single = ServeEngine(params, config, **kwargs)
    for p, n in requests:
        single.submit(p, n, rid=f"r{len(p)}-{n}")
    want = single.run()

    tp = ServeEngine(params, config, mesh=mesh, **kwargs)
    for p, n in requests:
        tp.submit(p, n, rid=f"r{len(p)}-{n}")
    got = tp.run()
    assert got == want

"""Disaggregated prefill/decode pools with KV handoff over the host
tier + SLO-class weighted fair scheduling (workloads/fleet.py
``Fleet(roles=)``, docs/SERVING.md "Disaggregated prefill/decode").

The pinned contracts: greedy streams on a prefill/decode split fleet
are BIT-IDENTICAL to the same seeded request stream on a mixed fleet
(and to the dense oracle); a handoff actually moves pages — exported
off the prefill replica with ONE gathered device_get, grafted into the
decode replica's radix index, reloaded on its admission sweep; the
full lifecycle composes (mid-handoff cancel/deadline, exporter crash
after the spill, decode-pool death degrading to mixed dispatch); WFQ
splits fresh-prompt dispatch in weight proportion with continuations
holding absolute precedence; ``Replica.load`` weighs mid-prefill
backlog by remaining prompt-bucket units; and ``schedule_per_class``
is a deterministic merge of per-class independent arrival processes."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.fleet import (
    DEAD,
    Fleet,
    FleetRequest,
    KVHandoff,
    Router,
    TrafficGen,
)
from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.paged import RadixKV, read_page, read_pages
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
PARAMS = init_params(CONFIG, jax.random.PRNGKey(0))


def _engine(**kw):
    base = dict(
        slots=2, page_size=4, prompt_bucket=4,
        prefix_cache=True, kv_offload=True,
    )
    base.update(kw)
    return ServeEngine(PARAMS, CONFIG, **base)


def _fleet(n, roles=None, *, engine_kw=None, **fleet_kw):
    fleet_kw.setdefault("chip_ids", [f"chip-{i}" for i in range(n)])
    fleet_kw.setdefault("hang_timeout_s", None)
    return Fleet(
        [_engine(**(engine_kw or {})) for _ in range(n)],
        roles=roles, **fleet_kw,
    )


def _oracle(prompt, new):
    return [int(t) for t in np.asarray(generate(
        PARAMS, jnp.asarray([prompt], jnp.int32), CONFIG,
        max_new_tokens=new,
    )[0])]


def _prompts(seed, n, lo=4, hi=24, new_lo=3, new_hi=12):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        plen = int(rng.integers(lo, hi))
        prompt = [int(t) for t in rng.integers(0, CONFIG.vocab_size, plen)]
        out.append((prompt, int(rng.integers(new_lo, new_hi))))
    return out


def _assert_no_leaks(fleet):
    for rep in fleet.replicas:
        if rep.state == DEAD:
            continue
        e = rep.engine
        assert not e._occupied.any(), rep.index
        assert e._committed_pages == 0, rep.index
        pinned = e.prefix.cached_pages if e.prefix is not None else 0
        assert e.ctrl.used_pages == pinned, rep.index
        assert not rep.rids, rep.index


# ---- validation ----------------------------------------------------------


def test_roles_validation():
    with pytest.raises(ValueError, match="roles"):
        _fleet(2, roles=["prefill"])  # length mismatch
    with pytest.raises(ValueError, match="roles"):
        _fleet(2, roles=["prefill", "verbs"])  # unknown role
    with pytest.raises(ValueError, match="wfq_weights"):
        _fleet(1, wfq_weights={"interactive": 0.0})
    fleet = _fleet(2, roles=["prefill", "decode"])
    assert fleet.roles() == {0: "prefill", 1: "decode"}
    assert fleet.disaggregated
    idx = fleet.add_replica(_engine(), "chip-2", role="decode")
    assert fleet.roles()[idx] == "decode"
    fleet.close()
    plain = _fleet(2)
    assert not plain.disaggregated
    assert plain.roles() == {0: "mixed", 1: "mixed"}
    plain.close()


# ---- the headline parity pin --------------------------------------------


def test_disagg_streams_bit_identical_to_mixed_and_oracle():
    """THE acceptance pin: the same seeded stream through a
    prefill/decode split fleet (WFQ armed) and an all-mixed fleet
    produces bit-identical greedy streams — and both match the dense
    oracle — while the split fleet actually hands off: tickets carry
    pages, decode replicas graft and reload them, and every handoff
    records its prefill-done -> first-decode-token window."""
    reqs = _prompts(3, 8, lo=9, hi=24)  # >= 2 full pages: pages move

    def run(roles, wfq=None):
        fleet = _fleet(3, roles, wfq_weights=wfq)
        for i, (p, nw) in enumerate(reqs):
            fleet.submit(p, nw, slo_class="interactive" if i % 2 else "bulk")
        streams = fleet.run()
        return streams, fleet

    mixed, mfleet = run(None)
    split, sfleet = run(
        ["prefill", "decode", "decode"],
        wfq={"interactive": 3.0, "bulk": 1.0},
    )
    assert split == mixed
    for rid, (p, nw) in zip(
        sorted(split, key=lambda r: int(r.split("-")[1])), reqs
    ):
        assert split[rid] == _oracle(p, nw), rid
    assert mfleet.kv_handoffs == 0
    assert sfleet.kv_handoffs == len(reqs)
    assert sfleet.handoff_pages > 0  # ticket pages actually grafted
    assert len(sfleet.handoff_s) == len(reqs)
    assert all(s > 0 for s in sfleet.handoff_s)
    # The prefill pool exported, the decode pool adopted + reloaded.
    assert sfleet.replicas[0].engine.kv_handoff_pages_out > 0
    decode_in = sum(
        sfleet.replicas[i].engine.kv_handoff_pages_in for i in (1, 2)
    )
    decode_reloads = sum(
        sfleet.replicas[i].engine.prefix.reloads for i in (1, 2)
    )
    assert decode_in > 0 and decode_reloads > 0
    # WFQ charged only fresh prompts, by class.
    assert sum(sfleet.wfq_dispatches.values()) == len(reqs)
    _assert_no_leaks(mfleet)
    _assert_no_leaks(sfleet)
    mfleet.close()
    sfleet.close()


def test_disagg_without_offload_degrades_bit_identical():
    """Roles on engines WITHOUT a prefix cache: export returns None,
    tickets ship empty, and the decode pool re-prefills — the split
    degrades to the replay path with identical tokens."""
    reqs = _prompts(5, 5)
    kw = dict(prefix_cache=False, kv_offload=False)
    mixed = _fleet(2, engine_kw=kw)
    split = _fleet(2, ["prefill", "decode"], engine_kw=kw)
    for p, nw in reqs:
        mixed.submit(p, nw)
    for p, nw in reqs:
        split.submit(p, nw)
    assert mixed.run() == split.run()
    assert split.kv_handoffs == len(reqs)
    assert split.handoff_pages == 0  # nothing to ship, still correct
    _assert_no_leaks(split)
    mixed.close()
    split.close()


def test_handoff_composes_with_budget_superstep_and_lora():
    """prefill_budget + superstep_k + a LoRA adapter on a split fleet:
    still bit-identical to the mixed fleet (the adapter salt rides the
    ticket)."""
    from workloads.multi_lora import synthetic_adapters

    adapters = synthetic_adapters(CONFIG, 1, rank=2, seed=5)
    adapters = {"tenant": adapters["tenant-0"]}
    kw = dict(
        prompt_bucket=8, prefill_budget=8, superstep_k=2,
        adapters=adapters,
    )
    reqs = _prompts(7, 6, lo=9, hi=20)

    def run(roles):
        fleet = _fleet(2, roles, engine_kw=kw)
        for i, (p, nw) in enumerate(reqs):
            fleet.submit(p, nw, adapter="tenant" if i % 2 else None)
        out = fleet.run()
        _assert_no_leaks(fleet)
        fleet.close()
        return out

    assert run(None) == run(["prefill", "decode"])


# ---- lifecycle composition ----------------------------------------------


def _run_until_ticket(fleet, rid):
    """Step until the rid's handoff ticket sits in the router queue."""
    for _ in range(200):
        fleet.step()
        fr = fleet._reqs[rid]
        if fr.handoff is not None and any(q is fr for q in fleet.queue):
            return fr
    raise AssertionError("no handoff ticket appeared")


def test_cancel_mid_handoff():
    fleet = _fleet(2, ["prefill", "decode"])
    p, nw = _prompts(11, 1, lo=9)[0]
    rid = fleet.submit(p, nw)
    # A second stream keeps the fleet busy so cancel's surfacing step
    # has work to return.
    other = fleet.submit([5] * 10, 6)
    fr = _run_until_ticket(fleet, rid)
    assert fleet.cancel(rid)
    assert fr.status == "cancelled"
    assert fr.handoff is None  # the ticket's blobs freed with it
    assert rid not in fleet._handoff_at
    fleet.run()
    assert fleet._reqs[other].status == "ok"
    _assert_no_leaks(fleet)
    fleet.close()


def test_deadline_expires_mid_handoff():
    fleet = _fleet(2, ["prefill", "decode"])
    p, nw = _prompts(13, 1, lo=9)[0]
    rid = fleet.submit(p, nw, deadline_s=0.05)
    fr = _run_until_ticket(fleet, rid)
    import time as _time

    _time.sleep(0.06)
    fleet.run()
    assert fr.status == "expired"
    assert fr.handoff is None
    _assert_no_leaks(fleet)
    fleet.close()


def test_prefill_crash_after_export_ticket_survives():
    """The exporter dying AFTER the spill cannot strand the ticket:
    its blobs are host RAM, independent of the dead engine — the
    decode pool grafts them and the stream completes bit-identically."""
    fleet = _fleet(2, ["prefill", "decode"])
    p, nw = _prompts(17, 1, lo=9, new_lo=6)[0]
    rid = fleet.submit(p, nw)
    fr = _run_until_ticket(fleet, rid)
    assert fr.handoff.blobs  # the ticket really carries pages
    fleet._fail_replica(
        fleet.replicas[0], RuntimeError("injected"), "crash"
    )
    assert fleet.replicas[0].state == DEAD
    fleet.run()
    assert fr.status == "ok"
    assert fr.tokens == _oracle(p, nw)
    assert fleet.replicas[1].engine.kv_handoff_pages_in > 0
    _assert_no_leaks(fleet)
    fleet.close()


def test_decode_pool_death_degrades_to_mixed_dispatch():
    """A dead decode pool must not strand tickets OR fresh prompts:
    dispatch degrades to the surviving prefill replica as mixed — the
    budget cap lifts (no live handoff target), streams complete
    bit-identically."""
    fleet = _fleet(2, ["prefill", "decode"])
    reqs = _prompts(19, 4, lo=9, new_lo=5)
    rids = [fleet.submit(p, nw) for p, nw in reqs]
    fleet._fail_replica(
        fleet.replicas[1], RuntimeError("injected"), "crash"
    )
    fleet.run()
    for rid, (p, nw) in zip(rids, reqs):
        fr = fleet._reqs[rid]
        assert fr.status == "ok", (rid, fr.error)
        assert fr.tokens == _oracle(p, nw)
    # No handoffs happened: the cap only arms with a live target.
    assert fleet.kv_handoffs == 0
    _assert_no_leaks(fleet)
    fleet.close()


def test_decode_pool_death_after_ticket_still_serves_it():
    """The harder ordering: the ticket exists FIRST, then the whole
    decode pool dies — the ticketed continuation degrades back onto
    the prefill replica (its own index still holds the pages) and
    completes bit-identically."""
    fleet = _fleet(2, ["prefill", "decode"])
    p, nw = _prompts(23, 1, lo=9, new_lo=6)[0]
    rid = fleet.submit(p, nw)
    fr = _run_until_ticket(fleet, rid)
    fleet._fail_replica(
        fleet.replicas[1], RuntimeError("injected"), "crash"
    )
    fleet.run()
    assert fr.status == "ok"
    assert fr.tokens == _oracle(p, nw)
    _assert_no_leaks(fleet)
    fleet.close()


def test_supervisor_resurrects_pool_role():
    """A resurrected pool member rejoins ITS pool: the supervisor
    carries the dead slot's role through the respawn."""
    from workloads.backoff import Backoff
    from workloads.supervisor import FleetSupervisor, make_engine_factory

    fleet = _fleet(2, ["prefill", "decode"])
    factory, oracle = make_engine_factory(
        PARAMS, CONFIG, engine_kw=dict(
            slots=2, page_size=4, prompt_bucket=4,
            prefix_cache=True, kv_offload=True,
        ), probe=([1, 2, 3], 4),
    )
    sup = FleetSupervisor(
        fleet, factory,
        backoff=Backoff(base_s=1e-3, max_s=1e-3, jitter=0.0),
        probe=([1, 2, 3], 4), probe_oracle=oracle,
    )
    assert sup.slot_for("chip-0").role == "prefill"
    fleet._fail_replica(
        fleet.replicas[0], RuntimeError("injected"), "crash"
    )
    assert sup.wait_healed(30.0), sup.states()
    new_idx = sup.slot_for("chip-0").index
    assert fleet.replicas[new_idx].role == "prefill"
    fleet.close()


# ---- router load scoring (satellite) ------------------------------------


def test_load_weights_midprefill_backlog():
    """A parked mid-prefill row weighs its REMAINING prompt tokens in
    prompt-bucket units — a long prompt two chunks in no longer looks
    as cheap as a finishing decode row — and the router therefore
    routes the next prompt AWAY from the replica chewing a long
    prefill."""
    kw = dict(prompt_bucket=4, prefill_budget=4)
    fleet = _fleet(2, engine_kw=kw)
    long_prompt = [7] * 32  # 8 bucket-units of sweep work
    fleet.submit(long_prompt, 4)
    fleet.step()  # dispatch + first budgeted chunk; the rest parks
    rep0 = fleet.replicas[0]
    assert rep0.engine._inflight_prefill  # genuinely parked mid-prefill
    # 32 prompt tokens at budget 4/step: >= 6 bucket-units remain.
    assert rep0.load() >= 6
    # The old scalar would have said 1 — equal to one queued request —
    # and least-loaded would have tied; now the short prompt must land
    # on the idle replica.
    rid2 = fleet.submit([9] * 4, 3)
    fleet.step()
    fr2 = fleet._reqs[rid2]
    assert fr2.replica == 1 or fr2.status == "ok"
    assert rid2 in fleet.replicas[1].rids or fr2.status == "ok"
    fleet.run()
    _assert_no_leaks(fleet)
    fleet.close()


# ---- batched spills (satellite) -----------------------------------------


def test_gathered_spill_bit_exact_and_single_sync(monkeypatch):
    """``_spill_pages`` pays ONE fused device_get for an n-page park
    and its per-page blobs are bit-exact against ``read_page``."""
    engine = _engine()
    prompt = [3] * 12  # 3 full pages
    engine.submit(prompt, 2)
    engine.run()
    pages = engine.prefix.lookup(prompt, 3, salt="")
    assert len(pages) == 3
    # Per-page reference bytes BEFORE the park moves anything.
    ref = [jax.device_get(read_page(engine.pools, p)) for p in pages]
    calls = []
    real = jax.device_get

    def counting(x):
        calls.append(1)
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    blobs = engine._spill_pages(pages)
    assert len(calls) == 1  # the n-fold round-trip collapse
    monkeypatch.undo()
    assert len(blobs) == 3
    for (main, draft), (rk, rv) in zip(blobs, ref):
        assert draft is None
        assert np.array_equal(np.asarray(main[0]), rk)
        assert np.array_equal(np.asarray(main[1]), rv)
    engine.close()


def test_park_spill_many_matches_serial_spill():
    """park(spill_many=) and park(spill=) produce identical host-tier
    state: same pages parked, and reloaded streams stay bit-identical
    (the serial/batched seam can never change a token)."""
    def parked_state(batched):
        engine = _engine()
        prompt = [4] * 16
        engine.submit(prompt, 2)
        engine.run()
        kw = (
            dict(spill_many=engine._spill_pages) if batched
            else dict(spill=engine._spill_page)
        )
        n = engine.prefix.park(prompt, salt="", **kw)
        out = (n, engine.prefix.offloaded_pages)
        # Resume: the next lookup reloads the parked pages and the
        # continuation must match the dense oracle.
        engine.submit(prompt, 5)
        streams = engine.run()
        engine.close()
        return out, list(streams.values())[0]

    (n_b, off_b), toks_b = parked_state(True)
    (n_s, off_s), toks_s = parked_state(False)
    assert (n_b, off_b) == (n_s, off_s)
    assert n_b == 4
    assert toks_b == toks_s == _oracle([4] * 16, 5)


def test_import_kv_refuses_incompatible_tickets():
    """Heterogeneous fleets are legal, so import must DEGRADE (refuse
    the graft, let replay re-prefill) rather than adopt blobs that
    would wedge a future admission's reload: a different page_size,
    and an adapter this engine does not serve (grafting it under the
    base salt would poison the base prefix cache with LoRA KV)."""
    src = _engine(page_size=8, prompt_bucket=8)
    prompt = [6] * 16
    src.submit(prompt, 2)
    src.run()
    blobs = src.export_kv(prompt)
    assert blobs
    dst = _engine()  # page_size=4: wrong shape — must refuse
    assert dst.import_kv(prompt, blobs) == 0
    assert dst.prefix.offloaded_pages == 0
    # Unknown adapter: refused outright, base salt untouched.
    src4 = _engine()
    src4.submit(prompt, 2)
    src4.run()
    blobs4 = src4.export_kv(prompt)
    assert dst.import_kv(prompt, blobs4, adapter="ghost") == 0
    assert dst.prefix.offloaded_pages == 0
    # And the compatible same-shape ticket still grafts.
    assert dst.import_kv(prompt, blobs4) == len(blobs4)
    for e in (src, dst, src4):
        e.close()


def test_heterogeneous_page_size_split_fleet_stays_oracle_true():
    """A split fleet whose pools disagree on page_size: every handoff
    ticket is refused at import (shape guard) and the continuation
    re-prefills — streams still bit-identical to the oracle."""
    engines = [
        _engine(page_size=8, prompt_bucket=8),
        _engine(page_size=4, prompt_bucket=4),
    ]
    fleet = Fleet(
        engines, chip_ids=["chip-0", "chip-1"], hang_timeout_s=None,
        roles=["prefill", "decode"],
    )
    reqs = _prompts(37, 4, lo=9, new_lo=4)
    rids = [fleet.submit(p, nw) for p, nw in reqs]
    fleet.run()
    for rid, (p, nw) in zip(rids, reqs):
        fr = fleet._reqs[rid]
        assert fr.status == "ok"
        assert fr.tokens == _oracle(p, nw)
    assert fleet.kv_handoffs == len(reqs)
    assert fleet.handoff_pages == 0  # every graft refused, none wedged
    _assert_no_leaks(fleet)
    fleet.close()


def test_spill_blobs_own_their_memory():
    """Gathered-spill blobs must be OWNED copies, not views into the
    padded batch buffer — one long-lived blob (a parked node, a
    handoff ticket) must pin one page of host RAM, not the whole
    gather."""
    engine = _engine()
    prompt = [8] * 12
    engine.submit(prompt, 2)
    engine.run()
    pages = engine.prefix.lookup(prompt, 3, salt="")
    for (mk, mv), draft in engine._spill_pages(pages):
        assert mk.base is None and mv.base is None
        assert draft is None
    engine.close()


def test_handoff_ticket_survives_engine_closed_race():
    """A decode replica closing between the dispatchable check and the
    submit must NOT consume the ticket: the requeued request keeps it
    for the next dispatch onto a live replica."""
    from workloads.errors import EngineClosed

    fleet = _fleet(2, ["prefill", "decode"])
    p, nw = _prompts(41, 1, lo=9, new_lo=6)[0]
    rid = fleet.submit(p, nw)
    fr = _run_until_ticket(fleet, rid)
    ticket = fr.handoff
    pages0 = fleet.handoff_pages
    fleet.replicas[1].engine.close()  # dies under the router
    with pytest.raises(EngineClosed):
        fleet._dispatch_to(fr, fleet.replicas[1])
    assert fr.handoff is ticket  # still attached
    assert fleet.handoff_pages == pages0  # nothing counted as served
    fleet.close()


def test_load_requests_keeps_request_units():
    """The autoscaler's depth signal reads load_requests() — one unit
    per request regardless of prompt length — while the router's
    load() weighs mid-prefill backlog; one long prompt must never read
    as dozens of queued requests to the scaling loop."""
    kw = dict(prompt_bucket=4, prefill_budget=4)
    fleet = _fleet(1, engine_kw=kw)
    fleet.submit([7] * 32, 4)
    fleet.step()
    rep = fleet.replicas[0]
    assert rep.load() >= 6  # router: bucket-weighted
    assert rep.load_requests() == 1  # autoscaler: request-count
    fleet.run()
    fleet.close()


def test_graft_respects_host_budget():
    """A partial graft (host budget exhausted) is a shorter future hit,
    never an error — and the continuation still streams bit-identically
    via re-prefill of the un-grafted suffix."""
    src = _engine()
    prompt = [6] * 16  # 4 pages
    src.submit(prompt, 2)
    src.run()
    blobs = src.export_kv(prompt)
    assert len(blobs) == 4
    dst = _engine(kv_host_pages=2)
    assert dst.import_kv(prompt, blobs) == 2  # budget-capped
    assert dst.prefix.offloaded_pages == 2
    dst.submit(prompt, 5)
    assert list(dst.run().values())[0] == _oracle(prompt, 5)
    src.close()
    dst.close()


# ---- WFQ (tentpole) ------------------------------------------------------


def _fr(rid, cls, prompt_len=4, tokens=()):
    fr = FleetRequest(
        rid, [1] * prompt_len, 8, None, slo_class=cls,
    )
    fr.tokens = list(tokens)
    return fr


def test_wfq_orders_by_weight_and_respects_continuations():
    fleet = _fleet(1, wfq_weights={"a": 3.0, "b": 1.0})
    fresh = [_fr(f"a{i}", "a") for i in range(4)] + [
        _fr(f"b{i}", "b") for i in range(4)
    ]
    cont = [_fr("c0", "b", tokens=[5])]
    order = [fr.rid for fr in fleet._wfq_order(cont + fresh)]
    # Continuations first; then finish-tag order: 'a' (weight 3) takes
    # 3 of the first 4 slots, 'b' lands at its virtual finish of 1.
    assert order[0] == "c0"
    assert order[1:] == ["a0", "a1", "a2", "b0", "a3", "b1", "b2", "b3"]
    # Finish tags weigh COST against weight: a 4-bucket 'a' prompt
    # finishes at 4/3, so the 1-bucket 'b' (finish 1) beats it to the
    # first slot DESPITE 'a' holding 3x the weight — and 'a' still
    # beats b1 (finish 2).
    big = [_fr(f"A{i}", "a", prompt_len=16) for i in range(2)] + [
        _fr(f"B{i}", "b") for i in range(2)
    ]
    order2 = [fr.rid for fr in fleet._wfq_order(big)]
    assert order2 == ["B0", "A0", "B1", "A1"]
    fleet.close()


def test_wfq_idle_class_banks_no_credit():
    """A class that idled while another was served re-enters at the
    CURRENT virtual time — it cannot monopolize dispatch to 'catch
    up' on credit it never queued for."""
    fleet = _fleet(1, wfq_weights={"a": 1.0, "b": 1.0})
    for i in range(6):  # six one-dispatch batches, as the loop would run
        fleet._wfq_charge(_fr(f"a{i}", "a"), fleet._wfq_v)
        fleet._wfq_v = fleet._wfq_vtime["a"]
    assert fleet._wfq_vtime["a"] == pytest.approx(6.0)
    order = [
        fr.rid for fr in fleet._wfq_order(
            [_fr("b0", "b"), _fr("a6", "a"), _fr("b1", "b")]
        )
    ]
    # 'b' starts at v_now (not 0), so it alternates instead of draining
    # every 'b' before 'a' gets another slot.
    assert order == ["a6", "b0", "b1"] or order == ["b0", "a6", "b1"]
    fleet.close()


def test_wfq_dispatch_split_on_one_replica():
    """End-to-end: a starved 1-replica fleet under WFQ serves the
    heavy class ~3x as often among the first dispatches, and every
    stream still finishes ok with oracle tokens."""
    fleet = _fleet(
        1, engine_kw=dict(slots=1), wfq_weights={"hi": 3.0, "lo": 1.0},
        slo_classes=None,
    )
    # slo classes: reuse defaults for validation; tag via wfq-only
    # classes is fine — wfq_weights classes need not be SLO classes.
    reqs = _prompts(29, 8, lo=4, hi=8, new_lo=2, new_hi=4)
    rids = []
    for i, (p, nw) in enumerate(reqs):
        rids.append(fleet.submit(
            p, nw, slo_class="interactive" if i < 4 else "bulk",
        ))
    fleet.wfq_weights = {"interactive": 3.0, "bulk": 1.0}
    fleet.run()
    assert fleet.wfq_dispatches["interactive"] == 4
    assert fleet.wfq_dispatches["bulk"] == 4
    for rid, (p, nw) in zip(rids, reqs):
        assert fleet._reqs[rid].tokens == _oracle(p, nw)
    _assert_no_leaks(fleet)
    fleet.close()


# ---- per-class traffic (satellite) --------------------------------------


def test_schedule_per_class_deterministic_and_independent():
    gen = TrafficGen(
        seed=5, rate_rps=50.0, class_mix=(("interactive", 3.0), ("bulk", 1.0)),
    )
    a = gen.schedule_per_class(16)
    b = gen.schedule_per_class(16)
    assert a == b  # deterministic per seed
    # Reordering the mix cannot move a token of either class.
    flipped = dataclasses.replace(
        gen, class_mix=(("bulk", 1.0), ("interactive", 3.0)),
    )
    assert flipped.schedule_per_class(16) == a
    # Each class's sub-stream IS its standalone process at its share.
    import zlib

    share = 3.0 / 4.0
    solo = dataclasses.replace(
        gen,
        seed=(gen.seed << 16) ^ zlib.crc32(b"interactive"),
        rate_rps=gen.rate_rps * share,
    ).schedule(12)  # round(16 * 0.75)
    sub = [(t, p, n) for t, p, n, c in a if c == "interactive"]
    assert sorted(sub) == sorted(solo)
    # And the class draw is genuinely per-process: bulk arrivals exist.
    stats = TrafficGen.schedule_stats(a)
    assert stats["class_counts"] == {"bulk": 4, "interactive": 12}
    assert set(stats["class_mean_rps"]) == {"bulk", "interactive"}
    assert all(
        r is None or 0 < r < 1e6
        for r in stats["class_mean_rps"].values()
    )
    # A single-arrival class has no span: its rate reads None, not
    # the absurd 1/epsilon.
    one = TrafficGen.schedule_stats([(0.5, [1], 2, "solo")])
    assert one["class_mean_rps"] == {"solo": None}
    with pytest.raises(ValueError, match="class_mix"):
        dataclasses.replace(gen, class_mix=()).schedule_per_class(4)


# ---- smoke for make disagg-check ----------------------------------------


def test_disagg_check_smoke():
    """ONE seeded two-pool round — the `make disagg-check` tripwire:
    a prefill+decode split serves a seeded stream bit-identically to
    the mixed fleet AND the dense oracle, with real page movement
    (export -> graft -> reload), every handoff window recorded, and no
    page/slot leaks on either pool."""
    reqs = _prompts(31, 6, lo=9, hi=24, new_lo=4)

    def run(roles):
        fleet = _fleet(2, roles, wfq_weights=(
            {"interactive": 3.0, "bulk": 1.0} if roles else None
        ))
        for i, (p, nw) in enumerate(reqs):
            fleet.submit(p, nw, slo_class="interactive" if i % 2 else "bulk")
        streams = fleet.run()
        _assert_no_leaks(fleet)
        return streams, fleet

    mixed, mf = run(None)
    split, sf = run(["prefill", "decode"])
    assert split == mixed
    for rid, (p, nw) in zip(
        sorted(split, key=lambda r: int(r.split("-")[1])), reqs
    ):
        assert split[rid] == _oracle(p, nw)
    assert sf.kv_handoffs == len(reqs)
    assert sf.handoff_pages > 0
    assert len(sf.handoff_s) == len(reqs)
    assert sf.replicas[1].engine.prefix.grafts > 0
    assert sf.replicas[1].engine.prefix.reloads > 0
    mf.close()
    sf.close()


def test_read_pages_matches_read_page():
    """The gathered-spill primitive is a pure batching of read_page:
    column i of read_pages == read_page(srcs[i]), bit-for-bit."""
    from workloads.paged import init_page_pools

    pools = init_page_pools(CONFIG, 8, 4)
    k = jax.random.PRNGKey(1)
    pools = (
        jax.random.normal(k, pools[0].shape, pools[0].dtype),
        jax.random.normal(jax.random.PRNGKey(2), pools[1].shape,
                          pools[1].dtype),
    )
    srcs = [5, 0, 3]
    gk, gv = jax.device_get(read_pages(pools, np.asarray(srcs, np.int32)))
    for i, s in enumerate(srcs):
        rk, rv = jax.device_get(read_page(pools, s))
        assert np.array_equal(gk[:, i], rk)
        assert np.array_equal(gv[:, i], rv)

"""Feature-matrix fuzz for the serving engine: randomized streams
through randomized engine configurations (prefix cache x pipelined x
speculative x adaptive spec="auto" x multi-LoRA x fan-out x eos x
chunked prefill), every request pinned exactly against the dense
reference model it should be equivalent to — for spec="auto" that means
bit-identical to the per-regime oracle across mode switches, fan-out /
LoRA / prefix-cache admissions straddling a switch included.
Deterministic seeds — failures reproduce."""

import jax
import jax.numpy as jnp
import numpy as np

from workloads.generate import generate
from workloads.lora import merge_lora
from workloads.model import ModelConfig, init_params
from workloads.multi_lora import synthetic_adapters
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)


def _run_one(seed: int, params, draft, adapters) -> None:
    rng = np.random.default_rng(seed)
    spec = bool(rng.integers(2))
    use_adapters = bool(rng.integers(2))
    # Sampling axis (VERDICT r4 item 4): temperature > 0 composes with
    # EVERY arm including speculative (lossless speculative sampling).
    # Sampled streams have no pathwise oracle — they're checked for
    # structural soundness (budgets, vocab range, drain) below; greedy
    # streams stay exactly pinned against the dense reference.
    sampling = bool(rng.integers(2))
    kw = dict(
        slots=int(rng.integers(1, 4)),
        page_size=int(rng.choice([4, 8])),
        prefix_cache=bool(rng.integers(2)),
        pipelined=bool(rng.integers(2)),
    )
    if sampling:
        kw.update(
            temperature=float(rng.choice([0.7, 1.0])),
            top_k=int(rng.choice([0, 40])),
            rng=jax.random.PRNGKey(seed),
        )
    kw["prompt_bucket"] = int(kw["page_size"] * rng.choice([2, 3]))
    # KV-cache hierarchy: the host-RAM offload tier randomizes on top
    # of the radix cache — spills/reloads are bit-exact byte
    # round-trips, so every oracle below holds offload on or off, and
    # the drain hygiene at the bottom proves reclaim.
    if kw["prefix_cache"] and rng.integers(2):
        kw["kv_offload"] = True
        if rng.integers(2):
            kw["kv_host_pages"] = int(rng.integers(1, 9))
    # Decode supersteps: k chained chunks per dispatch with device-side
    # retirement masks must be emission-invariant for every k, across
    # every other arm in this matrix (docs/SERVING.md "Decode
    # supersteps & double-buffered scheduling").
    kw["superstep_k"] = int(rng.choice([1, 1, 2, 4]))
    # Budgeted chunked-prefill interleaving: greedy streams must stay
    # pinned against the dense reference for ANY budget (including 1
    # token/step — every admission parks mid-prefill); sampled budgeted
    # streams keep the structural checks only (the engine key schedule
    # legitimately shifts when finishes cross step boundaries).
    if rng.integers(2):
        kw["prefill_budget"] = int(
            rng.choice([1, kw["prompt_bucket"], 2 * kw["prompt_bucket"]])
        )
    if spec:
        kw.update(draft_params=draft, draft_config=DRAFT_CONFIG,
                  gamma=int(rng.integers(2, 5)))
        if rng.integers(2):
            # Chained-retirement spec supersteps (device-side acceptance
            # masks, one readback per k rounds) must be
            # emission-invariant for every k across this whole matrix.
            kw["spec_superstep_k"] = int(rng.choice([1, 2, 4]))
        else:
            # Lookahead supersteps (k rounds per dispatch) must be
            # emission-invariant for every k.
            kw["spec_lookahead"] = int(rng.choice([1, 1, 2, 3]))
        if rng.integers(2):
            # Adaptive arm: injected thresholds force always-plain
            # (0.0), always-spec (slots) and mid-stream switching —
            # tokens must stay the per-regime oracle's in every case.
            kw.update(spec="auto", spec_breakeven=float(
                rng.choice([0.0, 1.0, 1.5, kw["slots"]])
            ))
    else:
        # chunk != page_size exercises the overshoot/boundary accounting.
        kw["chunk"] = int(kw["page_size"] * rng.choice([1, 2]))
    engine = ServeEngine(
        params, CONFIG, adapters=adapters if use_adapters else None, **kw
    )
    names = [None] + (sorted(adapters) if use_adapters else [])
    merged_cache: dict = {}

    def model_for(adapter):
        if adapter is None:
            return params
        if adapter not in merged_cache:
            merged_cache[adapter] = merge_lora(
                params, adapters[adapter], dtype=jnp.float32
            )
        return merged_cache[adapter]

    expected = {}  # rid -> (prompt, max_new, adapter, eos)
    n_requests = int(rng.integers(3, 7))
    for _ in range(n_requests):
        plen = int(rng.integers(1, 25))
        prompt = [int(t) for t in rng.integers(0, CONFIG.vocab_size, plen)]
        new = int(rng.integers(1, min(24, CONFIG.max_seq_len - plen) + 1))
        adapter = names[int(rng.integers(len(names)))]
        if rng.integers(4) == 0 and new >= 2:  # occasional fan-out pair
            rids = engine.submit_fanout(
                prompt, new, n_samples=2, adapter=adapter
            )
            for rid in rids:
                expected[rid] = (prompt, new, adapter, None)
        else:
            # Occasional eos mid-stream: pick the token the reference
            # model will emit at a known step, so retirement truly
            # triggers early.  Greedy arms only — a sampled stream has
            # no predictable token to make an eos of.
            eos = None
            if not sampling and rng.integers(4) == 0 and new >= 4:
                ref = generate(
                    model_for(adapter), jnp.asarray([prompt], jnp.int32),
                    CONFIG, max_new_tokens=new,
                )
                eos = int(np.asarray(ref[0, new // 2]))
            rid = engine.submit(prompt, new, eos_token=eos, adapter=adapter)
            expected[rid] = (prompt, new, adapter, eos)

    served = engine.run()
    assert set(served) == set(expected)
    if sampling:
        # No pathwise oracle under sampling: every request must get
        # exactly its token budget, in-vocab, and the pools must drain.
        for rid, (prompt, new, adapter, eos) in expected.items():
            got = list(served[rid])
            assert len(got) == new, (seed, rid, kw)
            assert all(0 <= t < CONFIG.vocab_size for t in got), (seed, rid)
        pinned = (
            engine.prefix.cached_pages if engine.prefix is not None else 0
        )
        assert engine.ctrl.used_pages == pinned, (seed, kw)
        return
    for rid, (prompt, new, adapter, eos) in expected.items():
        ref = [int(t) for t in np.asarray(generate(
            model_for(adapter), jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )[0])]
        if eos is not None and eos in ref:
            ref = ref[: ref.index(eos) + 1]
        got = list(served[rid])
        if eos is None:
            assert got == ref, (seed, rid, kw, adapter)
        else:
            # Retirement is detected at chunk/round boundaries, so a few
            # tokens past the eos may be emitted; the prefix up to and
            # including the eos must match exactly.
            assert got[: len(ref)] == ref, (seed, rid, kw, adapter, "eos")
            assert eos in got, (seed, rid, kw, adapter, "eos missing")
    # Hygiene: everything drained; only prefix-cache pins may remain.
    pinned = engine.prefix.cached_pages if engine.prefix is not None else 0
    assert engine.ctrl.used_pages == pinned, (seed, kw)
    _assert_kv_reclaimed(engine, seed, kw)


def _assert_kv_reclaimed(engine, seed, kw) -> None:
    """close() must reclaim EVERY page the KV hierarchy holds: resident
    cache pins release to the pool and offloaded host pages drop with
    the index that owns them — the no-leak contract for the offload
    tier (cancel/deadline/quarantine paths exercise the same clear()
    seam mid-run)."""
    engine.close()
    assert engine.ctrl.used_pages == 0, (seed, kw)
    if engine.prefix is not None:
        assert engine.prefix.cached_pages == 0, (seed, kw)
        assert getattr(engine.prefix, "offloaded_pages", 0) == 0, (seed, kw)


def test_engine_feature_matrix_fuzz():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    adapters = synthetic_adapters(CONFIG, 2, rank=4, scale=0.3, seed=3)
    for seed in range(8):
        _run_one(seed, params, draft, adapters)


# ---- fault-tolerance chaos arm ------------------------------------------
#
# Randomized cancels, deadlines and injected seam faults interleaved with
# normal traffic (spec="auto" included), asserting the lifecycle
# invariants: every accepted rid reaches EXACTLY one terminal status, no
# page/slot/commitment leak survives the stream, and every emitted token
# is a true prefix of the dense reference's greedy stream — replays after
# a quarantine are bit-identical, so even a request that faulted twice
# must finish with the uninterrupted stream.  Greedy-only: sampled
# replays are distributionally (not bitwise) equivalent, so they have no
# pathwise oracle.  Deterministic seeds — failures reproduce.

TERMINAL = {"ok", "cancelled", "expired", "failed"}


def _run_chaos(seed: int, params, draft, adapters) -> None:
    from workloads.errors import QueueFull
    from workloads.faults import FaultInjector

    rng = np.random.default_rng(seed + 4096)
    spec = bool(rng.integers(2))
    use_adapters = bool(rng.integers(2))
    kw = dict(
        slots=int(rng.integers(1, 4)),
        page_size=int(rng.choice([4, 8])),
        prefix_cache=bool(rng.integers(2)),
        pipelined=bool(rng.integers(2)),
    )
    kw["prompt_bucket"] = int(kw["page_size"] * rng.choice([2, 3]))
    # KV-cache hierarchy under chaos: offloaded pages must survive (or
    # be flushed by) quarantines, and replays through reloaded pages
    # must stay bit-identical — randomized here, reclaim asserted at
    # the bottom.
    if kw["prefix_cache"] and rng.integers(2):
        kw["kv_offload"] = True
        if rng.integers(2):
            kw["kv_host_pages"] = int(rng.integers(1, 9))
    # Decode supersteps under chaos: a fault mid-superstep drops the
    # whole in-flight superstep and replays bit-identically; cancels /
    # deadlines / health pauses must reclaim it without leaks.
    kw["superstep_k"] = int(rng.choice([1, 1, 2, 4]))
    # Budgeted chunked-prefill under chaos: mid-prefill cancels,
    # deadline expiries and seam faults must reclaim parked admissions
    # (the leak assertions below) and replays must stay bit-identical.
    if rng.integers(2):
        kw["prefill_budget"] = int(
            rng.choice([1, kw["prompt_bucket"], 2 * kw["prompt_bucket"]])
        )
    if spec:
        kw.update(draft_params=draft, draft_config=DRAFT_CONFIG,
                  gamma=int(rng.integers(2, 5)))
        if rng.integers(2):
            # Chained spec supersteps under chaos: a fault mid-scan
            # drops the whole in-flight superstep and replays
            # bit-identically; reclaim asserted at the bottom.
            kw["spec_superstep_k"] = int(rng.choice([1, 2, 4]))
        else:
            kw["spec_lookahead"] = int(rng.choice([1, 2]))
        if rng.integers(2):
            kw.update(spec="auto", spec_breakeven=float(
                rng.choice([0.0, 1.0, kw["slots"]])
            ))
    else:
        kw["chunk"] = int(kw["page_size"] * rng.choice([1, 2]))
    injector = FaultInjector.random(
        seed=seed, rate=0.04, max_fires=int(rng.integers(1, 5))
    )
    # Chip-time ledger under chaos (workloads/ledger.py): randomized on
    # so quarantines/replays/cancels hit the waste taxonomy; inertness
    # is implied by the oracle pins below and the books must still
    # balance at the bottom.
    if rng.integers(2):
        from workloads.ledger import ChipTimeLedger

        kw["ledger"] = ChipTimeLedger()
    engine = ServeEngine(
        params, CONFIG, adapters=adapters if use_adapters else None,
        fault_injector=injector, max_retries=2,
        max_pending=int(rng.choice([3, 16])), **kw,
    )
    # Goodput controller under chaos (workloads/control.py):
    # randomized ON whenever the ledger is armed, with instant
    # cooldowns so its online retunes (breakeven shifts, superstep
    # steps) actually land between chaotic steps — every oracle pin
    # below must STILL hold bit-identically (each retune drains
    # in-flight state through the mode-boundary rules first).
    controller = None
    if kw.get("ledger") is not None and rng.integers(2):
        from workloads.backoff import Backoff
        from workloads.control import GoodputController

        instant = Backoff(base_s=1e-6, max_s=1e-6, jitter=0.0)
        controller = GoodputController(
            engine, min_sample_tokens=16,
            retune_backoff=instant, wfq_backoff=instant,
        )
    names = [None] + (sorted(adapters) if use_adapters else [])
    expected = {}  # rid -> (prompt, max_new, adapter)
    for i in range(int(rng.integers(4, 8))):
        plen = int(rng.integers(1, 25))
        prompt = [int(t) for t in rng.integers(0, CONFIG.vocab_size, plen)]
        new = int(rng.integers(2, min(24, CONFIG.max_seq_len - plen) + 1))
        adapter = names[int(rng.integers(len(names)))]
        deadline = 0.02 if rng.integers(5) == 0 else None
        try:
            if rng.integers(4) == 0:
                rids = engine.submit_fanout(
                    prompt, new, n_samples=2, adapter=adapter,
                    deadline_s=deadline,
                )
            else:
                rids = [engine.submit(
                    prompt, new, adapter=adapter, deadline_s=deadline,
                )]
        except QueueFull:
            continue  # bounded admission did its job; nothing entered
        for rid in rids:
            expected[rid] = (prompt, new, adapter)
    terminal: dict[str, str] = {}
    steps = 0
    while not engine.idle:
        steps += 1
        assert steps < 800, (seed, kw, "engine failed to converge")
        live = [r for r in expected if r not in terminal]
        if live and rng.integers(8) == 0:
            engine.cancel(str(rng.choice(live)))
        for req in (
            controller.step() if controller is not None
            else engine.step()
        ):
            assert req.rid not in terminal, (seed, req.rid, "double terminal")
            assert req.status in TERMINAL, (seed, req.rid, req.status)
            terminal[req.rid] = req.status
    assert set(terminal) == set(expected), (
        seed, kw, set(expected) - set(terminal), set(terminal) - set(expected),
    )
    merged_cache: dict = {}

    def model_for(adapter):
        if adapter is None:
            return params
        if adapter not in merged_cache:
            merged_cache[adapter] = merge_lora(
                params, adapters[adapter], dtype=jnp.float32
            )
        return merged_cache[adapter]

    by_rid = {r.rid: r for r in engine.completed}
    for rid, (prompt, new, adapter) in expected.items():
        req = by_rid[rid]
        ref = [int(t) for t in np.asarray(generate(
            model_for(adapter), jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )[0])]
        got = list(req.tokens)
        if terminal[rid] == "ok":
            # Bit-identical INCLUDING any quarantine replays mid-stream.
            assert got == ref, (seed, rid, kw, req.retries)
        else:
            # Interrupted terminally: whatever was emitted must still be
            # a true prefix of the uninterrupted stream.
            assert got == ref[: len(got)], (seed, rid, terminal[rid], kw)
    # Hygiene: no slot, page, or commitment leak; fan-out bookkeeping
    # fully unwound; only prefix-cache pins may remain.
    assert not engine._occupied.any(), (seed, kw)
    assert engine._committed_pages == 0, (seed, kw)
    assert not engine._groups, (seed, kw)
    pinned = engine.prefix.cached_pages if engine.prefix is not None else 0
    assert engine.ctrl.used_pages == pinned, (seed, kw)
    _assert_kv_reclaimed(engine, seed, kw)
    if engine.ledger is not None:
        # Every rid reached exactly one terminal status above, so the
        # ledger must be fully classified: goodput + waste == every
        # token's worth of device work charged, nothing pending.
        verdict = engine.ledger.reconcile(expect_quiescent=True)
        assert verdict["ok"], (seed, kw, verdict)
        ok_tokens = sum(
            len(r.tokens) for r in engine.completed if r.status == "ok"
        )
        assert engine.ledger.goodput_tokens == ok_tokens, (seed, kw)
    if controller is not None:
        # The control loop ran every step; whatever it retuned, the
        # oracle pins above already proved the streams unmoved.
        assert controller.polls == steps, (seed, kw)
        # Only the controller retunes in this arm: the counters agree.
        assert controller.retunes_applied == engine.retunes, (seed, kw)


def test_engine_fault_chaos_smoke():
    """ONE cheap seeded chaos round — the `make faults-check` smoke
    (plain decode, no draft model, so the compile bill stays small)."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    adapters = synthetic_adapters(CONFIG, 2, rank=4, scale=0.3, seed=3)
    _run_chaos(2, params, None, adapters)


def test_engine_fault_chaos_fuzz():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    adapters = synthetic_adapters(CONFIG, 2, rank=4, scale=0.3, seed=3)
    for seed in range(6):
        _run_chaos(seed, params, draft, adapters)


def test_injector_off_streams_bit_identical():
    """The fault-tolerance machinery at rest is INERT: an armed-but-
    never-firing injector plus live lifecycle knobs produce streams
    bit-identical to an engine with none of it — sampling on, so the
    whole RNG key schedule is pinned too (this is the pre-PR stream:
    the feature-matrix fuzz above pins that same path against the dense
    reference)."""
    from workloads.faults import FaultInjector

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    prompt = [int(t) for t in range(1, 12)]

    def run(**extra):
        engine = ServeEngine(
            params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
            temperature=0.8, top_k=40, rng=jax.random.PRNGKey(5),
            pipelined=True, **extra,
        )
        for i in range(4):
            engine.submit(prompt[: 3 + i], 8 + i)
        return engine.run()

    plain = run()
    guarded = run(
        fault_injector=FaultInjector(), max_pending=64, max_retries=5,
        retry_backoff_s=0.5,
    )
    assert plain == guarded


# ---- fleet chaos arm -----------------------------------------------------
#
# Randomized replica crashes/hangs/slow-steps, health drains, live
# drains/adds/removes and cancels/deadlines interleaved with open-loop
# traffic across N=2..4 replicas (per-engine seam faults riding along),
# asserting the fleet-scope lifecycle invariants: every accepted rid
# reaches EXACTLY one terminal status fleet-wide, completed greedy
# streams are bit-identical to the single-engine dense oracle THROUGH
# cross-replica failover replays, interrupted streams are true
# prefixes, and no surviving replica leaks a slot/page/commitment.
# Deterministic seeds — failures reproduce.


def _run_fleet_chaos(seed: int, params, adapters) -> None:
    import shutil
    import tempfile

    root = tempfile.mkdtemp(prefix="fuzz-durable-")
    try:
        _run_fleet_chaos_impl(seed, params, adapters, root)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _run_fleet_chaos_impl(seed: int, params, adapters, root: str) -> None:
    import os

    from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
    from tpu_device_plugin.device import HealthEvent
    from workloads.errors import QueueFull
    from workloads.faults import REPLICA_SEAMS, FaultInjector
    from workloads.fleet import DEAD, Fleet

    rng = np.random.default_rng(seed + 77000)
    n = int(rng.integers(2, 5))
    use_adapters = bool(rng.integers(2))
    # Durable sessions under chaos (workloads/durable.py): on half the
    # seeds the fleet journals sessions (replicas with kv_offload also
    # share one --kv-disk-dir, durable seams riding the per-engine
    # injectors) and a SCHEDULED process restart lands mid-loop — the
    # fleet is torn down, a FRESH one rebuilt from nothing but the
    # journal + disk pages, and the same seeded stream continues.  All
    # the oracle pins below then hold ACROSS process death.
    durable = bool(rng.integers(2))
    journal_dir = os.path.join(root, "journal") if durable else None
    restart_at = int(rng.integers(3, 10)) if durable else None
    fleet_inj = FaultInjector.random(
        seed=seed, rate=0.03, seams=REPLICA_SEAMS,
        max_fires=int(rng.integers(1, n)),  # >= 1 replica always survives
    )
    engines = []
    engine_kws = []  # the restart rebuilds the same replica shapes
    for i in range(n):
        kw = dict(
            slots=int(rng.integers(1, 3)),
            page_size=int(rng.choice([4, 8])),
            prefix_cache=bool(rng.integers(2)),
            pipelined=bool(rng.integers(2)),
        )
        # The KV handoff's transfer fabric (disaggregated fleets
        # export/graft through the host tier when armed; degrade to
        # replay re-prefill when not — both must stay oracle-true).
        if kw["prefix_cache"] and rng.integers(2):
            kw["kv_offload"] = True
            if durable:
                # One shared directory — chain-key filenames make the
                # sharing the dedup, including across the restart.
                kw["kv_disk_dir"] = os.path.join(root, "kv")
        kw["prompt_bucket"] = int(kw["page_size"] * rng.choice([2, 3]))
        if rng.integers(2):
            kw["prefill_budget"] = int(
                rng.choice([1, kw["prompt_bucket"]])
            )
        engine_kws.append(kw)
        engines.append(ServeEngine(
            params, CONFIG,
            adapters=adapters if use_adapters else None,
            fault_injector=(
                # Default seams, so kv_disk_write_fail /
                # kv_disk_read_corrupt degrade paths fire under chaos.
                FaultInjector.random(
                    seed=seed * 13 + i, rate=0.02, max_fires=2
                ) if rng.integers(2) else None
            ),
            max_retries=2, **kw,
        ))
    # Fast-start snapshots under chaos (workloads/faststart.py): on
    # half the seeds every engine is primed with a snapshot captured
    # from replica 0 — heterogeneous per-replica configs mean some
    # primes legitimately REJECT (fingerprint mismatch → cold path);
    # either way the oracle pins below assert streams are unchanged.
    if rng.integers(2):
        from workloads.faststart import EngineSnapshot

        snap = EngineSnapshot.capture(engines[0])
        for eng in engines:
            snap.prime(eng)
    # Fleet-scope chip-time ledger under chaos (workloads/ledger.py):
    # per-replica ledgers + the fleet roll-up, randomized on — the
    # failover/cancel/handoff taxonomy must still balance fleet-wide
    # at the bottom (and the oracle pins below imply inertness).  Not
    # under the scheduled restart: the ledger is per-process state, so
    # a mid-run teardown legitimately splits its books.
    fleet_ledger = None
    if rng.integers(2) and not durable:
        from workloads.ledger import ChipTimeLedger, FleetLedger

        fleet_ledger = FleetLedger()
        for i, eng in enumerate(engines):
            eng.ledger = ChipTimeLedger(name=str(i))
    # Disaggregated prefill/decode pools on half the seeds: random
    # per-replica roles (any combination is legal — a missing pool
    # degrades to mixed dispatch), so crashes/hangs/health drains land
    # on exporters mid-handoff, on decode pools holding tickets, and
    # on degenerate all-prefill fleets alike.
    roles = None
    if rng.integers(2):
        roles = [
            str(rng.choice(["prefill", "decode", "mixed"]))
            for _ in range(n)
        ]
    max_pending = int(rng.choice([4, 32]))
    page_sched = bool(rng.integers(2))
    fleet = Fleet(
        engines, chip_ids=[f"chip-{i}" for i in range(n)],
        fault_injector=fleet_inj, max_failovers=2, slow_readback_s=0.0,
        # Injected replica_hang gives deterministic hang coverage; the
        # wall-clock watchdog would turn host-load-dependent XLA compile
        # times into nondeterministic replica kills.
        hang_timeout_s=None,
        max_pending=max_pending,
        roles=roles,
        ledger=fleet_ledger,
        # Page-granular dispatch on half the seeds: placement may move,
        # tokens must not (the kvsched degrade contract under chaos).
        page_scheduling=page_sched,
        journal_dir=journal_dir,
        journal_every=int(rng.choice([2, 5])) if durable else None,
    )
    # Goodput controller riding the fleet chaos (workloads/control.py):
    # randomized ON whenever the fleet ledger is armed (so never across
    # the scheduled restart — the controller, like the ledger, is
    # per-process state).  These draftless superstep-1 replicas give it
    # nothing to retune, which is itself the pin: the control loop
    # polls through failovers, health drains and live resizes without
    # actuating, and every oracle below holds bit-identically —
    # attach-but-inert is free under chaos.
    controller = None
    if fleet_ledger is not None and rng.integers(2):
        from workloads.backoff import Backoff
        from workloads.control import GoodputController

        instant = Backoff(base_s=1e-6, max_s=1e-6, jitter=0.0)
        controller = GoodputController(
            fleet, min_sample_tokens=16,
            retune_backoff=instant, wfq_backoff=instant,
        )
    names = [None] + (sorted(adapters) if use_adapters else [])
    expected = {}
    terminal_frs: dict = {}  # rid -> FleetRequest (survives the restart)
    pending_submits = []
    for _ in range(int(rng.integers(5, 10))):
        plen = int(rng.integers(1, 25))
        prompt = [int(t) for t in rng.integers(0, CONFIG.vocab_size, plen)]
        new = int(rng.integers(2, min(24, CONFIG.max_seq_len - plen) + 1))
        adapter = names[int(rng.integers(len(names)))]
        deadline = 0.05 if rng.integers(6) == 0 else None
        pending_submits.append((prompt, new, adapter, deadline))
    merged_cache: dict = {}

    def model_for(adapter):
        if adapter is None:
            return params
        if adapter not in merged_cache:
            merged_cache[adapter] = merge_lora(
                params, adapters[adapter], dtype=jnp.float32
            )
        return merged_cache[adapter]

    terminal: dict[str, str] = {}
    steps = 0
    added = False
    while pending_submits or not fleet.idle:
        steps += 1
        assert steps < 900, (seed, fleet.states(), "failed to converge")
        # Open-loop-ish trickle: a couple of submissions per step.
        for _ in range(min(len(pending_submits), int(rng.integers(1, 3)))):
            prompt, new, adapter, deadline = pending_submits.pop()
            sess = f"s{int(rng.integers(3))}" if rng.integers(2) else None
            try:
                rid = fleet.submit(
                    prompt, new, adapter=adapter, deadline_s=deadline,
                    session=sess,
                )
            except QueueFull:
                continue
            expected[rid] = (prompt, new, adapter)
        live = [r for r in expected if r not in terminal]
        if live and rng.integers(10) == 0:
            fleet.cancel(str(rng.choice(live)))
        if rng.integers(15) == 0:
            alive = fleet.alive
            if len(alive) > 1:
                fleet.deliver_health([HealthEvent(
                    chip_id=alive[int(rng.integers(len(alive)))].chip_id,
                    health=UNHEALTHY,
                )])
        if rng.integers(15) == 0:
            fleet.deliver_health([
                HealthEvent(chip_id="", health=HEALTHY)
            ])
        if rng.integers(20) == 0:
            drainable = [
                r.index for r in fleet.replicas if r.state == "active"
            ]
            if len(drainable) > 1:
                fleet.drain(int(rng.choice(drainable)))
        if not added and rng.integers(25) == 0:
            fleet.add_replica(ServeEngine(
                params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
                adapters=adapters if use_adapters else None,
            ), chip_id=f"chip-{n}")
            added = True
        for fr in (
            controller.step() if controller is not None
            else fleet.step()
        ):
            assert fr.rid not in terminal, (seed, fr.rid, "double terminal")
            assert fr.status in TERMINAL, (seed, fr.rid, fr.status)
            terminal[fr.rid] = fr.status
            terminal_frs[fr.rid] = fr
        if restart_at is not None and steps >= restart_at:
            # The scheduled process death: close() journals live
            # sessions, then a FRESH fleet (same replica shapes, empty
            # pools, empty radix) is rebuilt from what survived on
            # disk and the SAME seeded stream continues.  Terminal
            # non-ok rids are deliberately absent from the journal
            # (nothing to resume) — `terminal_frs` keeps their streams
            # for the oracle pins below; every still-live rid must
            # terminalize exactly once in the new process, or the
            # one-terminal-per-rid / set-equality asserts fail.
            restart_at = None
            fleet.close()
            engines = [
                ServeEngine(
                    params, CONFIG,
                    adapters=adapters if use_adapters else None,
                    max_retries=2, **kw,
                )
                for kw in engine_kws
            ]
            fleet = Fleet(
                engines, chip_ids=[f"chip-{i}" for i in range(n)],
                max_failovers=2, slow_readback_s=0.0,
                hang_timeout_s=None, max_pending=max_pending,
                roles=roles, page_scheduling=page_sched,
                journal_dir=journal_dir,
            )
            fleet.restore()
            added = True  # chip-n may exist; don't re-add post-restart
    assert set(terminal) == set(expected), (
        seed, set(expected) ^ set(terminal),
    )
    for rid, (prompt, new, adapter) in expected.items():
        fr = fleet._reqs.get(rid) or terminal_frs[rid]
        ref = [int(t) for t in np.asarray(generate(
            model_for(adapter), jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )[0])]
        if terminal[rid] == "ok":
            # Bit-identical through cross-replica failover replays.
            assert fr.tokens == ref, (seed, rid, fr.failovers, fr.segments)
        else:
            assert fr.tokens == ref[: len(fr.tokens)], (
                seed, rid, terminal[rid],
            )
    for rep in fleet.replicas:
        if rep.state == DEAD:
            continue
        e = rep.engine
        assert not e._occupied.any(), (seed, rep.index)
        assert e._committed_pages == 0, (seed, rep.index)
        assert not e._groups, (seed, rep.index)
        pinned = e.prefix.cached_pages if e.prefix is not None else 0
        assert e.ctrl.used_pages == pinned, (seed, rep.index)
        assert not rep.rids, (seed, rep.index)
    if fleet_ledger is not None:
        # Every rid terminal fleet-wide, so the roll-up must be fully
        # classified: goodput + waste == all charged device work, with
        # goodput cross-checked against the ok streams.
        verdict = fleet_ledger.reconcile(expect_quiescent=True)
        assert verdict["ok"], (seed, verdict)
        ok_tokens = sum(
            len(r.tokens) for r in fleet.completed if r.status == "ok"
        )
        assert fleet_ledger.goodput_tokens == ok_tokens, (seed, verdict)
    if controller is not None:
        assert controller.polls == steps, (seed, controller.states())
        # Nothing here is retunable (no drafts, superstep ceilings at
        # 1): the control loop must have observed without actuating.
        assert controller.retunes_applied == 0, (seed, controller.states())
    fleet.close()


def test_fleet_chaos_fuzz():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    adapters = synthetic_adapters(CONFIG, 2, rank=4, scale=0.3, seed=3)
    for seed in range(4):
        _run_fleet_chaos(seed, params, adapters)


# ---- supervised (self-healing) fleet chaos arm ---------------------------
#
# The fleet chaos arm with the FleetSupervisor armed: randomized replica
# crashes/hangs (and, on some seeds, scripted repeat-crash-on-restart
# respawn schedules) interleaved with cancels/deadlines/health events.
# The added contracts: the fleet CONVERGES BACK to full capacity without
# operator help (every non-quarantined slot serving; a scripted crash
# loop must instead quarantine its slot), resurrected replicas pass the
# bit-identical half-open probe before rejoining, and all the fleet
# invariants still hold — exactly one terminal status per rid, ok
# streams bit-identical to the dense oracle, interrupted streams true
# prefixes, no slot/page/commitment leak on any live replica.


def _run_supervised_chaos(seed: int, params, adapters) -> None:
    import shutil
    import tempfile

    root = tempfile.mkdtemp(prefix="fuzz-durable-sup-")
    try:
        _run_supervised_chaos_impl(seed, params, adapters, root)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _run_supervised_chaos_impl(seed: int, params, adapters, root) -> None:
    import os

    from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
    from tpu_device_plugin.device import HealthEvent
    from workloads.backoff import Backoff
    from workloads.errors import QueueFull
    from workloads.faults import FaultInjector, crash_loop_schedule
    from workloads.fleet import DEAD, Fleet
    from workloads.supervisor import (
        QUARANTINED,
        FleetSupervisor,
        make_engine_factory,
    )

    rng = np.random.default_rng(seed + 91000)
    n = int(rng.integers(2, 5))
    use_adapters = bool(rng.integers(2))
    engine_kw = dict(
        slots=int(rng.integers(1, 3)),
        page_size=int(rng.choice([4, 8])),
        prefix_cache=bool(rng.integers(2)),
        pipelined=bool(rng.integers(2)),
        adapters=adapters if use_adapters else None,
    )
    engine_kw["prompt_bucket"] = int(
        engine_kw["page_size"] * rng.choice([2, 3])
    )
    # Durable sessions under supervision: on half the seeds the fleet
    # journals (the supervisor checkpoints on deaths + wall cadence),
    # kv_offload replicas share one disk dir via the engine factory,
    # and a SCHEDULED full-process restart (fresh fleet + fresh
    # supervisor from the journal) lands mid-loop — convergence and
    # every oracle pin below must hold across it.
    durable = bool(rng.integers(2))
    journal_dir = os.path.join(root, "journal") if durable else None
    restart_at = int(rng.integers(3, 12)) if durable else None
    if durable and engine_kw["prefix_cache"]:
        engine_kw["kv_offload"] = True
        engine_kw["kv_disk_dir"] = os.path.join(root, "kv")
    fleet_inj = FaultInjector.random(
        seed=seed, rate=0.03,
        seams=("replica_crash", "replica_hang"),
        # The injector can kill at most n-1 replicas in total, but the
        # supervisor keeps resurrecting — live capacity recovers anyway.
        max_fires=int(rng.integers(1, n)),
    )
    engines = [
        ServeEngine(params, CONFIG, **engine_kw) for _ in range(n)
    ]
    mppr = int(rng.choice([3, 16]))
    page_sched = bool(rng.integers(2))
    fleet = Fleet(
        engines, chip_ids=[f"chip-{i}" for i in range(n)],
        fault_injector=fleet_inj, max_failovers=2,
        hang_timeout_s=None,
        max_pending_per_replica=mppr,
        # Page-granular dispatch on half the seeds: supervised
        # resurrection must stay stream-invariant either way.
        page_scheduling=page_sched,
        journal_dir=journal_dir,
    )
    # Fast-start snapshot on half the seeds: the factory primes every
    # resurrection with warmed state captured from replica 0 (same
    # engine_kw, so the fingerprint matches) and the supervisor carries
    # it — respawn streams must stay bit-identical snapshot on/off.
    snapshot = None
    if rng.integers(2):
        from workloads.faststart import EngineSnapshot

        snapshot = EngineSnapshot.capture(
            engines[0], probe=([1, 2, 3], 4),
        )
    factory, oracle = make_engine_factory(
        params, CONFIG, engine_kw=engine_kw, probe=([1, 2, 3], 4),
        snapshot=snapshot,
    )
    crash_loop = bool(rng.integers(2))

    def mk_sup(target, injector):
        return FleetSupervisor(
            target, factory,
            backoff=Backoff(base_s=1e-3, max_s=8e-3, jitter=0.0),
            probe=([1, 2, 3], 4), probe_oracle=oracle,
            snapshot=snapshot,
            crash_loop_k=3, crash_loop_window_s=60.0,
            fault_injector=injector,
            journal_every_s=1e-3 if durable else None,
        )

    sup = mk_sup(
        fleet,
        FaultInjector(crash_loop_schedule(2)) if crash_loop else None,
    )
    names = [None] + (sorted(adapters) if use_adapters else [])
    merged_cache: dict = {}

    def model_for(adapter):
        if adapter is None:
            return params
        if adapter not in merged_cache:
            merged_cache[adapter] = merge_lora(
                params, adapters[adapter], dtype=jnp.float32
            )
        return merged_cache[adapter]

    pending_submits = []
    for _ in range(int(rng.integers(5, 10))):
        plen = int(rng.integers(1, 25))
        prompt = [int(t) for t in rng.integers(0, CONFIG.vocab_size, plen)]
        new = int(rng.integers(2, min(24, CONFIG.max_seq_len - plen) + 1))
        adapter = names[int(rng.integers(len(names)))]
        deadline = 0.05 if rng.integers(6) == 0 else None
        pending_submits.append((prompt, new, adapter, deadline))
    expected = {}
    terminal: dict[str, str] = {}
    terminal_frs: dict = {}  # rid -> FleetRequest (survives the restart)
    steps = 0
    while pending_submits or not fleet.idle:
        steps += 1
        assert steps < 1500, (seed, fleet.states(), "failed to converge")
        for _ in range(min(len(pending_submits), int(rng.integers(1, 3)))):
            prompt, new, adapter, deadline = pending_submits.pop()
            sess = f"s{int(rng.integers(3))}" if rng.integers(2) else None
            try:
                rid = fleet.submit(
                    prompt, new, adapter=adapter, deadline_s=deadline,
                    session=sess,
                )
            except QueueFull:
                continue  # capacity-aware shedding did its job
            expected[rid] = (prompt, new, adapter)
        live = [r for r in expected if r not in terminal]
        if live and rng.integers(10) == 0:
            fleet.cancel(str(rng.choice(live)))
        if rng.integers(15) == 0:
            alive = fleet.alive
            if len(alive) > 1:
                ev = HealthEvent(
                    chip_id=alive[int(rng.integers(len(alive)))].chip_id,
                    health=UNHEALTHY,
                )
                fleet.deliver_health([ev])
                sup.note_health([ev])  # the supervisor honors the mark
        if rng.integers(15) == 0:
            ev = HealthEvent(chip_id="", health=HEALTHY)
            fleet.deliver_health([ev])
            sup.note_health([ev])
        for fr in sup.step():
            assert fr.rid not in terminal, (seed, fr.rid, "double terminal")
            assert fr.status in TERMINAL, (seed, fr.rid, fr.status)
            terminal[fr.rid] = fr.status
            terminal_frs[fr.rid] = fr
        if restart_at is not None and steps >= restart_at:
            # The scheduled full-process death: close() journals live
            # sessions, then a FRESH fleet AND supervisor rebuild from
            # the journal + disk pages and the same stream continues
            # (the dead process's quarantines/backoffs are gone with
            # it — slot history is process state, sessions are not).
            restart_at = None
            fleet.close()
            engines = [
                ServeEngine(params, CONFIG, **engine_kw)
                for _ in range(n)
            ]
            fleet = Fleet(
                engines, chip_ids=[f"chip-{i}" for i in range(n)],
                max_failovers=2, hang_timeout_s=None,
                max_pending_per_replica=mppr,
                page_scheduling=page_sched,
                journal_dir=journal_dir,
            )
            fleet.restore()
            sup = mk_sup(fleet, None)
    # Lift any lingering health marks so deferred resurrections can
    # proceed, then the fleet must converge BACK to full capacity.
    ev = HealthEvent(chip_id="", health=HEALTHY)
    fleet.deliver_health([ev])
    sup.note_health([ev])
    fleet.step()
    assert sup.wait_healed(30.0), (seed, sup.states(), fleet.states())
    serving = sum(1 for s in sup.slots if s.state == "serving")
    active = sum(1 for r in fleet.replicas if r.state == "active")
    assert active >= serving, (seed, sup.states(), fleet.states())
    if crash_loop and sup.crash_loops:
        # A scripted crash loop that actually tripped must have
        # quarantined its slot — quarantine IS the converged state.
        assert sup.quarantined, (seed, sup.states())
        for chip in sup.quarantined:
            slot = sup.slot_for(chip)
            assert slot.state == QUARANTINED and slot.index is None
    assert set(terminal) == set(expected), (
        seed, set(expected) ^ set(terminal),
    )
    for rid, (prompt, new, adapter) in expected.items():
        fr = fleet._reqs.get(rid) or terminal_frs[rid]
        ref = [int(t) for t in np.asarray(generate(
            model_for(adapter), jnp.asarray([prompt], jnp.int32), CONFIG,
            max_new_tokens=new,
        )[0])]
        if terminal[rid] == "ok":
            # Bit-identical through failovers AND resurrections.
            assert fr.tokens == ref, (seed, rid, fr.failovers, fr.segments)
        else:
            assert fr.tokens == ref[: len(fr.tokens)], (
                seed, rid, terminal[rid],
            )
    for rep in fleet.replicas:
        if rep.state == DEAD:
            continue
        e = rep.engine
        assert not e._occupied.any(), (seed, rep.index)
        assert e._committed_pages == 0, (seed, rep.index)
        assert not e._groups, (seed, rep.index)
        pinned = e.prefix.cached_pages if e.prefix is not None else 0
        assert e.ctrl.used_pages == pinned, (seed, rep.index)
        assert not rep.rids, (seed, rep.index)
    fleet.close()


def test_supervised_fleet_chaos_fuzz():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    adapters = synthetic_adapters(CONFIG, 2, rank=4, scale=0.3, seed=3)
    for seed in range(3):
        _run_supervised_chaos(seed, params, adapters)

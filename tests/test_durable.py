"""Durable sessions (docs/SERVING.md "Durable sessions"): the KV disk
tier below host RAM, the crash-surviving session journal, and
``Fleet.restore`` — exact continuation across full-process restarts.

The contracts split in four bands:
  * durable primitives (atomic writes never expose a torn file, disk
    pages are checksummed + named by chain key so dedup is structural,
    bfloat16 payloads round-trip bit-exactly — the npz void-degrade
    regression, corrupt reads quarantine to a miss, the mtime-LRU
    budget, journal rotation/epochs/torn-write fallback);
  * fault seams (``kv_disk_write_fail`` / ``kv_disk_read_corrupt`` /
    ``journal_torn_write`` drive exactly the production degrade paths);
  * restart bit-identity (a journaled fleet killed mid-stream is
    rebuilt in a FRESH fleet from nothing but the journal + per-page
    disk files, and every continuation matches the uninterrupted
    oracle — the restart moves time, never a token);
  * degrade hardening rides along (EngineSnapshot.load and
    DeviceTimeTable.refresh_from_artifact treat corrupt artifacts as
    cold starts, never crashes).
"""

import json
import os

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from workloads.durable import (
    KVDiskTier,
    SessionJournal,
    _pack_blob,
    _unpack_blob,
    atomic_write_bytes,
    atomic_write_json,
)
from workloads.faults import FaultInjector
from workloads.fleet import Fleet
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)


def _blob(seed=0, dtype=np.float32, draft=False):
    rng = np.random.default_rng(seed)
    def arr():
        return rng.standard_normal((2, 4, 8)).astype(dtype)
    return ((arr(), arr()), (arr(), arr()) if draft else None)


def _blobs_equal(a, b):
    (amk, amv), ad = a
    (bmk, bmv), bd = b
    if (ad is None) != (bd is None):
        return False
    pairs = [(amk, bmk), (amv, bmv)]
    if ad is not None:
        pairs += list(zip(ad, bd))
    return all(
        x.dtype == y.dtype and x.shape == y.shape and np.array_equal(x, y)
        for x, y in pairs
    )


# ---- atomic writes -------------------------------------------------------


def test_atomic_write_replaces_whole_file_and_cleans_tmp(tmp_path):
    """Successive writes leave exactly the LAST payload and no temp
    droppings — the invariant every durable artifact in the tree leans
    on (snapshots, journals, disk pages, postmortem bundles)."""
    path = str(tmp_path / "artifact.bin")
    atomic_write_bytes(path, b"first generation")
    atomic_write_bytes(path, b"second")
    with open(path, "rb") as f:
        assert f.read() == b"second"
    assert os.listdir(tmp_path) == ["artifact.bin"]


def test_atomic_write_json_round_trips(tmp_path):
    path = str(tmp_path / "doc.json")
    doc = {"b": [1, 2, 3], "a": {"nested": True}}
    atomic_write_json(path, doc)
    with open(path, encoding="utf-8") as f:
        assert json.load(f) == doc


# ---- disk pages ----------------------------------------------------------


def test_disk_page_roundtrip_preserves_bfloat16():
    """The regression pin: a plain np.savez/np.load round trip degrades
    ml_dtypes arrays to raw void (``|V2``) — which jnp.asarray then
    rejects at reload, killing every restored stream.  The raw-bytes +
    dtype-sidecar format must hand back the exact dtype and bytes."""
    blob = _blob(seed=3, dtype=ml_dtypes.bfloat16, draft=True)
    out = _unpack_blob(_pack_blob(blob))
    assert out[0][0].dtype == np.dtype(ml_dtypes.bfloat16)
    assert _blobs_equal(out, blob)
    # And the failure the format exists to prevent, so this pin fails
    # loudly if numpy ever changes the hazard out from under us.
    import io

    bio = io.BytesIO()
    np.savez(bio, mk=blob[0][0])
    bio.seek(0)
    with np.load(bio) as z:
        degraded = z["mk"]
    assert degraded.dtype != np.dtype(ml_dtypes.bfloat16)


def test_unpack_rejects_damage():
    data = _pack_blob(_blob())
    with pytest.raises(ValueError):
        _unpack_blob(b"NOTMAGIC" + data[8:])
    flipped = bytearray(data)
    flipped[-1] ^= 0xFF
    with pytest.raises(ValueError):
        _unpack_blob(bytes(flipped))
    with pytest.raises(ValueError):
        _unpack_blob(data[: len(data) // 2])


def test_disk_tier_put_get_dedup_and_counters(tmp_path):
    """Files are NAMED by chain key, so a second put of the same key —
    from any engine, replica, or process — is a touch, not a write."""
    tier = KVDiskTier(str(tmp_path))
    blob = _blob(seed=1, draft=True)
    assert tier.put("ab12", blob) and tier.writes == 1
    assert tier.put("ab12", blob) and tier.writes == 1
    assert tier.dedup_hits == 1 and tier.pages == 1
    # A second tier over the same directory sees the same file: the
    # directory IS the dedup namespace.
    other = KVDiskTier(str(tmp_path))
    assert other.contains("ab12")
    got = other.get("ab12")
    assert got is not None and _blobs_equal(got, blob)
    assert other.reads == 1
    with pytest.raises(ValueError):
        tier.put("not-hex!", blob)
    with pytest.raises(ValueError):
        KVDiskTier(str(tmp_path), budget_pages=0)


def test_disk_tier_budget_evicts_coldest_by_mtime(tmp_path):
    tier = KVDiskTier(str(tmp_path), budget_pages=2)
    for i, key in enumerate(("aa", "bb", "cc")):
        tier.put(key, _blob(seed=i))
        os.utime(tier._path(key), (i + 1, i + 1))  # deterministic ages
    assert tier.pages == 2 and tier.evictions == 1
    assert not tier.contains("aa")  # coldest went first
    assert tier.contains("bb") and tier.contains("cc")


def test_disk_tier_corrupt_read_quarantines_to_miss(tmp_path):
    """A damaged file is counted, unlinked, and served as a miss — the
    tier converges back to clean instead of re-reading the damage (and
    a re-put can then land a good copy)."""
    tier = KVDiskTier(str(tmp_path))
    blob = _blob(seed=2)
    tier.put("0f", blob)
    with open(tier._path("0f"), "r+b") as f:
        f.seek(30)
        f.write(b"\xff\xff\xff\xff")
    assert tier.get("0f") is None
    assert tier.read_corrupt == 1 and not tier.contains("0f")
    assert tier.put("0f", blob) and tier.writes == 2
    assert tier.get("0f") is not None


def test_disk_tier_fault_seams_degrade_not_raise(tmp_path):
    """The injector seams take exactly the production degrade paths: a
    failed write returns False (blob stays in host RAM), a corrupt
    read quarantines to a miss — neither ever raises to the caller."""
    inj = FaultInjector(
        {"kv_disk_write_fail": 1, "kv_disk_read_corrupt": 1}
    )
    tier = KVDiskTier(str(tmp_path), injector=inj)
    blob = _blob(seed=4)
    assert tier.put("e0", blob) is False and tier.write_failures == 1
    assert tier.put("e0", blob) is True  # crossing 2: lands
    assert tier.get("e0") is None  # injected damage -> quarantined
    assert tier.read_corrupt == 1 and not tier.contains("e0")


# ---- session journal -----------------------------------------------------


def test_journal_rotation_and_epochs_survive_restart(tmp_path):
    """Epochs are monotonic ACROSS writers (the kvsched claim-epoch
    discipline): a fresh-process journal over the same directory can
    never stamp an epoch a reader has already seen."""
    j1 = SessionJournal(str(tmp_path))
    assert j1.write([{"rid": "a"}]) == 0
    assert j1.write([{"rid": "a"}, {"rid": "b"}]) == 1
    records, reason = j1.load()
    assert reason == "ok" and [r["rid"] for r in records] == ["a", "b"]
    # The previous generation is the FIRST write, kept beside it.
    assert os.path.exists(j1.prev_path)
    j2 = SessionJournal(str(tmp_path))  # "fresh process"
    assert j2.write([{"rid": "c"}]) == 2
    assert j2.load()[0] == [{"rid": "c"}]


def test_journal_torn_write_falls_back_one_generation(tmp_path):
    inj = FaultInjector({"journal_torn_write": 2})
    j = SessionJournal(str(tmp_path), injector=inj)
    j.write([{"rid": "good"}])
    j.write([{"rid": "torn"}])  # crossing 2: dies mid-write
    assert j.writes == 1 and j.torn_writes == 1
    records, reason = j.load()
    assert reason == "fallback" and records == [{"rid": "good"}]


def test_journal_absent_and_doubly_corrupt(tmp_path):
    j = SessionJournal(str(tmp_path))
    assert j.load() == (None, "absent")
    j.write([{"rid": "x"}])
    j.write([{"rid": "y"}])
    for path in (j.path, j.prev_path):
        with open(path, "w", encoding="utf-8") as f:
            f.write('{"version": 1, "records"')  # torn prefix
    assert j.load() == (None, "corrupt")


# ---- restart bit-identity ------------------------------------------------


def _reqs():
    """The bench arm's shape at test scale: a shared system template
    (the disk tier dedups it) + per-request tails, budgets staggered so
    a 3-step kill lands genuinely mid-stream."""
    key = jax.random.PRNGKey(23)
    prefix = [int(t) for t in jax.random.randint(
        jax.random.fold_in(key, 0), (8,), 0, CONFIG.vocab_size, jnp.int32,
    )]
    reqs = []
    for i in range(4):
        tail = [int(t) for t in jax.random.randint(
            jax.random.fold_in(key, 100 + i), (1 + i % 4,), 0,
            CONFIG.vocab_size, jnp.int32,
        )]
        reqs.append((prefix + tail, 13 - (i * 4) % 8))
    return reqs


def _mk_fleet(params, root):
    """Two-replica fleet; ``root=None`` builds the durability-off
    oracle (no disk tier, no journal — the pay-for-what-you-use pin is
    that its streams are the reference)."""
    durable = root is not None
    engines = [
        ServeEngine(
            params, CONFIG, slots=2, page_size=4, chunk=4,
            prompt_bucket=4, pipelined=True, n_pages=14,
            prefix_cache=True,
            kv_offload=durable,
            kv_host_pages=28 if durable else None,
            kv_disk_dir=os.path.join(root, "kv") if durable else None,
        )
        for _ in range(2)
    ]
    return Fleet(
        engines, chip_ids=["chip-0", "chip-1"], hang_timeout_s=60.0,
        journal_dir=os.path.join(root, "journal") if durable else None,
    )


def test_durable_check_smoke(tmp_path):
    """The acceptance pin, end to end: kill a journaled fleet
    mid-stream, rebuild a FRESH fleet from nothing but the journal +
    per-page disk files, and every restored stream must be
    bit-identical to the uninterrupted durability-OFF oracle — then
    fresh submissions keep working (the rid counter fast-forwarded
    past every restored rid)."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    reqs = _reqs()

    oracle = _mk_fleet(params, None)
    rids = [oracle.submit(p, n) for p, n in reqs]
    oracle.run()
    done = {fr.rid: fr for fr in oracle.drain_completed()}
    assert {done[r].status for r in rids} == {"ok"}
    ref = [list(done[r].tokens) for r in rids]
    oracle.close()

    root = str(tmp_path)
    fleet = _mk_fleet(params, root)
    rids = [fleet.submit(p, n) for p, n in reqs]
    with fleet._lock:
        for _ in range(3):  # mid-stream, then the process "dies"
            if not fleet.idle:
                fleet.step()
    fleet.close()  # journals live sessions before going dark
    assert fleet.journal_writes >= 1
    assert os.listdir(os.path.join(root, "kv"))  # pages parked on disk

    fleet2 = _mk_fleet(params, root)
    restored = fleet2.restore()
    assert restored == len(reqs) and fleet2.sessions_restored == restored
    # The kill must land genuinely mid-stream, or this test silently
    # degrades to restoring completed sessions.
    assert sum(1 for fr in fleet2.queue if fr.tokens) >= 1
    assert fleet2.tokens_replayed > 0
    fleet2.run()
    done = {fr.rid: fr for fr in fleet2.drain_completed()}
    assert [list(done[r].tokens) for r in rids] == ref

    # Fresh work composes: no rid collision with the resurrected ones.
    fresh = fleet2.submit(reqs[0][0], 2)
    assert fresh not in rids
    fleet2.run()
    tokens, done, status = fleet2.poll(fresh)
    assert done and status == "ok" and len(tokens) == 2
    fleet2.close()


def test_restore_is_boot_time_only_and_cold_start_is_zero(tmp_path):
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    fleet = _mk_fleet(params, str(tmp_path))
    assert fleet.restore() == 0  # absent journal: cold start, no raise
    fleet.submit([1, 2, 3], 2)
    with pytest.raises(RuntimeError, match="boot-time"):
        fleet.restore()
    fleet.close()


def test_completed_sessions_restore_as_history_without_redispatch(tmp_path):
    """Terminal journal records come back pollable with their exact
    tokens but move no terminal counters (they were the dead process's
    work); a journaled-complete live stream finishes without a single
    new dispatch."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    reqs = _reqs()
    root = str(tmp_path)
    fleet = _mk_fleet(params, root)
    rids = [fleet.submit(p, n) for p, n in reqs]
    fleet.run()  # everything completes, THEN the process dies
    ref = {r: fleet.poll(r)[0] for r in rids}
    fleet.close()

    fleet2 = _mk_fleet(params, root)
    assert fleet2.restore() == len(reqs)
    for r in rids:
        tokens, done, status = fleet2.poll(r)
        assert done and status == "ok" and tokens == ref[r]
    assert not fleet2.queue  # nothing left to dispatch...
    assert fleet2.generated_tokens == 0  # ...and nothing re-decoded
    fleet2.close()


# ---- degrade hardening (snapshot + device table) -------------------------


def test_engine_snapshot_corrupt_artifact_degrades_to_cold(tmp_path):
    from workloads.faststart import EngineSnapshot

    path = str(tmp_path / "snap.json")
    with open(path, "w", encoding="utf-8") as f:
        f.write('{"version": 1, "config_fingerprint"')  # torn
    before = EngineSnapshot.load_errors
    assert EngineSnapshot.load(path) is None
    assert EngineSnapshot.load(str(tmp_path / "missing.json")) is None
    assert EngineSnapshot.load_errors == before + 2


def test_device_table_corrupt_artifact_adopts_nothing(tmp_path):
    from workloads.profiler import DeviceTimeTable

    path = str(tmp_path / "bench.json")
    with open(path, "w", encoding="utf-8") as f:
        f.write("{torn artifact")
    table = DeviceTimeTable()
    assert table.refresh_from_artifact(path) == 0
    assert table.refresh_from_artifact(["not", "a", "dict"]) == 0
    assert table.refresh_errors == 2

"""Serve latency telemetry (VERDICT r4 item 6): per-request
submit/first-token/retirement stamps on the engine, and the TTFT/e2e
percentile measurement built on them — pinned so that backpressured
admission SHOWS UP in the TTFT tail while token parity is untouched."""

import jax
import jax.numpy as jnp
import numpy as np

from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.perfbench import BenchScale, _pctl, measure_serve_latency
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)


def _stream_engine(params, slots: int):
    engine = ServeEngine(
        params, CONFIG, slots=slots, page_size=4, prompt_bucket=8
    )
    # Warm the compiles (slots=1 and slots=4 have different batch shapes,
    # so each engine pays its own) — the measured stream must see steady
    # state, not XLA compile time masquerading as queue wait.
    engine.submit([9], 12)
    engine.run()
    engine.completed.clear()
    rids = [engine.submit([1 + i, 2, 3], 12) for i in range(6)]
    served = engine.run()
    return engine, rids, served


def test_latency_stamps_are_ordered_and_complete():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine, rids, _ = _stream_engine(params, slots=2)
    assert len(engine.completed) == len(rids)
    for req in engine.completed:
        assert req.t_submit is not None
        assert req.t_submit <= req.t_first <= req.t_done
        assert req.ttft_secs >= 0 and req.e2e_secs >= req.ttft_secs


def test_backpressure_lands_in_ttft_tail_not_in_tokens():
    """The same 6-request stream through slots=1 (everything queues) and
    slots=4 (the last wave queues): tokens must be identical (greedy
    parity is latency-blind), while in BOTH engines the queued requests'
    TTFT must dominate the immediately-admitted ones' — queue wait is IN
    the client-visible first-token latency, which is exactly what the
    bench's serve_ttft_p99_ms field surfaces."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    eng1, rids1, served1 = _stream_engine(params, slots=1)
    eng4, rids4, served4 = _stream_engine(params, slots=4)
    for r1, r4 in zip(rids1, rids4):
        assert served1[r1] == served4[r4]
    for eng, rids in ((eng1, rids1), (eng4, rids4)):
        by_rid = {r.rid: r for r in eng.completed}
        ttfts = [by_rid[r].ttft_secs for r in rids]
        # The tail (queued arrivals) must sit far above the head
        # (admitted instantly): queue wait, not decode time, dominates.
        assert _pctl(ttfts, 0.99) > 4 * min(ttfts), (eng.slots, ttfts)
    # With one slot, arrival order IS service order: TTFT must be
    # monotonically non-decreasing along the submission order.
    by_rid1 = {r.rid: r for r in eng1.completed}
    ttft1 = [by_rid1[r].ttft_secs for r in rids1]
    assert all(a <= b * 1.5 for a, b in zip(ttft1, ttft1[1:])), ttft1
    assert ttft1[-1] > 3 * ttft1[0]


def test_at_admission_finish_gets_stamps_too():
    """max_new_tokens=1 retires at admission (never takes a slot): the
    stamps must still be complete, with t_done == t_first."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    engine = ServeEngine(params, CONFIG, slots=1, page_size=4, prompt_bucket=8)
    rid = engine.submit([5, 6], 1)
    served = engine.run()
    assert len(served[rid]) == 1
    (req,) = engine.completed
    assert req.rid == rid and req.t_done == req.t_first >= req.t_submit


def test_measure_serve_latency_fields_sane():
    out = measure_serve_latency(BenchScale.named("tiny"))
    assert out["serve_latency_requests"] == 6  # 3 x tiny batch
    for key in ("serve_ttft_p50_ms", "serve_ttft_p99_ms",
                "serve_e2e_p50_ms", "serve_e2e_p99_ms"):
        assert out[key] > 0
    assert out["serve_ttft_p50_ms"] <= out["serve_ttft_p99_ms"]
    assert out["serve_e2e_p50_ms"] <= out["serve_e2e_p99_ms"]
    assert out["serve_ttft_p99_ms"] <= out["serve_e2e_p99_ms"]


def test_pipelined_emission_lag_is_in_ttft():
    """Pipelined stepping defers emission by a chunk: the stamps must
    reflect OBSERVED emission (client-visible), so pipelined TTFT for a
    lone request is >= the unpipelined one measured the same way —
    and parity still holds."""
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    ref = generate(
        params, jnp.asarray([[9, 8, 7]], jnp.int32), CONFIG,
        max_new_tokens=10,
    )
    for pipelined in (False, True):
        engine = ServeEngine(
            params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
            pipelined=pipelined,
        )
        rid = engine.submit([9, 8, 7], 10)
        served = engine.run()
        assert served[rid] == [int(t) for t in np.asarray(ref[0])]
        (req,) = engine.completed
        assert req.t_submit <= req.t_first <= req.t_done

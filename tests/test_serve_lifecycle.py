"""Request-lifecycle fault tolerance (docs/SERVING.md "Fault
tolerance"): the typed error taxonomy, cancellation and deadlines,
bounded admission, close()/context-manager shutdown, the HealthFanout
bridge, and step-level quarantine + bit-identical replay under injected
seam faults — each contract pinned in isolation (tests/test_serve_fuzz
sweeps them interleaved)."""

import queue
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads import (
    EngineClosed,
    InvalidRequest,
    QueueFull,
    RequestTooLarge,
    ServeError,
)
from workloads.faults import ENGINE_SEAMS, FaultInjector, InjectedFault
from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)
PROMPT = [1, 2, 3, 4, 5, 6, 7]


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft():
    return init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))


def _engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("prompt_bucket", 8)
    return ServeEngine(params, CONFIG, **kw)


def _ref(params, prompt, n):
    return [int(t) for t in np.asarray(generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=n,
    )[0])]


def _statuses(engine):
    return {r.rid: r.status for r in engine.completed}


# ---- typed error taxonomy ----------------------------------------------


def test_error_taxonomy_types_and_messages(params):
    engine = _engine(params)
    # Size errors are RequestTooLarge AND (for back-compat) ValueError,
    # with the historical messages intact.
    with pytest.raises(RequestTooLarge, match="prompt length"):
        engine.submit([])
    with pytest.raises(ValueError, match="prompt length"):
        engine.submit([1] * CONFIG.max_seq_len)
    with pytest.raises(RequestTooLarge, match="exceeds max_seq_len"):
        engine.submit(PROMPT, CONFIG.max_seq_len)
    small = ServeEngine(
        params, CONFIG, slots=1, page_size=4, prompt_bucket=8, n_pages=2
    )
    with pytest.raises(RequestTooLarge, match="never be admitted"):
        small.submit(PROMPT, 40)
    with pytest.raises(InvalidRequest, match="unknown adapter"):
        engine.submit(PROMPT, 2, adapter="nope")
    with pytest.raises(InvalidRequest, match="max_new_tokens"):
        engine.submit(PROMPT, 0)
    with pytest.raises(InvalidRequest, match="deadline_s"):
        engine.submit(PROMPT, 2, deadline_s=0)
    with pytest.raises(InvalidRequest, match="n_samples"):
        engine.submit_fanout(PROMPT, 2, n_samples=0)
    engine.submit(PROMPT, 2, rid="dup")
    with pytest.raises(InvalidRequest, match="already in flight"):
        engine.submit(PROMPT, 2, rid="dup")
    # Everything is a ServeError; the hierarchy is importable from the
    # package root.
    for exc in (InvalidRequest, RequestTooLarge, QueueFull, EngineClosed):
        assert issubclass(exc, ServeError)
    assert issubclass(RequestTooLarge, InvalidRequest)
    engine.run()


def test_queue_full_is_typed_and_counted(params):
    engine = _engine(params, slots=1, max_pending=2)
    engine.submit(PROMPT, 2)
    engine.submit(PROMPT, 2)
    with pytest.raises(QueueFull) as exc_info:
        engine.submit(PROMPT, 2)
    assert exc_info.value.request.status == "rejected"
    with pytest.raises(QueueFull):
        engine.submit_fanout(PROMPT, 2, n_samples=2)
    assert engine.queue_rejections == 2
    served = engine.run()
    assert len(served) == 2  # the accepted ones, untouched
    assert set(_statuses(engine).values()) == {"ok"}


# ---- cancellation and deadlines ----------------------------------------


def test_cancel_queued_and_running(params):
    engine = _engine(params, slots=1, pipelined=True)
    r1 = engine.submit(PROMPT, 20)
    r2 = engine.submit(PROMPT, 20)
    engine.step()
    engine.step()
    assert engine.cancel(r2) is True  # still queued: never admitted
    assert engine.cancel(r1) is True  # running: drained, slot recycled
    assert engine.cancel(r1) is False  # already terminal
    assert engine.cancel("ghost") is False
    out = engine.run()
    sts = _statuses(engine)
    assert sts == {r1: "cancelled", r2: "cancelled"}
    by_rid = {r.rid: r for r in engine.completed}
    assert by_rid[r2].tokens == [] and by_rid[r2].t_admit is None
    # The running request keeps its already-emitted prefix of the true
    # stream (cancel stops it, it does not rewrite history).
    ref = _ref(params, PROMPT, 20)
    assert by_rid[r1].tokens == ref[: len(by_rid[r1].tokens)]
    assert set(out) == {r1, r2}
    assert engine.ctrl.used_pages == 0 and not engine._occupied.any()
    assert engine.requests_cancelled == 2


def test_cancel_pending_fanout_member_unwinds_group(params):
    engine = _engine(params, slots=1)
    ga, gb = engine.submit_fanout(PROMPT, 6, n_samples=2)
    engine.step()  # one slot: ga admits, gb still pending
    assert engine.cancel(gb)
    engine.run()
    sts = _statuses(engine)
    assert sts == {ga: "ok", gb: "cancelled"}
    assert not engine._groups  # countdown ran despite the cancel
    assert engine.ctrl.used_pages == 0


def test_deadline_expires_queued_and_running(params):
    engine = _engine(params, slots=1)
    ra = engine.submit(PROMPT, 30)
    rb = engine.submit(PROMPT, 30, deadline_s=0.001)  # starves in queue
    time.sleep(0.01)
    engine.run()
    sts = _statuses(engine)
    assert sts == {ra: "ok", rb: "expired"}
    assert engine.requests_expired == 1

    engine2 = _engine(params, slots=1, pipelined=True)
    rc = engine2.submit(PROMPT, 40, deadline_s=0.05)
    t0 = time.perf_counter()
    while not engine2.idle and time.perf_counter() - t0 < 30:
        engine2.step()
    sts = _statuses(engine2)
    by_rid = {r.rid: r for r in engine2.completed}
    # Fast hosts may finish all 40 tokens inside the deadline; either
    # way the terminal status is single and the state drains.
    assert sts[rc] in ("ok", "expired")
    if sts[rc] == "expired":
        ref = _ref(params, PROMPT, 40)
        assert by_rid[rc].tokens == ref[: len(by_rid[rc].tokens)]
    assert engine2.ctrl.used_pages == 0 and not engine2._occupied.any()


# ---- close() / context manager -----------------------------------------


def test_close_fails_inflight_and_is_idempotent(params):
    engine = _engine(params, slots=1, prefix_cache=True)
    r1 = engine.submit(PROMPT, 30)
    r2 = engine.submit(PROMPT, 30)
    engine.step()
    engine.close()
    engine.close()  # idempotent
    assert engine.closed
    sts = {r.rid: (r.status, r.error) for r in engine.completed}
    for rid in (r1, r2):
        assert sts[rid][0] == "failed" and "EngineClosed" in sts[rid][1]
    assert engine.ctrl.used_pages == 0  # prefix pins flushed too
    assert engine._committed_pages == 0
    with pytest.raises(EngineClosed):
        engine.submit(PROMPT, 2)
    with pytest.raises(EngineClosed):
        engine.step()
    with pytest.raises(EngineClosed):
        engine.cancel(r1)


def test_context_manager_closes_and_unbinds_gauges(params):
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import EngineObserver

    reg = Registry()
    obs = EngineObserver()
    obs.bind_registry(reg)
    assert any(n.startswith("engine_") for n, *_ in reg._gauges)
    with _engine(params, observer=obs) as engine:
        rid = engine.submit(PROMPT, 4)
        engine.run()
    assert engine.closed
    # close() released the gauge collectors (they would otherwise pin
    # the engine — and its params/pools — on the registry forever).
    assert not any(n.startswith("engine_") for n, *_ in reg._gauges)
    assert _statuses(engine)[rid] == "ok"
    # Lifecycle counters reached the registry through the bridge.
    assert "engine_requests_retired_total" in reg.render()


def test_close_leaves_engine_idle_and_flushes_counters(params):
    """close() must not strand state step() can never drain: the closed
    engine reads idle, and the close-failed requests' counter deltas and
    spans reach the registry BEFORE the gauges unbind (step() refuses to
    run afterwards, so the step-boundary push can never fire again)."""
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import EngineObserver

    reg = Registry()
    obs = EngineObserver(name="closing")
    obs.bind_registry(reg)
    engine = _engine(params, slots=1, observer=obs)
    r1 = engine.submit(PROMPT, 30)
    r2 = engine.submit(PROMPT, 30)
    engine.step()
    engine.close()
    assert engine.idle  # nothing left that a step could ever surface
    assert engine.requests_failed == 2
    assert (
        f"{PREFIX}_engine_requests_failed_total{{engine=\"closing\"}} 2"
        in reg.render()
    )
    spans = {s.rid: s for s in obs.drain_spans()}
    assert spans[r1].status == "failed"
    assert spans[r2].status == "failed"


# ---- fault injection: quarantine + replay ------------------------------


def test_fault_replay_is_bit_identical_per_seam(params):
    ref = _ref(params, PROMPT, 12)
    baseline = None
    for seam, crossing in (
        # One sweep admits the whole two-request stream, so the prefill
        # seams fault on their FIRST crossing; decode seams mid-stream.
        ("prefill_dispatch", 1), ("prefill_readback", 1),
        ("decode_dispatch", 2), ("decode_readback", 2),
    ):
        engine = _engine(
            params, pipelined=True,
            fault_injector=FaultInjector({seam: [crossing]}),
        )
        r1 = engine.submit(PROMPT, 12)
        r2 = engine.submit(PROMPT[:3], 8)
        out = engine.run()
        assert out[r1] == ref, (seam, out[r1])
        if baseline is None:
            baseline = out
        assert out == baseline, seam
        assert engine.steps_quarantined >= 1, seam
        assert len(engine.fault_recovery_s) >= 1, seam
        assert set(_statuses(engine).values()) == {"ok"}, seam
        assert engine.ctrl.used_pages == 0 and engine._committed_pages == 0


def test_fault_replay_spec_seams(params, draft):
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        draft_params=draft, draft_config=DRAFT_CONFIG, gamma=3,
        pipelined=True,
        fault_injector=FaultInjector(
            {"spec_dispatch": [2], "spec_readback": [3]}
        ),
    )
    rid = engine.submit(PROMPT, 12)
    out = engine.run()
    assert out[rid] == _ref(params, PROMPT, 12)
    assert engine.steps_quarantined == 2
    assert engine.ctrl.used_pages == 0 and not engine._occupied.any()


def test_retry_budget_exhaustion_fails_terminally(params):
    engine = _engine(
        params, slots=1, max_retries=2,
        fault_injector=FaultInjector(
            {"decode_dispatch": list(range(1, 20))}
        ),
    )
    rid = engine.submit(PROMPT, 12)
    engine.run()
    req = {r.rid: r for r in engine.completed}[rid]
    assert req.status == "failed"
    assert req.retries == 3  # budget + the final straw
    assert "InjectedFault" in req.error
    assert engine.requests_failed == 1
    assert engine.ctrl.used_pages == 0 and engine._committed_pages == 0


def test_injector_seams_are_exactly_the_engine_seams():
    """Every ENGINE seam the injector knows is one the engine actually
    crosses (grep the source for the check call), and vice versa — a
    renamed seam string would otherwise never fire.  Replica seams
    cross in workloads/fleet.py / the supervisor, not here."""
    import os
    import re

    src = open(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "workloads", "serve.py",
    ), encoding="utf-8").read()
    crossed = set(re.findall(r'_maybe_fault\("([a-z_]+)"\)', src))
    assert crossed == set(ENGINE_SEAMS)


def test_injected_fault_carries_seam_and_crossing():
    inj = FaultInjector({"decode_readback": 1})
    with pytest.raises(InjectedFault) as exc_info:
        inj.check("decode_readback")
    assert exc_info.value.seam == "decode_readback"
    assert exc_info.value.crossing == 1


# ---- health bridge ------------------------------------------------------


def test_health_bridge_pauses_requeues_and_resumes(params):
    from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
    from tpu_device_plugin.device import HealthEvent

    q = queue.Queue()
    engine = _engine(params, health_events=q, pipelined=True)
    rid = engine.submit(PROMPT, 12)
    engine.step()
    engine.step()
    q.put(HealthEvent(chip_id="chip-0", health=UNHEALTHY, code=2))
    engine.step()
    assert engine.paused
    assert not engine._occupied.any()  # in-flight work requeued
    assert engine.pending and engine.pending[0].rid == rid
    assert engine.pending[0].retries == 0  # no retry-budget charge
    occupancy_during_pause = engine._occupied.any()
    engine.step()  # held: no admission happens
    assert not occupancy_during_pause and not engine._occupied.any()
    # A second failing class while down must not flip anything.
    q.put(HealthEvent(chip_id="chip-1", health=UNHEALTHY, code=0))
    engine.step()
    assert engine.paused
    q.put(HealthEvent(chip_id="chip-0", health=HEALTHY, code=2))
    engine.step()
    assert engine.paused  # chip-1 still down
    q.put(HealthEvent(chip_id="chip-1", health=HEALTHY, code=0))
    out = engine.run()
    assert not engine.paused
    assert out[rid] == _ref(params, PROMPT, 12)  # replay bit-identical
    assert _statuses(engine)[rid] == "ok"
    assert engine.requests_retried >= 1


def test_health_unattributed_events_mix_with_per_chip(params):
    """HealthEvent's chip_id="" means "all chips" (the event could not
    be attributed).  On a raw health_events= queue an unattributed
    Healthy is the all-clear that lifts EVERY mark — a mixed-attribution
    stream must never strand the engine paused."""
    from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
    from tpu_device_plugin.device import HealthEvent

    q = queue.Queue()
    engine = _engine(params, health_events=q)
    rid = engine.submit(PROMPT, 8)
    # Per-chip fault, unattributed all-clear.
    q.put(HealthEvent(chip_id="chip-0", health=UNHEALTHY, code=2))
    engine.step()
    assert engine.paused
    q.put(HealthEvent(chip_id="", health=HEALTHY))
    engine.step()
    assert not engine.paused
    # Unattributed fault: only the unattributed all-clear lifts it.
    q.put(HealthEvent(chip_id="", health=UNHEALTHY, code=2))
    engine.step()
    assert engine.paused
    q.put(HealthEvent(chip_id="chip-0", health=HEALTHY, code=2))
    engine.step()
    assert engine.paused  # the fault was never attributed to chip-0
    q.put(HealthEvent(chip_id="", health=HEALTHY))
    out = engine.run()
    assert not engine.paused
    assert out[rid] == _ref(params, PROMPT, 8)
    assert _statuses(engine)[rid] == "ok"


def test_bind_health_subscribes_and_close_unsubscribes(params):
    class FakeFanout:
        def __init__(self):
            self.q = queue.Queue()
            self.unsubscribed = None

        def subscribe(self):
            return self.q

        def unsubscribe(self, q):
            self.unsubscribed = q

    fanout = FakeFanout()
    engine = _engine(params)
    engine.bind_health(fanout)
    with pytest.raises(RuntimeError, match="already bound"):
        engine.bind_health(fanout)
    rid = engine.submit(PROMPT, 4)
    engine.run()
    engine.close()
    assert fanout.unsubscribed is fanout.q
    assert _statuses(engine)[rid] == "ok"


# ---- observer integration ----------------------------------------------


def test_span_status_and_lifecycle_counters_on_registry(params):
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import EngineObserver

    reg = Registry()
    obs = EngineObserver(name="ft")
    obs.bind_registry(reg)
    engine = _engine(
        params, slots=1, max_pending=2, observer=obs,
        fault_injector=FaultInjector({"decode_dispatch": [2]}),
    )
    r1 = engine.submit(PROMPT, 10)
    r2 = engine.submit(PROMPT, 10, deadline_s=0.001)
    with pytest.raises(QueueFull):
        engine.submit(PROMPT, 2)
    time.sleep(0.01)
    engine.run()
    spans = {s.rid: s for s in obs.drain_spans()}
    assert spans[r1].status == "ok"
    assert spans[r2].status == "expired"
    text = reg.render()
    assert f"{PREFIX}_engine_requests_expired_total" in text
    assert f"{PREFIX}_engine_queue_rejections_total" in text
    assert f"{PREFIX}_engine_requests_retried_total" in text
    # The trace export carries the terminal status per request lane.
    from workloads.obs import trace_events

    obs.spans.extend(spans.values())
    trace = trace_events(obs)
    span_args = [
        e["args"] for e in trace["traceEvents"]
        if e.get("cat") == "request"
    ]
    assert any(a.get("status") == "expired" for a in span_args)

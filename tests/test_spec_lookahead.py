"""Speculative lookahead supersteps (paged.paged_spec_superstep +
ServeEngine(spec_lookahead=k)): k chained rounds per dispatch, tokens
read back once per superstep.  Parity is the bar: the emitted tokens
must be EXACTLY the single-round engine's tokens (greedy = the dense
reference) for every k, with eos, retirement lag, pipelining, sampling
and LoRA composed on top."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=96, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=96, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def models():
    return (
        init_params(CONFIG, jax.random.PRNGKey(0)),
        init_params(DRAFT_CONFIG, jax.random.PRNGKey(7)),
    )


def _engine(params, draft, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("prompt_bucket", 8)
    kw.setdefault("gamma", 3)
    return ServeEngine(
        params, CONFIG, draft_params=draft, draft_config=DRAFT_CONFIG, **kw
    )


def _ref(params, prompt, new):
    return [int(t) for t in np.asarray(
        generate(params, jnp.asarray([prompt], jnp.int32), CONFIG, new)[0]
    )]


@pytest.mark.parametrize("k", [2, 3])
def test_lookahead_greedy_matches_dense_reference(models, k):
    params, draft = models
    engine = _engine(params, draft, spec_lookahead=k)
    streams = [([3, 1, 4, 1, 5], 17), ([2, 7], 9), ([9] * 11, 13)]
    rids = [engine.submit(p, n) for p, n in streams]
    served = engine.run()
    for rid, (p, n) in zip(rids, streams):
        assert served[rid] == _ref(params, p, n), (k, rid)
    assert engine.ctrl.used_pages == 0


def test_lookahead_fewer_host_syncs_same_rounds(models):
    """The superstep's point: k rounds per dispatch.  spec_rounds counts
    device rounds either way, so a k=3 engine must finish the same work
    while stepping ~1/3 as many times."""
    params, draft = models
    ref = _ref(params, [5, 2, 9], 25)
    steps = {}
    for k in (1, 3):
        engine = _engine(params, draft, slots=1, spec_lookahead=k)
        rid = engine.submit([5, 2, 9], 25)
        n_steps, served = 0, {}
        while not engine.idle:
            for req in engine.step():
                served[req.rid] = req.tokens
            n_steps += 1
        steps[k] = n_steps
        assert served[rid] == ref, k
    assert steps[3] < steps[1], steps


def test_lookahead_eos_retires_with_bounded_overshoot(models):
    """A request hitting eos mid-superstep retires with its prefix
    intact; the rounds after eos are dead compute, never emission."""
    params, draft = models
    prompt = [4, 4, 8]
    full = _ref(params, prompt, 20)
    eos = full[6]
    engine = _engine(params, draft, spec_lookahead=3)
    rid = engine.submit(prompt, 20, eos_token=eos)
    got = engine.run()[rid]
    want = full[: full.index(eos) + 1]
    assert got[: len(want)] == want
    assert eos in got
    assert engine.ctrl.used_pages == 0


def test_lookahead_composes_with_pipelined(models):
    params, draft = models
    engine = _engine(params, draft, spec_lookahead=2, pipelined=True)
    streams = [([1, 2, 3], 15), ([6, 5], 11), ([7] * 5, 8)]
    rids = [engine.submit(p, n) for p, n in streams]
    served = engine.run()
    for rid, (p, n) in zip(rids, streams):
        assert served[rid] == _ref(params, p, n)
    assert engine.ctrl.used_pages == 0


def test_lookahead_composes_with_sampling_and_lora(models):
    from workloads.multi_lora import synthetic_adapters

    params, draft = models
    adapters = synthetic_adapters(CONFIG, 2, rank=4, scale=0.3, seed=3)
    engine = _engine(
        params, draft, spec_lookahead=2, temperature=0.8, top_k=40,
        rng=jax.random.PRNGKey(5), adapters=adapters,
    )
    names = [None] + sorted(adapters)
    rids = [
        engine.submit([1 + i, 2], 10, adapter=names[i % 3]) for i in range(4)
    ]
    served = engine.run()
    for rid in rids:
        toks = served[rid]
        assert len(toks) == 10
        assert all(0 <= t < CONFIG.vocab_size for t in toks)
    assert engine.ctrl.used_pages == 0


def test_lookahead_validation(models):
    params, draft = models
    with pytest.raises(ValueError, match="spec_lookahead"):
        _engine(params, draft, spec_lookahead=0)
    with pytest.raises(ValueError, match="spec_lookahead"):
        ServeEngine(params, CONFIG, spec_lookahead=2)


def test_lookahead_tp_sampling_structurally_sound(models):
    """TP x sampling x lookahead: the superstep program's sampling
    operand quad (rng/temperature/top_k/top_p shardings and unpack
    order) under the mesh — budgets exact, tokens in-vocab."""
    from workloads.train import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    params, draft = models
    mesh = make_mesh(2, model_parallel=2)
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        mesh=mesh, draft_params=draft, draft_config=DRAFT_CONFIG,
        gamma=3, spec_lookahead=2, temperature=0.9, top_k=40,
        rng=jax.random.PRNGKey(13),
    )
    rids = [engine.submit([2 + i, 5], 9) for i in range(3)]
    served = engine.run()
    for rid in rids:
        toks = served[rid]
        assert len(toks) == 9
        assert all(0 <= t < CONFIG.vocab_size for t in toks)
    assert engine.ctrl.used_pages == 0


def test_lookahead_tp_matches_greedy(models):
    """The superstep under a ("data", "model") mesh: scan-of-shard_map
    draft + GSPMD verify; tokens must equal the dense reference."""
    from workloads.train import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    params, draft = models
    mesh = make_mesh(2, model_parallel=2)
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        mesh=mesh, draft_params=draft, draft_config=DRAFT_CONFIG,
        gamma=3, spec_lookahead=2,
    )
    streams = [([1, 2, 3, 4], 12), ([9, 8, 7], 8)]
    rids = [engine.submit(p, n) for p, n in streams]
    served = engine.run()
    for rid, (p, n) in zip(rids, streams):
        assert served[rid] == _ref(params, p, n)
    assert engine.ctrl.used_pages == 0

"""Daemon lifecycle: smoke config, restart orchestration, signal handling.

Covers BASELINE configs[0] (chip-less node, failOnInitError=false, daemon
blocks quietly) and the reference's restart paths (SIGHUP, kubelet-socket
recreation, terminal signals — main.go:286-324)."""

import os
import queue
import signal
import threading
import time
from types import SimpleNamespace

import pytest

from tpu_device_plugin.backend.fake import FakeChipManager
from tpu_device_plugin.config import Config, Flags
from tpu_device_plugin.main import Daemon, FatalEvent, make_backend
from tpu_device_plugin.watchers import KubeletSocketWatcher, SignalEvent, SocketEvent

from .fake_kubelet import FakeKubelet


def run_daemon_async(daemon):
    result = {}

    def target():
        result["code"] = daemon.run()

    t = threading.Thread(target=target, daemon=True)
    t.start()
    return t, result


def make_daemon(tmp_path, kubelet, flags=None, backend=None):
    flags = flags or Flags(backend="fake", fake_topology="4x4")
    flags.device_plugin_path = kubelet.plugin_dir
    cfg = Config(flags=flags)
    backend = backend or FakeChipManager(n_chips=4, chips_per_tray=4)
    return Daemon(cfg, backend=backend, lease_dir=str(tmp_path / "leases"))


@pytest.fixture
def kubelet(tmp_path):
    k = FakeKubelet(str(tmp_path / "device-plugins"))
    k.start()
    yield k
    k.stop()


def test_smoke_cpu_only_node_blocks_quietly(tmp_path):
    """BASELINE configs[0]: no TPU stack, failOnInitError=false ⇒ no exit,
    no devices, clean shutdown on SIGTERM."""
    flags = Flags(backend="fake", fail_on_init_error=False,
                  device_plugin_path=str(tmp_path / "dp"))
    daemon = Daemon(
        Config(flags=flags),
        backend=FakeChipManager(fail_init=True),
        lease_dir=str(tmp_path / "leases"),
    )
    t, result = run_daemon_async(daemon)
    time.sleep(0.3)
    assert t.is_alive()  # blocked, not crashed
    daemon.request_stop()
    t.join(timeout=5)
    assert result["code"] == 0


def test_fail_on_init_error_exits_nonzero(tmp_path):
    flags = Flags(backend="fake", fail_on_init_error=True,
                  device_plugin_path=str(tmp_path / "dp"))
    daemon = Daemon(
        Config(flags=flags),
        backend=FakeChipManager(fail_init=True),
        lease_dir=str(tmp_path / "leases"),
    )
    assert daemon.run() == 1


def test_serve_register_and_terminal_signal(tmp_path, kubelet):
    daemon = make_daemon(tmp_path, kubelet)
    t, result = run_daemon_async(daemon)
    reg = kubelet.wait_for_registration()
    assert reg.resource_name == "google.com/tpu"
    assert daemon.started.wait(5)
    daemon.events.put(SignalEvent(signum=signal.SIGTERM))
    t.join(timeout=10)
    assert result["code"] == 0
    assert not os.path.exists(os.path.join(kubelet.plugin_dir, "tpu-tpu.sock"))


def test_sighup_restarts_and_reregisters(tmp_path, kubelet):
    daemon = make_daemon(tmp_path, kubelet)
    t, result = run_daemon_async(daemon)
    kubelet.wait_for_registration()
    assert daemon.started.wait(5)
    n_before = len(kubelet.registrations)

    daemon.events.put(SignalEvent(signum=signal.SIGHUP))
    deadline = time.monotonic() + 10
    while len(kubelet.registrations) <= n_before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert len(kubelet.registrations) > n_before  # re-registered after restart

    daemon.events.put(SignalEvent(signum=signal.SIGTERM))
    t.join(timeout=10)
    assert result["code"] == 0


def test_fatal_event_exits_nonzero(tmp_path, kubelet):
    daemon = make_daemon(tmp_path, kubelet)
    t, result = run_daemon_async(daemon)
    kubelet.wait_for_registration()
    assert daemon.started.wait(5)
    daemon.events.put(FatalEvent(message="crash budget exceeded"))
    t.join(timeout=10)
    assert result["code"] == 1


def test_kubelet_socket_watcher_detects_recreation(tmp_path):
    sock = tmp_path / "kubelet.sock"
    sock.write_text("")
    events: queue.Queue = queue.Queue()
    watcher = KubeletSocketWatcher(str(sock), events, poll_secs=0.05)
    watcher.start()
    try:
        time.sleep(0.15)  # baseline inode observed
        os.remove(sock)
        sock.write_text("")  # recreated -> new inode
        event = events.get(timeout=5)
        assert isinstance(event, SocketEvent)
    finally:
        watcher.stop()


def test_make_backend_selects_fake_topology():
    backend = make_backend(Flags(backend="fake", fake_topology="8x4"))
    backend.init()
    assert len(backend.devices()) == 8


def test_restart_backoff_escalates_caps_and_resets(tmp_path, monkeypatch):
    """Repeated plugin-start failures must back off EXPONENTIALLY to the
    cap — the flat RESTART_BACKOFF_SECS=5.0 retry hammered a broken
    kubelet socket at a fixed cadence forever — and one successful
    start resets the escalation (workloads/backoff.py policy)."""
    from workloads.backoff import Backoff

    from tpu_device_plugin import main as main_mod

    daemon = make_daemon(tmp_path, SimpleNamespace(plugin_dir=str(tmp_path)))
    daemon.restart_backoff = Backoff(
        base_s=1.0, factor=2.0, max_s=4.0, jitter=0.0
    )

    starts = {"n": 0}

    class FlakyPlugin:
        resource_name = "google.com/tpu"

        def start(self):
            starts["n"] += 1
            # Fail the first 4 starts (delays 1, 2, 4, 4 — capped),
            # succeed once, then fail again (the reset pin).
            if starts["n"] <= 4 or starts["n"] == 6:
                raise RuntimeError(f"kubelet socket refused ({starts['n']})")

        def stop(self):
            pass

    class FlakyStrategy:
        def get_plugins(self):
            return [FlakyPlugin()]

    monkeypatch.setattr(
        main_mod, "new_topology_strategy", lambda *a, **kw: FlakyStrategy()
    )
    delays = []

    def record_sleep(secs):
        delays.append(secs)
        return len(delays) >= 5  # terminal signal after the reset probe

    daemon._sleep_interruptible = record_sleep
    # Success (start #5) drops into the event loop; a kubelet-socket
    # recreation restarts the plugins, whose next start fails again.
    daemon.events.put(SocketEvent(path="kubelet.sock"))
    assert daemon._restart_loop(resource_config={}) == 0
    assert delays == [1.0, 2.0, 4.0, 4.0, 1.0]  # escalate, cap, reset
    assert starts["n"] == 6

"""Paged KV cache (workloads/paged.py): exact parity with the contiguous
cache, page accounting, prefix sharing, exhaustion."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.generate import decode_step, init_kv_cache
from workloads.model import ModelConfig, init_params
from workloads.paged import (
    PagePool,
    init_page_pool_array,
    paged_decode_step,
    table_array,
)

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.PRNGKey(0))


def test_paged_decode_matches_contiguous(params):
    """Token-by-token logits through the paged pool equal the contiguous
    cache exactly."""
    batch, steps, page_size = 2, 12, 4
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, steps), 0, CONFIG.vocab_size, jnp.int32
    )
    ctrl = PagePool(n_pages=16, page_size=page_size)
    for b in range(batch):
        ctrl.allocate(b, 1)
    pool = init_page_pool_array(CONFIG, 16, page_size)
    contiguous = init_kv_cache(CONFIG, batch, steps)

    max_pages = ctrl.pages_needed(steps)
    for pos in range(steps):
        for b in range(batch):
            ctrl.extend(b, pos + 1)
        tables = table_array([ctrl.tables[b] for b in range(batch)], max_pages)
        want, contiguous = decode_step(
            params, contiguous, tokens[:, pos], jnp.int32(pos), CONFIG
        )
        got, pool = paged_decode_step(
            params, pool, tables, tokens[:, pos], jnp.int32(pos), CONFIG
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-4,
            err_msg=f"position {pos}",
        )


def test_on_demand_allocation_uses_fewer_pages():
    ctrl = PagePool(n_pages=100, page_size=4)
    ctrl.allocate("a", 6)  # 2 pages, not max_len/4
    assert ctrl.used_pages == 2
    ctrl.extend("a", 9)
    assert ctrl.used_pages == 3
    ctrl.release("a")
    assert ctrl.used_pages == 0


def test_prefix_fork_shares_full_pages():
    ctrl = PagePool(n_pages=100, page_size=4)
    parent = ctrl.allocate("parent", 10)  # 3 pages, last partially full
    child = ctrl.fork("parent", "child", shared_tokens=8)  # page boundary
    assert child == parent[:2]
    assert ctrl.used_pages == 3  # no new physical pages for the child
    ctrl.extend("child", 12)  # child grows its own tail
    assert ctrl.used_pages == 4
    # Shared pages survive the parent's release, die with the child's.
    ctrl.release("parent")
    assert ctrl.used_pages == 3
    ctrl.release("child")
    assert ctrl.used_pages == 0


def test_fork_off_page_boundary_fails_loud():
    # A partial tail page cannot be shared: silently dropping it would
    # leave mask-admitted positions with zero k/v in the child.
    ctrl = PagePool(n_pages=100, page_size=4)
    ctrl.allocate("parent", 10)
    with pytest.raises(ValueError, match="page boundary"):
        ctrl.fork("parent", "child", shared_tokens=10)


def test_forked_sequences_decode_like_independent_ones(params):
    """Two sequences sharing prompt pages produce the same logits as two
    fully independent caches fed the same history."""
    page_size = 4
    prompt_len, steps = 8, 4
    prompt = jax.random.randint(
        jax.random.PRNGKey(2), (1, prompt_len), 0, CONFIG.vocab_size, jnp.int32
    )
    # Reference: contiguous, batch 2, identical histories diverging after
    # the prompt.
    div = jax.random.randint(
        jax.random.PRNGKey(3), (2, steps), 0, CONFIG.vocab_size, jnp.int32
    )
    history = jnp.concatenate([jnp.tile(prompt, (2, 1)), div], axis=1)
    contiguous = init_kv_cache(CONFIG, 2, prompt_len + steps)
    want = []
    for pos in range(prompt_len + steps):
        logits, contiguous = decode_step(
            params, contiguous, history[:, pos], jnp.int32(pos), CONFIG
        )
        want.append(logits)

    # Paged: one parent consumes the prompt, the child forks and both
    # consume their divergent tails in lockstep (batch axis = [parent,
    # child]).
    ctrl = PagePool(n_pages=32, page_size=page_size)
    pool = init_page_pool_array(CONFIG, 32, page_size)
    ctrl.allocate(0, 1)
    for pos in range(prompt_len):
        ctrl.extend(0, pos + 1)
        tables = table_array([ctrl.tables[0]], ctrl.pages_needed(prompt_len))
        _, pool = paged_decode_step(
            params, pool, tables, prompt[:, pos], jnp.int32(pos), CONFIG
        )
    ctrl.fork(0, 1, shared_tokens=prompt_len)
    # The fork shares only FULL pages; the parent's partial tail page (if
    # any) must be re-filled for the child.  prompt_len == 2*page_size
    # here, so every prompt page is full and shared.
    assert ctrl.used_pages == ctrl.pages_needed(prompt_len)

    total = prompt_len + steps
    max_pages = ctrl.pages_needed(total)
    got = []
    for pos in range(prompt_len, total):
        for b in (0, 1):
            ctrl.extend(b, pos + 1)
        tables = table_array(
            [ctrl.tables[0], ctrl.tables[1]], max_pages
        )
        logits, pool = paged_decode_step(
            params, pool, tables, div[:, pos - prompt_len], jnp.int32(pos),
            CONFIG,
        )
        got.append(logits)

    for i, (g, w) in enumerate(zip(got, want[prompt_len:])):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=2e-4,
            err_msg=f"divergent step {i}",
        )


def test_pool_exhaustion_fails_loud():
    ctrl = PagePool(n_pages=2, page_size=4)
    ctrl.allocate("a", 8)
    with pytest.raises(RuntimeError, match="exhausted"):
        ctrl.allocate("b", 4)


def test_double_allocate_fails_loud():
    ctrl = PagePool(n_pages=8, page_size=4)
    ctrl.allocate("a", 4)
    with pytest.raises(ValueError, match="already holds"):
        ctrl.allocate("a", 4)
    ctrl.release("a")
    ctrl.allocate("a", 4)  # fine after release

"""Paged KV cache (workloads/paged.py): exact parity with the contiguous
cache through the Pallas paged-attention kernel, per-row positions,
ragged prefill, page accounting, prefix sharing, exhaustion."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.generate import decode_step, init_kv_cache
from workloads.model import ModelConfig, init_params
from workloads.paged import (
    PagePool,
    init_page_pools,
    paged_decode_step,
    paged_prefill,
    table_array,
)

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.PRNGKey(0))


def _lockstep_reference(params, config, tokens):
    """Contiguous-cache logits for a [batch, steps] token stream."""
    batch, steps = tokens.shape
    cache = init_kv_cache(config, batch, steps)
    out = []
    for pos in range(steps):
        logits, cache = decode_step(
            params, cache, tokens[:, pos], jnp.int32(pos), config
        )
        out.append(logits)
    return out


def test_paged_decode_matches_contiguous(params):
    """Token-by-token logits through the paged pools equal the contiguous
    cache."""
    batch, steps, page_size = 2, 12, 4
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, steps), 0, CONFIG.vocab_size, jnp.int32
    )
    ctrl = PagePool(n_pages=16, page_size=page_size)
    for b in range(batch):
        ctrl.allocate(b, 1)
    pools = init_page_pools(CONFIG, 16, page_size)
    want = _lockstep_reference(params, CONFIG, tokens)

    max_pages = ctrl.pages_needed(steps)
    for pos in range(steps):
        for b in range(batch):
            ctrl.extend(b, pos + 1)
        tables = table_array([ctrl.tables[b] for b in range(batch)], max_pages)
        got, pools = paged_decode_step(
            params, pools, tables, tokens[:, pos], jnp.int32(pos), CONFIG
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want[pos]), atol=2e-4,
            err_msg=f"position {pos}",
        )


@pytest.mark.parametrize(
    "config",
    [
        ModelConfig(max_seq_len=64, n_layers=2, n_heads=4, n_kv_heads=2,
                    dtype=jnp.float32),
        ModelConfig(max_seq_len=64, n_layers=2, attention_window=5,
                    dtype=jnp.float32),
    ],
    ids=["gqa", "window"],
)
def test_paged_decode_matches_contiguous_variants(config):
    """Grouped-query and sliding-window configs hold the same parity."""
    params = init_params(config, jax.random.PRNGKey(0))
    batch, steps, page_size = 2, 11, 4
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, steps), 0, config.vocab_size, jnp.int32
    )
    ctrl = PagePool(n_pages=16, page_size=page_size)
    for b in range(batch):
        ctrl.allocate(b, steps)
    pools = init_page_pools(config, 16, page_size)
    want = _lockstep_reference(params, config, tokens)
    tables = table_array(
        [ctrl.tables[b] for b in range(batch)], ctrl.pages_needed(steps)
    )
    for pos in range(steps):
        got, pools = paged_decode_step(
            params, pools, tables, tokens[:, pos], jnp.int32(pos), config
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want[pos]), atol=2e-4,
            err_msg=f"position {pos}",
        )


def test_per_row_positions_match_lockstep(params):
    """Rows at DIFFERENT depths decode in one call: feeding the same
    per-row histories through per-row positions gives each row the same
    logits as its own lockstep run — the continuous-batching contract."""
    page_size = 4
    depths = [3, 9]  # row 0 starts at position 3, row 1 at position 9
    steps = 4
    tokens = jax.random.randint(
        jax.random.PRNGKey(5), (2, 16), 0, CONFIG.vocab_size, jnp.int32
    )
    # Reference: each row alone, contiguous cache, its own positions.
    want_rows = []
    for r, d in enumerate(depths):
        cache = init_kv_cache(CONFIG, 1, d + steps)
        for pos in range(d + steps):
            logits, cache = decode_step(
                params, cache, tokens[r : r + 1, pos], jnp.int32(pos), CONFIG
            )
            if pos >= d:
                want_rows.append((r, pos, logits))

    # Paged: seed each row's history with per-row decode steps, then step
    # both rows together with per-row positions.
    ctrl = PagePool(n_pages=32, page_size=page_size)
    pools = init_page_pools(CONFIG, 32, page_size)
    max_pages = ctrl.pages_needed(max(depths) + steps)
    for r, d in enumerate(depths):
        ctrl.allocate(r, d + steps)
    tables = table_array([ctrl.tables[0], ctrl.tables[1]], max_pages)
    # Seed histories one row at a time (the other row writes to its own
    # future positions' pages, which is harmless: positions are per-row).
    for r, d in enumerate(depths):
        for pos in range(d):
            _, pools = paged_decode_step(
                params, pools, tables[r : r + 1], tokens[r : r + 1, pos],
                jnp.int32(pos), CONFIG,
            )
    got = {}
    positions = np.asarray(depths, np.int32)
    for s in range(steps):
        tok = jnp.asarray(
            [tokens[r, int(positions[r])] for r in range(2)], jnp.int32
        )
        # COPY the mirror before it crosses into the dispatch: on the CPU
        # backend jnp.asarray may alias the numpy buffer zero-copy, and
        # the in-place `positions += 1` below would race the device's
        # deferred read (observed as an order-dependent full-suite-only
        # failure; same discipline as ServeEngine._dev).
        logits, pools = paged_decode_step(
            params, pools, tables, tok, jnp.asarray(positions.copy()), CONFIG
        )
        for r in range(2):
            got[(r, int(positions[r]))] = logits[r : r + 1]
        positions += 1

    for r, pos, want in want_rows:
        np.testing.assert_allclose(
            np.asarray(got[(r, pos)]), np.asarray(want), atol=2e-4,
            err_msg=f"row {r} position {pos}",
        )


def test_ragged_prefill_matches_contiguous(params):
    """One compiled prefill handles rows of different true lengths: each
    row's next-token logits equal its own contiguous-cache run, and
    padded positions never corrupt allocated pages."""
    page_size = 4
    bucket = 12
    lengths = [5, 12, 1]
    prompts_np = np.zeros((3, bucket), np.int32)
    rng = np.random.default_rng(0)
    for r, n in enumerate(lengths):
        prompts_np[r, :n] = rng.integers(0, CONFIG.vocab_size, n)
    ctrl = PagePool(n_pages=32, page_size=page_size)
    pools = init_page_pools(CONFIG, 32, page_size)
    for r, n in enumerate(lengths):
        ctrl.allocate(r, n)
    tables = table_array(
        [ctrl.tables[r] for r in range(3)], ctrl.pages_needed(bucket),
        fill=ctrl.trash,
    )
    logits, pools = paged_prefill(
        params, pools, tables, jnp.asarray(prompts_np),
        jnp.asarray(lengths, jnp.int32), CONFIG,
    )
    for r, n in enumerate(lengths):
        cache = init_kv_cache(CONFIG, 1, n)
        for pos in range(n):
            want, cache = decode_step(
                params, cache, jnp.asarray(prompts_np[r : r + 1, pos]),
                jnp.int32(pos), CONFIG,
            )
        np.testing.assert_allclose(
            np.asarray(logits[r]), np.asarray(want[0]), atol=2e-4,
            err_msg=f"row {r} (true length {n})",
        )
    # Decode continues per-row from the ragged prefill.
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    for r, n in enumerate(lengths):
        ctrl.extend(r, n + 1)
    tables = table_array(
        [ctrl.tables[r] for r in range(3)], ctrl.pages_needed(bucket + 1),
        fill=ctrl.trash,
    )
    step_logits, pools = paged_decode_step(
        params, pools, tables, tok, jnp.asarray(lengths, jnp.int32), CONFIG
    )
    assert np.all(np.isfinite(np.asarray(step_logits)))


def test_xla_fallback_matches_kernel():
    """The gathered-view XLA fallback (used on hardware for head dims
    Mosaic cannot lay out) computes exactly what the kernel computes —
    GQA, per-row lengths and sliding window included."""
    import jax

    from workloads.ops.paged_attention import (
        _paged_attention_xla,
        paged_attention,
    )

    L, n_pages, Hkv, ps, hd = 2, 12, 2, 4, 16
    heads, batch, maxp = 4, 3, 3
    kp = jax.random.normal(jax.random.PRNGKey(0), (L, n_pages, Hkv, ps, hd))
    vp = jax.random.normal(jax.random.PRNGKey(1), (L, n_pages, Hkv, ps, hd))
    q = jax.random.normal(jax.random.PRNGKey(2), (batch, heads, hd))
    rng = np.random.default_rng(3)
    tables = jnp.asarray(
        rng.choice(n_pages, size=(batch, maxp), replace=False), jnp.int32
    )
    lengths = jnp.asarray([1, 7, 12], jnp.int32)
    for window in (None, 5):
        want = paged_attention(
            q, kp, vp, tables, lengths, layer=1, window=window, interpret=True
        )
        got = _paged_attention_xla(
            q, kp, vp, tables, lengths, layer=1, window=window
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5,
            err_msg=f"window={window}",
        )


def test_length_zero_row_is_safe():
    """A fully-dead row (length 0 — an empty serve slot) must not index
    the block table at -1: the kv_map clamps its last-page computation,
    matching _finalize's claim that such rows are supported (their
    output is zeros from the l_safe guard).  Live rows are unaffected."""
    import jax

    from workloads.ops.paged_attention import (
        _paged_attention_xla,
        paged_attention,
    )

    L, n_pages, Hkv, ps, hd = 1, 8, 2, 4, 16
    heads, batch, maxp = 2, 2, 2
    kp = jax.random.normal(jax.random.PRNGKey(0), (L, n_pages, Hkv, ps, hd))
    vp = jax.random.normal(jax.random.PRNGKey(1), (L, n_pages, Hkv, ps, hd))
    q = jax.random.normal(jax.random.PRNGKey(2), (batch, heads, hd))
    tables = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
    lengths = jnp.asarray([0, 6], jnp.int32)  # row 0 is dead
    for impl in (
        lambda *a: paged_attention(*a, layer=0, interpret=True),
        lambda *a: _paged_attention_xla(*a, layer=0, window=None),
    ):
        out = np.asarray(impl(q, kp, vp, tables, lengths))
        assert np.all(np.isfinite(out))
        np.testing.assert_array_equal(out[0], np.zeros_like(out[0]))
        # Row 1 matches itself with the dead row absent.
        alone = impl(q[1:], kp, vp, tables[1:], lengths[1:])
        np.testing.assert_allclose(out[1], np.asarray(alone[0]), atol=1e-6)


def test_prefill_padding_never_writes_other_pages(params):
    """Padding table columns (whatever their value — here the dangerous
    default 0) must not be written by a ragged prefill: the scatter is
    redirected to the trash page, so another sequence's physical page 0
    keeps its bytes."""
    page_size = 4
    ctrl = PagePool(n_pages=16, page_size=page_size)
    pools = init_page_pools(CONFIG, 16, page_size)
    victim = ctrl.allocate("victim", 4)
    assert victim == [0]  # the free list hands out page 0 first
    k_pages, v_pages = pools
    sentinel_k = jnp.full_like(k_pages[:, 0], 7.25)
    sentinel_v = jnp.full_like(v_pages[:, 0], -3.5)
    pools = (
        k_pages.at[:, 0].set(sentinel_k),
        v_pages.at[:, 0].set(sentinel_v),
    )
    # One row, true length 2 (1 real page), bucket 8 (2 prefill columns):
    # the second column pads with the DEFAULT fill 0 == the victim's page.
    ctrl.allocate("row", 2)
    tables = table_array([ctrl.tables["row"]], 2)
    prompts = jnp.zeros((1, 8), jnp.int32).at[0, :2].set(jnp.asarray([5, 6]))
    _, pools = paged_prefill(
        params, pools, tables, prompts, jnp.asarray([2], jnp.int32), CONFIG
    )
    np.testing.assert_array_equal(np.asarray(pools[0][:, 0]), np.asarray(sentinel_k))
    np.testing.assert_array_equal(np.asarray(pools[1][:, 0]), np.asarray(sentinel_v))


def test_on_demand_allocation_uses_fewer_pages():
    ctrl = PagePool(n_pages=100, page_size=4)
    ctrl.allocate("a", 6)  # 2 pages, not max_len/4
    assert ctrl.used_pages == 2
    ctrl.extend("a", 9)
    assert ctrl.used_pages == 3
    ctrl.release("a")
    assert ctrl.used_pages == 0


def test_prefix_fork_shares_full_pages():
    ctrl = PagePool(n_pages=100, page_size=4)
    parent = ctrl.allocate("parent", 10)  # 3 pages, last partially full
    child = ctrl.fork("parent", "child", shared_tokens=8)  # page boundary
    assert child == parent[:2]
    assert ctrl.used_pages == 3  # no new physical pages for the child
    ctrl.extend("child", 12)  # child grows its own tail
    assert ctrl.used_pages == 4
    # Shared pages survive the parent's release, die with the child's.
    ctrl.release("parent")
    assert ctrl.used_pages == 3
    ctrl.release("child")
    assert ctrl.used_pages == 0


def test_fork_off_page_boundary_fails_loud():
    # A partial tail page cannot be shared: silently dropping it would
    # leave mask-admitted positions with zero k/v in the child.
    ctrl = PagePool(n_pages=100, page_size=4)
    ctrl.allocate("parent", 10)
    with pytest.raises(ValueError, match="page boundary"):
        ctrl.fork("parent", "child", shared_tokens=10)


def test_forked_sequences_decode_like_independent_ones(params):
    """Two sequences sharing prompt pages produce the same logits as two
    fully independent caches fed the same history."""
    page_size = 4
    prompt_len, steps = 8, 4
    prompt = jax.random.randint(
        jax.random.PRNGKey(2), (1, prompt_len), 0, CONFIG.vocab_size, jnp.int32
    )
    # Reference: contiguous, batch 2, identical histories diverging after
    # the prompt.
    div = jax.random.randint(
        jax.random.PRNGKey(3), (2, steps), 0, CONFIG.vocab_size, jnp.int32
    )
    history = jnp.concatenate([jnp.tile(prompt, (2, 1)), div], axis=1)
    want = _lockstep_reference(params, CONFIG, history)

    # Paged: one parent consumes the prompt, the child forks and both
    # consume their divergent tails in lockstep (batch axis = [parent,
    # child]).
    ctrl = PagePool(n_pages=32, page_size=page_size)
    pools = init_page_pools(CONFIG, 32, page_size)
    ctrl.allocate(0, 1)
    for pos in range(prompt_len):
        ctrl.extend(0, pos + 1)
        tables = table_array([ctrl.tables[0]], ctrl.pages_needed(prompt_len))
        _, pools = paged_decode_step(
            params, pools, tables, prompt[:, pos], jnp.int32(pos), CONFIG
        )
    ctrl.fork(0, 1, shared_tokens=prompt_len)
    # The fork shares only FULL pages; prompt_len == 2*page_size here, so
    # every prompt page is full and shared.
    assert ctrl.used_pages == ctrl.pages_needed(prompt_len)

    total = prompt_len + steps
    max_pages = ctrl.pages_needed(total)
    got = []
    for pos in range(prompt_len, total):
        for b in (0, 1):
            ctrl.extend(b, pos + 1)
        tables = table_array(
            [ctrl.tables[0], ctrl.tables[1]], max_pages
        )
        logits, pools = paged_decode_step(
            params, pools, tables, div[:, pos - prompt_len], jnp.int32(pos),
            CONFIG,
        )
        got.append(logits)

    for i, (g, w) in enumerate(zip(got, want[prompt_len:])):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=2e-4,
            err_msg=f"divergent step {i}",
        )


def test_pool_exhaustion_fails_loud():
    ctrl = PagePool(n_pages=2, page_size=4)
    ctrl.allocate("a", 8)
    with pytest.raises(RuntimeError, match="exhausted"):
        ctrl.allocate("b", 4)


def test_double_allocate_fails_loud():
    ctrl = PagePool(n_pages=8, page_size=4)
    ctrl.allocate("a", 4)
    with pytest.raises(ValueError, match="already holds"):
        ctrl.allocate("a", 4)
    ctrl.release("a")
    ctrl.allocate("a", 4)  # fine after release

"""Lossless speculative SAMPLING (VERDICT r4 item 4): the rejection rule
itself (paged._spec_accept) is verified distributionally against the
target distribution it must be equivalent to; the engine composition is
pinned for greedy-parity (temperature 0 unchanged), self-draft
all-acceptance, and structural sanity under temperature > 0.

Reference pendant: none — the reference daemon has no model code; the
acceptance rule is the standard speculative-sampling formulation
(draft x ~ q accepted with min(1, p(x)/q(x)); residual max(p-q,0)
renormalised on rejection), whose marginal is exactly p."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.paged import _spec_accept
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)


def test_spec_accept_marginal_matches_target_distribution():
    """The committed first token's marginal must be EXACTLY p no matter
    how bad q is — checked empirically over many keys on a small vocab,
    in the worst interesting case (q and p substantially disagree)."""
    vocab, gamma = 5, 2
    q_dist = jnp.asarray([0.70, 0.10, 0.10, 0.05, 0.05], jnp.float32)
    p_dist = jnp.asarray([0.10, 0.40, 0.20, 0.20, 0.10], jnp.float32)
    q = jnp.broadcast_to(q_dist, (1, gamma, vocab))
    p = jnp.broadcast_to(p_dist, (1, gamma + 1, vocab))

    @jax.jit
    def one(key):
        k_draft, k_accept = jax.random.split(key)
        drafts = jax.random.categorical(
            k_draft, jnp.log(q_dist)[None, :], shape=(1, gamma)
        ).astype(jnp.int32)
        committed, n = _spec_accept(drafts, q, p, k_accept)
        return committed[0, 0], n[0]

    n_trials = 4000
    firsts, ns = jax.vmap(one)(
        jax.random.split(jax.random.PRNGKey(0), n_trials)
    )
    counts = np.bincount(np.asarray(firsts), minlength=vocab) / n_trials
    # TV distance well inside 4-sigma sampling noise for 4000 draws.
    assert np.abs(counts - np.asarray(p_dist)).sum() < 0.06, counts
    # Acceptance must actually exercise all outcomes (reject-at-0 through
    # all-accept), otherwise the marginal test is vacuous.
    assert set(np.unique(np.asarray(ns))) == {0, 1, 2}


def test_spec_accept_identical_distributions_accept_everything():
    """q == p: the acceptance ratio is 1 so every draft is accepted and
    the bonus token comes from p — the self-draft fast path."""
    vocab, gamma, batch = 7, 3, 4
    dist = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(1), (batch, vocab))
    )
    q = jnp.broadcast_to(dist[:, None], (batch, gamma, vocab))
    p = jnp.broadcast_to(dist[:, None], (batch, gamma + 1, vocab))
    drafts = jax.vmap(
        lambda k, d: jax.random.categorical(
            k, jnp.log(d)[None], shape=(1, gamma)
        )[0]
    )(jax.random.split(jax.random.PRNGKey(2), batch), dist).astype(jnp.int32)
    committed, n = _spec_accept(drafts, q, p, jax.random.PRNGKey(3))
    assert (np.asarray(n) == gamma).all()
    np.testing.assert_array_equal(
        np.asarray(committed[:, :gamma]), np.asarray(drafts)
    )


def test_spec_accept_certain_rejection_resamples_from_residual():
    """q concentrated on token 0, p on token 1: the draft (always 0) is
    always rejected and the correction must come from the residual —
    which is p with q's mass removed, i.e. token 1."""
    vocab, gamma = 4, 1
    q_dist = jnp.asarray([1.0, 0.0, 0.0, 0.0], jnp.float32)
    p_dist = jnp.asarray([0.0, 1.0, 0.0, 0.0], jnp.float32)
    q = q_dist[None, None]
    p = jnp.broadcast_to(p_dist, (1, gamma + 1, vocab))
    drafts = jnp.zeros((1, gamma), jnp.int32)
    for seed in range(5):
        committed, n = _spec_accept(drafts, q, p, jax.random.PRNGKey(seed))
        assert int(n[0]) == 0
        assert int(committed[0, 0]) == 1


@pytest.fixture(scope="module")
def models():
    return (
        init_params(CONFIG, jax.random.PRNGKey(0)),
        init_params(DRAFT_CONFIG, jax.random.PRNGKey(7)),
    )


def _spec_engine(params, draft_params, draft_config, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("prompt_bucket", 8)
    return ServeEngine(
        params, CONFIG, draft_params=draft_params,
        draft_config=draft_config, gamma=3, **kw,
    )


def test_greedy_spec_tokens_unchanged_by_sampling_support(models):
    """temperature 0 through a real (different) draft: exact parity with
    the dense greedy reference — the greedy path's tokens must be
    untouched by the sampling extension."""
    params, draft = models
    engine = _spec_engine(params, draft, DRAFT_CONFIG)
    prompt = [3, 1, 4, 1, 5]
    rid = engine.submit(prompt, 12)
    got = engine.run()[rid]
    ref = generate(
        params, jnp.asarray([prompt], jnp.int32), CONFIG, max_new_tokens=12
    )
    assert got == [int(t) for t in np.asarray(ref[0])]


def test_sampling_spec_self_draft_accepts_every_round(models):
    """draft == target at temperature 1: p == q per position, so every
    round commits gamma+1 tokens — the round count collapses to
    ceil((new-1)/(gamma+1)) for a single request."""
    params, _ = models
    gamma, new = 3, 1 + 2 * 4  # first token + exactly 2 full rounds
    engine = ServeEngine(
        params, CONFIG, slots=1, page_size=4, prompt_bucket=8,
        draft_params=params, draft_config=CONFIG, gamma=gamma,
        temperature=1.0, rng=jax.random.PRNGKey(11),
    )
    rid = engine.submit([5, 2, 9], new)
    got = engine.run()[rid]
    assert len(got) == new
    assert engine.spec_rounds == 2, engine.spec_rounds


def test_sampling_spec_real_draft_structurally_sound(models):
    """A real (disagreeing) draft at temperature>0 with top-k: requests
    get exactly their token budgets, tokens stay in-vocab, pools drain."""
    params, draft = models
    engine = _spec_engine(
        params, draft, DRAFT_CONFIG, temperature=0.9, top_k=40,
        rng=jax.random.PRNGKey(5),
    )
    rids = [engine.submit([1 + i, 2, 3], 9 + i) for i in range(3)]
    served = engine.run()
    for i, rid in enumerate(rids):
        toks = served[rid]
        assert len(toks) == 9 + i
        assert all(0 <= t < CONFIG.vocab_size for t in toks)
    assert engine.ctrl.used_pages == 0


def test_sampling_spec_pipelined_matches_budgets(models):
    """The chained (pipelined) spec variant under sampling: same
    structural guarantees, one round's readback overlapping the next."""
    params, draft = models
    engine = _spec_engine(
        params, draft, DRAFT_CONFIG, temperature=0.8,
        rng=jax.random.PRNGKey(6), pipelined=True,
    )
    rids = [engine.submit([7, 8], 10) for _ in range(3)]
    served = engine.run()
    for rid in rids:
        assert len(served[rid]) == 10
    assert engine.ctrl.used_pages == 0


def test_sampling_spec_composes_with_lora(models):
    """spec x sampling x multi-LoRA: the adapted target's distributions
    drive acceptance; structural budgets hold per tenant."""
    from workloads.multi_lora import synthetic_adapters

    params, draft = models
    adapters = synthetic_adapters(CONFIG, 2, rank=4, scale=0.3, seed=3)
    engine = _spec_engine(
        params, draft, DRAFT_CONFIG, temperature=0.7,
        rng=jax.random.PRNGKey(9), adapters=adapters,
    )
    names = [None] + sorted(adapters)
    rids = [
        engine.submit([2, 4, 6], 8, adapter=names[i % 3]) for i in range(3)
    ]
    served = engine.run()
    for rid in rids:
        assert len(served[rid]) == 8
    assert engine.ctrl.used_pages == 0

"""Fleet serving & failover contracts (workloads/fleet.py): N
ServeEngine replicas behind the least-loaded/affinity router, each an
isolated fault domain.

The pinned contracts: exactly ONE terminal status per rid fleet-wide;
replica crash/hang (and HealthFanout Unhealthy drains) fail in-flight
work over to survivors via replay, with ok greedy streams bit-identical
to the single-engine dense oracle and interrupted streams true
prefixes; drains charge no failover budgets while true faults do; zero
slot/page/commitment leaks on survivors; graceful drain/remove and live
add; the HTTP/SSE front end streams real tokens; mixed-attribution
health streams drain exactly the affected replicas and can never strand
the whole fleet paused."""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
from tpu_device_plugin.device import HealthEvent
from workloads.errors import EngineClosed, InvalidRequest, QueueFull
from workloads.faults import REPLICA_SEAMS, FaultInjector
from workloads.fleet import (
    DEAD,
    DRAINING,
    Fleet,
    FleetServer,
    Router,
    TrafficGen,
    drive_open_loop,
)
from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
PARAMS = init_params(CONFIG, jax.random.PRNGKey(0))
TERMINAL = {"ok", "cancelled", "expired", "failed"}


def _engine(**kw):
    base = dict(slots=2, page_size=4, prompt_bucket=8)
    base.update(kw)
    return ServeEngine(PARAMS, CONFIG, **base)


def _fleet(n=2, *, engine_kw=None, **fleet_kw):
    fleet_kw.setdefault(
        "chip_ids", [f"chip-{i}" for i in range(n)]
    )
    # Wall-clock watchdog off by default: a loaded CI host's XLA compile
    # times must never read as replica hangs.  The watchdog has its own
    # dedicated test below.
    fleet_kw.setdefault("hang_timeout_s", None)
    return Fleet(
        [_engine(**(engine_kw or {})) for _ in range(n)], **fleet_kw
    )


def _oracle(prompt, new):
    return [int(t) for t in np.asarray(generate(
        PARAMS, jnp.asarray([prompt], jnp.int32), CONFIG,
        max_new_tokens=new,
    )[0])]


def _prompts(seed, n, lo=1, hi=20, new_lo=2, new_hi=16):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        plen = int(rng.integers(lo, hi))
        prompt = [int(t) for t in rng.integers(0, CONFIG.vocab_size, plen)]
        out.append((prompt, int(rng.integers(new_lo, new_hi))))
    return out


def _run_collecting(
    fleet, expected, *, max_steps=600, mid_step=None, terminal=None,
):
    """Step to convergence, asserting one terminal status per rid."""
    terminal = dict(terminal or {})
    steps = 0
    while not fleet.idle:
        steps += 1
        assert steps < max_steps, (fleet.states(), "failed to converge")
        if mid_step is not None:
            mid_step(steps)
        for fr in fleet.step():
            assert fr.rid not in terminal, (fr.rid, "double terminal")
            assert fr.status in TERMINAL, (fr.rid, fr.status)
            terminal[fr.rid] = fr.status
    assert set(terminal) >= set(expected), set(expected) - set(terminal)
    return terminal


def _assert_no_leaks(fleet):
    for rep in fleet.replicas:
        if rep.state == DEAD:
            continue
        e = rep.engine
        assert not e._occupied.any(), rep.index
        assert e._committed_pages == 0, rep.index
        assert not e._groups, rep.index
        pinned = e.prefix.cached_pages if e.prefix is not None else 0
        assert e.ctrl.used_pages == pinned, rep.index
        assert not rep.rids, rep.index


# ---- basic serving -------------------------------------------------------


def test_fleet_serves_bit_identical_to_dense_oracle():
    fleet = _fleet(2)
    reqs = _prompts(0, 6)
    rids = [fleet.submit(p, n) for p, n in reqs]
    served = fleet.run()
    for rid, (prompt, new) in zip(rids, reqs):
        assert served[rid] == _oracle(prompt, new), rid
    # Both replicas actually took work (least-loaded spreads).
    assert all(r.engine.requests_admitted > 0 for r in fleet.replicas)
    assert fleet.requests_ok == 6
    _assert_no_leaks(fleet)
    fleet.close()
    assert all(r.state == DEAD for r in fleet.replicas)


def test_router_least_loaded_and_session_affinity():
    router = Router(affinity_slack=8)
    fleet = _fleet(3, router=router)
    reqs = _prompts(1, 9, new_lo=4)
    # Three sessions, three requests each: affinity must pin a session
    # to one replica (slack is generous), least-loaded must spread the
    # three sessions across replicas.
    placed: dict[str, set[int]] = {}
    rids = {}
    for i, (p, n) in enumerate(reqs):
        sess = f"s{i % 3}"
        rid = fleet.submit(p, n, session=sess)
        rids[rid] = (p, n, sess)
    fleet.step()  # one dispatch pass places everything queued
    for rid, (_p, _n, sess) in rids.items():
        fr = fleet._reqs[rid]
        if fr.replica is not None:
            placed.setdefault(sess, set()).add(fr.replica)
    for sess, replicas in placed.items():
        assert len(replicas) == 1, (sess, replicas)
    assert len({next(iter(v)) for v in placed.values()}) == 3
    assert router.affinity_hits >= 6
    served = fleet.run()
    for rid, (p, n, _s) in rids.items():
        assert served.get(rid, fleet._reqs[rid].tokens) == _oracle(p, n)
    fleet.close()


def test_fleet_bounded_admission_and_validation():
    fleet = _fleet(1, max_pending=2)
    fleet.submit([1, 2], 2)
    fleet.submit([3, 4], 2)
    with pytest.raises(QueueFull):
        fleet.submit([5, 6], 2)
    assert fleet.queue_rejections == 1
    with pytest.raises(InvalidRequest):
        fleet.submit([1], 0)
    with pytest.raises(Exception):
        fleet.submit([], 2)
    rid = "dup"
    fleet.run()
    fleet.submit([1], 1, rid=rid)
    with pytest.raises(InvalidRequest):
        fleet.submit([2], 1, rid=rid)
    fleet.run()
    fleet.close()
    with pytest.raises(EngineClosed):
        fleet.submit([1], 1)
    with pytest.raises(EngineClosed):
        fleet.step()
    fleet.close()  # idempotent


def test_fleet_cancel_and_deadline_one_terminal_each():
    fleet = _fleet(2)
    reqs = _prompts(2, 5, new_lo=8, new_hi=16)
    rids = [fleet.submit(p, n) for p, n in reqs]
    # Cancel one while still router-queued, one after dispatch.
    assert fleet.cancel(rids[0]) is True
    early = {fr.rid: fr.status for fr in fleet.step()}
    assert fleet.cancel(rids[1]) is True
    assert fleet.cancel("nope") is False
    expired_rid = fleet.submit([7, 7, 7], 12, deadline_s=1e-4)
    time.sleep(0.002)
    terminal = _run_collecting(
        fleet, rids + [expired_rid], terminal=early
    )
    assert terminal[rids[0]] == "cancelled"
    assert terminal[rids[1]] == "cancelled"
    assert terminal[expired_rid] == "expired"
    for rid, (p, n) in list(zip(rids, reqs))[2:]:
        assert fleet._reqs[rid].tokens == _oracle(p, n)
    # Cancelling an already-terminal rid is a no-op, not a second status.
    assert fleet.cancel(rids[0]) is False
    _assert_no_leaks(fleet)
    fleet.close()


# ---- failover: crash / hang / slow --------------------------------------


def test_replica_crash_fails_over_bit_identically():
    """The headline acceptance contract: N=4 under the open-loop
    generator, a replica crash mid-stream — every rid one terminal
    status, ok streams bit-identical to the single-engine oracle,
    survivors leak-free, recovery latency recorded."""
    n = 4
    # Crossing 2n+1 = fleet step 3, replica 0 — mid-stream, in-flight.
    injector = FaultInjector({"replica_crash": 2 * n + 1})
    fleet = _fleet(n, fault_injector=injector, max_failovers=2)
    reqs = _prompts(3, 12, lo=4, hi=20, new_lo=8, new_hi=16)
    rids = [fleet.submit(p, nw) for p, nw in reqs]
    terminal = _run_collecting(fleet, rids)
    assert fleet.replica_crashes == 1
    assert fleet.replicas[0].state == DEAD
    assert fleet.failover_requeues >= 1
    assert len(fleet.failover_recovery_s) == 1
    assert fleet.failover_recovery_s[0] > 0
    for rid, (p, nw) in zip(rids, reqs):
        fr = fleet._reqs[rid]
        ref = _oracle(p, nw)
        if terminal[rid] == "ok":
            assert fr.tokens == ref, (rid, fr.failovers, fr.segments)
        else:
            assert fr.tokens == ref[: len(fr.tokens)], rid
    # At least one ok stream actually crossed the failover (segments>1).
    crossed = [
        r for r in rids
        if fleet._reqs[r].segments > 1 and terminal[r] == "ok"
    ]
    assert crossed, "crash failed over no in-flight request"
    _assert_no_leaks(fleet)
    fleet.close()


def test_replica_hang_counts_separately_and_fails_over():
    injector = FaultInjector({"replica_hang": 3})  # step 2, replica 0
    fleet = _fleet(2, fault_injector=injector)
    reqs = _prompts(4, 6, new_lo=6)
    rids = [fleet.submit(p, n) for p, n in reqs]
    terminal = _run_collecting(fleet, rids)
    assert fleet.replica_hangs == 1 and fleet.replica_crashes == 0
    assert fleet.replicas[0].state == DEAD
    for rid, (p, n) in zip(rids, reqs):
        if terminal[rid] == "ok":
            assert fleet._reqs[rid].tokens == _oracle(p, n)
    _assert_no_leaks(fleet)
    fleet.close()


def test_slow_auto_drain_never_takes_the_last_dispatchable_replica():
    """A 1-replica fleet under persistent replica_slow must keep
    serving degraded — auto-draining the only dispatchable replica
    would park the queue forever."""
    injector = FaultInjector({"replica_slow": [1, 2, 3, 4, 5, 6]})
    fleet = _fleet(
        1, fault_injector=injector, slow_readback_s=0.0,
        slow_drain_after=2,
    )
    reqs = _prompts(13, 4, new_lo=4)
    rids = [fleet.submit(p, n) for p, n in reqs]
    terminal = _run_collecting(fleet, rids)
    assert fleet.replicas[0].state == "active"
    assert all(terminal[r] == "ok" for r in rids)
    _assert_no_leaks(fleet)
    fleet.close()


def test_harvested_complete_stream_finishes_ok_not_replayed():
    """A replica dying between emitting a stream's last token and
    retiring the request leaves a bit-complete harvest: the fleet must
    finish it 'ok' — a zero-budget replay would InvalidRequest a
    stream the client already received in full."""
    from workloads.fleet import FleetRequest

    fleet = _fleet(2)
    fr = FleetRequest(rid="r-done", prompt=[1, 2], max_new_tokens=3)
    fr.tokens = [5, 6, 7]  # complete
    fleet._reqs[fr.rid] = fr
    finished = fleet._requeue_victims([fr], charge=True)
    assert [f.rid for f in finished] == ["r-done"]
    assert fr.status == "ok" and fr.failovers == 0
    assert not fleet.queue
    # EOS-terminated harvest counts as complete too.
    fr2 = FleetRequest(
        rid="r-eos", prompt=[1], max_new_tokens=8, eos_token=9,
    )
    fr2.tokens = [4, 9]
    fleet._reqs[fr2.rid] = fr2
    finished = fleet._requeue_victims([fr2], charge=True)
    assert fr2.status == "ok" and not fleet.queue
    fleet.close()


def test_replica_slow_auto_drains_not_kills():
    injector = FaultInjector(
        {"replica_slow": [1, 3, 5]}  # replica 0's first three steps
    )
    fleet = _fleet(
        2, fault_injector=injector, slow_readback_s=0.0,
        slow_drain_after=3,
    )
    reqs = _prompts(5, 6, new_lo=6)
    rids = [fleet.submit(p, n) for p, n in reqs]
    terminal = _run_collecting(fleet, rids)
    assert fleet.replicas[0].state == DRAINING  # degraded, not dead
    assert fleet.replica_crashes == 0 and fleet.replica_hangs == 0
    assert all(terminal[r] == "ok" for r in rids)
    for rid, (p, n) in zip(rids, reqs):
        assert fleet._reqs[rid].tokens == _oracle(p, n)
    # A drained replica takes no new work until resumed.
    admitted0 = fleet.replicas[0].engine.requests_admitted
    rid = fleet.submit([9, 9], 4)
    fleet.step()
    assert fleet.replicas[0].engine.requests_admitted == admitted0
    assert fleet._reqs[rid].status in ("running", "ok")
    fleet.resume(0)
    assert fleet.replicas[0].state == "active"
    fleet.run()
    _assert_no_leaks(fleet)
    fleet.close()


def test_hang_watchdog_exempts_warmup_and_kills_wedged_steps():
    """The wall-clock watchdog must not mistake one-time XLA compiles
    for a wedge (a replica's FIRST step is exempt), but a genuinely
    wedged later step kills the replica and fails its work over."""
    # Warm-up exemption: first steps are compile-dominated and far
    # exceed a tight timeout, yet no replica may die for it.
    fleet = _fleet(2, hang_timeout_s=0.05)
    for p, n in _prompts(11, 4, new_lo=4):
        fleet.submit(p, n)
    fleet.step()
    assert fleet.replica_hangs == 0
    assert all(r.state != DEAD for r in fleet.replicas)
    fleet.close()

    # Kill path: compiles warmed off the clock, then one wedged step.
    # (A failover replay can compile a fresh prefill bucket on the
    # survivor, which a tight watchdog may legitimately also count as
    # a hang — so replica 0's death is pinned exactly, the rest of the
    # fleet's fate only via the lifecycle invariants.)
    fleet = _fleet(2, hang_timeout_s=None)
    for p, n in _prompts(11, 4, new_lo=4):
        fleet.submit(p, n)
    fleet.run()
    fleet.drain_completed()
    fleet.hang_timeout_s = 0.5
    real_step = fleet.replicas[0].engine.step

    def wedged_step():
        time.sleep(0.8)
        return real_step()

    fleet.replicas[0].engine.step = wedged_step
    reqs = _prompts(12, 4, new_lo=6)
    rids = [fleet.submit(p, n) for p, n in reqs]
    terminal = _run_collecting(fleet, rids)
    assert fleet.replica_hangs >= 1 and fleet.replica_crashes == 0
    assert fleet.replicas[0].state == DEAD
    for rid, (p, n) in zip(rids, reqs):
        fr, ref = fleet._reqs[rid], _oracle(p, n)
        if terminal[rid] == "ok":
            assert fr.tokens == ref, rid
        else:
            assert fr.tokens == ref[: len(fr.tokens)], rid
    if fleet.replicas[1].state != DEAD:
        assert any(s == "ok" for s in terminal.values())
        _assert_no_leaks(fleet)
    fleet.close()


def test_failover_budget_exhaustion_fails_terminally():
    """Every replica dies: requests charged past max_failovers (or left
    with no live replica) fail terminally — never spin, never double."""
    injector = FaultInjector({"replica_crash": [3, 4]})  # step 2: both die
    fleet = _fleet(2, fault_injector=injector, max_failovers=1)
    reqs = _prompts(6, 4, new_lo=8)
    rids = [fleet.submit(p, n) for p, n in reqs]
    terminal = _run_collecting(fleet, rids)
    assert all(r.state == DEAD for r in fleet.replicas)
    assert set(terminal.values()) <= {"failed", "ok"}
    assert any(s == "failed" for s in terminal.values())
    for rid, (p, n) in zip(rids, reqs):
        fr = fleet._reqs[rid]
        ref = _oracle(p, n)
        assert fr.tokens == ref[: len(fr.tokens)], rid  # true prefix
    fleet.close()


# ---- health: fleet-scope HealthFanout contracts --------------------------


def test_health_drain_is_uncharged_and_bit_identical():
    """A HealthFanout Unhealthy on one chip drains exactly that
    replica: its work fails over to survivors WITHOUT charging
    failover budgets, streams stay oracle-identical, and the replica
    resumes on recovery."""
    fleet = _fleet(2)
    reqs = _prompts(7, 6, new_lo=8, new_hi=16)
    rids = [fleet.submit(p, n) for p, n in reqs]
    early = {fr.rid: fr.status for fr in fleet.step()}  # dispatch + step
    assert any(r for r in fleet.replicas[0].rids)
    fleet.deliver_health([HealthEvent(chip_id="chip-0", health=UNHEALTHY)])
    early.update((fr.rid, fr.status) for fr in fleet.step())
    assert fleet.replicas[0].paused
    assert fleet.replicas[1].dispatchable
    # Drained, not charged: requeues counted on the drain side only.
    assert fleet.failover_requeues == 0
    assert fleet.drain_requeues >= 1
    assert not fleet.replicas[0].rids  # nothing stranded on the sick one
    terminal = _run_collecting(fleet, rids, terminal=early)
    assert all(terminal[r] == "ok" for r in rids)
    for rid, (p, n) in zip(rids, reqs):
        assert fleet._reqs[rid].tokens == _oracle(p, n), rid
    assert fleet.replicas[0].state == "active"  # drained, never dead
    # Recovery: the replica serves again.
    fleet.deliver_health([HealthEvent(chip_id="chip-0", health=HEALTHY)])
    rid = fleet.submit([3, 1], 4, session=None)
    fleet.run()
    assert not fleet.replicas[0].paused
    assert fleet._reqs[rid].status == "ok"
    _assert_no_leaks(fleet)
    fleet.close()


def test_health_mixed_attribution_never_strands_the_fleet():
    """The PR-4 all-chips contract at N engines: per-chip events drain
    exactly the named replica; an unattributed Unhealthy pauses every
    replica (work parks in place — nowhere to fail over to); a
    per-chip Healthy cannot clear the unattributed mark; the
    unattributed all-clear lifts every mark on every replica."""
    fleet = _fleet(3)
    # Long enough that nothing can finish before the fleet-wide pause
    # (>= 14 tokens needs >= 4 decode chunks; only 2 steps run first).
    reqs = _prompts(8, 6, new_lo=14, new_hi=16)
    rids = [fleet.submit(p, n) for p, n in reqs]
    fleet.step()
    # Per-chip: only replica 1 pauses.
    fleet.deliver_health([HealthEvent(chip_id="chip-1", health=UNHEALTHY)])
    fleet.step()
    assert [r.paused for r in fleet.replicas] == [False, True, False]
    # Unattributed Unhealthy: everyone pauses; nothing bounces (no
    # dispatchable survivor) and nothing reaches a terminal status.
    fleet.deliver_health([HealthEvent(chip_id="", health=UNHEALTHY)])
    drains_before = fleet.drain_requeues
    fleet.step()
    fleet.step()
    assert all(r.paused for r in fleet.replicas)
    assert fleet.drain_requeues == drains_before
    assert not any(fleet._reqs[r].done for r in rids)
    # A per-chip recovery cannot clear the unattributed mark.
    fleet.deliver_health([HealthEvent(chip_id="chip-0", health=HEALTHY)])
    fleet.step()
    assert all(r.paused for r in fleet.replicas)
    # The unattributed all-clear lifts every mark — fleet-wide resume.
    fleet.deliver_health([HealthEvent(chip_id="", health=HEALTHY)])
    terminal = _run_collecting(fleet, rids)
    assert not any(r.paused for r in fleet.replicas)
    assert all(terminal[r] == "ok" for r in rids)
    for rid, (p, n) in zip(rids, reqs):
        assert fleet._reqs[rid].tokens == _oracle(p, n), rid
    assert fleet.replica_crashes == 0 and fleet.failover_requeues == 0
    _assert_no_leaks(fleet)
    fleet.close()


def test_health_events_via_fanout_subscription():
    """bind_health routes a real fanout-shaped subscription through the
    same per-chip delivery (duck-typed fanout: subscribe/unsubscribe)."""
    import queue as _queue

    class _FakeFanout:
        def __init__(self):
            self.q = _queue.Queue()
            self.unsubscribed = False

        def subscribe(self):
            return self.q

        def unsubscribe(self, q):
            self.unsubscribed = True

    fanout = _FakeFanout()
    fleet = _fleet(2)
    fleet.bind_health(fanout)
    rid = fleet.submit(list(range(1, 6)), 8)
    fleet.step()
    fanout.q.put(HealthEvent(chip_id="chip-0", health=UNHEALTHY))
    fleet.step()
    assert fleet.replicas[0].paused and not fleet.replicas[1].paused
    fanout.q.put(HealthEvent(chip_id="chip-0", health=HEALTHY))
    fleet.run()
    assert fleet._reqs[rid].status == "ok"
    fleet.close()
    assert fanout.unsubscribed


# ---- membership: drain / remove / add -----------------------------------


def test_graceful_drain_remove_and_live_add():
    fleet = _fleet(2)
    reqs = _prompts(9, 6, new_lo=8)
    rids = [fleet.submit(p, n) for p, n in reqs]
    fleet.step()
    fleet.drain(0)
    assert fleet.replicas[0].state == DRAINING
    with pytest.raises(RuntimeError):
        fleet.remove(0)  # still holds work, not forced
    # In-flight work finishes ON the draining replica (graceful).
    on_drained = set(fleet.replicas[0].rids)
    terminal = _run_collecting(fleet, rids)
    assert all(terminal[r] == "ok" for r in rids)
    assert fleet.drain_requeues == 0 and fleet.failover_requeues == 0
    assert on_drained  # it really had work to finish
    fleet.remove(0)
    assert fleet.replicas[0].state == DEAD
    assert fleet.replicas[0].engine.closed
    # Live add: a fresh engine joins and takes work immediately.
    idx = fleet.add_replica(_engine(), chip_id="chip-2")
    assert idx == 2
    more = _prompts(10, 4, new_lo=4)
    rids2 = [fleet.submit(p, n) for p, n in more]
    fleet.run()
    assert fleet.replicas[2].engine.requests_admitted > 0
    for rid, (p, n) in zip(rids2, more):
        assert fleet._reqs[rid].status == "ok"
        assert fleet._reqs[rid].tokens == _oracle(p, n)
    _assert_no_leaks(fleet)
    fleet.close()


def test_forced_remove_fails_over_uncharged():
    fleet = _fleet(2)
    reqs = _prompts(11, 5, new_lo=10, new_hi=16)
    rids = [fleet.submit(p, n) for p, n in reqs]
    fleet.step()
    assert fleet.replicas[0].rids  # it holds in-flight work
    fleet.remove(0, force=True)
    assert fleet.replicas[0].state == DEAD
    terminal = _run_collecting(fleet, rids)
    assert all(terminal[r] == "ok" for r in rids)
    assert fleet.failover_requeues == 0  # operator action: uncharged
    assert fleet.drain_requeues >= 1
    for rid, (p, n) in zip(rids, reqs):
        assert fleet._reqs[rid].tokens == _oracle(p, n), rid
    _assert_no_leaks(fleet)
    fleet.close()


def test_engine_withdraw_is_statusless():
    """The router's reclaim seam on the engine: a withdrawn pending
    request keeps its lifecycle open (no terminal status, no counter),
    while running requests refuse to withdraw."""
    engine = _engine()
    rid_q = engine.submit([1, 2, 3], 4)
    rid_run = engine.submit([4, 5], 4)
    req = engine.withdraw(rid_q)
    assert req is not None and req.rid == rid_q
    assert req.status == "queued" and not req.done
    assert engine.requests_cancelled == 0
    assert len(engine.completed) == 0
    engine.step()  # rid_run admits
    assert engine.withdraw(rid_run) is None
    assert engine.withdraw("unknown") is None
    engine.run()
    engine.close()
    with pytest.raises(EngineClosed):
        engine.withdraw("x")


# ---- traffic generator and open-loop drive ------------------------------


def test_trafficgen_is_seeded_bursty_and_heavy_tailed():
    gen = TrafficGen(seed=3, rate_rps=200.0, max_prompt=24, vocab=64)
    a, b = gen.schedule(200), gen.schedule(200)
    assert a == b  # deterministic per seed
    assert a != TrafficGen(seed=4, rate_rps=200.0, vocab=64).schedule(200)
    offsets = [t for t, _, _ in a]
    assert offsets == sorted(offsets)
    plens = [len(p) for _, p, _ in a]
    # Heavy tail: mass at the floor AND excursions to the cap.
    assert min(plens) == 1 and max(plens) == 24
    assert sorted(plens)[len(plens) // 2] < 8
    gaps = [b - a for a, b in zip(offsets, offsets[1:])]
    # Bursty: the largest gap dwarfs the median one.
    assert max(gaps) > 5 * sorted(gaps)[len(gaps) // 2]
    for _, p, n in a:
        assert all(0 <= t < 64 for t in p)
        assert 1 <= n <= gen.max_new


def test_open_loop_drive_serves_the_schedule():
    fleet = _fleet(2)
    gen = TrafficGen(
        seed=1, rate_rps=500.0, max_prompt=16, max_new=8,
        vocab=CONFIG.vocab_size,
    )
    served = drive_open_loop(
        fleet, gen.schedule(10), session_every=3
    )
    assert len(served) == 10
    assert fleet.requests_ok == 10
    by_rid = {fr.rid: fr for fr in fleet.completed}
    for rid, tokens in served.items():
        fr = by_rid[rid]
        assert tokens == _oracle(fr.prompt, fr.max_new_tokens), rid
    _assert_no_leaks(fleet)
    fleet.close()


# ---- HTTP/SSE front end --------------------------------------------------


def test_sse_front_end_streams_real_tokens():
    import urllib.error
    import urllib.request

    fleet = _fleet(2)
    server = FleetServer(fleet, 0)
    port = server.start()
    try:
        prompt, new = [5, 4, 3, 2, 1], 7
        body = json.dumps({
            "prompt": prompt, "max_new_tokens": new, "session": "s1",
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        tokens, final = [], None
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers["Content-Type"] == "text/event-stream"
            for line in resp:
                if not line.startswith(b"data: "):
                    continue
                ev = json.loads(line[6:])
                if ev.get("done"):
                    final = ev
                    break
                tokens.extend(ev["tokens"])
        assert final is not None and final["status"] == "ok"
        assert final["n_tokens"] == len(tokens)
        assert tokens == _oracle(prompt, new)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as resp:
            health = json.loads(resp.read())
        assert health["ok"] is True
        assert set(health["replicas"]) == {"0", "1"}
        assert all(
            v["state"] == "active" for v in health["replicas"].values()
        )
        # Validation maps to 400, not a wedged stream.
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=json.dumps({"prompt": []}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(bad, timeout=10)
        assert err.value.code == 400
    finally:
        server.stop()
        fleet.close()


# ---- chaos smoke (make fleet-check) -------------------------------------


def _run_fleet_chaos(seed: int) -> None:
    """One seeded chaos round: open-loop-style traffic over N=2..4
    replicas with randomized replica crashes/hangs/slow steps, engine
    seam faults, health drains, cancels and deadlines — the lifecycle
    invariants must hold throughout."""
    rng = np.random.default_rng(seed + 9000)
    n = int(rng.integers(2, 5))
    fleet_inj = FaultInjector.random(
        seed=seed, rate=0.03, seams=REPLICA_SEAMS,
        max_fires=int(rng.integers(1, n)),  # never kills every replica
    )
    engines = []
    for i in range(n):
        eng_inj = (
            FaultInjector.random(seed=seed * 7 + i, rate=0.02, max_fires=2)
            if rng.integers(2) else None
        )
        engines.append(_engine(
            slots=int(rng.integers(1, 3)),
            prefix_cache=bool(rng.integers(2)),
            pipelined=bool(rng.integers(2)),
            fault_injector=eng_inj, max_retries=2,
        ))
    fleet = Fleet(
        engines, chip_ids=[f"chip-{i}" for i in range(n)],
        fault_injector=fleet_inj, max_failovers=2,
        slow_readback_s=0.0,
        # Deterministic chaos: hangs come from the injected seam, not
        # the load-dependent wall-clock watchdog.
        hang_timeout_s=None,
    )
    expected = {}
    for p, nw in _prompts(seed, int(rng.integers(5, 9)), new_lo=2):
        deadline = 0.05 if rng.integers(6) == 0 else None
        sess = f"s{int(rng.integers(3))}" if rng.integers(2) else None
        try:
            rid = fleet.submit(p, nw, deadline_s=deadline, session=sess)
        except QueueFull:
            continue
        expected[rid] = (p, nw)

    def mid(step):
        live = [r for r in expected if not fleet._reqs[r].done]
        if live and rng.integers(10) == 0:
            fleet.cancel(str(rng.choice(live)))
        if rng.integers(12) == 0:
            alive = fleet.alive
            if len(alive) > 1:
                fleet.deliver_health([HealthEvent(
                    chip_id=alive[0].chip_id, health=UNHEALTHY,
                )])
        if rng.integers(12) == 0:
            fleet.deliver_health([HealthEvent(chip_id="", health=HEALTHY)])

    terminal = _run_collecting(fleet, expected, mid_step=mid)
    assert set(terminal) == set(expected)
    for rid, (p, nw) in expected.items():
        fr = fleet._reqs[rid]
        ref = _oracle(p, nw)
        if terminal[rid] == "ok":
            assert fr.tokens == ref, (seed, rid, fr.failovers)
        else:
            assert fr.tokens == ref[: len(fr.tokens)], (seed, rid)
    _assert_no_leaks(fleet)
    fleet.close()


def test_fleet_chaos_smoke():
    """ONE cheap seeded chaos round — the `make fleet-check` smoke."""
    _run_fleet_chaos(1)


# ---- capacity-aware admission: dispatchable semantics (PR 13) ------------


def test_admission_bound_counts_dispatchable_replicas_only():
    """The capacity-aware bound scales with replicas that accept NEW
    work — DRAINING and health-paused replicas still finish in-flight
    work but buy no fresh queue budget, and the QueueFull message
    names the dispatchable count (the autoscaler's brownout builds on
    this bound)."""
    fleet = _fleet(3, max_pending_per_replica=2)
    assert fleet.dispatchable_count == 3
    assert fleet.admission_bound == 6
    # A drain drops the bound immediately...
    fleet.drain(2)
    assert fleet.dispatchable_count == 2
    assert fleet.admission_bound == 4
    # ...and so does a health pause (previously only deaths did).
    fleet.deliver_health([
        HealthEvent(chip_id="chip-1", health=UNHEALTHY)
    ])
    fleet.step()
    assert fleet.replicas[1].paused
    assert fleet.dispatchable_count == 1
    assert fleet.admission_bound == 2
    fleet.submit([1, 2], 2)
    fleet.submit([3, 4], 2)
    with pytest.raises(QueueFull) as exc:
        fleet.submit([5, 6], 2)
    msg = str(exc.value)
    assert "capacity-aware" in msg
    assert "1 dispatchable" in msg
    # Recovery on both axes restores the bound.
    fleet.deliver_health([HealthEvent(chip_id="", health=HEALTHY)])
    fleet.step()
    fleet.resume(2)
    assert fleet.admission_bound == 6
    fleet.run()
    _assert_no_leaks(fleet)
    fleet.close()


def test_admission_factor_tightens_any_bound_never_unbounded():
    """The brownout knob: a factor < 1 tightens static and
    capacity-aware bounds alike (floored at 1), and an unbounded
    fleet stays unbounded — there is nothing to tighten."""
    fleet = _fleet(2, max_pending=8)
    assert fleet.admission_bound == 8
    fleet.admission_factor = 0.5
    assert fleet.admission_bound == 4
    fleet.admission_factor = 0.01
    assert fleet.admission_bound == 1  # never zero
    fleet.close()
    unbounded = _fleet(1)
    unbounded.admission_factor = 0.5
    assert unbounded.admission_bound is None
    unbounded.close()


# ---- TrafficGen step-load / ramp schedules (PR 13) -----------------------


def test_trafficgen_step_schedule_is_seeded_and_compresses_the_window():
    gen = TrafficGen(seed=3, rate_rps=100.0, max_prompt=24, vocab=64)
    base = gen.schedule(200)
    span = base[-1][0]
    profile = TrafficGen.step_profile(0.25 * span, 0.25 * span, 4.0)
    a = gen.schedule(200, profile)
    b = gen.schedule(200, profile)
    # Bit-identical across runs for a fixed seed.
    assert a == b
    assert a != TrafficGen(
        seed=4, rate_rps=100.0, max_prompt=24, vocab=64
    ).schedule(200, profile)
    # Prompts and budgets are PROFILE-INDEPENDENT: only arrival times
    # move (the rng draw sequence never forks).
    assert [(p, n) for _, p, n in a] == [(p, n) for _, p, n in base]
    offsets = [t for t, _, _ in a]
    assert offsets == sorted(offsets)
    # The x4 window really compresses arrivals: the spike's mean gap
    # is well under the calm prefix's.
    lo, hi = 0.25 * span, 0.5 * span
    in_win = [t for t in offsets if lo <= t < hi]
    before = [t for t in offsets if t < lo]
    assert len(in_win) >= 3 and len(before) >= 3

    def mean_gap(ts):
        return (ts[-1] - ts[0]) / max(1, len(ts) - 1)

    assert mean_gap(in_win) < 0.6 * mean_gap(before), (
        mean_gap(in_win), mean_gap(before),
    )
    # Validation is loud.
    with pytest.raises(ValueError):
        TrafficGen.step_profile(0.0, 0.0, 4.0)
    with pytest.raises(ValueError):
        TrafficGen.step_profile(0.0, 1.0, 0.0)
    with pytest.raises(ValueError):
        gen.schedule(5, lambda t: 0.0)


def test_trafficgen_ramp_schedule_monotonically_tightens_gaps():
    gen = TrafficGen(
        seed=5, rate_rps=50.0, burst_factor=1.0, max_prompt=8, vocab=64,
    )
    base = gen.schedule(300)
    span = base[-1][0]
    ramp = TrafficGen.ramp_profile(0.0, span, 8.0)
    a = gen.schedule(300, ramp)
    assert a == gen.schedule(300, ramp)  # seeded determinism
    offsets = [t for t, _, _ in a]
    thirds = len(offsets) // 3
    first = offsets[thirds - 1] - offsets[0]
    last = offsets[-1] - offsets[-thirds]
    # The same arrival count takes much less time at the ramp's top.
    assert last < first, (first, last)
    with pytest.raises(ValueError):
        TrafficGen.ramp_profile(0.0, 0.0, 2.0)
    with pytest.raises(ValueError):
        TrafficGen.ramp_profile(0.0, 1.0, 0.0)


def test_trafficgen_classed_schedules_preserve_mix_under_rate_changes():
    """The class draw is positional on its own rng: a step or ramp
    profile changes arrival TIMES, never the class sequence — so the
    autoscaler bench's spike serves exactly the calm trace's class
    assignment."""
    gen = TrafficGen(seed=11, rate_rps=100.0, max_prompt=16, vocab=64)
    calm = gen.schedule_classed(150)
    span = calm[-1][0]
    profile = TrafficGen.step_profile(0.2 * span, 0.3 * span, 4.0)
    stepped = gen.schedule_classed(150, profile)
    assert [c for _, _, _, c in stepped] == [c for _, _, _, c in calm]
    assert [(p, n) for _, p, n, _ in stepped] == [
        (p, n) for _, p, n, _ in calm
    ]
    # And the mix respects the configured weights (3:1 default).
    counts = TrafficGen.schedule_stats(stepped)["class_counts"]
    assert set(counts) == {"interactive", "bulk"}
    assert counts["interactive"] > counts["bulk"]


def test_trafficgen_schedule_stats_report():
    gen = TrafficGen(seed=7, rate_rps=200.0, max_prompt=12, vocab=64)
    sched = gen.schedule(100)
    stats = TrafficGen.schedule_stats(sched, window_s=0.5)
    assert stats["arrivals"] == 100
    assert stats["span_s"] > 0
    assert stats["mean_rps"] > 0
    assert stats["peak_rps"] >= stats["mean_rps"] * 0.5
    assert stats["prompt_tokens"] == sum(len(p) for _, p, _ in sched)
    assert stats["budget_tokens"] == sum(n for _, _, n in sched)
    assert "class_counts" not in stats  # unclassed schedule
    assert TrafficGen.schedule_stats([])["arrivals"] == 0
    with pytest.raises(ValueError):
        TrafficGen.schedule_stats(sched, window_s=0.0)


# ---- FleetServer operator endpoints (PR 13) ------------------------------


def _post(port, path):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=b"", method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_fleet_server_operator_drain_undrain_over_http():
    fleet = _fleet(2)
    server = FleetServer(fleet, 0)
    port = server.start()
    try:
        code, body = _post(port, "/drain/1")
        assert code == 200 and body["state"] == DRAINING
        assert fleet.replicas[1].state == DRAINING
        code, body = _post(port, "/undrain/1")
        assert code == 200 and body["state"] == "active"
        assert fleet.replicas[1].state == "active"
        # Bad inputs answer, loudly, without killing the handler.
        code, _ = _post(port, "/drain/9")
        assert code == 404
        code, _ = _post(port, "/drain/x")
        assert code == 400
        # No supervisor: /clear is a conflict, not a crash.
        code, body = _post(port, "/clear/chip-0")
        assert code == 409 and "supervisor" in body["error"]
    finally:
        server.stop()
        fleet.close()


def test_fleet_server_clear_lifts_quarantine_over_http():
    from workloads.backoff import Backoff
    from workloads.supervisor import FleetSupervisor, make_engine_factory

    fleet = _fleet(2)
    factory, oracle = make_engine_factory(
        PARAMS, CONFIG, engine_kw=dict(slots=2, page_size=4, prompt_bucket=8),
        probe=([1, 2, 3], 4),
    )
    sup = FleetSupervisor(
        fleet, factory, backoff=Backoff(base_s=1e-3, max_s=8e-3, jitter=0.0),
        probe=([1, 2, 3], 4), probe_oracle=oracle,
    )
    sup.quarantine("chip-1", reason="operator test")
    server = FleetServer(fleet, 0, supervisor=sup)
    port = server.start()
    try:
        code, _ = _post(port, "/clear/nope")
        assert code == 404
        code, body = _post(port, "/clear/chip-1")
        assert code == 200
        assert sup.slot_for("chip-1").state != "quarantined"
    finally:
        server.stop()
        fleet.close()


# ---- dispatch_score: the one routing seam --------------------------------


def test_dispatch_score_pins_both_pre_unification_views():
    """Replica.dispatch_score IS the router's scalar: the request-count
    view must equal load() exactly, and the page-scheduled view
    page_load() + goodput_penalty() — pinned so the unification can
    never drift from the two pre-existing scoring paths."""
    from workloads.ledger import ChipTimeLedger

    fleet = _fleet(2)
    for p, n in _prompts(5, 4, new_lo=6, new_hi=10):
        fleet.submit(p, n)
    fleet.step()  # dispatch + begin prefill: non-trivial loads
    for rep in fleet.replicas:
        assert rep.dispatch_score() == rep.load()
        assert rep.goodput_penalty() == 0  # no ledger armed: no bias
        assert rep.dispatch_score(page_scheduling=True) == rep.page_load()
    # A ledger mid-burn adds its handicap to the PAGE view only.
    led = ChipTimeLedger(name="x")
    led.tokens_accounted = 100
    led.goodput_tokens = 25
    rep = fleet.replicas[0]
    rep.engine.ledger = led
    assert rep.goodput_penalty() == 3  # (1 - 0.25) * 4 penalty pages
    assert rep.dispatch_score(page_scheduling=True) == (
        rep.page_load() + 3
    )
    assert rep.dispatch_score() == rep.load()  # request view unbiased
    fleet.run()
    fleet.close()


def test_goodput_penalty_steers_marginal_dispatch_to_clean_replica():
    """Page-scheduled routing reads the ledger: with otherwise-equal
    page loads, the replica burning its chip-time on waste carries the
    handicap and LOSES the marginal dispatch it would have tie-won by
    index — and the stream itself is unaffected."""
    from workloads.ledger import ChipTimeLedger

    fleet = _fleet(2, page_scheduling=True)
    wasteful = fleet.replicas[0].engine
    wasteful.ledger = ChipTimeLedger(name="w")
    wasteful.ledger.tokens_accounted = 100  # zero goodput: full handicap
    rid = fleet.submit([1, 2, 3], 24)
    fleet.step()
    assert fleet._reqs[rid].replica == 1, "handicap ignored by router"
    out = fleet.run()
    assert out[rid] == _oracle([1, 2, 3], 24)
    fleet.close()

import os
import sys

# Run all JAX-touching tests on a virtual 8-device CPU mesh so sharding logic
# is exercised without TPU hardware.  The interpreter may preload jax with a
# TPU platform latched from the environment (sitecustomize), so setting env
# vars is not enough — update the live config before any backend initialises.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: XLA_FLAGS alone handles it
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

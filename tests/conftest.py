import os
import sys

# Run all JAX-touching tests on a virtual 8-device CPU mesh so sharding logic
# is exercised without TPU hardware.  The interpreter may preload jax with a
# TPU platform latched from the environment (sitecustomize), so setting env
# vars is not enough — update the live config before any backend initialises.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: XLA_FLAGS alone handles it
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import re  # noqa: E402

import pytest  # noqa: E402

# Test modules that compile JAX programs are dominated by XLA compile time
# (~12 min CPU for the full slice) and carry the `slow` marker, so `make test`
# (-m "not slow") stays the sub-minute daemon suite; CI and `make test-all`
# run everything.  Classification is by content — a module that imports jax or
# the workloads package is slow — so new workload tests are picked up without
# maintaining a name list.
_FAST_DESPITE_JAX = {
    # Drives subprocess pods with tiny matmul kernels; wall time is seconds.
    "test_oversubscribe",
    # Pure host-side control-plane properties (PagePool/PrefixCache):
    # imports workloads.paged but never traces a jax program.
    "test_paged_properties",
    # Metrics-name lint + exposition-format parsing: imports
    # workloads.obs (deliberately jax-free) and scans source text.
    "test_metrics_lint",
    # Daemon lifecycle against the fake kubelet: imports
    # workloads.backoff (deliberately jax-free) for the restart-backoff
    # pin; never traces a jax program.
    "test_daemon",
    # Chip-time-ledger attribution + flight-recorder/postmortem units:
    # imports workloads.ledger (deliberately jax-free) and drives it
    # with fake engines; never traces a jax program.
    "test_postmortem",
    # Device-time table + regression-sentry units and the trace-lane
    # validator regressions: imports workloads.profiler (deliberately
    # jax-free) and drives fake engines; the real jax.profiler capture
    # smoke lives in test_profile_capture.py (slow / profile-check).
    "test_profiler",
    # Goodput-controller hill-climb/hysteresis/WFQ units +
    # FleetLedger.class_economics: imports workloads.control and
    # workloads.ledger (both deliberately jax-free) and drives fake
    # engines; the real-engine retune transitions live in
    # test_control.py (slow / control-check).
    "test_control_units",
}
_JAX_IMPORT_RE = re.compile(r"^\s*(?:import|from)\s+(?:jax|workloads)\b", re.MULTILINE)
_slow_file_cache: dict[str, bool] = {}


def _is_slow_module(path: str) -> bool:
    cached = _slow_file_cache.get(path)
    if cached is None:
        try:
            with open(path, encoding="utf-8") as f:
                cached = bool(_JAX_IMPORT_RE.search(f.read()))
        except OSError:
            cached = False
        _slow_file_cache[path] = cached
    return cached


def pytest_collection_modifyitems(config, items):
    for item in items:
        name = item.module.__name__.rsplit(".", 1)[-1]
        if name not in _FAST_DESPITE_JAX and _is_slow_module(str(item.fspath)):
            item.add_marker(pytest.mark.slow)


# Hang watchdog: the tier-1 driver kills a silent suite at its timeout
# and all diagnosis is lost.  faulthandler dumps every thread's stack to
# stderr shortly BEFORE that deadline instead (repeat=False: one dump,
# then the run continues to its natural timeout), so a wedged test —
# a deadlocked health-fanout thread, a stuck device readback — leaves
# its stacks behind.  TEST_WATCHDOG_SECS overrides; 0 disables.
def pytest_configure(config):
    import faulthandler

    try:
        secs = float(os.environ.get("TEST_WATCHDOG_SECS", "780"))
    except ValueError:
        secs = 780.0
    if secs > 0:
        faulthandler.dump_traceback_later(secs, repeat=False, file=sys.stderr)


def pytest_unconfigure(config):
    import faulthandler

    faulthandler.cancel_dump_traceback_later()

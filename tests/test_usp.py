"""2D sequence parallelism (Ulysses x ring) vs dense attention, and vs each
1D formulation, on meshes carved from the 8-device CPU pool."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from workloads.ops.usp import usp_attention

from .test_flash_attention import make_qkv, naive_attention


def mesh_2d(n_ring, n_uly, extra=None):
    n = n_ring * n_uly * (extra or 1)
    devices = np.array(jax.devices()[:n])
    if extra:
        return Mesh(
            devices.reshape(extra, n_ring, n_uly), ("data", "seq_r", "seq_u")
        )
    return Mesh(devices.reshape(n_ring, n_uly), ("seq_r", "seq_u"))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
def test_matches_dense(causal, shape):
    q, k, v = make_qkv(batch=2, seq=64, heads=8, head_dim=16)
    mesh = mesh_2d(*shape)
    out = usp_attention(q, k, v, mesh, causal=causal)
    expected = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_matches_1d_formulations():
    from workloads.ops.ring import ring_attention
    from workloads.ops.ulysses import ulysses_attention

    q, k, v = make_qkv(batch=1, seq=64, heads=8, head_dim=16)
    mesh = mesh_2d(2, 4)
    out_2d = usp_attention(q, k, v, mesh)
    ring_mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    np.testing.assert_allclose(
        np.asarray(out_2d),
        np.asarray(ring_attention(q, k, v, ring_mesh)),
        atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(out_2d),
        np.asarray(ulysses_attention(q, k, v, ring_mesh)),
        atol=2e-5,
    )


def test_gradients_match_dense():
    q, k, v = make_qkv(batch=1, seq=32, heads=4, head_dim=16)
    mesh = mesh_2d(2, 2)

    def loss_usp(q, k, v):
        return jnp.sum(usp_attention(q, k, v, mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(naive_attention(q, k, v, True) ** 2)

    got = jax.grad(loss_usp, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=1e-4, err_msg=f"d{name}"
        )


def test_with_data_axis_and_jit():
    """Batch sharded on a data axis alongside the 2D seq sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = make_qkv(batch=4, seq=32, heads=4, head_dim=16)
    mesh = mesh_2d(2, 2, extra=2)
    sharding = NamedSharding(mesh, P("data", ("seq_r", "seq_u"), None, None))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    out = jax.jit(
        lambda q, k, v: usp_attention(q, k, v, mesh, batch_axis="data")
    )(q, k, v)
    assert out.sharding.spec == P("data", ("seq_r", "seq_u"), None, None)
    expected = naive_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_rejects_indivisible():
    q, k, v = make_qkv(batch=1, seq=60, heads=8, head_dim=16)
    mesh = mesh_2d(2, 4)
    with pytest.raises(ValueError, match="seq"):
        usp_attention(q, k, v, mesh)
    q2, k2, v2 = make_qkv(batch=1, seq=64, heads=2, head_dim=16)
    with pytest.raises(ValueError, match="heads"):
        usp_attention(q2, k2, v2, mesh)


def test_usp_train_step():
    """Full training step over ("data", "seq_r", "seq_u"): the 2D
    long-context configuration learns and matches the dense loss scale."""
    from workloads.model import ModelConfig
    from workloads.train import (
        make_seq_parallel_train_step,
        make_train_state,
        make_usp_mesh,
        synthetic_batch,
    )

    config = ModelConfig(max_seq_len=33, n_layers=1)  # 32 % (2*2) == 0
    mesh = make_usp_mesh(8, ring=2, ulysses=2)  # data=2
    assert dict(mesh.shape) == {"data": 2, "seq_r": 2, "seq_u": 2, "model": 1}
    (params, opt_state), optimizer = make_train_state(config, mesh)
    step = make_seq_parallel_train_step(config, mesh, optimizer, attention="usp")
    tokens = synthetic_batch(config, batch_size=4)
    params, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    _, _, loss2 = step(params, opt_state, tokens)
    assert float(loss2) < float(loss)


def test_mode_mesh_mismatch_fails_loud():
    from workloads.model import ModelConfig
    from workloads.train import (
        make_seq_parallel_train_step,
        make_sp_mesh,
        make_usp_mesh,
    )

    config = ModelConfig(max_seq_len=17, n_layers=1)

    class _Opt:
        pass

    with pytest.raises(ValueError, match="make_usp_mesh"):
        make_seq_parallel_train_step(
            config, make_sp_mesh(8), _Opt(), attention="usp"
        )
    with pytest.raises(ValueError, match="make_sp_mesh"):
        make_seq_parallel_train_step(
            config, make_usp_mesh(8), _Opt(), attention="ring"
        )


def test_mesh_builders_reject_zero_devices():
    from workloads.train import make_sp_mesh, make_usp_mesh

    with pytest.raises(ValueError, match="positive"):
        make_sp_mesh(0)
    with pytest.raises(ValueError, match="positive"):
        make_usp_mesh(0)

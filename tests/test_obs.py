"""Engine observability (workloads/obs.py): the observer is INERT —
token streams bit-identical on/off — while its step records, lifecycle
spans, Prometheus bridge and chrome-trace export all describe the run
faithfully; plus the mode-trace knob/drain and the export guard."""

import json
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from tpu_device_plugin.metrics import MetricsServer, Registry
from workloads.model import ModelConfig, init_params
from workloads.obs import EngineObserver, trace_events
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def models():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    draft = init_params(DRAFT_CONFIG, jax.random.PRNGKey(7))
    return params, draft


def _engine(params, observer=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("prompt_bucket", 8)
    return ServeEngine(params, CONFIG, observer=observer, **kw)


# A backpressured mixed stream: queue wait, instant finish
# (max_new_tokens=1), mid-stream retirement and slot turnover all occur.
STREAM = (([1, 2, 3], 10), ([4, 5], 6), ([7, 8, 9, 10], 4), ([6], 1))


def _run_stream(engine):
    rids = [engine.submit(p, n) for p, n in STREAM]
    out = engine.run()
    return [list(out[r]) for r in rids]


def test_token_streams_bit_identical_observer_on_off(models):
    """The tentpole guarantee: the observer (rings AND registry bridge
    live) changes no token, no telemetry counter, under sampling —
    where any RNG-order disturbance would show instantly."""
    params, _ = models

    def run(observer):
        engine = _engine(
            params, observer, temperature=0.8, top_k=5,
            rng=jax.random.PRNGKey(3),
        )
        return engine, _run_stream(engine)

    obs = EngineObserver()
    obs.bind_registry(Registry())
    e_on, streams_on = run(obs)
    e_off, streams_off = run(None)
    assert streams_on == streams_off
    for attr in (
        "generated_tokens", "chunks_run", "prefill_dispatches",
        "admission_readbacks", "requests_admitted", "requests_retired",
    ):
        assert getattr(e_on, attr) == getattr(e_off, attr), attr


def test_step_records_describe_the_run(models):
    params, _ = models
    obs = EngineObserver()
    engine = _engine(params, obs)
    _run_stream(engine)
    steps = obs.drain_steps()
    assert steps and not obs.steps  # drained clear
    assert [r.index for r in steps] == list(range(len(steps)))
    assert sum(r.tokens for r in steps) == engine.generated_tokens
    assert sum(r.admitted for r in steps) == engine.requests_admitted == 4
    assert sum(r.retired for r in steps) == engine.requests_retired == 4
    assert sum(r.decode_dispatches for r in steps) == engine.chunks_run
    assert sum(r.sweeps for r in steps) == engine.prefill_sweeps
    for r in steps:
        assert r.mode in ("plain", "idle")
        assert 0 <= r.occupancy <= engine.slots
        assert r.dur_secs >= r.readback_secs >= 0.0
    assert obs.dropped_steps == 0


def test_request_spans_and_segments(models):
    params, _ = models
    obs = EngineObserver()
    engine = _engine(params, obs)
    _run_stream(engine)
    spans = obs.drain_spans()
    assert len(spans) == 4 and not obs.spans
    by_rid = {s.rid: s for s in spans}
    for (prompt, n), rid in zip(STREAM, ("req-0", "req-1", "req-2", "req-3")):
        span = by_rid[rid]
        assert span.n_tokens <= n
        # Stamp ordering -> non-negative segments that add up to e2e.
        assert span.queue_wait_secs >= 0
        assert span.prefill_secs >= 0
        assert span.decode_secs >= 0
        total = span.queue_wait_secs + span.prefill_secs + span.decode_secs
        assert total == pytest.approx(span.e2e_secs, abs=1e-9)
        assert span.ttft_secs == pytest.approx(
            span.queue_wait_secs + span.prefill_secs, abs=1e-9
        )
    # The instant-EOS-shaped request (max_new_tokens=1) finished AT
    # admission: first token is last token.
    assert by_rid["req-3"].t_first == by_rid["req-3"].t_done
    # Later waves queued behind the first: someone actually waited.
    assert max(s.queue_wait_secs for s in spans) > 0


def test_prometheus_bridge_scrapes_next_to_plugin_metrics(models):
    """The engine series land on a shared registry, scrapeable over the
    REAL MetricsServer — on an ephemeral port that the server reports
    back (the port-0 contract parallel CI relies on)."""
    params, _ = models
    reg = Registry()
    reg.describe("allocations_total", "plugin-side neighbour")
    reg.inc("allocations_total", {"resource": "google.com/tpu"}, 2)
    obs = EngineObserver(name="scrape")
    obs.bind_registry(reg)
    engine = _engine(params, obs)
    _run_stream(engine)
    server = MetricsServer(0, reg)
    assert server.port == 0
    port = server.start()
    try:
        assert port > 0 and server.port == port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ).read().decode()
    finally:
        server.stop()
    assert 'tpu_device_plugin_allocations_total{resource="google.com/tpu"} 2' in body
    assert (
        f'tpu_device_plugin_engine_tokens_total{{engine="scrape"}} '
        f"{engine.generated_tokens}" in body
    )
    assert 'engine_requests_admitted_total{engine="scrape"} 4' in body
    assert 'engine_queue_depth{engine="scrape"} 0' in body
    assert 'engine_slots{engine="scrape"} 2' in body
    # Serve histograms carry the seconds-scale ladder, not the
    # sub-second Allocate default.
    assert 'engine_e2e_seconds_bucket{engine="scrape",le="60.0"}' in body
    assert "TYPE tpu_device_plugin_engine_e2e_seconds histogram" in body


def test_unbind_registry_releases_gauges_and_engine(models):
    """A retiring engine must not keep scraping as live state: unbind
    removes the gauge collectors (whose closures pin the engine) while
    the accumulated counter/histogram series stay, monotonic."""
    params, _ = models
    reg = Registry()
    obs = EngineObserver()
    obs.bind_registry(reg)
    engine = _engine(params, obs)
    _run_stream(engine)
    before = reg.render()
    assert 'engine_slots{engine="0"} 2' in before
    tokens_line = f'engine_tokens_total{{engine="0"}} {engine.generated_tokens}'
    assert tokens_line in before
    obs.unbind_registry()
    after = reg.render()
    assert "engine_slots{" not in after  # dead engine's gauges gone
    assert "engine_queue_depth{" not in after
    assert tokens_line in after  # counters persist, monotonic
    assert obs._engine is None and obs._registry is None
    obs.unbind_registry()  # idempotent


def test_export_trace_covers_spec_mode_switches(models, tmp_path):
    """A spec="auto" run whose occupancy crosses the threshold: the
    exported timeline is schema-valid trace_event JSON carrying BOTH
    decode modes' step events plus every request's lanes."""
    from tools.trace_export import validate_file

    params, draft = models
    obs = EngineObserver(name="trace")
    engine = ServeEngine(
        params, CONFIG, slots=3, page_size=4, prompt_bucket=8,
        draft_params=draft, draft_config=DRAFT_CONFIG, gamma=3,
        spec="auto", spec_breakeven=1.5, observer=obs,
    )
    for prompt, new in (([5, 6, 7], 24), ([1, 2], 6), ([9], 4)):
        engine.submit(prompt, new)
    engine.run()
    assert engine.mode_switches >= 1  # the crossing actually happened
    path = tmp_path / "trace.json"
    n = engine.export_trace(str(path))
    assert validate_file(str(path)) == []
    trace = json.loads(path.read_text())
    events = trace["traceEvents"]
    assert len(events) == n
    step_names = {e["name"] for e in events if e.get("cat") == "step"}
    assert "step[plain]" in step_names and "step[spec]" in step_names
    lanes = {e["args"]["rid"] for e in events if e.get("cat") == "request"}
    assert lanes == {"req-0", "req-1", "req-2"}
    segs = {e["name"] for e in events if e.get("cat") == "request"}
    assert segs == {"queued", "prefill", "decode"}
    counters = {e["name"] for e in events if e["ph"] == "C"}
    assert counters == {"occupancy", "queue_depth"}
    # trace_events is non-destructive: the rings still hold the run.
    assert obs.steps and obs.spans
    assert trace == trace_events(obs)


def test_mode_trace_knob_and_drain(models):
    """The decode_mode_trace bound is a constructor knob with a
    drain-style API — history is handed back, not silently dropped."""
    params, draft = models

    def spec_engine(**kw):
        engine = ServeEngine(
            params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
            draft_params=draft, draft_config=DRAFT_CONFIG, gamma=3,
            spec="auto", spec_breakeven=2.0, **kw,
        )
        engine.submit([1, 2, 3], 12)
        engine.run()
        return engine

    bounded = spec_engine(mode_trace_limit=2)
    assert bounded.decode_mode_trace.maxlen == 2
    assert len(bounded.decode_mode_trace) <= 2
    unbounded = spec_engine(mode_trace_limit=None)
    assert unbounded.decode_mode_trace.maxlen is None
    assert len(unbounded.decode_mode_trace) == (
        unbounded.spec_mode_steps + unbounded.plain_mode_steps
    )
    drained = unbounded.drain_mode_trace()
    assert drained and not unbounded.decode_mode_trace
    for occ, mode in drained:
        assert mode in ("spec", "plain") and 1 <= occ <= 2
    with pytest.raises(ValueError, match="mode_trace_limit"):
        _engine(params, mode_trace_limit=0)


def test_export_trace_without_observer_is_a_loud_error(models):
    params, _ = models
    engine = _engine(params)
    with pytest.raises(RuntimeError, match="observer"):
        engine.export_trace("/tmp/never-written.json")


def test_observer_constructor_validates_ring_bounds():
    with pytest.raises(ValueError, match="step_limit"):
        EngineObserver(step_limit=0)

"""Decode supersteps + double-buffered scheduling
(paged.paged_decode_superstep + ServeEngine(superstep_k=k)): k chained
decode chunks per device dispatch with DEVICE-SIDE eos/max-token
retirement masks, host bookkeeping overlapping the superstep's compute,
and one fused readback per superstep.  Parity is the bar: greedy token
streams must be EXACTLY the k=1 engine's (= the dense reference) for
every k, across serial/batched admission, pipelining, budgeted chunked
prefill and spec="auto" — with over-decode accounting, mid-superstep
lifecycle reclaim (cancel/deadline/quarantine/close), page
pre-commitment and fleet failover composed on top."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
DRAFT_CONFIG = ModelConfig(
    max_seq_len=64, n_layers=1, d_model=32, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)

STREAMS = [([3, 1, 4, 1, 5], 17), ([2, 7], 9), ([9] * 11, 13)]


@pytest.fixture(scope="module")
def models():
    return (
        init_params(CONFIG, jax.random.PRNGKey(0)),
        init_params(DRAFT_CONFIG, jax.random.PRNGKey(7)),
    )


def _engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("prompt_bucket", 8)
    return ServeEngine(params, CONFIG, **kw)


def _ref(params, prompt, new):
    return [int(t) for t in np.asarray(
        generate(params, jnp.asarray([prompt], jnp.int32), CONFIG, new)[0]
    )]


@pytest.mark.parametrize("k", [2, 3, 5])
def test_superstep_greedy_matches_dense_reference(models, k):
    params, _ = models
    engine = _engine(params, superstep_k=k)
    rids = [engine.submit(p, n) for p, n in STREAMS]
    served = engine.run()
    for rid, (p, n) in zip(rids, STREAMS):
        assert served[rid] == _ref(params, p, n), (k, rid)
    assert engine.ctrl.used_pages == 0


@pytest.mark.parametrize(
    "mode_kw",
    [
        {"batched_admission": False},
        {},
        {"pipelined": True},
        {"prefill_budget": 1},
        {"pipelined": True, "prefill_budget": 8},
    ],
    ids=["serial", "batched", "pipelined", "budget1", "piped-budget"],
)
def test_superstep_bit_identical_across_modes(models, mode_kw):
    """The tentpole parity pin: for every admission/overlap mode the
    k>1 engine's greedy streams equal the k=1 engine's byte-for-byte
    (WHEN decode work runs cannot change WHAT it computes)."""
    params, _ = models
    served = {}
    for k in (1, 3):
        engine = _engine(params, superstep_k=k, **mode_kw)
        rids = [engine.submit(p, n) for p, n in STREAMS]
        out = engine.run()
        served[k] = [out[rid] for rid in rids]
        assert engine.ctrl.used_pages == 0, (k, mode_kw)
    assert served[3] == served[1], mode_kw


def test_superstep_spec_auto_bit_identical(models):
    """spec="auto" x superstep: whichever side of the break-even each
    step lands on (always-plain, always-spec, switching), the emitted
    tokens stay the per-regime oracle's for every k."""
    params, draft = models
    for breakeven in (0.0, 1.0, 2.0):
        engine = _engine(
            params, superstep_k=2, draft_params=draft,
            draft_config=DRAFT_CONFIG, gamma=3, spec="auto",
            spec_breakeven=breakeven,
        )
        rids = [engine.submit(p, n) for p, n in STREAMS]
        served = engine.run()
        for rid, (p, n) in zip(rids, STREAMS):
            assert served[rid] == _ref(params, p, n), (breakeven, rid)
        assert engine.ctrl.used_pages == 0, breakeven


def test_superstep_fewer_steps_same_tokens(models):
    """The superstep's point: one host round-trip per k chunks.  A k=4
    engine must finish the same stream in fewer step() iterations (and
    strictly fewer decode host syncs) than the k=1 engine."""
    params, _ = models
    ref = _ref(params, [5, 2, 9], 33)
    steps = {}
    for k in (1, 4):
        engine = _engine(params, slots=1, superstep_k=k)
        rid = engine.submit([5, 2, 9], 33)
        n_steps, served = 0, {}
        while not engine.idle:
            for req in engine.step():
                served[req.rid] = req.tokens
            n_steps += 1
        steps[k] = n_steps
        assert served[rid] == ref, k
    assert steps[4] < steps[1], steps


def test_superstep_device_masks_stop_emission_at_eos(models):
    """Unlike the k=1 chunk path (host-side eos at readback), the
    device retirement mask freezes a row the step it emits its eos —
    the emitted stream ends EXACTLY at the eos token, and the frozen
    remainder is counted as over-decode."""
    params, _ = models
    prompt = [4, 4, 8]
    full = _ref(params, prompt, 20)
    eos = full[6]
    engine = _engine(params, superstep_k=3)
    rid = engine.submit(prompt, 20, eos_token=eos)
    got = engine.run()[rid]
    assert got == full[: full.index(eos) + 1]
    assert engine.tokens_overdecoded > 0
    assert engine.ctrl.used_pages == 0


def test_superstep_overdecode_bounded_and_reconciled(models):
    """Over-decode is bounded by ONE superstep per retiring row and the
    fused readback reconciles it exactly: dead device steps = dispatched
    decode capacity minus emitted tokens, never emission."""
    params, _ = models
    k, chunk = 3, 4
    engine = _engine(params, page_size=chunk, superstep_k=k)
    rids = [engine.submit(p, n) for p, n in STREAMS]
    served = engine.run()
    span = k * chunk
    # Each retiring row wastes < one superstep; three requests retired.
    assert 0 < engine.tokens_overdecoded <= len(STREAMS) * span
    # Exact reconciliation: every dispatched decode slot-step is either
    # an emitted token, dead over-decode, or an empty-slot lane (the
    # [slots] dispatch always runs every lane).
    emitted_decode = sum(len(served[r]) for r in rids) - len(rids)
    occupied_lane_steps = emitted_decode + engine.tokens_overdecoded
    assert occupied_lane_steps <= engine.supersteps_run * span * engine.slots
    assert engine.ctrl.used_pages == 0


def test_superstep_page_precommit_never_faults(models):
    """Tables pre-extend k*chunk ahead capped at each row's retirement
    ceiling, inside the admission-time worst-case commitment — so a
    pool sized exactly to the commitment serves requests ending at
    max_seq_len without the allocator ever raising mid-scan."""
    params, _ = models
    for pipelined in (False, True):
        engine = _engine(
            params, slots=1, superstep_k=4, pipelined=pipelined,
        )
        # One request spanning the full context window: prompt + new =
        # max_seq_len, retirement far off any superstep boundary.
        new = CONFIG.max_seq_len - 3
        n_pages = engine._worst_case_pages(3, new)
        tight = _engine(
            params, slots=1, superstep_k=4, pipelined=pipelined,
            n_pages=n_pages,
        )
        rid = tight.submit([5, 2, 9], new)
        served = tight.run()
        assert served[rid] == _ref(params, [5, 2, 9], new), pipelined
        assert tight.ctrl.used_pages == 0


def test_superstep_cancel_and_deadline_reclaim(models):
    params, _ = models
    engine = _engine(params, superstep_k=2, pipelined=True)
    r1 = engine.submit([3, 1, 4], 30)
    r2 = engine.submit([2, 7], 30)
    engine.step()
    engine.step()  # a superstep is now in flight
    assert engine.cancel(r1)
    served = engine.run()
    statuses = {r.rid: r.status for r in engine.completed}
    assert statuses[r1] == "cancelled" and statuses[r2] == "ok"
    # The cancelled stream is a true prefix of the dense reference.
    assert served[r1] == _ref(params, [3, 1, 4], 30)[: len(served[r1])]
    assert served[r2] == _ref(params, [2, 7], 30)
    assert engine.ctrl.used_pages == 0

    engine = _engine(params, slots=1, superstep_k=2)
    rd = engine.submit([1, 2, 3], 40, deadline_s=0.05)
    engine.step()
    time.sleep(0.08)
    engine.run()
    statuses = {r.rid: r.status for r in engine.completed}
    assert statuses[rd] == "expired"
    assert engine.ctrl.used_pages == 0


def test_superstep_quarantine_drops_and_replays_bit_identical(models):
    """A seam fault mid-superstep quarantines the WHOLE in-flight
    superstep (PR-4 rules: state dropped, not drained) and the replays
    resume bit-identically under the retry budget."""
    from workloads.faults import FaultInjector

    params, _ = models
    for seam in ("decode_dispatch", "decode_readback"):
        for pipelined in (False, True):
            engine = _engine(
                params, superstep_k=2, pipelined=pipelined,
                fault_injector=FaultInjector({seam: [2]}), max_retries=2,
            )
            rids = [engine.submit(p, n) for p, n in STREAMS]
            served = engine.run()
            for rid, (p, n) in zip(rids, STREAMS):
                assert served[rid] == _ref(params, p, n), (seam, pipelined)
            assert engine.steps_quarantined >= 1
            # No unconsumed superstep survives the stream (the chained
            # device carry may — it is a dead placeholder, like the
            # plain path's _chained_tok).
            assert not engine._pending_super
            assert engine.ctrl.used_pages == 0


def test_superstep_close_reclaims_in_flight(models):
    params, _ = models
    engine = _engine(params, superstep_k=3, pipelined=True)
    rid = engine.submit([5, 5], 40)
    engine.step()
    engine.step()
    engine.close()
    statuses = {r.rid: r.status for r in engine.completed}
    assert statuses[rid] == "failed"
    assert not engine._pending_super
    assert engine.ctrl.used_pages == 0
    assert engine.idle


def test_superstep_host_sync_telemetry(models):
    """StepRecord.host_sync_ms / tokens_overdecoded ride the observer,
    and the registry families engine_host_sync_seconds /
    engine_tokens_overdecoded_total accumulate — with streams untouched
    (the observer-inert contract)."""
    from tpu_device_plugin.metrics import Registry
    from workloads.obs import EngineObserver

    params, _ = models
    prompt = [4, 4, 8]
    full = _ref(params, prompt, 20)
    bare = _engine(params, superstep_k=2)
    rid = bare.submit(prompt, 20, eos_token=full[6])
    want = bare.run()[rid]

    obs = EngineObserver()
    reg = Registry()
    obs.bind_registry(reg)
    engine = _engine(params, superstep_k=2, observer=obs)
    rid = engine.submit(prompt, 20, eos_token=full[6])
    assert engine.run()[rid] == want  # inert: bit-identical with obs on
    steps = obs.drain_steps()
    assert sum(r.host_sync_ms for r in steps) > 0
    assert sum(r.tokens_overdecoded for r in steps) == engine.tokens_overdecoded
    assert engine.tokens_overdecoded > 0
    text = reg.render()
    assert "engine_tokens_overdecoded_total" in text
    assert "engine_host_sync_seconds_bucket" in text
    obs.unbind_registry()


def test_superstep_fanout_prefix_and_lora_compose(models):
    from workloads.multi_lora import synthetic_adapters

    params, _ = models
    adapters = synthetic_adapters(CONFIG, 2, rank=4, scale=0.3, seed=3)
    engine = _engine(
        params, superstep_k=2, prefix_cache=True, adapters=adapters,
    )
    rids = [engine.submit(p, n) for p, n in STREAMS]
    frids = engine.submit_fanout([6, 2, 6, 2, 6], 8, n_samples=2)
    arid = engine.submit([1, 2, 3], 7, adapter=sorted(adapters)[0])
    served = engine.run()
    for rid, (p, n) in zip(rids, STREAMS):
        assert served[rid] == _ref(params, p, n)
    for rid in frids:
        assert served[rid] == _ref(params, [6, 2, 6, 2, 6], 8)
    from workloads.lora import merge_lora

    merged = merge_lora(
        params, adapters[sorted(adapters)[0]], dtype=jnp.float32
    )
    assert served[arid] == [int(t) for t in np.asarray(generate(
        merged, jnp.asarray([[1, 2, 3]], jnp.int32), CONFIG, 7
    )[0])]
    assert engine.ctrl.used_pages == engine.prefix.cached_pages


def test_superstep_sampling_structurally_sound(models):
    params, _ = models
    engine = _engine(
        params, superstep_k=2, temperature=0.8, top_k=40,
        rng=jax.random.PRNGKey(5),
    )
    rids = [engine.submit([1 + i, 2], 10) for i in range(4)]
    served = engine.run()
    for rid in rids:
        toks = served[rid]
        assert len(toks) == 10
        assert all(0 <= t < CONFIG.vocab_size for t in toks)
    assert engine.ctrl.used_pages == 0


def test_superstep_fleet_failover_replays_through(models):
    """A replica crash mid-stream fails superstep engines' in-flight
    work over to a survivor by replay — greedy streams bit-identical,
    one terminal status per rid, no leak (the PR-6 contract with k>1
    domains)."""
    from workloads.faults import FaultInjector
    from workloads.fleet import Fleet

    params, _ = models
    def build():
        return [
            _engine(params, superstep_k=2, rng=jax.random.PRNGKey(42 + i))
            for i in range(2)
        ]

    fleet = Fleet(build(), fault_injector=FaultInjector(
        {"replica_crash": [3]}
    ))
    rids = [fleet.submit(p, n) for p, n in STREAMS for _ in range(2)]
    served = fleet.run()
    assert fleet.replica_crashes == 1
    expected = [(p, n) for p, n in STREAMS for _ in range(2)]
    for rid, (p, n) in zip(rids, expected):
        assert served[rid] == _ref(params, p, n), rid
    statuses = [r.status for r in fleet.completed]
    assert statuses.count("ok") == len(rids)
    for rep in fleet.replicas:
        if rep.state != "dead":
            assert rep.engine.ctrl.used_pages == 0
    fleet.close()


def test_superstep_drains_inflight_spec_after_last_retirement(models):
    """Regression pin: a pipelined SPEC superstep whose consume retires
    every slot leaves its successor in flight with zero occupancy — the
    double-buffered step must still drain it (run() would otherwise
    spin on idle forever)."""
    params, draft = models
    for spec_kw in (
        {},
        {"spec": "auto", "spec_breakeven": 1.0},
    ):
        engine = _engine(
            params, superstep_k=2, pipelined=True, draft_params=draft,
            draft_config=DRAFT_CONFIG, gamma=2, **spec_kw,
        )
        rids = [engine.submit(p, n) for p, n in STREAMS]
        served = engine.run()  # must terminate
        for rid, (p, n) in zip(rids, STREAMS):
            assert served[rid] == _ref(params, p, n), spec_kw
        assert engine._pending_spec is None
        assert engine.ctrl.used_pages == 0


def test_superstep_validation(models):
    params, _ = models
    with pytest.raises(ValueError, match="superstep_k"):
        _engine(params, superstep_k=0)


def test_superstep_tp_matches_greedy(models):
    """The superstep under a ("data", "model") mesh: scan-of-shard_map
    decode; tokens must equal the dense reference."""
    from workloads.train import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    params, _ = models
    mesh = make_mesh(2, model_parallel=2)
    engine = ServeEngine(
        params, CONFIG, slots=2, page_size=4, prompt_bucket=8,
        mesh=mesh, superstep_k=2,
    )
    rids = [engine.submit(p, n) for p, n in STREAMS]
    served = engine.run()
    for rid, (p, n) in zip(rids, STREAMS):
        assert served[rid] == _ref(params, p, n)
    assert engine.ctrl.used_pages == 0


def test_superstep_parity_smoke(models):
    """The `make superstep-check` tripwire: a fast k-sweep whose greedy
    streams must all equal the k=1 oracle, over-decode reconciled, no
    leaks — one seeded round of the full-matrix fuzz rides the slow
    suite."""
    params, _ = models
    oracle = None
    for k in (1, 2, 4):
        engine = _engine(params, superstep_k=k, pipelined=(k == 4))
        rids = [engine.submit(p, n) for p, n in STREAMS]
        served = engine.run()
        out = [served[rid] for rid in rids]
        if oracle is None:
            oracle = out
        else:
            assert out == oracle, k
        assert engine.ctrl.used_pages == 0, k

"""Closed-loop autoscaling contracts (workloads/autoscaler.py): the
fleet resizes itself from its own signals through the supervisor's
seams, with backoff hysteresis, and degrades gracefully (brownout,
preemption-via-offload) when capacity cannot arrive in time.

The pinned contracts: scale-up only through the bit-identical canary
probe (a diverging engine never joins); scale-down is a graceful drain
of the least-loaded replica, never below min_replicas, never the last
dispatchable one, with supervised slots forgotten so retirement is not
resurrected; separate up/down cooldowns gate flapping deterministically
(fake clock); spawn failures consult the scale_spawn_fail seam and
escalate the up-gate; ladder step 1 tightens the admission bound (typed
QueueFull names the brownout); ladder step 2 parks bulk-class streams
via host offload and resumes them as EXACT continuations, uncharged;
ok streams stay bit-identical to the dense oracle through resizes,
preemptions, crashes, spawn failures and health drains; no
slot/page/commitment leaks anywhere."""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.autoscaler import FleetAutoscaler
from workloads.backoff import Backoff
from workloads.errors import QueueFull
from workloads.faults import FaultInjector
from workloads.fleet import DEAD, DRAINING, Fleet, FleetServer, TrafficGen
from workloads.generate import generate
from workloads.model import ModelConfig, init_params
from workloads.serve import ServeEngine
from workloads.supervisor import FleetSupervisor, make_engine_factory

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)
PARAMS = init_params(CONFIG, jax.random.PRNGKey(0))
TERMINAL = {"ok", "cancelled", "expired", "failed"}
ENGINE_KW = dict(slots=2, page_size=4, prompt_bucket=8)
FAST = Backoff(base_s=1e-3, max_s=8e-3, jitter=0.0)


def _engine(**kw):
    base = dict(ENGINE_KW)
    base.update(kw)
    return ServeEngine(PARAMS, CONFIG, **base)


def _fleet(n=1, *, engine_kw=None, **fleet_kw):
    fleet_kw.setdefault("chip_ids", [f"chip-{i}" for i in range(n)])
    fleet_kw.setdefault("hang_timeout_s", None)
    return Fleet(
        [_engine(**(engine_kw or {})) for _ in range(n)], **fleet_kw
    )


def _autoscaler(fleet, *, engine_kw=None, factory=None, **kw):
    ekw = dict(ENGINE_KW)
    ekw.update(engine_kw or {})
    if factory is None:
        def factory(slot):
            return ServeEngine(PARAMS, CONFIG, **ekw)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("up_backoff", FAST)
    kw.setdefault("down_backoff", FAST)
    kw.setdefault("down_consecutive", 2)
    kw.setdefault("depth_high", 1.0)
    kw.setdefault("queue_wait_p99_target_s", 0.2)
    kw.setdefault("window_s", 0.5)
    return FleetAutoscaler(fleet, factory, **kw)


def _oracle(prompt, new):
    return [int(t) for t in np.asarray(generate(
        PARAMS, jnp.asarray([prompt], jnp.int32), CONFIG,
        max_new_tokens=new,
    )[0])]


def _prompts(seed, n, lo=1, hi=20, new_lo=4, new_hi=16):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        plen = int(rng.integers(lo, hi))
        prompt = [int(t) for t in rng.integers(0, CONFIG.vocab_size, plen)]
        out.append((prompt, int(rng.integers(new_lo, new_hi))))
    return out


def _assert_no_leaks(fleet):
    for rep in fleet.replicas:
        if rep.state == DEAD:
            continue
        e = rep.engine
        assert not e._occupied.any(), rep.index
        assert e._committed_pages == 0, rep.index
        assert not e._groups, rep.index
        pinned = e.prefix.cached_pages if e.prefix is not None else 0
        assert e.ctrl.used_pages == pinned, rep.index
        assert not rep.rids, rep.index


# ---- validation ----------------------------------------------------------


def test_autoscaler_validates_its_knobs():
    fleet = _fleet(1)
    for bad in (
        dict(min_replicas=0),
        dict(min_replicas=3, max_replicas=2),
        dict(queue_wait_p99_target_s=0.0),
        dict(depth_high=0),
        dict(burn_high=0),
        dict(clear_fraction=1.0),
        dict(clear_fraction=0.0),
        dict(severe_factor=1.0),
        dict(window_s=0),
        dict(down_consecutive=0),
        dict(brownout_factor=1.0),
        dict(brownout_factor=0.0),
        dict(preempt_batch=0),
        dict(probe=([], 4)),
        dict(probe=([1], 0)),
        dict(probe_max_steps=0),
    ):
        with pytest.raises(ValueError):
            _autoscaler(fleet, **bad)
    fleet.close()


# ---- the closed loop -----------------------------------------------------


def test_scales_up_under_pressure_then_back_down_bit_identical():
    """The headline loop: queue pressure scales 1 -> N (probed joins),
    the drained fleet scales back to the floor, and every stream is
    bit-identical to the dense oracle — elasticity is invisible to
    tokens."""
    fleet = _fleet(1)
    asc = _autoscaler(fleet)
    asc.calibrate_probe()
    fleet.submit([1], 2)
    asc.run()  # warm, off the pressure clock
    reqs = _prompts(3, 12)
    rids = [fleet.submit(p, n) for p, n in reqs]
    served = asc.run()
    assert asc.scale_ups >= 1, asc.decisions
    assert len(fleet.alive) >= 2
    for rid, (prompt, new) in zip(rids, reqs):
        assert served[rid] == _oracle(prompt, new), rid
    # The spike is over: the loop must converge back to the floor.
    assert asc.wait_quiescent(20.0), (
        asc.states(), asc.decisions, fleet.states(),
    )
    assert len(fleet.alive) == 1
    assert asc.scale_downs >= 1
    assert asc.recover_s, "the breach window never closed"
    assert asc.overprovision_chip_s >= 0.0
    # Removed replicas are really gone (closed, not leaked).
    for rep in fleet.replicas:
        if rep.state == DEAD:
            assert rep.engine.closed
    _assert_no_leaks(fleet)
    fleet.close()


def test_never_scales_past_max_replicas():
    fleet = _fleet(1)
    asc = _autoscaler(fleet, max_replicas=2)
    asc.calibrate_probe()
    for p, n in _prompts(5, 14):
        fleet.submit(p, n)
    asc.run()
    assert asc.scale_ups <= 1
    assert sum(1 for r in fleet.replicas if r.state != DEAD) <= 2
    fleet.close()


def test_hysteresis_gates_scaling_with_a_fake_clock():
    """Deterministic cooldown gating: one scale-up per up-cooldown
    however often the breached signal polls, scale-down only after
    down_consecutive clear polls AND the down-gate, and never below
    min_replicas."""
    fleet = _fleet(1)
    asc = _autoscaler(
        fleet,
        up_backoff=Backoff(base_s=10.0, max_s=10.0, jitter=0.0),
        down_backoff=Backoff(base_s=10.0, max_s=10.0, jitter=0.0),
        down_consecutive=2,
        clock=lambda: 0.0,
    )
    asc.calibrate_probe()
    fleet.submit([1], 2)
    fleet.run()  # warm the engine compiles
    # Build queue pressure WITHOUT stepping: three queued requests on
    # one replica breach depth_high=1.
    for p, n in _prompts(7, 3):
        fleet.submit(p, n)
    asc.poll(now=100.0)
    assert asc.scale_ups == 1 and len(fleet.alive) == 2
    # Same breach, inside the up-cooldown: no second spawn.
    asc.poll(now=105.0)
    assert asc.scale_ups == 1
    # Past the gate: the second spawn lands.
    asc.poll(now=111.0)
    assert asc.scale_ups == 2 and len(fleet.alive) == 3
    # Serve everything; the signal clears.
    fleet.run()
    # One clear poll is not enough (down_consecutive=2)...
    asc.poll(now=130.0)
    assert asc.scale_downs == 0
    # ...the second clear poll drains the least-loaded replica.
    asc.poll(now=131.0)
    assert asc.scale_downs == 1
    assert DRAINING in {r.state for r in fleet.replicas}
    # The next down waits out the down-gate however clear the signal.
    asc.poll(now=132.0)
    asc.poll(now=133.0)
    assert asc.scale_downs == 1
    asc.poll(now=145.0)
    asc.poll(now=146.0)
    assert asc.scale_downs == 2
    # Retirements complete; the floor holds through further polls.
    for t in range(160, 260, 10):
        asc.poll(now=float(t))
    assert len(fleet.alive) == 1
    assert asc.scale_downs == 2  # min_replicas floor
    _assert_no_leaks(fleet)
    fleet.close()


def test_spawn_failure_consults_seam_and_escalates_the_up_gate():
    inj = FaultInjector({"scale_spawn_fail": [1, 2]})
    fleet = _fleet(1)
    asc = _autoscaler(
        fleet, fault_injector=inj,
        up_backoff=Backoff(base_s=10.0, max_s=100.0, jitter=0.0),
        clock=lambda: 0.0,
    )
    asc.calibrate_probe()
    fleet.submit([1], 2)
    fleet.run()
    for p, n in _prompts(9, 3):
        fleet.submit(p, n)
    asc.poll(now=10.0)  # first attempt: seam fires
    assert asc.spawn_failures == 1 and asc.scale_ups == 0
    assert inj.crossings["scale_spawn_fail"] == 1
    # Inside the escalated gate: no retry.
    asc.poll(now=15.0)
    assert asc.spawn_failures == 1
    # Past delay(0)=10: second attempt, seam fires again, gate doubles.
    asc.poll(now=21.0)
    assert asc.spawn_failures == 2 and asc.scale_ups == 0
    asc.poll(now=30.0)  # inside delay(1)=20
    assert asc.spawn_failures == 2
    asc.poll(now=42.0)  # past it: the third attempt succeeds
    assert asc.scale_ups == 1 and len(fleet.alive) == 2
    assert asc.spawn_failures == 2
    # Ladder engaged while capacity could not arrive (breach + no
    # growth): the brownout step recorded itself.
    assert asc.brownouts >= 1
    fleet.run()
    _assert_no_leaks(fleet)
    fleet.close()


def test_probe_divergence_keeps_the_replica_out():
    """A factory whose engines compute different tokens must never
    join: the canary diverges, the spawn counts as a failure."""
    bad_params = jax.tree.map(lambda w: w * 1.5, PARAMS)

    def bad_factory(slot):
        return ServeEngine(bad_params, CONFIG, **ENGINE_KW)

    fleet = _fleet(1)
    asc = _autoscaler(fleet, factory=bad_factory, clock=lambda: 0.0)
    # Oracle from the GOOD fleet's weights.
    asc._probe_oracle = _oracle([1, 2, 3], 4)
    fleet.submit([1], 2)
    fleet.run()
    for p, n in _prompts(11, 3):
        fleet.submit(p, n)
    asc.poll(now=10.0)
    assert asc.scale_ups == 0
    assert asc.spawn_failures == 1
    assert len(fleet.alive) == 1
    ev = [e for e in asc.events if e.kind == "spawn_failed"]
    assert ev and "diverged" in ev[-1].detail
    fleet.run()
    fleet.close()


def test_never_drains_the_last_dispatchable_replica():
    """Two replicas, one health-paused: however clear the signal, the
    lone dispatchable replica is not drained (and the paused one is
    not a candidate)."""
    from tpu_device_plugin.api.constants import UNHEALTHY
    from tpu_device_plugin.device import HealthEvent

    fleet = _fleet(2)
    asc = _autoscaler(fleet, min_replicas=1, clock=lambda: 0.0)
    fleet.submit([1], 2)
    fleet.run()
    fleet.deliver_health([
        HealthEvent(chip_id="chip-0", health=UNHEALTHY)
    ])
    fleet.step()  # apply the pause
    assert fleet.replicas[0].paused
    assert fleet.dispatchable_count == 1
    for t in range(0, 100, 5):
        asc.poll(now=float(t))
    assert asc.scale_downs == 0
    assert fleet.replicas[1].state == "active"
    fleet.close()


# ---- the degradation ladder ---------------------------------------------


def test_brownout_tightens_admission_bound_and_names_it():
    """Ladder step 1 at pinned capacity: the capacity-aware bound
    tightens to brownout_factor and QueueFull says so; recovery
    restores it."""
    fleet = _fleet(1, max_pending_per_replica=4)
    asc = _autoscaler(
        fleet, min_replicas=1, max_replicas=1,  # capacity cannot grow
        brownout_factor=0.5, clock=lambda: 0.0,
    )
    fleet.submit([1], 2)
    fleet.run()
    assert fleet.admission_bound == 4
    for p, n in _prompts(13, 2):
        fleet.submit(p, n)
    asc.poll(now=10.0)  # breach, cannot grow -> brownout
    assert asc.ladder_level == 1 and asc.brownouts == 1
    assert fleet.admission_factor == 0.5
    assert fleet.admission_bound == 2
    with pytest.raises(QueueFull) as exc:
        fleet.submit([9, 9], 2)
    assert "brownout" in str(exc.value)
    assert "dispatchable" in str(exc.value)
    # Serve the queue; clear polls walk the ladder back down.
    fleet.run()
    asc.poll(now=20.0)
    assert asc.ladder_level == 0
    assert fleet.admission_factor == 1.0
    assert fleet.admission_bound == 4
    _assert_no_leaks(fleet)
    fleet.close()


def test_preemption_parks_bulk_and_resumes_exact_continuation():
    """Ladder step 2: a running bulk stream is preempted — prefix
    pages pushed to the host tier, rid requeued UNCHARGED with its
    class parked — and resumes as an exact continuation once the
    interactive burst passes."""
    engine_kw = dict(prefix_cache=True, kv_offload=True)
    fleet = _fleet(1, engine_kw=engine_kw)
    asc = _autoscaler(
        fleet, engine_kw=engine_kw, min_replicas=1, max_replicas=1,
        severe_factor=1.2, preempt_batch=2, clock=lambda: 0.0,
    )
    fleet.submit([1], 2)
    fleet.run()
    prompt = [5, 4, 3, 2, 1, 9, 8, 7]
    new = 40
    rid_bulk = fleet.submit(prompt, new, slo_class="bulk")
    fleet.step()  # bulk is mid-decode
    for p, n in _prompts(17, 5):
        fleet.submit(p, n, slo_class="interactive")
    asc.poll(now=10.0)  # rung 1
    asc.poll(now=11.0)  # rung 2: preempt
    assert asc.ladder_level == 2
    assert fleet.preemptions >= 1
    fr = fleet._reqs[rid_bulk]
    assert fr.status == "queued" and fr.preemptions == 1
    assert fr.failovers == 0  # uncharged
    eng = fleet.replicas[0].engine
    assert eng.requests_preempted >= 1
    assert eng.pages_parked >= 1
    assert eng.prefix.offloaded_pages >= 1
    # While parked, the class is excluded from dispatch.
    assert "bulk" in fleet.parked_classes
    fleet.step()
    assert fr.status == "queued"
    # Drive with the control loop: the burst drains, the ladder steps
    # down, the bulk stream unparks and finishes.
    deadline = time.monotonic() + 30.0
    while not fleet.idle and time.monotonic() < deadline:
        asc.step()
    assert fleet.idle, (asc.states(), fleet.states())
    assert fr.status == "ok"
    assert fr.tokens == _oracle(prompt, new)
    assert not fleet.parked_classes
    assert fleet.preempt_resume_s  # the resume window closed
    assert asc.preemptions_total >= 1
    _assert_no_leaks(fleet)
    fleet.close()


# ---- supervisor interplay ------------------------------------------------


def test_supervised_scale_ups_are_adopted_and_downs_forgotten():
    """With a supervisor armed: a scaled-up replica is adopted (its
    later crash is healed), and a scaled-down slot is forgotten (its
    deliberate retirement is NOT resurrected)."""
    fleet = _fleet(1)
    factory, oracle = make_engine_factory(
        PARAMS, CONFIG, engine_kw=ENGINE_KW, probe=([1, 2, 3], 4)
    )
    sup = FleetSupervisor(
        fleet, factory, backoff=FAST, probe=([1, 2, 3], 4),
        probe_oracle=oracle,
    )
    asc = _autoscaler(
        fleet, factory=factory, supervisor=sup, probe_oracle=oracle,
        clock=lambda: 0.0,
    )
    fleet.submit([1], 2)
    fleet.run()
    for p, n in _prompts(19, 3):
        fleet.submit(p, n)
    asc.poll(now=10.0)
    assert asc.scale_ups == 1
    new_index = len(fleet.replicas) - 1
    chip = fleet.replicas[new_index].chip_id
    assert chip.startswith("scale-")
    assert sup.slot_for(chip).state == "serving"  # adopted
    fleet.run()
    # Crash the adopted replica: the SUPERVISOR heals it.
    fleet.replicas[new_index].engine.close()
    fleet.submit([2, 3], 4)
    deadline = time.monotonic() + 20.0
    while not fleet.idle and time.monotonic() < deadline:
        sup.step()
        time.sleep(0.002)
    assert sup.wait_healed(20.0), sup.states()
    assert sup.restarts_total >= 1
    # Now scale down: the retired slot must be FORGOTTEN, and the
    # supervisor must not resurrect it.
    restarts_before = sup.restarts_total
    for t in range(20, 60, 1):
        asc.poll(now=float(t))
        sup.poll()
        if asc.scale_downs and not asc._retiring:
            break
    assert asc.scale_downs >= 1
    forgotten = [
        s for s in sup.slots if s.state == "forgotten"
    ]
    assert forgotten, sup.states()
    for _ in range(50):
        sup.poll()
    assert sup.restarts_total == restarts_before
    fleet.close()


# ---- observability -------------------------------------------------------


def test_events_and_observer_counters_land_on_the_registry():
    from tpu_device_plugin.metrics import PREFIX, Registry
    from workloads.obs import AutoscalerObserver

    reg = Registry()
    obs = AutoscalerObserver(name="t")
    obs.bind_registry(reg)
    fleet = _fleet(1)
    asc = _autoscaler(fleet, observer=obs, clock=lambda: 0.0)
    asc.calibrate_probe()
    fleet.submit([1], 2)
    fleet.run()
    for p, n in _prompts(23, 3):
        fleet.submit(p, n)
    asc.poll(now=10.0)
    fleet.run()
    asc.poll(now=30.0)
    asc.poll(now=31.0)
    kinds = {e.kind for e in asc.events}
    assert "breach" in kinds and "scale_up" in kinds
    assert "recovered" in kinds
    text = reg.render()
    assert f"{PREFIX}_autoscaler_scale_ups_total" in text
    assert f"{PREFIX}_autoscaler_replicas_live" in text
    assert f"{PREFIX}_autoscaler_decisions_total" in text
    assert 'action="scale_up"' in text
    obs.unbind_registry()
    fleet.close()


def test_fleet_server_reports_autoscaler_state():
    import urllib.request

    fleet = _fleet(1)
    asc = _autoscaler(fleet, clock=lambda: 0.0)
    server = FleetServer(fleet, 0, autoscaler=asc)
    port = server.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as resp:
            health = json.loads(resp.read())
        assert health["autoscaler"]["ladder_level"] == 0
        assert health["autoscaler"]["min_replicas"] == 1
    finally:
        server.stop()
        fleet.close()


# ---- the make autoscale-check smoke -------------------------------------


def test_autoscale_check_smoke():
    """The `make autoscale-check` tripwire: a seeded step-load burst
    scales the fleet 1 -> N and back, the SLO-recovery window closes,
    ok streams are bit-identical to the dense oracle, and no
    page/slot/host-blob leaks remain anywhere."""
    engine_kw = dict(prefix_cache=True, kv_offload=True)
    fleet = _fleet(1, engine_kw=engine_kw)
    asc = _autoscaler(fleet, engine_kw=engine_kw, max_replicas=3)
    asc.calibrate_probe()
    fleet.submit([1], 2)
    asc.run()  # warm
    gen = TrafficGen(
        seed=29, rate_rps=500.0, max_prompt=16, min_new=4, max_new=12,
        vocab=CONFIG.vocab_size,
    )
    reqs = [(p, n) for _, p, n in gen.schedule(12)]
    rids = [fleet.submit(p, n) for p, n in reqs]
    served = asc.run()
    assert asc.scale_ups >= 1, asc.decisions
    for rid, (prompt, new) in zip(rids, reqs):
        assert served[rid] == _oracle(prompt, new), rid
    assert asc.wait_quiescent(20.0), (asc.states(), fleet.states())
    assert len(fleet.alive) == 1
    assert asc.recover_s
    assert asc.ladder_level == 0
    assert fleet.admission_factor == 1.0
    assert not fleet.parked_classes
    for rep in fleet.replicas:
        if rep.state != DEAD and rep.engine.prefix is not None:
            # No host-blob leaks: the offload tier only holds what the
            # index owns.
            assert rep.engine.prefix.offloaded_pages >= 0
    _assert_no_leaks(fleet)
    fleet.close()


# ---- resize chaos fuzz ---------------------------------------------------


@pytest.mark.slow
def test_autoscaler_resize_chaos_fuzz():
    """Crashes, spawn failures and health drains injected DURING
    resizes (supervisor + autoscaler armed together): the fleet must
    keep every invariant — exactly one terminal status per rid, ok
    streams bit-identical to the dense oracle (through failovers,
    resurrections, scale-ups/downs and preemptions), interrupted
    streams true prefixes, ladder fully unwound at the end, no leaks
    on any live replica."""
    from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY
    from tpu_device_plugin.device import HealthEvent

    for seed in range(2):
        rng = np.random.default_rng(seed + 411000)
        engine_kw = dict(
            slots=int(rng.integers(1, 3)),
            page_size=4, prompt_bucket=8,
            prefix_cache=bool(rng.integers(2)),
        )
        if engine_kw["prefix_cache"] and rng.integers(2):
            engine_kw["kv_offload"] = True
        fleet = Fleet(
            [ServeEngine(PARAMS, CONFIG, **engine_kw)],
            chip_ids=["chip-0"], hang_timeout_s=None,
            fault_injector=FaultInjector.random(
                seed=seed, rate=0.02,
                seams=("replica_crash", "replica_hang"),
                max_fires=2,
            ),
            max_failovers=3,
            # A short burn window: chaos-induced SLO misses must decay
            # within the test's horizon or the breach (and therefore
            # the ladder) would outlive the load by the default 60 s.
            slo_window_s=2.0,
        )
        factory, oracle = make_engine_factory(
            PARAMS, CONFIG, engine_kw=engine_kw, probe=([1, 2, 3], 4)
        )
        sup = FleetSupervisor(
            fleet, factory, backoff=FAST, probe=([1, 2, 3], 4),
            probe_oracle=oracle,
        )
        asc = FleetAutoscaler(
            fleet, factory, min_replicas=1, max_replicas=3,
            supervisor=sup, probe_oracle=oracle,
            up_backoff=FAST, down_backoff=FAST, down_consecutive=2,
            depth_high=1.0, queue_wait_p99_target_s=0.2, window_s=0.5,
            severe_factor=1.5, preempt_batch=2,
            fault_injector=FaultInjector.random(
                seed=seed + 7, rate=0.3, seams=("scale_spawn_fail",),
            ),
        )
        fleet.submit([1], 2)
        asc.run()  # warm
        classes = [None, "interactive", "bulk"]
        pending = []
        for _ in range(int(rng.integers(8, 14))):
            plen = int(rng.integers(1, 20))
            prompt = [
                int(t) for t in rng.integers(0, CONFIG.vocab_size, plen)
            ]
            new = int(rng.integers(2, 16))
            pending.append((
                prompt, new, classes[int(rng.integers(3))],
            ))
        expected = {}
        terminal = {}
        deadline = time.monotonic() + 120.0
        while pending or not fleet.idle:
            assert time.monotonic() < deadline, (
                seed, fleet.states(), asc.states(), asc.last_signals,
            )
            if not pending:
                # Load is drained; the remaining work is waiting out
                # signal windows (burn/queue-wait decay with WALL
                # time) — don't spin a million no-op steps.
                time.sleep(0.001)
            for _ in range(min(len(pending), int(rng.integers(1, 4)))):
                prompt, new, cls = pending.pop()
                try:
                    rid = fleet.submit(prompt, new, slo_class=cls)
                except QueueFull:
                    continue  # the brownout/bound did its job
                expected[rid] = (prompt, new)
            if rng.integers(15) == 0:
                alive = fleet.alive
                if len(alive) > 1:
                    ev = HealthEvent(
                        chip_id=alive[
                            int(rng.integers(len(alive)))
                        ].chip_id,
                        health=UNHEALTHY,
                    )
                    fleet.deliver_health([ev])
                    sup.note_health([ev])
            if rng.integers(12) == 0:
                ev = HealthEvent(chip_id="", health=HEALTHY)
                fleet.deliver_health([ev])
                sup.note_health([ev])
            for fr in asc.step():
                assert fr.rid not in terminal, (seed, fr.rid)
                assert fr.status in TERMINAL, (seed, fr.rid, fr.status)
                terminal[fr.rid] = fr.status
        ev = HealthEvent(chip_id="", health=HEALTHY)
        fleet.deliver_health([ev])
        sup.note_health([ev])
        fleet.step()
        # The controller must unwind fully once the load is gone.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and (
            asc.ladder_level or asc._retiring
        ):
            asc.step()
        assert asc.ladder_level == 0, (seed, asc.states())
        assert fleet.admission_factor == 1.0
        assert not fleet.parked_classes
        assert set(terminal) == set(expected), (
            seed, set(expected) ^ set(terminal),
        )
        for rid, (prompt, new) in expected.items():
            fr = fleet._reqs[rid]
            ref = _oracle(prompt, new)
            if terminal[rid] == "ok":
                assert fr.tokens == ref, (
                    seed, rid, fr.failovers, fr.preemptions,
                )
            else:
                assert fr.tokens == ref[: len(fr.tokens)], (
                    seed, rid, terminal[rid],
                )
        _assert_no_leaks(fleet)
        fleet.close()


# ---- waste-budget SLO (the GoodputController's autoscaler seam) ----------


def test_waste_budget_validates_its_range():
    fleet = _fleet(1)
    for bad in (0.0, 1.0, -0.2, 1.5):
        with pytest.raises(ValueError):
            _autoscaler(fleet, waste_budget=bad)
    fleet.close()


def test_waste_budget_holds_scale_up_until_waste_clears():
    """Don't scale up into measured waste: while the (controller-fed)
    waste fraction exceeds the budget, a breached signal buys a
    waste_hold — counted once per open window — and the ladder
    engages instead; the moment waste returns inside the budget the
    ordinary probed scale-up proceeds."""
    fleet = _fleet(1)
    asc = _autoscaler(
        fleet, waste_budget=0.3,
        up_backoff=Backoff(base_s=10.0, max_s=10.0, jitter=0.0),
        clock=lambda: 0.0,
    )
    assert asc.states()["waste_budget"] == 0.3
    asc.calibrate_probe()
    fleet.submit([1], 2)
    fleet.run()  # warm the engine compiles
    for p, n in _prompts(7, 3):  # breach depth_high without stepping
        fleet.submit(p, n)
    asc.waste_fraction_hint = 0.8  # the controller's smoothed view
    asc.poll(now=100.0)
    assert asc.scale_ups == 0 and len(fleet.alive) == 1
    assert asc.waste_holds == 1
    assert asc.decisions.get("waste_hold") == 1
    assert asc.ladder_level >= 1  # the ladder attacks the spike instead
    assert any(ev.kind == "waste_hold" for ev in asc.events)
    # The same open window re-polled (past the up-gate): still held,
    # still ONE hold counted.
    asc.poll(now=111.0)
    assert asc.scale_ups == 0 and asc.waste_holds == 1
    assert asc.states()["waste_fraction"] == 0.8
    # Waste back inside the budget: capacity may grow again.
    asc.waste_fraction_hint = 0.1
    asc.poll(now=122.0)
    assert asc.scale_ups == 1 and len(fleet.alive) == 2
    assert asc.waste_holds == 1
    asc.run()
    _assert_no_leaks(fleet)
    fleet.close()


def test_waste_headroom_relaxes_the_scale_down_streak():
    """Eager scale-down: waste comfortably inside the budget collapses
    the down_consecutive streak to a single clear poll — capacity
    above the floor under goodput headroom is pure overprovision."""
    fleet = _fleet(2)
    asc = _autoscaler(
        fleet, waste_budget=0.5, down_consecutive=3, max_replicas=2,
    )
    asc.calibrate_probe()
    fleet.submit([1], 2)
    fleet.run()
    # Clear signal, but waste OUTSIDE the headroom band (> budget *
    # clear_fraction = 0.25): the full 3-poll streak still applies.
    asc.waste_fraction_hint = 0.4
    asc.poll(now=100.0)
    assert asc.scale_downs == 0
    # Waste drops into the headroom band: the very next clear poll
    # drains a replica without waiting out the streak.
    asc.waste_fraction_hint = 0.05
    asc.poll(now=101.0)
    assert asc.scale_downs == 1
    assert asc.wait_quiescent(20.0), asc.states()
    assert len(fleet.alive) == 1
    _assert_no_leaks(fleet)
    fleet.close()

"""Regression tripwire (tools/bench_diff.py): artifact parsing, drop/gain
detection, platform guards."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_diff  # noqa: E402


def test_load_metrics_handles_driver_artifact_and_bench_stdout(tmp_path):
    artifact = tmp_path / "BENCH_r09.json"
    artifact.write_text(json.dumps({"parsed": {"mfu": 0.5}}, indent=2))
    assert bench_diff.load_metrics(str(artifact)) == {"mfu": 0.5}
    stdout = tmp_path / "out.txt"
    stdout.write_text("log line\nmore logs\n" + json.dumps({"mfu": 0.6}) + "\n")
    assert bench_diff.load_metrics(str(stdout)) == {"mfu": 0.6}


def test_diff_warns_on_drop_and_notes_gains():
    old = {"mfu": 0.5, "decode_tokens_per_sec": 1000.0,
           "serve_tokens_per_sec": 100.0}
    new = {"mfu": 0.45, "decode_tokens_per_sec": 1100.0,
           "serve_tokens_per_sec": 101.0}
    lines = bench_diff.diff(new, old, threshold=0.02)
    assert any(line.startswith("WARN") and "mfu" in line for line in lines)
    assert any(line.startswith("INFO") and "decode" in line for line in lines)
    # 1% move: below threshold, silent.
    assert not any("serve_tokens_per_sec" in line for line in lines)


def test_diff_skips_busy_across_platform_change_and_flags_fallback():
    old = {"busy_platform": "axon", "aggregate_chip_busy_fraction": 0.99}
    new = {"busy_platform": "cpu", "aggregate_chip_busy_fraction": 0.5,
           "busy_platform_fallback": True, "busy_fallback_reason": "boom"}
    lines = bench_diff.diff(new, old, threshold=0.02)
    assert not any("aggregate_chip_busy_fraction" in line for line in lines)
    assert any("platform changed" in line for line in lines)
    assert any("FALLBACK" in line and "boom" in line for line in lines)


def test_cli_against_committed_artifact(tmp_path):
    new = tmp_path / "new.json"
    new.write_text(json.dumps({"mfu": 0.0001}))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_diff.py"), str(new)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0  # loud, never a gate
    assert "WARN" in out.stdout and "mfu" in out.stdout


def test_latest_committed_picks_highest_round(tmp_path):
    for n in (1, 3, 2):
        (tmp_path / f"BENCH_r{n:02d}.json").write_text("{}")
    assert bench_diff.latest_committed(str(tmp_path)).endswith("BENCH_r03.json")

"""Regression tripwire (tools/bench_diff.py): artifact parsing, drop/gain
detection, platform guards."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
sys.path.insert(0, REPO)

import bench_diff  # noqa: E402


def test_load_metrics_handles_driver_artifact_and_bench_stdout(tmp_path):
    artifact = tmp_path / "BENCH_r09.json"
    artifact.write_text(json.dumps({"parsed": {"mfu": 0.5}}, indent=2))
    assert bench_diff.load_metrics(str(artifact)) == {"mfu": 0.5}
    stdout = tmp_path / "out.txt"
    stdout.write_text("log line\nmore logs\n" + json.dumps({"mfu": 0.6}) + "\n")
    assert bench_diff.load_metrics(str(stdout)) == {"mfu": 0.6}


def test_load_metrics_reads_tail_when_parsed_is_null(tmp_path):
    """A driver artifact whose tail holds an INTACT JSON line but whose
    parsed field is null (e.g. the driver parsed a different line) must
    still yield the metrics."""
    tail = "some log\n" + json.dumps({"mfu": 0.55, "value": 1.0}) + "\n"
    artifact = tmp_path / "BENCH_r08.json"
    artifact.write_text(json.dumps({"parsed": None, "tail": tail}))
    assert bench_diff.load_metrics(str(artifact))["mfu"] == 0.55


def test_load_metrics_salvages_front_truncated_tail(tmp_path):
    """The round-4 failure shape: the driver's 2000-byte tail cut the
    single bench line mid-object.  Every key after the cut is intact —
    load_metrics must recover them rather than silently finding no
    metrics (which made the tripwire inert for a full round)."""
    full = json.dumps({
        "metric": "allocate_p50_latency_ms", "value": 0.47, "unit": "ms",
        "vs_baseline": 0.0094, "mfu": 0.5577,
        "serve_tokens_per_sec": 3180.5, "aggregate_chip_busy_fraction": 0.9996,
    })
    truncated = full[len('{"metric": "allocate_p50_latency_ms", "va'):]
    artifact = tmp_path / "BENCH_r07.json"
    artifact.write_text(json.dumps({"parsed": None, "tail": truncated + "\n"}))
    got = bench_diff.load_metrics(str(artifact))
    assert got["mfu"] == 0.5577
    assert got["serve_tokens_per_sec"] == 3180.5
    assert "metric" not in got  # the truncated-away prefix is gone, not faked


def test_load_metrics_skips_marker_lines_and_non_metric_parsed(tmp_path):
    """Neither a driver-appended status line after the metrics line nor a
    'parsed' dict that latched onto a non-metric line may mask recoverable
    metrics."""
    metrics = json.dumps({"mfu": 0.51, "value": 1.0})
    tail = metrics + "\n" + json.dumps({"exit": 0}) + "\n"
    artifact = tmp_path / "BENCH_r08.json"
    artifact.write_text(json.dumps({"parsed": {"exit": 0}, "tail": tail}))
    assert bench_diff.load_metrics(str(artifact))["mfu"] == 0.51


def test_load_metrics_exits_loudly_on_unusable_artifact(tmp_path):
    import pytest

    artifact = tmp_path / "BENCH_r06.json"
    artifact.write_text(json.dumps({"parsed": None, "tail": "no json here"}))
    with pytest.raises(SystemExit, match="unusable"):
        bench_diff.load_metrics(str(artifact))


def test_committed_r04_artifact_is_recoverable():
    """The real committed round-4 artifact (front-truncated tail) must be
    readable by the tripwire — this was VERDICT r4 item 1."""
    got = bench_diff.load_metrics(os.path.join(REPO, "BENCH_r04.json"))
    assert got["mfu"] == 0.5577
    assert got["aggregate_chip_busy_fraction"] == 0.9996


def test_compact_headline_fits_capture_and_carries_tracked_metrics():
    """bench.py's FINAL stdout line must fit the driver's 2000-byte tail
    capture and carry every tripwire-tracked metric, so BENCH_r05+ always
    parses (VERDICT r4: r04's single fat line truncated mid-JSON)."""
    import bench as bench_mod

    fat = {k: 12345.6789 for k in bench_diff.TRACKED_UP}
    fat.update({
        "metric": "allocate_p50_latency_ms", "value": 0.5, "unit": "ms",
        "vs_baseline": 0.01, "busy_platform": "axon",
        "flash_vs_xla_detail": {str(s): {"flash_ms": 1.0} for s in range(20)},
    })
    line = bench_mod.compact_headline(fat)
    assert len(line.encode()) <= 1900
    parsed = json.loads(line)
    for key in bench_diff.TRACKED_UP:
        assert key in parsed, key
    assert "flash_vs_xla_detail" not in parsed  # detail stays off the line


def test_diff_warns_on_drop_and_notes_gains():
    old = {"mfu": 0.5, "decode_tokens_per_sec": 1000.0,
           "serve_tokens_per_sec": 100.0}
    new = {"mfu": 0.45, "decode_tokens_per_sec": 1100.0,
           "serve_tokens_per_sec": 101.0}
    lines = bench_diff.diff(new, old, threshold=0.02)
    assert any(line.startswith("WARN") and "mfu" in line for line in lines)
    assert any(line.startswith("INFO") and "decode" in line for line in lines)
    # 1% move: below threshold, silent.
    assert not any("serve_tokens_per_sec" in line for line in lines)


def test_diff_says_no_baseline_instead_of_skipping_silently():
    """A TRACKED metric present in the fresh run but absent from the
    baseline artifact must print an explicit NO BASELINE line — the
    committed artifact predates PRs 6-9, so the fleet_*/selfheal_*
    guardrails were dead AND invisible until this note existed."""
    old = {"mfu": 0.5}
    new = {"mfu": 0.5, "fleet_tokens_per_sec": 900.0,
           "fleet_slo_attainment_interactive": 0.98,
           "selfheal_restore_ms": 120.0}
    lines = bench_diff.diff(new, old, threshold=0.02)
    for key in (
        "fleet_tokens_per_sec", "fleet_slo_attainment_interactive",
        "selfheal_restore_ms",
    ):
        assert any(
            line.startswith("NOTE") and "NO BASELINE" in line
            and key in line for line in lines
        ), (key, lines)
    # A metric absent from BOTH sides stays silent (nothing to note).
    assert not any("superstep_tokens_per_sec" in line for line in lines)


def test_every_tracked_metric_rides_the_compact_headline():
    """bench_diff's guardrails read the compact headline the driver
    captures; a tracked key missing from bench.COMPACT_KEYS would make
    its tripwire silently dead on every driver run."""
    import bench as bench_mod

    tracked = set(bench_diff.TRACKED_UP) | set(bench_diff.TRACKED_DOWN)
    missing = tracked - set(bench_mod.COMPACT_KEYS)
    assert not missing, missing


def test_diff_skips_busy_across_platform_change_and_flags_fallback():
    old = {"busy_platform": "axon", "aggregate_chip_busy_fraction": 0.99}
    new = {"busy_platform": "cpu", "aggregate_chip_busy_fraction": 0.5,
           "busy_platform_fallback": True, "busy_fallback_reason": "boom"}
    lines = bench_diff.diff(new, old, threshold=0.02)
    assert not any("aggregate_chip_busy_fraction" in line for line in lines)
    assert any("platform changed" in line for line in lines)
    assert any("FALLBACK" in line and "boom" in line for line in lines)


def test_cli_against_committed_artifact(tmp_path):
    new = tmp_path / "new.json"
    new.write_text(json.dumps({"mfu": 0.0001}))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_diff.py"), str(new)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0  # loud, never a gate
    assert "WARN" in out.stdout and "mfu" in out.stdout


def test_latest_committed_picks_highest_round(tmp_path):
    for n in (1, 3, 2):
        (tmp_path / f"BENCH_r{n:02d}.json").write_text("{}")
    assert bench_diff.latest_committed(str(tmp_path)).endswith("BENCH_r03.json")

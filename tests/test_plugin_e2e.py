"""End-to-end plugin tests over real unix-socket gRPC with a fake kubelet.

Covers registration, ListAndWatch streaming (incl. health transitions and
recovery), Allocate semantics for exclusive and time-sliced resources, and
GetPreferredAllocation spreading — the full kubelet-facing surface
(reference call stacks: SURVEY.md §3.2-3.4).
"""

import os
import time
import threading

import pytest

from tpu_device_plugin.api import pb
from tpu_device_plugin.api.constants import HEALTHY, UNHEALTHY, VERSION
from tpu_device_plugin.backend.fake import FakeChipManager
from tpu_device_plugin.config import Config, Flags
from tpu_device_plugin.device import Unit
from tpu_device_plugin.plugin import TpuDevicePlugin
from tpu_device_plugin.allocator import SimplePolicy

from .fake_kubelet import FakeKubelet


def chip_units(mgr):
    return [Unit(id=c.id, chips=[c]) for c in mgr.devices()]


@pytest.fixture
def kubelet(tmp_path):
    k = FakeKubelet(str(tmp_path / "device-plugins"))
    k.start()
    yield k
    k.stop()


def make_plugin(kubelet, mgr, lease_dir, **kwargs):
    cfg = Config(flags=Flags(backend="fake", driver_root="/"))
    defaults = dict(
        config=cfg,
        resource_name="google.com/tpu",
        units_fn=lambda: chip_units(mgr),
        chip_manager=mgr,
        socket_path=os.path.join(kubelet.plugin_dir, "tpu.sock"),
        kubelet_socket=kubelet.socket_path,
        allocate_policy=None,
        lease_dir=lease_dir,
    )
    defaults.update(kwargs)
    return TpuDevicePlugin(**defaults)


@pytest.fixture
def backend():
    mgr = FakeChipManager(n_chips=4, chips_per_tray=4)
    mgr.init()
    return mgr


def first_response(stream):
    return next(iter(stream))


def test_register_and_list(kubelet, backend, tmp_path):
    plugin = make_plugin(kubelet, backend, str(tmp_path / "leases"))
    plugin.start()
    try:
        reg = kubelet.wait_for_registration()
        assert reg.version == VERSION
        assert reg.resource_name == "google.com/tpu"
        assert reg.endpoint == "tpu.sock"
        assert not reg.options.get_preferred_allocation_available

        stub = kubelet.plugin_client(reg.endpoint)
        resp = first_response(stub.ListAndWatch(pb.Empty()))
        assert [d.ID for d in resp.devices] == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
        assert all(d.health == HEALTHY for d in resp.devices)
        assert all(d.topology.nodes[0].ID == 0 for d in resp.devices)

        opts = stub.GetDevicePluginOptions(pb.Empty())
        assert not opts.get_preferred_allocation_available
    finally:
        plugin.stop()
    assert not os.path.exists(plugin.socket_path)


def test_allocate_exclusive(kubelet, backend, tmp_path):
    plugin = make_plugin(kubelet, backend, str(tmp_path / "leases"))
    plugin.start()
    try:
        stub = kubelet.plugin_client("tpu.sock")
        resp = stub.Allocate(
            pb.AllocateRequest(
                container_requests=[
                    pb.ContainerAllocateRequest(devicesIDs=["tpu-1", "tpu-2"])
                ]
            )
        )
        (container,) = resp.container_responses
        assert container.envs["TPU_VISIBLE_CHIPS"] == "tpu-1,tpu-2"
        # libtpu process env: chip indices + process grid.
        assert container.envs["TPU_VISIBLE_DEVICES"] == "1,2"
        assert container.envs["TPU_PROCESS_BOUNDS"] == "1,1,1"
        assert container.envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,1,1"
        assert "TPU_ALLOW_MULTIPLE_LIBTPU_LOAD" not in container.envs
        # Device nodes are passed by default (primary mechanism on TPU).
        paths = [d.host_path for d in container.devices]
        assert "/dev/accel1" in paths and "/dev/accel2" in paths
        assert all(d.permissions == "rw" for d in container.devices)
        assert container.annotations["tpu-device-plugin/chips"] == "tpu-1,tpu-2"
    finally:
        plugin.stop()


def test_allocate_unknown_device_rejected(kubelet, backend, tmp_path):
    import grpc

    plugin = make_plugin(kubelet, backend, str(tmp_path / "leases"))
    plugin.start()
    try:
        stub = kubelet.plugin_client("tpu.sock")
        with pytest.raises(grpc.RpcError) as err:
            stub.Allocate(
                pb.AllocateRequest(
                    container_requests=[
                        pb.ContainerAllocateRequest(devicesIDs=["nope"])
                    ]
                )
            )
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        plugin.stop()


def test_health_transition_and_recovery(kubelet, backend, tmp_path):
    plugin = make_plugin(kubelet, backend, str(tmp_path / "leases"))
    plugin.start()
    try:
        stub = kubelet.plugin_client("tpu.sock")
        stream = stub.ListAndWatch(pb.Empty())
        it = iter(stream)
        first = next(it)
        assert all(d.health == HEALTHY for d in first.devices)

        backend.inject("tpu-2", UNHEALTHY)
        update = next(it)
        health = {d.ID: d.health for d in update.devices}
        assert health["tpu-2"] == UNHEALTHY
        assert health["tpu-0"] == HEALTHY

        # Recovery path (the reference's server.go:259 FIXME, fixed here).
        backend.inject("tpu-2", HEALTHY)
        update = next(it)
        assert {d.ID: d.health for d in update.devices}["tpu-2"] == HEALTHY
        stream.cancel()
    finally:
        plugin.stop()


def test_shared_resource_replicas_and_preferred_allocation(kubelet, backend, tmp_path):
    plugin = make_plugin(
        kubelet,
        backend,
        str(tmp_path / "leases"),
        resource_name="google.com/shared-tpu",
        socket_path=os.path.join(kubelet.plugin_dir, "shared-tpu.sock"),
        replicas=2,
    )
    plugin.start()
    try:
        reg = kubelet.wait_for_registration()
        assert reg.options.get_preferred_allocation_available

        stub = kubelet.plugin_client("shared-tpu.sock")
        resp = first_response(stub.ListAndWatch(pb.Empty()))
        ids = [d.ID for d in resp.devices]
        assert len(ids) == 8  # 4 chips x 2 replicas
        assert "tpu-0-replica-0" in ids and "tpu-3-replica-1" in ids

        # Preferred allocation spreads across physical chips.
        pref = stub.GetPreferredAllocation(
            pb.PreferredAllocationRequest(
                container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=ids, allocation_size=2
                    )
                ]
            )
        )
        (presp,) = pref.container_responses
        chosen = list(presp.deviceIDs)
        assert len({c.split("-replica-")[0] for c in chosen}) == 2

        # Allocating two replicas of one chip yields ONE visible chip and the
        # sharing environment.
        resp = stub.Allocate(
            pb.AllocateRequest(
                container_requests=[
                    pb.ContainerAllocateRequest(
                        devicesIDs=["tpu-0-replica-0", "tpu-0-replica-1"]
                    )
                ]
            )
        )
        (container,) = resp.container_responses
        assert container.envs["TPU_VISIBLE_CHIPS"] == "tpu-0"
        assert container.envs["TPU_VISIBLE_DEVICES"] == "0"
        assert container.envs["TPU_ALLOW_MULTIPLE_LIBTPU_LOAD"] == "1"
        assert container.envs["TPU_DEVICE_PLUGIN_SHARED"] == "1"
        lease_dir = container.envs["TPU_SHARED_LEASE_DIR"]
        assert any(m.host_path == lease_dir for m in container.mounts)
    finally:
        plugin.stop()


def test_auto_replicas_one_per_gib(kubelet, tmp_path):
    mgr = FakeChipManager(n_chips=1, chips_per_tray=4, hbm_gib=16)
    mgr.init()
    plugin = make_plugin(
        kubelet,
        mgr,
        str(tmp_path / "leases"),
        replicas=1,
        auto_replicas=True,
    )
    plugin.start()
    try:
        stub = kubelet.plugin_client("tpu.sock")
        resp = first_response(stub.ListAndWatch(pb.Empty()))
        assert len(resp.devices) == 16  # 16 GiB HBM -> 16 replicas
    finally:
        plugin.stop()


def test_auto_replicas_kv_pages_per_chip(kubelet, tmp_path):
    mgr = FakeChipManager(n_chips=1, chips_per_tray=4, hbm_gib=16)
    mgr.init()
    plugin = make_plugin(
        kubelet,
        mgr,
        str(tmp_path / "leases"),
        replicas=1,
        auto_replicas=True,
        kv_page_bytes=4 << 30,
    )
    plugin.start()
    try:
        stub = kubelet.plugin_client("tpu.sock")
        resp = first_response(stub.ListAndWatch(pb.Empty()))
        # 16 GiB HBM / 4 GiB per KV page -> 4 replicas, not 16.
        assert len(resp.devices) == 4
    finally:
        plugin.stop()


def test_policy_path_preferred_allocation(kubelet, backend, tmp_path):
    plugin = make_plugin(
        kubelet,
        backend,
        str(tmp_path / "leases"),
        allocate_policy=SimplePolicy(),
    )
    plugin.start()
    try:
        stub = kubelet.plugin_client("tpu.sock")
        pref = stub.GetPreferredAllocation(
            pb.PreferredAllocationRequest(
                container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=["tpu-0", "tpu-1", "tpu-2", "tpu-3"],
                        must_include_deviceIDs=["tpu-2"],
                        allocation_size=2,
                    )
                ]
            )
        )
        (presp,) = pref.container_responses
        assert list(presp.deviceIDs) == ["tpu-0", "tpu-2"]
    finally:
        plugin.stop()


def test_volume_mounts_strategy_and_index_ids(kubelet, backend, tmp_path):
    cfg = Config(
        flags=Flags(
            backend="fake",
            device_list_strategy="volume-mounts",
            device_id_strategy="index",
        )
    )
    plugin = make_plugin(kubelet, backend, str(tmp_path / "leases"), config=cfg)
    plugin.start()
    try:
        stub = kubelet.plugin_client("tpu.sock")
        resp = stub.Allocate(
            pb.AllocateRequest(
                container_requests=[
                    pb.ContainerAllocateRequest(devicesIDs=["tpu-3"])
                ]
            )
        )
        (container,) = resp.container_responses
        assert container.envs["TPU_VISIBLE_CHIPS"] == "/var/run/tpu-container-devices"
        mounts = {m.container_path: m.host_path for m in container.mounts}
        assert mounts["/var/run/tpu-container-devices/3"] == "/dev/null"
    finally:
        plugin.stop()


def test_server_stays_up_without_spurious_restarts(kubelet, backend, tmp_path):
    """Regression: grpc's wait_for_termination returns True on *timeout*;
    misreading it restarted a healthy server every 0.5s until the crash
    budget declared the plugin fatal."""
    fatals = []
    plugin = make_plugin(
        kubelet, backend, str(tmp_path / "leases"), on_fatal=fatals.append
    )
    plugin.start()
    try:
        server = plugin._server
        time.sleep(1.6)  # several monitor periods
        assert plugin._server is server  # no silent restart happened
        assert fatals == []
        assert os.path.exists(plugin.socket_path)
        # And the server still answers.
        stub = kubelet.plugin_client("tpu.sock")
        stub.GetDevicePluginOptions(pb.Empty())
    finally:
        plugin.stop()


def test_prestart_container_noop(kubelet, backend, tmp_path):
    plugin = make_plugin(kubelet, backend, str(tmp_path / "leases"))
    plugin.start()
    try:
        stub = kubelet.plugin_client("tpu.sock")
        stub.PreStartContainer(pb.PreStartContainerRequest(devicesIDs=["tpu-0"]))
    finally:
        plugin.stop()

"""Pallas flash attention vs the naive reference, interpret mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.ops.attention import flash_attention


def naive_attention(q, k, v, causal=True):
    head_dim = q.shape[-1]
    s = jnp.einsum("bshk,bthk->bhst", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s / np.sqrt(head_dim)
    if causal:
        seq = q.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhst,bthk->bshk", w, v.astype(jnp.float32)).astype(q.dtype)


def make_qkv(batch=2, seq=64, heads=2, head_dim=32, dtype=jnp.float32, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (batch, seq, heads, head_dim)
    return tuple(jax.random.normal(k, shape, dtype) for k in keys)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_naive(causal):
    q, k, v = make_qkv()
    out = flash_attention(q, k, v, causal, True)
    expected = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_blocked_path_matches_naive():
    """seq larger than the block sizes: exercises the online-softmax loop
    and the causal block-skip bound."""
    q, k, v = make_qkv(seq=96)
    out = flash_attention(q, k, v, True, True, 32, 16)
    expected = naive_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ragged_seq_padding():
    """seq not a multiple of the blocks: padded rows/cols must not leak."""
    q, k, v = make_qkv(seq=50)
    out = flash_attention(q, k, v, True, True, 16, 16)
    expected = naive_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_bfloat16_compute():
    q, k, v = make_qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, True, True)
    expected = naive_attention(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected, np.float32), atol=3e-2
    )


def test_gradients_match_naive():
    q, k, v = make_qkv(seq=48, head_dim=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, True, 16, 16) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v, True) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=1e-4, err_msg=f"d{name}"
        )


def test_jit_and_model_integration(monkeypatch):
    """flash path selected through the model config compiles under jit.
    The short-seq routing would send seq=16 to the dense core, so the
    threshold is dropped to keep the kernel in the compiled path."""
    import workloads.model as model_mod
    from workloads.model import ModelConfig, init_params, make_forward_fn

    monkeypatch.setattr(model_mod, "flash_min_seq", lambda: 1)
    config = ModelConfig(max_seq_len=32, attention_impl="flash")
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = jax.jit(make_forward_fn(config))(params, tokens)
    assert logits.shape == (2, 16, config.vocab_size)

    naive_cfg = ModelConfig(max_seq_len=32, attention_impl="native")
    expected = jax.jit(make_forward_fn(naive_cfg))(params, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(expected), atol=5e-2
    )


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_backward_matches_xla_backward(causal):
    """The blocked backward kernels against the dense-XLA backward, on a
    blocked + ragged shape (padding rows must not leak gradient)."""
    q, k, v = make_qkv(seq=50, head_dim=16)

    def loss(impl):
        def f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal, True, 16, 16, impl) ** 2
            )
        return f

    got = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=1e-4, err_msg=f"d{name}"
        )


def test_pallas_backward_matches_naive_gradients():
    q, k, v = make_qkv(seq=48, head_dim=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, True, 16, 16, "pallas") ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v, True) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=1e-4, err_msg=f"d{name}"
        )


class TestGroupedQuery:
    """GQA: k/v carry fewer heads; the kernels read each shared k/v head
    through grid index maps (no materialised repeat)."""

    @staticmethod
    def gqa_ref(q, k, v, causal):
        group = q.shape[2] // k.shape[2]
        return naive_attention(
            q, jnp.repeat(k, group, axis=2), jnp.repeat(v, group, axis=2),
            causal,
        )

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("kv_heads", [1, 2])
    def test_forward_matches_repeated_reference(self, causal, kv_heads):
        q, _, _ = make_qkv(heads=4, seq=96)
        _, k, v = make_qkv(heads=kv_heads, seq=96, seed=1)
        out = flash_attention(q, k, v, causal, True, 32, 32)
        expected = self.gqa_ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=2e-5)

    @pytest.mark.parametrize("bwd_impl", ["pallas", "xla"])
    def test_gradients_match_repeated_reference(self, bwd_impl):
        q, _, _ = make_qkv(heads=4, seq=64)
        _, k, v = make_qkv(heads=2, seq=64, seed=1)

        def loss_flash(q, k, v):
            return (
                flash_attention(q, k, v, True, True, 32, 32, bwd_impl) ** 2
            ).sum()

        def loss_ref(q, k, v):
            return (self.gqa_ref(q, k, v, True) ** 2).sum()

        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        assert got[1].shape == k.shape and got[2].shape == v.shape
        for name, g, w in zip("dq dk dv".split(), got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-4, err_msg=name
            )

    def test_indivisible_heads_rejected(self):
        q, _, _ = make_qkv(heads=4)
        _, k, v = make_qkv(heads=3, seed=1)
        with pytest.raises(ValueError, match="multiple of kv heads"):
            flash_attention(q, k, v, True, True)


class TestSlidingWindow:
    """Causal sliding-window attention: row i attends [i-window+1, i]."""

    @staticmethod
    def banded_ref(q, k, v, window):
        head_dim = q.shape[-1]
        seq = q.shape[1]
        s = jnp.einsum(
            "bshk,bthk->bhst", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / np.sqrt(head_dim)
        ids = jnp.arange(seq)
        mask = (ids[None, :] <= ids[:, None]) & (
            ids[None, :] > ids[:, None] - window
        )
        s = jnp.where(mask[None, None], s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhst,bthk->bshk", w, v.astype(jnp.float32)).astype(
            q.dtype
        )

    @pytest.mark.parametrize("window", [1, 16, 40])
    def test_forward_matches_banded_reference(self, window):
        q, k, v = make_qkv(seq=96)
        out = flash_attention(q, k, v, True, True, 32, 32, window=window)
        expected = self.banded_ref(q, k, v, window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=2e-5)

    @pytest.mark.parametrize("bwd_impl", ["pallas", "xla"])
    def test_gradients_match_banded_reference(self, bwd_impl):
        window = 24
        q, k, v = make_qkv(seq=96)

        def loss_flash(q, k, v):
            return (
                flash_attention(q, k, v, True, True, 32, 32, bwd_impl,
                                window) ** 2
            ).sum()

        def loss_ref(q, k, v):
            return (self.banded_ref(q, k, v, window) ** 2).sum()

        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, g, w in zip("dq dk dv".split(), got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-4, err_msg=name
            )

    def test_window_one_attends_self_only(self):
        q, k, v = make_qkv(seq=32)
        out = flash_attention(q, k, v, True, True, 32, 32, window=1)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(v), atol=2e-5
        )

    def test_window_with_gqa(self):
        q, _, _ = make_qkv(heads=4, seq=64)
        _, k, v = make_qkv(heads=2, seq=64, seed=1)
        group = 2
        out = flash_attention(q, k, v, True, True, 32, 32, window=16)
        expected = self.banded_ref(
            q, jnp.repeat(k, group, axis=2), jnp.repeat(v, group, axis=2), 16
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=2e-5)

    def test_validation(self):
        q, k, v = make_qkv(seq=16)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, False, True, window=8)
        with pytest.raises(ValueError, match="window"):
            flash_attention(q, k, v, True, True, window=0)


class TestSegmentIds:
    """Sequence packing: segment ids mask cross-document attention."""

    @staticmethod
    def seg_ref(q, k, v, seg, causal=True):
        head_dim = q.shape[-1]
        seq = q.shape[1]
        s = jnp.einsum(
            "bshk,bthk->bhst", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / np.sqrt(head_dim)
        mask = seg[:, :, None] == seg[:, None, :]
        if causal:
            mask = mask & jnp.tril(jnp.ones((seq, seq), bool))[None]
        s = jnp.where(mask[:, None], s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhst,bthk->bshk", w, v.astype(jnp.float32)).astype(
            q.dtype
        )

    @staticmethod
    def make_segments(batch, seq, boundary):
        ids = (jnp.arange(seq) >= boundary).astype(jnp.int32)
        return jnp.tile(ids[None], (batch, 1))

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_segmented_reference(self, causal):
        q, k, v = make_qkv(seq=96)
        seg = self.make_segments(2, 96, boundary=40)
        out = flash_attention(q, k, v, causal, True, 32, 32, segment_ids=seg)
        expected = self.seg_ref(q, k, v, seg, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=2e-5)

    def test_packed_equals_separate_documents(self):
        """The defining property: two documents packed into one sequence
        attend exactly as if each were its own sequence."""
        q1, k1, v1 = make_qkv(batch=1, seq=32, seed=0)
        q2, k2, v2 = make_qkv(batch=1, seq=32, seed=1)
        packed_q = jnp.concatenate([q1, q2], axis=1)
        packed_k = jnp.concatenate([k1, k2], axis=1)
        packed_v = jnp.concatenate([v1, v2], axis=1)
        seg = self.make_segments(1, 64, boundary=32)
        packed = flash_attention(
            packed_q, packed_k, packed_v, True, True, 32, 32,
            segment_ids=seg,
        )
        sep1 = flash_attention(q1, k1, v1, True, True, 32, 32)
        sep2 = flash_attention(q2, k2, v2, True, True, 32, 32)
        np.testing.assert_allclose(
            np.asarray(packed[:, :32]), np.asarray(sep1), atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(packed[:, 32:]), np.asarray(sep2), atol=2e-5
        )

    @pytest.mark.parametrize("bwd_impl", ["pallas", "xla"])
    def test_gradients_match_segmented_reference(self, bwd_impl):
        q, k, v = make_qkv(seq=64)
        seg = self.make_segments(2, 64, boundary=24)

        def loss_flash(q, k, v):
            return (
                flash_attention(q, k, v, True, True, 32, 32, bwd_impl,
                                segment_ids=seg) ** 2
            ).sum()

        def loss_ref(q, k, v):
            return (self.seg_ref(q, k, v, seg) ** 2).sum()

        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, g, w in zip("dq dk dv".split(), got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-4, err_msg=name
            )

    def test_segments_compose_with_gqa(self):
        q, _, _ = make_qkv(heads=4, seq=64)
        _, k, v = make_qkv(heads=2, seq=64, seed=1)
        seg = self.make_segments(2, 64, boundary=24)
        out = flash_attention(q, k, v, True, True, 32, 32, segment_ids=seg)
        expected = self.seg_ref(
            q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2), seg
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=2e-5)


def test_segment_ids_shape_validated():
    q, k, v = make_qkv(seq=16)
    bad = jnp.zeros((2, 8), jnp.int32)  # too short
    with pytest.raises(ValueError, match="segment_ids shape"):
        flash_attention(q, k, v, True, True, segment_ids=bad)
    with pytest.raises(ValueError, match="segment_ids shape"):
        flash_attention(q, k, v, True, True,
                        segment_ids=jnp.zeros((1, 16), jnp.int32))

"""Pallas flash attention vs the naive reference, interpret mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.ops.attention import flash_attention


def naive_attention(q, k, v, causal=True):
    head_dim = q.shape[-1]
    s = jnp.einsum("bshk,bthk->bhst", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s / np.sqrt(head_dim)
    if causal:
        seq = q.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhst,bthk->bshk", w, v.astype(jnp.float32)).astype(q.dtype)


def make_qkv(batch=2, seq=64, heads=2, head_dim=32, dtype=jnp.float32, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (batch, seq, heads, head_dim)
    return tuple(jax.random.normal(k, shape, dtype) for k in keys)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_naive(causal):
    q, k, v = make_qkv()
    out = flash_attention(q, k, v, causal, True)
    expected = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_blocked_path_matches_naive():
    """seq larger than the block sizes: exercises the online-softmax loop
    and the causal block-skip bound."""
    q, k, v = make_qkv(seq=96)
    out = flash_attention(q, k, v, True, True, 32, 16)
    expected = naive_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ragged_seq_padding():
    """seq not a multiple of the blocks: padded rows/cols must not leak."""
    q, k, v = make_qkv(seq=50)
    out = flash_attention(q, k, v, True, True, 16, 16)
    expected = naive_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_bfloat16_compute():
    q, k, v = make_qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, True, True)
    expected = naive_attention(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected, np.float32), atol=3e-2
    )


def test_gradients_match_naive():
    q, k, v = make_qkv(seq=48, head_dim=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, True, 16, 16) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v, True) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=1e-4, err_msg=f"d{name}"
        )


def test_jit_and_model_integration():
    """flash path selected through the model config compiles under jit."""
    from workloads.model import ModelConfig, init_params, make_forward_fn

    config = ModelConfig(max_seq_len=32, attention_impl="flash")
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = jax.jit(make_forward_fn(config))(params, tokens)
    assert logits.shape == (2, 16, config.vocab_size)

    naive_cfg = ModelConfig(max_seq_len=32, attention_impl="native")
    expected = jax.jit(make_forward_fn(naive_cfg))(params, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(expected), atol=5e-2
    )


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_backward_matches_xla_backward(causal):
    """The blocked backward kernels against the dense-XLA backward, on a
    blocked + ragged shape (padding rows must not leak gradient)."""
    q, k, v = make_qkv(seq=50, head_dim=16)

    def loss(impl):
        def f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal, True, 16, 16, impl) ** 2
            )
        return f

    got = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=1e-4, err_msg=f"d{name}"
        )


def test_pallas_backward_matches_naive_gradients():
    q, k, v = make_qkv(seq=48, head_dim=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, True, 16, 16, "pallas") ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v, True) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=1e-4, err_msg=f"d{name}"
        )

"""JAX workloads on the virtual 8-device CPU mesh: forward, sharded train
step, lease client, busy probe, graft entry points."""

import os
import sys
import threading

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def jax_cpu():
    import jax

    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return jax


def test_forward_shapes_and_dtype(jax_cpu):
    import jax.numpy as jnp

    from workloads.model import ModelConfig, forward, init_params

    config = ModelConfig(max_seq_len=16)
    params = init_params(config, jax_cpu.random.PRNGKey(0))
    tokens = jnp.zeros((2, 8), jnp.int32)
    logits = forward(params, tokens, config)
    assert logits.shape == (2, 8, config.vocab_size)
    assert logits.dtype == jnp.float32


def test_loss_decreases_over_steps(jax_cpu):
    from workloads.model import ModelConfig
    from workloads.train import (
        make_mesh,
        make_train_state,
        make_train_step,
        synthetic_batch,
    )

    config = ModelConfig(max_seq_len=16, n_layers=1)
    mesh = make_mesh(8)
    (params, opt_state), optimizer = make_train_state(config, mesh)
    step = make_train_step(config, mesh, optimizer)
    tokens = synthetic_batch(config, batch_size=8)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_mesh_shape_and_param_sharding(jax_cpu):
    from jax.sharding import PartitionSpec as P

    from workloads.model import ModelConfig
    from workloads.train import make_mesh, make_train_state

    mesh = make_mesh(8)
    assert dict(mesh.shape) == {"data": 2, "model": 4}
    config = ModelConfig(max_seq_len=16, n_layers=1)
    (params, opt_state), _ = make_train_state(config, mesh)
    wqkv = params["layers"][0]["wqkv"]
    assert wqkv.sharding.spec == P(None, None, "model", None)
    # The head axis is actually split 4 ways across the model axis.
    assert wqkv.addressable_shards[0].data.shape[2] == config.n_heads // 4
    # Default optimizer: first moment in bf16 (the measured HBM-stream
    # lever, docs/MFU_EXPERIMENTS.md) — and STILL sharded like its
    # parameter, not silently replicated by the dtype mismatch.
    import jax.numpy as jnp

    mu = opt_state[0].mu["layers"][0]["wqkv"]
    assert mu.dtype == jnp.bfloat16
    assert mu.sharding.spec == P(None, None, "model", None)
    nu = opt_state[0].nu["layers"][0]["wqkv"]
    assert nu.dtype == jnp.float32  # second moment keeps full precision


def test_graft_entry_compiles(jax_cpu):
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as graft

    fn, args = graft.entry()
    lowered = jax_cpu.jit(fn).lower(*args)
    compiled = lowered.compile()
    out = compiled(*args)
    assert out.shape[0] == args[1].shape[0]


def test_graft_dryrun_multichip(jax_cpu, capsys):
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)
    assert "mesh={'data': 2, 'model': 4}" in capsys.readouterr().out


class TestLease:
    def test_gang_lease_mutual_exclusion(self, tmp_path):
        from workloads import lease

        lease_dir = str(tmp_path)
        chips = ["tpu-0", "tpu-1"]
        order = []
        ready = threading.Event()
        release_main = threading.Event()

        def competitor():
            ready.set()
            with lease.chip_lease(chips, lease_dir):
                order.append("competitor")

        with lease.chip_lease(chips, lease_dir):
            order.append("main")
            t = threading.Thread(target=competitor)
            t.start()
            ready.wait(5)
            # Competitor must be blocked while we hold the gang lease.
            assert lease.try_chip_lease(chips, lease_dir) is None
        t.join(timeout=10)
        assert order == ["main", "competitor"]

    def test_try_lease_release(self, tmp_path):
        from workloads import lease

        release = lease.try_chip_lease(["tpu-0"], str(tmp_path))
        assert release is not None
        assert lease.try_chip_lease(["tpu-0"], str(tmp_path)) is None
        release()
        release2 = lease.try_chip_lease(["tpu-0"], str(tmp_path))
        assert release2 is not None
        release2()

    def test_env_defaults(self, tmp_path, monkeypatch):
        from workloads import lease

        monkeypatch.setenv("TPU_VISIBLE_CHIPS", "tpu-1,tpu-0")
        monkeypatch.setenv("TPU_SHARED_LEASE_DIR", str(tmp_path))
        with lease.chip_lease():
            assert os.path.exists(tmp_path / "chip-tpu-0.lock")
            assert os.path.exists(tmp_path / "chip-tpu-1.lock")

    def test_hold_claim_leases(self, tmp_path, monkeypatch):
        """Lifetime declaration: no-op without the env, flocks taken and
        held (observable via claim_lease_state) with it, idempotent, and
        SHARED — time-sliced siblings on one chip all hold at once and
        the chip reads alive until the LAST of them exits."""
        import fcntl

        from tpu_device_plugin.sharing import claim_lease_path, claim_lease_state
        from workloads import lease

        monkeypatch.setenv("TPU_VISIBLE_CHIPS", "tpu-0,tpu-1")
        monkeypatch.delenv("TPU_CLAIM_LEASE_DIR", raising=False)
        assert lease.hold_claim_leases() == 0  # non-mixed: no env, no-op

        monkeypatch.setenv("TPU_CLAIM_LEASE_DIR", str(tmp_path))
        held = lease.hold_claim_leases()
        try:
            assert held == 2
            assert claim_lease_state("tpu-0", str(tmp_path)) is True
            assert claim_lease_state("tpu-1", str(tmp_path)) is True
            # Idempotent: the second call already declares these chips.
            assert lease.hold_claim_leases() == 0
            assert claim_lease_state("tpu-9", str(tmp_path)) is None
            # A sibling's shared flock composes with ours (no blocking).
            sib = os.open(claim_lease_path(str(tmp_path), "tpu-0"), os.O_RDWR)
            fcntl.flock(sib, fcntl.LOCK_SH)
        finally:
            for fd in lease._claim_fds:
                os.close(fd)
            lease._claim_fds.clear()
            lease._claim_paths.clear()
        # One sibling still alive: the chip still reads alive.
        assert claim_lease_state("tpu-0", str(tmp_path)) is True
        os.close(sib)
        # The LAST holder's exit reads as observed death.
        assert claim_lease_state("tpu-0", str(tmp_path)) is False


def test_burst_calibration_floors_and_caps(monkeypatch):
    """A jitter-dominated (or degenerate) slope must not size an
    hours-long lease-holding burst: the per-step estimate is floored and
    the step count capped."""
    import workloads.busy_probe as bp

    # Degenerate slope: measure_slope_secs returns its 1e-9 floor.
    monkeypatch.setattr(
        "workloads.perfbench.measure_slope_secs", lambda *a, **k: 1e-9
    )
    assert bp._calibrate_steps(lambda n: None, 1.0) == 100_000
    # A sane slope passes through: 10 ms/step at a 1 s target = 100.
    monkeypatch.setattr(
        "workloads.perfbench.measure_slope_secs", lambda *a, **k: 0.01
    )
    assert bp._calibrate_steps(lambda n: None, 1.0) == 100


def test_busy_probe_aggregation(tmp_path, monkeypatch):
    from workloads import busy_probe

    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "tpu-0")
    monkeypatch.setenv("TPU_SHARED_LEASE_DIR", str(tmp_path / "leases"))
    report = str(tmp_path / "stats.jsonl")
    stats = busy_probe.run_probe(0.5, report, matrix_dim=64)
    assert stats["bursts"] >= 1
    assert 0 < stats["busy_fraction"] <= 1
    agg = busy_probe.aggregate(report)
    assert agg["pods"] == 1
    assert agg["aggregate_busy_fraction"] > 0


class TestGroupedQueryModel:
    """GQA config (n_kv_heads < n_heads) through the full model: flash and
    native cores agree, and the sharded train step runs on the mesh."""

    def test_flash_and_native_forward_agree(self, jax_cpu, monkeypatch):
        import jax.numpy as jnp
        import numpy as np

        import workloads.model as model_mod
        from workloads.model import ModelConfig, forward, init_params

        # Keep the kernel in the path despite the short-seq dense routing.
        monkeypatch.setattr(model_mod, "flash_min_seq", lambda: 1)

        base = dict(
            max_seq_len=16, n_layers=1, n_heads=4, n_kv_heads=2,
            dtype=jnp.float32,
        )
        native = ModelConfig(**base, attention_impl="native")
        flash = ModelConfig(**base, attention_impl="flash")
        params = init_params(native, jax_cpu.random.PRNGKey(0))
        tokens = jnp.arange(16, dtype=jnp.int32).reshape(2, 8) % native.vocab_size
        np.testing.assert_allclose(
            np.asarray(forward(params, tokens, native)),
            np.asarray(forward(params, tokens, flash)),
            atol=2e-4,
        )

    def test_param_tree_and_sharded_train_step(self, jax_cpu):
        from jax.sharding import PartitionSpec as P

        from workloads.model import ModelConfig
        from workloads.train import (
            make_mesh,
            make_train_state,
            make_train_step,
            synthetic_batch,
        )

        config = ModelConfig(
            max_seq_len=16, n_layers=1, n_heads=8, n_kv_heads=4
        )
        mesh = make_mesh(8)  # model_parallel=4 divides the 4 kv heads
        (params, opt_state), optimizer = make_train_state(config, mesh)
        layer = params["layers"][0]
        assert "wqkv" not in layer
        assert layer["wq"].sharding.spec == P(None, "model", None)
        assert layer["wkv"].shape == (
            config.d_model, 2, 4, config.head_dim
        )
        step = make_train_step(config, mesh, optimizer)
        tokens = synthetic_batch(config, batch_size=8)
        params, opt_state, loss = step(params, opt_state, tokens)
        assert float(loss) > 0

    def test_indivisible_kv_heads_rejected(self, jax_cpu):
        import pytest as _pytest

        from workloads.model import ModelConfig

        with _pytest.raises(ValueError, match="positive divisor"):
            ModelConfig(n_heads=4, n_kv_heads=3)


def test_flash_config_routes_short_seq_to_dense(jax_cpu):
    """attention_impl="flash" at short seq uses the dense core (measured
    faster below the crossover) unless the score matrix would exceed the
    memory cap — pinned by checking the jaxpr for the pallas call."""
    import jax.numpy as jnp

    from workloads.model import ModelConfig, forward, init_params

    config = ModelConfig(max_seq_len=32, attention_impl="flash")
    params = init_params(config, jax_cpu.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    jaxpr = str(jax_cpu.make_jaxpr(lambda p, t: forward(p, t, config))(params, tokens))
    assert "pallas_call" not in jaxpr  # short seq -> dense core


def test_flash_crossover_consults_device_kind(jax_cpu, monkeypatch):
    """The flash/dense crossover is a per-device-kind measurement, not a
    constant: known kinds read their measured row, unknown kinds (future
    generations, CPU test hosts) fall back to the default instead of a
    guess — and the bench sweep is the documented way to add a row."""
    import workloads.model as model_mod

    class _Dev:
        def __init__(self, kind):
            self.device_kind = kind

    def fake_devices(kind):
        monkeypatch.setattr(model_mod.jax, "devices", lambda: [_Dev(kind)])

    fake_devices("TPU v5 lite")
    assert model_mod.flash_min_seq() == 2048  # measured v5e value
    fake_devices("TPU v99 hyperdrive")
    assert model_mod.flash_min_seq() == model_mod._FLASH_MIN_SEQ_DEFAULT
    fake_devices("cpu")
    assert model_mod.flash_min_seq() == model_mod._FLASH_MIN_SEQ_DEFAULT


def test_kernel_select_per_bucket_dispatch(jax_cpu, monkeypatch):
    """The per-(seq-bucket) kernel dispatch table
    (workloads/ops/kernel_select.py): a measured override wins, the
    per-device-kind defaults cover known chips (flash 0.80x dense at
    1024 on the bench chip -> xla there, flash from 2048), sequences
    past the largest bucket take flash's asymptotic regime, and
    unknown hardware falls back to the legacy single crossover so CPU
    hosts behave exactly as before the table existed."""
    from workloads.ops import kernel_select as ks

    try:
        # Unknown kind (CPU): no table -> threshold fallback.
        assert ks.kernel_table() is None
        assert ks.kernel_for_seq(1024, default_min_seq=2048) == "xla"
        assert ks.kernel_for_seq(2048, default_min_seq=2048) == "flash"
        # Known kind: measured per-bucket picks.
        class _Dev:
            device_kind = "TPU v5 lite"

        monkeypatch.setattr(jax_cpu, "devices", lambda: [_Dev()])
        assert ks.kernel_for_seq(1024) == "xla"  # measured 0.80x
        assert ks.kernel_for_seq(2048) == "flash"
        assert ks.kernel_for_seq(1 << 20) == "flash"  # past the table
        # Injected measurement overrides everything.
        ks.set_kernel_table(
            ks.table_from_measurements({1024: 1.3, 2048: 0.9})
        )
        assert ks.kernel_for_seq(512) == "flash"
        assert ks.kernel_for_seq(2000) == "xla"
        # Artifact round trip: the bench's kernel_pick_seq* fields
        # rebuild the same table.
        art = {"kernel_pick_seq1024": "flash", "kernel_pick_seq2048": "xla",
               "unrelated": 1}
        assert ks.table_from_artifact(art) == {1024: "flash", 2048: "xla"}
        with pytest.raises(ValueError):
            ks.set_kernel_table({128: "fast"})
    finally:
        ks.set_kernel_table(None)

"""KV-cached decoding vs the dense forward: per-step logits and greedy
tokens must match exactly (float32)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.generate import decode_step, generate, init_kv_cache
from workloads.model import ModelConfig, forward, init_params

CONFIG = ModelConfig(max_seq_len=32, n_layers=2, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.PRNGKey(0))


def test_cached_logits_match_dense_forward(params):
    """Feeding a sequence token-by-token through the cache reproduces the
    dense forward's logits at every position."""
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 10), 0, CONFIG.vocab_size, jnp.int32
    )
    dense = forward(params, tokens, CONFIG)  # [b, 10, vocab]

    cache = init_kv_cache(CONFIG, batch=2, max_len=10)
    for pos in range(10):
        logits, cache = decode_step(
            params, cache, tokens[:, pos], jnp.int32(pos), CONFIG
        )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(dense[:, pos]), atol=2e-4,
            err_msg=f"position {pos}",
        )


def test_generate_matches_step_by_step_dense(params):
    """Greedy generation equals re-running the dense forward each step."""
    prompt = jax.random.randint(
        jax.random.PRNGKey(2), (2, 5), 0, CONFIG.vocab_size, jnp.int32
    )
    got = generate(params, prompt, CONFIG, max_new_tokens=6)
    assert got.shape == (2, 6)

    seq = prompt
    expected = []
    for _ in range(6):
        logits = forward(params, seq, CONFIG)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expected.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    expected = jnp.stack(expected, axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_generate_rejects_overlong(params):
    prompt = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="exceeds"):
        generate(params, prompt, CONFIG, max_new_tokens=10)


def test_generate_single_scan_under_jit(params):
    """The whole decode is one compiled call — a second invocation with the
    same shapes hits the jit cache (no retrace)."""
    prompt = jnp.zeros((1, 4), jnp.int32)
    generate(params, prompt, CONFIG, max_new_tokens=4)
    before = generate._cache_size()
    generate(params, prompt + 1, CONFIG, max_new_tokens=4)
    assert generate._cache_size() == before


def test_generate_rejects_empty_prompt(params):
    with pytest.raises(ValueError, match="at least one token"):
        generate(params, jnp.zeros((1, 0), jnp.int32), CONFIG, max_new_tokens=4)


class TestGroupedQuery:
    """GQA (n_kv_heads < n_heads): cached decode still matches the dense
    forward exactly, and the cache is group-factor smaller."""

    GQA = ModelConfig(
        max_seq_len=32, n_layers=2, n_heads=4, n_kv_heads=2,
        dtype=jnp.float32,
    )

    @pytest.fixture(scope="class")
    def gqa_params(self):
        return init_params(self.GQA, jax.random.PRNGKey(0))

    def test_cache_shrinks_by_group_factor(self):
        cache = init_kv_cache(self.GQA, batch=2, max_len=8)
        assert cache.shape[4] == 2  # kv heads, not n_heads

    def test_cached_logits_match_dense_forward(self, gqa_params):
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 10), 0, self.GQA.vocab_size, jnp.int32
        )
        dense = forward(gqa_params, tokens, self.GQA)
        cache = init_kv_cache(self.GQA, batch=2, max_len=10)
        for pos in range(10):
            logits, cache = decode_step(
                gqa_params, cache, tokens[:, pos], jnp.int32(pos), self.GQA
            )
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(dense[:, pos]), atol=2e-4,
                err_msg=f"position {pos}",
            )

    def test_generate_runs(self, gqa_params):
        prompt = jnp.zeros((1, 4), jnp.int32)
        out = generate(prompt=prompt, params=gqa_params, config=self.GQA,
                       max_new_tokens=4)
        assert out.shape == (1, 4)


def test_generate_with_tensor_parallel_params():
    """Serving under the training shardings: generate() consumes params
    laid out by the tensor-parallel specs on the 8-device mesh and matches
    the replicated result token-for-token."""
    from workloads.train import make_mesh, make_train_state

    config = ModelConfig(max_seq_len=32, n_layers=2, dtype=jnp.float32)
    mesh = make_mesh(8)
    (sharded_params, _), _ = make_train_state(config, mesh)
    plain = init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(
        jax.random.PRNGKey(3), (2, 5), 0, config.vocab_size, jnp.int32
    )
    got = generate(sharded_params, prompt, config, max_new_tokens=6)
    want = generate(plain, prompt, config, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestSampling:
    """Temperature / top-k / nucleus sampling, all static-shape inside the
    one-scan decode."""

    def test_temperature_zero_is_greedy(self, params):
        prompt = jnp.zeros((2, 4), jnp.int32)
        greedy = generate(params, prompt, CONFIG, max_new_tokens=5)
        also = generate(
            params, prompt, CONFIG, max_new_tokens=5, temperature=0.0
        )
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(also))

    def test_sampling_is_seeded_and_varies(self, params):
        prompt = jnp.zeros((2, 4), jnp.int32)
        a = generate(params, prompt, CONFIG, max_new_tokens=8,
                     temperature=1.0, rng=jax.random.PRNGKey(0))
        b = generate(params, prompt, CONFIG, max_new_tokens=8,
                     temperature=1.0, rng=jax.random.PRNGKey(0))
        c = generate(params, prompt, CONFIG, max_new_tokens=8,
                     temperature=5.0, rng=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_requires_rng_when_sampling(self, params):
        with pytest.raises(ValueError, match="requires an rng"):
            generate(params, jnp.zeros((1, 4), jnp.int32), CONFIG,
                     max_new_tokens=2, temperature=1.0)

    def test_top_k_one_is_greedy(self, params):
        prompt = jnp.zeros((2, 4), jnp.int32)
        greedy = generate(params, prompt, CONFIG, max_new_tokens=6)
        topk1 = generate(params, prompt, CONFIG, max_new_tokens=6,
                         temperature=0.8, top_k=1,
                         rng=jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))

    def test_sample_logits_top_k_masks(self):
        from workloads.generate import sample_logits

        logits = jnp.array([[3.0, 2.0, 1.0, 0.0]])
        picks = {
            int(sample_logits(logits, jax.random.PRNGKey(s), 1.0, 2, 1.0)[0])
            for s in range(64)
        }
        assert picks <= {0, 1}  # only the top-2 survive the mask
        assert len(picks) == 2

    def test_sample_logits_top_p_nucleus(self):
        from workloads.generate import sample_logits

        # softmax ~ [0.64, 0.24, 0.09, 0.03]: p=0.5 keeps only token 0;
        # p=0.7 keeps {0, 1}.
        logits = jnp.array([[4.0, 3.0, 2.0, 1.0]])
        only0 = {
            int(sample_logits(logits, jax.random.PRNGKey(s), 1.0, 0, 0.5)[0])
            for s in range(32)
        }
        assert only0 == {0}
        both = {
            int(sample_logits(logits, jax.random.PRNGKey(s), 1.0, 0, 0.7)[0])
            for s in range(64)
        }
        assert both == {0, 1}


def test_sampling_knobs_do_not_retrace(params):
    """Varying temperature/top_k/top_p hits the jit cache — only the
    greedy-vs-sampling switch compiles a second executable."""
    prompt = jnp.zeros((1, 4), jnp.int32)
    generate(params, prompt, CONFIG, max_new_tokens=4,
             temperature=0.7, top_k=10, top_p=0.9, rng=jax.random.PRNGKey(0))
    before = generate._cache_size()
    generate(params, prompt, CONFIG, max_new_tokens=4,
             temperature=1.3, top_k=3, top_p=0.5, rng=jax.random.PRNGKey(1))
    assert generate._cache_size() == before


def test_sliding_window_decode_matches_dense_forward():
    """A windowed config: the cached decode's banded mask reproduces the
    dense forward's windowed logits at every position."""
    config = ModelConfig(
        max_seq_len=32, n_layers=2, attention_window=4, dtype=jnp.float32
    )
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 12), 0, config.vocab_size, jnp.int32
    )
    dense = forward(params, tokens, config)
    from workloads.generate import decode_step, init_kv_cache

    cache = init_kv_cache(config, 2, 12)
    for pos in range(12):
        logits, cache = decode_step(
            params, cache, tokens[:, pos], jnp.int32(pos), config
        )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(dense[:, pos]), atol=2e-4,
            err_msg=f"position {pos}",
        )

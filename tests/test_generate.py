"""KV-cached decoding vs the dense forward: per-step logits and greedy
tokens must match exactly (float32)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from workloads.generate import decode_step, generate, init_kv_cache
from workloads.model import ModelConfig, forward, init_params

CONFIG = ModelConfig(max_seq_len=32, n_layers=2, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.PRNGKey(0))


def test_cached_logits_match_dense_forward(params):
    """Feeding a sequence token-by-token through the cache reproduces the
    dense forward's logits at every position."""
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 10), 0, CONFIG.vocab_size, jnp.int32
    )
    dense = forward(params, tokens, CONFIG)  # [b, 10, vocab]

    cache = init_kv_cache(CONFIG, batch=2, max_len=10)
    for pos in range(10):
        logits, cache = decode_step(
            params, cache, tokens[:, pos], jnp.int32(pos), CONFIG
        )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(dense[:, pos]), atol=2e-4,
            err_msg=f"position {pos}",
        )


def test_generate_matches_step_by_step_dense(params):
    """Greedy generation equals re-running the dense forward each step."""
    prompt = jax.random.randint(
        jax.random.PRNGKey(2), (2, 5), 0, CONFIG.vocab_size, jnp.int32
    )
    got = generate(params, prompt, CONFIG, max_new_tokens=6)
    assert got.shape == (2, 6)

    seq = prompt
    expected = []
    for _ in range(6):
        logits = forward(params, seq, CONFIG)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expected.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    expected = jnp.stack(expected, axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_generate_rejects_overlong(params):
    prompt = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="exceeds"):
        generate(params, prompt, CONFIG, max_new_tokens=10)


def test_generate_single_scan_under_jit(params):
    """The whole decode is one compiled call — a second invocation with the
    same shapes hits the jit cache (no retrace)."""
    prompt = jnp.zeros((1, 4), jnp.int32)
    generate(params, prompt, CONFIG, max_new_tokens=4)
    before = generate._cache_size()
    generate(params, prompt + 1, CONFIG, max_new_tokens=4)
    assert generate._cache_size() == before


def test_generate_rejects_empty_prompt(params):
    with pytest.raises(ValueError, match="at least one token"):
        generate(params, jnp.zeros((1, 0), jnp.int32), CONFIG, max_new_tokens=4)


class TestGroupedQuery:
    """GQA (n_kv_heads < n_heads): cached decode still matches the dense
    forward exactly, and the cache is group-factor smaller."""

    GQA = ModelConfig(
        max_seq_len=32, n_layers=2, n_heads=4, n_kv_heads=2,
        dtype=jnp.float32,
    )

    @pytest.fixture(scope="class")
    def gqa_params(self):
        return init_params(self.GQA, jax.random.PRNGKey(0))

    def test_cache_shrinks_by_group_factor(self):
        cache = init_kv_cache(self.GQA, batch=2, max_len=8)
        assert cache.shape[4] == 2  # kv heads, not n_heads

    def test_cached_logits_match_dense_forward(self, gqa_params):
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 10), 0, self.GQA.vocab_size, jnp.int32
        )
        dense = forward(gqa_params, tokens, self.GQA)
        cache = init_kv_cache(self.GQA, batch=2, max_len=10)
        for pos in range(10):
            logits, cache = decode_step(
                gqa_params, cache, tokens[:, pos], jnp.int32(pos), self.GQA
            )
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(dense[:, pos]), atol=2e-4,
                err_msg=f"position {pos}",
            )

    def test_generate_runs(self, gqa_params):
        prompt = jnp.zeros((1, 4), jnp.int32)
        out = generate(prompt=prompt, params=gqa_params, config=self.GQA,
                       max_new_tokens=4)
        assert out.shape == (1, 4)

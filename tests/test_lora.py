"""LoRA fine-tuning (workloads/lora.py): zero-init identity, frozen base,
loss decrease, int8 base, CLI."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from workloads.lora import lora_init, make_lora_train_step, merge_lora
from workloads.model import ModelConfig, forward, init_params

CONFIG = ModelConfig(max_seq_len=16, n_layers=2, dtype=jnp.float32)


def test_zero_init_is_identity():
    params = init_params(CONFIG, jax.random.PRNGKey(0))
    adapters = lora_init(CONFIG, rank=4, key=jax.random.PRNGKey(1))
    tokens = jnp.arange(16, dtype=jnp.int32).reshape(2, 8)
    base = forward(params, tokens, CONFIG)
    merged = forward(merge_lora(params, adapters), tokens, CONFIG)
    np.testing.assert_allclose(np.asarray(merged), np.asarray(base), atol=1e-5)


def test_training_updates_only_adapters_and_loss_falls():
    from workloads.train import make_mesh, synthetic_batch

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    adapters = lora_init(CONFIG, rank=4, key=jax.random.PRNGKey(1))
    mesh = make_mesh()
    optimizer = optax.adamw(1e-2)
    opt_state = optimizer.init(adapters)
    step = make_lora_train_step(CONFIG, mesh, optimizer, params)
    tokens = synthetic_batch(CONFIG, 8, seed=0)
    first = last = None
    frozen_before = np.asarray(params["layers"][0]["wqkv"]).copy()
    for _ in range(20):
        adapters, opt_state, loss = step(adapters, opt_state, tokens)
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < first, (first, last)
    # The base tree is untouched (it is never even an argument).
    np.testing.assert_array_equal(
        np.asarray(params["layers"][0]["wqkv"]), frozen_before
    )
    # b moved away from zero.
    assert float(jnp.abs(adapters[0]["wqkv"]["b"]).max()) > 0


def test_int8_base_merge_and_step():
    from workloads.quant import quantize_params
    from workloads.train import make_mesh, synthetic_batch

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    qbase = quantize_params(params)
    adapters = lora_init(CONFIG, rank=2, key=jax.random.PRNGKey(1))
    tokens = jnp.arange(16, dtype=jnp.int32).reshape(2, 8)
    merged = forward(merge_lora(qbase, adapters), tokens, CONFIG)
    assert merged.shape == (2, 8, CONFIG.vocab_size)

    mesh = make_mesh()
    optimizer = optax.adamw(1e-2)
    step = make_lora_train_step(CONFIG, mesh, optimizer, qbase)
    adapters, _, loss = step(adapters, optimizer.init(adapters),
                             synthetic_batch(CONFIG, 8, seed=0))
    assert np.isfinite(float(loss))


def test_gqa_targets_wq_wkv():
    gqa = ModelConfig(
        max_seq_len=16, n_layers=1, n_heads=4, n_kv_heads=2,
        dtype=jnp.float32,
    )
    adapters = lora_init(gqa, rank=2, key=jax.random.PRNGKey(0))
    assert set(adapters[0]) == {"wq", "wkv", "wo"}


def test_rank_validation():
    import pytest

    with pytest.raises(ValueError, match="rank"):
        lora_init(CONFIG, rank=0, key=jax.random.PRNGKey(0))


def test_cli_entry():
    from workloads.lora import main

    assert main(["--steps", "3", "--rank", "2", "--batch-size", "4",
                 "--seq-len", "16"]) == 0
    assert main(["--steps", "3", "--rank", "2", "--batch-size", "4",
                 "--seq-len", "16", "--int8-base"]) == 0


def test_merge_rejects_layer_count_mismatch():
    import pytest

    params = init_params(CONFIG, jax.random.PRNGKey(0))
    adapters = lora_init(CONFIG, rank=2, key=jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match="mismatch"):
        merge_lora(params, adapters[:1])


def test_merge_dtype_follows_base():
    params = jax.tree.map(
        lambda w: w.astype(jnp.bfloat16), init_params(CONFIG, jax.random.PRNGKey(0))
    )
    adapters = lora_init(CONFIG, rank=2, key=jax.random.PRNGKey(1))
    merged = merge_lora(params, adapters)
    assert merged["layers"][0]["wqkv"].dtype == jnp.bfloat16

"""Topology model: ICI distance, tray grouping, pair scoring."""

from tpu_device_plugin.topology import (
    SCORE_DCN,
    SCORE_SAME_TRAY,
    Topology,
    build_fake_topology,
    grid_coords,
)


def test_grid_coords_row_major():
    assert grid_coords(4, (2, 2, 1)) == [(0, 0, 0), (1, 0, 0), (0, 1, 0), (1, 1, 0)]


def test_fake_topology_v5e4():
    topo = build_fake_topology(4, 4)
    assert len(topo.chips_by_id) == 4
    assert topo.torus_shape == (4, 1, 1)
    trays = topo.trays()
    assert list(trays) == [0]
    assert [c.index for c in trays[0]] == [0, 1, 2, 3]
    assert topo.chips_by_id["tpu-0"].device_paths == ["/dev/accel0"]
    assert topo.chips_by_id["tpu-0"].hbm_gib == 16


def test_fake_topology_two_trays():
    topo = build_fake_topology(8, 4)
    trays = topo.trays()
    assert sorted(trays) == [0, 1]
    assert [c.id for c in trays[1]] == ["tpu-4", "tpu-5", "tpu-6", "tpu-7"]


def test_ici_distance_mesh():
    topo = build_fake_topology(8, 4)  # 4x2 mesh
    assert topo.ici_distance("tpu-0", "tpu-1") == 1
    assert topo.ici_distance("tpu-0", "tpu-3") == 3
    assert topo.ici_distance("tpu-0", "tpu-4") == 1  # vertically adjacent
    assert topo.ici_distance("tpu-0", "tpu-7") == 4
    assert topo.ici_distance("tpu-0", "nope") is None


def test_ici_distance_torus_wraparound():
    topo = build_fake_topology(8, 4)
    topo.wraparound = True
    # 4-wide ring: 0 -> 3 is one hop backwards.
    assert topo.ici_distance("tpu-0", "tpu-3") == 1


def test_pair_scores_ordering():
    topo = build_fake_topology(8, 4)
    same_tray = topo.pair_score("tpu-0", "tpu-1")
    cross_tray = topo.pair_score("tpu-0", "tpu-4")
    assert same_tray == SCORE_SAME_TRAY
    assert same_tray > cross_tray > SCORE_DCN


def test_remote_chips_scored_via_ici():
    topo = build_fake_topology(4, 4)
    topo.torus_shape = (4, 2, 1)
    topo.remote_coords["remote-0"] = (0, 1, 0)
    topo.remote_trays["remote-0"] = 4
    assert not topo.is_local("remote-0")
    assert topo.ici_distance("tpu-0", "remote-0") == 1
    # Remote-but-ICI-connected beats unknown/DCN-only.
    assert topo.pair_score("tpu-0", "remote-0") > SCORE_DCN
    assert topo.pair_score("tpu-0", "unknown-chip") == SCORE_DCN


def test_set_score_prefers_compact_sets():
    topo = build_fake_topology(8, 4)
    compact = topo.set_score(["tpu-0", "tpu-1"])
    spread = topo.set_score(["tpu-0", "tpu-7"])
    assert compact > spread

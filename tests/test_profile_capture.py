"""Real jax.profiler capture smoke (`make profile-check`): a seeded
serve loop runs inside a bounded ProfileSession and the dump lands on
disk; the single-engine chrome trace AND the 2-replica merged fleet
trace carry device lanes and pass tools/trace_export.py --validate
(docs/OBSERVABILITY.md "Device-time profiling & regression sentry").

The jax-free profiler units (sentry semantics, table round-trips, the
validator's collision regressions) live in tests/test_profiler.py;
this module exists to prove the one thing those cannot — that
ProfileSession drives the REAL jax.profiler and the observer's device
attribution survives a real engine's dispatch cadence.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)

from workloads.model import ModelConfig, init_params
from workloads.obs import EngineObserver, fleet_trace_events, trace_events
from workloads.profiler import DeviceTimeTable, ProfileSession, device_report
from workloads.serve import ServeEngine

from trace_export import validate_trace  # noqa: E402

CONFIG = ModelConfig(max_seq_len=64, n_layers=2, dtype=jnp.float32)

STREAM = (([1, 2, 3], 6), ([4, 5], 4), ([7, 8, 9], 3))


def _run_observed(obs):
    engine = ServeEngine(
        params=_PARAMS, config=CONFIG, slots=2, page_size=4,
        prompt_bucket=8, observer=obs,
    )
    rids = [engine.submit(p, n) for p, n in STREAM]
    out = engine.run()
    return [list(out[r]) for r in rids]


_PARAMS = None


def setup_module(module):
    global _PARAMS
    _PARAMS = init_params(CONFIG, jax.random.PRNGKey(0))


def test_profile_capture_smoke(tmp_path):
    out_dir = str(tmp_path / "profiles")
    profiler = ProfileSession(out_dir, max_secs=60.0)
    obs0 = EngineObserver(
        name="r0", replica="0", device_table=DeviceTimeTable()
    )
    started = profiler.start()
    assert profiler.active
    streams0 = _run_observed(obs0)
    capture = profiler.stop()
    assert not profiler.active

    # The dump exists on disk and the session accounted its bytes.
    assert capture is not None and capture["dir"] == started["dir"]
    dumped = [
        os.path.join(root, fn)
        for root, _, fns in os.walk(capture["dir"]) for fn in fns
    ]
    assert dumped, "jax.profiler capture must leave files on disk"
    assert capture["bytes"] > 0
    assert profiler.bytes_spent == capture["bytes"]
    assert profiler.state()["captures"] == [capture]

    # The profiled run still served its tokens, and the device table
    # calibrated from the real dispatch cadence.
    assert all(streams0)
    assert len(obs0.device_table) > 0
    assert 0.0 < obs0.device_busy_fraction <= 1.0
    report = device_report([obs0])
    assert 0.0 < report["device_busy_fraction"] <= 1.0

    # Single-engine trace: device lane declared and populated.
    trace = trace_events(obs0)
    assert validate_trace(trace) == []
    device_events = [
        ev for ev in trace["traceEvents"]
        if ev["ph"] == "X" and ev["name"].startswith("device[")
    ]
    assert device_events, "attributed steps must land on the device lane"
    assert all(ev["pid"] == 2 and ev["tid"] == 2 for ev in device_events)

    # Merged 2-replica fleet trace: each replica keeps its own device
    # lane after the pid rebase, and the merge validates end to end
    # through the SAME file path the serve CLI writes.
    obs1 = EngineObserver(
        name="r1", replica="1", device_table=DeviceTimeTable()
    )
    streams1 = _run_observed(obs1)
    assert streams1 == streams0  # same seeded stream on both replicas
    merged = fleet_trace_events(None, [obs0, obs1])
    path = str(tmp_path / "merged-trace.json")
    with open(path, "w") as f:
        json.dump(merged, f)
    from trace_export import validate_file

    assert validate_file(path) == []
    device_lanes = {
        ev["pid"] for ev in merged["traceEvents"]
        if ev["ph"] == "X" and ev["name"].startswith("device[")
    }
    assert len(device_lanes) == 2, (
        "both replicas must keep a device lane after the merge"
    )

    # A second capture into the same session stacks its budget.
    profiler.start(secs=5.0)
    second = profiler.stop()
    assert second is not None and len(profiler.captures) == 2
    assert profiler.bytes_spent >= capture["bytes"]
